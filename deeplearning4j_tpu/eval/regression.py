"""Regression metrics, per output column.

Reference: eval/RegressionEvaluation.java:26 — columnar MSE, MAE, RMSE,
relative squared error (RSE), and Pearson correlation R, accumulated
incrementally across minibatches via running sums (same streaming-moments
design as the reference's sumOfMeans/sumOfSquares fields).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


class RegressionEvaluation:
    def __init__(self, column_names: Optional[Sequence[str]] = None, precision: int = 5):
        self.column_names = list(column_names) if column_names else None
        self.precision = precision
        self._n = 0
        self._sum_err_sq = None  # Σ(y-ŷ)²  per column
        self._sum_abs_err = None  # Σ|y-ŷ|
        self._sum_y = None
        self._sum_y_sq = None
        self._sum_p = None
        self._sum_p_sq = None
        self._sum_yp = None

    def _ensure(self, cols: int):
        if self._sum_err_sq is None:
            z = lambda: np.zeros(cols, dtype=np.float64)
            self._sum_err_sq, self._sum_abs_err = z(), z()
            self._sum_y, self._sum_y_sq = z(), z()
            self._sum_p, self._sum_p_sq, self._sum_yp = z(), z(), z()
            if self.column_names is None:
                self.column_names = [f"col_{i}" for i in range(cols)]

    def eval(self, labels, predictions, mask=None) -> None:
        y = np.asarray(labels, dtype=np.float64)
        p = np.asarray(predictions, dtype=np.float64)
        if y.ndim == 1:
            y, p = y[:, None], p[:, None]
        if y.ndim == 3:  # [B, T, C] time series -> flatten time into batch
            y, p = y.reshape(-1, y.shape[-1]), p.reshape(-1, p.shape[-1])
            if mask is not None:
                mask = np.asarray(mask).reshape(-1)
        if mask is not None:
            keep = np.asarray(mask).astype(bool)
            y, p = y[keep], p[keep]
        self._ensure(y.shape[1])
        err = y - p
        self._n += y.shape[0]
        self._sum_err_sq += (err**2).sum(axis=0)
        self._sum_abs_err += np.abs(err).sum(axis=0)
        self._sum_y += y.sum(axis=0)
        self._sum_y_sq += (y**2).sum(axis=0)
        self._sum_p += p.sum(axis=0)
        self._sum_p_sq += (p**2).sum(axis=0)
        self._sum_yp += (y * p).sum(axis=0)

    def num_columns(self) -> int:
        return 0 if self._sum_err_sq is None else len(self._sum_err_sq)

    def mean_squared_error(self, col: int) -> float:
        return float(self._sum_err_sq[col] / self._n)

    def mean_absolute_error(self, col: int) -> float:
        return float(self._sum_abs_err[col] / self._n)

    def root_mean_squared_error(self, col: int) -> float:
        return float(np.sqrt(self.mean_squared_error(col)))

    def relative_squared_error(self, col: int) -> float:
        """Σ(y-ŷ)² / Σ(y-ȳ)² (reference: RegressionEvaluation.relativeSquaredError)."""
        mean_y = self._sum_y[col] / self._n
        denom = self._sum_y_sq[col] - self._n * mean_y**2
        return float(self._sum_err_sq[col] / denom) if denom != 0 else float("nan")

    def correlation_r2(self, col: int) -> float:
        """Pearson correlation coefficient (reference: correlationR2)."""
        n = self._n
        num = n * self._sum_yp[col] - self._sum_y[col] * self._sum_p[col]
        den_y = n * self._sum_y_sq[col] - self._sum_y[col] ** 2
        den_p = n * self._sum_p_sq[col] - self._sum_p[col] ** 2
        den = np.sqrt(den_y * den_p)
        return float(num / den) if den != 0 else float("nan")

    def average_mean_squared_error(self) -> float:
        return float(np.mean([self.mean_squared_error(i) for i in range(self.num_columns())]))

    def average_mean_absolute_error(self) -> float:
        return float(np.mean([self.mean_absolute_error(i) for i in range(self.num_columns())]))

    def stats(self) -> str:
        lines = [
            f"{'Column':<16}{'MSE':>12}{'MAE':>12}{'RMSE':>12}{'RSE':>12}{'R':>12}"
        ]
        for i, name in enumerate(self.column_names or []):
            lines.append(
                f"{name:<16}{self.mean_squared_error(i):>12.{self.precision}f}"
                f"{self.mean_absolute_error(i):>12.{self.precision}f}"
                f"{self.root_mean_squared_error(i):>12.{self.precision}f}"
                f"{self.relative_squared_error(i):>12.{self.precision}f}"
                f"{self.correlation_r2(i):>12.{self.precision}f}"
            )
        return "\n".join(lines)
