"""Prediction records: link evaluation results back to source records.

Reference: eval/meta/Prediction.java + the Evaluation.java record-metadata
overloads (eval(labels, out, List<RecordMetaData>) / getPredictionErrors() /
getPredictionsByActualClass() / getPredictionByPredictedClass()) — the
mechanism that makes misclassified examples traceable to the records that
produced them (VERDICT round-2 task 6).
"""

from __future__ import annotations

from typing import Any, List, Optional


class Prediction:
    """One example's (actual, predicted, provenance) triple
    (reference: eval/meta/Prediction.java)."""

    __slots__ = ("actual_class", "predicted_class", "record_metadata")

    def __init__(self, actual_class: int, predicted_class: int,
                 record_metadata: Any = None):
        self.actual_class = int(actual_class)
        self.predicted_class = int(predicted_class)
        self.record_metadata = record_metadata

    def is_correct(self) -> bool:
        return self.actual_class == self.predicted_class

    def get_record(self):
        """Reload the originating record (reference: Prediction.getRecord —
        requires metadata carrying a restartable reader)."""
        if self.record_metadata is None:
            raise ValueError("prediction carries no record metadata")
        return self.record_metadata.load()

    def __repr__(self):
        return (f"Prediction(actual={self.actual_class}, "
                f"predicted={self.predicted_class}, "
                f"meta={self.record_metadata!r})")
