"""ROC / AUC evaluation (thresholded), binary + multi-class.

Reference: eval/ROC.java:34 (thresholded ROC: ``thresholdSteps`` buckets,
per-threshold TP/FP/TN/FN counters, trapezoidal ``calculateAUC``) and
eval/ROCMultiClass.java (one-vs-all ROC per class). Counter updates here are
vectorized numpy over all thresholds at once instead of the reference's
per-threshold loop.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np


class ROC:
    """Binary ROC. ``eval`` accepts labels/probabilities as [N] (probability of
    class 1) or [N, 2] one-hot/softmax (reference ROC.eval handles both)."""

    def __init__(self, threshold_steps: int = 30):
        self.threshold_steps = int(threshold_steps)
        # thresholds 0, 1/steps, ..., 1 inclusive (reference: ROC.java init)
        self.thresholds = np.linspace(0.0, 1.0, self.threshold_steps + 1)
        self.tp = np.zeros_like(self.thresholds, dtype=np.int64)
        self.fp = np.zeros_like(self.tp)
        self.tn = np.zeros_like(self.tp)
        self.fn = np.zeros_like(self.tp)
        self.count = 0

    @staticmethod
    def _to_binary(arr) -> np.ndarray:
        arr = np.asarray(arr, dtype=np.float64)
        if arr.ndim == 2 and arr.shape[1] == 2:
            return arr[:, 1]
        if arr.ndim == 2 and arr.shape[1] == 1:
            return arr[:, 0]
        if arr.ndim == 1:
            return arr
        raise ValueError(f"ROC needs binary labels/probs; got shape {arr.shape}")

    def eval(self, labels, probabilities) -> None:
        y = self._to_binary(labels) > 0.5
        p = self._to_binary(probabilities)
        self.count += y.size
        # predicted positive at threshold t: p >= t  ([N, T] comparison)
        pred_pos = p[:, None] >= self.thresholds[None, :]
        pos = y[:, None]
        self.tp += (pred_pos & pos).sum(axis=0)
        self.fp += (pred_pos & ~pos).sum(axis=0)
        self.fn += (~pred_pos & pos).sum(axis=0)
        self.tn += (~pred_pos & ~pos).sum(axis=0)

    def get_results(self) -> List[Tuple[float, float, float]]:
        """[(threshold, fpr, tpr)] (reference: ROC.getResults)."""
        with np.errstate(invalid="ignore", divide="ignore"):
            tpr = np.where(self.tp + self.fn > 0, self.tp / np.maximum(self.tp + self.fn, 1), 0.0)
            fpr = np.where(self.fp + self.tn > 0, self.fp / np.maximum(self.fp + self.tn, 1), 0.0)
        return list(zip(self.thresholds.tolist(), fpr.tolist(), tpr.tolist()))

    def calculate_auc(self) -> float:
        """Trapezoidal AUC over the ROC points (reference: ROC.calculateAUC)."""
        pts = self.get_results()
        # sort by fpr ascending (thresholds descending ≈ fpr ascending)
        curve = sorted([(f, t) for _, f, t in pts] + [(0.0, 0.0), (1.0, 1.0)])
        auc = 0.0
        for (x0, y0), (x1, y1) in zip(curve[:-1], curve[1:]):
            auc += (x1 - x0) * (y0 + y1) / 2.0
        return float(auc)


class ROCMultiClass:
    """One-vs-all ROC per class (reference: eval/ROCMultiClass.java)."""

    def __init__(self, threshold_steps: int = 30):
        self.threshold_steps = threshold_steps
        self._per_class: Dict[int, ROC] = {}

    def eval(self, labels, probabilities) -> None:
        labels = np.asarray(labels)
        probabilities = np.asarray(probabilities)
        if labels.ndim != 2:
            raise ValueError("ROCMultiClass needs one-hot [N, C] labels")
        for c in range(labels.shape[1]):
            roc = self._per_class.setdefault(c, ROC(self.threshold_steps))
            roc.eval(labels[:, c], probabilities[:, c])

    def calculate_auc(self, cls: int) -> float:
        return self._per_class[cls].calculate_auc()

    def calculate_average_auc(self) -> float:
        if not self._per_class:
            return float("nan")
        return float(np.mean([r.calculate_auc() for r in self._per_class.values()]))

    def get_results(self, cls: int):
        return self._per_class[cls].get_results()
