"""Classification evaluation: accuracy/precision/recall/F1 + confusion matrix.

Reference: eval/Evaluation.java:46 (eval:191, stats:352), ConfusionMatrix.java
(SURVEY.md §2.1 "Evaluation"). Accumulates over batches host-side (numpy);
the argmax runs on device.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class ConfusionMatrix:
    """Counts[actual][predicted] (reference: eval/ConfusionMatrix.java)."""

    def __init__(self, n_classes: int):
        self.matrix = np.zeros((n_classes, n_classes), dtype=np.int64)

    def add(self, actual: np.ndarray, predicted: np.ndarray):
        np.add.at(self.matrix, (actual, predicted), 1)

    def get_count(self, actual: int, predicted: int) -> int:
        return int(self.matrix[actual, predicted])


class Evaluation:
    """Accumulating classification metrics (reference: eval/Evaluation.java)."""

    def __init__(
        self,
        n_classes: Optional[int] = None,
        labels: Optional[List[str]] = None,
        top_n: int = 1,
    ):
        self.n_classes = n_classes
        self.label_names = labels
        self.confusion: Optional[ConfusionMatrix] = None
        self.examples = 0
        self.top_n = max(1, top_n)
        self.top_n_correct = 0
        # Prediction records, populated only when eval() receives metadata
        # (reference: Evaluation.java metadata overloads + eval/meta/)
        self.predictions: List = []

    def _ensure(self, n: int):
        if self.confusion is None:
            self.n_classes = self.n_classes or n
            self.confusion = ConfusionMatrix(self.n_classes)

    def eval(self, labels, predictions, record_metadata=None) -> None:
        """labels: one-hot [B,C] (or int [B]); predictions: prob/score [B,C].

        Reference: Evaluation.eval:191 — row-argmax both sides into the
        confusion matrix. Time-series [B,T,C] inputs are flattened over time.
        ``record_metadata`` (one entry per example, e.g. from a
        RecordReaderDataSetIterator with ``collect_metadata=True``) additionally
        records per-example :class:`~deeplearning4j_tpu.eval.meta.Prediction`s
        so misclassifications are traceable (reference metadata overload).
        """
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if predictions.ndim == 3:
            predictions = predictions.reshape(-1, predictions.shape[-1])
            labels = labels.reshape(-1, labels.shape[-1]) if labels.ndim == 3 else labels
        self._ensure(predictions.shape[-1])
        pred_idx = predictions.argmax(-1)
        act_idx = labels.argmax(-1) if labels.ndim == 2 else labels.astype(np.int64)
        if record_metadata is not None and len(record_metadata) != len(pred_idx):
            # validate BEFORE mutating: a caller catching this must not be
            # left with the batch half-counted
            raise ValueError(
                f"record_metadata has {len(record_metadata)} entries for "
                f"{len(pred_idx)} examples"
            )
        self.confusion.add(act_idx, pred_idx)
        self.examples += len(pred_idx)
        if record_metadata is not None:
            from .meta import Prediction

            self.predictions.extend(
                Prediction(a, p, m)
                for a, p, m in zip(act_idx, pred_idx, record_metadata)
            )
        if self.top_n > 1:
            k = min(self.top_n, predictions.shape[-1])
            topk = np.argpartition(predictions, -k, axis=-1)[:, -k:]
            self.top_n_correct += int((topk == act_idx[:, None]).any(-1).sum())

    # ---- record-metadata attribution (reference: Evaluation.java meta API) ----
    def prediction_errors(self) -> List:
        """Misclassified examples with provenance (reference:
        Evaluation.getPredictionErrors)."""
        return [p for p in self.predictions if not p.is_correct()]

    def predictions_by_actual_class(self, cls: int) -> List:
        return [p for p in self.predictions if p.actual_class == cls]

    def predictions_by_predicted_class(self, cls: int) -> List:
        return [p for p in self.predictions if p.predicted_class == cls]

    # ---- metrics (reference: Evaluation accuracy()/precision()/recall()/f1()) ----
    def _tp(self) -> np.ndarray:
        return np.diag(self.confusion.matrix)

    def accuracy(self) -> float:
        m = self.confusion.matrix
        return float(self._tp().sum() / max(m.sum(), 1))

    def top_n_accuracy(self) -> float:
        """Top-N accuracy (reference: Evaluation topNAccuracy); top-1 == accuracy()."""
        if self.top_n <= 1:
            return self.accuracy()
        return self.top_n_correct / max(self.examples, 1)

    def precision(self, cls: Optional[int] = None) -> float:
        m = self.confusion.matrix
        col = m.sum(axis=0)
        with np.errstate(invalid="ignore", divide="ignore"):
            per = np.where(col > 0, self._tp() / np.maximum(col, 1), 0.0)
        return float(per[cls]) if cls is not None else float(per[col > 0].mean() if (col > 0).any() else 0.0)

    def recall(self, cls: Optional[int] = None) -> float:
        m = self.confusion.matrix
        row = m.sum(axis=1)
        per = np.where(row > 0, self._tp() / np.maximum(row, 1), 0.0)
        return float(per[cls]) if cls is not None else float(per[row > 0].mean() if (row > 0).any() else 0.0)

    def f1(self, cls: Optional[int] = None) -> float:
        p = self.precision(cls)
        r = self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) > 0 else 0.0

    def false_positive_rate(self, cls: int) -> float:
        m = self.confusion.matrix
        fp = m[:, cls].sum() - m[cls, cls]
        tn = m.sum() - m[cls, :].sum() - m[:, cls].sum() + m[cls, cls]
        return float(fp / max(fp + tn, 1))

    def _label(self, cls: int) -> str:
        if self.label_names and cls < len(self.label_names):
            return self.label_names[cls]
        return str(cls)

    def stats(self) -> str:
        """Printable summary incl. the per-class breakdown the reference
        prints (reference: Evaluation.stats:352)."""
        lines = [
            "========================Evaluation Metrics========================",
            f" # of classes:    {self.n_classes}",
            f" Examples:        {self.examples}",
            f" Accuracy:        {self.accuracy():.4f}",
            f" Precision:       {self.precision():.4f}",
            f" Recall:          {self.recall():.4f}",
            f" F1 Score:        {self.f1():.4f}",
            "",
            "Per-class:  label          precision  recall   f1      count",
        ]
        # vectorized per-class metrics: one pass over the C x C matrix
        # (per-class method calls in a loop would be O(C^3) at C=1000)
        m = self.confusion.matrix
        tp = np.diag(m).astype(np.float64)
        col = m.sum(axis=0)
        row = m.sum(axis=1)
        prec = np.where(col > 0, tp / np.maximum(col, 1), 0.0)
        rec = np.where(row > 0, tp / np.maximum(row, 1), 0.0)
        denom = prec + rec
        f1s = np.where(denom > 0, 2 * prec * rec / np.maximum(denom, 1e-300), 0.0)
        for c in range(self.n_classes or 0):
            lines.append(
                f"            {self._label(c):<14} "
                f"{prec[c]:<9.4f} {rec[c]:<8.4f} "
                f"{f1s[c]:<7.4f} {int(row[c])}"
            )
        lines += [
            "",
            "=========================Confusion Matrix=========================",
            str(self.confusion.matrix),
            "==================================================================",
        ]
        return "\n".join(lines)
