"""Interop tier: the Keras-backend gateway (reference: deeplearning4j-keras
Py4J GatewayServer, SURVEY.md §2.7)."""

from .gateway import GatewayClient, GatewayServer

__all__ = ["GatewayClient", "GatewayServer"]
