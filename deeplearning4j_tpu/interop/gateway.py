"""Keras-backend gateway server.

Reference: deeplearning4j-keras/keras/Server.java stands up a Py4J
``GatewayServer(new DeepLearning4jEntryPoint())`` so external Python Keras
drives DL4J as a training backend (entry point fits models from HDF5
batches). The TPU-native equivalent is transport-agnostic JSON frames over
TCP (no Py4J/JVM): an external process submits a Keras 1.x model-config
JSON, then streams training batches; this framework compiles and trains it
on the TPU and serves predictions back.

Frame format: uint32 length + JSON. Arrays travel base64(np.save) inside the
JSON — small, dependency-free, and structurally validated on decode.
"""

from __future__ import annotations

import base64
import io
import socket
import threading
from typing import Dict, Optional

import numpy as np

from ..utils.netio import (
    recv_json_frame as _recv_frame,
    send_json_frame as _send_frame,
)


def _encode_array(a: np.ndarray) -> str:
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(a), allow_pickle=False)
    return base64.b64encode(buf.getvalue()).decode()


def _decode_array(s: str) -> np.ndarray:
    return np.load(io.BytesIO(base64.b64decode(s)), allow_pickle=False)


class GatewayServer:
    """Entry point (reference: DeepLearning4jEntryPoint.java).

    Ops: sequential_to_multilayernetwork / fit / predict / evaluate / close.
    One model per session id.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._models: Dict[str, object] = {}
        self._lock = threading.Lock()
        # per-model locks: concurrent sessions hitting the same model_id
        # serialize their fit/predict/evaluate (the Py4J reference entry
        # point is effectively single-threaded per model)
        self._model_locks: Dict[str, threading.Lock] = {}
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(16)
        self.host, self.port = self._srv.getsockname()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="dl4j-keras-gateway")
        self._thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        try:
            socket.create_connection((self.host, self.port), timeout=1).close()
        except OSError:
            pass
        self._srv.close()
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()

    # -- dispatch -------------------------------------------------------
    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._session, args=(conn,), daemon=True).start()

    def _session(self, conn: socket.socket) -> None:
        try:
            while True:
                req = _recv_frame(conn)
                if req is None:
                    return
                try:
                    resp = self._dispatch(req)
                except Exception as e:  # error surface to the client
                    resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
                _send_frame(conn, resp)
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def _dispatch(self, req: dict) -> dict:
        op = req.get("op")
        if op == "sequential_to_multilayernetwork":
            # reference: DeepLearning4jEntryPoint.sequentialToMultilayerNetwork
            from ..modelimport.keras import import_keras_sequential_config  # noqa: PLC0415
            from ..nn.multilayer import MultiLayerNetwork  # noqa: PLC0415

            conf, _ = import_keras_sequential_config(
                req["model_config"], req.get("training_config")
            )
            net = MultiLayerNetwork(conf).init()
            with self._lock:
                self._models[req["model_id"]] = net
                self._model_locks[req["model_id"]] = threading.Lock()
            return {"ok": True, "num_params": net.num_params()}
        model_id = req.get("model_id", "")
        with self._lock:
            net = self._models.get(model_id)
            model_lock = self._model_locks.get(model_id)
        if net is None:
            raise KeyError(f"unknown model_id '{req.get('model_id')}'")
        with model_lock:
            return self._dispatch_model_op(op, req, net, model_id)

    def _dispatch_model_op(self, op: str, req: dict, net, model_id: str) -> dict:
        if op == "fit":
            from ..datasets.iterators import DataSet  # noqa: PLC0415

            x = _decode_array(req["features"])
            y = _decode_array(req["labels"])
            net.fit(DataSet(x, y), epochs=int(req.get("epochs", 1)))
            return {"ok": True, "loss": float(net._last_loss)}
        if op == "predict":
            out = net.output(_decode_array(req["features"]))
            return {"ok": True, "output": _encode_array(np.asarray(out))}
        if op == "evaluate":
            from ..datasets.iterators import DataSet  # noqa: PLC0415

            score = net.score(DataSet(_decode_array(req["features"]),
                                      _decode_array(req["labels"])))
            return {"ok": True, "score": float(score)}
        if op == "close":
            with self._lock:
                self._models.pop(model_id, None)
                self._model_locks.pop(model_id, None)
            return {"ok": True}
        raise ValueError(f"unknown op '{op}'")


class GatewayClient:
    """Client helper for the gateway protocol (what external Keras-side glue
    would implement in its own language)."""

    def __init__(self, host: str, port: int):
        self._sock = socket.create_connection((host, port))

    def _call(self, **req) -> dict:
        _send_frame(self._sock, req)
        resp = _recv_frame(self._sock)
        if resp is None:
            raise ConnectionError("gateway closed")
        if not resp.get("ok"):
            raise RuntimeError(resp.get("error", "gateway error"))
        return resp

    def create_model(self, model_id: str, model_config,
                     training_config: Optional[dict] = None) -> int:
        r = self._call(op="sequential_to_multilayernetwork", model_id=model_id,
                       model_config=model_config, training_config=training_config)
        return r["num_params"]

    def fit(self, model_id: str, features, labels, epochs: int = 1) -> float:
        r = self._call(op="fit", model_id=model_id,
                       features=_encode_array(np.asarray(features)),
                       labels=_encode_array(np.asarray(labels)),
                       epochs=epochs)
        return r["loss"]

    def predict(self, model_id: str, features) -> np.ndarray:
        r = self._call(op="predict", model_id=model_id,
                       features=_encode_array(np.asarray(features)))
        return _decode_array(r["output"])

    def evaluate(self, model_id: str, features, labels) -> float:
        return self._call(op="evaluate", model_id=model_id,
                          features=_encode_array(np.asarray(features)),
                          labels=_encode_array(np.asarray(labels)))["score"]

    def close(self) -> None:
        self._sock.close()
