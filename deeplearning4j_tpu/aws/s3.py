"""Object-store data plumbing (reference: deeplearning4j-aws s3/uploader/
S3Uploader.java, s3/reader/BaseS3DataSetIterator.java).

Cloud clients are NOT baked into this image, so the s3://-and-gs:// transports
gate on their SDK at construction (boto3 / google-cloud-storage). Everything
ABOVE the transport — the uploader, downloader, listing, and the caching
dataset iterator — is transport-agnostic and fully exercised offline through
the built-in ``file://`` client (also the injection seam for tests and for
other object stores via :func:`register_client`). Object-store-resident
corpora drop into fit() unchanged.
"""

from __future__ import annotations

import hashlib
import os
import shutil
from typing import Callable, Dict, Iterator, List, Optional


class LocalFileSystemClient:
    """s3-style client over a local directory tree (``file://`` scheme).

    'bucket' is an absolute directory path component; keys are relative
    paths. Gives the full uploader/downloader/iterator stack an offline
    transport (and tests a real one).
    """

    def upload_file(self, local_path: str, bucket: str, key: str) -> None:
        dest = os.path.join("/", bucket, key)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        shutil.copyfile(local_path, dest)

    def download_file(self, bucket: str, key: str, local_path: str) -> None:
        shutil.copyfile(os.path.join("/", bucket, key), local_path)

    def list_objects_v2(self, Bucket: str, Prefix: str = "") -> dict:  # noqa: N803 - s3 API shape
        base = os.path.join("/", Bucket)
        # Walk only the prefix subtree: file:///abs/path parses to bucket=""
        # and walking base ("/") would traverse the entire filesystem.
        start = os.path.join(base, Prefix)
        root = start if os.path.isdir(start) else os.path.dirname(start)
        out = []
        for r, _, files in os.walk(root):
            for f in files:
                key = os.path.relpath(os.path.join(r, f), base)
                if key.startswith(Prefix):
                    out.append({"Key": key})
        return {"Contents": sorted(out, key=lambda o: o["Key"])}


_CLIENT_FACTORIES: Dict[str, Callable[[], tuple]] = {}


def register_client(scheme: str, factory: Callable[[], tuple]) -> None:
    """Install a client factory for a URL scheme. ``factory`` returns
    ``(kind, client)`` where kind is "s3" (boto3-shaped API) or "gs"
    (google-cloud-storage-shaped). Tests and alternative stores hook in here."""
    _CLIENT_FACTORIES[scheme] = factory


register_client("file", lambda: ("s3", LocalFileSystemClient()))


def _client_for(scheme: str):
    if scheme in _CLIENT_FACTORIES:
        return _CLIENT_FACTORIES[scheme]()
    if scheme == "s3":
        try:
            import boto3  # noqa: PLC0415
        except ImportError as e:
            raise ImportError(
                "boto3 is required for s3:// paths (not in this image); "
                "install it or use local files"
            ) from e
        return ("s3", boto3.client("s3"))
    if scheme == "gs":
        try:
            from google.cloud import storage  # noqa: PLC0415
        except ImportError as e:
            raise ImportError(
                "google-cloud-storage is required for gs:// paths (not in "
                "this image); install it or use local files"
            ) from e
        return ("gs", storage.Client())
    raise ValueError(
        f"Unsupported scheme '{scheme}' (use s3://, gs://, file://, or "
        "register_client)"
    )


def _split_url(url: str):
    scheme, rest = url.split("://", 1)
    bucket, _, key = rest.partition("/")
    return scheme, bucket, key


class S3Uploader:
    """reference: s3/uploader/S3Uploader.java (multi-part upload of models/
    datasets). upload(local_path, 's3://bucket/key' or 'gs://...')."""

    def upload(self, local_path: str, url: str) -> None:
        scheme, bucket, key = _split_url(url)
        kind, client = _client_for(scheme)
        if kind == "s3":
            client.upload_file(local_path, bucket, key)
        else:
            client.bucket(bucket).blob(key).upload_from_filename(local_path)

    def upload_directory(self, local_dir: str, url_prefix: str) -> List[str]:
        uploaded = []
        for root, _, files in os.walk(local_dir):
            for f in files:
                p = os.path.join(root, f)
                rel = os.path.relpath(p, local_dir)
                target = url_prefix.rstrip("/") + "/" + rel.replace(os.sep, "/")
                self.upload(p, target)
                uploaded.append(target)
        return uploaded


class S3Downloader:
    def download(self, url: str, local_path: str) -> str:
        scheme, bucket, key = _split_url(url)
        kind, client = _client_for(scheme)
        os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
        if kind == "s3":
            client.download_file(bucket, key, local_path)
        else:
            client.bucket(bucket).blob(key).download_to_filename(local_path)
        return local_path

    def list_keys(self, url_prefix: str) -> List[str]:
        scheme, bucket, prefix = _split_url(url_prefix)
        kind, client = _client_for(scheme)
        if kind == "s3":
            resp = client.list_objects_v2(Bucket=bucket, Prefix=prefix)
            return [o["Key"] for o in resp.get("Contents", [])]
        return [b.name for b in client.bucket(bucket).list_blobs(prefix=prefix)]


class BaseS3DataSetIterator:
    """Stream object-store keys as local files (reference:
    s3/reader/BaseS3DataSetIterator.java); subclasses/callers parse each
    downloaded file into DataSets (e.g. via CSVRecordReader)."""

    def __init__(self, url_prefix: str, cache_dir: Optional[str] = None):
        self.url_prefix = url_prefix
        self.cache_dir = cache_dir or os.path.join(
            os.path.expanduser("~/.dl4j-tpu"), "s3cache"
        )
        self._downloader = S3Downloader()
        self._keys = self._downloader.list_keys(url_prefix)

    def __iter__(self) -> Iterator[str]:
        scheme, bucket, _ = _split_url(self.url_prefix)
        for key in self._keys:
            digest = hashlib.sha1(key.encode()).hexdigest()[:12]
            local = os.path.join(
                self.cache_dir, f"{digest}_{os.path.basename(key)}"
            )
            if not os.path.exists(local):
                self._downloader.download(f"{scheme}://{bucket}/{key}", local)
            yield local

    def __len__(self) -> int:
        return len(self._keys)
