"""Cluster provisioning (reference: aws/ec2/provision/ClusterSetup.java spins
up EC2 workers for distributed training).

The TPU-native equivalent provisions TPU slices; this class shells the
gcloud CLI when present (no cloud SDKs are baked into this image) and
otherwise raises with the exact command to run — keeping the capability
surface documented and scriptable rather than silently absent.
"""

from __future__ import annotations

import shutil
import subprocess
from typing import List, Optional


class ClusterSetup:
    """reference: ec2/provision/ClusterSetup.java (sizing + launch + wiring).

    gcloud-backed: ``create()`` provisions a TPU pod slice whose hosts then
    join one jax.distributed runtime (parallel/mesh.initialize_multihost).
    """

    def __init__(self, name: str, accelerator_type: str = "v5litepod-8",
                 zone: str = "us-central1-a", version: str = "tpu-ubuntu2204-base"):
        self.name = name
        self.accelerator_type = accelerator_type
        self.zone = zone
        self.version = version

    def _command(self, action: str, extra: Optional[List[str]] = None) -> List[str]:
        cmd = [
            "gcloud", "compute", "tpus", "tpu-vm", action, self.name,
            f"--zone={self.zone}",
        ]
        if action == "create":
            cmd += [
                f"--accelerator-type={self.accelerator_type}",
                f"--version={self.version}",
            ]
        return cmd + (extra or [])

    def _run(self, action: str, extra: Optional[List[str]] = None) -> str:
        cmd = self._command(action, extra)
        if shutil.which("gcloud") is None:
            raise RuntimeError(
                "gcloud CLI not available; run manually:\n  " + " ".join(cmd)
            )
        out = subprocess.run(cmd, check=True, capture_output=True, text=True)
        return out.stdout

    def create(self) -> str:
        return self._run("create")

    def delete(self) -> str:
        return self._run("delete", ["--quiet"])

    def describe(self) -> str:
        return self._run("describe")
