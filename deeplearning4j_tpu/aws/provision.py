"""Cluster provisioning (reference: deeplearning4j-aws ec2/provision/ —
ClusterSetup.java sizes+launches EC2 workers, HostProvisioner.java SSHes to
each host to upload artifacts and run commands, and ClusterSetup
.provisionWorkers fans provisioning threads over the host list).

The TPU-native equivalent provisions TPU slices and wires their hosts into
one ``jax.distributed`` runtime: ``ClusterSetup`` shells the gcloud CLI
(``create``/``delete``/``describe``/``list_hosts``), ``HostProvisioner``
runs per-host ssh/scp, and ``launch_distributed`` is the provision →
``initialize_multihost`` handoff — every host gets the SAME script with its
``--process-id`` and host 0 as the coordinator, exactly the argument
contract of :func:`deeplearning4j_tpu.parallel.mesh.initialize_multihost`.

No cloud SDK is baked into this image, so all subprocess entry points
resolve their binary from PATH at call time (``gcloud_binary`` /
``ssh_binary`` attributes) — tests install fakes on PATH and exercise the
full logic; a missing binary raises with the exact command to run manually.
"""

from __future__ import annotations

import json
import shutil
import subprocess
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence


def _run_cmd(cmd: List[str], binary_hint: str) -> str:
    if shutil.which(cmd[0]) is None:
        raise RuntimeError(
            f"{binary_hint} CLI not available; run manually:\n  " + " ".join(cmd)
        )
    out = subprocess.run(cmd, check=True, capture_output=True, text=True)
    return out.stdout


class HostProvisioner:
    """Per-host ssh/scp runner (reference: HostProvisioner.java —
    runRemoteCommand:101, uploadForDeployment:152, uploadAndRun:92)."""

    def __init__(self, host: str, user: Optional[str] = None, port: int = 22,
                 ssh_binary: str = "ssh", scp_binary: str = "scp",
                 extra_ssh_args: Sequence[str] = ()):
        self.host = host
        self.user = user
        self.port = int(port)
        self.ssh_binary = ssh_binary
        self.scp_binary = scp_binary
        self.extra_ssh_args = list(extra_ssh_args)

    @property
    def _target(self) -> str:
        return f"{self.user}@{self.host}" if self.user else self.host

    def run_remote_command(self, command: str) -> str:
        cmd = [self.ssh_binary, "-p", str(self.port), *self.extra_ssh_args,
               self._target, command]
        return _run_cmd(cmd, self.ssh_binary)

    def upload_for_deployment(self, local_path: str, remote_path: str) -> str:
        cmd = [self.scp_binary, "-P", str(self.port), *self.extra_ssh_args,
               local_path, f"{self._target}:{remote_path}"]
        return _run_cmd(cmd, self.scp_binary)

    def upload_and_run(self, script: str, root_dir: str = "") -> str:
        """Upload a script and execute it (HostProvisioner.uploadAndRun:92)."""
        remote = (root_dir.rstrip("/") + "/" if root_dir else "./") + "run.sh"
        self.upload_for_deployment(script, remote)
        return self.run_remote_command(f"chmod +x {remote} && {remote}")


class ClusterSetup:
    """reference: ec2/provision/ClusterSetup.java (sizing + launch + wiring).

    gcloud-backed: ``create()`` provisions a TPU pod slice whose hosts then
    join one jax.distributed runtime (parallel/mesh.initialize_multihost);
    ``provision_workers`` is the thread fan-out of
    ClusterSetup.provisionWorkers:94.
    """

    def __init__(self, name: str, accelerator_type: str = "v5litepod-8",
                 zone: str = "us-central1-a",
                 version: str = "tpu-ubuntu2204-base",
                 gcloud_binary: str = "gcloud"):
        self.name = name
        self.accelerator_type = accelerator_type
        self.zone = zone
        self.version = version
        self.gcloud_binary = gcloud_binary

    def _command(self, action: str, extra: Optional[List[str]] = None) -> List[str]:
        cmd = [
            self.gcloud_binary, "compute", "tpus", "tpu-vm", action, self.name,
            f"--zone={self.zone}",
        ]
        if action == "create":
            cmd += [
                f"--accelerator-type={self.accelerator_type}",
                f"--version={self.version}",
            ]
        return cmd + (extra or [])

    def _run(self, action: str, extra: Optional[List[str]] = None) -> str:
        return _run_cmd(self._command(action, extra), "gcloud")

    def create(self) -> str:
        return self._run("create")

    def delete(self) -> str:
        return self._run("delete", ["--quiet"])

    def describe(self) -> str:
        return self._run("describe")

    def list_hosts(self) -> List[str]:
        """Worker-host addresses of the slice, coordinator (process 0)
        first — parsed from ``describe --format=json`` networkEndpoints."""
        raw = self._run("describe", ["--format=json"])
        info = json.loads(raw)
        hosts = [ep.get("ipAddress") for ep in info.get("networkEndpoints", [])
                 if ep.get("ipAddress")]
        if not hosts:
            raise RuntimeError(
                f"describe returned no networkEndpoints for {self.name}: {raw[:500]}"
            )
        return hosts

    def provision_workers(self, hosts: Sequence[str], script: str,
                          user: Optional[str] = None,
                          ssh_binary: str = "ssh", scp_binary: str = "scp",
                          max_workers: int = 16) -> Dict[str, str]:
        """Upload+run ``script`` on every host concurrently (the reference's
        provisioning thread per worker, ClusterSetup.provisionWorkers:94-121).
        Returns {host: output}; raises if any host fails."""
        if not hosts:
            raise ValueError("no hosts to provision")

        def one(host: str) -> str:
            return HostProvisioner(host, user=user, ssh_binary=ssh_binary,
                                   scp_binary=scp_binary).upload_and_run(script)

        with ThreadPoolExecutor(max_workers=min(max_workers, len(hosts))) as ex:
            outs = list(ex.map(one, hosts))
        return dict(zip(hosts, outs))

    def launch_distributed(self, hosts: Sequence[str], train_command: str,
                           coordinator_port: int = 8476,
                           user: Optional[str] = None,
                           ssh_binary: str = "ssh",
                           max_workers: int = 16) -> Dict[str, str]:
        """The provision → initialize_multihost handoff: run
        ``train_command`` on every host with the cluster wiring appended —
        ``--coordinator host0:port --num-processes N --process-id i`` —
        the argument contract of parallel/mesh.initialize_multihost (host 0
        is the coordinator, as the reference wires the driver first)."""
        if not hosts:
            raise ValueError("no hosts to launch on")
        coord = f"{hosts[0]}:{coordinator_port}"
        n = len(hosts)

        def one(idx_host) -> str:
            i, host = idx_host
            cmd = (f"{train_command} --coordinator {coord} "
                   f"--num-processes {n} --process-id {i}")
            return HostProvisioner(host, user=user,
                                   ssh_binary=ssh_binary).run_remote_command(cmd)

        with ThreadPoolExecutor(max_workers=min(max_workers, n)) as ex:
            outs = list(ex.map(one, enumerate(hosts)))
        return {h: o for h, o in zip(hosts, outs)}
