"""Cloud storage/provisioning utilities (reference: deeplearning4j-aws —
S3Uploader/S3 readers + EC2 ClusterSetup, SURVEY.md §2.4)."""

from .s3 import BaseS3DataSetIterator, S3Downloader, S3Uploader
from .provision import ClusterSetup, HostProvisioner

__all__ = [
    "BaseS3DataSetIterator",
    "S3Downloader",
    "S3Uploader",
    "ClusterSetup",
    "HostProvisioner",
]
