"""Training listener SPI.

Reference: optimize/api/IterationListener.java + TrainingListener.java and the
stock listeners in optimize/listeners/ (ScoreIterationListener,
CollectScoresIterationListener, PerformanceListener — SURVEY.md §5.5).

``iteration_done(model, iteration, score)`` receives the score as a device
array; listeners that need the float call ``float(score)`` (the
``block_until_ready`` sync point is theirs to pay, keeping the train loop's
async dispatch intact when no listener syncs — the reference had the same
concern with the CUDA grid executioner).
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional, Tuple

logger = logging.getLogger(__name__)


class IterationListener:
    """SPI (reference: optimize/api/IterationListener.java).

    ``supports_staged``: True when the listener consumes only the
    (iteration, score) arguments — such listeners work under the staged
    fit path (``fit(stage_on_device=K)``), where ``iteration_done`` replays
    AFTER a whole scanned dispatch and ``model``'s params/state already
    hold end-of-window values. Listeners that read per-iteration model
    state (params, gradients, inputs) must leave this False so staging
    auto-disables and they keep observing true per-step state."""

    supports_staged = False

    def iteration_done(self, model, iteration: int, score) -> None:
        pass


class TrainingListener(IterationListener):
    """Adds epoch hooks (reference: optimize/api/TrainingListener.java)."""

    def on_epoch_start(self, model, epoch: int) -> None:
        pass

    def on_epoch_end(self, model, epoch: int) -> None:
        pass


class ScoreIterationListener(TrainingListener):
    """Log score every N iterations (reference: ScoreIterationListener)."""

    supports_staged = True  # consumes only (iteration, score)

    def __init__(self, print_every: int = 10):
        self.print_every = max(1, print_every)

    def iteration_done(self, model, iteration, score):
        if iteration % self.print_every == 0:
            logger.info("Score at iteration %d is %s", iteration, float(score))


class CollectScoresIterationListener(TrainingListener):
    """Accumulate (iteration, score) pairs (reference: CollectScoresIterationListener)."""

    supports_staged = True  # consumes only (iteration, score)

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self.scores: List[Tuple[int, float]] = []

    def iteration_done(self, model, iteration, score):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, float(score)))


class PerformanceListener(TrainingListener):
    """Throughput: samples/sec + batches/sec (reference: PerformanceListener.java —
    the in-tree measurement hook called out in SURVEY.md §6)."""

    supports_staged = True  # wall-clock + score only; staged throughput is
    #                           attributed to the window's steps evenly via
    #                           the model's staged_step_time hint (set by
    #                           fit_on_device during the replay loop, where
    #                           wall-clock deltas between callbacks are ~0).
    #                           Per-step time is ACCUMULATED per callback
    #                           (hint when staged, wall-clock delta when not)
    #                           so a frequency window spanning a staged/
    #                           per-batch boundary still sums real time. The
    #                           first dispatch of a program includes its JIT
    #                           compile, same as any cold-start interval.

    def __init__(self, frequency: int = 1, report_score: bool = False):
        self.frequency = max(1, frequency)
        self.report_score = report_score
        self._last_time: Optional[float] = None
        self._last_iter = 0
        self._accum = 0.0  # time attributed to steps since the last record
        self.history: List[dict] = []

    def iteration_done(self, model, iteration, score):
        now = time.perf_counter()
        staged_dt = getattr(model, "staged_step_time", None)
        if self._last_time is not None:
            self._accum += staged_dt if staged_dt is not None \
                else now - self._last_time
        self._last_time = now
        if iteration % self.frequency:
            return
        iters = iteration - self._last_iter
        if self._last_iter:  # the first qualifying callback only seeds
            dt = self._accum
            batch = getattr(model, "last_batch_size", None)
            rec = {
                "iteration": iteration,
                "batches_per_sec": iters / dt if dt > 0 else float("inf"),
            }
            if batch:
                rec["samples_per_sec"] = (
                    iters * batch / dt if dt > 0 else float("inf")
                )
            if self.report_score:
                rec["score"] = float(score)
            self.history.append(rec)
            logger.info("perf: %s", rec)
        self._last_iter = iteration
        self._accum = 0.0
