"""Training listener SPI.

Reference: optimize/api/IterationListener.java + TrainingListener.java and the
stock listeners in optimize/listeners/ (ScoreIterationListener,
CollectScoresIterationListener, PerformanceListener — SURVEY.md §5.5).

``iteration_done(model, iteration, score)`` receives the score as a device
array; listeners that need the float call ``float(score)`` (the
``block_until_ready`` sync point is theirs to pay, keeping the train loop's
async dispatch intact when no listener syncs — the reference had the same
concern with the CUDA grid executioner).
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional, Tuple

logger = logging.getLogger(__name__)


class IterationListener:
    """SPI (reference: optimize/api/IterationListener.java).

    ``supports_staged``: True when the listener consumes only the
    (iteration, score) arguments — such listeners work under the staged
    fit path (``fit(stage_on_device=K)``), where ``iteration_done`` replays
    AFTER a whole scanned dispatch and ``model``'s params/state already
    hold end-of-window values. Listeners that read per-iteration model
    state (params, gradients, inputs) must leave this False so staging
    auto-disables and they keep observing true per-step state."""

    supports_staged = False

    def iteration_done(self, model, iteration: int, score) -> None:
        pass


class TrainingListener(IterationListener):
    """Adds epoch hooks (reference: optimize/api/TrainingListener.java)."""

    def on_epoch_start(self, model, epoch: int) -> None:
        pass

    def on_epoch_end(self, model, epoch: int) -> None:
        pass


class ScoreIterationListener(TrainingListener):
    """Log score every N iterations (reference: ScoreIterationListener).

    Scores land in the telemetry registry (``dl4jtpu_score`` gauge +
    ``dl4jtpu_score_reports_total`` counter) rather than listener-private
    state, so the logged number is also the scraped number. The ``float()``
    host sync stays on the print cadence, exactly as before."""

    supports_staged = True  # consumes only (iteration, score)

    def __init__(self, print_every: int = 10, registry=None):
        from ..telemetry import get_registry  # noqa: PLC0415

        self.print_every = max(1, print_every)
        reg = registry if registry is not None else get_registry()
        self._score_gauge = reg.gauge(
            "dl4jtpu_score", "last reported training score")
        self._reports = reg.counter(
            "dl4jtpu_score_reports_total", "score reports emitted")

    def iteration_done(self, model, iteration, score):
        if iteration % self.print_every == 0:
            value = float(score)
            self._score_gauge.set(value)
            self._reports.inc()
            logger.info("Score at iteration %d is %s", iteration, value)


class CollectScoresIterationListener(TrainingListener):
    """Accumulate (iteration, score) pairs (reference: CollectScoresIterationListener)."""

    supports_staged = True  # consumes only (iteration, score)

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self.scores: List[Tuple[int, float]] = []

    def iteration_done(self, model, iteration, score):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, float(score)))


class PerformanceListener(TrainingListener):
    """Throughput: samples/sec + batches/sec (reference: PerformanceListener.java —
    the in-tree measurement hook called out in SURVEY.md §6)."""

    supports_staged = True  # wall-clock + score only; staged throughput is
    #                           attributed to the window's steps evenly via
    #                           the model's staged_step_time hint (set by
    #                           fit_on_device during the replay loop, where
    #                           wall-clock deltas between callbacks are ~0).
    #                           Per-step time is ACCUMULATED per callback
    #                           (hint when staged, wall-clock delta when not)
    #                           so a frequency window spanning a staged/
    #                           per-batch boundary still sums real time. The
    #                           first dispatch of a program includes its JIT
    #                           compile, same as any cold-start interval.

    def __init__(self, frequency: int = 1, report_score: bool = False,
                 registry=None):
        from ..telemetry import get_registry  # noqa: PLC0415

        self.frequency = max(1, frequency)
        self.report_score = report_score
        self._last_time: Optional[float] = None
        self._last_iter = 0
        self._accum = 0.0  # time attributed to steps since the last record
        self.history: List[dict] = []
        reg = registry if registry is not None else get_registry()
        self._batches_gauge = reg.gauge(
            "dl4jtpu_throughput_batches_per_sec",
            "training throughput over the last report window")
        self._samples_gauge = reg.gauge(
            "dl4jtpu_throughput_samples_per_sec",
            "training sample throughput over the last report window")

    def iteration_done(self, model, iteration, score):
        now = time.perf_counter()
        staged_dt = getattr(model, "staged_step_time", None)
        if self._last_time is not None:
            self._accum += staged_dt if staged_dt is not None \
                else now - self._last_time
        self._last_time = now
        if iteration % self.frequency:
            return
        iters = iteration - self._last_iter
        if self._last_iter:  # the first qualifying callback only seeds
            dt = self._accum
            batch = getattr(model, "last_batch_size", None)
            rec = {
                "iteration": iteration,
                "batches_per_sec": iters / dt if dt > 0 else float("inf"),
            }
            if batch:
                rec["samples_per_sec"] = (
                    iters * batch / dt if dt > 0 else float("inf")
                )
            if self.report_score:
                rec["score"] = float(score)
            if dt > 0:  # scraped gauges mirror the appended record
                self._batches_gauge.set(rec["batches_per_sec"])
                if "samples_per_sec" in rec:
                    self._samples_gauge.set(rec["samples_per_sec"])
            self.history.append(rec)
            logger.info("perf: %s", rec)
        self._last_iter = iteration
        self._accum = 0.0


class ComposableIterationListener(TrainingListener):
    """Forward every callback to a group of listeners as one attachment
    (reference: optimize/listeners/ComposableIterationListener.java).

    Capability flags aggregate conservatively: staged fit stays available
    only if EVERY child supports it, and gradient instrumentation turns on
    if ANY child needs it (at frequency 1, since children may disagree on
    cadence)."""

    def __init__(self, *listeners):
        if len(listeners) == 1 and isinstance(listeners[0], (list, tuple)):
            listeners = tuple(listeners[0])
        self.listeners: List[TrainingListener] = list(listeners)

    @property
    def supports_staged(self) -> bool:  # type: ignore[override]
        return all(getattr(l, "supports_staged", False) for l in self.listeners)

    @property
    def needs_gradients(self) -> bool:
        return any(getattr(l, "needs_gradients", False) for l in self.listeners)

    @property
    def needs_input(self) -> bool:
        return any(getattr(l, "needs_input", False) for l in self.listeners)

    @property
    def frequency(self) -> int:
        """gcd of the instrumentation-needing children's frequencies: the
        composite fires the instrumented step on a superset of every
        child's cadence WITHOUT forcing it every iteration (a child at
        frequency=50 keeps the donated fast path 49 of 50 steps)."""
        import math

        freqs = [max(1, int(getattr(l, "frequency", 1)))
                 for l in self.listeners
                 if getattr(l, "needs_gradients", False)
                 or getattr(l, "needs_input", False)]
        return math.gcd(*freqs) if freqs else 1

    def iteration_done(self, model, iteration, score):
        for l in self.listeners:
            l.iteration_done(model, iteration, score)

    def on_epoch_start(self, model, epoch):
        for l in self.listeners:
            if isinstance(l, TrainingListener):
                l.on_epoch_start(model, epoch)

    def on_epoch_end(self, model, epoch):
        for l in self.listeners:
            if isinstance(l, TrainingListener):
                l.on_epoch_end(model, epoch)


class ParamAndGradientIterationListener(TrainingListener):
    """Text/file dump of per-parameter and per-gradient statistics —
    "much of the same information as the UI histogram listener, but in a
    text-based format (for example, when learning on a system accessed via
    SSH)" (reference: optimize/listeners/
    ParamAndGradientIterationListener.java: mean / min / max / meanAbs per
    parameter tensor and its gradient, tab-delimited, header row,
    optionally appended to a file).

    Reads ``model.params`` and ``model._last_grads`` — the instrumented
    step populates the latter when ``needs_gradients`` listeners are
    attached, on exactly the iterations this listener's frequency selects
    (same machinery as the UI StatsListener)."""

    supports_staged = False   # reads per-iteration model state
    needs_gradients = True

    def __init__(self, iterations: int = 1, print_header: bool = True,
                 print_mean: bool = True, print_min_max: bool = True,
                 print_mean_abs_value: bool = True,
                 output_to_file: bool = False, file: Optional[str] = None,
                 delimiter: str = "\t"):
        self.frequency = max(1, iterations)
        self.print_header = print_header
        self.print_mean = print_mean
        self.print_min_max = print_min_max
        self.print_mean_abs_value = print_mean_abs_value
        self.output_to_file = output_to_file
        self.file = file
        self.delimiter = delimiter
        self._header_written = False
        self.lines: List[str] = []  # also kept in memory (test/REPL use)

    @staticmethod
    def _leaf_names(tree) -> List[str]:
        import jax

        return [jax.tree_util.keystr(p)
                for p, _ in jax.tree_util.tree_flatten_with_path(tree)[0]]

    def _stats(self, arr) -> List[str]:
        import numpy as np

        a = np.asarray(arr, dtype=np.float64)
        out = []
        if self.print_mean:
            out.append(repr(float(a.mean())))
        if self.print_min_max:
            out.extend((repr(float(a.min())), repr(float(a.max()))))
        if self.print_mean_abs_value:
            out.append(repr(float(np.abs(a).mean())))
        return out

    def _emit(self, line: str) -> None:
        if not (self.output_to_file and self.file):
            self.lines.append(line)  # in-memory only when not file-backed
        if self.output_to_file and self.file:
            try:
                with open(self.file, "a") as f:
                    f.write(line + "\n")
            except OSError as e:  # reference logs and keeps training
                logger.warning("ParamAndGradientIterationListener: %s", e)
        else:
            logger.info("%s", line)

    def iteration_done(self, model, iteration, score):
        if iteration % self.frequency:
            return
        import jax

        params = getattr(model, "params", None)
        grads = getattr(model, "_last_grads", None)
        names = self._leaf_names(params)
        if self.print_header and not self._header_written:
            cols = ["iteration", "score"]
            stat_names = ([ "mean"] if self.print_mean else []) + \
                (["min", "max"] if self.print_min_max else []) + \
                (["meanAbs"] if self.print_mean_abs_value else [])
            for n in names:
                cols.extend(f"param{n}.{s}" for s in stat_names)
                cols.extend(f"grad{n}.{s}" for s in stat_names)
            self._emit(self.delimiter.join(cols))
            self._header_written = True
        fields = [str(iteration), repr(float(score))]
        g_leaves = (jax.tree_util.tree_leaves(grads)
                    if grads is not None else [])
        p_leaves = jax.tree_util.tree_leaves(params)
        for i, p in enumerate(p_leaves):
            fields.extend(self._stats(p))
            if i < len(g_leaves):
                fields.extend(self._stats(g_leaves[i]))
            else:  # gradients unavailable this step: blank columns
                n_stats = (int(self.print_mean) + 2 * int(self.print_min_max)
                           + int(self.print_mean_abs_value))
                fields.extend([""] * n_stats)
        self._emit(self.delimiter.join(fields))
