"""Batch optimizers: Solver facade, line search, LBFGS, conjugate gradient.

Reference (SURVEY.md §2.1 "Training loop (Solver)"): optimize/Solver.java
builds a ConvexOptimizer — StochasticGradientDescent.java:51-72 (the default,
covered by our optax-based per-batch path), plus the line-search family:
LBFGS.java, ConjugateGradient.java, LineGradientDescent.java, all stepping
through BackTrackLineSearch.java (Armijo backtracking, 354 LoC).

TPU-native design: the objective is the net's pure ``loss_fn`` on a fixed
batch; parameters flatten once via ``ravel_pytree``; value+gradient is ONE
jitted XLA call, and the optimizer logic (two-loop recursion, β_PR, Armijo
loop) runs host-side between device calls — the standard shape for
full-batch second-order-ish methods on accelerators.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def back_track_line_search(
    f: Callable[[np.ndarray], float],
    x: np.ndarray,
    fx: float,
    grad: np.ndarray,
    direction: np.ndarray,
    initial_step: float = 1.0,
    c1: float = 1e-4,
    rho: float = 0.5,
    max_iterations: int = 20,
    min_step: float = 1e-12,
) -> Tuple[float, float]:
    """Armijo backtracking (reference: BackTrackLineSearch.optimize).

    Returns (step, f(x + step·direction)); step 0.0 when no decrease found.
    """
    slope = float(np.dot(grad, direction))
    if slope >= 0:
        return 0.0, fx  # not a descent direction
    step = initial_step
    for _ in range(max_iterations):
        fnew = f(x + step * direction)
        if np.isfinite(fnew) and fnew <= fx + c1 * step * slope:
            return step, float(fnew)
        step *= rho
        if step < min_step:
            break
    return 0.0, fx


class _BatchOptimizer:
    """Shared machinery: flatten params, jit value_and_grad on a batch."""

    def __init__(self, max_iterations: int = 100, tolerance: float = 1e-5):
        self.max_iterations = int(max_iterations)
        self.tolerance = float(tolerance)
        self.score_history: List[float] = []

    def _setup(self, net, x, y):
        from jax.flatten_util import ravel_pytree  # noqa: PLC0415

        net.init()
        flat0, unravel = ravel_pytree(net.params)

        @jax.jit
        def vg(flat):
            loss, grads = jax.value_and_grad(
                lambda p: net.loss_fn(p, x, y, train=False)
            )(unravel(flat))
            gflat, _ = ravel_pytree(grads)
            return loss, gflat

        def value(flat_np):
            return float(vg(jnp.asarray(flat_np, jnp.float32))[0])

        def value_grad(flat_np):
            loss, g = vg(jnp.asarray(flat_np, jnp.float32))
            return float(loss), np.asarray(g, np.float64)

        return np.asarray(flat0, np.float64), unravel, value, value_grad

    def _finish(self, net, flat, unravel):
        net.init(params=jax.tree_util.tree_map(
            lambda a, b: jnp.asarray(b, a.dtype),
            net.params, unravel(jnp.asarray(flat, jnp.float32))
        ), force=True)

    def optimize(self, net, x, y) -> float:
        raise NotImplementedError


class LineGradientDescent(_BatchOptimizer):
    """Steepest descent + Armijo line search (reference: LineGradientDescent.java)."""

    def optimize(self, net, x, y) -> float:
        flat, unravel, value, value_grad = self._setup(net, x, y)
        fx, g = value_grad(flat)
        for _ in range(self.max_iterations):
            self.score_history.append(fx)
            step, fnew = back_track_line_search(value, flat, fx, g, -g)
            if step > 0.0 and fnew < fx:  # apply the final accepted step too
                flat = flat + step * (-g)
            if step == 0.0 or fx - fnew < self.tolerance:
                fx = min(fx, fnew)
                break
            fx, g = value_grad(flat)
        self._finish(net, flat, unravel)
        return fx


class ConjugateGradient(_BatchOptimizer):
    """Nonlinear CG, Polak-Ribière with automatic restart (reference:
    ConjugateGradient.java)."""

    def optimize(self, net, x, y) -> float:
        flat, unravel, value, value_grad = self._setup(net, x, y)
        fx, g = value_grad(flat)
        d = -g
        for _ in range(self.max_iterations):
            self.score_history.append(fx)
            step, fnew = back_track_line_search(value, flat, fx, g, d)
            if step > 0.0 and fnew < fx:  # apply the final accepted step too
                flat = flat + step * d
            if step == 0.0 or fx - fnew < self.tolerance:
                fx = min(fx, fnew)
                break
            fx, g_new = value_grad(flat)
            beta = float(np.dot(g_new, g_new - g) / max(np.dot(g, g), 1e-30))
            beta = max(beta, 0.0)  # PR+ restart
            d = -g_new + beta * d
            g = g_new
        self._finish(net, flat, unravel)
        return fx


class LBFGS(_BatchOptimizer):
    """Limited-memory BFGS, two-loop recursion (reference: LBFGS.java,
    default history m=4)."""

    def __init__(self, max_iterations: int = 100, tolerance: float = 1e-5,
                 m: int = 4):
        super().__init__(max_iterations, tolerance)
        self.m = int(m)

    def optimize(self, net, x, y) -> float:
        flat, unravel, value, value_grad = self._setup(net, x, y)
        fx, g = value_grad(flat)
        s_hist: List[np.ndarray] = []
        y_hist: List[np.ndarray] = []
        for it in range(self.max_iterations):
            self.score_history.append(fx)
            # two-loop recursion
            q = g.copy()
            alphas = []
            for s, yv in zip(reversed(s_hist), reversed(y_hist)):
                rho = 1.0 / max(np.dot(yv, s), 1e-30)
                a = rho * np.dot(s, q)
                alphas.append((a, rho, s, yv))
                q -= a * yv
            if y_hist:
                gamma = np.dot(s_hist[-1], y_hist[-1]) / max(
                    np.dot(y_hist[-1], y_hist[-1]), 1e-30
                )
                q *= gamma
            for a, rho, s, yv in reversed(alphas):
                b = rho * np.dot(yv, q)
                q += (a - b) * s
            d = -q
            step, fnew = back_track_line_search(
                value, flat, fx, g, d, initial_step=1.0 if it > 0 else min(
                    1.0, 1.0 / max(np.linalg.norm(g), 1e-30)
                ),
            )
            flat_new = flat + step * d
            if step == 0.0 or fx - fnew < self.tolerance:
                if step > 0.0 and fnew < fx:
                    flat = flat_new
                fx = min(fx, fnew)
                break
            fx, g_new = value_grad(flat_new)
            s_hist.append(flat_new - flat)
            y_hist.append(g_new - g)
            if len(s_hist) > self.m:
                s_hist.pop(0)
                y_hist.pop(0)
            flat, g = flat_new, g_new
        self._finish(net, flat, unravel)
        return fx


_OPTIMIZERS = {
    "lbfgs": LBFGS,
    "conjugate_gradient": ConjugateGradient,
    "line_gradient_descent": LineGradientDescent,
}


class Solver:
    """Facade (reference: optimize/Solver.java Builder): picks the
    ConvexOptimizer by algorithm name and runs it on a batch. The
    "stochastic_gradient_descent" algorithm is the networks' own per-batch
    optax path (fit()); this class covers the batch/line-search family."""

    def __init__(self, algorithm: str = "lbfgs", max_iterations: int = 100,
                 tolerance: float = 1e-5, **kwargs):
        if algorithm not in _OPTIMIZERS:
            raise ValueError(
                f"Unknown algorithm '{algorithm}'; available: "
                f"{sorted(_OPTIMIZERS)} (stochastic gradient descent = net.fit)"
            )
        self.optimizer = _OPTIMIZERS[algorithm](
            max_iterations=max_iterations, tolerance=tolerance, **kwargs
        )

    def optimize(self, net, data) -> float:
        from ..datasets.iterators import DataSet  # noqa: PLC0415

        if isinstance(data, tuple):
            data = DataSet(np.asarray(data[0]), np.asarray(data[1]))
        return self.optimizer.optimize(net, data.features, data.labels)

    @property
    def score_history(self) -> List[float]:
        return self.optimizer.score_history
