"""Era model zoo (reference: trainedmodels/TrainedModels.java + TrainedModelHelper.java).

The reference downloads pretrained VGG16 weights in Keras HDF5 form and
imports them; labels come from ImageNetLabels (Utils/ImageNetLabels.java).
This build has zero network egress, so the zoo exposes (a) the exact VGG16
architecture as a config factory and (b) loaders that take a *local* Keras
HDF5 weight archive / labels file supplied by the user.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

from ..nn.conf.inputs import InputType
from ..nn.conf.multi_layer import MultiLayerConfiguration
from ..nn.conf.preprocessors import CnnToFeedForwardPreProcessor
from ..nn.layers.convolution import ConvolutionLayer
from ..nn.layers.dense import DenseLayer, OutputLayer
from ..nn.layers.pooling import SubsamplingLayer
from ..nn.updaters import UpdaterConfig


def vgg16_configuration(
    n_classes: int = 1000, height: int = 224, width: int = 224, channels: int = 3
) -> MultiLayerConfiguration:
    """VGG-16 (Simonyan & Zisserman 2014) exactly as the reference's
    TrainedModels.VGG16 lays it out: 13 same-padded 3x3 convs in 5 blocks with
    2x2 max-pools, then 4096-4096-softmax."""

    def conv(n: int) -> ConvolutionLayer:
        return ConvolutionLayer(
            n_out=n, kernel=(3, 3), stride=(1, 1), convolution_mode="same",
            activation="relu",
        )

    def pool() -> SubsamplingLayer:
        return SubsamplingLayer(pooling_type="max", kernel=(2, 2), stride=(2, 2))

    layers: List[object] = [
        conv(64), conv(64), pool(),
        conv(128), conv(128), pool(),
        conv(256), conv(256), conv(256), pool(),
        conv(512), conv(512), conv(512), pool(),
        conv(512), conv(512), conv(512), pool(),
        DenseLayer(n_out=4096, activation="relu"),
        DenseLayer(n_out=4096, activation="relu"),
        OutputLayer(n_out=n_classes, activation="softmax", loss="mcxent"),
    ]
    # flatten between last pool and first dense
    preprocessors = {len(layers) - 3: CnnToFeedForwardPreProcessor()}
    return MultiLayerConfiguration(
        layers=layers,
        input_type=InputType.convolutional(height, width, channels),
        preprocessors=preprocessors,
        updater=UpdaterConfig(updater="nesterovs", learning_rate=0.01),
    )


class TrainedModels:
    """Facade matching the reference's TrainedModels enum surface."""

    VGG16 = "VGG16"

    @staticmethod
    def configuration(name: str) -> MultiLayerConfiguration:
        if name == TrainedModels.VGG16:
            return vgg16_configuration()
        raise ValueError(f"Unknown trained model '{name}' (available: VGG16)")

    @staticmethod
    def load(name: str, weights_path: str):
        """Build the model and load pretrained weights from a *local* Keras
        HDF5 archive (reference: TrainedModelHelper downloads then imports;
        here the file must already be on disk — no egress).

        Handles both full-model saves (``model_config`` present) and the
        canonical weights-only VGG16 archive, whose layers carry 'th'-ordered
        ``param_0``/``param_1`` datasets and no config: those are paired
        positionally with this zoo's architecture."""
        if name != TrainedModels.VGG16:
            raise ValueError(f"Unknown trained model '{name}'")
        if not os.path.exists(weights_path):
            raise FileNotFoundError(
                f"VGG16 weights archive not found at {weights_path}; download "
                "the Keras VGG16 HDF5 weights on a connected machine first"
            )
        from . import hdf5  # noqa: PLC0415
        from .keras import import_keras_sequential_model_and_weights  # noqa: PLC0415

        if hdf5.read_model_config(weights_path) is not None:
            return import_keras_sequential_model_and_weights(
                weights_path, enforce_training_config=False
            )
        return _load_vgg16_weights_only(weights_path)


def _load_vgg16_weights_only(weights_path: str):
    """Pair the archive's weight-bearing layers, in file order, with the
    VGG16 architecture's weight-bearing layers (convs are 'th' OIHW)."""
    import numpy as np  # noqa: PLC0415

    from ..nn.multilayer import MultiLayerNetwork  # noqa: PLC0415
    from . import hdf5  # noqa: PLC0415
    from .keras import KerasImportError  # noqa: PLC0415

    conf = vgg16_configuration()
    net = MultiLayerNetwork(conf).init()
    archive = hdf5.read_layer_weights(weights_path)
    weighted = [(ln, w) for ln, w in archive.items() if w]

    new_params = list(net.params)
    targets = [
        i for i, l in enumerate(conf.layers)
        if isinstance(l, (ConvolutionLayer, DenseLayer))
    ]
    if len(weighted) != len(targets):
        raise KerasImportError(
            f"Archive has {len(weighted)} weighted layers; VGG16 expects "
            f"{len(targets)}"
        )
    from .keras import _cnn_flatten_dense_indices, _permute_th_flatten_dense_kernel  # noqa: PLC0415

    flatten_dense = _cnn_flatten_dense_indices(conf)
    for idx, (lname, wdict) in zip(targets, weighted):
        arrs = [wdict[k] for k in sorted(wdict)]  # param_0, param_1
        if len(arrs) != 2:
            raise KerasImportError(
                f"Layer '{lname}' has {len(arrs)} arrays; expected W and b"
            )
        w, b = (arrs if arrs[0].ndim > arrs[1].ndim else (arrs[1], arrs[0]))
        if w.ndim == 4:  # 'th' OIHW → HWIO
            w = np.transpose(w, (2, 3, 1, 0))
        elif idx in flatten_dense:
            # The canonical 'th' archive's first FC kernel has rows in C,H,W
            # flatten order; our flatten is H,W,C (ADVICE round 1, high).
            h, wd, c = flatten_dense[idx]
            w = _permute_th_flatten_dense_kernel(w, h, wd, c)
        expect = tuple(new_params[idx]["W"].shape)
        if tuple(w.shape) != expect:
            raise KerasImportError(
                f"Layer '{lname}': weight shape {w.shape} != model {expect}"
            )
        new_params[idx] = {**new_params[idx], "W": w, "b": b}
    net.init(params=tuple(new_params), force=True)
    return net


def imagenet_labels(path: Optional[str] = None) -> List[str]:
    """1000 ImageNet class labels (reference: Utils/ImageNetLabels.java reads a
    downloaded JSON). Reads a local JSON file: either a list of labels or the
    keras-style {"0": ["n01440764", "tench"], ...} mapping."""
    if path is None or not os.path.exists(path):
        raise FileNotFoundError(
            "ImageNet labels file required (no network egress); pass the path "
            "to a local imagenet_class_index.json"
        )
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, list):
        return [str(x) for x in data]
    return [data[str(i)][1] for i in range(len(data))]
