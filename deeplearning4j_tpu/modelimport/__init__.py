"""Keras model import (reference: deeplearning4j-modelimport, SURVEY.md §2.7).

TPU-native re-design: the reference reads Keras 1.x HDF5 archives through
JavaCPP HDF5 bindings (modelimport/.../Hdf5Archive.java) and translates layer
configs into DL4J confs (KerasModel.java:59, KerasSequentialModel.java:138).
Here the archive is read with h5py and translated into our dataclass configs;
weights land directly in param pytrees (no flat-vector copy step).
"""

from .hdf5 import Hdf5Archive
from .keras import (
    KerasImportError,
    import_keras_model_and_weights,
    import_keras_model_config,
    import_keras_sequential_config,
    import_keras_sequential_model_and_weights,
)
from .trained_models import TrainedModels, imagenet_labels, vgg16_configuration

__all__ = [
    "Hdf5Archive",
    "KerasImportError",
    "import_keras_model_and_weights",
    "import_keras_model_config",
    "import_keras_sequential_config",
    "import_keras_sequential_model_and_weights",
    "TrainedModels",
    "vgg16_configuration",
    "imagenet_labels",
]
