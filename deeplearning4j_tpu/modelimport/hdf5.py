"""HDF5 archive access (reference: modelimport/.../Hdf5Archive.java).

The reference wraps JavaCPP HDF5 (native dependency #2, SURVEY.md §2.9); the
TPU-native build uses h5py, gated so the rest of the framework imports without
it.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import numpy as np


def _require_h5py():
    try:
        import h5py  # noqa: PLC0415
    except ImportError as e:  # pragma: no cover - h5py is in the image
        raise ImportError(
            "h5py is required for Keras model import (reference parity: "
            "Hdf5Archive.java)"
        ) from e
    return h5py


def _decode(v: Any) -> Any:
    if isinstance(v, bytes):
        return v.decode("utf-8")
    if isinstance(v, np.ndarray) and v.dtype.kind in ("S", "O"):
        return [_decode(x) for x in v.tolist()]
    return v


class Hdf5Archive:
    """Read-only view of a Keras HDF5 archive.

    Mirrors the query surface of the reference's ``Hdf5Archive``:
    attributes-as-JSON, group listing, dataset reads — but returns numpy
    arrays ready to drop into JAX pytrees.
    """

    def __init__(self, path: str):
        h5py = _require_h5py()
        self._f = h5py.File(path, "r")

    # -- lifecycle -----------------------------------------------------
    def close(self) -> None:
        self._f.close()

    def __enter__(self) -> "Hdf5Archive":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- queries -------------------------------------------------------
    def has_attribute(self, name: str, *groups: str) -> bool:
        return name in self._group(*groups).attrs

    def read_attribute_as_string(self, name: str, *groups: str) -> str:
        return _decode(self._group(*groups).attrs[name])

    def read_attribute_as_json(self, name: str, *groups: str) -> Any:
        return json.loads(self.read_attribute_as_string(name, *groups))

    def read_string_list_attribute(self, name: str, *groups: str) -> List[str]:
        return [_decode(x) for x in self._group(*groups).attrs[name]]

    def get_groups(self, *groups: str) -> List[str]:
        import h5py  # noqa: PLC0415

        g = self._group(*groups)
        return [k for k in g.keys() if isinstance(g[k], h5py.Group)]

    def get_data_sets(self, *groups: str) -> List[str]:
        import h5py  # noqa: PLC0415

        g = self._group(*groups)
        return [k for k in g.keys() if isinstance(g[k], h5py.Dataset)]

    def read_data_set(self, name: str, *groups: str) -> np.ndarray:
        return np.asarray(self._group(*groups)[name])

    def _group(self, *groups: str):
        g = self._f
        for name in groups:
            g = g[name]
        return g


def read_layer_weights(path: str) -> Dict[str, Dict[str, np.ndarray]]:
    """Read every layer's weights: {layer_name: {weight_name: array}}.

    Handles both archive flavors the reference handles: full-model saves
    (weights under ``/model_weights``) and weights-only saves (layers at the
    root), each carrying ``layer_names`` / per-layer ``weight_names`` attrs.
    """
    h5py = _require_h5py()
    out: Dict[str, Dict[str, np.ndarray]] = {}
    with h5py.File(path, "r") as f:
        root = f["model_weights"] if "model_weights" in f else f
        layer_names: Optional[List[str]] = None
        if "layer_names" in root.attrs:
            layer_names = [_decode(x) for x in root.attrs["layer_names"]]
        else:
            layer_names = [k for k in root.keys() if isinstance(root[k], h5py.Group)]
        for ln in layer_names:
            g = root[ln]
            if "weight_names" in g.attrs:
                weight_names = [_decode(x) for x in g.attrs["weight_names"]]
            else:
                weight_names = list(g.keys())
            weights = {}
            for wn in weight_names:
                node = g[wn]
                if isinstance(node, h5py.Group):  # keras2 nested "{layer}/{var}:0"
                    for sub in node.keys():
                        weights[f"{wn}/{sub}"] = np.asarray(node[sub])
                else:
                    weights[wn] = np.asarray(node)
            out[ln] = weights
    return out


def read_model_config(path: str) -> Optional[dict]:
    """Read the ``model_config`` JSON attribute of a full-model save."""
    h5py = _require_h5py()
    with h5py.File(path, "r") as f:
        if "model_config" not in f.attrs:
            return None
        return json.loads(_decode(f.attrs["model_config"]))


def read_training_config(path: str) -> Optional[dict]:
    h5py = _require_h5py()
    with h5py.File(path, "r") as f:
        if "training_config" not in f.attrs:
            return None
        return json.loads(_decode(f.attrs["training_config"]))
