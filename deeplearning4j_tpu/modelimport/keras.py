"""Keras 1.x model import → TPU-native configs + param pytrees.

Reference behavior being matched (SURVEY.md §2.7):
- ``KerasModelImport.importKerasModelAndWeights`` (KerasModelImport.java:48)
- ``KerasSequentialModel`` parse → MultiLayerConfiguration
  (KerasSequentialModel.java:138) and weight copy (:214)
- ``KerasModel`` parse → ComputationGraphConfiguration (KerasModel.java:59)
- per-layer translators (keras/layers/Keras*.java): Dense, Convolution2D,
  MaxPooling2D/AveragePooling2D, GlobalPooling, BatchNormalization, LSTM,
  Embedding, Merge, Dropout, Activation, Flatten, ZeroPadding2D, Input, Loss.

Design differences from the reference (deliberate, TPU-native):
- weights land straight into layer param pytrees (dicts), not a flat vector;
- conv kernels are stored HWIO (XLA-native) so 'th' (OIHW) kernels are
  transposed once at import;
- batch-norm running stats go to the layer's *state* pytree (non-trainable),
  matching our functional BN, rather than into trainable params.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..nn.conf.computation_graph import ComputationGraphConfiguration
from ..nn.conf.inputs import InputType
from ..nn.conf.multi_layer import MultiLayerConfiguration
from ..nn.conf.preprocessors import (
    CnnToFeedForwardPreProcessor,
    RnnToFeedForwardPreProcessor,
)
from ..nn.graph.vertices import (
    ElementWiseVertex,
    MergeVertex,
    PreprocessorVertex,
)
from ..nn.layers.convolution import ConvolutionLayer, ZeroPaddingLayer
from ..nn.layers.dense import (
    ActivationLayer,
    DenseLayer,
    DropoutLayer,
    OutputLayer,
)
from ..nn.layers.normalization import BatchNormalization
from ..nn.layers.pooling import GlobalPoolingLayer, SubsamplingLayer
from ..nn.layers.recurrent import (
    GravesLSTM,
    LastTimeStepLayer,
    RnnEmbeddingLayer,
    RnnOutputLayer,
)
from ..nn.updaters import UpdaterConfig
from . import hdf5


class KerasImportError(Exception):
    """Unsupported Keras config (reference: InvalidKerasConfigurationException /
    UnsupportedKerasConfigurationException)."""


# ---------------------------------------------------------------------------
# name catalogs
# ---------------------------------------------------------------------------

_ACTIVATIONS = {
    "linear": "identity",
    "relu": "relu",
    "tanh": "tanh",
    "sigmoid": "sigmoid",
    "hard_sigmoid": "hardsigmoid",
    "softmax": "softmax",
    "softplus": "softplus",
    "softsign": "softsign",
    "elu": "elu",
    "selu": "selu",
}

_LOSSES = {
    "categorical_crossentropy": "mcxent",
    "sparse_categorical_crossentropy": "mcxent",
    "binary_crossentropy": "xent",
    "mean_squared_error": "mse",
    "mse": "mse",
    "mean_absolute_error": "mae",
    "mae": "mae",
    "mean_absolute_percentage_error": "mape",
    "mape": "mape",
    "mean_squared_logarithmic_error": "msle",
    "msle": "msle",
    "hinge": "hinge",
    "squared_hinge": "squared_hinge",
    "kullback_leibler_divergence": "kl_divergence",
    "kld": "kl_divergence",
    "poisson": "poisson",
    "cosine_proximity": "cosine_proximity",
}

_OPTIMIZERS = {
    "sgd": "sgd",
    "adam": "adam",
    "adamax": "adam",
    "nadam": "adam",
    "rmsprop": "rmsprop",
    "adagrad": "adagrad",
    "adadelta": "adadelta",
}


def _map_activation(name: Optional[str]) -> str:
    if not name:
        return "identity"
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise KerasImportError(f"Unsupported Keras activation '{name}'") from None


def _map_loss(name: str) -> str:
    try:
        return _LOSSES[name]
    except KeyError:
        raise KerasImportError(f"Unsupported Keras loss '{name}'") from None


def _updater_from_training_config(tc: Optional[dict]) -> UpdaterConfig:
    if not tc or "optimizer_config" not in tc:
        return UpdaterConfig()
    oc = tc["optimizer_config"]
    name = oc.get("class_name", "SGD").lower()
    cfg = oc.get("config", {})
    updater = _OPTIMIZERS.get(name, "sgd")
    kw: Dict[str, Any] = {"updater": updater}
    if "lr" in cfg:
        kw["learning_rate"] = float(cfg["lr"])
    if "momentum" in cfg:
        kw["momentum"] = float(cfg["momentum"])
    if "beta_1" in cfg:
        kw["beta1"] = float(cfg["beta_1"])
    if "beta_2" in cfg:
        kw["beta2"] = float(cfg["beta_2"])
    if "epsilon" in cfg and updater in ("adam", "rmsprop", "adadelta"):
        kw["epsilon"] = float(cfg["epsilon"])
    if "rho" in cfg:
        kw["rho"] = float(cfg["rho"])
        kw["rms_decay"] = float(cfg["rho"])
    return UpdaterConfig(**kw)


# ---------------------------------------------------------------------------
# shape helpers
# ---------------------------------------------------------------------------


def _input_type_from_shape(shape: List[Optional[int]], dim_ordering: str) -> InputType:
    """batch_input_shape (leading None = batch) → InputType."""
    dims = [int(d) for d in shape[1:] if d is not None] if shape else []
    n = len([d for d in shape[1:]])
    if n == 1:
        return InputType.feed_forward(dims[0])
    if n == 2:
        # [time, features] — time may be None (variable length)
        t = shape[1]
        return InputType.recurrent(int(shape[2]), None if t is None else int(t))
    if n == 3:
        if dim_ordering == "tf":
            h, w, c = shape[1], shape[2], shape[3]
        else:  # 'th' = channels first
            c, h, w = shape[1], shape[2], shape[3]
        return InputType.convolutional(int(h), int(w), int(c))
    raise KerasImportError(f"Unsupported input shape {shape}")


def _pair(v, default=None) -> Tuple[int, int]:
    if v is None:
        return default
    if isinstance(v, (list, tuple)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


_BORDER_MODES = {"valid": "truncate", "same": "same", "full": None}


def _conv_mode(border_mode: str) -> str:
    mode = _BORDER_MODES.get(border_mode, "unknown")
    if mode is None or mode == "unknown":
        raise KerasImportError(f"Unsupported Keras border_mode '{border_mode}'")
    return mode


# ---------------------------------------------------------------------------
# per-layer translators (reference: keras/layers/Keras*.java)
# ---------------------------------------------------------------------------


def _translate_layer(class_name: str, cfg: dict):
    """Return a layer/pseudo-layer for one Keras layer config.

    Returns one of: BaseLayer instance, ("flatten",), ("input",), ("merge", mode),
    ("reshape", target) — pseudo-entries are resolved by the callers.
    """
    name = cfg.get("name", "")
    act = _map_activation(cfg.get("activation")) if "activation" in cfg else None

    if class_name == "Dense":
        return DenseLayer(
            name=name,
            # keras 1: output_dim/bias; keras 2: units/use_bias
            n_out=int(cfg["output_dim"] if "output_dim" in cfg else cfg["units"]),
            activation=act or "identity",
            has_bias=bool(cfg.get("bias", cfg.get("use_bias", True))),
        )
    if class_name in ("Convolution2D", "Conv2D"):
        n_out = cfg["nb_filter"] if "nb_filter" in cfg else cfg["filters"]
        kernel = (
            (int(cfg["nb_row"]), int(cfg["nb_col"]))
            if "nb_row" in cfg
            else _pair(cfg["kernel_size"])
        )
        return ConvolutionLayer(
            name=name,
            n_out=int(n_out),
            kernel=kernel,
            stride=_pair(cfg.get("subsample") or cfg.get("strides"), (1, 1)),
            convolution_mode=_conv_mode(cfg.get("border_mode", cfg.get("padding", "valid"))),
            activation=act or "identity",
            has_bias=bool(cfg.get("bias", cfg.get("use_bias", True))),
        )
    if class_name in ("MaxPooling2D", "AveragePooling2D"):
        return SubsamplingLayer(
            name=name,
            pooling_type="max" if class_name.startswith("Max") else "avg",
            kernel=_pair(cfg.get("pool_size"), (2, 2)),
            stride=_pair(cfg.get("strides") or cfg.get("pool_size"), (2, 2)),
            convolution_mode=_conv_mode(cfg.get("border_mode", cfg.get("padding", "valid"))),
        )
    if class_name in ("GlobalMaxPooling2D", "GlobalAveragePooling2D",
                      "GlobalMaxPooling1D", "GlobalAveragePooling1D"):
        return GlobalPoolingLayer(
            name=name,
            pooling_type="max" if "Max" in class_name else "avg",
        )
    if class_name == "BatchNormalization":
        if int(cfg.get("mode", 0)) != 0:
            raise KerasImportError(
                "Only BatchNormalization mode=0 is importable (feature-wise)"
            )
        return BatchNormalization(
            name=name,
            eps=float(cfg.get("epsilon", 1e-5)),
            decay=float(cfg.get("momentum", 0.99)),
        )
    if class_name == "LSTM":
        layer = GravesLSTM(
            name=name,
            n_out=int(cfg["output_dim"]),
            activation=_map_activation(cfg.get("activation", "tanh")),
            gate_activation=_map_activation(cfg.get("inner_activation", "hard_sigmoid")),
            forget_gate_bias_init=1.0 if cfg.get("unit_forget_bias", True) else 0.0,
        )
        return (layer, bool(cfg.get("return_sequences", False)))
    if class_name == "Embedding":
        return RnnEmbeddingLayer(
            name=name,
            n_in=int(cfg["input_dim"]),
            n_out=int(cfg["output_dim"]),
        )
    if class_name == "Dropout":
        # keras 'p' and our 'dropout' are both drop probabilities
        return DropoutLayer(name=name, dropout=float(cfg.get("p", 0.5)))
    if class_name == "Activation":
        return ActivationLayer(name=name, activation=act or "identity")
    if class_name == "ZeroPadding2D":
        pad = cfg.get("padding", (1, 1))
        if isinstance(pad, (list, tuple)) and len(pad) == 2:
            return ZeroPaddingLayer(
                name=name,
                pad_top=int(pad[0]), pad_bottom=int(pad[0]),
                pad_left=int(pad[1]), pad_right=int(pad[1]),
            )
        if isinstance(pad, (list, tuple)) and len(pad) == 4:
            return ZeroPaddingLayer(
                name=name,
                pad_top=int(pad[0]), pad_bottom=int(pad[1]),
                pad_left=int(pad[2]), pad_right=int(pad[3]),
            )
        raise KerasImportError(f"Unsupported ZeroPadding2D padding {pad!r}")
    if class_name == "Flatten":
        return ("flatten",)
    if class_name == "InputLayer":
        return ("input",)
    if class_name == "Merge":
        return ("merge", cfg.get("mode", "concat"))
    if class_name in ("TimeDistributedDense", "TimeDistributed"):
        raise KerasImportError(f"Unsupported Keras layer '{class_name}'")
    raise KerasImportError(f"Unsupported Keras layer '{class_name}'")


# ---------------------------------------------------------------------------
# sequential path
# ---------------------------------------------------------------------------


def import_keras_sequential_config(
    model_config: Any,
    training_config: Optional[dict] = None,
) -> Tuple[MultiLayerConfiguration, List[Optional[str]]]:
    """Keras Sequential JSON → MultiLayerConfiguration.

    Returns (config, keras_name_per_layer) where the second list maps each of
    our layer indices to the Keras layer name whose weights feed it (None for
    importer-inserted layers like LastTimeStep).
    """
    if isinstance(model_config, str):
        model_config = json.loads(model_config)
    if isinstance(model_config, dict):
        if model_config.get("class_name") != "Sequential":
            raise KerasImportError(
                f"Not a Sequential model: {model_config.get('class_name')}"
            )
        layer_dicts = model_config["config"]
        if isinstance(layer_dicts, dict):  # keras2: {"layers": [...]}
            layer_dicts = layer_dicts["layers"]
    else:
        layer_dicts = model_config

    layers: List[Any] = []
    keras_names: List[Optional[str]] = []
    preprocessors: Dict[int, Any] = {}
    input_type: Optional[InputType] = None
    pending_flatten = False
    current_kind: Optional[str] = None  # "cnn" | "ff" | "rnn"

    input_ordering = (
        "th" if _model_channels_first(layer_dicts, _dim_orderings(layer_dicts)) else "tf"
    )
    for ld in layer_dicts:
        class_name = ld["class_name"]
        cfg = ld.get("config", ld)
        if input_type is None:
            shape = cfg.get("batch_input_shape")
            if shape is not None:
                input_type = _input_type_from_shape(shape, input_ordering)
            elif "input_dim" in cfg:
                input_type = InputType.feed_forward(int(cfg["input_dim"]))
        translated = _translate_layer(class_name, cfg)
        if translated == ("input",):
            continue
        if translated == ("flatten",):
            pending_flatten = True
            continue
        return_sequences = True
        if isinstance(translated, tuple) and isinstance(translated[0], GravesLSTM):
            translated, return_sequences = translated

        idx = len(layers)
        if pending_flatten:
            if current_kind == "cnn" or (current_kind is None and input_type and input_type.kind == "cnn"):
                preprocessors[idx] = CnnToFeedForwardPreProcessor()
            elif current_kind == "rnn":
                preprocessors[idx] = RnnToFeedForwardPreProcessor()
            pending_flatten = False
        layers.append(translated)
        keras_names.append(cfg.get("name") or None)
        if isinstance(translated, ConvolutionLayer) or isinstance(translated, SubsamplingLayer):
            current_kind = "cnn"
        elif isinstance(translated, (GravesLSTM, RnnEmbeddingLayer)):
            current_kind = "rnn"
        elif isinstance(translated, (DenseLayer, GlobalPoolingLayer)):
            current_kind = "ff"

        if isinstance(translated, GravesLSTM) and not return_sequences:
            layers.append(LastTimeStepLayer())
            keras_names.append(None)
            current_kind = "ff"

    if input_type is None:
        raise KerasImportError(
            "Model config declares no input shape (batch_input_shape/input_dim)"
        )

    # fold trailing loss into an OutputLayer (reference: enforceTrainingConfig path)
    if training_config and "loss" in training_config:
        loss = _map_loss(
            training_config["loss"]
            if isinstance(training_config["loss"], str)
            else list(training_config["loss"].values())[0]
        )
        _fold_output_layer(layers, keras_names, loss)

    updater = _updater_from_training_config(training_config)
    return (
        MultiLayerConfiguration(
            layers=layers,
            input_type=input_type,
            updater=updater,
            preprocessors=preprocessors,
        ),
        keras_names,
    )


def _fold_output_layer(layers: List[Any], keras_names: List[Optional[str]], loss: str) -> None:
    """Turn the trailing Dense(+Activation) into an OutputLayer with the loss."""
    if not layers:
        return
    last = layers[-1]
    if isinstance(last, ActivationLayer) and len(layers) >= 2 and type(layers[-2]) is DenseLayer:
        dense = layers[-2]
        out = OutputLayer(
            name=dense.name, n_out=dense.n_out, activation=last.activation,
            has_bias=dense.has_bias, loss=loss,
        )
        name = keras_names[-2]
        del layers[-2:], keras_names[-2:]
        layers.append(out)
        keras_names.append(name)
    elif type(last) is DenseLayer:
        out = OutputLayer(
            name=last.name, n_out=last.n_out, activation=last.activation,
            has_bias=last.has_bias, loss=loss,
        )
        name = keras_names[-1]
        del layers[-1:], keras_names[-1:]
        layers.append(out)
        keras_names.append(name)
    elif isinstance(last, GravesLSTM):
        layers.append(RnnOutputLayer(n_out=last.n_out, activation="identity", loss=loss))
        keras_names.append(None)


# ---------------------------------------------------------------------------
# functional (graph) path
# ---------------------------------------------------------------------------

_MERGE_MODES = {"sum": "add", "mul": "product", "max": "max", "ave": "average"}


def import_keras_model_config(
    model_config: Any,
    training_config: Optional[dict] = None,
) -> Tuple[ComputationGraphConfiguration, Dict[str, str]]:
    """Keras functional-Model JSON → ComputationGraphConfiguration.

    Returns (config, {vertex_name: keras_layer_name}) for weight transfer.
    """
    if isinstance(model_config, str):
        model_config = json.loads(model_config)
    if model_config.get("class_name") == "Sequential":
        raise KerasImportError("Use import_keras_sequential_config for Sequential models")
    cfg = model_config["config"]
    layer_dicts = cfg["layers"]
    input_layers = [x[0] for x in cfg["input_layers"]]
    output_layers = [x[0] for x in cfg["output_layers"]]

    builder = ComputationGraphConfiguration.builder()
    builder.add_inputs(*input_layers)
    name_map: Dict[str, str] = {}
    input_types: Dict[str, InputType] = {}
    # kind of each vertex's output, for Flatten/preprocessor decisions
    kind: Dict[str, str] = {}
    input_ordering = (
        "th" if _model_channels_first(layer_dicts, _dim_orderings(layer_dicts)) else "tf"
    )

    for ld in layer_dicts:
        class_name = ld["class_name"]
        lcfg = ld.get("config", ld)
        lname = ld.get("name") or lcfg.get("name")
        inbound = [n[0] for n in (ld.get("inbound_nodes") or [[]])[0]]

        if class_name == "InputLayer":
            shape = lcfg.get("batch_input_shape")
            input_types[lname] = _input_type_from_shape(shape, input_ordering)
            kind[lname] = input_types[lname].kind
            continue

        translated = _translate_layer(class_name, lcfg)
        if translated == ("flatten",):
            src = inbound[0]
            preproc = (
                RnnToFeedForwardPreProcessor()
                if kind.get(src) == "rnn"
                else CnnToFeedForwardPreProcessor()
            )
            builder.add_vertex(
                lname, PreprocessorVertex(preprocessor=preproc), src
            )
            kind[lname] = "ff"
            continue
        if isinstance(translated, tuple) and translated[0] == "merge":
            mode = translated[1]
            if mode in ("concat", "concat_along_depth"):
                builder.add_vertex(lname, MergeVertex(), *inbound)
            elif mode in _MERGE_MODES:
                builder.add_vertex(lname, ElementWiseVertex(op=_MERGE_MODES[mode]), *inbound)
            else:
                raise KerasImportError(f"Unsupported Merge mode '{mode}'")
            kind[lname] = kind.get(inbound[0], "ff")
            continue
        return_sequences = True
        if isinstance(translated, tuple) and isinstance(translated[0], GravesLSTM):
            translated, return_sequences = translated
        builder.add_layer(lname, translated, *inbound)
        name_map[lname] = lname
        kind[lname] = (
            "cnn" if isinstance(translated, (ConvolutionLayer, SubsamplingLayer, ZeroPaddingLayer))
            else "rnn" if isinstance(translated, (GravesLSTM, RnnEmbeddingLayer))
            else kind.get(inbound[0] if inbound else "", "ff")
        )
        if isinstance(translated, GravesLSTM) and not return_sequences:
            post = f"{lname}__last"
            builder.add_layer(post, LastTimeStepLayer(), lname)
            # downstream layers consume the inserted vertex
            _rename_downstream(layer_dicts, lname, post)
            kind[post] = "ff"

    builder.set_outputs(*[_resolve_output(n, layer_dicts) for n in output_layers])
    if input_types:
        builder.set_input_types(*[input_types[n] for n in input_layers])
    if training_config:
        builder.updater(_updater_from_training_config(training_config))
    return builder.build(), name_map


def _rename_downstream(layer_dicts, old: str, new: str) -> None:
    for ld in layer_dicts:
        for node in ld.get("inbound_nodes") or []:
            for ref in node:
                if ref[0] == old:
                    ref[0] = new


def _resolve_output(name: str, layer_dicts) -> str:
    for ld in layer_dicts:
        lname = ld.get("name") or ld.get("config", {}).get("name")
        if lname == name and ld["class_name"] == "LSTM" and not ld.get(
            "config", {}
        ).get("return_sequences", False):
            return f"{name}__last"
    return name


# ---------------------------------------------------------------------------
# weight transfer
# ---------------------------------------------------------------------------


def _weight_suffix(weight_name: str, layer_name: str) -> str:
    """'dense_1_W' → 'W'; 'dense_1/kernel:0' → 'kernel'."""
    n = weight_name.split("/")[-1]
    if n.endswith(":0"):
        n = n[:-2]
    prefix = layer_name + "_"
    if n.startswith(prefix):
        n = n[len(prefix):]
    return n


def _find(weights: Dict[str, np.ndarray], layer_name: str, *suffixes: str):
    for k, v in weights.items():
        if _weight_suffix(k, layer_name) in suffixes:
            return v
    return None


# Keras 2 renamed the conv classes; their kernels are always stored HWIO
# regardless of data_format (only Keras 1 'th' kernels are OIHW).
_KERAS2_CONV_CLASSES = {"Conv1D", "Conv2D", "Conv3D", "SeparableConv2D", "Conv2DTranspose"}
_CONV_CLASSES = _KERAS2_CONV_CLASSES | {"Convolution1D", "Convolution2D", "Convolution3D", "AtrousConvolution2D"}


def _layer_dicts_of(model_config: Any) -> list:
    if isinstance(model_config, str):
        model_config = json.loads(model_config)
    if isinstance(model_config, dict):
        cfgs = model_config.get("config")
        if isinstance(cfgs, dict):
            cfgs = cfgs.get("layers", [])
        return cfgs or []
    return model_config or []


def _dim_orderings(model_config: Any) -> Dict[str, str]:
    """{keras layer name: layout tag}.

    - ``'th'``    Keras 1 channels-first: OIHW conv kernels AND channels-first
      activations (this is the only tag that triggers a kernel transpose).
    - ``'th-k2'`` Keras 2 ``data_format=channels_first``: kernels already HWIO,
      activations channels-first (flatten order still needs permuting).
    - ``'tf'``    channels-last throughout.

    Keras 1 layers (``dim_ordering`` key, or no marker at all) default to
    'th'; Keras 2 layers (``data_format`` key or Keras-2 conv class names)
    default to 'tf' — a channels-last Conv2D kernel must NOT be transposed.
    """
    out: Dict[str, str] = {}
    for ld in _layer_dicts_of(model_config):
        c = ld.get("config", ld)
        name = ld.get("name") or c.get("name")
        if not name:
            continue
        cls = ld.get("class_name", "")
        if "dim_ordering" in c:
            out[name] = "th" if c["dim_ordering"] == "th" else "tf"
        elif c.get("data_format") == "channels_first":
            out[name] = "th-k2"
        elif "data_format" in c or cls in _KERAS2_CONV_CLASSES:
            out[name] = "tf"
        else:
            out[name] = "th"
    return out


def _model_channels_first(model_config: Any, orderings: Dict[str, str]) -> bool:
    """Are this model's image activations channels-first? Decided by the conv
    stack when one exists; a conv-free model is channels-first only when it
    carries no Keras 2 markers at all (Keras 1 'th' default)."""
    lds = _layer_dicts_of(model_config)
    if any(ld.get("class_name") in _CONV_CLASSES for ld in lds):
        return _channels_first_flatten(model_config, orderings)
    for ld in lds:
        c = ld.get("config", ld)
        if "data_format" in c or ld.get("class_name") in _KERAS2_CONV_CLASSES:
            return False
    return True


def _channels_first_flatten(model_config: Any, orderings: Dict[str, str]) -> bool:
    """True if the model's conv stack is channels-first, i.e. a Keras Flatten
    emitted rows in C,H,W order while our CnnToFeedForwardPreProcessor flattens
    NHWC (H,W,C) — the following Dense kernel's rows must be permuted."""
    for ld in _layer_dicts_of(model_config):
        if ld.get("class_name") in _CONV_CLASSES:
            c = ld.get("config", ld)
            name = ld.get("name") or c.get("name")
            if orderings.get(name, "th") in ("th", "th-k2"):
                return True
    return False


def _permute_th_flatten_dense_kernel(w: np.ndarray, h: int, wd: int, c: int) -> np.ndarray:
    """Reorder Dense kernel rows from channels-first flatten order (C,H,W) to
    our NHWC flatten order (H,W,C). Shapes coincide (C*H*W == H*W*C) so this
    corruption is silent without the permutation (ADVICE round 1, high)."""
    n_out = w.shape[-1]
    return np.ascontiguousarray(
        w.reshape(c, h, wd, n_out).transpose(1, 2, 0, 3).reshape(h * wd * c, n_out)
    )


def _cnn_flatten_dense_indices(conf) -> Dict[int, Tuple[int, int, int]]:
    """{layer idx: (h, w, c)} for Dense-family layers that consume a
    CnnToFeedForwardPreProcessor flatten of a CNN activation."""
    out: Dict[int, Tuple[int, int, int]] = {}
    cur = conf.input_type
    for i, layer in enumerate(conf.layers):
        pre = conf.preprocessors.get(i)
        if (
            isinstance(pre, CnnToFeedForwardPreProcessor)
            and cur.kind == "cnn"
            and isinstance(layer, DenseLayer)
        ):
            out[i] = (cur.height, cur.width, cur.channels)
        if pre is not None:
            cur = pre.get_output_type(cur)
        cur = layer.get_output_type(cur)
    return out


def _convert_layer_weights(
    layer, weights: Dict[str, np.ndarray], layer_name: str, dim_ordering: str = "th"
):
    """Keras arrays → (params_update, state_update) for one of our layers."""
    params: Dict[str, np.ndarray] = {}
    state: Dict[str, np.ndarray] = {}
    if isinstance(layer, ConvolutionLayer):
        w = _find(weights, layer_name, "W", "kernel")
        if w is not None:
            if w.ndim != 4:
                raise KerasImportError(f"Conv weight rank {w.ndim} != 4")
            if dim_ordering == "th":  # OIHW → HWIO
                w = np.transpose(w, (2, 3, 1, 0))
            params["W"] = w
        b = _find(weights, layer_name, "b", "bias")
        if b is not None and layer.has_bias:
            params["b"] = b
    elif isinstance(layer, BatchNormalization):
        for src, dst in (("gamma", "gamma"), ("beta", "beta")):
            v = _find(weights, layer_name, src)
            if v is not None:
                params[dst] = v
        mean = _find(weights, layer_name, "running_mean", "moving_mean")
        # keras 1.x 'running_std' actually holds the variance
        var = _find(weights, layer_name, "running_std", "running_var", "moving_variance")
        if mean is not None:
            state["mean"] = mean
        if var is not None:
            state["var"] = var
    elif isinstance(layer, GravesLSTM):
        H = layer.n_out
        # our gate column order is [a(candidate), f, o, i] (LSTMHelpers parity)
        order = ("c", "f", "o", "i")
        Ws = [_find(weights, layer_name, f"W_{g}") for g in order]
        Us = [_find(weights, layer_name, f"U_{g}") for g in order]
        bs = [_find(weights, layer_name, f"b_{g}") for g in order]
        if any(w is not None for w in Ws + Us + bs) and not all(
            w is not None for w in Ws + Us + bs
        ):
            missing = [
                f"{kind}_{g}"
                for kind, arrs in (("W", Ws), ("U", Us), ("b", bs))
                for g, a in zip(order, arrs)
                if a is None
            ]
            raise KerasImportError(
                f"LSTM layer '{layer_name}' is missing weight arrays: {missing}"
            )
        if all(w is not None for w in Ws):
            params["W"] = np.concatenate(Ws, axis=1)
            params["RW"] = np.concatenate(Us, axis=1)
            params["b"] = np.concatenate(bs, axis=0)
            # keras has no peepholes → zeros
            params["pF"] = np.zeros(H, dtype=params["W"].dtype)
            params["pI"] = np.zeros(H, dtype=params["W"].dtype)
            params["pO"] = np.zeros(H, dtype=params["W"].dtype)
    elif isinstance(layer, (DenseLayer, RnnEmbeddingLayer)):  # incl. OutputLayer
        w = _find(weights, layer_name, "W", "kernel", "embeddings")
        if w is not None:
            params["W"] = w
        b = _find(weights, layer_name, "b", "bias")
        if b is not None and getattr(layer, "has_bias", True):
            params["b"] = b
    return params, state


def _apply_updates(orig_params, orig_state, updates, state_updates):
    import jax.numpy as jnp  # noqa: PLC0415

    new_params = dict(orig_params)
    for k, v in updates.items():
        if k in orig_params:
            expect = tuple(orig_params[k].shape)
            if tuple(v.shape) != expect:
                raise KerasImportError(
                    f"Weight shape mismatch for '{k}': keras {v.shape} vs model {expect}"
                )
        new_params[k] = jnp.asarray(v, dtype=orig_params[k].dtype if k in orig_params else None)
    new_state = dict(orig_state) if isinstance(orig_state, dict) else orig_state
    for k, v in state_updates.items():
        new_state[k] = jnp.asarray(v)
    return new_params, new_state


# ---------------------------------------------------------------------------
# public facade (reference: KerasModelImport.java)
# ---------------------------------------------------------------------------


def import_keras_sequential_model_and_weights(
    path: str, enforce_training_config: bool = True
):
    """HDF5 full-model archive → initialized MultiLayerNetwork.

    Reference: KerasModelImport.importKerasSequentialModelAndWeights.
    """
    from ..nn.multilayer import MultiLayerNetwork  # noqa: PLC0415

    model_config = hdf5.read_model_config(path)
    if model_config is None:
        raise KerasImportError(f"No model_config attribute in {path}")
    training_config = hdf5.read_training_config(path) if enforce_training_config else None
    conf, keras_names = import_keras_sequential_config(model_config, training_config)
    net = MultiLayerNetwork(conf).init()

    all_weights = hdf5.read_layer_weights(path)
    orderings = _dim_orderings(model_config)
    flatten_dense = _cnn_flatten_dense_indices(conf)
    th_flatten = _channels_first_flatten(model_config, orderings)
    new_params = list(net.params)
    new_state = list(net.state)
    for i, (layer, kname) in enumerate(zip(conf.layers, keras_names)):
        if not kname or kname not in all_weights:
            continue
        p_upd, s_upd = _convert_layer_weights(
            layer, all_weights[kname], kname, orderings.get(kname, "th")
        )
        if th_flatten and i in flatten_dense and p_upd.get("W") is not None:
            h, wd, c = flatten_dense[i]
            p_upd["W"] = _permute_th_flatten_dense_kernel(np.asarray(p_upd["W"]), h, wd, c)
        new_params[i], new_state[i] = _apply_updates(
            new_params[i], new_state[i], p_upd, s_upd
        )
    net.init(params=tuple(new_params), force=True)
    net.state = tuple(new_state)
    return net


def import_keras_model_and_weights(path: str, enforce_training_config: bool = True):
    """HDF5 full-model archive → initialized ComputationGraph.

    Reference: KerasModelImport.importKerasModelAndWeights (KerasModelImport.java:48).
    """
    from ..nn.graph.computation_graph import ComputationGraph  # noqa: PLC0415

    model_config = hdf5.read_model_config(path)
    if model_config is None:
        raise KerasImportError(f"No model_config attribute in {path}")
    if model_config.get("class_name") == "Sequential":
        return import_keras_sequential_model_and_weights(path, enforce_training_config)
    training_config = hdf5.read_training_config(path) if enforce_training_config else None
    conf, name_map = import_keras_model_config(model_config, training_config)
    net = ComputationGraph(conf).init()

    all_weights = hdf5.read_layer_weights(path)
    orderings = _dim_orderings(model_config)
    th_flatten = _channels_first_flatten(model_config, orderings)
    try:
        vtypes = conf.vertex_input_types() if conf.input_types else {}
    except ValueError:
        vtypes = {}
    new_params = dict(net.params)
    new_state = dict(net.state)
    for vname, kname in name_map.items():
        if kname not in all_weights:
            continue
        vertex = conf.vertices[vname]
        layer = getattr(vertex, "layer", None)
        if layer is None:
            continue
        p_upd, s_upd = _convert_layer_weights(
            layer, all_weights[kname], kname, orderings.get(kname, "th")
        )
        if th_flatten and isinstance(layer, DenseLayer) and p_upd.get("W") is not None:
            srcs = conf.vertex_inputs.get(vname, [])
            sv = conf.vertices.get(srcs[0]) if len(srcs) == 1 else None
            if isinstance(sv, PreprocessorVertex) and isinstance(
                getattr(sv, "preprocessor", None), CnnToFeedForwardPreProcessor
            ):
                it = (vtypes.get(srcs[0]) or [None])[0]
                if it is not None and it.kind == "cnn":
                    p_upd["W"] = _permute_th_flatten_dense_kernel(
                        np.asarray(p_upd["W"]), it.height, it.width, it.channels
                    )
        new_params[vname], new_state[vname] = _apply_updates(
            new_params[vname], new_state[vname], p_upd, s_upd
        )
    net.init(params=new_params, force=True)
    net.state = new_state
    return net
