"""Dynamic micro-batcher: coalesce concurrent requests under a latency budget.

Serving traffic arrives one small request at a time; TPU executables want
large, shape-stable batches. The batcher bridges the two: requests enqueue
from any thread, a dispatcher thread coalesces whatever arrived within the
latency budget (``DL4JTPU_SERVE_MAX_DELAY_MS``) — capped at
``DL4JTPU_SERVE_MAX_BATCH`` rows — into ONE row-concatenated dispatch, and
the inference fast path pads that to the nearest pow2 bucket with masked
tails, so every mixed-size burst reuses the same bounded executable set.

Semantics:

- The **latency budget** is the longest any request waits for company: the
  first request of a cycle starts the clock, the dispatch fires when the
  budget lapses or the row cap fills, whichever is first. Budget 0 degrades
  to per-request dispatch (useful for tests / latency-critical models).
- Only **shape-compatible** requests coalesce (same trailing dims + dtype);
  stragglers of a different shape stay queued for the next cycle, they are
  never dropped.
- Failures propagate per request: an exception in the dispatch function
  rejects exactly the futures of that batch.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, List, Optional, Tuple

import numpy as np

from ..telemetry.tracing import record_trace_event, use_trace

__all__ = ["MicroBatcher", "MAX_DELAY_ENV", "MAX_BATCH_ENV"]

# env knobs (see docs/serving.md): how long a request may wait for company,
# and the most rows one coalesced dispatch may carry
MAX_DELAY_ENV = "DL4JTPU_SERVE_MAX_DELAY_MS"
MAX_BATCH_ENV = "DL4JTPU_SERVE_MAX_BATCH"
_DEFAULT_DELAY_MS = 2.0
_DEFAULT_MAX_BATCH = 64

_NULL_CM = contextlib.nullcontext()


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class _Request:
    __slots__ = ("features", "future", "enqueued", "trace")

    def __init__(self, features: np.ndarray, trace=None):
        self.features = features
        self.future: "Future[np.ndarray]" = Future()
        self.enqueued = time.perf_counter()
        self.trace = trace  # Optional[telemetry.tracing.TraceContext]


class MicroBatcher:
    """One model's request queue + dispatcher thread.

    ``dispatch(features)`` receives the row-concatenated batch and returns
    the row-aligned outputs (the inference fast path — bucketing, masking
    and slicing live there, not here).
    """

    def __init__(self, dispatch: Callable[[np.ndarray], np.ndarray], *,
                 max_delay_ms: Optional[float] = None,
                 max_batch: Optional[int] = None,
                 on_batch: Optional[Callable[..., None]] = None,
                 on_request: Optional[Callable[..., None]] = None):
        self._dispatch = dispatch
        self.max_delay_s = (
            _env_float(MAX_DELAY_ENV, _DEFAULT_DELAY_MS)
            if max_delay_ms is None else float(max_delay_ms)) / 1000.0
        self.max_batch = int(
            _env_float(MAX_BATCH_ENV, _DEFAULT_MAX_BATCH)
            if max_batch is None else max_batch)
        self._on_batch = on_batch
        self._on_request = on_request
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: "deque[_Request]" = deque()
        self._in_flight = 0
        self._pending = 0  # submitted, future not yet resolved
        self._closed = False
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    # ------------------------------------------------------------- client
    def submit(self, features, trace=None) -> "Future[np.ndarray]":
        """Enqueue one request ([rows, ...features]); returns a Future of
        the row-aligned output. ``trace`` (a sampled ``TraceContext``)
        rides the request so the coalesced dispatch can link back to it."""
        features = np.asarray(features)
        if features.ndim < 2:
            raise ValueError(
                f"request must be batched ([rows, ...]); got shape "
                f"{features.shape}")
        req = _Request(features, trace=trace)
        with self._cv:
            if self._closed:
                raise RuntimeError("batcher is stopped")
            self._queue.append(req)
            self._pending += 1
            self._cv.notify()
        return req.future

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def in_flight(self) -> int:
        """Requests currently inside a dispatch (popped off the queue but
        futures not yet resolved)."""
        with self._lock:
            return self._in_flight

    def pending(self) -> int:
        """Requests whose future is not yet resolved — queued, held by the
        collector while it waits for company, or mid-dispatch. This is
        the drain invariant (queue_depth alone misses the held ones)."""
        with self._lock:
            return self._pending

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Wait until every submitted request resolved. The caller is
        responsible for stopping admission first (the batcher itself
        keeps accepting — admission policy lives in the service).
        Returns True when fully drained within the timeout."""
        from ..runtime.resilience import Deadline
        deadline = Deadline(timeout_s)
        while True:
            with self._lock:
                if not self._pending:
                    return True
            if not deadline.pace(0.005):
                break
        with self._lock:
            return not self._pending

    def stop(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._worker.join(timeout=5)
        # reject whatever never dispatched
        with self._lock:
            leftover = list(self._queue)
            self._queue.clear()
            self._pending -= len(leftover)
        for req in leftover:
            req.future.set_exception(RuntimeError("batcher stopped"))

    # ---------------------------------------------------------- dispatcher
    @staticmethod
    def _shape_key(features: np.ndarray) -> Tuple:
        return (features.shape[1:], str(features.dtype))

    def _collect(self) -> List[_Request]:
        """Block for the first request, then soak up shape-compatible
        company until the latency budget lapses or the row cap fills."""
        with self._cv:
            while not self._queue and not self._closed:
                self._cv.wait(timeout=0.1)
            if self._closed and not self._queue:
                return []
            first = self._queue.popleft()
        group = [first]
        rows = int(first.features.shape[0])
        key = self._shape_key(first.features)
        deadline = first.enqueued + self.max_delay_s
        while rows < self.max_batch:
            with self._cv:
                # scan for the next compatible request that still FITS the
                # row cap (the cap bounds the compiled bucket — overshoot
                # would dispatch into a bucket warmup never compiled);
                # incompatible/oversize ones keep their position
                hit = None
                for i, req in enumerate(self._queue):
                    if (self._shape_key(req.features) == key
                            and rows + int(req.features.shape[0])
                            <= self.max_batch):
                        hit = i
                        break
                if hit is not None:
                    req = self._queue[hit]
                    del self._queue[hit]
                else:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0 or self._closed:
                        break
                    self._cv.wait(timeout=remaining)
                    continue
            group.append(req)
            rows += int(req.features.shape[0])
        return group

    def _run(self) -> None:
        while True:
            group = self._collect()
            if not group:
                return
            with self._lock:
                self._in_flight = len(group)
            try:
                self._dispatch_group(group)
            finally:
                with self._lock:
                    self._in_flight = 0
                    self._pending -= len(group)

    def _dispatch_group(self, group: List[_Request]) -> None:
        t0 = time.perf_counter()
        feats = (group[0].features if len(group) == 1 else
                 np.concatenate([r.features for r in group]))
        # ONE dispatch span for the coalesced batch: parented under the
        # first sampled member, with fan-in links to EVERY sampled member's
        # span — the trace shows exactly which strangers a request shared
        # device work with. Installed as current so the inference fast path
        # (infer.dispatch) parents under it.
        traced = [r.trace for r in group
                  if r.trace is not None and r.trace.sampled]
        dispatch_ctx = traced[0].child() if traced else None
        ts_us = time.time() * 1e6
        try:
            with use_trace(dispatch_ctx) if dispatch_ctx is not None \
                    else _NULL_CM:
                out = self._dispatch(feats)
        except Exception as e:  # noqa: BLE001 - reject THIS batch only
            if dispatch_ctx is not None:
                record_trace_event(
                    dispatch_ctx, "serve.batch",
                    duration_s=time.perf_counter() - t0, ts_us=ts_us,
                    error=f"{type(e).__name__}: {e}"[:200],
                    links=[{"trace_id": t.trace_id, "span_id": t.span_id}
                           for t in traced])
            for req in group:
                if not req.future.cancelled():
                    req.future.set_exception(e)
            return
        seconds = time.perf_counter() - t0
        if dispatch_ctx is not None:
            record_trace_event(
                dispatch_ctx, "serve.batch", duration_s=seconds,
                ts_us=ts_us, rows=int(feats.shape[0]),
                requests=len(group), sampled_members=len(traced),
                links=[{"trace_id": t.trace_id, "span_id": t.span_id}
                       for t in traced])
        out = np.asarray(out)
        offset = 0
        done = time.perf_counter()
        for req in group:
            n = int(req.features.shape[0])
            if not req.future.cancelled():
                req.future.set_result(out[offset:offset + n])
            if self._on_request is not None:
                self._on_request(done - req.enqueued, req.trace)
            offset += n
        if self._on_batch is not None:
            self._on_batch(rows=int(feats.shape[0]),
                           requests=len(group), seconds=seconds,
                           queue_depth=self.queue_depth())
