"""Serving front-end: the "millions of users" leg of the north star.

Layers (bottom up):

- :mod:`runtime.inference` — the AOT-bucketed fast path every entry point
  here dispatches through (dtype canonicalization, pow2 bucket + masked
  padding, compile-manager admission, donation, fused argmax).
- :mod:`serving.batcher` — dynamic micro-batching: concurrent requests
  coalesce under a latency budget (``DL4JTPU_SERVE_MAX_DELAY_MS``,
  ``DL4JTPU_SERVE_MAX_BATCH``) into one padded dispatch.
- :mod:`serving.decode` — continuous batching for stateful RNN decode:
  sessions own slots of one shared ``rnn_time_step`` state batch; masked
  ticks step only the sessions with a pending token.
- :mod:`serving.service` — the multi-model registry + serving metrics
  (``dl4jtpu_serve_*``), exposed over HTTP by ``ui/server.py``
  (POST ``/serving/predict``, POST ``/serving/rnn``, GET ``/api/serving``).

See docs/serving.md for the endpoint contract and knob semantics.
"""

from .batcher import MAX_BATCH_ENV, MAX_DELAY_ENV, MicroBatcher
from .decode import DECODE_SLOTS_ENV, DecodeServer
from .service import (LATENCY_BUDGET_ENV, MAX_QUEUE_ENV, AdmissionError,
                      InferenceService, ServiceDraining, get_service,
                      reset_services, service_names, set_service)

__all__ = [
    "AdmissionError",
    "DECODE_SLOTS_ENV",
    "DecodeServer",
    "InferenceService",
    "LATENCY_BUDGET_ENV",
    "MAX_BATCH_ENV",
    "MAX_DELAY_ENV",
    "MAX_QUEUE_ENV",
    "MicroBatcher",
    "ServiceDraining",
    "get_service",
    "reset_services",
    "service_names",
    "set_service",
]
