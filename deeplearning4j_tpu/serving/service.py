"""InferenceService: multi-model serving front-end over the fast path.

One service owns named models (both net classes), a dynamic micro-batcher
per model (``batcher.py``), optional continuous decode streams for
recurrent models (``decode.py``), and the serving observability the ISSUE 7
acceptance names:

- ``dl4jtpu_serve_requests_total{model}`` / ``dl4jtpu_serve_rows_total`` /
  ``dl4jtpu_serve_batches_total`` — traffic counters,
- ``dl4jtpu_serve_latency_seconds{model}`` — end-to-end request latency
  histogram (enqueue → result), the Prometheus twin of the exact p50/p99
  computed from a bounded recent-latency ring in :meth:`stats`,
- ``dl4jtpu_serve_queue_depth{model}`` + ``dl4jtpu_serve_batch_fill_ratio``
  gauges — how much headroom the batcher has and how full the pow2 buckets
  run,
- flight-recorder ``serve_dispatch`` events per coalesced dispatch.

Multi-model tenancy needs no code here: every model's executables live in
the process-wide compile-manager LRU next to the training entries, so cold
models age out under eviction pressure and hot models stay resident.

Services are named: ``get_service()`` returns the process-wide default
(what ``ui/server.py`` exposes over HTTP — POST ``/serving/predict``, POST
``/serving/rnn``, GET ``/api/serving``), ``get_service("edge")`` creates /
returns an independent one, and ``reset_services()`` tears the registry
down between tests so multi-service suites never cross-contaminate.

Admission control (ISSUE 13): each model can carry a queue-depth cap and a
latency budget. A request that would breach either is **shed** with
:class:`AdmissionError` (HTTP fronts map it to 429 + Retry-After) instead
of queueing into a latency spiral; a draining service refuses new traffic
with :class:`ServiceDraining` (503) while in-flight requests finish.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, Optional

import numpy as np

from ..telemetry.tracing import current_trace, record_trace_event, trace_span
from .batcher import MAX_BATCH_ENV, MAX_DELAY_ENV, MicroBatcher
from .decode import DecodeServer

__all__ = ["AdmissionError", "InferenceService", "LATENCY_BUDGET_ENV",
           "MAX_QUEUE_ENV", "ServiceDraining", "get_service",
           "reset_services", "service_names", "set_service"]

# service-wide admission defaults (per-model register() args override):
# how many requests may wait in a model's queues before shedding, and the
# p99 latency (ms, over the recent ring) beyond which new traffic sheds.
# 0 = limit disabled.
MAX_QUEUE_ENV = "DL4JTPU_SERVE_MAX_QUEUE"
LATENCY_BUDGET_ENV = "DL4JTPU_SERVE_LATENCY_BUDGET_MS"

# recompute the admission p99 at most this often — np.percentile over the
# 2048-sample ring per request would cost more than the dispatch
_P99_REFRESH_S = 0.25


class AdmissionError(RuntimeError):
    """Request shed by admission control (HTTP fronts answer 429).

    ``retry_after_s`` is the server's backoff hint: roughly how long the
    current queue needs to clear at the configured batch cadence.
    """

    def __init__(self, model: str, reason: str, retry_after_s: float):
        super().__init__(
            f"model {model!r}: request shed ({reason}); "
            f"retry after {retry_after_s:.3f}s")
        self.model = model
        self.reason = reason
        self.retry_after_s = retry_after_s


class ServiceDraining(RuntimeError):
    """Service is draining: no new admissions, in-flight work finishes."""

# request latencies span sub-ms (warm CPU micro-batch) to seconds (cold
# accelerator dispatch) — finer low end than the step-time default buckets
LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0)


def _percentile(values, q: float):
    if not values:
        return None
    return float(np.percentile(np.asarray(values, np.float64), q))


def _env_limit(name: str, kind=float):
    raw = os.environ.get(name)
    if raw is None:
        return None
    try:
        value = kind(float(raw))
    except ValueError:
        return None
    return value if value > 0 else None


class _ModelEntry:
    def __init__(self, name: str, net, batcher: MicroBatcher,
                 argmax_batcher: MicroBatcher,
                 max_queue_depth: Optional[int] = None,
                 latency_budget_ms: Optional[float] = None):
        self.name = name
        self.net = net
        self.batcher = batcher
        self.max_queue_depth = max_queue_depth
        self.latency_budget_ms = latency_budget_ms
        self.shed = 0
        self._p99_cache = (0.0, None)  # (computed_at, value)
        # class-index requests coalesce separately: logits and int32-argmax
        # dispatches can never share a transfer, but argmax traffic still
        # deserves the latency-budget batching (they dispatched direct
        # before — the ISSUE 10 serving-hardening satellite)
        self.argmax_batcher = argmax_batcher
        self.decoder: Optional[DecodeServer] = None
        self.lock = threading.Lock()
        self.latencies: "deque[float]" = deque(maxlen=2048)
        self.requests = 0
        self.rows = 0
        self.batches = 0
        self.fill_sum = 0.0
        self.last_dispatch: Optional[dict] = None
        self.version: Optional[int] = None  # hot-swap bookkeeping
        self.swapped_at: Optional[float] = None
        self.swaps = 0

    def depth(self) -> int:
        return (self.batcher.queue_depth()
                + self.argmax_batcher.queue_depth())

    def recent_p99(self) -> Optional[float]:
        """p99 over the latency ring, cached for _P99_REFRESH_S — cheap
        enough to consult on every admission decision."""
        now = time.perf_counter()
        at, value = self._p99_cache
        if now - at > _P99_REFRESH_S:
            with self.lock:  # batcher callbacks append concurrently
                ring = list(self.latencies)
            value = _percentile(ring, 99)
            self._p99_cache = (now, value)
        return value

    def stop(self) -> None:
        self.batcher.stop()
        self.argmax_batcher.stop()
        if self.decoder is not None:
            self.decoder.stop()


class InferenceService:
    """Named-model registry + per-model micro-batchers + serving metrics."""

    def __init__(self, registry=None, *,
                 max_delay_ms: Optional[float] = None,
                 max_batch: Optional[int] = None):
        if registry is None:
            from ..telemetry import get_registry  # noqa: PLC0415

            registry = get_registry()
        self.registry = registry
        self.max_delay_ms = max_delay_ms
        self.max_batch = max_batch
        self._lock = threading.Lock()
        self._models: Dict[str, _ModelEntry] = {}
        self._draining = False
        self.requests_total = registry.counter(
            "dl4jtpu_serve_requests_total",
            "inference requests served, by model", labelnames=("model",))
        self.rows_total = registry.counter(
            "dl4jtpu_serve_rows_total",
            "example rows served, by model", labelnames=("model",))
        self.batches_total = registry.counter(
            "dl4jtpu_serve_batches_total",
            "coalesced micro-batch dispatches, by model",
            labelnames=("model",))
        self.latency = registry.histogram(
            "dl4jtpu_serve_latency_seconds",
            "end-to-end request latency (enqueue to result), by model",
            labelnames=("model",), buckets=LATENCY_BUCKETS)
        self.queue_depth = registry.gauge(
            "dl4jtpu_serve_queue_depth",
            "requests waiting in the micro-batch queue, by model",
            labelnames=("model",))
        self.batch_fill = registry.gauge(
            "dl4jtpu_serve_batch_fill_ratio",
            "real rows / pow2 bucket rows of the last dispatch, by model",
            labelnames=("model",))
        # request-size classes: the distribution DL4JTPU_SERVE_MAX_BATCH
        # tuning needs (a cap far above the p99 request size wastes bucket
        # warmup; far below it splits bursts) — pow2 buckets to match the
        # compiled bucket family
        self.request_rows = registry.histogram(
            "dl4jtpu_serve_request_rows",
            "rows per inference request, by model",
            labelnames=("model",),
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512))
        self.swaps_total = registry.counter(
            "dl4jtpu_serve_swaps_total",
            "live hot-swaps of a served model's parameters, by model",
            labelnames=("model",))
        self.shed_total = registry.counter(
            "dl4jtpu_serve_shed_total",
            "requests shed by admission control, by model and reason",
            labelnames=("model", "reason"))
        # every serving process grows metric history automatically: the
        # Deadline-paced sampler ticks the default registry into the
        # process HistoryStore behind GET /api/history (no-op when
        # DL4JTPU_HISTORY=0; idempotent across services)
        try:
            from ..telemetry.history import ensure_default_sampler  # noqa: PLC0415

            ensure_default_sampler()
        except Exception:  # noqa: BLE001 - observability never blocks ctor
            pass

    # ------------------------------------------------------------ registry
    @staticmethod
    def _is_graph(net) -> bool:
        return hasattr(net.conf, "network_inputs")

    def register(self, name: str, net, layout=None, *,
                 max_delay_ms: Optional[float] = None,
                 max_batch: Optional[int] = None,
                 max_queue_depth: Optional[int] = None,
                 latency_budget_ms: Optional[float] = None,
                 ) -> "InferenceService":
        """Serve ``net`` as ``name``. Graphs must be single-input /
        single-output (the row-concatenating batcher has one features
        tensor per request).

        ``layout``: a :class:`~deeplearning4j_tpu.parallel.MeshLayout` to
        serve under — params/opt-state shard by the SAME dp×fsdp×tp rule
        set (and precision policy) training uses, and the inference fast
        path places request tensors on the layout's mesh. A net that
        arrives already sharded (``MeshLayout.apply`` / ParallelWrapper)
        keeps its placement without passing anything here.

        Per-model knobs (each falls back service-wide when None): the
        batcher pair ``max_delay_ms``/``max_batch`` (ctor arg → env →
        TUNED.json → default), and the admission pair ``max_queue_depth``
        (shed at this many queued requests) / ``latency_budget_ms`` (shed
        while the ring p99 exceeds it) — env → TUNED.json → disabled.
        Shed requests raise :class:`AdmissionError` (HTTP: 429)."""
        if self._is_graph(net):
            if (len(net.conf.network_inputs) != 1
                    or len(net.conf.network_outputs) != 1):
                raise ValueError(
                    f"model {name!r}: only single-input/single-output "
                    "graphs can be served through the micro-batcher")
        net.init()
        if layout is not None:
            layout.apply(net)
        # tuned-config auto-apply (tune/store.py): a matching TUNED.json
        # entry supplies the batcher knobs — unless the user already chose
        # them, by service ctor arg OR by process env (explicit wins)
        from ..tune import store as _tuned  # noqa: PLC0415

        tuned = _tuned.auto_apply(net, "serve", explicit=[
            knob for knob, user_set in (
                ("serve_max_delay_ms",
                 max_delay_ms is not None
                 or self.max_delay_ms is not None
                 or os.environ.get(MAX_DELAY_ENV) is not None),
                ("serve_max_batch",
                 max_batch is not None
                 or self.max_batch is not None
                 or os.environ.get(MAX_BATCH_ENV) is not None),
                ("serve_max_queue_depth",
                 max_queue_depth is not None
                 or os.environ.get(MAX_QUEUE_ENV) is not None),
                ("serve_latency_budget_ms",
                 latency_budget_ms is not None
                 or os.environ.get(LATENCY_BUDGET_ENV) is not None),
            ) if user_set])
        if max_delay_ms is None:
            max_delay_ms = self.max_delay_ms
        delay_ms = (max_delay_ms if max_delay_ms is not None
                    else tuned.get("serve_max_delay_ms"))
        if max_batch is None:
            max_batch = self.max_batch
        rows_cap = (max_batch if max_batch is not None
                    else tuned.get("serve_max_batch"))
        if max_queue_depth is None:
            max_queue_depth = _env_limit(MAX_QUEUE_ENV, int)
            if max_queue_depth is None:
                max_queue_depth = tuned.get("serve_max_queue_depth")
        if latency_budget_ms is None:
            latency_budget_ms = _env_limit(LATENCY_BUDGET_ENV)
            if latency_budget_ms is None:
                latency_budget_ms = tuned.get("serve_latency_budget_ms")
        # 0 / negative means "limit disabled" wherever it came from
        if max_queue_depth is not None and int(max_queue_depth) <= 0:
            max_queue_depth = None
        if latency_budget_ms is not None and float(latency_budget_ms) <= 0:
            latency_budget_ms = None
        entry_holder: list = []

        def dispatch(feats: np.ndarray) -> np.ndarray:
            return self._run_model(entry_holder[0], feats, argmax=False)

        def dispatch_argmax(feats: np.ndarray) -> np.ndarray:
            return self._run_model(entry_holder[0], feats, argmax=True)

        batcher = MicroBatcher(
            dispatch,
            max_delay_ms=delay_ms, max_batch=rows_cap,
            on_batch=lambda **kw: self._record_batch(name, **kw),
            on_request=lambda s, t=None: self._record_request(name, s, t))
        argmax_batcher = MicroBatcher(
            dispatch_argmax,
            max_delay_ms=delay_ms, max_batch=rows_cap,
            on_batch=lambda **kw: self._record_batch(name, kind="argmax",
                                                     **kw),
            on_request=lambda s, t=None: self._record_request(name, s, t))
        entry = _ModelEntry(
            name, net, batcher, argmax_batcher,
            max_queue_depth=(None if max_queue_depth is None
                             else int(max_queue_depth)),
            latency_budget_ms=(None if latency_budget_ms is None
                               else float(latency_budget_ms)))
        entry_holder.append(entry)
        with self._lock:
            old = self._models.get(name)
            self._models[name] = entry
        if old is not None:
            old.stop()
        # SLO declaration is env-opt-in (DL4JTPU_SLO_*): fleets that want
        # burn-rate alerting set the knobs; unset, nothing evaluates and
        # existing behavior (tests included) is untouched. Programmatic
        # declaration stays available via get_slo_monitor().declare().
        try:
            from ..telemetry import slo as _slo  # noqa: PLC0415

            if any(os.environ.get(k) for k in (
                    _slo.SLO_LATENCY_BUDGET_ENV,
                    _slo.SLO_LATENCY_TARGET_ENV,
                    _slo.SLO_AVAILABILITY_TARGET_ENV)):
                _slo.get_slo_monitor().declare_from_env(
                    name, latency_budget_ms=entry.latency_budget_ms)
        except Exception:  # observability must never fail registration
            pass
        return self

    def unregister(self, name: str) -> None:
        with self._lock:
            entry = self._models.pop(name, None)
        if entry is not None:
            entry.stop()

    def hot_swap(self, name: str, params=None, *, net=None, state=None,
                 version: Optional[int] = None) -> None:
        """Swap a served model's parameters live — the train→serve handoff.

        A pure pointer flip behind the entry lock: the served net keeps its
        compile-manager token and its abstract signature (same config, same
        shapes/dtypes), so every cached executable still matches — no
        restart, no warm-compile storm. In-flight dispatches already passed
        the old pytree into their executable and complete bit-exactly on
        it; every dispatch after the flip sees the new pytree, never a mix.

        Pass ``params`` (and optionally ``state``) directly — snapshot
        copies, not the live training buffers, when the trainer donates —
        or ``net`` to copy the references from another model object.
        ``version`` tags the swap in :meth:`stats`/flight events.
        """
        entry = self._entry(name)
        if params is None:
            if net is None:
                raise ValueError("hot_swap needs params= or net=")
            params, state = net.params, net.state
        with entry.lock:
            entry.net.params = params
            if state is not None:
                entry.net.state = state
            entry.version = version
            entry.swapped_at = time.time()
            entry.swaps += 1
        self.swaps_total.labels(model=name).inc()
        try:
            from ..telemetry.flight_recorder import get_flight_recorder  # noqa: PLC0415

            get_flight_recorder().record(
                "serve_swap", model=name,
                version=None if version is None else int(version))
        except Exception:  # observability must never fail a swap
            pass

    def models(self):
        with self._lock:
            return sorted(self._models)

    def _entry(self, name: str) -> _ModelEntry:
        with self._lock:
            entry = self._models.get(name)
        if entry is None:
            raise KeyError(f"unknown model {name!r}; registered: "
                           f"{self.models()}")
        return entry

    # ------------------------------------------------------------ dispatch
    def _run_model(self, entry: _ModelEntry, feats: np.ndarray,
                   argmax: bool) -> np.ndarray:
        from ..runtime import inference as _inf

        net = entry.net
        if self._is_graph(net):
            return _inf.graph_output(net, [feats], argmax=argmax)[0]
        return _inf.mln_output(net, feats, argmax=argmax)

    def warmup(self, name: str, example, *, argmax: bool = False,
               max_rows: Optional[int] = None) -> int:
        """Compile-ahead for serving: run every pow2 row bucket from 1 up to
        the micro-batcher's row cap through the fast path (plus the
        fused-argmax variants when ``argmax``), so live traffic — whatever
        mix of request sizes the batcher coalesces — pays zero compiles.
        ``example`` is one request ([rows, ...features]); only its trailing
        shape/dtype matter. Returns the number of buckets warmed."""
        from ..runtime.compile_manager import next_pow2

        entry = self._entry(name)
        example = np.asarray(example)
        cap = next_pow2(max_rows if max_rows is not None
                        else entry.batcher.max_batch)
        rows, warmed = 1, 0
        while rows <= cap:
            probe = np.zeros((rows,) + example.shape[1:], example.dtype)
            self._run_model(entry, probe, argmax=False)
            if argmax:
                self._run_model(entry, probe, argmax=True)
            warmed += 1
            rows *= 2
        return warmed

    def predict(self, name: str, features, *, argmax: bool = False,
                timeout_s: float = 30.0, trace=None) -> np.ndarray:
        """Serve one request through the model's micro-batcher. ``argmax``
        requests coalesce on their OWN batcher (mixing them with logits
        requests would force two device transfers per batch) and dispatch
        on the fused-argmax executable — only int32 class indices cross
        the device boundary, same as the old direct path.

        ``trace``: an optional :class:`TraceContext` (falls back to the
        thread's current context). A sampled request records a
        ``serve.request`` span wrapping admission and the batched wait,
        and rides into the coalesced dispatch for fan-in linking; a shed
        or over-budget request upgrades an unsampled context post-hoc.

        Raises :class:`ServiceDraining` while the service drains and
        :class:`AdmissionError` when the model's queue-depth cap or
        latency budget would be breached (shed now beats queueing into a
        latency spiral — the caller backs off ``retry_after_s``)."""
        ctx = trace if trace is not None else current_trace()
        if ctx is None or not ctx.sampled:
            return self._predict(name, features, argmax, timeout_s, ctx)
        with trace_span(ctx, "serve.request", model=name,
                        argmax=bool(argmax)) as sp:
            return self._predict(name, features, argmax, timeout_s, sp.ctx)

    def _predict(self, name: str, features, argmax: bool,
                 timeout_s: float, ctx) -> np.ndarray:
        if self._draining:
            raise ServiceDraining(f"service draining; model {name!r} "
                                  "not admitting new requests")
        entry = self._entry(name)
        self._admit(entry, ctx)
        features = np.asarray(features)
        if features.ndim >= 1:
            self.request_rows.labels(model=name).observe(
                int(features.shape[0]))
        batcher = entry.argmax_batcher if argmax else entry.batcher
        fut = batcher.submit(
            features,
            trace=ctx if ctx is not None and ctx.sampled else None)
        self.queue_depth.labels(model=name).set(
            entry.batcher.queue_depth() + entry.argmax_batcher.queue_depth())
        return fut.result(timeout=timeout_s)

    def _admit(self, entry: _ModelEntry, ctx=None) -> None:
        depth = entry.depth()
        if (entry.max_queue_depth is not None
                and depth >= entry.max_queue_depth):
            # backoff hint: cycles needed to clear the queue at the
            # batcher's cadence (delay budget per coalesced dispatch)
            cycles = depth / max(1, entry.batcher.max_batch)
            retry = max(0.05, cycles * max(entry.batcher.max_delay_s,
                                           0.002))
            self._shed(entry, "queue_depth", retry, ctx)
        if entry.latency_budget_ms is not None:
            p99 = entry.recent_p99()
            if p99 is not None and p99 * 1000.0 > entry.latency_budget_ms:
                self._shed(entry, "latency_budget",
                           max(0.05, 2 * entry.latency_budget_ms / 1000.0),
                           ctx)

    def _shed(self, entry: _ModelEntry, reason: str,
              retry_after_s: float, ctx=None) -> None:
        with entry.lock:
            entry.shed += 1
        self.shed_total.labels(model=entry.name, reason=reason).inc()
        tid = None
        if ctx is not None:
            # always-sample on shed: upgrade an unsampled head post-hoc so
            # the 429 the client sees has a trace behind it
            ctx.upgrade(f"shed:{reason}")
            record_trace_event(ctx.child(), "serve.shed",
                               model=entry.name, reason=reason,
                               retry_after_s=round(retry_after_s, 3))
            tid = ctx.trace_id
        try:
            from ..telemetry.slo import get_slo_monitor  # noqa: PLC0415

            mon = get_slo_monitor()
            mon.observe(entry.name, shed=True, trace_id=tid)
            mon.maybe_evaluate()
        except Exception:  # observability must never fail a shed
            pass
        raise AdmissionError(entry.name, reason, round(retry_after_s, 3))

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful drain: stop admitting (predict raises
        :class:`ServiceDraining`), wait for every model's queued AND
        in-flight requests to finish. Returns True when fully drained.
        The service stays registered — callers deregister/stop after."""
        self._draining = True
        with self._lock:
            entries = list(self._models.values())
        deadline = time.perf_counter() + timeout_s
        ok = True
        for e in entries:
            for b in (e.batcher, e.argmax_batcher):
                remaining = deadline - time.perf_counter()
                ok = b.drain(timeout_s=max(0.0, remaining)) and ok
        return ok

    @property
    def draining(self) -> bool:
        return self._draining

    # ----------------------------------------------------------- decode
    def decoder(self, name: str) -> DecodeServer:
        """The model's continuous-decode stream (created on first use)."""
        entry = self._entry(name)
        with entry.lock:
            if entry.decoder is None:
                entry.decoder = DecodeServer(
                    entry.net,
                    max_delay_ms=self.max_delay_ms,
                    on_batch=lambda **kw: self._record_batch(
                        name, kind="decode", **kw),
                    on_request=lambda s, t=None: self._record_request(
                        name, s, t))
            return entry.decoder

    # ------------------------------------------------------------ metrics
    def _record_request(self, name: str, seconds: float,
                        trace=None) -> None:
        entry = self._models.get(name)
        if trace is not None and entry is not None \
                and entry.latency_budget_ms is not None \
                and seconds * 1000.0 > entry.latency_budget_ms:
            # always-sample on latency over budget (post-hoc upgrade)
            trace.upgrade("latency_budget")
        tid = (trace.trace_id
               if trace is not None and trace.sampled else None)
        self.requests_total.labels(model=name).inc()
        # exemplar: tail buckets on /metrics point at a concrete trace
        self.latency.labels(model=name).observe(seconds, exemplar=tid)
        if entry is not None:
            with entry.lock:  # logits/argmax/decode callbacks race here
                entry.requests += 1
                entry.latencies.append(float(seconds))
        try:
            from ..telemetry.slo import get_slo_monitor  # noqa: PLC0415

            mon = get_slo_monitor()
            mon.observe(name, latency_s=float(seconds), trace_id=tid)
            mon.maybe_evaluate()
        except Exception:  # observability must never fail a request
            pass

    def _record_batch(self, name: str, *, rows: int, requests: int,
                      seconds: float, queue_depth: int,
                      bucket_rows: Optional[int] = None,
                      kind: str = "predict") -> None:
        from ..runtime.compile_manager import next_pow2

        bucket = bucket_rows if bucket_rows is not None else next_pow2(rows)
        fill = rows / bucket if bucket else 0.0
        self.batches_total.labels(model=name).inc()
        self.rows_total.labels(model=name).inc(rows)
        self.queue_depth.labels(model=name).set(queue_depth)
        self.batch_fill.labels(model=name).set(fill)
        entry = self._models.get(name)
        if entry is not None:
            with entry.lock:
                entry.rows += rows
                entry.batches += 1
                entry.fill_sum += fill
                entry.last_dispatch = {
                "kind": kind, "rows": rows, "requests": requests,
                "bucket_rows": bucket, "fill_ratio": round(fill, 4),
                "seconds": round(seconds, 6)}
        try:
            from ..telemetry.flight_recorder import get_flight_recorder  # noqa: PLC0415

            get_flight_recorder().record(
                "serve_dispatch", model=name, mode=kind, rows=int(rows),
                requests=int(requests), bucket_rows=int(bucket),
                fill_ratio=round(fill, 4), seconds=round(seconds, 6))
        except Exception:  # observability must never fail a request
            pass

    # -------------------------------------------------------------- stats
    def stats(self) -> dict:
        """JSON-ready serving snapshot (the /api/serving payload): per-model
        traffic, exact p50/p99 over the recent-latency ring, batch fill,
        live queue depth, decode stream state, plus the shared compile-cache
        view that explains executable tenancy."""
        from ..runtime.compile_manager import get_compile_manager

        with self._lock:
            entries = dict(self._models)
        from ..parallel.layout import layout_of  # noqa: PLC0415

        models = {}
        for name, e in entries.items():
            with e.lock:  # the ring keeps appending while we snapshot
                lats = list(e.latencies)
            lo = layout_of(e.net)
            models[name] = {
                "layout": lo.describe() if lo is not None else None,
                "requests_total": e.requests,
                "rows_total": e.rows,
                "batches_total": e.batches,
                "version": e.version,
                "swaps_total": e.swaps,
                "swapped_at": e.swapped_at,
                "queue_depth": (e.batcher.queue_depth()
                                + e.argmax_batcher.queue_depth()),
                "mean_batch_fill_ratio": (
                    round(e.fill_sum / e.batches, 4) if e.batches else None),
                "latency_seconds": {
                    "p50": _percentile(lats, 50),
                    "p99": _percentile(lats, 99),
                    "max": max(lats) if lats else None,
                    "samples": len(lats),
                },
                "last_dispatch": e.last_dispatch,
                "decode_sessions": (
                    e.decoder.sessions() if e.decoder is not None else 0),
                "batcher": {
                    "max_delay_ms": round(e.batcher.max_delay_s * 1000, 3),
                    "max_batch": e.batcher.max_batch,
                },
                "admission": {
                    "max_queue_depth": e.max_queue_depth,
                    "latency_budget_ms": e.latency_budget_ms,
                    "shed_total": e.shed,
                },
            }
        return {
            "models": models,
            "draining": self._draining,
            "compile_cache": get_compile_manager().stats(),
        }

    def stop(self) -> None:
        with self._lock:
            entries = list(self._models.values())
            self._models.clear()
        for e in entries:
            e.stop()


# ---------------------------------------------------------------- registry
# Named services replace the old single process-global: a process can host
# independent serving fronts (a fleet worker's own service next to the
# default UI one) and tests reset the whole registry instead of leaking
# models into each other through one shared singleton.
DEFAULT_SERVICE = "default"
_SERVICES: Dict[str, InferenceService] = {}
_SERVICES_LOCK = threading.Lock()


def get_service(name: str = DEFAULT_SERVICE) -> InferenceService:
    """The named serving front-end, created on first use. The no-arg call
    keeps its historic meaning: the process-wide default service (what
    the UI server exposes)."""
    with _SERVICES_LOCK:
        service = _SERVICES.get(name)
        if service is None:
            service = _SERVICES[name] = InferenceService()
        return service


def set_service(service: Optional[InferenceService],
                name: str = DEFAULT_SERVICE) -> None:
    """Install (or, with None, remove) a named service. The no-arg form
    swaps the process-wide default (tests / custom deployments)."""
    with _SERVICES_LOCK:
        if service is None:
            _SERVICES.pop(name, None)
        else:
            _SERVICES[name] = service


def service_names():
    with _SERVICES_LOCK:
        return sorted(_SERVICES)


def reset_services(*, stop: bool = True) -> None:
    """Test hook: clear the whole service registry (stopping batchers by
    default) so multi-service suites start from a clean slate."""
    with _SERVICES_LOCK:
        services = list(_SERVICES.values())
        _SERVICES.clear()
    if stop:
        for service in services:
            try:
                service.stop()
            except Exception:  # noqa: BLE001 - best-effort teardown
                pass
