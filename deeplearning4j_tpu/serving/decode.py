"""Continuous batching for stateful RNN decode.

Streaming decode (char-RNN sampling, seq2seq generation) is the worst case
for naive serving: every client holds private recurrent state and sends one
token at a time, so per-client dispatch runs the chip at batch 1. This
module keeps ONE slot-batched stream per model instead: each decode session
owns a row of the net's streaming ``rnn_time_step`` state, and a ticker
coalesces whichever sessions have a token pending (within the micro-batch
latency budget) into a single masked step over the full slot batch.

Exactness rides on the proven ``rnn_time_step`` mask contract: a slot whose
mask is 0 this tick holds its LSTM h/c bit-exactly — so idle sessions are
unaffected by other sessions' steps, and a session's output trajectory is
identical to running it alone (pinned by tests/test_serving.py).

Slot lifecycle: ``open()`` claims a free slot and zeroes its state rows
(host-side — session churn is rare next to step traffic), ``step()``
submits one token/frame, ``close()`` frees the slot.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from concurrent.futures import Future
from typing import Dict, Optional

import numpy as np

from ..telemetry.tracing import current_trace, record_trace_event
from .batcher import MAX_DELAY_ENV, _env_float

__all__ = ["DecodeServer", "DECODE_SLOTS_ENV"]

# env knob: slot capacity of the continuous decode batch (pow2 recommended —
# it IS the compiled batch dimension)
DECODE_SLOTS_ENV = "DL4JTPU_SERVE_DECODE_SLOTS"
_DEFAULT_SLOTS = 8


class _Pending:
    __slots__ = ("features", "future", "enqueued", "trace")

    def __init__(self, features: np.ndarray, trace=None):
        self.features = features
        self.future: "Future[np.ndarray]" = Future()
        self.enqueued = time.perf_counter()
        self.trace = trace  # session lineage (TraceContext) for this tick


class DecodeServer:
    """Slot-batched streaming decode over one recurrent net."""

    def __init__(self, net, *, capacity: Optional[int] = None,
                 max_delay_ms: Optional[float] = None,
                 on_batch=None, on_request=None):
        from ..runtime.compile_manager import next_pow2

        self.net = net
        cap = (int(_env_float(DECODE_SLOTS_ENV, _DEFAULT_SLOTS))
               if capacity is None else int(capacity))
        self.capacity = max(1, next_pow2(cap))
        self.max_delay_s = (
            _env_float(MAX_DELAY_ENV, 2.0)
            if max_delay_ms is None else float(max_delay_ms)) / 1000.0
        self._on_batch = on_batch
        self._on_request = on_request
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # serializes net state access: the ticker's step vs open()'s
        # slot-state reset (the net's _rnn_state is one shared pytree)
        self._net_lock = threading.Lock()
        self._sessions: Dict[str, int] = {}           # session id -> slot
        self._pending: Dict[int, _Pending] = {}       # slot -> request
        # session id -> sampled TraceContext: every tick of a session
        # parents under the SAME context, so a session's trace reads as one
        # lineage across ticks instead of disconnected fragments
        self._traces: Dict[str, object] = {}
        self._closed = False
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    # ------------------------------------------------------------ sessions
    def open(self, trace=None) -> str:
        """Claim a free slot; returns the session id. A sampled ``trace``
        (or the thread's current context) becomes the session's lineage:
        every subsequent tick span parents under it."""
        ctx = trace if trace is not None else current_trace()
        with self._lock:
            used = set(self._sessions.values())
            free = next((i for i in range(self.capacity) if i not in used),
                        None)
            if free is None:
                raise RuntimeError(
                    f"all {self.capacity} decode slots are in use "
                    f"(raise {DECODE_SLOTS_ENV})")
            sid = uuid.uuid4().hex[:12]
            self._sessions[sid] = free
            if ctx is not None and ctx.sampled:
                session_ctx = ctx.child()
                self._traces[sid] = session_ctx
                record_trace_event(session_ctx, "decode.open",
                                   session=sid, slot=free)
            self._reset_slot(free)
            return sid

    def close(self, session_id: str) -> None:
        with self._cv:
            slot = self._sessions.pop(session_id, None)
            self._traces.pop(session_id, None)
            pend = self._pending.pop(slot, None) if slot is not None else None
        if pend is not None:
            pend.future.set_exception(RuntimeError("session closed"))

    def sessions(self) -> int:
        with self._lock:
            return len(self._sessions)

    def _reset_slot(self, slot: int) -> None:
        """Zero one slot's rows of the streaming state (fresh session).
        Host-side round trip by design: churn is rare, and a device-side
        per-slot scatter would compile one tiny program per slot index."""
        import jax  # noqa: PLC0415
        import jax.numpy as jnp  # noqa: PLC0415

        def zero_row(a):
            host = np.array(a)
            host[slot] = 0
            return jnp.asarray(host)

        with self._net_lock:
            if self.net._rnn_state is None:
                return  # first tick initializes a zero state anyway
            self.net._rnn_state = jax.tree_util.tree_map(
                zero_row, self.net._rnn_state)

    # ---------------------------------------------------------------- step
    def step(self, session_id: str, features, timeout_s: float = 30.0):
        """One decode step for a session: ``features`` is a single frame
        [features...]. Returns the net's output row for that frame once the
        coalesced tick it joined has run."""
        features = np.asarray(features)
        with self._cv:
            slot = self._sessions.get(session_id)
            if slot is None:
                raise KeyError(f"unknown decode session {session_id!r}")
            if slot in self._pending:
                raise RuntimeError(
                    f"session {session_id!r} already has a step in flight")
            pend = _Pending(features, trace=self._traces.get(session_id))
            self._pending[slot] = pend
            self._cv.notify()
        return pend.future.result(timeout=timeout_s)

    # --------------------------------------------------------------- ticker
    def _collect(self) -> Dict[int, _Pending]:
        with self._cv:
            while not self._pending and not self._closed:
                self._cv.wait(timeout=0.1)
            if self._closed:
                return {}
            first_t = min(p.enqueued for p in self._pending.values())
            deadline = first_t + self.max_delay_s
            # wait out the budget so concurrent sessions join this tick;
            # a full slot set dispatches immediately
            while (len(self._pending) < len(self._sessions)
                   and not self._closed):
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                self._cv.wait(timeout=remaining)
            batch = dict(self._pending)
            self._pending.clear()
            return batch

    def _run(self) -> None:
        while True:
            batch = self._collect()
            if not batch:
                if self._closed:
                    return
                continue
            t0 = time.perf_counter()
            try:
                feat_dim = next(iter(batch.values())).features.shape
                x = np.zeros((self.capacity, 1) + tuple(feat_dim),
                             np.float32)
                mask = np.zeros((self.capacity, 1), np.float32)
                for slot, pend in batch.items():
                    x[slot, 0] = pend.features
                    mask[slot, 0] = 1.0
                with self._net_lock:
                    # _net_lock exists precisely to serialize the single
                    # stateful net's rnn_time_step against swap()
                    out = self.net.rnn_time_step(  # dl4jtpu: ignore[DT401]
                        x, features_mask=mask)
                out = np.asarray(out)
                if out.ndim == 3:  # [slots, 1, C] -> [slots, C]
                    out = out[:, 0]
            except Exception as e:  # noqa: BLE001 - reject THIS tick only
                for pend in batch.values():
                    pend.future.set_exception(e)
                continue
            seconds = time.perf_counter() - t0
            done = time.perf_counter()
            for slot, pend in batch.items():
                pend.future.set_result(out[slot])
                if pend.trace is not None and pend.trace.sampled:
                    record_trace_event(
                        pend.trace.child(), "decode.tick", slot=slot,
                        duration_s=done - pend.enqueued,
                        tick_rows=len(batch))
                if self._on_request is not None:
                    self._on_request(done - pend.enqueued, pend.trace)
            if self._on_batch is not None:
                self._on_batch(rows=len(batch), requests=len(batch),
                               seconds=seconds, queue_depth=0,
                               bucket_rows=self.capacity)

    def stop(self) -> None:
        with self._cv:
            self._closed = True
            pend = list(self._pending.values())
            self._pending.clear()
            self._cv.notify_all()
        for p in pend:
            p.future.set_exception(RuntimeError("decode server stopped"))
        self._worker.join(timeout=5)
