"""Telemetry session: the glue between jitted steps and the registry.

One :class:`Telemetry` object rides a training run. The fit paths hand it
the device-side metrics vector each step (``on_step``) or the whole stacked
``[steps, NUM_SLOTS]`` array of a staged dispatch (``on_staged``); it fetches
to host at most once every ``fetch_every`` steps (ONE ``np.asarray`` of the
stacked pending vectors), records into the registry, and feeds the watchdog.

The overhead contract, explicit because it is the whole point:

- ``on_step`` appends a device scalar vector and bumps host-side counters —
  no device read, no sync. The step's async dispatch pipeline is untouched.
- A fetch happens when K vectors are pending (or at ``flush()``, which the
  fit loops call once at the end of training). ``fetch_count`` is public so
  tests can assert the ceil(steps/K) bound.
- ``on_staged`` is one fetch for the whole dispatch regardless of K: the
  scan already materialized per-step rows, and the losses fetch that
  precedes it has already paid the sync.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from . import device as device_stats
from .registry import MetricsRegistry, get_registry
from .watchdog import Watchdog


class Telemetry:
    """Per-run recorder: K-step device fetch -> registry + watchdog."""

    # staticmethod indirection so tests can count host fetches
    _fetch = staticmethod(np.asarray)

    DEFAULT_FETCH_EVERY = 10

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        fetch_every: Optional[int] = None,
        watchdog: Optional[Watchdog] = None,
        prefix: str = "dl4jtpu_train",
        flight_recorder=None,
        sample_memory: bool = True,
    ):
        from .flight_recorder import get_flight_recorder  # noqa: PLC0415

        self.registry = registry if registry is not None else get_registry()
        # None = library default AND tunable: the tuned-config auto-apply
        # (tune/store.py) may retarget the cadence; an explicit value is a
        # user setting and always wins
        self.fetch_every_explicit = fetch_every is not None
        self.fetch_every = max(1, int(
            self.DEFAULT_FETCH_EVERY if fetch_every is None else fetch_every))
        self.watchdog = watchdog
        # black box: step rows ring into the flight recorder at fetch time,
        # and the recorder rides the watchdog as a sink so an anomaly dumps
        # a post-mortem bundle (telemetry/flight_recorder.py)
        self.flight = (flight_recorder if flight_recorder is not None
                       else get_flight_recorder())
        self.sample_memory = bool(sample_memory)
        if self.watchdog is not None and self.flight is not None:
            if not any(getattr(s, "__self__", None) is self.flight
                       for s in self.watchdog.sinks):
                self.watchdog.add_sink(self.flight.watchdog_sink)
        self.fetch_count = 0
        self._pending: List[Tuple[int, object, Optional[float]]] = []
        self._last_step_t: Optional[float] = None
        r = self.registry
        self.steps = r.counter(f"{prefix}_steps_total",
                               "optimizer steps dispatched")
        self.loss_gauge = r.gauge(f"{prefix}_loss",
                                  "last fetched training loss")
        self.grad_norm_gauge = r.gauge(f"{prefix}_grad_norm",
                                       "last fetched global gradient norm")
        self.grad_norm_hist = r.histogram(
            f"{prefix}_grad_norm_hist", "fetched global gradient norms",
            buckets=(0.001, 0.01, 0.1, 0.5, 1.0, 2.0, 5.0, 10.0, 50.0,
                     100.0, 1000.0),
        )
        self.step_time_hist = r.histogram(
            f"{prefix}_step_time_seconds",
            "per-step wall time (staged dispatches attribute the dispatch "
            "evenly across its steps)",
        )
        self.nonfinite_steps = r.counter(
            f"{prefix}_nonfinite_steps_total",
            "steps whose loss/gradients contained NaN/Inf")
        self.fetches = r.counter(
            f"{prefix}_fetches_total",
            "host fetches of device-side step metrics")

    # ------------------------------------------------------------- per-step
    def on_step(self, iteration: int, mvec,
                step_time_s: Optional[float] = None) -> None:
        """Record one step's DEVICE metrics vector; fetch only at K pending.

        When no explicit ``step_time_s`` is given, the wall-clock delta
        since the previous ``on_step`` stands in — under async dispatch the
        queue's backpressure makes the steady-state inter-dispatch interval
        the honest per-step time (PerformanceListener's convention); the
        first step of a run has no interval and records none.
        """
        import time  # noqa: PLC0415

        now = time.perf_counter()
        if step_time_s is None and self._last_step_t is not None:
            step_time_s = now - self._last_step_t
        self._last_step_t = now
        self.steps.inc()
        if step_time_s is not None:
            self.step_time_hist.observe(step_time_s)
        self._pending.append((int(iteration), mvec, step_time_s))
        if len(self._pending) >= self.fetch_every:
            self.flush()

    def flush(self) -> None:
        """Fetch all pending vectors in ONE host sync and record them."""
        if not self._pending:
            return
        import jax.numpy as jnp  # noqa: PLC0415 - keep module import light

        pending, self._pending = self._pending, []
        rows = self._fetch(jnp.stack([m for _, m, _ in pending]))
        self.fetch_count += 1
        self.fetches.inc()
        for (iteration, _, step_time_s), row in zip(pending, rows):
            self._record_row(iteration, row, step_time_s)
        self._sample_memory()

    # -------------------------------------------------------------- staged
    def on_staged(self, first_iteration: int, mvecs,
                  per_step_time_s: Optional[float] = None) -> None:
        """Record a staged dispatch's ``[steps, NUM_SLOTS]`` metrics.

        One fetch for the whole window; ``per_step_time_s`` is the even
        per-step share of the dispatch wall time (callback wall-clock deltas
        measure nothing during the post-scan replay — same convention as
        ``fit_on_device``'s ``staged_step_time``).
        """
        rows = self._fetch(mvecs)
        self.fetch_count += 1
        self.fetches.inc()
        self.steps.inc(len(rows))
        self._last_step_t = None  # wall deltas across a staged window lie
        for j, row in enumerate(rows):
            if per_step_time_s is not None:
                self.step_time_hist.observe(per_step_time_s)
            self._record_row(first_iteration + j, row, per_step_time_s)
        self._sample_memory()

    # ------------------------------------------------------------- shared
    def _sample_memory(self) -> None:
        """Live HBM gauges + peak watermark, once per fetch (never per
        step); the watermark also rings into the flight recorder."""
        if not self.sample_memory:
            return
        from . import memory as _tmem  # noqa: PLC0415

        _tmem.sample_device_memory(self.registry, flight=self.flight)

    def _record_row(self, iteration: int, row,
                    step_time_s: Optional[float]) -> None:
        loss = float(row[device_stats.LOSS])
        gnorm = float(row[device_stats.GRAD_NORM])
        nonfinite = float(row[device_stats.NONFINITE])
        self.loss_gauge.set(loss)
        self.grad_norm_gauge.set(gnorm)
        if np.isfinite(gnorm):
            self.grad_norm_hist.observe(gnorm)
        if nonfinite > 0:
            self.nonfinite_steps.inc()
        if self.flight is not None:
            # the step's row rings into the black box AT FETCH TIME — the
            # steady-state cost is K dict appends per host sync, not per step
            self.flight.record(
                "step", iteration=int(iteration), loss=loss, grad_norm=gnorm,
                nonfinite=nonfinite,
                step_time_s=(None if step_time_s is None
                             else float(step_time_s)))
        if self.watchdog is not None:
            self.watchdog.observe(iteration, loss, gnorm, nonfinite,
                                  step_time_s)
