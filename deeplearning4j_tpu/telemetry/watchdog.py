"""Watchdog: turn fetched step metrics into structured anomaly events.

Consumes the rows the K-step fetch materializes (loss, grad-norm, non-finite
flag, optional step time) and emits :class:`AnomalyEvent`s for the failure
modes that silently burn TPU-hours in production:

- ``nan-loss``: the non-finite flag fired or the fetched loss is NaN/Inf
  (the reference's training just diverged quietly; here an alertable event).
- ``exploding-grad-norm``: grad norm above ``grad_norm_limit``.
- ``stalled-step-time``: a step took more than ``stall_factor`` times the
  rolling median (or more than ``step_time_limit_s`` absolutely) — the
  tunnel-hang / input-starvation signature.

Sinks are pluggable callables ``sink(event)``; the default keeps events in
``watchdog.events`` and logs a warning. Every event also increments
``dl4jtpu_anomalies_total{kind=...}`` in the registry, so an alerting stack
can fire off the counter without parsing logs.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from .registry import MetricsRegistry, get_registry

logger = logging.getLogger(__name__)

NAN_LOSS = "nan-loss"
EXPLODING_GRAD_NORM = "exploding-grad-norm"
STALLED_STEP_TIME = "stalled-step-time"
# online-learning drift kinds (emitted by runtime/online.py through
# Watchdog.emit — the same sink/counter/flight-dump plumbing as the
# per-step kinds above; see docs/streaming.md)
LOSS_DRIFT = "loss-drift"
INPUT_SHIFT = "input-shift"
# SLO burn-rate breach (emitted by telemetry/slo.py through Watchdog.emit;
# auto-dumps a flight bundle whose spans section carries the offending
# sampled traces — see docs/observability.md)
SLO_BURN = "slo-burn"


@dataclass(frozen=True)
class AnomalyEvent:
    kind: str           # NAN_LOSS | EXPLODING_GRAD_NORM | STALLED_STEP_TIME
    iteration: int
    value: float        # the offending measurement
    threshold: float    # the limit it crossed
    message: str
    timestamp: float = field(default_factory=time.time)

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "iteration": self.iteration,
            "value": self.value,
            "threshold": self.threshold,
            "message": self.message,
            "timestamp": self.timestamp,
        }


def logging_sink(event: AnomalyEvent) -> None:
    logger.warning("telemetry watchdog: %s", event.to_dict())


class Watchdog:
    """Anomaly detector over fetched step metrics."""

    def __init__(
        self,
        sinks: Optional[List[Callable[[AnomalyEvent], None]]] = None,
        grad_norm_limit: float = 1e3,
        step_time_limit_s: Optional[float] = None,
        stall_factor: float = 10.0,
        stall_warmup_steps: int = 5,
        registry: Optional[MetricsRegistry] = None,
    ):
        self.sinks = list(sinks) if sinks is not None else [logging_sink]
        self.grad_norm_limit = float(grad_norm_limit)
        self.step_time_limit_s = step_time_limit_s
        self.stall_factor = float(stall_factor)
        self.stall_warmup_steps = int(stall_warmup_steps)
        self.events: List[AnomalyEvent] = []
        self._step_times: List[float] = []
        # guards sinks/events/_step_times: observe() runs on the fetch
        # thread while add_sink()/emit() arrive from serving/online threads
        self._lock = threading.Lock()
        reg = registry if registry is not None else get_registry()
        self._anomalies = reg.counter(
            "dl4jtpu_anomalies_total",
            "watchdog anomaly events by kind",
            labelnames=("kind",),
        )

    def add_sink(self, sink: Callable[[AnomalyEvent], None]) -> None:
        with self._lock:
            self.sinks.append(sink)

    def _emit(self, kind: str, iteration: int, value: float,
              threshold: float, message: str) -> None:
        event = AnomalyEvent(kind=kind, iteration=iteration, value=value,
                             threshold=threshold, message=message)
        with self._lock:
            self.events.append(event)
            sinks = list(self.sinks)
        self._anomalies.labels(kind=kind).inc()
        for sink in sinks:
            try:
                sink(event)
            except Exception:  # a broken sink must never kill the train loop
                logger.exception("telemetry watchdog sink failed")

    def emit(self, kind: str, iteration: int, value: float,
             threshold: float, message: str) -> None:
        """Emit a caller-detected anomaly through the watchdog's sinks and
        counter — the hook the online-learning drift detectors use (their
        signals live in window statistics the per-step ``observe`` path
        never sees)."""
        self._emit(str(kind), int(iteration), float(value), float(threshold),
                   str(message))

    def observe(self, iteration: int, loss: float, grad_norm: float,
                nonfinite: float = 0.0,
                step_time_s: Optional[float] = None) -> None:
        """Check one fetched step row; called by Telemetry at fetch time."""
        if nonfinite > 0 or not math.isfinite(loss):
            self._emit(
                NAN_LOSS, iteration, loss, 0.0,
                f"non-finite loss/gradients at iteration {iteration} "
                f"(loss={loss})",
            )
        elif math.isfinite(grad_norm) and grad_norm > self.grad_norm_limit:
            self._emit(
                EXPLODING_GRAD_NORM, iteration, grad_norm,
                self.grad_norm_limit,
                f"gradient norm {grad_norm:.4g} exceeds limit "
                f"{self.grad_norm_limit:.4g} at iteration {iteration}",
            )
        if step_time_s is None:
            return
        limit = None
        if self.step_time_limit_s is not None:
            limit = float(self.step_time_limit_s)
        else:
            with self._lock:
                if len(self._step_times) >= self.stall_warmup_steps:
                    med = sorted(self._step_times)[
                        len(self._step_times) // 2]
                    limit = med * self.stall_factor
        if limit is not None and step_time_s > limit:
            self._emit(
                STALLED_STEP_TIME, iteration, step_time_s, limit,
                f"step {iteration} took {step_time_s:.4g}s "
                f"(limit {limit:.4g}s)",
            )
        else:
            # stalls don't poison the baseline median
            with self._lock:
                self._step_times.append(float(step_time_s))
                if len(self._step_times) > 256:
                    del self._step_times[0]
