"""Metric time-series history: the autoscaler's sensor suite.

Every observability surface before this one answered "what is true right
now" (``/metrics``, ``/api/fleet``, ``/api/serving``). ROADMAP direction
2 (traffic-aware autoscaling + predictive warm pools) needs *trends*:
per-model offered load over time, queue-depth history, measured
bundle-boot→READY seconds. This module is that sensor plane:

- :class:`HistoryStore` — a bounded, multi-resolution in-process
  time-series store. Each series keeps a raw ring plus 1m/5m rollup
  rings (count/sum/min/max/last per bucket), so a query spanning hours
  downsamples instead of truncating. Counters are recorded as
  **derived rates** with Prometheus-style monotonic-reset handling;
  histogram snapshots become interval-quantile series (``name:p50`` /
  ``name:p99``). Timestamps come from a wall-anchored *monotonic*
  clock (never step backward under NTP) and every recording/query
  method takes an explicit injected ``now`` for tests.
- :class:`HistorySampler` — ticks a :class:`MetricsRegistry` snapshot
  into the store on a ``Deadline``-paced thread (the sanctioned
  no-``time.sleep`` pacing idiom from ``runtime/resilience.py``).
- :class:`FleetRecordingRules` — derives the named autoscaler sensors
  from a router's fleet stats (offered load, shed rate, exact p99 from
  the merged latency rings, queue depth, boot→READY seconds, warm-pool
  compile counts) and maintains EWMA + Holt linear-trend forecasts per
  key sensor, exported as ``dl4jtpu_forecast_*`` gauges with horizon
  labels (``ewma`` / ``trend_per_s`` / ``60s`` / ``300s``).

Stale-series rule (the PR 17 stale-ring rule applied to ingestion): a
worker whose heartbeat exceeds ``max(5·poll_s, 2s)`` has its series
marked stale via :meth:`HistoryStore.mark_stale` — an **explicit gap**
point (value ``None``), never a silently flat-lined last value —
counted in ``dl4jtpu_history_stale_series_total``. The next real sample
under the same labels (a respawned worker keeps its worker id) clears
the flag and the series resumes.

Memory is bounded by construction: per-series rings are fixed-length
deques, the series map is LRU-capped (``max_series``), and the
estimated footprint is exported as ``dl4jtpu_history_bytes`` (the soak
test asserts it stays under :attr:`HistoryStore.byte_budget`).

``GET /api/history`` (router, worker, UI server) serves
:meth:`HistoryStore.http_query`; docs/observability.md § "Metric
history & derived signals" documents the query grammar.
"""

from __future__ import annotations

import math
import os
import re
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from .registry import MetricsRegistry, get_registry

__all__ = [
    "HISTORY_ENV",
    "HISTORY_INTERVAL_ENV",
    "FORECAST_HORIZONS_S",
    "FORECAST_SENSORS",
    "RECORDING_RULES",
    "Forecast",
    "FleetRecordingRules",
    "HistorySampler",
    "HistoryStore",
    "ensure_default_sampler",
    "get_default_sampler",
    "get_history_store",
    "history_enabled",
    "parse_prometheus_text",
    "set_default_sampler",
    "set_history_store",
]

HISTORY_ENV = "DL4JTPU_HISTORY"               # "0"/"false" disables
HISTORY_INTERVAL_ENV = "DL4JTPU_HISTORY_INTERVAL_S"  # sampler tick, s

# resolution ladder: raw ring + rollup rings (seconds -> ring length).
# Defaults hold ~6 min of raw, 4 h of 1m buckets, 24 h of 5m buckets.
_RAW_LEN = 360
_ROLLUPS: Tuple[Tuple[float, int], ...] = ((60.0, 240), (300.0, 288))
_MAX_SERIES = 512
_MAX_ANNOTATIONS = 256

# footprint model (measured CPython approximations, documented in
# docs/observability.md): a raw point is a (float, float) tuple in a
# deque slot; a rollup bucket is a 6-slot object.
_POINT_BYTES = 120
_BUCKET_BYTES = 240
_SERIES_BYTES = 640        # per-series fixed overhead (dict entry, deques)
_ANNOTATION_BYTES = 512

# the recording-rule series FleetRecordingRules derives — the autoscaler
# sensor suite by name (docs/observability.md has the full table)
RECORDING_RULES: Tuple[str, ...] = (
    "fleet.offered_load",          # requests/s per model (counter->rate)
    "fleet.shed_rate",             # sheds/s per model (counter->rate)
    "fleet.latency_p50_seconds",   # exact, merged worker latency rings
    "fleet.latency_p99_seconds",   # exact, merged worker latency rings
    "fleet.queue_depth",           # summed ready-worker queue depth
    "fleet.workers_ready",         # live, ready worker count
    "worker.queue_depth",          # per {worker,model}
    "worker.boot_ready_seconds",   # spawn->READY_SENTINEL, per worker
    "worker.compiles_since_ready",  # warm-pool signal, per worker
)

# sensors that additionally carry EWMA/Holt forecasts
FORECAST_SENSORS: Tuple[str, ...] = (
    "offered_load", "shed_rate", "latency_p99_seconds", "queue_depth")
FORECAST_HORIZONS_S: Tuple[float, ...] = (60.0, 300.0)

_AGGS = ("mean", "min", "max", "last", "sum")


def history_enabled() -> bool:
    """The ``DL4JTPU_HISTORY`` kill switch (default: enabled)."""
    raw = os.environ.get(HISTORY_ENV, "1").strip().lower()
    return raw not in ("0", "false", "off", "no")


def _interval_from_env(default: float = 1.0) -> float:
    raw = os.environ.get(HISTORY_INTERVAL_ENV)
    if not raw:
        return default
    try:
        return max(0.01, float(raw))
    except ValueError:
        return default


# --------------------------------------------------------------------- store

class _Bucket:
    """One rollup bucket: count/sum/min/max/last over a resolution window."""

    __slots__ = ("start", "count", "sum", "min", "max", "last")

    def __init__(self, start: float, value: float):
        self.start = start
        self.count = 1
        self.sum = value
        self.min = value
        self.max = value
        self.last = value

    def add(self, value: float) -> None:
        self.count += 1
        self.sum += value
        self.min = min(self.min, value)
        self.max = max(self.max, value)
        self.last = value

    def agg(self, how: str) -> float:
        if how == "mean":
            return self.sum / self.count
        if how == "min":
            return self.min
        if how == "max":
            return self.max
        if how == "sum":
            return self.sum
        return self.last


class _Series:
    """One named+labelled series: raw ring + rollup rings + counter state."""

    __slots__ = ("name", "labels", "kind", "raw", "rollups",
                 "last_cum", "last_cum_ts", "resets", "stale", "last_ts")

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...],
                 kind: str, raw_len: int,
                 rollups: Tuple[Tuple[float, int], ...]):
        self.name = name
        self.labels = labels
        self.kind = kind
        self.raw: deque = deque(maxlen=raw_len)   # (ts, value|None)
        self.rollups: Dict[float, deque] = {
            res: deque(maxlen=length) for res, length in rollups}
        self.last_cum: Optional[float] = None     # counter rate state
        self.last_cum_ts = 0.0
        self.resets = 0
        self.stale = False
        self.last_ts = 0.0


class HistoryStore:
    """Bounded multi-resolution time-series store with injectable clock."""

    def __init__(self, registry: Optional[MetricsRegistry] = None, *,
                 raw_len: int = _RAW_LEN,
                 rollups: Tuple[Tuple[float, int], ...] = _ROLLUPS,
                 max_series: int = _MAX_SERIES,
                 max_annotations: int = _MAX_ANNOTATIONS):
        reg = registry if registry is not None else get_registry()
        self.registry = reg
        self.raw_len = int(raw_len)
        self.rollups = tuple((float(r), int(n)) for r, n in rollups)
        self.max_series = int(max_series)
        self.max_annotations = int(max_annotations)
        # wall-anchored monotonic clock: comparable to time.time() (flight
        # events, cross-process splicing) but immune to NTP steps
        self._wall0 = time.time()
        self._mono0 = time.monotonic()
        # record() lands from sampler/scrape threads while query() runs on
        # HTTP handler threads — every structure below is guarded here.
        # Reentrant: compound operations (record_counter, query) hold it
        # across the helper calls that re-acquire it
        self._lock = threading.RLock()
        self._series: Dict[tuple, _Series] = {}
        self._hist_state: Dict[tuple, tuple] = {}  # (ts, bounds, cum counts)
        self._annotations: deque = deque(maxlen=self.max_annotations)
        self.samples_total = 0
        self.evicted_total = 0
        self.stale_marked_total = 0
        self._m_samples = reg.counter(
            "dl4jtpu_history_samples_total",
            "time-series points recorded into the history store")
        self._m_series = reg.gauge(
            "dl4jtpu_history_series",
            "live series held by the history store")
        self._m_bytes = reg.gauge(
            "dl4jtpu_history_bytes",
            "estimated history-store footprint (rings + rollups + "
            "annotations), bounded by construction")
        self._m_stale = reg.counter(
            "dl4jtpu_history_stale_series_total",
            "series marked stale (explicit gap) because their worker's "
            "heartbeat exceeded the stale cutoff")
        self._m_evicted = reg.counter(
            "dl4jtpu_history_evicted_series_total",
            "series evicted (LRU) to hold the max_series bound")
        self._m_annotations = reg.counter(
            "dl4jtpu_history_annotations_total",
            "timeline annotations spliced from flight events, by kind",
            labelnames=("kind",))

    # ------------------------------------------------------------- clock
    def now(self) -> float:
        """Wall-anchored monotonic timestamp (seconds)."""
        return self._wall0 + (time.monotonic() - self._mono0)

    def _ts(self, now: Optional[float]) -> float:
        return self.now() if now is None else float(now)

    @property
    def byte_budget(self) -> int:
        """The documented worst-case footprint at this configuration."""
        per_series = (self.raw_len * _POINT_BYTES + _SERIES_BYTES
                      + sum(n * _BUCKET_BYTES for _, n in self.rollups))
        return (self.max_series * per_series
                + self.max_annotations * _ANNOTATION_BYTES)

    # ----------------------------------------------------------- recording
    def _key(self, name: str, labels: Optional[dict]) -> tuple:
        lab = tuple(sorted((str(k), str(v))
                           for k, v in (labels or {}).items()))
        return (str(name), lab)

    def _get_series(self, name: str, labels: Optional[dict],
                    kind: str) -> _Series:
        """Find-or-create a series; LRU-evict past max_series."""
        key = self._key(name, labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                if len(self._series) >= self.max_series:
                    victim = min(self._series,
                                 key=lambda k: self._series[k].last_ts)
                    del self._series[victim]
                    self.evicted_total += 1
                    self._m_evicted.inc()
                s = _Series(str(name), key[1], kind, self.raw_len,
                            self.rollups)
                self._series[key] = s
            return s

    def _append(self, s: _Series, ts: float, value: float) -> None:
        with self._lock:
            s.raw.append((ts, value))
            s.last_ts = ts
            s.stale = False  # a real sample resumes a stale series
            for res, ring in s.rollups.items():
                start = math.floor(ts / res) * res
                if ring and ring[-1].start == start:
                    ring[-1].add(value)
                elif not ring or ring[-1].start < start:
                    ring.append(_Bucket(start, value))
                # late sample older than the open bucket: raw keeps it
            self.samples_total += 1
            self._m_samples.inc()

    def record_gauge(self, name: str, value: float,
                     labels: Optional[dict] = None,
                     now: Optional[float] = None) -> float:
        """Record one gauge point; returns the recorded value."""
        ts = self._ts(now)
        v = float(value)
        with self._lock:
            s = self._get_series(name, labels, "gauge")
            self._append(s, ts, v)
        return v

    def record_counter(self, name: str, cumulative: float,
                       labels: Optional[dict] = None,
                       now: Optional[float] = None) -> Optional[float]:
        """Record a cumulative counter observation; the stored point is
        the derived per-second RATE. A drop in the cumulative value is a
        monotonic reset (process respawn): the rate is computed from the
        post-reset value alone, Prometheus ``rate()`` convention. The
        first observation is baseline-only and returns None."""
        ts = self._ts(now)
        v = float(cumulative)
        with self._lock:
            s = self._get_series(name, labels, "counter")
            prev, prev_ts = s.last_cum, s.last_cum_ts
            s.last_cum, s.last_cum_ts = v, ts
            if prev is None or ts <= prev_ts:
                s.last_ts = ts
                s.stale = False
                return None
            delta = v - prev
            if delta < 0:  # counter reset
                s.resets += 1
                delta = v
            rate = delta / (ts - prev_ts)
            self._append(s, ts, rate)
        return rate

    def record_histogram(self, name: str, buckets: dict,
                         labels: Optional[dict] = None,
                         now: Optional[float] = None,
                         quantiles: Tuple[float, ...] = (0.5, 0.99),
                         ) -> Dict[str, float]:
        """Turn a cumulative histogram snapshot (``{bound_str: cum_count}``
        with a ``+Inf`` key — the shape ``MetricFamily.summary()`` and the
        Prometheus text buckets produce) into interval-quantile gauge
        points named ``<name>:p50`` / ``<name>:p99``. The first snapshot
        per series is baseline-only."""
        ts = self._ts(now)
        try:
            parsed = sorted((float(b), float(c)) for b, c in buckets.items())
        except (TypeError, ValueError):
            return {}
        bounds = [b for b, _ in parsed]
        cum = [c for _, c in parsed]
        key = self._key(name, labels)
        out: Dict[str, float] = {}
        with self._lock:
            prev = self._hist_state.get(key)
            if len(self._hist_state) >= self.max_series and key not in \
                    self._hist_state:
                victim = min(self._hist_state,
                             key=lambda k: self._hist_state[k][0])
                del self._hist_state[victim]
            self._hist_state[key] = (ts, bounds, cum)
            if prev is None or prev[1] != bounds:
                return {}
            interval = [c - p for c, p in zip(cum, prev[2])]
            if any(x < 0 for x in interval):  # histogram reset (respawn)
                interval = list(cum)
            # de-cumulate into per-bucket counts
            per_bucket = [interval[0]] + [
                interval[i] - interval[i - 1]
                for i in range(1, len(interval))]
            total = interval[-1] if interval else 0.0
            if total <= 0:
                return {}
            for q in quantiles:
                v = _bucket_quantile(bounds, per_bucket, total, q)
                if v is None:
                    continue
                qname = f"{name}:p{int(round(q * 100))}"
                s = self._get_series(qname, labels, "gauge")
                self._append(s, ts, v)
                out[qname] = v
        return out

    # -------------------------------------------------------------- stale
    def mark_stale(self, labels: dict,
                   now: Optional[float] = None) -> int:
        """Mark every series carrying ``labels`` (subset match) stale:
        append one explicit gap point (value None) and count it. Series
        already stale are not re-marked; the next real sample under the
        same labels resumes the series."""
        ts = self._ts(now)
        want = {str(k): str(v) for k, v in labels.items()}
        marked = 0
        with self._lock:
            for s in self._series.values():
                lab = dict(s.labels)
                if s.stale or not all(lab.get(k) == v
                                      for k, v in want.items()):
                    continue
                if not s.raw:
                    continue
                s.raw.append((ts, None))  # the gap — never a flat-line
                s.stale = True
                s.last_ts = ts
                marked += 1
            self.stale_marked_total += marked
        if marked:
            self._m_stale.inc(marked)
        return marked

    # -------------------------------------------------------- annotations
    def annotate(self, kind: str, now: Optional[float] = None,
                 record_flight: bool = True, **payload) -> dict:
        """Splice one timeline annotation (rollout/respawn/swap/slo-burn
        flight events, or anything an operator posts). Rings a
        ``history_annotation`` flight event so the black box shows the
        splice itself."""
        ts = self._ts(now)
        ann = {"ts": ts, "kind": str(kind)}
        for k, v in payload.items():
            ann[str(k)] = v if isinstance(v, (int, float, bool, type(None))) \
                else str(v)[:200]
        with self._lock:
            self._annotations.append(ann)
        self._m_annotations.labels(kind=str(kind)).inc()
        if record_flight:
            try:
                from .flight_recorder import get_flight_recorder  # noqa: PLC0415

                get_flight_recorder().record(
                    "history_annotation", source_kind=str(kind), at=ts)
            except Exception:  # noqa: BLE001 - annotation must never raise
                pass
        return ann

    def annotations(self, start: Optional[float] = None,
                    end: Optional[float] = None) -> List[dict]:
        with self._lock:
            anns = list(self._annotations)
        if start is not None:
            anns = [a for a in anns if a["ts"] >= start]
        if end is not None:
            anns = [a for a in anns if a["ts"] <= end]
        return anns

    # ----------------------------------------------------------- ingestion
    def ingest_snapshot(self, snapshot: dict,
                        extra_labels: Optional[dict] = None,
                        prefix: str = "dl4jtpu_",
                        now: Optional[float] = None) -> int:
        """Ingest a ``MetricsRegistry.snapshot()``: counters become rate
        series, gauges record as-is, histograms become interval-quantile
        series. Returns the number of rows ingested."""
        ts = self._ts(now)
        rows = 0
        for name, fam in snapshot.items():
            if not name.startswith(prefix):
                continue
            kind = fam.get("type")
            for row in fam.get("values", ()):
                labels = dict(row.get("labels") or {})
                if extra_labels:
                    labels.update(extra_labels)
                if kind == "counter":
                    self.record_counter(name, row["value"], labels, now=ts)
                elif kind == "gauge":
                    self.record_gauge(name, row["value"], labels, now=ts)
                elif kind == "histogram":
                    self.record_histogram(name, row.get("buckets") or {},
                                          labels, now=ts)
                else:
                    continue
                rows += 1
        self._update_footprint()
        return rows

    def ingest_prometheus(self, text: str,
                          extra_labels: Optional[dict] = None,
                          prefix: str = "dl4jtpu_",
                          now: Optional[float] = None) -> int:
        """Ingest a Prometheus text exposition (a worker's ``/metrics``).
        Histogram families are reassembled from their ``_bucket`` lines
        into interval-quantile series; ``_count`` records as a rate."""
        ts = self._ts(now)
        types, samples = parse_prometheus_text(text)
        rows = 0
        hist_cum: Dict[tuple, dict] = {}
        for name, labels, value in samples:
            base = name
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix) and \
                        types.get(name[: -len(suffix)]) == "histogram":
                    base = name[: -len(suffix)]
                    break
            if not base.startswith(prefix):
                continue
            lab = dict(labels)
            if base != name and name.endswith("_bucket"):
                le = lab.pop("le", None)
                if le is None:
                    continue
                if extra_labels:
                    lab.update(extra_labels)
                hist_cum.setdefault(
                    (base, tuple(sorted(lab.items()))), {})[le] = value
                continue
            if extra_labels:
                lab.update(extra_labels)
            if base != name and name.endswith("_sum"):
                continue  # quantiles + count-rate carry the signal
            if base != name and name.endswith("_count"):
                self.record_counter(f"{base}:count", value, lab, now=ts)
                rows += 1
                continue
            kind = types.get(name, "gauge")
            if kind == "counter":
                self.record_counter(name, value, lab, now=ts)
            else:
                self.record_gauge(name, value, lab, now=ts)
            rows += 1
        for (base, labkey), buckets in hist_cum.items():
            self.record_histogram(base, buckets, dict(labkey), now=ts)
            rows += 1
        self._update_footprint()
        return rows

    # --------------------------------------------------------------- query
    def query(self, select=None, labels: Optional[dict] = None,
              start: Optional[float] = None, end: Optional[float] = None,
              range_s: float = 300.0, step: Optional[float] = None,
              agg: str = "mean", now: Optional[float] = None,
              limit: int = 256) -> dict:
        """Query the store. ``select``: None (all), a name, or a list of
        names; a name ending in ``*`` prefix-matches. ``labels``: subset
        filter. Time range: ``[start, end]`` absolute seconds (default:
        the trailing ``range_s`` window). ``step``: resample into
        fixed-width bins (empty bins are explicit ``None`` gaps) with
        ``agg`` in mean|min|max|last|sum; without ``step`` the source
        resolution's points are returned as-is. The source resolution is
        the raw ring for short ranges/steps and the 1m/5m rollups beyond
        (``mean`` over rollups is the exact sample mean — buckets carry
        count+sum)."""
        if agg not in _AGGS:
            raise ValueError(f"agg must be one of {_AGGS}, got {agg!r}")
        ts_now = self._ts(now)
        end_ts = ts_now if end is None else float(end)
        start_ts = (end_ts - float(range_s)) if start is None else \
            float(start)
        if step is not None:
            step = float(step)
            if step <= 0:
                raise ValueError(f"step must be > 0, got {step}")
        wanted = None
        if select is not None:
            wanted = [select] if isinstance(select, str) else list(select)
        want_labels = {str(k): str(v)
                       for k, v in (labels or {}).items()}
        span = max(0.0, end_ts - start_ts)
        source = self._pick_source(span, step)
        out_series = []
        with self._lock:
            keys = sorted(self._series)
            for key in keys:
                s = self._series[key]
                if wanted is not None and not _name_matches(s.name, wanted):
                    continue
                lab = dict(s.labels)
                if want_labels and not all(lab.get(k) == v
                                           for k, v in want_labels.items()):
                    continue
                pts = self._collect(s, source, start_ts, end_ts, step, agg)
                out_series.append({
                    "name": s.name, "labels": lab, "kind": s.kind,
                    "stale": s.stale, "resets": s.resets, "points": pts})
                if len(out_series) >= limit:
                    break
        return {
            "now": ts_now, "start": start_ts, "end": end_ts,
            "step": step, "agg": agg, "source": source,
            "series": out_series,
            "annotations": self.annotations(start_ts, end_ts),
        }

    def _pick_source(self, span: float, step: Optional[float]):
        """raw | rollup resolution, by step first, else by span."""
        if step is not None:
            for res, _ in sorted(self.rollups, reverse=True):
                if step >= res:
                    return res
            return "raw"
        smallest = min(res for res, _ in self.rollups)
        if span <= 2 * smallest * 5:  # ~10 min at the default ladder
            return "raw"
        for res, length in sorted(self.rollups):
            if span <= res * length:
                return res
        return max(res for res, _ in self.rollups)

    def _collect(self, s: _Series, source, start: float, end: float,
                 step: Optional[float], agg: str) -> List[list]:
        """Points for one series from the chosen resolution."""
        with self._lock:
            if source == "raw":
                pts = [(ts, v) for ts, v in s.raw if start <= ts <= end]
            else:
                ring = s.rollups.get(source)
                if ring is None:
                    return []
                buckets = [b for b in ring if start <= b.start <= end]
        if source == "raw":
            if step is None:
                return [[ts, v] for ts, v in pts]
            return _resample_points(pts, start, end, step, agg)
        if step is None:
            return [[b.start, b.agg(agg)] for b in buckets]
        return _resample_buckets(buckets, start, end, step, agg)

    def http_query(self, params: dict) -> dict:
        """Map ``GET /api/history`` query-string params onto
        :meth:`query`. Grammar (docs/observability.md):
        ``series=a,b,fleet.*`` · ``worker=`` / ``model=`` label filters ·
        ``start`` / ``end`` absolute or ``range_s`` trailing window ·
        ``step`` · ``agg=mean|min|max|last|sum``."""
        select = None
        if params.get("series"):
            select = [p for p in str(params["series"]).split(",") if p]
        labels = {k: params[k] for k in ("worker", "model")
                  if params.get(k)}

        def _f(key):
            return float(params[key]) if params.get(key) else None

        return self.query(
            select=select, labels=labels or None,
            start=_f("start"), end=_f("end"),
            range_s=_f("range_s") or 300.0,
            step=_f("step"), agg=params.get("agg") or "mean",
            now=_f("now"))

    # --------------------------------------------------------------- stats
    def _update_footprint(self) -> None:
        with self._lock:
            b = self._bytes_locked()
            n = len(self._series)
        self._m_bytes.set(b)
        self._m_series.set(n)

    def _bytes_locked(self) -> int:
        with self._lock:
            pts = sum(len(s.raw) for s in self._series.values())
            buckets = sum(len(r) for s in self._series.values()
                          for r in s.rollups.values())
            return (pts * _POINT_BYTES + buckets * _BUCKET_BYTES
                    + len(self._series) * _SERIES_BYTES
                    + len(self._annotations) * _ANNOTATION_BYTES)

    def bytes_estimate(self) -> int:
        with self._lock:
            return self._bytes_locked()

    def series_names(self) -> List[str]:
        with self._lock:
            return sorted({s.name for s in self._series.values()})

    def stats(self) -> dict:
        with self._lock:
            n = len(self._series)
            stale = sum(1 for s in self._series.values() if s.stale)
            bytes_now = self._bytes_locked()
            samples = self.samples_total
            evicted = self.evicted_total
            anns = len(self._annotations)
        return {
            "series": n, "stale_series": stale,
            "samples_total": samples, "evicted_total": evicted,
            "annotations": anns, "bytes": bytes_now,
            "byte_budget": self.byte_budget,
            "raw_len": self.raw_len,
            "rollups": [[res, n_] for res, n_ in self.rollups],
            "max_series": self.max_series,
        }

    def clear(self) -> None:
        with self._lock:
            self._series.clear()
            self._hist_state.clear()
            self._annotations.clear()


def _name_matches(name: str, wanted: List[str]) -> bool:
    for w in wanted:
        if w.endswith("*"):
            if name.startswith(w[:-1]):
                return True
        elif name == w:
            return True
    return False


def _resample_points(pts, start: float, end: float, step: float,
                     agg: str) -> List[list]:
    """Raw (ts, value) points into [start + k·step) bins; empty bins (and
    gap points) yield explicit None."""
    n_bins = max(0, int(math.floor((end - start) / step)) + 1)
    n_bins = min(n_bins, 4096)
    out = [[start + i * step, None] for i in range(n_bins)]
    acc: Dict[int, list] = {}
    for ts, v in pts:
        if v is None:
            continue
        i = int((ts - start) // step)
        if 0 <= i < n_bins:
            acc.setdefault(i, []).append(v)
    for i, vals in acc.items():
        if agg == "mean":
            out[i][1] = sum(vals) / len(vals)
        elif agg == "min":
            out[i][1] = min(vals)
        elif agg == "max":
            out[i][1] = max(vals)
        elif agg == "sum":
            out[i][1] = sum(vals)
        else:
            out[i][1] = vals[-1]
    return out


def _resample_buckets(buckets, start: float, end: float, step: float,
                      agg: str) -> List[list]:
    """Rollup buckets into bins. ``mean`` merges by count+sum, so the
    result is the exact sample mean, not a mean-of-means."""
    n_bins = max(0, int(math.floor((end - start) / step)) + 1)
    n_bins = min(n_bins, 4096)
    out = [[start + i * step, None] for i in range(n_bins)]
    acc: Dict[int, list] = {}
    for b in buckets:
        i = int((b.start - start) // step)
        if 0 <= i < n_bins:
            acc.setdefault(i, []).append(b)
    for i, bs in acc.items():
        if agg == "mean":
            out[i][1] = sum(b.sum for b in bs) / sum(b.count for b in bs)
        elif agg == "min":
            out[i][1] = min(b.min for b in bs)
        elif agg == "max":
            out[i][1] = max(b.max for b in bs)
        elif agg == "sum":
            out[i][1] = sum(b.sum for b in bs)
        else:
            out[i][1] = bs[-1].last
    return out


def _bucket_quantile(bounds, per_bucket, total: float,
                     q: float) -> Optional[float]:
    """Prometheus histogram_quantile: linear interpolation inside the
    bucket holding rank q·total; the +Inf bucket clamps to the largest
    finite bound."""
    rank = q * total
    cum = 0.0
    finite = [b for b in bounds if math.isfinite(b)]
    if not finite:
        return None
    for i, b in enumerate(bounds):
        c = per_bucket[i]
        prev_cum = cum
        cum += c
        if cum >= rank and c > 0:
            if not math.isfinite(b):
                return finite[-1]
            lo = bounds[i - 1] if i > 0 and math.isfinite(bounds[i - 1]) \
                else 0.0
            return lo + (b - lo) * ((rank - prev_cum) / c)
    return finite[-1]


# --------------------------------------------------- prometheus text parsing

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+(\S+)")
_LABEL_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_TYPE_RE = re.compile(r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) (\w+)")


def parse_prometheus_text(text: str):
    """Minimal Prometheus text-format parser. Returns ``(types, samples)``
    where types maps family name -> type and samples is a list of
    ``(name, labels_dict, value)``. Exemplar suffixes are stripped."""
    types: Dict[str, str] = {}
    samples: List[Tuple[str, dict, float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            m = _TYPE_RE.match(line)
            if m:
                types[m.group(1)] = m.group(2)
            continue
        line = line.split(" # ", 1)[0].rstrip()  # OpenMetrics exemplar
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name, _, labelstr, raw = m.groups()
        try:
            value = float(raw)
        except ValueError:
            continue
        if math.isnan(value):
            continue
        labels = {k: v.replace('\\"', '"').replace("\\\\", "\\")
                  .replace("\\n", "\n")
                  for k, v in _LABEL_PAIR_RE.findall(labelstr or "")}
        samples.append((name, labels, value))
    return types, samples


# -------------------------------------------------------------------- sampler

class HistorySampler:
    """Ticks a registry snapshot into the store on a Deadline-paced
    thread. ``tick(now=...)`` is public so tests drive it with an
    injected clock and no thread."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 store: Optional[HistoryStore] = None, *,
                 interval_s: Optional[float] = None,
                 extra_labels: Optional[dict] = None,
                 prefix: str = "dl4jtpu_",
                 site: str = "telemetry.history.sampler"):
        from ..runtime.resilience import DeadlinePolicy  # noqa: PLC0415

        self.registry = registry if registry is not None else get_registry()
        self.store = store if store is not None else get_history_store()
        self.interval_s = (float(interval_s) if interval_s is not None
                           else _interval_from_env())
        self.extra_labels = dict(extra_labels or {})
        self.prefix = prefix
        self._policy = DeadlinePolicy(site, self.interval_s)
        self._stop = threading.Event()
        self._enabled = threading.Event()
        self._enabled.set()
        self._lock = threading.Lock()
        self.ticks = 0
        self._thread: Optional[threading.Thread] = None

    def tick(self, now: Optional[float] = None) -> int:
        """One sampling pass; returns rows ingested."""
        rows = self.store.ingest_snapshot(
            self.registry.snapshot(), extra_labels=self.extra_labels,
            prefix=self.prefix, now=now)
        with self._lock:
            self.ticks += 1
        return rows

    def _loop(self) -> None:
        while not self._stop.is_set():
            deadline = self._policy.start()
            if self._enabled.is_set():
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 - sampling must outlive blips
                    pass
            deadline.wait_event(self._stop)

    def start(self) -> "HistorySampler":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="dl4jtpu-history-sampler")
            self._thread.start()
        return self

    def pause(self) -> None:
        """Stop ingesting without killing the pacing thread (the bench
        overhead gate toggles this between interleaved trials)."""
        self._enabled.clear()

    def resume(self) -> None:
        self._enabled.set()

    @property
    def paused(self) -> bool:
        return not self._enabled.is_set()

    def stop(self) -> None:
        self._stop.set()

    def stats(self) -> dict:
        with self._lock:
            ticks = self.ticks
        return {"interval_s": self.interval_s, "ticks": ticks,
                "paused": self.paused, "prefix": self.prefix}


# ------------------------------------------------------------------ forecast

class Forecast:
    """Holt linear trend with irregular-interval updates; ``beta=0``
    degenerates to plain EWMA (level only, zero trend)."""

    __slots__ = ("alpha", "beta", "level", "trend", "last_ts", "n")

    def __init__(self, alpha: float = 0.5, beta: float = 0.3):
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.level: Optional[float] = None
        self.trend = 0.0
        self.last_ts: Optional[float] = None
        self.n = 0

    def update(self, value: float, ts: float) -> None:
        v = float(value)
        if self.level is None or self.last_ts is None:
            self.level, self.last_ts, self.n = v, float(ts), 1
            return
        dt = float(ts) - self.last_ts
        if dt <= 0:
            return
        prev_level = self.level
        predicted = self.level + self.trend * dt
        self.level = self.alpha * v + (1.0 - self.alpha) * predicted
        if self.beta > 0:
            self.trend = (self.beta * (self.level - prev_level) / dt
                          + (1.0 - self.beta) * self.trend)
        self.last_ts = float(ts)
        self.n += 1

    def forecast(self, horizon_s: float) -> Optional[float]:
        if self.level is None:
            return None
        return self.level + self.trend * float(horizon_s)


class FleetRecordingRules:
    """Derives the autoscaler sensor suite (``RECORDING_RULES``) from a
    router's ``stats()`` payload each scrape tick, and maintains EWMA +
    Holt forecasts per key sensor, exported as ``dl4jtpu_forecast_*``
    gauges with horizon labels (``ewma``, ``trend_per_s``, ``60s``,
    ``300s``). One instance per router; the forecast gauge families are
    declared HERE (the single DT406 owning module)."""

    def __init__(self, store: Optional[HistoryStore] = None,
                 registry: Optional[MetricsRegistry] = None, *,
                 alpha: float = 0.5, beta: float = 0.3):
        reg = registry if registry is not None else get_registry()
        self.store = store if store is not None else get_history_store()
        self.alpha = float(alpha)
        self.beta = float(beta)
        # observe_fleet runs on the router's scrape thread; stats()/tests
        # read the forecast table from others
        self._lock = threading.Lock()
        self._forecasts: Dict[tuple, Tuple[Forecast, Forecast]] = {}
        self._fam = {
            "offered_load": reg.gauge(
                "dl4jtpu_forecast_offered_load",
                "EWMA/Holt forecast of per-model offered load "
                "(requests/s), by horizon",
                labelnames=("model", "horizon")),
            "shed_rate": reg.gauge(
                "dl4jtpu_forecast_shed_rate",
                "EWMA/Holt forecast of per-model shed rate (sheds/s), "
                "by horizon",
                labelnames=("model", "horizon")),
            "latency_p99_seconds": reg.gauge(
                "dl4jtpu_forecast_latency_p99_seconds",
                "EWMA/Holt forecast of the exact merged-ring p99 latency, "
                "by horizon",
                labelnames=("model", "horizon")),
            "queue_depth": reg.gauge(
                "dl4jtpu_forecast_queue_depth",
                "EWMA/Holt forecast of summed ready-worker queue depth, "
                "by horizon",
                labelnames=("model", "horizon")),
        }

    def observe_fleet(self, fleet_stats: dict,
                      now: Optional[float] = None) -> Dict[str, float]:
        """One recording-rule pass over a router ``stats()`` payload.
        Returns the sensor values observed this tick (rate sensors are
        absent on their baseline tick)."""
        ts = self.store._ts(now)  # noqa: SLF001 - same-module clock
        model = str(fleet_stats.get("model", "default"))
        lab = {"model": model}
        sensors: Dict[str, Optional[float]] = {}
        sensors["offered_load"] = self.store.record_counter(
            "fleet.offered_load", fleet_stats.get("requests_total", 0),
            lab, now=ts)
        sensors["shed_rate"] = self.store.record_counter(
            "fleet.shed_rate", fleet_stats.get("shed_total", 0),
            lab, now=ts)
        lat = fleet_stats.get("latency_seconds") or {}
        if lat.get("p50") is not None:
            self.store.record_gauge("fleet.latency_p50_seconds",
                                    lat["p50"], lab, now=ts)
        if lat.get("p99") is not None:
            sensors["latency_p99_seconds"] = self.store.record_gauge(
                "fleet.latency_p99_seconds", lat["p99"], lab, now=ts)
        workers = fleet_stats.get("workers") or []
        ready = [w for w in workers if w.get("ready")]
        qd = float(sum(w.get("queue_depth") or 0 for w in ready))
        sensors["queue_depth"] = self.store.record_gauge(
            "fleet.queue_depth", qd, lab, now=ts)
        self.store.record_gauge("fleet.workers_ready", len(ready),
                                lab, now=ts)
        for w in workers:
            wlab = {"model": model, "worker": str(w.get("id"))}
            if w.get("ready"):
                self.store.record_gauge("worker.queue_depth",
                                        w.get("queue_depth") or 0,
                                        wlab, now=ts)
            if w.get("boot_seconds") is not None:
                self.store.record_gauge("worker.boot_ready_seconds",
                                        w["boot_seconds"], wlab, now=ts)
            if w.get("compiles_since_ready") is not None:
                self.store.record_gauge("worker.compiles_since_ready",
                                        w["compiles_since_ready"],
                                        wlab, now=ts)
        self._update_forecasts(sensors, model, ts)
        return {k: v for k, v in sensors.items() if v is not None}

    def _update_forecasts(self, sensors: Dict[str, Optional[float]],
                          model: str, ts: float) -> None:
        for sensor in FORECAST_SENSORS:
            value = sensors.get(sensor)
            if value is None:
                continue
            with self._lock:
                pair = self._forecasts.get((sensor, model))
                if pair is None:
                    pair = (Forecast(self.alpha, 0.0),
                            Forecast(self.alpha, self.beta))
                    self._forecasts[(sensor, model)] = pair
                ewma, holt = pair
                ewma.update(value, ts)
                holt.update(value, ts)
                level, trend = ewma.level, holt.trend
                horizons = {f"{int(h)}s": holt.forecast(h)
                            for h in FORECAST_HORIZONS_S}
            fam = self._fam[sensor]
            fam.labels(model=model, horizon="ewma").set(level)
            fam.labels(model=model, horizon="trend_per_s").set(trend)
            for hname, hval in horizons.items():
                if hval is not None:
                    fam.labels(model=model, horizon=hname).set(hval)

    def forecast_table(self) -> dict:
        """{(sensor, model): {ewma, trend_per_s, <horizon>s...}} for
        stats/debugging."""
        out = {}
        with self._lock:
            for (sensor, model), (ewma, holt) in self._forecasts.items():
                row = {"ewma": ewma.level, "trend_per_s": holt.trend,
                       "samples": holt.n}
                for h in FORECAST_HORIZONS_S:
                    row[f"{int(h)}s"] = holt.forecast(h)
                out[f"{sensor}{{model={model}}}"] = row
        return out


# ------------------------------------------------------------------ globals

_STORE: Optional[HistoryStore] = None
_SAMPLER: Optional[HistorySampler] = None
# reentrant: ensure_default_sampler holds it while HistorySampler's ctor
# re-enters through get_history_store()
_GLOBAL_LOCK = threading.RLock()


def get_history_store() -> HistoryStore:
    """The process-wide history store (what ``/api/history`` serves)."""
    global _STORE
    with _GLOBAL_LOCK:
        if _STORE is None:
            _STORE = HistoryStore()
        return _STORE


def set_history_store(store: Optional[HistoryStore]) -> None:
    """Swap the process-wide store (tests); None resets to lazy
    re-creation."""
    global _STORE
    with _GLOBAL_LOCK:
        _STORE = store


def ensure_default_sampler(interval_s: Optional[float] = None,
                           ) -> Optional[HistorySampler]:
    """Start the process-wide sampler over the default registry (no-op
    when ``DL4JTPU_HISTORY=0``; idempotent). The serving front-end calls
    this on construction so any serving/worker process grows history
    automatically."""
    if not history_enabled():
        return None
    global _SAMPLER
    with _GLOBAL_LOCK:
        if _SAMPLER is None:
            _SAMPLER = HistorySampler(interval_s=interval_s)
            _SAMPLER.start()
        return _SAMPLER


def get_default_sampler() -> Optional[HistorySampler]:
    with _GLOBAL_LOCK:
        return _SAMPLER


def set_default_sampler(sampler: Optional[HistorySampler]) -> None:
    """Swap the process-wide sampler (tests). The old sampler is NOT
    stopped — callers own that."""
    global _SAMPLER
    with _GLOBAL_LOCK:
        _SAMPLER = sampler


# the annotation splice rings its own flight-event kind; registered here
# (the owning module) and listed in flight_recorder.py's inventory table
def _register_kinds() -> None:
    try:
        from .flight_recorder import register_event_kind  # noqa: PLC0415

        register_event_kind("history_annotation")
    except Exception:  # noqa: BLE001 - registration must never block import
        pass


_register_kinds()
