"""SLO monitor: declared objectives + multi-window burn-rate alerting.

A latency ring and a shed counter say what happened; an SLO says whether
it was *acceptable*. This module evaluates declared objectives over the
serving observations the stack already produces:

- ``latency`` — the fraction of requests over the per-model latency
  budget must stay under ``1 - latency_target`` (default target 0.99:
  at most 1% of requests may breach the budget).
- ``availability`` — shed + errored requests must stay under
  ``1 - availability_target`` (default 0.999).

**Burn rate** is the classic SRE ratio: observed bad fraction divided by
the allowed bad fraction. Burn 1.0 spends the error budget exactly at
the sustainable pace; burn 14 exhausts a month's budget in ~2 days.
Alerting is **multi-window** (fast 5m AND slow 1h must both burn hot) so
one bad micro-batch can't page anyone, while a sustained regression
fires within minutes.

A breach emits a Watchdog :class:`AnomalyEvent` (kind ``slo-burn``),
which the flight recorder auto-dumps — and because sampled trace spans
ride the global span ring, the dump bundle's ``spans`` section carries
the offending traces; the ``slo_burn`` flight event lists their ids
directly. Exported series: ``dl4jtpu_slo_burn_rate{model,objective}``
(fast-window burn) and ``dl4jtpu_slo_breaches_total{model,objective}``.
``GET /api/slo`` (router, worker and UI server) serves :meth:`stats`.

Timestamps are injectable (``observe(..., now=...)`` /
``evaluate(now=...)``) so the burn math is testable on synthetic rings.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .registry import MetricsRegistry, get_registry
from .watchdog import SLO_BURN, Watchdog

__all__ = [
    "SLO_AVAILABILITY_TARGET_ENV",
    "SLO_LATENCY_BUDGET_ENV",
    "SLO_LATENCY_TARGET_ENV",
    "SLOMonitor",
    "get_slo_monitor",
    "set_slo_monitor",
]

# env-declared objectives for services that don't declare programmatically
SLO_LATENCY_BUDGET_ENV = "DL4JTPU_SLO_LATENCY_BUDGET_MS"
SLO_LATENCY_TARGET_ENV = "DL4JTPU_SLO_LATENCY_TARGET"
SLO_AVAILABILITY_TARGET_ENV = "DL4JTPU_SLO_AVAILABILITY_TARGET"

_FAST_WINDOW_S = 300.0    # 5 minutes
_SLOW_WINDOW_S = 3600.0   # 1 hour


def _env_float(name: str) -> Optional[float]:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return None
    try:
        return float(raw)
    except ValueError:
        return None


class _Objectives:
    """One model's declared targets."""

    __slots__ = ("latency_budget_ms", "latency_target",
                 "availability_target")

    def __init__(self, latency_budget_ms: Optional[float],
                 latency_target: float, availability_target: float):
        self.latency_budget_ms = latency_budget_ms
        self.latency_target = float(latency_target)
        self.availability_target = float(availability_target)

    def to_dict(self) -> dict:
        return {
            "latency_budget_ms": self.latency_budget_ms,
            "latency_target": self.latency_target,
            "availability_target": self.availability_target,
        }


class SLOMonitor:
    """Declared objectives + timestamped observation rings + burn math."""

    def __init__(self, registry: Optional[MetricsRegistry] = None, *,
                 watchdog: Optional[Watchdog] = None,
                 fast_window_s: float = _FAST_WINDOW_S,
                 slow_window_s: float = _SLOW_WINDOW_S,
                 fast_burn_threshold: float = 14.4,
                 slow_burn_threshold: float = 6.0,
                 min_breach_interval_s: float = 60.0,
                 ring_size: int = 8192):
        reg = registry if registry is not None else get_registry()
        self.registry = reg
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.fast_burn_threshold = float(fast_burn_threshold)
        self.slow_burn_threshold = float(slow_burn_threshold)
        self.min_breach_interval_s = float(min_breach_interval_s)
        self.ring_size = int(ring_size)
        # observation tuples: (ts, latency_s|None, bad_avail, trace_id|None)
        self._rings: Dict[str, deque] = {}
        self._objectives: Dict[str, _Objectives] = {}
        self._last_breach: Dict[tuple, float] = {}
        self._breaches: List[dict] = []
        self._last_eval = 0.0
        # observe() lands from batcher callback threads while evaluate()
        # runs on whichever thread tripped the throttle
        self._lock = threading.Lock()
        self._watchdog = watchdog
        self._m_burn = reg.gauge(
            "dl4jtpu_slo_burn_rate",
            "fast-window SLO burn rate (bad fraction / error budget), "
            "by model and objective",
            labelnames=("model", "objective"))
        self._m_breaches = reg.counter(
            "dl4jtpu_slo_breaches_total",
            "multi-window SLO burn-rate breaches, by model and objective",
            labelnames=("model", "objective"))

    # --------------------------------------------------------- declaration
    def declare(self, model: str, *,
                latency_budget_ms: Optional[float] = None,
                latency_target: float = 0.99,
                availability_target: float = 0.999) -> "SLOMonitor":
        """Declare (or re-declare) a model's objectives. A None latency
        budget disables the latency objective; availability is always
        evaluated."""
        with self._lock:
            self._objectives[str(model)] = _Objectives(
                None if latency_budget_ms is None
                else float(latency_budget_ms),
                latency_target, availability_target)
            self._rings.setdefault(str(model),
                                   deque(maxlen=self.ring_size))
        return self

    def declare_from_env(self, model: str,
                         latency_budget_ms: Optional[float] = None) -> None:
        """Declare from the ``DL4JTPU_SLO_*`` env knobs; an explicit
        ``latency_budget_ms`` (e.g. the admission budget) is the fallback
        when the env doesn't name one."""
        budget = _env_float(SLO_LATENCY_BUDGET_ENV)
        if budget is None:
            budget = latency_budget_ms
        self.declare(
            model,
            latency_budget_ms=budget,
            latency_target=_env_float(SLO_LATENCY_TARGET_ENV) or 0.99,
            availability_target=(
                _env_float(SLO_AVAILABILITY_TARGET_ENV) or 0.999))

    def objectives(self, model: str) -> Optional[dict]:
        with self._lock:
            obj = self._objectives.get(str(model))
        return obj.to_dict() if obj is not None else None

    # -------------------------------------------------------- observations
    def observe(self, model: str, *, latency_s: Optional[float] = None,
                shed: bool = False, error: bool = False,
                trace_id: Optional[str] = None,
                now: Optional[float] = None) -> None:
        """One serving observation: a completed request's latency, or a
        shed/errored request (no latency — it never ran)."""
        ts = time.time() if now is None else float(now)
        with self._lock:
            ring = self._rings.get(str(model))
            if ring is None:
                ring = self._rings[str(model)] = deque(
                    maxlen=self.ring_size)
            ring.append((ts, latency_s, bool(shed or error), trace_id))

    # ---------------------------------------------------------- burn math
    def _window(self, ring, budget_ms: Optional[float],
                window_s: float, now: float):
        """(total, latency_bad, avail_bad, offending trace ids) over the
        trailing window."""
        cutoff = now - window_s
        total = lat_bad = avail_bad = 0
        offending: List[str] = []
        for ts, latency_s, bad_avail, trace_id in ring:
            if ts < cutoff:
                continue
            total += 1
            bad = False
            if bad_avail:
                avail_bad += 1
                bad = True
            if (budget_ms is not None and latency_s is not None
                    and latency_s * 1000.0 > budget_ms):
                lat_bad += 1
                bad = True
            if bad and trace_id is not None:
                offending.append(trace_id)
        return total, lat_bad, avail_bad, offending

    @staticmethod
    def _burn(bad: int, total: int, target: float) -> float:
        if total <= 0:
            return 0.0
        allowed = max(1e-9, 1.0 - float(target))
        return (bad / total) / allowed

    def burn_rates(self, model: str,
                   now: Optional[float] = None) -> Dict[str, dict]:
        """{objective: {fast, slow, offending_traces}} for one model."""
        ts = time.time() if now is None else float(now)
        with self._lock:
            obj = self._objectives.get(str(model))
            ring = list(self._rings.get(str(model)) or ())
        if obj is None:
            return {}
        out: Dict[str, dict] = {}
        for window_name, window_s in (("fast", self.fast_window_s),
                                      ("slow", self.slow_window_s)):
            total, lat_bad, avail_bad, offending = self._window(
                ring, obj.latency_budget_ms, window_s, ts)
            if obj.latency_budget_ms is not None:
                row = out.setdefault("latency", {"offending_traces": []})
                row[window_name] = self._burn(lat_bad, total,
                                              obj.latency_target)
                row[f"{window_name}_total"] = total
            row = out.setdefault("availability", {"offending_traces": []})
            row[window_name] = self._burn(avail_bad, total,
                                          obj.availability_target)
            row[f"{window_name}_total"] = total
            if window_name == "fast":
                for r in out.values():
                    r["offending_traces"] = sorted(set(offending))[-16:]
        return out

    # ---------------------------------------------------------- evaluation
    def _get_watchdog(self) -> Watchdog:
        if self._watchdog is None:
            from .flight_recorder import get_flight_recorder  # noqa: PLC0415

            wd = Watchdog(registry=self.registry)
            wd.add_sink(get_flight_recorder().watchdog_sink)
            self._watchdog = wd
        return self._watchdog

    def maybe_evaluate(self, now: Optional[float] = None,
                       min_interval_s: float = 1.0) -> None:
        """Hot-path hook: evaluate at most every ``min_interval_s`` — one
        monotonic read when throttled."""
        t = time.monotonic()
        with self._lock:
            if t - self._last_eval < min_interval_s:
                return
            self._last_eval = t
        self.evaluate(now=now)

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """Evaluate every declared objective; returns the breaches fired
        by THIS call (after per-objective rate limiting)."""
        ts = time.time() if now is None else float(now)
        with self._lock:
            models = sorted(self._objectives)
        fired: List[dict] = []
        for model in models:
            rates = self.burn_rates(model, now=ts)
            for objective, row in rates.items():
                fast, slow = row.get("fast", 0.0), row.get("slow", 0.0)
                self._m_burn.labels(model=model,
                                    objective=objective).set(fast)
                if (fast < self.fast_burn_threshold
                        or slow < self.slow_burn_threshold):
                    continue
                key = (model, objective)
                mono = time.monotonic()
                with self._lock:
                    last = self._last_breach.get(key)
                    if (last is not None and mono - last
                            < self.min_breach_interval_s):
                        continue
                    self._last_breach[key] = mono
                breach = {
                    "model": model, "objective": objective,
                    "fast_burn": round(fast, 4),
                    "slow_burn": round(slow, 4),
                    "offending_traces": row.get("offending_traces", []),
                    "timestamp": ts,
                }
                with self._lock:
                    self._breaches.append(breach)
                    del self._breaches[:-64]
                fired.append(breach)
                self._m_breaches.labels(model=model,
                                        objective=objective).inc()
                try:
                    from .flight_recorder import get_flight_recorder  # noqa: PLC0415

                    get_flight_recorder().record(
                        "slo_burn", model=model, objective=objective,
                        fast_burn=round(fast, 4), slow_burn=round(slow, 4),
                        offending_traces=list(
                            row.get("offending_traces", [])))
                except Exception:  # pragma: no cover - defensive
                    pass
                self._get_watchdog().emit(
                    SLO_BURN, iteration=0, value=fast,
                    threshold=self.fast_burn_threshold,
                    message=(
                        f"SLO burn: model {model!r} {objective} burning "
                        f"{fast:.1f}x fast / {slow:.1f}x slow (thresholds "
                        f"{self.fast_burn_threshold}/"
                        f"{self.slow_burn_threshold}); offending traces: "
                        f"{row.get('offending_traces', [])}"))
        return fired

    # --------------------------------------------------------------- stats
    def stats(self) -> dict:
        """The ``GET /api/slo`` payload."""
        with self._lock:
            models = sorted(self._objectives)
            objectives = {m: self._objectives[m].to_dict() for m in models}
            breaches = list(self._breaches)
            samples = {m: len(self._rings.get(m) or ()) for m in models}
        return {
            "windows": {"fast_s": self.fast_window_s,
                        "slow_s": self.slow_window_s},
            "thresholds": {"fast_burn": self.fast_burn_threshold,
                           "slow_burn": self.slow_burn_threshold},
            "objectives": objectives,
            "burn": {m: self.burn_rates(m) for m in models},
            "samples": samples,
            "recent_breaches": breaches[-16:],
            "breaches_total": len(breaches),
        }


_GLOBAL: Optional[SLOMonitor] = None
_GLOBAL_LOCK = threading.Lock()


def get_slo_monitor() -> SLOMonitor:
    """The process-wide SLO monitor (serving observes into it; the UI
    server, fleet worker and router serve its stats)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = SLOMonitor()
        return _GLOBAL


def set_slo_monitor(monitor: Optional[SLOMonitor]) -> None:
    """Swap the process-wide monitor (tests); None resets to lazy
    re-creation."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        _GLOBAL = monitor
