"""Process-wide metrics registry: counters, gauges, histograms.

The reference DL4J observed training through three disconnected mechanisms
(PerformanceListener samples/sec, Spark per-phase stats, StatsListener memory
sections — SURVEY.md §5.1). This registry is the single store they all write
to here: every hot path (fit loops, ParallelWrapper, param server, streaming
pipeline, bench) records named metrics, and two exposition formats read them
back — Prometheus text (``prometheus_text()``, served at ``/metrics`` by
``ui/server.py``) and a JSON snapshot (``snapshot()``, the machine-readable
twin used by bench artifacts and the UI system page).

Design constraints, TPU-honest by construction:

- Recording is host-side arithmetic under a lock — no jax import, no device
  interaction. Device-side values reach the registry only through the
  K-step fetch in :mod:`telemetry.session`, never per step.
- Families are idempotent: ``registry.counter(name, ...)`` returns the
  existing family when one is already registered (re-registration with a
  different type or label set is a hard error, not silent aliasing).
- Labels follow the Prometheus model: a family declares label names once;
  ``family.labels(phase="data")`` returns the child series. Label-less
  families proxy the child API directly (``counter.inc()``).
"""

from __future__ import annotations

import math
import re
import threading
from typing import Dict, Iterable, Optional, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Default histogram buckets in seconds — spans train steps from sub-ms
# (char-rnn scan body) to multi-second (cold ResNet dispatch).
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


def _fmt(v: float) -> str:
    """Prometheus sample value: integral floats render without the dot."""
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if isinstance(v, float) and v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _render_labels(names: Tuple[str, ...], values: Tuple[str, ...],
                   extra: Optional[Tuple[str, str]] = None) -> str:
    pairs = [f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)]
    if extra is not None:
        pairs.append(f'{extra[0]}="{_escape_label(extra[1])}"')
    return "{" + ",".join(pairs) + "}" if pairs else ""


class _Counter:
    """Monotone child series."""

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter increment must be >= 0, got {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class _Gauge:
    """Set/inc/dec child series."""

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class _Histogram:
    """Fixed-bucket child series with sum/count/min/max."""

    def __init__(self, buckets: Tuple[float, ...]):
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket bound")
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)  # +1 = +Inf
        self._sum = 0.0
        self._count = 0
        self._min: Optional[float] = None
        self._max: Optional[float] = None
        # last exemplar per bucket index ((trace_id, value) or None) —
        # lets prometheus_text() point tail buckets at concrete sampled
        # traces (OpenMetrics exemplar syntax)
        self._exemplars = [None] * (len(self.buckets) + 1)

    def observe(self, value: float, exemplar: Optional[str] = None) -> None:
        v = float(value)
        with self._lock:
            i = 0
            while i < len(self.buckets) and v > self.buckets[i]:
                i += 1
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            self._min = v if self._min is None else min(self._min, v)
            self._max = v if self._max is None else max(self._max, v)
            if exemplar is not None:
                self._exemplars[i] = (str(exemplar), v)

    def summary(self) -> dict:
        """JSON-ready summary: the shape bench artifacts embed."""
        with self._lock:
            cum = 0
            buckets = {}
            for bound, c in zip(self.buckets, self._counts):
                cum += c
                buckets[_fmt(bound)] = cum
            buckets["+Inf"] = self._count
            return {
                "count": self._count,
                "sum": round(self._sum, 9),
                "mean": round(self._sum / self._count, 9) if self._count else 0.0,
                "min": self._min,
                "max": self._max,
                "buckets": buckets,
            }

    @property
    def count(self) -> int:
        with self._lock:
            return self._count


_KINDS = {"counter": _Counter, "gauge": _Gauge, "histogram": _Histogram}


class MetricFamily:
    """One named metric with zero or more labelled child series."""

    def __init__(self, name: str, help: str, kind: str,
                 labelnames: Tuple[str, ...] = (),
                 buckets: Optional[Tuple[float, ...]] = None):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln) or ln == "le":
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.help = help
        self.kind = kind
        self.labelnames = tuple(labelnames)
        self._buckets = tuple(buckets) if buckets is not None else DEFAULT_BUCKETS
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}

    def _make_child(self):
        if self.kind == "histogram":
            return _Histogram(self._buckets)
        return _KINDS[self.kind]()

    def labels(self, **labelvalues):
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(sorted(labelvalues))}"
            )
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child()
                self._children[key] = child
            return child

    def _default_child(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} declares labels {self.labelnames}; "
                "use .labels(...) to select a series"
            )
        return self.labels()

    # label-less convenience proxies
    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default_child().dec(amount)

    def set(self, value: float) -> None:
        self._default_child().set(value)

    def observe(self, value: float, exemplar: Optional[str] = None) -> None:
        self._default_child().observe(value, exemplar=exemplar)

    @property
    def value(self) -> float:
        return self._default_child().value

    def summary(self) -> dict:
        return self._default_child().summary()

    def _items(self):
        with self._lock:
            return sorted(self._children.items())


class MetricsRegistry:
    """Named-metric store with Prometheus and JSON exposition."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, MetricFamily] = {}

    def _register(self, name: str, help: str, kind: str,
                  labelnames: Tuple[str, ...],
                  buckets: Optional[Tuple[float, ...]] = None) -> MetricFamily:
        labelnames = tuple(labelnames)
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered as {fam.kind}"
                        f"{fam.labelnames}; cannot re-register as {kind}"
                        f"{labelnames}"
                    )
                return fam
            fam = MetricFamily(name, help, kind, labelnames, buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labelnames: Iterable[str] = ()) -> MetricFamily:
        return self._register(name, help, "counter", tuple(labelnames))

    def gauge(self, name: str, help: str = "",
              labelnames: Iterable[str] = ()) -> MetricFamily:
        return self._register(name, help, "gauge", tuple(labelnames))

    def histogram(self, name: str, help: str = "",
                  labelnames: Iterable[str] = (),
                  buckets: Optional[Iterable[float]] = None) -> MetricFamily:
        return self._register(
            name, help, "histogram", tuple(labelnames),
            tuple(buckets) if buckets is not None else None,
        )

    def get(self, name: str) -> Optional[MetricFamily]:
        with self._lock:
            return self._families.get(name)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._families.pop(name, None)

    def clear(self) -> None:
        with self._lock:
            self._families.clear()

    def _sorted_families(self):
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    # ------------------------------------------------------------ exposition
    def prometheus_text(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines = []
        for fam in self._sorted_families():
            items = fam._items()
            if not items:
                continue
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for key, child in items:
                base = _render_labels(fam.labelnames, key)
                if fam.kind == "histogram":
                    cum = 0
                    with child._lock:
                        counts = list(child._counts)
                        total, s = child._count, child._sum
                        exemplars = list(child._exemplars)
                    for i, (bound, c) in enumerate(
                            zip(child.buckets, counts)):
                        cum += c
                        line = (
                            f"{fam.name}_bucket"
                            f"{_render_labels(fam.labelnames, key, ('le', _fmt(bound)))}"
                            f" {cum}"
                        )
                        ex = exemplars[i]
                        if ex is not None:
                            # OpenMetrics exemplar: the sampled trace whose
                            # observation last landed in this bucket
                            line += (f' # {{trace_id="{_escape_label(ex[0])}"'
                                     f"}} {_fmt(ex[1])}")
                        lines.append(line)
                    inf_line = (
                        f"{fam.name}_bucket"
                        f"{_render_labels(fam.labelnames, key, ('le', '+Inf'))}"
                        f" {total}"
                    )
                    ex = exemplars[len(child.buckets)]
                    if ex is not None:
                        inf_line += (f' # {{trace_id="{_escape_label(ex[0])}"'
                                     f"}} {_fmt(ex[1])}")
                    lines.append(inf_line)
                    lines.append(f"{fam.name}_sum{base} {_fmt(s)}")
                    lines.append(f"{fam.name}_count{base} {total}")
                else:
                    lines.append(f"{fam.name}{base} {_fmt(child.value)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> dict:
        """JSON-ready snapshot: {name: {type, help, values: [...]}}."""
        out: dict = {}
        for fam in self._sorted_families():
            values = []
            for key, child in fam._items():
                labels = dict(zip(fam.labelnames, key))
                if fam.kind == "histogram":
                    row = {"labels": labels, **child.summary()}
                else:
                    row = {"labels": labels, "value": child.value}
                values.append(row)
            out[fam.name] = {"type": fam.kind, "help": fam.help,
                             "values": values}
        return out


_GLOBAL_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry (served at ``/metrics``)."""
    return _GLOBAL_REGISTRY
