"""HBM memory accounting: where the bytes go, before and while they go there.

The reference's only memory visibility was StatsListener's JVM heap sections
(SURVEY.md §5.1) — numbers with no connection to what the model allocates.
On TPU the blind spot is HBM: the failure mode is an OOM at compile or
dispatch time with no attribution. This module is the missing accounting
layer, three sources feeding one registry:

- **Static, from XLA itself.** :func:`executable_memory` reads
  ``compiled.memory_analysis()`` off an AOT executable — argument/output/
  temp/generated-code bytes as the compiler laid them out. The compile
  manager records this for every executable it admits
  (``dl4jtpu_executable_hbm_bytes{kind=...}`` + a cache-wide total).
- **Projected, from the model.** :func:`memory_report` walks a net's
  layers/vertices with ``jax.eval_shape`` (no FLOPs, no allocation) and
  attributes param + gradient + optimizer-state + activation bytes per
  layer, for both ``MultiLayerNetwork`` and ``ComputationGraph``.
  :func:`preflight` compares the projected peak against the live limit and
  raises a "will not fit, biggest consumers are X/Y/Z" error BEFORE the
  first fit/warmup dispatch pays a doomed compile.
- **Live, from PJRT.** :func:`device_memory_stats` is the single
  implementation of per-device ``memory_stats()`` collection (profiler's
  old function is now a thin wrapper); :func:`sample_device_memory`
  additionally records registry gauges + a peak watermark and is called on
  every telemetry fetch — live HBM rides the same K-step cadence as the
  training metrics, never a per-step sync.
"""

from __future__ import annotations

import os
from typing import Any, List, Optional

from .registry import MetricsRegistry, get_registry

# env knob: explicit per-device HBM budget for preflight when PJRT exposes
# no memory_stats (see docs/observability.md)
HBM_LIMIT_ENV = "DL4JTPU_HBM_LIMIT_BYTES"

# timesteps probe substituted for variable-length recurrent inputs (the
# same convention as analysis/graph_checks.DEFAULT_TIMESTEPS_PROBE)
DEFAULT_TIMESTEPS_PROBE = 16

_MA_FIELDS = {
    "argument": "argument_size_in_bytes",
    "output": "output_size_in_bytes",
    "temp": "temp_size_in_bytes",
    "generated_code": "generated_code_size_in_bytes",
    "alias": "alias_size_in_bytes",
}


def _fmt_bytes(n: float) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0:
            return f"{n:.1f} {unit}"
        n /= 1024.0
    return f"{n:.1f} TiB"


# --------------------------------------------------------- static (from XLA)
def executable_memory(compiled) -> dict:
    """Byte accounting of one AOT executable from XLA's own
    ``memory_analysis()``. Always returns a record: when the backend
    doesn't expose the analysis the record carries ``available: False``
    and a reason instead of silently reading zero."""
    try:
        ma = compiled.memory_analysis()
    except Exception as e:
        return {"available": False,
                "reason": f"{type(e).__name__}: {e}"[:200]}
    if ma is None:
        return {"available": False,
                "reason": "memory_analysis unavailable on this backend"}
    out: dict = {"available": True}
    for kind, attr in _MA_FIELDS.items():
        out[f"{kind}_bytes"] = int(getattr(ma, attr, 0) or 0)
    # peak working set of one execution: inputs + outputs + scratch + code,
    # minus input/output buffers the compiler aliased (donation)
    out["total_bytes"] = max(
        0,
        out["argument_bytes"] + out["output_bytes"] + out["temp_bytes"]
        + out["generated_code_bytes"] - out["alias_bytes"],
    )
    return out


# ----------------------------------------------------------- live (from PJRT)
def device_memory_stats(registry: Optional[MetricsRegistry] = None) -> List[dict]:
    """Per-device PJRT memory stats — THE live-HBM source (the UI
    StatsListener, ``profiler.device_memory_stats`` and the telemetry fetch
    all read through here). With ``registry`` the rows also land as
    ``dl4jtpu_device_hbm_bytes{device,kind}`` gauges."""
    out: List[dict] = []
    try:
        import jax  # noqa: PLC0415 - keep module import light

        for d in jax.devices():
            try:
                ms = d.memory_stats()
            except Exception:
                ms = None
            if ms:
                out.append({
                    "device": int(d.id),
                    "bytes_in_use": ms.get("bytes_in_use"),
                    "peak_bytes_in_use": ms.get("peak_bytes_in_use"),
                    "bytes_limit": ms.get("bytes_limit"),
                })
    except Exception:  # pragma: no cover - no jax / broken backend
        pass
    if registry is not None and out:
        fam = registry.gauge(
            "dl4jtpu_device_hbm_bytes",
            "live per-device HBM (PJRT memory_stats)",
            labelnames=("device", "kind"),
        )
        for row in out:
            for kind in ("bytes_in_use", "bytes_limit"):
                if row.get(kind) is not None:
                    fam.labels(device=row["device"],
                               kind=kind.replace("bytes_", "").replace(
                                   "_bytes", "")).set(row[kind])
    return out


def sample_device_memory(registry: Optional[MetricsRegistry] = None,
                         flight=None) -> List[dict]:
    """Record live HBM gauges + a sticky peak watermark; called on every
    telemetry fetch (K-step cadence — never per step). ``flight``: a
    :class:`~.flight_recorder.FlightRecorder` to drop a ``memory`` event
    into (the post-mortem trail of watermarks)."""
    reg = registry if registry is not None else get_registry()
    rows = device_memory_stats(reg)
    if not rows:
        return rows
    peak_fam = reg.gauge(
        "dl4jtpu_device_hbm_peak_bytes",
        "peak HBM watermark per device (sticky max of PJRT peaks)",
        labelnames=("device",),
    )
    for row in rows:
        peak = row.get("peak_bytes_in_use") or row.get("bytes_in_use") or 0
        child = peak_fam.labels(device=row["device"])
        if peak > child.value:
            child.set(peak)
    if flight is not None:
        try:
            flight.record("memory", devices=[
                {k: row.get(k) for k in ("device", "bytes_in_use",
                                         "peak_bytes_in_use")}
                for row in rows
            ])
        except Exception:  # observability must never kill the train loop
            pass
    return rows


# ----------------------------------------------- projected (from the model)
def _bytes_of(tree) -> int:
    """Exact byte count of a pytree of arrays / ShapeDtypeStructs."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            total += int(np.prod(leaf.shape, dtype=np.int64)) * \
                np.dtype(leaf.dtype).itemsize
    return int(total)


def _input_structs(net, batch_or_struct, timesteps_probe=None):
    """Input ShapeDtypeStructs for a net: an int batch size builds them from
    the declared input types; arrays/structs (or a list for multi-input
    graphs) are shelled to shape/dtype only. ``timesteps_probe`` overrides
    the length substituted for variable-length recurrent inputs (so IR/cost
    probes can model the real training sequence length, not the default)."""
    import jax
    import numpy as np

    conf = net.conf

    def shell(a):
        if isinstance(a, jax.ShapeDtypeStruct):
            return a
        a = np.asarray(a) if not hasattr(a, "dtype") else a
        return jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)

    if batch_or_struct is None:
        batch_or_struct = 32
    if isinstance(batch_or_struct, (int, np.integer)):
        b = int(batch_or_struct)
        if hasattr(conf, "vertices"):
            its = conf.input_types
        else:
            if conf.input_type is None:
                raise ValueError(
                    "memory_report needs conf.input_type (or pass example "
                    "arrays/ShapeDtypeStructs instead of a batch size)")
            its = [conf.input_type]
        t_probe = (DEFAULT_TIMESTEPS_PROBE if timesteps_probe is None
                   else int(timesteps_probe))
        structs = []
        for it in its:
            if getattr(it, "kind", None) == "rnn" and it.timesteps is None:
                shape = (t_probe, it.size)
            else:
                shape = it.example_shape()
            structs.append(jax.ShapeDtypeStruct((b,) + tuple(shape),
                                                np.float32))
        return structs
    if isinstance(batch_or_struct, (list, tuple)):
        return [shell(a) for a in batch_or_struct]
    return [shell(batch_or_struct)]


def _opt_state_struct(tx, params_subtree):
    """Shape-only optimizer state for one layer's params. Elementwise optax
    transforms (sgd/adam/rmsprop/...) build per-leaf state, so initializing
    on the subtree attributes exactly that layer's share; scalar bookkeeping
    (step counts) double-counts by a few bytes per layer."""
    import jax

    try:
        return _bytes_of(jax.eval_shape(tx.init, params_subtree))
    except Exception:
        return 0


def memory_report(net, batch_or_struct=None) -> dict:
    """Per-layer/vertex HBM attribution for a ``MultiLayerNetwork`` or
    ``ComputationGraph`` — pure ``jax.eval_shape``, nothing allocates.

    ``batch_or_struct``: an int batch size (default 32), example arrays, or
    ``jax.ShapeDtypeStruct`` shells (a list for multi-input graphs).

    Returns ``{"layers": [...], "totals": {...}, "top_consumers": [...]}``.
    Param and optimizer totals are exact (counted off the live pytrees);
    activation bytes are the traced layer outputs at the given batch; the
    projected peak models one training step's working set::

        params + gradients(= params) + optimizer state + activations + inputs

    XLA's buffer reuse can beat this and ``remat`` shrinks the activation
    term — treat it as the planning number, not a measurement. The measured
    twin is the compile cache's ``memory_analysis`` records.
    """
    import jax

    net.init()
    conf = net.conf
    inputs = _input_structs(net, batch_or_struct)
    is_graph = hasattr(conf, "vertices")
    tx = net._tx

    if is_graph:
        acts, _, _ = jax.eval_shape(
            lambda xs: net._activations(net.params, xs, net.state, False,
                                        None, None),
            inputs,
        )
        names = list(net._topo)
        params_of = lambda n: net.params[n]  # noqa: E731
        act_of = lambda n: acts.get(n)  # noqa: E731
        label_of = lambda n: n  # noqa: E731
        type_of = lambda n: (  # noqa: E731
            type(getattr(conf.vertices[n], "layer", None)).__name__
            if getattr(conf.vertices[n], "layer", None) is not None
            else type(conf.vertices[n]).__name__)
    else:
        acts = jax.eval_shape(lambda x: net.feed_forward(x), inputs[0])
        names = list(range(len(conf.layers)))
        params_of = lambda i: net.params[i]  # noqa: E731
        act_of = lambda i: acts[i]  # noqa: E731
        label_of = lambda i: f"layer[{i}]"  # noqa: E731
        type_of = lambda i: type(conf.layers[i]).__name__  # noqa: E731

    rows = []
    for n in names:
        p_bytes = _bytes_of(params_of(n))
        a = act_of(n)
        a_bytes = _bytes_of(a)
        o_bytes = _opt_state_struct(tx, params_of(n)) if p_bytes else 0
        rows.append({
            "name": label_of(n),
            "type": type_of(n),
            "param_bytes": p_bytes,
            "grad_bytes": p_bytes,  # autodiff mirrors the param pytree
            "opt_state_bytes": o_bytes,
            "activation_bytes": a_bytes,
            "activation_shape": (list(a.shape)
                                 if hasattr(a, "shape") else None),
            "total_bytes": 2 * p_bytes + o_bytes + a_bytes,
        })

    param_total = _bytes_of(net.params)
    opt_total = _bytes_of(net.opt_state)
    act_total = sum(r["activation_bytes"] for r in rows)
    input_total = _bytes_of(inputs)
    projected = 2 * param_total + opt_total + act_total + input_total
    report = {
        "model": type(net).__name__,
        "dtype": conf.dtype,
        "remat": bool(getattr(conf, "remat", False)),
        "inputs": [{"shape": list(s.shape), "dtype": str(s.dtype),
                    "bytes": _bytes_of(s)} for s in inputs],
        "layers": rows,
        "totals": {
            "param_bytes": param_total,
            "grad_bytes": param_total,
            "opt_state_bytes": opt_total,
            "activation_bytes": act_total,
            "input_bytes": input_total,
            "projected_peak_bytes": projected,
        },
        "top_consumers": [
            {"name": r["name"], "type": r["type"],
             "total_bytes": r["total_bytes"],
             "human": _fmt_bytes(r["total_bytes"])}
            for r in sorted(rows, key=lambda r: -r["total_bytes"])[:3]
        ],
    }
    return report


# ----------------------------------------------------------------- preflight
class MemoryPreflightError(RuntimeError):
    """Raised when the projected peak will not fit the HBM budget; carries
    the full :func:`memory_report` as ``.report``."""

    def __init__(self, message: str, report: dict,
                 projected_bytes: int, limit_bytes: int):
        super().__init__(message)
        self.report = report
        self.projected_bytes = projected_bytes
        self.limit_bytes = limit_bytes


def _hbm_limit() -> tuple:
    """(limit_bytes, source) — live PJRT limit, the env override, or host
    MemAvailable as the CPU stand-in; (None, reason) when nothing knows."""
    rows = device_memory_stats()
    for row in rows:
        if row.get("bytes_limit"):
            return int(row["bytes_limit"]), f"device {row['device']} memory_stats"
    env = os.environ.get(HBM_LIMIT_ENV)
    if env:
        try:
            return int(env), f"env {HBM_LIMIT_ENV}"
        except ValueError:
            pass
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024, \
                        "host MemAvailable (cpu stand-in)"
    except OSError:
        pass
    return None, "no memory_stats, env override, or /proc/meminfo"


def preflight(net, batch_or_struct=None, *, limit_bytes: Optional[int] = None,
              headroom: float = 0.9, registry: Optional[MetricsRegistry] = None,
              flight: Optional[Any] = None, layout: Optional[Any] = None) -> dict:
    """Will this net + batch fit? Raises :class:`MemoryPreflightError` with
    the biggest consumers named BEFORE any fit/warmup dispatch pays a doomed
    compile; returns the annotated :func:`memory_report` when it fits (or
    when no limit source exists — ``report["preflight"]["checked"]`` says
    which). ``headroom`` reserves a fraction of the limit for XLA scratch
    and fragmentation.

    ``layout``: a :class:`~deeplearning4j_tpu.parallel.MeshLayout` — the
    check then runs against the PER-DEVICE projection (params/grads/
    moments divided by each leaf's fsdp/tp shard factor and dropped to the
    precision policy's storage dtype; activations and inputs divided by the
    data×fsdp batch factor). A model whose global working set exceeds one
    device's HBM passes preflight when the layout makes its per-device
    share fit — the capability jump fsdp exists for."""
    report = memory_report(net, batch_or_struct)
    source = "explicit limit_bytes"
    if limit_bytes is None:
        limit_bytes, source = _hbm_limit()
    # fold in the DT2xx IR scan + static roofline cost: "donation dropped,
    # step predicted HBM-bound" belongs in the same pre-dispatch report as
    # "will not fit". With a layout the scan also runs the DT3xx
    # sharding-flow pass — its predicted collective census lands in the
    # report and its propagated activation specs drive the per-device
    # activation projection below (a tp-sharded hidden activation counts
    # its tp split, not just the batch factor). Advisory — a failed scan
    # never blocks preflight.
    activation_factors = None
    try:
        ir = net.analyze_ir(batch_or_struct, layout=layout) \
            if layout is not None else net.analyze_ir(batch_or_struct)
        report["ir"] = {
            "findings": [f.to_dict() for f in ir["findings"]],
            "static_cost": ir["static_cost"],
        }
        if "shard_flow" in ir:
            report["ir"]["shard_flow"] = ir["shard_flow"]
            activation_factors = {
                tuple(r["shape"]): r["factor"]
                for r in ir["shard_flow"].get("activation_factors", [])}
        from ..analysis.ir_checks import record_findings  # noqa: PLC0415

        record_findings(ir["findings"], registry=registry, flight=flight)
    except Exception as e:  # no input type / exotic net: note and move on
        report["ir"] = {"error": f"{type(e).__name__}: {e}"[:200]}
    if layout is not None:
        # fsdp HBM math (docs/distributed.md): what ONE device holds
        net.init()
        report["layout"] = layout.describe()
        report["totals"]["per_device"] = layout.sharded_totals(
            net, report, activation_factors)
    if flight is not None:
        try:
            flight.attach_memory_report(report)
        except Exception:
            pass
    if limit_bytes is None:
        report["preflight"] = {"checked": False, "reason": source}
        return report
    projected = report["totals"]["projected_peak_bytes"]
    if layout is not None:
        projected = report["totals"]["per_device"]["projected_peak_bytes"]
    budget = int(limit_bytes * headroom)
    report["preflight"] = {
        "checked": True,
        "fits": projected <= budget,
        "projected_peak_bytes": projected,
        "per_device": layout is not None,
        "limit_bytes": int(limit_bytes),
        "headroom": headroom,
        "limit_source": source,
    }
    if registry is not None:
        registry.gauge(
            "dl4jtpu_projected_peak_hbm_bytes",
            "memory_report projected training peak of the last preflight",
        ).set(projected)
    if projected > budget:
        top = ", ".join(
            f"{c['name']} ({c['type']}, {c['human']})"
            for c in report["top_consumers"])
        what = ("projected per-device training peak" if layout is not None
                else "projected training peak")
        raise MemoryPreflightError(
            f"{what} {_fmt_bytes(projected)} exceeds "
            f"{_fmt_bytes(budget)} ({headroom:.0%} of "
            f"{_fmt_bytes(limit_bytes)} from {source}); "
            f"biggest consumers: {top}",
            report, projected, int(limit_bytes),
        )
    return report
