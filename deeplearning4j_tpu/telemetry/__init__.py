"""Unified metrics/span/watchdog spine for training and serving.

The reference DL4J observed training through three disconnected mechanisms —
PerformanceListener samples/sec, Spark per-phase stats, StatsListener memory
sections (SURVEY.md §5.1). This package is the single instrumentation path
that replaces all of them, TPU-honest by construction (no per-step host
syncs; see docs/observability.md):

- :mod:`registry` — process-wide counters/gauges/histograms with Prometheus
  text exposition (``GET /metrics`` on the UI server) and JSON snapshots.
- :mod:`spans` — host spans exporting Chrome/Perfetto trace JSON, wrapped in
  ``jax.profiler.TraceAnnotation`` so they align with XLA slices.
- :mod:`device` — the per-step jnp metrics vector computed inside the jitted
  step (loss, grad norm, non-finite flag).
- :mod:`session` — :class:`Telemetry`, the K-step-fetch glue the fit paths
  call.
- :mod:`watchdog` — structured anomaly events (nan-loss,
  exploding-grad-norm, stalled-step-time) with pluggable sinks.
- :mod:`memory` — HBM accounting: XLA ``memory_analysis`` of cached
  executables, per-layer attribution via ``jax.eval_shape``
  (:func:`memory_report`), the :func:`preflight` will-it-fit check, and
  the single live ``device_memory_stats`` source.
- :mod:`flight_recorder` — bounded event ring + post-mortem JSON dump
  bundles, auto-triggered by watchdog anomalies.
- :mod:`tracing` — distributed request tracing: head-sampled
  :class:`TraceContext` propagated across processes via the
  ``x-dl4jtpu-trace`` header, per-hop Chrome-trace spans in a bounded
  ring, latency-histogram exemplars.
- :mod:`slo` — declared objectives (latency budget, availability) with
  multi-window burn-rate alerting over serving observations; breaches
  emit ``slo-burn`` watchdog anomalies and flight bundles.
- :mod:`history` — bounded multi-resolution time-series store
  (raw→1m→5m rollups, counter→rate, histogram-quantile series), the
  Deadline-paced :class:`HistorySampler`, and the fleet recording
  rules + EWMA/Holt ``dl4jtpu_forecast_*`` signals behind
  ``GET /api/history`` — the autoscaler's sensor suite.
"""

from .flight_recorder import (
    FlightRecorder,
    get_flight_recorder,
    install_crash_hook,
)
from .history import (
    FleetRecordingRules,
    Forecast,
    HistorySampler,
    HistoryStore,
    ensure_default_sampler,
    get_default_sampler,
    get_history_store,
    history_enabled,
    parse_prometheus_text,
    set_default_sampler,
    set_history_store,
)
from .memory import (
    MemoryPreflightError,
    device_memory_stats,
    executable_memory,
    memory_report,
    preflight,
    sample_device_memory,
)
from .registry import (
    DEFAULT_BUCKETS,
    MetricFamily,
    MetricsRegistry,
    get_registry,
)
from .session import Telemetry
from .slo import SLOMonitor, get_slo_monitor, set_slo_monitor
from .spans import Span, SpanRecorder, get_recorder, span
from .tracing import (
    TRACE_HEADER,
    TRACE_SAMPLE_ENV,
    TraceContext,
    TraceRing,
    current_trace,
    get_trace_ring,
    record_trace_event,
    sample_rate,
    set_default_baggage,
    should_sample,
    trace_span,
    use_trace,
)
from .watchdog import (
    EXPLODING_GRAD_NORM,
    INPUT_SHIFT,
    LOSS_DRIFT,
    NAN_LOSS,
    SLO_BURN,
    STALLED_STEP_TIME,
    AnomalyEvent,
    Watchdog,
    logging_sink,
)

__all__ = [
    "DEFAULT_BUCKETS",
    "MetricFamily",
    "MetricsRegistry",
    "get_registry",
    "Telemetry",
    "Span",
    "SpanRecorder",
    "get_recorder",
    "span",
    "AnomalyEvent",
    "Watchdog",
    "logging_sink",
    "NAN_LOSS",
    "EXPLODING_GRAD_NORM",
    "STALLED_STEP_TIME",
    "LOSS_DRIFT",
    "INPUT_SHIFT",
    "SLO_BURN",
    "TRACE_HEADER",
    "TRACE_SAMPLE_ENV",
    "TraceContext",
    "TraceRing",
    "current_trace",
    "get_trace_ring",
    "record_trace_event",
    "sample_rate",
    "set_default_baggage",
    "should_sample",
    "trace_span",
    "use_trace",
    "SLOMonitor",
    "get_slo_monitor",
    "set_slo_monitor",
    "FleetRecordingRules",
    "Forecast",
    "HistorySampler",
    "HistoryStore",
    "ensure_default_sampler",
    "get_default_sampler",
    "get_history_store",
    "history_enabled",
    "parse_prometheus_text",
    "set_default_sampler",
    "set_history_store",
    "FlightRecorder",
    "get_flight_recorder",
    "install_crash_hook",
    "MemoryPreflightError",
    "device_memory_stats",
    "executable_memory",
    "memory_report",
    "preflight",
    "sample_device_memory",
]
