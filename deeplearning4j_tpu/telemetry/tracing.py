"""Distributed request tracing: Dapper-style context propagation.

PRs 2/4/13 gave every process its own spans, metrics and flight ring —
but nothing followed ONE request across process boundaries. This module
closes that gap with the three classic pieces (Dapper §3):

- :class:`TraceContext` — 128-bit ``trace_id``, 64-bit ``span_id``,
  parent pointer, a head-sampling flag and string baggage (model name,
  checkpoint version). It rides the ``x-dl4jtpu-trace`` HTTP header
  between the fleet router and its workers, and a thread-local *current
  context* (:func:`current_trace` / :func:`use_trace`) inside a process,
  so deep layers (compile-manager dispatch, resilience retries) pick it
  up without signature churn.
- **Head sampling** — the decision is made ONCE at the ingress (the
  router), from ``DL4JTPU_TRACE_SAMPLE`` (a float or an ``1/N`` ratio,
  default 1/256), and propagates in the context. Interesting requests
  are upgraded post-hoc: an admission shed, a failed worker or a
  latency-budget breach flips ``sampled`` mid-request so its remaining
  hops record (and a ``trace_upgrade`` flight event marks the partial
  head — the documented tail-sampling caveat: hops BEFORE the upgrade
  were never recorded).
- **Bounded recording** — sampled spans land in the per-process
  :class:`TraceRing` (queryable by trace id, what ``GET
  /api/trace/<id>`` serves) AND the global
  :class:`~deeplearning4j_tpu.telemetry.spans.SpanRecorder` ring, so
  flight-recorder dump bundles carry the offending traces in their
  ``spans`` section. Every recorded span bumps
  ``dl4jtpu_trace_spans_total{hop}``.

Span events are Chrome trace-event dicts (``ph: "X"``, µs timestamps)
whose ``args`` carry ``trace_id``/``span_id``/``parent_id`` — a merged
trace is therefore a plain ``SpanRecorder.chrome_trace``-shaped document
(see ``fleet/router.py``'s merge endpoint). A coalesced micro-batch
dispatch records ONE span whose ``args.links`` list points at every
member request's span (fan-in links — the trace shows exactly which
strangers a request waited for).

Unsampled requests cost one thread-local read per hop — the serve-bench
overhead gate in scripts/check.sh holds default sampling within 3% of
tracing disabled.
"""

from __future__ import annotations

import random
import threading
import time
import urllib.parse
import uuid
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .registry import get_registry

__all__ = [
    "TRACE_HEADER",
    "TRACE_SAMPLE_ENV",
    "TraceContext",
    "TraceRing",
    "TraceSpan",
    "current_trace",
    "get_trace_ring",
    "record_trace_event",
    "sample_rate",
    "set_default_baggage",
    "should_sample",
    "trace_span",
    "use_trace",
]

# the one propagation header: "trace_id:span_id:sampled01[;key=value...]"
TRACE_HEADER = "x-dl4jtpu-trace"
# head-sampling rate at the ingress: a float ("0.01") or a ratio ("1/256")
TRACE_SAMPLE_ENV = "DL4JTPU_TRACE_SAMPLE"
_DEFAULT_SAMPLE = 1.0 / 256.0

# process-level baggage merged into every NEW root context (the serving
# side stamps the live checkpoint version here on swap, so traces born
# after a rollout carry the version they were served by)
_DEFAULT_BAGGAGE: Dict[str, str] = {}
_BAGGAGE_LOCK = threading.Lock()


def set_default_baggage(key: str, value: Optional[str]) -> None:
    """Set (or, with None, drop) one process-level baggage entry."""
    with _BAGGAGE_LOCK:
        if value is None:
            _DEFAULT_BAGGAGE.pop(str(key), None)
        else:
            _DEFAULT_BAGGAGE[str(key)] = str(value)


def _default_baggage() -> Dict[str, str]:
    with _BAGGAGE_LOCK:
        return dict(_DEFAULT_BAGGAGE)


def sample_rate() -> float:
    """The configured head-sampling rate (``DL4JTPU_TRACE_SAMPLE``)."""
    import os  # noqa: PLC0415

    raw = os.environ.get(TRACE_SAMPLE_ENV)
    if raw is None or raw == "":
        return _DEFAULT_SAMPLE
    raw = raw.strip()
    try:
        if "/" in raw:
            num, den = raw.split("/", 1)
            return float(num) / float(den)
        return float(raw)
    except (ValueError, ZeroDivisionError):
        return _DEFAULT_SAMPLE


def should_sample(rate: Optional[float] = None) -> bool:
    """One head-sampling decision. Deterministic at the edges: rate >= 1
    always samples, rate <= 0 never does."""
    r = sample_rate() if rate is None else float(rate)
    if r <= 0.0:
        return False
    if r >= 1.0:
        return True
    return random.random() < r


@dataclass
class TraceContext:
    """One request's position in a distributed trace."""

    trace_id: str
    span_id: str
    parent_id: Optional[str] = None
    sampled: bool = False
    baggage: Dict[str, str] = field(default_factory=dict)

    @classmethod
    def new(cls, sampled: Optional[bool] = None,
            baggage: Optional[Dict[str, str]] = None) -> "TraceContext":
        """A fresh root context (the ingress mints one per request).
        ``sampled=None`` takes the head-sampling decision here."""
        merged = _default_baggage()
        if baggage:
            merged.update({str(k): str(v) for k, v in baggage.items()})
        return cls(
            trace_id=uuid.uuid4().hex,
            span_id=uuid.uuid4().hex[:16],
            parent_id=None,
            sampled=should_sample() if sampled is None else bool(sampled),
            baggage=merged)

    def child(self) -> "TraceContext":
        """A child context: same trace, new span id, parent = this span."""
        return TraceContext(
            trace_id=self.trace_id,
            span_id=uuid.uuid4().hex[:16],
            parent_id=self.span_id,
            sampled=self.sampled,
            baggage=dict(self.baggage))

    # ------------------------------------------------------------- codec
    def to_header(self) -> str:
        parts = [f"{self.trace_id}:{self.span_id}:"
                 f"{1 if self.sampled else 0}"]
        for k in sorted(self.baggage):
            parts.append(f"{urllib.parse.quote(str(k), safe='')}="
                         f"{urllib.parse.quote(str(self.baggage[k]), safe='')}")
        return ";".join(parts)

    @classmethod
    def from_header(cls, value: Optional[str]) -> Optional["TraceContext"]:
        """Parse the propagation header; None on anything malformed (a
        garbled header must never fail the request it rode in on)."""
        if not value:
            return None
        try:
            head, *bags = str(value).split(";")
            trace_id, span_id, flag = head.split(":")
            if not trace_id or not span_id:
                return None
            baggage = {}
            for item in bags:
                if not item or "=" not in item:
                    continue
                k, v = item.split("=", 1)
                baggage[urllib.parse.unquote(k)] = urllib.parse.unquote(v)
            return cls(trace_id=trace_id, span_id=span_id, parent_id=None,
                       sampled=flag.strip() == "1", baggage=baggage)
        except (ValueError, AttributeError):
            return None

    def upgrade(self, reason: str) -> bool:
        """Post-hoc sample upgrade (shed / error / latency over budget):
        flip ``sampled`` so the remaining hops record, and mark the
        partial head with a ``trace_upgrade`` flight event. Returns True
        when this call performed the flip."""
        if self.sampled:
            return False
        self.sampled = True
        try:
            from .flight_recorder import get_flight_recorder  # noqa: PLC0415

            get_flight_recorder().record(
                "trace_upgrade", trace_id=self.trace_id,
                span_id=self.span_id, reason=str(reason))
        except Exception:  # observability must never fail the request
            pass
        return True


# ------------------------------------------------------------ thread-local
_TLS = threading.local()


def current_trace() -> Optional[TraceContext]:
    """The thread's active trace context (set by :func:`use_trace` /
    :class:`TraceSpan`), or None."""
    return getattr(_TLS, "ctx", None)


@contextmanager
def use_trace(ctx: Optional[TraceContext]):
    """Install ``ctx`` as the thread's current context for the block."""
    prev = getattr(_TLS, "ctx", None)
    _TLS.ctx = ctx
    try:
        yield ctx
    finally:
        _TLS.ctx = prev


# ------------------------------------------------------------------ ring
class TraceRing:
    """Bounded per-process store of sampled span events, queryable by
    trace id — what the fleet merge endpoint reads."""

    def __init__(self, capacity: int = 4096):
        self.capacity = int(capacity)
        self.dropped = 0
        self._lock = threading.Lock()
        self._events: List[dict] = []

    def add(self, event: dict) -> None:
        with self._lock:
            if len(self._events) >= self.capacity:
                # drop oldest: recent traces are the ones being debugged
                del self._events[0]
                self.dropped += 1
            self._events.append(event)

    def spans_for(self, trace_id: str) -> List[dict]:
        tid = str(trace_id)
        with self._lock:
            return [e for e in self._events
                    if (e.get("args") or {}).get("trace_id") == tid]

    @property
    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0


_GLOBAL_RING = TraceRing()


def get_trace_ring() -> TraceRing:
    """The process-wide trace-span ring."""
    return _GLOBAL_RING


def _spans_counter():
    return get_registry().counter(
        "dl4jtpu_trace_spans_total",
        "distributed-trace spans recorded, by hop name",
        labelnames=("hop",))


def _record(event: dict, hop: str) -> None:
    """One recorded span: trace ring + global span ring + counter."""
    _GLOBAL_RING.add(event)
    try:
        from .spans import get_recorder  # noqa: PLC0415

        get_recorder().add(event)
    except Exception:  # pragma: no cover - defensive
        pass
    try:
        _spans_counter().labels(hop=str(hop)).inc()
    except Exception:  # pragma: no cover - defensive
        pass


def record_trace_event(ctx: TraceContext, hop: str, *,
                       duration_s: float = 0.0,
                       ts_us: Optional[float] = None, **args) -> dict:
    """Record one span for ``ctx`` without timing a block — retroactive
    spans (a shed decision, an upgrade marker) and instant annotations."""
    import os  # noqa: PLC0415

    event = {
        "name": str(hop),
        "ph": "X",
        "ts": time.time() * 1e6 if ts_us is None else float(ts_us),
        "dur": max(0.0, float(duration_s)) * 1e6,
        "pid": os.getpid(),
        "tid": threading.get_ident(),
        "args": {
            "trace_id": ctx.trace_id,
            "span_id": ctx.span_id,
            "parent_id": ctx.parent_id,
            **args,
        },
    }
    _record(event, hop)
    return event


class TraceSpan:
    """One traced hop: a context manager that opens a CHILD span of
    ``ctx``, installs it as the thread's current context for the block,
    and records a Chrome-trace event on exit. A None/unsampled parent
    degrades to a no-op (``self.ctx`` stays None)."""

    def __init__(self, ctx: Optional[TraceContext], hop: str,
                 links: Optional[List[dict]] = None, **args):
        self.hop = str(hop)
        self.links = links
        self.args = dict(args)
        self.ctx = (ctx.child() if ctx is not None and ctx.sampled
                    else None)
        self._parent_sampled_from = ctx
        self._ts_us: Optional[float] = None
        self._t0: Optional[float] = None
        self._use = None

    def __enter__(self) -> "TraceSpan":
        if self.ctx is None:
            return self
        self._ts_us = time.time() * 1e6
        self._t0 = time.perf_counter()
        self._use = use_trace(self.ctx)
        self._use.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self.ctx is None:
            return
        dur = time.perf_counter() - (self._t0 or time.perf_counter())
        self._use.__exit__(exc_type, exc, tb)
        if exc is not None:
            self.args.setdefault("error", f"{type(exc).__name__}: {exc}"[:200])
        if self.links:
            self.args["links"] = list(self.links)
        record_trace_event(
            self.ctx, self.hop, duration_s=dur, ts_us=self._ts_us,
            **self.args)


def trace_span(ctx: Optional[TraceContext], hop: str,
               links: Optional[List[dict]] = None, **args) -> TraceSpan:
    """``with trace_span(ctx, "serve.request", model=name) as sp: ...`` —
    the usual entry point; ``sp.ctx`` is the child context to propagate
    further down (None when the request is unsampled)."""
    return TraceSpan(ctx, hop, links=links, **args)
