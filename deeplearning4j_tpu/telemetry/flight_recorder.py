"""Anomaly flight recorder: a black box for runs that die at 3am.

A bounded in-process ring buffer of structured events — step stats (from the
device-side metrics vector, recorded at fetch time), compiles/evictions,
bucketing shape transitions, staged dispatches, memory watermarks, watchdog
anomalies. Steady-state cost is one deque append under a lock; the ring
never grows past ``capacity``.

Registered as a Watchdog sink (``Telemetry`` wires this automatically): on a
nan-loss / exploding-grad-norm / stalled-step anomaly — or an explicit
:meth:`FlightRecorder.dump`, or the crash hook — it writes a self-contained
JSON dump bundle: the last-K events, the most recent memory report, the
compile-cache state (including per-executable ``memory_analysis`` records),
a full registry snapshot, recent spans, and device/env info. The bundle is
what turns "the run died" into a diagnosable artifact.

Dump location: ``DL4JTPU_FLIGHT_DIR`` (env) > the recorder's ``dump_dir`` >
the system temp dir. Schema: ``dl4jtpu-flight-v1`` (docs/observability.md).
"""

from __future__ import annotations

import collections
import json
import logging
import os
import sys
import tempfile
import threading
import time
from typing import List, Optional

from .registry import MetricsRegistry, get_registry
from .watchdog import EXPLODING_GRAD_NORM, NAN_LOSS, SLO_BURN, STALLED_STEP_TIME

logger = logging.getLogger(__name__)

FLIGHT_DIR_ENV = "DL4JTPU_FLIGHT_DIR"
SCHEMA = "dl4jtpu-flight-v1"

# ---------------------------------------------------------------------------
# Event-kind registry. Every kind the ring records must be registered here
# (or via register_event_kind at import time of the owning module) — the
# DT406 telemetry-schema lint audits record() call sites against this set,
# and replay tooling treats unregistered kinds as schema drift. record()
# itself stays permissive at runtime: an unknown kind rings fine, it just
# fails the static scan until someone declares it.
_EVENT_KINDS: set = set()
_EVENT_KINDS_LOCK = threading.Lock()


def register_event_kind(kind: str) -> str:
    """Declare a flight-recorder event kind; returns it (idempotent), so
    owners can write ``MY_KIND = register_event_kind("my_kind")``."""
    with _EVENT_KINDS_LOCK:
        _EVENT_KINDS.add(str(kind))
    return str(kind)


def registered_event_kinds() -> frozenset:
    with _EVENT_KINDS_LOCK:
        return frozenset(_EVENT_KINDS)


# kinds this module records
STEP = register_event_kind("step")
COMPILE = register_event_kind("compile")
EVICTION = register_event_kind("eviction")
BUCKET_SHAPE = register_event_kind("bucket_shape")
STAGED_DISPATCH = register_event_kind("staged_dispatch")
MEMORY = register_event_kind("memory")
ANOMALY = register_event_kind("anomaly")
DUMP = register_event_kind("dump")
CRASH = register_event_kind("crash")

# kinds owned by the rest of the stack. They live here, in the schema
# owner, so the DT406 audit (and offline replay tools) can see the full
# contract without importing jax-heavy modules; a module introducing a NEW
# kind adds it to its own import-time register_event_kind call AND this
# table stays the human-readable inventory.
for _kind in (
    # runtime/compile_manager.py, telemetry/session.py, analysis
    "ir_finding",
    # nn kernel selection + tuned-config auto-apply
    "kernel_select", "tuned_config_applied",
    # serving/service.py
    "serve_dispatch", "serve_swap",
    # runtime/online.py
    "online_start", "online_stop", "online_pause", "online_resume",
    "online_swap", "online_rollback", "online_rollback_skipped",
    "online_poisoned_span", "online_replay", "online_replay_unsupported",
    "online_replay_error", "online_source_error", "online_source_reconnect",
    "online_loop_error",
    # runtime/resilience.py
    "resilience_retry", "resilience_giveup", "deadline_expired",
    "circuit_closed", "circuit_open", "circuit_half_open",
    # telemetry/tracing.py (post-hoc sample upgrade on shed/error/slow)
    "trace_upgrade",
    # telemetry/slo.py (multi-window burn-rate breach)
    "slo_burn",
    # fleet/router.py (rolling rollout + dead-worker respawn, spliced into
    # merged traces as instant events)
    "fleet_rollout", "fleet_respawn",
    # telemetry/history.py (flight event -> history-timeline annotation
    # splice; rung once per annotation so the black box shows the splice)
    "history_annotation",
):
    register_event_kind(_kind)
del _kind


class FlightRecorder:
    """Bounded event ring + post-mortem dump bundles.

    ``capacity``: ring size (events beyond it drop oldest-first — the
    counter ``dropped`` keeps the total). ``auto_dump_kinds``: anomaly
    kinds that trigger a dump when this recorder is a watchdog sink;
    ``min_dump_interval_s`` rate-limits auto-dumps so a NaN storm writes
    one bundle, not thousands.
    """

    def __init__(self, capacity: int = 4096,
                 dump_dir: Optional[str] = None,
                 registry: Optional[MetricsRegistry] = None,
                 auto_dump_kinds=(NAN_LOSS, EXPLODING_GRAD_NORM,
                                  STALLED_STEP_TIME, SLO_BURN),
                 min_dump_interval_s: float = 30.0):
        if int(capacity) < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.dump_dir = dump_dir
        self.auto_dump_kinds = frozenset(auto_dump_kinds)
        self.min_dump_interval_s = float(min_dump_interval_s)
        self.dropped = 0
        self.dumps: List[str] = []
        self._dump_seq = 0  # filename sequence, reserved under _lock
        self.last_memory_report: Optional[dict] = None
        self._lock = threading.Lock()
        self._events: "collections.deque[dict]" = collections.deque(
            maxlen=self.capacity)
        # rate limit is PER REASON: a stall dump must not swallow the
        # nan-loss bundle that follows it — different failure classes each
        # get their post-mortem, while a storm of one kind writes one file
        self._last_dump_t: dict = {}
        reg = registry if registry is not None else get_registry()
        self._registry = reg
        self._events_total = reg.counter(
            "dl4jtpu_flight_events_total",
            "flight-recorder events recorded, by kind",
            labelnames=("kind",))
        self._dumps_total = reg.counter(
            "dl4jtpu_flight_dumps_total",
            "flight-recorder dump bundles written, by reason",
            labelnames=("reason",))

    # ------------------------------------------------------------ recording
    def record(self, kind: str, **payload) -> None:
        """Append one structured event (near-zero cost; never raises)."""
        event = {"ts": time.time(), "kind": str(kind)}
        event.update(payload)
        with self._lock:
            if len(self._events) >= self.capacity:
                self.dropped += 1  # deque maxlen drops the oldest
            self._events.append(event)
        try:
            self._events_total.labels(kind=str(kind)).inc()
        except Exception:  # pragma: no cover - defensive
            pass

    @property
    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    def attach_memory_report(self, report: dict) -> None:
        """Keep the latest :func:`telemetry.memory.memory_report` so dumps
        carry per-layer attribution alongside the raw watermarks."""
        self.last_memory_report = report

    # --------------------------------------------------------- watchdog sink
    def watchdog_sink(self, event) -> None:
        """Watchdog sink: ring the anomaly, auto-dump (rate-limited)."""
        payload = event.to_dict()
        payload["anomaly"] = payload.pop("kind")  # "kind" names the ring slot
        self.record(ANOMALY, **payload)
        if event.kind not in self.auto_dump_kinds:
            return
        now = time.monotonic()
        with self._lock:
            last = self._last_dump_t.get(event.kind)
        if last is not None and now - last < self.min_dump_interval_s:
            return
        try:
            self.dump(reason=event.kind)
        except Exception:  # a broken dump must never kill the train loop
            logger.exception("flight-recorder auto-dump failed")

    # ------------------------------------------------------------- snapshots
    def snapshot(self, last: Optional[int] = None) -> dict:
        """JSON-ready view for the UI (``GET /api/flightrecorder``)."""
        events = self.events
        if last is not None and last >= 0:
            events = events[-last:]
        with self._lock:  # dumps/dropped race concurrent dump()/record()
            dumps = list(self.dumps)
            dropped = self.dropped
        return {
            "capacity": self.capacity,
            "recorded": len(events),
            "dropped": dropped,
            "events": events,
            "dumps": dumps,
        }

    def bundle(self, reason: str = "manual") -> dict:
        """The self-contained post-mortem dict (what :meth:`dump` writes).
        Every section is collected defensively — a broken collector yields
        an ``{"error": ...}`` stanza, never a missing bundle."""
        def guarded(fn):
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 - post-mortem must survive
                return {"error": f"{type(e).__name__}: {e}"[:300]}

        def compile_cache():
            from ..runtime.compile_manager import get_compile_manager  # noqa: PLC0415

            return get_compile_manager().stats()

        def device_env():
            info: dict = {"python": sys.version.split()[0]}
            import jax  # noqa: PLC0415

            info["jax"] = jax.__version__
            info["backend"] = jax.default_backend()
            devs = jax.devices()
            info["device_count"] = len(devs)
            info["device_platform"] = devs[0].platform if devs else "none"
            info["env"] = {k: v for k, v in os.environ.items()
                           if k.startswith(("DL4JTPU_", "JAX_", "XLA_"))}
            return info

        def spans_tail():
            from .spans import get_recorder  # noqa: PLC0415

            return get_recorder().events[-200:]

        def memory_section():
            from . import memory as _tmem  # noqa: PLC0415

            return {"devices": _tmem.device_memory_stats(),
                    "report": self.last_memory_report}

        return {
            "schema": SCHEMA,
            "reason": str(reason),
            "timestamp": time.time(),
            "pid": os.getpid(),
            "events": self.events,
            "dropped_events": self.dropped,
            "memory": guarded(memory_section),
            "compile_cache": guarded(compile_cache),
            "registry": guarded(self._registry.snapshot),
            "spans": guarded(spans_tail),
            "environment": guarded(device_env),
        }

    def dump(self, reason: str = "manual",
             path: Optional[str] = None) -> str:
        """Write the bundle as one JSON file; returns its path. Directory:
        explicit ``path`` > ``DL4JTPU_FLIGHT_DIR`` > ``dump_dir`` > the
        system temp dir."""
        bundle = self.bundle(reason)
        if path is None:
            directory = (os.environ.get(FLIGHT_DIR_ENV) or self.dump_dir
                         or tempfile.gettempdir())
            os.makedirs(directory, exist_ok=True)
            safe = "".join(c if c.isalnum() or c in "-_" else "-"
                           for c in str(reason))[:48]
            # reserve the sequence number atomically — len(self.dumps)
            # would hand two racing dumps the same filename
            with self._lock:
                seq = self._dump_seq
                self._dump_seq += 1
            path = os.path.join(
                directory,
                f"flight_{time.strftime('%Y%m%d-%H%M%S')}_"
                f"{os.getpid()}_{seq}_{safe}.json")
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(bundle, fh, default=str)
        # publish under the ring lock: snapshot() iterates dumps and
        # watchdog_sink reads _last_dump_t from other threads
        with self._lock:
            self._last_dump_t[str(reason)] = time.monotonic()
            self.dumps.append(path)
        self.record(DUMP, reason=str(reason), path=path)
        try:
            self._dumps_total.labels(reason=str(reason)).inc()
        except Exception:  # pragma: no cover - defensive
            pass
        logger.warning("flight recorder dumped %s (%s)", path, reason)
        return path


_GLOBAL: Optional[FlightRecorder] = None
_GLOBAL_LOCK = threading.Lock()
_HOOK_INSTALLED = False


def get_flight_recorder() -> FlightRecorder:
    """The process-wide default recorder (the compile manager, bucketed
    stager and Telemetry sessions record into it unless handed their own)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = FlightRecorder()
        return _GLOBAL


def set_flight_recorder(recorder: Optional[FlightRecorder]) -> None:
    """Swap the process-wide recorder (soak harnesses / tests want a
    private dump dir); ``None`` resets to lazy re-creation of the
    default."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        _GLOBAL = recorder


def install_crash_hook(recorder: Optional[FlightRecorder] = None) -> FlightRecorder:
    """Dump on an unhandled exception (``sys.excepthook`` wrap) and, at
    interpreter exit, when anomalies were ringed but never dumped — the
    last-ditch artifact for a run that dies outside the watchdog's view.
    Idempotent; returns the hooked recorder."""
    global _HOOK_INSTALLED
    rec = recorder if recorder is not None else get_flight_recorder()
    with _GLOBAL_LOCK:
        if _HOOK_INSTALLED:
            return rec
        _HOOK_INSTALLED = True
    prev_hook = sys.excepthook

    def hook(exc_type, exc, tb):
        try:
            rec.record("crash", error=f"{exc_type.__name__}: {exc}"[:300])
            rec.dump(reason="crash")
        except Exception:  # pragma: no cover - never mask the real error
            pass
        prev_hook(exc_type, exc, tb)

    sys.excepthook = hook

    import atexit  # noqa: PLC0415

    def exit_dump():
        try:
            anomalies = [e for e in rec.events if e["kind"] == ANOMALY]
            if anomalies and not rec.dumps:
                rec.dump(reason="atexit-undumped-anomalies")
        except Exception:  # pragma: no cover
            pass

    atexit.register(exit_dump)
    return rec
