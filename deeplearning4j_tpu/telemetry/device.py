"""Device-side step accumulators: the jnp vector carried out of the jit step.

The instrumentation contract that keeps telemetry off the dispatch critical
path: everything per-step is computed INSIDE the jitted program as a tiny
``[NUM_SLOTS]`` float32 vector (loss, global grad norm, non-finite flag) and
returned alongside the step outputs. The host appends these device scalars to
a buffer without reading them — fetching (the only host sync) happens once
every K steps in :class:`telemetry.session.Telemetry`, or once per staged
``fit_on_device`` dispatch where the scan stacks them to ``[steps, NUM_SLOTS]``.

``step_stats`` is pure jnp and works both traced (inside ``jax.jit``) and
eager (on the grad-stats path, where the step already returns gradients) —
eager jnp ops dispatch async and still never block the host.
"""

from __future__ import annotations

# Slot layout of the per-step metrics vector.
LOSS = 0
GRAD_NORM = 1
NONFINITE = 2
NUM_SLOTS = 3

# Test seam: a callable invoked at TRACE time from inside step_stats. Because
# Python in a traced body runs only while XLA traces it, counting calls here
# counts compilations — the "counting tracer" the telemetry tests use to
# prove the instrumented step compiles once, not per iteration.
_TRACE_HOOK = None


def step_stats(loss, grads=None):
    """Build the per-step metrics vector (float32 ``[NUM_SLOTS]``).

    ``loss``: scalar. ``grads``: gradient pytree (or None when the step has
    no gradient view — grad norm reports 0). The non-finite flag is 1.0 when
    the loss or any gradient leaf contains NaN/Inf.
    """
    import jax
    import jax.numpy as jnp

    if _TRACE_HOOK is not None:
        _TRACE_HOOK()
    loss32 = jnp.asarray(loss, jnp.float32)
    finite = jnp.isfinite(loss32)
    if grads is not None:
        leaves = [l for l in jax.tree_util.tree_leaves(grads)
                  if jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)]
        if leaves:
            sq = sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
            gnorm = jnp.sqrt(sq)
            finite = jnp.logical_and(finite, jnp.isfinite(gnorm))
        else:
            gnorm = jnp.zeros((), jnp.float32)
    else:
        gnorm = jnp.zeros((), jnp.float32)
    nonfinite = 1.0 - finite.astype(jnp.float32)
    return jnp.stack([loss32, gnorm, nonfinite])
