"""Host-side spans with Chrome/Perfetto trace export.

A :class:`Span` measures one named host-side region (data load, dispatch,
sync, checkpoint write). Two properties make it TPU-honest:

- Entering a span also enters ``jax.profiler.TraceAnnotation(name)``, so when
  a ``jax.profiler.trace`` capture is active the host span appears in the
  SAME xplane timeline as the XLA device slices it encloses — host and device
  views line up instead of living in two disconnected tools.
- Closing a span never syncs the device: it records wall-clock enqueue time.
  Under async dispatch a span around an un-synced jit call measures dispatch,
  not execution — wrap the sync point (the host fetch) in its own span when
  execution time is what you want.

Completed spans land in a :class:`SpanRecorder` ring buffer and export as
Chrome trace-event JSON (``chrome://tracing`` / Perfetto "trace event"
format): complete events (``ph: "X"``), microsecond timestamps, pid/tid, and
user args. Durations optionally feed a registry histogram
(``dl4jtpu_span_seconds{name=...}``) so span timing is also scrapeable.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import List, Optional

from .registry import MetricsRegistry


class SpanRecorder:
    """Bounded collector of completed span events (Chrome trace dicts)."""

    def __init__(self, max_events: int = 100_000):
        self.max_events = int(max_events)
        self.dropped = 0
        self._lock = threading.Lock()
        self._events: List[dict] = []

    def add(self, event: dict) -> None:
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
                return
            self._events.append(event)

    @property
    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    def chrome_trace(self) -> dict:
        """Trace-event-format document (load in Perfetto / chrome://tracing)."""
        return {
            "traceEvents": self.events,
            "displayTimeUnit": "ms",
            "otherData": {"producer": "deeplearning4j_tpu.telemetry"},
        }

    def export_chrome_trace(self, path: str) -> str:
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(self.chrome_trace(), fh)
        return path


_GLOBAL_RECORDER = SpanRecorder()


def get_recorder() -> SpanRecorder:
    """The process-wide default span recorder."""
    return _GLOBAL_RECORDER


class Span:
    """One named region; context manager or explicit ``start()``/``stop()``."""

    def __init__(self, name: str, recorder: Optional[SpanRecorder] = None,
                 registry: Optional[MetricsRegistry] = None, **args):
        self.name = str(name)
        self.recorder = recorder if recorder is not None else _GLOBAL_RECORDER
        self._registry = registry
        self.args = {k: v for k, v in args.items()}
        self._annotation = None
        self._t0: Optional[float] = None
        self._ts_us: Optional[float] = None
        self.duration_s: Optional[float] = None

    def start(self) -> "Span":
        if self._t0 is not None:
            raise RuntimeError(f"span {self.name!r} already started")
        try:
            import jax  # noqa: PLC0415 - keep telemetry importable without jax

            self._annotation = jax.profiler.TraceAnnotation(self.name)
            self._annotation.__enter__()
        except Exception:
            self._annotation = None  # no profiler backend: host-only span
        self._ts_us = time.time() * 1e6
        self._t0 = time.perf_counter()
        return self

    def stop(self) -> float:
        if self._t0 is None:
            raise RuntimeError(f"span {self.name!r} was never started")
        dur = time.perf_counter() - self._t0
        self._t0 = None
        if self._annotation is not None:
            try:
                self._annotation.__exit__(None, None, None)
            finally:
                self._annotation = None
        self.duration_s = dur
        event = {
            "name": self.name,
            "ph": "X",
            "ts": self._ts_us,
            "dur": dur * 1e6,
            "pid": os.getpid(),
            "tid": threading.get_ident(),
        }
        if self.args:
            event["args"] = self.args
        self.recorder.add(event)
        if self._registry is not None:
            self._registry.histogram(
                "dl4jtpu_span_seconds", "host span durations",
                labelnames=("name",),
            ).labels(name=self.name).observe(dur)
        return dur

    def __enter__(self) -> "Span":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def span(name: str, recorder: Optional[SpanRecorder] = None,
         registry: Optional[MetricsRegistry] = None, **args) -> Span:
    """``with span("data_load", batch=i): ...`` — the usual entry point."""
    return Span(name, recorder=recorder, registry=registry, **args)
