"""Pass 5b: runtime env-hygiene and telemetry-schema lint (DT403, DT404,
DT406) + the combined DT4xx tier driver.

- **DT403** — raw ``os.environ`` mutation (subscript writes/deletes,
  ``pop``/``update``/``setdefault``/``clear``, ``os.putenv``). The only
  sanctioned mutation path is :class:`tune.EnvScope` /
  :func:`tune.scoped_env`, whose own implementation carries the justified
  ignore pragma. Reads (``os.environ.get``) and copies
  (``dict(os.environ)``) stay clean.
- **DT404** — bare ``time.sleep`` anywhere: the AST successor to the old
  check.sh grep gate. Poll loops belong on
  ``runtime.resilience.Deadline.pace`` (stop-event aware, accounted),
  waits on ``wait_event``/``event.wait(timeout)``.
- **DT406** — telemetry schema consistency. A :class:`TelemetrySchema`
  accumulates every ``dl4jtpu_*`` metric declaration
  (``registry.counter/gauge/histogram`` with a literal name) and every
  flight-recorder ``record(<kind>)`` site across all scanned files, then
  reports metric names declared with conflicting types/label sets (or
  from two modules), and event kinds no module registered with
  :func:`telemetry.flight_recorder.register_event_kind`.

:func:`check_runtime_source` runs the whole DT4xx tier (delegating
DT400-DT402/DT405 to :mod:`analysis.concurrency`) on one source;
:func:`check_runtime_paths` scans files/trees with ONE schema aggregated
across all of them — that is what ``python -m deeplearning4j_tpu.analysis
--concurrency`` and ``conf.analyze(concurrency=True)`` invoke.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .ast_checks import _full_name, _last
from .concurrency import check_concurrency_source
from .findings import Finding, merge_findings, sort_findings
from .pragmas import filter_findings
from .rules import get_rule

__all__ = [
    "TelemetrySchema",
    "check_runtime_file",
    "check_runtime_package",
    "check_runtime_paths",
    "check_runtime_source",
]

_ENV_MUTATORS = {"pop", "update", "setdefault", "clear", "popitem"}
_METRIC_CTORS = {"counter", "gauge", "histogram"}
_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules", "build",
              "dist"}


def _env_bases(tree: ast.Module) -> Set[str]:
    """Dotted names that refer to os.environ in this module."""
    bases = {"os.environ"}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "os":
            for alias in node.names:
                if alias.name == "environ":
                    bases.add(alias.asname or "environ")
    return bases


def _sleep_names(tree: ast.Module) -> Set[str]:
    names = {"time.sleep"}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name == "sleep":
                    names.add(alias.asname or "sleep")
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "time" and alias.asname:
                    names.add(f"{alias.asname}.sleep")
    return names


def _scan_env_and_sleep(tree: ast.Module, filename: str) -> List[Finding]:
    rule403 = get_rule("DT403")
    rule404 = get_rule("DT404")
    env_bases = _env_bases(tree)
    sleep_names = _sleep_names(tree)
    findings: List[Finding] = []

    def env_write(node: ast.AST, what: str) -> None:
        findings.append(rule403.finding(
            f"raw os.environ mutation ({what}) — prior state (including "
            f"absence) is lost",
            file=filename, line=node.lineno, col=node.col_offset,
            context=what))

    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for target in targets:
                if (isinstance(target, ast.Subscript)
                        and _full_name(target.value) in env_bases):
                    env_write(node, "subscript assignment")
        elif isinstance(node, ast.Delete):
            for target in node.targets:
                if (isinstance(target, ast.Subscript)
                        and _full_name(target.value) in env_bases):
                    env_write(node, "del")
        elif isinstance(node, ast.Call):
            fname = _full_name(node.func)
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _ENV_MUTATORS
                    and _full_name(node.func.value) in env_bases):
                env_write(node, f"environ.{node.func.attr}()")
            elif fname in ("os.putenv", "os.unsetenv"):
                env_write(node, f"{fname}()")
            elif fname in sleep_names:
                findings.append(rule404.finding(
                    "bare time.sleep(): no deadline, no stop event, "
                    "invisible to resilience stats",
                    file=filename, line=node.lineno, col=node.col_offset,
                    context=fname))
    return findings


def _registered_event_kinds() -> Optional[Set[str]]:
    try:
        from ..telemetry.flight_recorder import registered_event_kinds
    except Exception:  # pragma: no cover - analysis must run without deps
        return None
    try:
        return set(registered_event_kinds())
    except Exception:  # pragma: no cover
        return None


class TelemetrySchema:
    """Cross-file accumulator for DT406.

    ``collect()`` one parsed module at a time, then ``findings()`` once at
    the end — metric-name collisions only exist across the whole scanned
    set, so per-file checking would miss exactly the drift this rule is
    for.
    """

    def __init__(self, registered_kinds: Optional[Set[str]] = None):
        self.registered = (registered_kinds if registered_kinds is not None
                           else _registered_event_kinds())
        # metric name -> (ctor kind, labels-or-None, file, line)
        self.metrics: Dict[str, Tuple[str, Optional[Tuple[str, ...]],
                                      str, int]] = {}
        self._conflicts: List[Finding] = []
        self._events: List[Tuple[str, str, int, int]] = []
        self._sources: Dict[str, str] = {}

    # -- collection --------------------------------------------------------
    def collect(self, tree: ast.Module, source: str, filename: str) -> None:
        self._sources[filename] = source
        consts: Dict[str, str] = {}
        for stmt in tree.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, str)):
                consts[stmt.targets[0].id] = stmt.value.value
        flight_class_calls: Set[ast.Call] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and "FlightRecorder" in node.name:
                flight_class_calls.update(
                    c for c in ast.walk(node) if isinstance(c, ast.Call))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            self._collect_metric(node, filename)
            self._collect_event(node, flight_class_calls, consts, filename)

    def _collect_metric(self, call: ast.Call, filename: str) -> None:
        if not isinstance(call.func, ast.Attribute):
            return
        kind = call.func.attr
        if kind not in _METRIC_CTORS or not call.args:
            return
        first = call.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)
                and first.value.startswith("dl4jtpu_")):
            return
        name = first.value
        labels = self._labelnames(call)
        prior = self.metrics.get(name)
        if prior is None:
            self.metrics[name] = (kind, labels, filename, call.lineno)
            return
        pkind, plabels, pfile, pline = prior
        rule = get_rule("DT406")
        if pkind != kind:
            self._conflicts.append(rule.finding(
                f"metric '{name}' declared as {kind} here but as {pkind} "
                f"at {pfile}:{pline} — dashboards split the series",
                file=filename, line=call.lineno, col=call.col_offset,
                context=name))
        elif labels is not None and plabels is not None \
                and labels != plabels:
            self._conflicts.append(rule.finding(
                f"metric '{name}' declared with labels {list(labels)} here "
                f"but {list(plabels)} at {pfile}:{pline} — label sets must "
                f"be stable",
                file=filename, line=call.lineno, col=call.col_offset,
                context=name))
        elif os.path.abspath(pfile) != os.path.abspath(filename):
            self._conflicts.append(rule.finding(
                f"metric '{name}' declared in two modules (here and "
                f"{pfile}:{pline}) — each metric needs one owning module",
                file=filename, line=call.lineno, col=call.col_offset,
                context=name))

    @staticmethod
    def _labelnames(call: ast.Call) -> Optional[Tuple[str, ...]]:
        expr = None
        if len(call.args) >= 3:
            expr = call.args[2]
        for kw in call.keywords:
            if kw.arg == "labelnames":
                expr = kw.value
        if expr is None:
            return ()
        if isinstance(expr, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in expr.elts):
            return tuple(e.value for e in expr.elts)
        return None  # dynamic label expression: skip the comparison

    def _collect_event(self, call: ast.Call,
                       flight_class_calls: Set[ast.Call],
                       consts: Dict[str, str], filename: str) -> None:
        if not (isinstance(call.func, ast.Attribute)
                and call.func.attr == "record" and call.args):
            return
        receiver = call.func.value
        rname = _full_name(receiver)
        if isinstance(receiver, ast.Call):
            rname = _full_name(receiver.func)
        is_flight = "flight" in rname or _last(rname) in ("rec", "recorder")
        if not is_flight and rname == "self":
            # FlightRecorder's own helpers call self.record(...)
            is_flight = call in flight_class_calls
        if not is_flight:
            return
        first = call.args[0]
        kind: Optional[str] = None
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            kind = first.value
        elif isinstance(first, ast.Name):
            kind = consts.get(first.id)
        if kind is None:
            return  # dynamic kind expression: nothing to audit statically
        self._events.append((kind, filename, call.lineno, call.col_offset))

    # -- reporting ---------------------------------------------------------
    def findings(self) -> List[Finding]:
        out = list(self._conflicts)
        if self.registered is not None:
            rule = get_rule("DT406")
            for kind, filename, line, col in self._events:
                if kind not in self.registered:
                    out.append(rule.finding(
                        f"flight-recorder event kind '{kind}' is recorded "
                        f"but never registered — register_event_kind() it "
                        f"in the owning module",
                        file=filename, line=line, col=col, context=kind))
        by_file: Dict[str, List[Finding]] = {}
        for f in out:
            by_file.setdefault(f.file, []).append(f)
        filtered: List[Finding] = []
        for filename, group in by_file.items():
            source = self._sources.get(filename)
            filtered.extend(filter_findings(group, source)
                            if source is not None else group)
        return sort_findings(filtered)


def check_runtime_source(source: str, filename: str = "<source>", *,
                         schema: Optional[TelemetrySchema] = None
                         ) -> List[Finding]:
    """The full DT4xx tier on one source string.

    With ``schema=None`` (standalone use, tests) a private schema is
    created and its DT406 findings are included; pass a shared schema to
    aggregate metric/event declarations across files and call
    ``schema.findings()`` yourself at the end.
    """
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return check_concurrency_source(source, filename)  # DT100
    findings = filter_findings(
        sort_findings(_scan_env_and_sleep(tree, filename)), source)
    findings += check_concurrency_source(source, filename)
    own_schema = schema is None
    if own_schema:
        schema = TelemetrySchema()
    schema.collect(tree, source, filename)
    if own_schema:
        findings += schema.findings()
    return sort_findings(merge_findings(findings))


def _iter_py_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS
                                 and not d.startswith("."))
                for name in sorted(files):
                    if name.endswith(".py"):
                        out.append(os.path.join(root, name))
        elif path.endswith(".py"):
            out.append(path)
    return out


def check_runtime_paths(paths: Sequence[str]) -> List[Finding]:
    """DT4xx over files/directories with ONE schema across all of them."""
    schema = TelemetrySchema()
    findings: List[Finding] = []
    for py in _iter_py_files(paths):
        with open(py, "r", encoding="utf-8") as fh:
            source = fh.read()
        findings += check_runtime_source(source, filename=py, schema=schema)
    findings += schema.findings()
    return sort_findings(merge_findings(findings))


def check_runtime_file(path: str) -> List[Finding]:
    return check_runtime_paths([path])


def check_runtime_package() -> List[Finding]:
    """Self-scan of the package's threaded runtime stack — the surface the
    check.sh gate holds clean at --fail-on warning."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    dirs = [os.path.join(pkg_dir, d)
            for d in ("serving", "fleet", "runtime", "telemetry",
                      "streaming")]
    return check_runtime_paths([d for d in dirs if os.path.isdir(d)])
