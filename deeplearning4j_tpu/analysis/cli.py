"""CLI: ``python -m deeplearning4j_tpu.analysis [paths...]``.

- ``.py`` files (and directories, walked recursively) get the AST pass.
- ``.json`` files are parsed as serialized configs (``to_json`` output of
  MultiLayerConfiguration / ComputationGraphConfiguration) and get the
  graph pass.

``--fail-on`` picks the exit-code threshold: exit 1 when any finding at
or above that severity survives pragmas, else 0. ``--json`` emits a
machine-readable report on stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

from .findings import Finding, SEVERITY_ORDER, count_by_severity, sort_findings
from .rules import RULES

_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules", "build", "dist"}


def _iter_py_files(root: str):
    if os.path.isfile(root):
        yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames if d not in _SKIP_DIRS and not d.startswith(".")
        )
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def _analyze_json_config(path: str, batch: int, timesteps: int) -> List[Finding]:
    from .graph_checks import check_config

    with open(path, "r", encoding="utf-8") as fh:
        d = json.load(fh)
    return check_config(d, batch=batch, timesteps_probe=timesteps, source=path)


def _list_rules() -> str:
    lines = ["rule    severity  scope  title"]
    for rid in sorted(RULES):
        r = RULES[rid]
        lines.append(f"{rid}   {r.severity:<8}  {r.scope:<5}  {r.title}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.analysis",
        description="dl4jtpu-check: static analysis for model configs (.json) "
                    "and JAX/TPU pitfalls (.py).",
    )
    ap.add_argument("paths", nargs="*", help=".py files, directories, or "
                    "serialized config .json files")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable JSON report on stdout")
    ap.add_argument("--fail-on", default="error",
                    choices=["error", "warning", "info", "never"],
                    help="exit 1 when a finding at/above this severity "
                    "survives (default: error)")
    ap.add_argument("--batch", type=int, default=4,
                    help="batch size for the eval_shape probe (default 4)")
    ap.add_argument("--timesteps", type=int, default=16,
                    help="probe length substituted for variable timesteps")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        print(_list_rules())
        return 0
    if not args.paths:
        ap.error("no paths given (or use --list-rules)")

    findings: List[Finding] = []
    n_files = 0
    for path in args.paths:
        if not os.path.exists(path):
            print(f"error: no such path: {path}", file=sys.stderr)
            return 2
        if path.endswith(".json"):
            n_files += 1
            try:
                findings += _analyze_json_config(path, args.batch, args.timesteps)
            except Exception as e:
                print(f"error: could not analyze config {path}: {e}",
                      file=sys.stderr)
                return 2
        else:
            from .ast_checks import check_file

            for py in _iter_py_files(path):
                n_files += 1
                findings += check_file(py)

    findings = sort_findings(findings)
    counts = count_by_severity(findings)
    if args.as_json:
        print(json.dumps({
            "version": 1,
            "files_analyzed": n_files,
            "counts": counts,
            "findings": [f.to_dict() for f in findings],
        }, indent=2))
    else:
        for f in findings:
            print(f.format_human())
        print(f"{len(findings)} finding(s) ({counts['error']} error, "
              f"{counts['warning']} warning, {counts['info']} info) "
              f"across {n_files} file(s)")

    if args.fail_on == "never":
        return 0
    threshold = SEVERITY_ORDER[args.fail_on]
    worst = max((SEVERITY_ORDER[f.severity] for f in findings), default=-1)
    return 1 if worst >= threshold else 0
