"""CLI: ``python -m deeplearning4j_tpu.analysis [paths...]``.

- ``.py`` files (and directories, walked recursively) get the AST pass —
  or, with ``--concurrency``, the DT4xx runtime-guard tier (thread-entry
  discovery + lock census, env hygiene, telemetry schema aggregated
  across every given path).
- ``.json`` files are parsed as serialized configs (``to_json`` output of
  MultiLayerConfiguration / ComputationGraphConfiguration) and get the
  graph pass — plus the jaxpr-level DT2xx IR pass with ``--ir`` (the config
  is instantiated into its network class and the real train step is traced;
  the per-config ``static_cost`` roofline report lands in the JSON output)
  and the DT5xx numerics pass with ``--numerics`` (dtype-flow + value-range
  abstract interpretation over the same traced step; with ``--ir`` both
  passes share a single trace).

``--fail-on`` picks the exit-code threshold: exit 1 when any finding at
or above that severity survives pragmas, else 0. ``--json`` emits a
machine-readable report on stdout. ``--ignore DT204,DT206`` drops rule ids
from the report (IR findings carry no source line, so this is their
suppression mechanism — the headless twin of the inline pragma).

Findings from all passes are merged, deduplicated and stable-sorted, so
analyzing the same artifact twice (or a fact two passes both discover)
reports once, in a deterministic order.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

from .findings import Finding, SEVERITY_ORDER, count_by_severity, merge_findings
from .rules import RULES

_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules", "build", "dist"}


def _iter_py_files(root: str):
    if os.path.isfile(root):
        yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames if d not in _SKIP_DIRS and not d.startswith(".")
        )
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def _parse_mesh(text: str):
    """``--mesh data=2,fsdp=2,tp=1[,bf16][,zero1]`` -> an ABSTRACT
    MeshLayout (no devices needed — the sharding-flow pass is pure spec
    algebra, so a 64-chip layout analyzes fine from a laptop)."""
    from ..parallel.layout import MeshLayout

    sizes = {"data": 1, "fsdp": 1, "tp": 1}
    params_dtype = None
    zero_stage = 3
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        if part in ("bf16", "bfloat16"):
            params_dtype = "bfloat16"
        elif part in ("zero1", "zero-1"):
            zero_stage = 1
        elif "=" in part:
            k, v = part.split("=", 1)
            if k.strip() not in sizes:
                raise ValueError(f"unknown mesh axis {k.strip()!r} "
                                 "(data/fsdp/tp)")
            sizes[k.strip()] = int(v)
        else:
            raise ValueError(f"cannot parse mesh part {part!r}")
    return MeshLayout.abstract(params_dtype=params_dtype,
                               zero_stage=zero_stage, **sizes)


def _analyze_json_config(path: str, batch: int, timesteps: int,
                         ir: bool, costs: list, layout=None,
                         numerics: bool = False) -> List[Finding]:
    from .graph_checks import check_config

    with open(path, "r", encoding="utf-8") as fh:
        d = json.load(fh)
    findings = check_config(d, batch=batch, timesteps_probe=timesteps,
                            source=path)
    if ir or numerics:
        from ..nn.conf.computation_graph import ComputationGraphConfiguration
        from ..nn.conf.multi_layer import MultiLayerConfiguration

        conf = (ComputationGraphConfiguration.from_dict(d)
                if "vertices" in d else MultiLayerConfiguration.from_dict(d))
    if ir:
        from .ir_checks import analyze_config_ir

        # --ir --numerics shares one trace: the DT5xx pass rides the same
        # jaxpr walk as DT2xx and lands in the same cost report
        ir_findings, cost = analyze_config_ir(
            conf, batch=batch, timesteps_probe=timesteps, source=path,
            layout=layout, numerics=numerics)
        findings += ir_findings
        costs.append({"source": path, **cost})
    elif numerics:
        from .numerics import analyze_config_numerics

        num_findings, num_summary = analyze_config_numerics(
            conf, batch=batch, timesteps_probe=timesteps, source=path)
        findings += num_findings
        costs.append({"source": path, "numerics": num_summary})
    return findings


def _list_rules() -> str:
    lines = ["rule    severity  scope  title"]
    for rid in sorted(RULES):
        r = RULES[rid]
        lines.append(f"{rid}   {r.severity:<8}  {r.scope:<5}  {r.title}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.analysis",
        description="dl4jtpu-check: static analysis for model configs (.json) "
                    "and JAX/TPU pitfalls (.py); --ir adds the jaxpr-level "
                    "DT2xx pass + static roofline cost model on configs.",
    )
    ap.add_argument("paths", nargs="*", help=".py files, directories, or "
                    "serialized config .json files")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable JSON report on stdout")
    ap.add_argument("--fail-on", default="error",
                    choices=["error", "warning", "info", "never"],
                    help="exit 1 when a finding at/above this severity "
                    "survives (default: error)")
    ap.add_argument("--batch", type=int, default=4,
                    help="batch size for the eval_shape/IR probe (default 4)")
    ap.add_argument("--timesteps", type=int, default=16,
                    help="probe length substituted for variable timesteps")
    ap.add_argument("--ir", action="store_true",
                    help="run the DT2xx jaxpr/IR pass + static cost model on "
                    "each .json config (traces the real train step)")
    ap.add_argument("--mesh", default=None, metavar="SPEC",
                    help="with --ir: run the DT3xx sharding-flow pass under "
                    "an abstract dp x fsdp x tp layout, e.g. "
                    "--mesh data=2,fsdp=4,tp=2,bf16,zero1 — predicts the "
                    "collective census + communication roofline with no "
                    "devices attached")
    ap.add_argument("--numerics", action="store_true",
                    help="run the DT5xx numerics pass (dtype-flow + "
                    "value-range abstract interpretation) on each .json "
                    "config's traced train step; composes with --ir "
                    "sharing a single trace")
    ap.add_argument("--concurrency", action="store_true",
                    help="run the DT4xx runtime-guard tier on .py inputs "
                    "(thread-entry/lock census, env hygiene, telemetry "
                    "schema) instead of the DT1xx JAX-pitfall pass; the "
                    "telemetry schema aggregates across ALL given paths")
    ap.add_argument("--ignore", default="",
                    help="comma-separated rule ids to drop from the report "
                    "(e.g. DT204,DT206 — the suppression mechanism for IR "
                    "findings, which carry no source line for pragmas)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        print(_list_rules())
        return 0
    if not args.paths:
        ap.error("no paths given (or use --list-rules)")
    ignored = {r.strip() for r in args.ignore.split(",") if r.strip()}
    unknown = ignored - set(RULES)
    if unknown:
        print(f"error: --ignore names unknown rule(s): "
              f"{', '.join(sorted(unknown))}", file=sys.stderr)
        return 2
    layout = None
    if args.mesh:
        if not args.ir:
            print("error: --mesh requires --ir (the sharding-flow pass "
                  "runs on the traced step)", file=sys.stderr)
            return 2
        try:
            layout = _parse_mesh(args.mesh)
        except (ValueError, TypeError) as e:
            print(f"error: bad --mesh spec: {e}", file=sys.stderr)
            return 2

    findings: List[Finding] = []
    costs: list = []
    n_files = 0
    schema = None  # one DT406 schema across every --concurrency path
    for path in args.paths:
        if not os.path.exists(path):
            print(f"error: no such path: {path}", file=sys.stderr)
            return 2
        if path.endswith(".json"):
            n_files += 1
            try:
                findings += _analyze_json_config(path, args.batch,
                                                 args.timesteps, args.ir,
                                                 costs, layout=layout,
                                                 numerics=args.numerics)
            except Exception as e:
                print(f"error: could not analyze config {path}: {e}",
                      file=sys.stderr)
                return 2
        elif args.concurrency:
            from .runtime_checks import TelemetrySchema, check_runtime_source

            if schema is None:
                schema = TelemetrySchema()
            for py in _iter_py_files(path):
                n_files += 1
                with open(py, "r", encoding="utf-8") as fh:
                    findings += check_runtime_source(fh.read(), filename=py,
                                                     schema=schema)
        else:
            from .ast_checks import check_file

            for py in _iter_py_files(path):
                n_files += 1
                findings += check_file(py)
    if schema is not None:
        findings += schema.findings()

    findings = merge_findings(f for f in findings
                              if f.rule_id not in ignored)
    counts = count_by_severity(findings)
    if args.as_json:
        report = {
            "version": 1,
            "files_analyzed": n_files,
            "counts": counts,
            "findings": [f.to_dict() for f in findings],
        }
        if args.ir or args.numerics:
            report["static_cost"] = costs
        print(json.dumps(report, indent=2))
    else:
        for f in findings:
            print(f.format_human())
        for cost in costs:
            num = cost.get("numerics")
            if num:
                rules = num.get("rules") or {}
                hits = ", ".join(f"{k}x{v}" for k, v in sorted(rules.items())) \
                    or "clean"
                print(f"{cost['source']}: numerics: {hits} "
                      f"(seeded {num.get('invars_seeded', 0)} invars)")
            rl = cost.get("roofline")
            if rl is None:
                continue
            print(f"{cost['source']}: static_cost: "
                  f"{cost['flops']:,} FLOPs/step, "
                  f"{cost['hbm_bytes']:,} HBM bytes/step, "
                  f"AI {cost['arithmetic_intensity']:.2f} FLOPs/byte, "
                  f"predicted {rl['predicted_step_seconds']:.3g}s/step "
                  f"({rl['bound']}-bound)")
            flow = cost.get("shard_flow")
            if flow:
                rows = ", ".join(
                    f"{r['kind']}[{','.join(r['axes'])}]x{r['count']}"
                    f"={r['bytes']:,}B" for r in flow["census"]) or "none"
                print(f"{cost['source']}: predicted collectives/step: {rows} "
                      f"({flow['comm_bytes_per_step']:,} bytes over ICI)")
        print(f"{len(findings)} finding(s) ({counts['error']} error, "
              f"{counts['warning']} warning, {counts['info']} info) "
              f"across {n_files} file(s)")

    if args.fail_on == "never":
        return 0
    threshold = SEVERITY_ORDER[args.fail_on]
    worst = max((SEVERITY_ORDER[f.severity] for f in findings), default=-1)
    return 1 if worst >= threshold else 0
