"""Pass 4: static sharding-propagation over a traced jaxpr (DT3xx).

PR 8 gave every program ONE sharding source of truth (``parallel.MeshLayout``)
but nothing could predict what GSPMD *does* with those specs: the implicit
all-gathers, producer/consumer reshards and per-scan-step collectives only
show up in the post-SPMD HLO after a compile. This pass abstract-interprets
the jaxpr with the layout's PartitionSpecs as the abstract values — per-eqn
propagation rules calibrated against the measured post-SPMD census of this
container's XLA (tests/test_shard_flow.py holds them to parity):

- elementwise eqns take the per-dim union of their operands' axes; when one
  mesh axis would land on two different dims, the smaller-payload operand is
  gathered (GSPMD's choice for the broadcast bias under fsdp);
- ``dot_general``/``conv``: a contraction dim sharded identically on both
  sides becomes partial sums → a predicted **all-reduce** with the exact
  payload bytes; a contraction dim sharded on ONE side (or fighting a kept
  dim for the axis) gathers that operand first — kept-dim shards win, which
  is what GSPMD picks for both the ZeRO param gather and the tp activation
  gather;
- ``reshape``/``slice``/``concatenate``/``pad`` that split, merge or cut a
  sharded dim force an all-gather (only a merge-major / split-major sharded
  dim survives);
- ``reduce_*`` over a sharded dim is an all-reduce of the result;
- ``scan`` multiplies its body's collectives by the trip count (gathers of
  loop-invariant consts are hoisted and count once); ``while`` counts one
  iteration (per-step semantics, the staged fori path).

Collective payloads are **per-device bytes** (global bytes divided by the
factor of every mesh axis still sharding the tensor) — exactly the shapes
the post-SPMD HLO prints, so the predicted census and the measured census
key identically: ``(kind, mesh axes) -> {count, bytes}``.

Outputs: a predicted collective census, the DT300-DT305 rule family
(implicit activation all-gather / producer-consumer reshard / oversized
non-batch contraction all-reduce / batch axis dropped / per-scan-step
collective / head-aware-tp advisory), and the communication bytes that feed
the ``DL4JTPU_ICI_GBPS`` roofline term. :func:`hlo_collective_census` parses
the measured twin out of a compiled executable's HLO text and
:func:`compare_census` holds the two to byte-level parity — the ground truth
that keeps this pass honest (``BENCH_MODEL=shard`` runs it per variant).

Everything is host-side spec algebra over ``jax.make_jaxpr`` traces: no
compile, no dispatch — cheap enough to run at CompileManager admission.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from .cost_model import _aval_bytes
from .findings import Finding, merge_findings
from .rules import get_rule

__all__ = [
    "analyze_shard_flow",
    "propagate_jaxpr",
    "check_network_shard_flow",
    "hlo_collective_census",
    "compare_census",
    "flow_report",
]

IR_SOURCE = "<shardflow>"

# DT300/DT301 only fire above this payload: tiny gathers (a broadcast bias)
# are GSPMD's normal cost of doing business, not a finding
DT300_FLOOR_BYTES = 1 << 20  # 1 MiB
DT301_FLOOR_BYTES = 1 << 20
# DT302: a single non-batch-axis contraction all-reduce at/above this payload
# is "oversized" (tp activation all-reduces; grad syncs over batch axes are
# DT207's expected territory and exempt)
DT302_FLOOR_BYTES = 8 << 20  # 8 MiB

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}

_HLO_KINDS = {
    "all-reduce": "all_reduce",
    "all-gather": "all_gather",
    "reduce-scatter": "reduce_scatter",
    "all-to-all": "all_to_all",
    "collective-permute": "collective_permute",
}

# jaxpr-level collective primitive -> census kind
_PRIM_KINDS = {
    "psum": "all_reduce", "pmax": "all_reduce", "pmin": "all_reduce",
    "pmean": "all_reduce", "all_gather": "all_gather",
    "reduce_scatter": "reduce_scatter", "psum_scatter": "reduce_scatter",
    "all_to_all": "all_to_all", "ppermute": "collective_permute",
    "pbroadcast": "all_reduce",
}


def _fmt_bytes(n: float) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0:
            return f"{n:.1f} {unit}"
        n /= 1024.0
    return f"{n:.1f} TiB"


# --------------------------------------------------------------- spec algebra
def _norm_spec(pspec, ndim: int) -> Tuple[Tuple[str, ...], ...]:
    """A PartitionSpec (or tuple) as ndim per-dim tuples of axis names."""
    entries = tuple(pspec) if pspec is not None else ()
    out = []
    for d in range(ndim):
        e = entries[d] if d < len(entries) else None
        if e is None:
            out.append(())
        elif isinstance(e, (tuple, list)):
            out.append(tuple(str(a) for a in e))
        else:
            out.append((str(e),))
    return tuple(out)


def _spec_axes(spec) -> frozenset:
    return frozenset(a for dim in spec for a in dim)


class _St:
    """Abstract value of one var: its spec, the gather payload basis
    (``charge`` — global bytes, looked through broadcasts so gathering a
    broadcast bias charges the pre-broadcast vector), two lineage flags
    (``param``: descends from a parameter invar, so its gather is the
    documented ZeRO cost; ``invariant``: loop-invariant inside scan — its
    gather is hoisted and counted once), and ``pending``: mesh axes over
    which the value is an UNREDUCED partial sum. GSPMD keeps partial sums
    lazy through additive accumulation (the per-time-step dW adds into the
    scan carry; ONE all-reduce fires after the loop), so the all-reduce is
    emitted at the first non-linear consumer, not at the contraction."""

    __slots__ = ("spec", "charge", "param", "invariant", "pending", "psrc")

    def __init__(self, spec, charge: int, param: bool = False,
                 invariant: bool = False,
                 pending: frozenset = frozenset(), psrc: str = ""):
        self.spec = spec
        self.charge = int(charge)
        self.param = param
        self.invariant = invariant
        self.pending = frozenset(pending)
        self.psrc = psrc


class _Flow:
    """One propagation run over a closed jaxpr (plus nested sub-jaxprs)."""

    def __init__(self, axis_sizes: Dict[str, int],
                 batch_axes: Sequence[str]):
        self.sizes = {str(k): int(v) for k, v in axis_sizes.items()}
        self.batch_axes = frozenset(str(a) for a in batch_axes)
        self.events: List[dict] = []
        # inside a shard_map body: every mesh axis is manual, GSPMD inserts
        # nothing — only explicit collectives count, and check_rep's
        # pbroadcast bookkeeping compiles to nothing
        self._manual = False
        # shape -> {shard factor: #vars} over every eqn output (activation
        # projection for preflight's per-device estimate)
        self.shape_factors: Dict[Tuple[int, ...], Dict[int, int]] = {}

    # ------------------------------------------------------------- helpers
    def _factor(self, spec, exclude: frozenset = frozenset()) -> int:
        f = 1
        for a in _spec_axes(spec):
            if a not in exclude:
                f *= self.sizes.get(a, 1)
        return max(1, f)

    def _emit(self, kind: str, axes: Iterable[str], payload: int, *,
              cause: str, prim: str, mult: int, scope: str,
              trip: int, record: bool, param: bool = False) -> None:
        if not record or payload <= 0:
            return
        # size-1 mesh axes compile to nothing (XLA elides the trivial
        # replica group) — shard_map's transpose still psums over every
        # axis absent from an in_spec, so a 5-axis mesh with tp=seq=1
        # would otherwise predict phantom all-reduces the measured HLO
        # census can never show
        axes = tuple(sorted({a for a in axes if self.sizes.get(a, 1) > 1}))
        if not axes:
            return
        if kind == "all_reduce" and len(axes) > 1:
            # XLA lowers a multi-axis all-reduce as one stage PER mesh axis
            # (measured HLO shows e.g. data-groups then seq-groups, full
            # payload each) — mirror that so the censuses line up
            for a in axes:
                self.events.append({
                    "kind": kind, "axes": (a,), "bytes": int(payload),
                    "count": int(max(1, mult)), "cause": cause,
                    "prim": prim, "scope": scope, "trip": int(trip),
                    "param": bool(param), "manual": bool(self._manual),
                })
            return
        self.events.append({
            "kind": kind, "axes": axes, "bytes": int(payload),
            "count": int(max(1, mult)), "cause": cause, "prim": prim,
            "scope": scope, "trip": int(trip), "param": bool(param),
            "manual": bool(self._manual),
        })

    def _gather(self, st: _St, dim_axes: Dict[int, set], *, cause: str,
                prim: str, mult: int, scope: str, trip: int,
                record: bool) -> None:
        """Strip ``dim_axes`` from ``st`` (in place — every later consumer
        sees the gathered tensor, modeling GSPMD's reuse of one all-gather)
        and emit the event. Payload = per-device bytes of the gathered
        result: charge / factor of the axes that KEEP sharding it."""
        removed = set()
        new_spec = list(st.spec)
        for d, axes in dim_axes.items():
            keep = tuple(a for a in new_spec[d] if a not in axes)
            removed |= set(new_spec[d]) - set(keep)
            new_spec[d] = keep
        if not removed:
            return
        payload = st.charge // self._factor(tuple(new_spec))
        eff_mult = 1 if (st.invariant and scope == "scan") else mult
        st.spec = tuple(new_spec)
        self._emit("all_gather", removed, payload, cause=cause, prim=prim,
                   mult=eff_mult, scope=scope, trip=trip, record=record,
                   param=st.param)

    def _materialize(self, st: _St, *, mult, scope, trip, record) -> None:
        """Emit the deferred all-reduce of a partial-sum value (in place —
        every later consumer sees it reduced)."""
        if not st.pending:
            return
        payload = st.charge // self._factor(st.spec)
        eff_mult = 1 if (st.invariant and scope == "scan") else mult
        self._emit("all_reduce", st.pending, payload, cause="contraction",
                   prim=st.psrc or "partial_sum", mult=eff_mult, scope=scope,
                   trip=trip, record=record, param=st.param)
        st.pending = frozenset()

    def _note_shape(self, aval, spec) -> None:
        shape = tuple(int(s) for s in getattr(aval, "shape", ()) or ())
        if not shape:
            return
        row = self.shape_factors.setdefault(shape, {})
        f = self._factor(spec)
        row[f] = row.get(f, 0) + 1

    # ------------------------------------------------------------ the walk
    def walk(self, closed, in_states: Sequence[_St], *, mult: int = 1,
             scope: str = "top", trip: int = 1,
             record: bool = True) -> List[_St]:
        from jax import core  # noqa: PLC0415

        jaxpr = closed.jaxpr
        env: Dict[Any, _St] = {}

        def fresh(aval, **kw):
            ndim = len(getattr(aval, "shape", ()) or ())
            return _St(tuple(() for _ in range(ndim)), _aval_bytes(aval), **kw)

        def read(v) -> _St:
            if isinstance(v, core.Literal):
                return fresh(v.aval)
            st = env.get(v)
            if st is None:
                st = fresh(v.aval)
                env[v] = st
            return st

        # copy the caller's states: gathers mutate specs in place (one
        # gather serves every later consumer), and a probe walk (carry
        # fixpoint) must not leak its gathers into the recorded walk
        for v, st in zip(jaxpr.invars, in_states):
            env[v] = _St(st.spec, st.charge, param=st.param,
                         invariant=st.invariant, pending=st.pending,
                         psrc=st.psrc)
        for v in jaxpr.constvars:
            env[v] = fresh(v.aval, invariant=True)

        for eqn in jaxpr.eqns:
            outs = self._eqn(eqn, read, mult=mult, scope=scope, trip=trip,
                             record=record)
            for v, st in zip(eqn.outvars, outs):
                env[v] = st
                if record:
                    self._note_shape(v.aval, st.spec)
        return [read(v) for v in jaxpr.outvars]

    # -------------------------------------------------------- eqn handlers
    def _eqn(self, eqn, read, *, mult, scope, trip, record) -> List[_St]:
        name = eqn.primitive.name
        kw = dict(mult=mult, scope=scope, trip=trip, record=record)
        if name == "dot_general":
            return self._dot(eqn, read, **kw)
        if name == "conv_general_dilated":
            return self._conv(eqn, read, **kw)
        if name in ("reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
                    "reduce_and", "reduce_or", "argmax", "argmin",
                    "reduce_precision") and "axes" in eqn.params:
            return self._reduce(eqn, read, **kw)
        if name == "broadcast_in_dim":
            return self._broadcast(eqn, read)
        if name == "reshape":
            return self._reshape(eqn, read, **kw)
        if name == "transpose":
            return self._transpose(eqn, read)
        if name == "squeeze":
            return self._squeeze(eqn, read)
        if name in ("slice", "dynamic_slice"):
            return self._slice(eqn, read, **kw)
        if name == "split":
            return self._split(eqn, read, **kw)
        if name == "concatenate":
            return self._concat(eqn, read, **kw)
        if name == "pad":
            return self._pad(eqn, read, **kw)
        if name in ("cumsum", "cumprod", "cummax", "cummin", "cumlogsumexp",
                    "sort"):
            return self._axis_op(eqn, read, **kw)
        if name.startswith("reduce_window"):
            return self._reduce_window(eqn, read, **kw)
        if name in ("gather",) or name.startswith("scatter"):
            return self._gather_scatter(eqn, read, **kw)
        if name in _PRIM_KINDS:
            return self._explicit_collective(eqn, read, **kw)
        if name == "scan":
            return self._scan(eqn, read, **kw)
        if name == "while":
            return self._while(eqn, read, **kw)
        if name == "cond":
            return self._cond(eqn, read, **kw)
        if name == "shard_map":
            return self._shard_map(eqn, read, **kw)
        sub = self._wrapped_jaxpr(eqn)
        if sub is not None and len(sub.jaxpr.invars) == len(eqn.invars):
            outs = self.walk(sub, [read(v) for v in eqn.invars], **kw)
            if len(outs) == len(eqn.outvars):
                return outs
            return [self._default_out(eqn, read, i)
                    for i in range(len(eqn.outvars))]
        return [self._meet(eqn, read, i, **kw)
                for i in range(len(eqn.outvars))]

    @staticmethod
    def _wrapped_jaxpr(eqn):
        """The single nested jaxpr of a 1:1 wrapper (pjit / remat /
        custom_jvp / custom_vjp / closed_call), or None."""
        from jax import core  # noqa: PLC0415

        found = None
        for v in eqn.params.values():
            j = None
            if isinstance(v, core.ClosedJaxpr):
                j = v
            elif isinstance(v, core.Jaxpr):
                j = core.ClosedJaxpr(v, ())
            if j is not None:
                if found is not None:
                    return None  # more than one: not a simple wrapper
                found = j
        return found

    def _default_out(self, eqn, read, i) -> _St:
        """Outputs of unknown prims inherit the spec of a same-shaped
        operand (prefer a sharded one), else replicate."""
        out = eqn.outvars[i].aval
        shape = tuple(getattr(out, "shape", ()) or ())
        best = None
        for v in eqn.invars:
            st = read(v)
            if tuple(getattr(v.aval, "shape", ()) or ()) == shape:
                if best is None or (_spec_axes(st.spec)
                                    and not _spec_axes(best.spec)):
                    best = st
        if best is None:
            return _St(tuple(() for _ in shape), _aval_bytes(out))
        return _St(best.spec, _aval_bytes(out), param=best.param,
                   invariant=best.invariant)

    def _meet(self, eqn, read, i, *, mult, scope, trip, record) -> _St:
        """Elementwise meet with numpy broadcasting (dims align from the
        right, size-1 dims are unsharded): per-out-dim union over the
        operands; a mesh axis claimed for two different out dims gathers
        the smaller-charge claimant (the broadcast bias, under fsdp)."""
        out = eqn.outvars[i].aval
        shape = tuple(int(s) for s in getattr(out, "shape", ()) or ())
        aligned: List[Tuple[_St, int]] = []  # (state, out-dim offset)
        for v in eqn.invars:
            vshape = tuple(getattr(v.aval, "shape", ()) or ())
            if len(vshape) > len(shape):
                continue
            off = len(shape) - len(vshape)
            if all(vshape[d] in (1, shape[off + d])
                   for d in range(len(vshape))):
                aligned.append((read(v), off))
        if not aligned:
            return _St(tuple(() for _ in shape), _aval_bytes(out))
        # axis -> out dim -> [(state, local dim)]
        claims: Dict[str, Dict[int, List[Tuple[_St, int]]]] = {}
        for st, off in aligned:
            for d, axes in enumerate(st.spec):
                for a in axes:
                    claims.setdefault(a, {}).setdefault(
                        off + d, []).append((st, d))
        for a, by_dim in claims.items():
            if len(by_dim) <= 1:
                continue
            # keep the dim claimed by the largest payload; gather the rest
            keep_dim = max(by_dim, key=lambda d: max(
                s.charge for s, _ in by_dim[d]))
            for d, sts in by_dim.items():
                if d == keep_dim:
                    continue
                for st, local in sts:
                    self._gather(
                        st, {local: {a}},
                        cause=("param_gather" if st.param else "mismatch"),
                        prim=eqn.primitive.name, mult=mult, scope=scope,
                        trip=trip, record=record)
        # additive ops carry partial sums through (add_any is autodiff's
        # cotangent accumulator — the per-step dW += path); anything else
        # forces the deferred all-reduce first. convert_element_type is NOT
        # in the list: XLA all-reduces in the math dtype BEFORE a narrowing
        # cast (measured: fsdp+bf16 grads all-reduce in f32).
        if eqn.primitive.name in ("add", "sub", "add_any"):
            pend = frozenset().union(*(st.pending for st, _ in aligned))
            psrc = next((st.psrc for st, _ in aligned if st.psrc), "")
        else:
            for st, _ in aligned:
                self._materialize(st, mult=mult, scope=scope, trip=trip,
                                  record=record)
            pend, psrc = frozenset(), ""
        spec = []
        for d in range(len(shape)):
            axes = set()
            for st, off in aligned:
                local = d - off
                if 0 <= local < len(st.spec):
                    axes |= set(st.spec[local])
            spec.append(tuple(sorted(axes)))
        return _St(tuple(spec), _aval_bytes(out),
                   param=all(st.param for st, _ in aligned),
                   invariant=all(st.invariant for st, _ in aligned),
                   pending=pend, psrc=psrc)

    # dot_general: the heart of the pass
    def _dot(self, eqn, read, *, mult, scope, trip, record) -> List[_St]:
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        lhs_v, rhs_v = eqn.invars[0], eqn.invars[1]
        ls, rs = read(lhs_v), read(rhs_v)
        for st in (ls, rs):
            self._materialize(st, mult=mult, scope=scope, trip=trip,
                              record=record)
        out = eqn.outvars[0].aval

        def role(side, d):
            cdims, bdims = (lc, lb) if side == 0 else (rc, rb)
            if d in cdims:
                return "contract"
            if d in bdims:
                return "batch"
            return "kept"

        claims: Dict[str, List[Tuple[int, int, str, _St]]] = {}
        for side, st in ((0, ls), (1, rs)):
            for d, axes in enumerate(st.spec):
                for a in axes:
                    claims.setdefault(a, []).append((side, d, role(side, d),
                                                     st))
        partial: set = set()
        for a, cl in claims.items():
            roles = {c[2] for c in cl}
            contract_cl = [c for c in cl if c[2] == "contract"]
            if roles == {"contract"} and len({c[0] for c in cl}) == 2:
                partial.add(a)  # sharded contraction on BOTH sides
                continue
            if roles == {"batch"}:
                continue  # batch-dim sharding flows to the result
            if roles == {"contract"} and len({c[0] for c in cl}) == 1:
                # one-sided sharded contraction where the OTHER operand (and
                # hence the result) never touches the axis: GSPMD slices the
                # unsharded side locally (free) and keeps the result an
                # unreduced partial sum — the row-parallel Megatron pattern
                # (attention_out / lstm_gates W / ffn_down role specs). No
                # gather is emitted; ONE all-reduce fires at the first
                # non-linear consumer. ZeRO layouts never take this route:
                # fsdp also shards the activation batch dim, so the fsdp
                # axis carries mixed roles and falls through to the gather.
                partial.add(a)
                continue
            if contract_cl:
                # one-sided contraction shard (or contraction fighting a
                # kept dim for the axis): gather the contraction side —
                # kept-dim shards win, matching GSPMD (ZeRO param gather,
                # tp activation gather)
                for side, d, _, st in contract_cl:
                    self._gather(
                        st, {d: {a}},
                        cause=("param_gather" if st.param
                               else "activation_gather"),
                        prim="dot_general", mult=mult, scope=scope,
                        trip=trip, record=record)
                continue
            if len(cl) > 1:
                # the axis claims kept dims on both sides: keep the bigger;
                # on a tie keep the RHS claim — in autodiff's dW dots the
                # cotangent is the lhs and GSPMD gathers it ONCE (every
                # consumer reuses the gather and dW comes out in the
                # param's orientation, so the optimizer adds stay local)
                keep = max(cl, key=lambda c: (c[3].charge, c[0]))
                for side, d, _, st in cl:
                    if (side, d) == (keep[0], keep[1]):
                        continue
                    self._gather(
                        st, {d: {a}},
                        cause=("param_gather" if st.param else "mismatch"),
                        prim="dot_general", mult=mult, scope=scope,
                        trip=trip, record=record)

        # result spec: [batch dims..., lhs kept..., rhs kept...]
        lkept = [d for d in range(len(ls.spec)) if d not in lc and d not in lb]
        rkept = [d for d in range(len(rs.spec)) if d not in rc and d not in rb]
        entries: List[Tuple[str, ...]] = []
        for bl, br in zip(lb, rb):
            entries.append(tuple(sorted(set(ls.spec[bl]) | set(rs.spec[br]))))
        entries += [ls.spec[d] for d in lkept]
        entries += [rs.spec[d] for d in rkept]
        spec = tuple(entries)
        # a sharded contraction leaves the result an UNREDUCED partial sum:
        # the all-reduce stays lazy through additive accumulation and fires
        # at the first non-linear consumer (GSPMD keeps the per-step dW
        # partial through the backward scan and reduces once after it)
        return [_St(spec, _aval_bytes(out), pending=frozenset(partial),
                    psrc="dot_general")]

    def _conv(self, eqn, read, *, mult, scope, trip, record) -> List[_St]:
        dn = eqn.params["dimension_numbers"]
        ls, rs = read(eqn.invars[0]), read(eqn.invars[1])
        for st in (ls, rs):
            self._materialize(st, mult=mult, scope=scope, trip=trip,
                              record=record)
        out = eqn.outvars[0].aval
        # sharded lhs spatial dims need halo exchange — model as a gather
        spatial = set(dn.lhs_spec[2:])
        strip = {d: set(ls.spec[d]) for d in spatial if ls.spec[d]}
        if strip:
            self._gather(ls, strip, cause="activation_gather", prim="conv",
                         mult=mult, scope=scope, trip=trip, record=record)
        partial = set(ls.spec[dn.lhs_spec[1]]) & set(rs.spec[dn.rhs_spec[1]])
        one_sided = ((set(ls.spec[dn.lhs_spec[1]])
                      | set(rs.spec[dn.rhs_spec[1]])) - partial)
        for st, d in ((ls, dn.lhs_spec[1]), (rs, dn.rhs_spec[1])):
            axes = set(st.spec[d]) & one_sided
            if axes:
                self._gather(st, {d: axes},
                             cause=("param_gather" if st.param
                                    else "activation_gather"),
                             prim="conv", mult=mult, scope=scope, trip=trip,
                             record=record)
        entries = [()] * len(getattr(out, "shape", ()))
        entries[dn.out_spec[0]] = ls.spec[dn.lhs_spec[0]]
        entries[dn.out_spec[1]] = rs.spec[dn.rhs_spec[0]]
        # one axis cannot shard two result dims: the kernel's claim loses
        batch_axes_here = set(entries[dn.out_spec[0]])
        dup = batch_axes_here & set(entries[dn.out_spec[1]])
        if dup:
            self._gather(rs, {dn.rhs_spec[0]: dup},
                         cause=("param_gather" if rs.param else "mismatch"),
                         prim="conv", mult=mult, scope=scope, trip=trip,
                         record=record)
            entries[dn.out_spec[1]] = rs.spec[dn.rhs_spec[0]]
        spec = tuple(entries)
        return [_St(spec, _aval_bytes(out), pending=frozenset(partial),
                    psrc="conv")]

    def _reduce(self, eqn, read, *, mult, scope, trip, record) -> List[_St]:
        st = read(eqn.invars[0])
        name = eqn.primitive.name
        axes = tuple(eqn.params["axes"])
        reduced = {a for d in axes for a in st.spec[d]}
        spec = tuple(e for d, e in enumerate(st.spec) if d not in axes)
        if name == "reduce_sum":
            # additive: the cross-device reduce joins the pending partial
            # sums and stays lazy (the bias grad / loss mean pattern)
            pend = st.pending | frozenset(reduced)
            return [_St(spec, _aval_bytes(ov.aval), param=st.param,
                        invariant=st.invariant, pending=pend,
                        psrc=st.psrc or name) for ov in eqn.outvars]
        # max/min/prod/arg reductions are not additive: materialize the
        # operand, then the cross-device reduce fires eagerly
        self._materialize(st, mult=mult, scope=scope, trip=trip,
                          record=record)
        outs = [_St(spec, _aval_bytes(ov.aval), param=st.param,
                    invariant=st.invariant) for ov in eqn.outvars]
        if reduced:
            payload = outs[0].charge // self._factor(spec)
            self._emit("all_reduce", reduced, payload, cause="reduce",
                       prim=name, mult=mult, scope=scope,
                       trip=trip, record=record)
        return outs

    def _broadcast(self, eqn, read) -> List[_St]:
        st = read(eqn.invars[0])
        out = eqn.outvars[0].aval
        in_shape = tuple(eqn.invars[0].aval.shape)
        bdims = tuple(eqn.params["broadcast_dimensions"])
        entries = [()] * len(out.shape)
        for i, bd in enumerate(bdims):
            if in_shape[i] == out.shape[bd]:
                entries[bd] = st.spec[i]
        # charge looks through the broadcast: gathering the broadcast bias
        # costs the pre-broadcast vector (GSPMD hoists the gather above it)
        return [_St(tuple(entries), st.charge, param=st.param,
                    invariant=st.invariant, pending=st.pending,
                    psrc=st.psrc)]

    def _transpose(self, eqn, read) -> List[_St]:
        st = read(eqn.invars[0])
        perm = tuple(eqn.params["permutation"])
        return [_St(tuple(st.spec[p] for p in perm),
                    _aval_bytes(eqn.outvars[0].aval), param=st.param,
                    invariant=st.invariant, pending=st.pending,
                    psrc=st.psrc)]

    def _squeeze(self, eqn, read) -> List[_St]:
        st = read(eqn.invars[0])
        dims = set(eqn.params["dimensions"])
        spec = tuple(e for d, e in enumerate(st.spec) if d not in dims)
        return [_St(spec, _aval_bytes(eqn.outvars[0].aval), param=st.param,
                    invariant=st.invariant, pending=st.pending,
                    psrc=st.psrc)]

    def _reshape(self, eqn, read, *, mult, scope, trip, record) -> List[_St]:
        st = read(eqn.invars[0])
        in_shape = tuple(int(s) for s in eqn.invars[0].aval.shape)
        out_shape = tuple(int(s) for s in eqn.outvars[0].aval.shape)
        spec, lost = _reshape_spec(in_shape, out_shape, st.spec, self.sizes)
        if lost:
            self._gather(st, {d: set(a) for d, a in lost.items()},
                         cause=("param_gather" if st.param else "reshape"),
                         prim="reshape", mult=mult, scope=scope, trip=trip,
                         record=record)
            spec, _ = _reshape_spec(in_shape, out_shape, st.spec, self.sizes)
        return [_St(spec, _aval_bytes(eqn.outvars[0].aval), param=st.param,
                    invariant=st.invariant, pending=st.pending,
                    psrc=st.psrc)]

    def _slice(self, eqn, read, *, mult, scope, trip, record) -> List[_St]:
        st = read(eqn.invars[0])
        in_shape = tuple(int(s) for s in eqn.invars[0].aval.shape)
        out_shape = tuple(int(s) for s in eqn.outvars[0].aval.shape)
        strip = {d: set(st.spec[d]) for d in range(len(in_shape))
                 if st.spec[d] and out_shape[d] != in_shape[d]}
        if strip:
            self._gather(st, strip, cause=("param_gather" if st.param
                                           else "slice"),
                         prim=eqn.primitive.name, mult=mult, scope=scope,
                         trip=trip, record=record)
        return [_St(st.spec, _aval_bytes(eqn.outvars[0].aval),
                    param=st.param, invariant=st.invariant)]

    def _split(self, eqn, read, *, mult, scope, trip, record) -> List[_St]:
        st = read(eqn.invars[0])
        axis = int(eqn.params.get("axis", 0))
        if st.spec[axis]:
            self._gather(st, {axis: set(st.spec[axis])},
                         cause=("param_gather" if st.param else "slice"),
                         prim="split", mult=mult, scope=scope, trip=trip,
                         record=record)
        return [_St(st.spec, _aval_bytes(ov.aval), param=st.param,
                    invariant=st.invariant, pending=st.pending,
                    psrc=st.psrc) for ov in eqn.outvars]

    def _concat(self, eqn, read, *, mult, scope, trip, record) -> List[_St]:
        dim = int(eqn.params["dimension"])
        out = eqn.outvars[0].aval
        states = [read(v) for v in eqn.invars]
        for st in states:
            self._materialize(st, mult=mult, scope=scope, trip=trip,
                              record=record)
        for st in states:
            if dim < len(st.spec) and st.spec[dim]:
                self._gather(st, {dim: set(st.spec[dim])},
                             cause=("param_gather" if st.param else "concat"),
                             prim="concatenate", mult=mult, scope=scope,
                             trip=trip, record=record)
        entries = []
        for d in range(len(out.shape)):
            axes = set()
            for st in states:
                if d < len(st.spec):
                    axes |= set(st.spec[d])
            entries.append(tuple(sorted(axes)) if d != dim else ())
        return [_St(tuple(entries), _aval_bytes(out))]

    def _pad(self, eqn, read, *, mult, scope, trip, record) -> List[_St]:
        st = read(eqn.invars[0])
        cfg = eqn.params["padding_config"]
        strip = {d: set(st.spec[d]) for d, (lo, hi, interior)
                 in enumerate(cfg)
                 if st.spec[d] and (lo or hi or interior)}
        if strip:
            self._gather(st, strip, cause=("param_gather" if st.param
                                           else "pad"),
                         prim="pad", mult=mult, scope=scope, trip=trip,
                         record=record)
        return [_St(st.spec, _aval_bytes(eqn.outvars[0].aval),
                    param=st.param, invariant=st.invariant)]

    def _axis_op(self, eqn, read, *, mult, scope, trip, record) -> List[_St]:
        """cumsum/sort-style ops that couple every element along one dim:
        a sharded op dim must be gathered first."""
        st = read(eqn.invars[0])
        self._materialize(st, mult=mult, scope=scope, trip=trip,
                          record=record)
        d = int(eqn.params.get("axis", eqn.params.get("dimension", 0)))
        if d < len(st.spec) and st.spec[d]:
            self._gather(st, {d: set(st.spec[d])},
                         cause=("param_gather" if st.param else "slice"),
                         prim=eqn.primitive.name, mult=mult, scope=scope,
                         trip=trip, record=record)
        return [_St(st.spec, _aval_bytes(ov.aval), param=st.param,
                    invariant=st.invariant) for ov in eqn.outvars]

    def _reduce_window(self, eqn, read, *, mult, scope, trip,
                       record) -> List[_St]:
        """Pooling: dims with window 1 keep their sharding; a sharded
        windowed (spatial) dim needs halo exchange — model as a gather."""
        st = read(eqn.invars[0])
        self._materialize(st, mult=mult, scope=scope, trip=trip,
                          record=record)
        window = tuple(eqn.params.get("window_dimensions",
                                      (1,) * len(st.spec)))
        strip = {d: set(st.spec[d]) for d in range(len(st.spec))
                 if st.spec[d] and d < len(window) and window[d] != 1}
        if strip:
            self._gather(st, strip, cause="activation_gather",
                         prim=eqn.primitive.name, mult=mult, scope=scope,
                         trip=trip, record=record)
        out = eqn.outvars[0].aval
        spec = tuple(st.spec[d] if d < len(st.spec) else ()
                     for d in range(len(out.shape)))
        return [_St(spec, _aval_bytes(out), param=st.param,
                    invariant=st.invariant)]

    def _gather_scatter(self, eqn, read, *, mult, scope, trip,
                        record) -> List[_St]:
        """Dynamic indexing into a sharded operand: model as a full gather
        of the operand (upper bound — GSPMD sometimes does better)."""
        st = read(eqn.invars[0])
        self._materialize(st, mult=mult, scope=scope, trip=trip,
                          record=record)
        if _spec_axes(st.spec):
            self._gather(st, {d: set(st.spec[d])
                              for d in range(len(st.spec)) if st.spec[d]},
                         cause=("param_gather" if st.param else "gather_op"),
                         prim=eqn.primitive.name, mult=mult, scope=scope,
                         trip=trip, record=record)
        return [self._default_out(eqn, read, i)
                for i in range(len(eqn.outvars))]

    def _explicit_collective(self, eqn, read, *, mult, scope, trip,
                             record) -> List[_St]:
        if eqn.primitive.name == "pbroadcast" and self._manual:
            # shard_map check_rep replication bookkeeping — compiles to
            # nothing, never a wire transfer
            return [self._default_out(eqn, read, i)
                    for i in range(len(eqn.outvars))]
        for v in eqn.invars:
            self._materialize(read(v), mult=mult, scope=scope, trip=trip,
                              record=record)
        axes = eqn.params.get("axes") or eqn.params.get("axis_name") or ()
        if not isinstance(axes, (tuple, list)):
            axes = (axes,)
        payload = sum(_aval_bytes(v.aval) for v in eqn.invars)
        self._emit(_PRIM_KINDS[eqn.primitive.name],
                   [str(a) for a in axes], payload, cause="explicit",
                   prim=eqn.primitive.name, mult=mult, scope=scope,
                   trip=trip, record=record)
        return [self._default_out(eqn, read, i)
                for i in range(len(eqn.outvars))]

    def _shard_map(self, eqn, read, *, mult, scope, trip,
                   record) -> List[_St]:
        """Manual region (the ring / all-to-all attention kernels ride
        shard_map). Every mesh axis is manual inside, so GSPMD inserts NO
        collectives in the body — the walk models only the explicit ones
        (ppermute, psum, ...), whose payloads are the body's per-shard aval
        bytes: the same per-device convention the measured census counts.
        At the boundary, an outer sharding axis that ``in_names`` does not
        carry on that dim forces an all-gather (manual axes absent from the
        spec require replicated inputs); outputs take their specs straight
        from ``out_names``."""
        from jax import core  # noqa: PLC0415

        body = eqn.params.get("jaxpr")
        if isinstance(body, core.Jaxpr):
            body = core.ClosedJaxpr(body, ())
        in_names = eqn.params.get("in_names")
        out_names = eqn.params.get("out_names")
        kw = dict(mult=mult, scope=scope, trip=trip, record=record)
        if (not isinstance(body, core.ClosedJaxpr) or in_names is None
                or out_names is None
                or len(body.jaxpr.invars) != len(eqn.invars)
                or len(body.jaxpr.outvars) != len(eqn.outvars)):
            return [self._meet(eqn, read, i, **kw)
                    for i in range(len(eqn.outvars))]

        def names_spec(names, ndim):
            return tuple(tuple(str(a) for a in names.get(d, ()))
                         for d in range(ndim))

        inner_in = []
        for v, iv, names in zip(eqn.invars, body.jaxpr.invars, in_names):
            st = read(v)
            self._materialize(st, **kw)
            want = names_spec(dict(names), len(st.spec))
            need = {d: set(st.spec[d]) - set(want[d])
                    for d in range(len(st.spec))
                    if set(st.spec[d]) - set(want[d])}
            if need:
                self._gather(st, need,
                             cause=("param_gather" if st.param
                                    else "mismatch"),
                             prim="shard_map", **kw)
            ishape = tuple(getattr(iv.aval, "shape", ()) or ())
            inner_in.append(_St(tuple(() for _ in ishape),
                                _aval_bytes(iv.aval)))
        prev_manual = self._manual
        self._manual = True
        try:
            self.walk(body, inner_in, **kw)
        finally:
            self._manual = prev_manual
        outs = []
        for ov, names in zip(eqn.outvars, out_names):
            oshape = tuple(getattr(ov.aval, "shape", ()) or ())
            spec = names_spec(dict(names), len(oshape))
            outs.append(_St(spec, _aval_bytes(ov.aval)))
        return outs

    # ------------------------------------------------------- control flow
    def _carry_fixpoint(self, probe, carry: List[_St]) -> List[_St]:
        """Stable carry specs for a loop body: iterate carry-in <- body-out
        (GSPMD may shard a replicated init to match the body) up to 3
        rounds; on oscillation fall back to the in/out intersection."""
        for _ in range(3):
            outs = probe(carry)
            changed = False
            nxt = []
            for st, out in zip(carry, outs):
                spec = out.spec if len(out.spec) == len(st.spec) else st.spec
                if spec != st.spec:
                    changed = True
                nxt.append(_St(spec, st.charge, param=st.param))
            carry = nxt
            if not changed:
                return carry
        outs = probe(carry)
        return [
            _St(tuple(tuple(a for a in st.spec[d]
                            if d < len(out.spec) and a in set(out.spec[d]))
                      for d in range(len(st.spec))),
                st.charge, param=st.param)
            for st, out in zip(carry, outs)]

    def _scan(self, eqn, read, *, mult, scope, trip, record) -> List[_St]:
        from jax import core  # noqa: PLC0415

        body = eqn.params["jaxpr"]
        if isinstance(body, core.Jaxpr):
            body = core.ClosedJaxpr(body, ())
        n_consts = int(eqn.params.get("num_consts", 0))
        n_carry = int(eqn.params.get("num_carry", 0))
        length = int(eqn.params.get("length", 1))
        in_states = [read(v) for v in eqn.invars]
        for st in in_states:
            self._materialize(st, mult=mult, scope=scope, trip=trip,
                              record=record)
        consts = []
        for st in in_states[:n_consts]:
            consts.append(_St(st.spec, st.charge, param=st.param,
                              invariant=True))
        carry = [_St(st.spec, st.charge, param=st.param)
                 for st in in_states[n_consts:n_consts + n_carry]]
        xs = []
        for st, v in zip(in_states[n_consts + n_carry:],
                         eqn.invars[n_consts + n_carry:]):
            # the body sees per-step slices: drop the leading scan dim
            # (a sharded scan dim would be gathered; unsupported layout)
            xs.append(_St(tuple(st.spec[1:]),
                          st.charge // max(1, int(v.aval.shape[0])),
                          param=st.param))
        # Carry fixpoint, GSPMD-style: the carry may BECOME sharded when the
        # body produces it sharded (resharding the init is a one-time free
        # slice), so iterate carry-in <- body-out until stable; if it
        # oscillates, settle on the intersection (axes that survive the
        # loop) — that direction only under-shards, never invents sharding.
        carry = self._carry_fixpoint(
            lambda c: self.walk(body, consts + c + xs, mult=mult * length,
                                scope="scan", trip=length,
                                record=False)[:len(carry)], carry)
        outs = self.walk(body, consts + carry + xs, mult=mult * length,
                         scope="scan", trip=length, record=record)
        result = []
        for i, ov in enumerate(eqn.outvars):
            st = outs[i] if i < len(outs) else None
            if st is None:
                result.append(_St(tuple(() for _ in ov.aval.shape),
                                  _aval_bytes(ov.aval)))
            elif i < n_carry:
                # the carry leaves the loop still pending: the accumulated
                # partial dW all-reduces ONCE, outside the scan
                result.append(_St(st.spec, _aval_bytes(ov.aval),
                                  pending=st.pending, psrc=st.psrc))
            else:  # stacked ys gain a leading unsharded time dim
                result.append(_St(((),) + tuple(st.spec),
                                  _aval_bytes(ov.aval), pending=st.pending,
                                  psrc=st.psrc))
        return result

    def _while(self, eqn, read, *, mult, scope, trip, record) -> List[_St]:
        from jax import core  # noqa: PLC0415

        def closed(j):
            return (core.ClosedJaxpr(j, ()) if isinstance(j, core.Jaxpr)
                    else j)

        cn = int(eqn.params.get("cond_nconsts", 0))
        bn = int(eqn.params.get("body_nconsts", 0))
        cond = closed(eqn.params["cond_jaxpr"])
        body = closed(eqn.params["body_jaxpr"])
        in_states = [read(v) for v in eqn.invars]
        for st in in_states:
            self._materialize(st, mult=mult, scope=scope, trip=trip,
                              record=record)
        cc = in_states[:cn]
        bc = in_states[cn:cn + bn]
        carry = [_St(st.spec, st.charge, param=st.param)
                 for st in in_states[cn + bn:]]
        carry = self._carry_fixpoint(
            lambda c: self.walk(body, bc + c, mult=mult, scope="while",
                                trip=1, record=False)[:len(carry)], carry)
        self.walk(cond, cc + carry, mult=mult, scope="while", trip=1,
                  record=record)
        outs = self.walk(body, bc + carry, mult=mult, scope="while", trip=1,
                         record=record)
        return [_St(st.spec, _aval_bytes(ov.aval), pending=st.pending,
                    psrc=st.psrc)
                for st, ov in zip(outs, eqn.outvars)]

    def _cond(self, eqn, read, *, mult, scope, trip, record) -> List[_St]:
        from jax import core  # noqa: PLC0415

        branches = [core.ClosedJaxpr(b, ()) if isinstance(b, core.Jaxpr)
                    else b for b in eqn.params["branches"]]
        ops = [read(v) for v in eqn.invars[1:]]
        for st in ops:
            self._materialize(st, mult=mult, scope=scope, trip=trip,
                              record=record)
        best_events: Optional[List[dict]] = None
        best_outs: Optional[List[_St]] = None
        best_bytes = -1
        for br in branches:
            mark = len(self.events)
            outs = self.walk(br, [(_St(s.spec, s.charge, param=s.param))
                                  for s in ops],
                             mult=mult, scope=scope, trip=trip,
                             record=record)
            ev = self.events[mark:]
            del self.events[mark:]
            total = sum(e["bytes"] * e["count"] for e in ev)
            if total > best_bytes:
                best_bytes, best_events, best_outs = total, ev, outs
        if record and best_events:
            self.events.extend(best_events)
        outs = best_outs or []
        return [(outs[i] if i < len(outs)
                 else _St(tuple(() for _ in ov.aval.shape),
                          _aval_bytes(ov.aval)))
                for i, ov in enumerate(eqn.outvars)]


def _reshape_spec(in_shape, out_shape, spec, sizes):
    """Map a sharding spec through a reshape. Returns ``(out_spec, lost)``
    where ``lost`` maps input dims to axes that cannot survive (a sharded
    dim merged as a minor factor, or split such that the shard factor does
    not divide the major output factor) — GSPMD keeps only a MAJOR-most
    sharded factor whose shard count divides the major output dim."""
    groups = _reshape_groups(in_shape, out_shape)
    out_entries = [()] * len(out_shape)
    lost: Dict[int, set] = {}
    for in_dims, out_dims in groups:
        sharded = [(d, spec[d]) for d in in_dims if d < len(spec) and spec[d]]
        if not sharded:
            continue
        if len(in_dims) == 1 and len(out_dims) == 1:
            out_entries[out_dims[0]] = spec[in_dims[0]]
            continue
        d0 = in_dims[0]
        for d, axes in sharded:
            factor = 1
            for a in axes:
                factor *= sizes.get(a, 1)
            if (d == d0 and out_dims and out_shape[out_dims[0]] % factor == 0):
                out_entries[out_dims[0]] = axes
            else:
                lost.setdefault(d, set()).update(axes)
    return tuple(out_entries), lost


def _reshape_groups(in_shape, out_shape):
    """Partition the dims of a reshape into minimal groups with equal
    element products (the standard factor-matching walk)."""
    groups = []
    i = j = 0
    while i < len(in_shape) or j < len(out_shape):
        gi, gj = [i], [j]
        pi = in_shape[i] if i < len(in_shape) else 1
        pj = out_shape[j] if j < len(out_shape) else 1
        while pi != pj:
            if pi < pj and gi[-1] + 1 < len(in_shape):
                gi.append(gi[-1] + 1)
                pi *= in_shape[gi[-1]]
            elif pj < pi and gj[-1] + 1 < len(out_shape):
                gj.append(gj[-1] + 1)
                pj *= out_shape[gj[-1]]
            else:
                break
        groups.append((
            [d for d in gi if d < len(in_shape)],
            [d for d in gj if d < len(out_shape)]))
        i = gi[-1] + 1
        j = gj[-1] + 1
    return groups


# ------------------------------------------------------------- entry points
def propagate_jaxpr(closed_jaxpr, in_specs, layout, *,
                    declared_out_specs: Optional[Sequence] = None,
                    param_flags: Optional[Sequence[bool]] = None) -> _Flow:
    """Run the propagation over ``closed_jaxpr``.

    ``in_specs``: one PartitionSpec (or None) per flat invar.
    ``param_flags``: True for invars that are parameters/optimizer moments
    (their gathers are the documented ZeRO cost, not DT300 material).
    ``declared_out_specs``: specs the leading outvars are REQUIRED to have
    (the declared param/opt placements); a propagated spec that gained
    extra axes predicts the output-boundary all-gather (ZeRO-1's per-step
    param gather).
    """
    sizes = dict(layout.axis_sizes)
    flow = _Flow(sizes, layout.batch_axes)
    invars = closed_jaxpr.jaxpr.invars
    states = []
    for i, v in enumerate(invars):
        ndim = len(getattr(v.aval, "shape", ()) or ())
        spec = _norm_spec(in_specs[i] if i < len(in_specs) else None, ndim)
        # drop axes the layout does not know (defensive) and axes of size 1
        spec = tuple(tuple(a for a in dim if sizes.get(a, 1) > 1)
                     for dim in spec)
        states.append(_St(
            spec, _aval_bytes(v.aval),
            param=(bool(param_flags[i])
                   if param_flags and i < len(param_flags) else False)))
    outs = flow.walk(closed_jaxpr, states, record=True)
    # outputs must be materialized: a partial-sum result crossing the
    # program boundary pays its deferred all-reduce (the loss mean, a grad
    # returned raw)
    for st in outs:
        flow._materialize(st, mult=1, scope="top", trip=1, record=True)
    if declared_out_specs:
        for i, decl in enumerate(declared_out_specs):
            if decl is None or i >= len(outs):
                continue
            ov = closed_jaxpr.jaxpr.outvars[i]
            ndim = len(getattr(ov.aval, "shape", ()) or ())
            want = _spec_axes(tuple(
                tuple(a for a in dim if sizes.get(a, 1) > 1)
                for dim in _norm_spec(decl, ndim)))
            have = _spec_axes(outs[i].spec)
            extra = have - want
            if extra:
                payload = _aval_bytes(ov.aval) // flow._factor(
                    tuple((tuple(want),)) if want else ((),))
                flow._emit("all_gather", extra, payload, cause="output",
                           prim="output", mult=1, scope="top", trip=1,
                           record=True, param=True)
    return flow


def _census_rows(events: List[dict]) -> List[dict]:
    agg: Dict[Tuple[str, Tuple[str, ...]], dict] = {}
    for e in events:
        key = (e["kind"], e["axes"])
        row = agg.setdefault(key, {"kind": e["kind"],
                                   "axes": list(e["axes"]),
                                   "count": 0, "bytes": 0})
        row["count"] += e["count"]
        row["bytes"] += e["bytes"] * e["count"]
    return sorted(agg.values(), key=lambda r: (-r["bytes"], r["kind"]))


def flow_report(flow: _Flow) -> dict:
    """JSON-ready summary of one propagation run: the predicted census
    (per-device payload bytes, keyed like the measured HLO census), the
    communication total feeding the ICI roofline term, and the per-shape
    shard factors preflight's activation projection uses."""
    census = _census_rows(flow.events)
    factors = []
    for shape, counts in sorted(flow.shape_factors.items()):
        f = max(counts, key=lambda k: (counts[k], k))
        factors.append({"shape": list(shape), "factor": int(f)})
    return {
        "census": census,
        "comm_bytes_per_step": int(sum(r["bytes"] for r in census)),
        "events": len(flow.events),
        "activation_factors": factors,
    }


def shard_findings(flow: _Flow, *, source: str = IR_SOURCE,
                   dt300_floor: int = DT300_FLOOR_BYTES,
                   dt301_floor: int = DT301_FLOOR_BYTES,
                   dt302_floor: int = DT302_FLOOR_BYTES,
                   pipeline_microbatches: Optional[int] = None,
                   pipe_axis: str = "pipe") -> List[Finding]:
    """DT300-DT304 over the recorded events (DT305 needs layer knowledge
    and is emitted by :func:`check_network_shard_flow`); DT306 — the piped
    twin of DT304 — when ``pipeline_microbatches`` is given: a collective
    inside a pipeline stage body repeating once per micro-batch tick."""
    findings: List[Finding] = []
    batch = flow.batch_axes
    for e in flow.events:
        payload = e["bytes"]
        axes = ", ".join(e["axes"])
        where = f" inside {e['scope']}" if e["scope"] in ("scan",
                                                          "while") else ""
        if e["kind"] == "all_gather" and not e["param"] \
                and e["cause"] not in ("output",) \
                and payload >= dt300_floor:
            findings.append(get_rule("DT300").finding(
                f"{e['prim']}{where} forces a full all-gather of a sharded "
                f"tensor over ({axes}): ~{_fmt_bytes(payload)} "
                f"materialized per step (cause: {e['cause']})",
                file=source, context=e["prim"]))
        if e["cause"] == "mismatch" and not e["param"] \
                and payload >= dt301_floor:
            findings.append(get_rule("DT301").finding(
                f"producer/consumer sharding mismatch at {e['prim']}"
                f"{where}: GSPMD reshards ~{_fmt_bytes(payload)} over "
                f"({axes}) between the two placements",
                file=source, context=e["prim"]))
        if e["kind"] == "all_reduce" and payload >= dt302_floor \
                and not set(e["axes"]) <= batch:
            findings.append(get_rule("DT302").finding(
                f"{e['prim']}{where} contraction over a ({axes})-sharded "
                f"dim all-reduces ~{_fmt_bytes(payload)} of activations "
                "per step — larger than a gradient sync has any right to be",
                file=source, context=e["prim"]))
        if e["kind"] == "all_gather" and not e["param"] \
                and e["cause"] not in ("output",) \
                and set(e["axes"]) & batch:
            findings.append(get_rule("DT303").finding(
                f"{e['prim']}{where} drops the batch axis ({axes}): "
                "downstream compute runs replicated on every device "
                f"(~{_fmt_bytes(payload)} gathered, parallel speedup lost)",
                file=source, context=e["prim"]))
        if e["scope"] == "scan" and e["trip"] > 1 and e["count"] > 1:
            findings.append(get_rule("DT304").finding(
                f"{e['kind']} inside a scan body runs every step: "
                f"{e['count']}x ~{_fmt_bytes(payload)} over ({axes}) per "
                f"optimizer step (trip count {e['trip']})",
                file=source, context=e["prim"]))
    if pipeline_microbatches and pipeline_microbatches > 1:
        # DT306: inside the (manual) pipelined region, the pipe-axis
        # ppermute handoffs and final psum ARE the schedule — but any other
        # collective appearing >= M times is running once per micro-batch
        # tick (e.g. an fsdp param gather traced inside a stage body
        # instead of hoisted before the tick loop)
        per_tick: Dict[Tuple[str, Tuple[str, ...], str], dict] = {}
        for e in flow.events:
            if not e.get("manual"):
                continue
            if pipe_axis in e["axes"]:
                continue
            key = (e["kind"], e["axes"], e["prim"])
            row = per_tick.setdefault(key, {"count": 0, "bytes": 0})
            row["count"] += e["count"]
            row["bytes"] += e["bytes"] * e["count"]
        for (kind, e_axes, prim), row in sorted(per_tick.items()):
            if row["count"] >= pipeline_microbatches:
                findings.append(get_rule("DT306").finding(
                    f"{kind} over ({', '.join(e_axes)}) repeats inside the "
                    f"pipeline stage body: {row['count']}x per step "
                    f"(~{_fmt_bytes(row['bytes'])} total) with "
                    f"{pipeline_microbatches} micro-batches — hoist it "
                    "above the tick loop so it runs once per step, not "
                    "once per micro-batch",
                    file=source, context=prim))
    return merge_findings(findings)


def _flatten_specs(spec_tree) -> List[Any]:
    """Flatten a pytree of PartitionSpecs. P is a tuple subclass, so a
    plain tree_flatten would explode it into its entries — treat every
    PartitionSpec as a leaf."""
    import jax  # noqa: PLC0415
    from jax.sharding import PartitionSpec  # noqa: PLC0415

    return jax.tree_util.tree_flatten(
        spec_tree, is_leaf=lambda x: isinstance(x, PartitionSpec))[0]


def analyze_shard_flow(fn, example_args, in_specs, layout, *,
                       declared_out_specs=None, param_argnums: Sequence[int]
                       = (), pipeline_microbatches: Optional[int] = None,
                       source: str = IR_SOURCE) -> dict:
    """Trace ``fn`` over ``example_args`` (arrays or ShapeDtypeStructs —
    nothing executes) and run the propagation seeded with ``in_specs`` (a
    pytree-of-PartitionSpecs per argument, or flat list). Returns
    ``{"findings": [...], **flow_report}``. ``pipeline_microbatches``
    enables the DT306 per-microbatch-collective advisory for pipelined
    steps."""
    import jax  # noqa: PLC0415

    closed = jax.make_jaxpr(fn)(*example_args)
    flat_specs = _flatten_specs(in_specs)
    flags = []
    for i, a in enumerate(example_args):
        n = len(jax.tree_util.tree_leaves(a))
        flags += [i in set(param_argnums)] * n
    flow = propagate_jaxpr(closed, flat_specs, layout,
                           declared_out_specs=(
                               _flatten_specs(declared_out_specs)
                               if declared_out_specs is not None else None),
                           param_flags=flags)
    report = flow_report(flow)
    report["findings"] = shard_findings(
        flow, source=source, pipeline_microbatches=pipeline_microbatches)
    return report


_HEAD_AWARE_LAYERS = ("LSTM", "Attention")


def check_network_shard_flow(net, batch_or_struct=None, layout=None, *,
                             train: bool = True,
                             timesteps_probe: Optional[int] = None,
                             source: str = IR_SOURCE) -> dict:
    """The shard-flow pass over a net's REAL train step (or forward pass
    with ``train=False``) under ``layout``: params/moments seeded with
    ``param_specs``/``opt_specs``, the batch with ``batch_spec``. Returns
    ``{"findings": [...], "census": [...], "comm_bytes_per_step": ...}``.
    Zero device dispatches — pure ``jax.make_jaxpr`` spec algebra."""
    import jax  # noqa: PLC0415

    from ..telemetry.memory import (  # noqa: PLC0415
        DEFAULT_TIMESTEPS_PROBE, _input_structs)
    from .ir_checks import _label_structs, _shell_tree  # noqa: PLC0415

    if layout is None:
        raise ValueError("check_network_shard_flow needs a MeshLayout")
    t_probe = (DEFAULT_TIMESTEPS_PROBE if timesteps_probe is None
               else int(timesteps_probe))
    net.init()
    if getattr(layout, "roles", False) and hasattr(layout, "bind"):
        layout.bind(net)  # resolve role sites so param_specs are head-aware
    inputs = _input_structs(net, batch_or_struct, timesteps_probe=t_probe)
    conf_dtype = getattr(net.conf, "dtype", "float32")
    params = _shell_tree(net.params, conf_dtype)
    is_graph = hasattr(net.conf, "vertices")
    x_arg = inputs if is_graph else inputs[0]
    from jax.sharding import PartitionSpec as P  # noqa: PLC0415

    param_specs = layout.param_specs(params)
    batch = layout.batch_spec()
    _in_fn = getattr(layout, "input_spec", None)

    def _in_spec(leaf):
        # seq-axis layouts shard [B,T,..] request tensors on time too
        if _in_fn is not None:
            return _in_fn(getattr(leaf, "ndim", None))
        return batch

    # seq-axis layouts execute attention through the shard_map ring
    # kernels (layout.apply installs the mesh) — trace the SAME program
    # here, else the census models a local kernel the net will never run
    _restore = None
    _seq_axis = getattr(layout, "_seq_axis", None)
    if _seq_axis is not None:
        from ..nn.layers.attention import (  # noqa: PLC0415
            get_attention_mesh, set_attention_mesh)
        _prev = get_attention_mesh()
        set_attention_mesh(layout.mesh, _seq_axis, nets=(net,),
                           batch_axes=getattr(layout, "_batch_axes", ()))

        def _restore():
            if _prev is None:
                set_attention_mesh(None, nets=(net,))
            else:
                set_attention_mesh(
                    _prev[0], _prev[1], nets=(net,),
                    batch_axes=_prev[2] if len(_prev) > 2 else ())

    try:
        if train:
            opt_state = _shell_tree(net.opt_state, conf_dtype)
            state = _shell_tree(net.state, conf_dtype)
            rng = jax.ShapeDtypeStruct(tuple(net._rng.shape), net._rng.dtype)
            labels = _label_structs(net, int(inputs[0].shape[0]), t_probe)
            step = net._build_train_step()
            inner = getattr(step, "__wrapped__", step)
            args = (params, opt_state, state, x_arg, labels, rng, None, None)
            opt_specs = (layout.opt_specs(opt_state)
                         if hasattr(layout, "opt_specs")
                         else layout.param_specs(opt_state))
            in_spec_tree = (param_specs, opt_specs,
                            jax.tree_util.tree_map(lambda _: P(), state),
                            jax.tree_util.tree_map(_in_spec, x_arg),
                            jax.tree_util.tree_map(_in_spec, labels),
                            P(), None, None)
            n_param = len(jax.tree_util.tree_leaves(params))
            n_opt = len(jax.tree_util.tree_leaves(opt_state))
            flags = [True] * (n_param + n_opt)
            declared = (_flatten_specs(param_specs)
                        + _flatten_specs(opt_specs))
        else:
            state = _shell_tree(net.state, conf_dtype)
            if is_graph:
                def inner(p, xs):
                    acts, _, _ = net._activations(p, xs, state, False, None,
                                                  None)
                    return acts
            else:
                def inner(p, x):
                    out, _, _ = net._forward(p, x, state, False, None)
                    return out
            args = (params, x_arg)
            in_spec_tree = (param_specs,
                            jax.tree_util.tree_map(_in_spec, x_arg))
            flags = [True] * len(jax.tree_util.tree_leaves(params))
            declared = None

        closed = jax.make_jaxpr(inner)(*args)
    finally:
        if _restore is not None:
            _restore()
    flat_specs = _flatten_specs(in_spec_tree)
    flow = propagate_jaxpr(closed, flat_specs, layout,
                           declared_out_specs=declared, param_flags=flags)
    report = flow_report(flow)
    report["layout"] = layout.describe()
    findings = shard_findings(flow, source=source)

    # DT305: generic tp specs on attention/LSTM-gate sites — the per-step
    # tp collectives on their activations would vanish under head-aware
    # specs (shard heads/gates, not the flat last dim). Advisory. A site
    # that RESOLVED through a head-aware role rule (attention_qkv/
    # attention_out/lstm_gates via MeshLayout(roles=True)) is exempt: its
    # remaining tp traffic is the intended ONE-all-reduce Megatron pattern.
    tp_axis = getattr(layout, "_tp_axis", None)
    if tp_axis is not None:
        conf = net.conf
        if hasattr(conf, "vertices"):
            layer_types = [type(getattr(v, "layer", v)).__name__
                           for v in conf.vertices.values()]
        else:
            layer_types = [type(l).__name__ for l in conf.layers]
        resolved = (layout.role_resolved_types()
                    if getattr(layout, "roles", False)
                    and hasattr(layout, "role_resolved_types") else set())
        sites = sorted({t for t in layer_types
                        if any(k in t for k in _HEAD_AWARE_LAYERS)
                        and t not in resolved})
        tp_events = [e for e in flow.events
                     if tp_axis in e["axes"] and not e["param"]]
        if sites and tp_events:
            total = sum(e["bytes"] * e["count"] for e in tp_events)
            findings.append(get_rule("DT305").finding(
                f"{len(tp_events)} per-step tp collective(s) "
                f"(~{_fmt_bytes(total)}) land on activations of "
                f"{', '.join(sites)}: the generic last-dim tp spec splits "
                "heads/gates across devices — resolve these sites through "
                "the layer-roles registry: MeshLayout(..., roles=True) "
                "reads the layers' PARAM_ROLES declarations, and "
                "parallel.roles.register_layer_role(layer_cls, param, "
                "role) opts custom layers in (docs/distributed.md, 'Layer "
                "roles & head-aware tp')", file=source, context="tp"))
    report["findings"] = merge_findings(findings)
    return report


# ----------------------------------------------------- measured census (HLO)
_HLO_OP_RE = re.compile(
    r"=\s*(?P<result>\([^=]*?\)|[a-z0-9]+\[[0-9,]*\](?:\{[^}]*\})?)\s+"
    r"(?P<op>all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute)(?:-start)?\(")
_SHAPE_RE = re.compile(r"([a-z]+[0-9]+|pred)\[([0-9,]*)\]")
_GROUPS_RE = re.compile(
    r"replica_groups=(\{\{[0-9,{} ]*\}\}|\[[0-9,]+\]<=\[[0-9,]+\]"
    r"(?:T\([0-9,]+\))?)")
_PAIRS_RE = re.compile(r"source_target_pairs=\{((?:\{[0-9, ]+\},?)*)\}")


def _shape_bytes(dtype: str, dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d.strip():
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


def _parse_groups(text: str) -> Optional[frozenset]:
    """replica_groups in either literal ``{{0,1},{2,3}}`` or iota
    ``[2,2]<=[4]`` / ``[2,2]<=[2,2]T(1,0)`` form -> frozenset of
    frozensets of device ids."""
    text = text.strip()
    if text.startswith("{"):
        groups = re.findall(r"\{([0-9, ]+)\}", text)
        return frozenset(frozenset(int(x) for x in g.split(","))
                         for g in groups if g.strip())
    m = re.match(r"\[([0-9,]+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?", text)
    if not m:
        return None
    gshape = [int(x) for x in m.group(1).split(",")]
    ishape = [int(x) for x in m.group(2).split(",")]
    ids = np.arange(int(np.prod(ishape))).reshape(ishape)
    if m.group(3):
        perm = [int(x) for x in m.group(3).split(",")]
        ids = ids.transpose(perm)
    ids = ids.reshape(gshape)
    return frozenset(frozenset(int(x) for x in row) for row in ids)


def _axis_groups(mesh) -> List[Tuple[Tuple[str, ...], frozenset]]:
    """Every non-trivial subset of mesh axes -> its replica-group set."""
    import itertools  # noqa: PLC0415

    names = list(mesh.axis_names)
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    out = []
    live = [n for n in names if mesh.shape[n] > 1]
    for r in range(1, len(live) + 1):
        for sub in itertools.combinations(live, r):
            sub_dims = [names.index(n) for n in sub]
            other = [d for d in range(len(names)) if d not in sub_dims]
            moved = np.transpose(ids, other + sub_dims)
            moved = moved.reshape(-1, int(np.prod(
                [ids.shape[d] for d in sub_dims], dtype=np.int64)))
            groups = frozenset(frozenset(int(x) for x in row)
                               for row in moved)
            out.append((tuple(sub), groups))
    return out


def hlo_collective_census(hlo_text: str, layout=None) -> List[dict]:
    """The MEASURED census: parse a compiled executable's post-SPMD HLO for
    collective ops. Each row: ``{kind, axes, count, bytes}`` — bytes are the
    per-device ``max(operands, results)`` payload (the convention the
    predicted census uses), axes the mesh axes whose replica groups match
    (``["?"]`` when no axis subset of the given layout's mesh matches).
    All-gathers of the same source operands over the same groups/dims are
    one LOGICAL collective counted once — XLA may materialize extra copies
    purely for consumer layouts; ``layout_dups`` on the row records them.
    """
    mesh = getattr(layout, "mesh", None) if layout is not None else None
    axis_groups = _axis_groups(mesh) if mesh is not None else []
    rows: Dict[Tuple[str, Tuple[str, ...]], dict] = {}
    seen_gathers: Dict[tuple, dict] = {}
    for line in hlo_text.splitlines():
        m = _HLO_OP_RE.search(line)
        if not m:
            continue
        kind = _HLO_KINDS[m.group("op")]
        result_bytes = sum(_shape_bytes(d, s)
                           for d, s in _SHAPE_RE.findall(m.group("result")))
        operands = line[m.end():]
        # operand list ends at the first attribute (channel_id=, dimensions=,
        # replica_groups=, to_apply=, metadata=)
        op_text = re.split(r"\b(?:channel_id|dimensions|replica_groups|"
                           r"source_target_pairs|to_apply|metadata)=",
                           operands)[0]
        operand_bytes = sum(_shape_bytes(d, s)
                            for d, s in _SHAPE_RE.findall(op_text))
        payload = max(result_bytes, operand_bytes)
        axes: Tuple[str, ...] = ("?",)
        gm = _GROUPS_RE.search(line)
        if gm:
            groups = _parse_groups(gm.group(1))
            if groups is not None:
                if all(len(g) <= 1 for g in groups):
                    continue  # degenerate single-device groups
                for sub, expected in axis_groups:
                    if groups == expected:
                        axes = sub
                        break
        elif kind == "collective_permute":
            # permutes carry source_target_pairs, not replica_groups:
            # attribute to the smallest axis subset whose groups contain
            # every pair (a seq-ring's hops stay within each seq group)
            pm = _PAIRS_RE.search(line)
            if pm:
                pairs = [tuple(int(x) for x in p.split(","))
                         for p in re.findall(r"\{([0-9, ]+)\}",
                                             pm.group(1))]
                if pairs and all(s != t for s, t in pairs):
                    for sub, expected in axis_groups:
                        if all(any({s, t} <= g for g in expected)
                               for s, t in pairs):
                            axes = sub
                            break
        row = rows.setdefault((kind, axes), {
            "kind": kind, "axes": list(axes), "count": 0, "bytes": 0})
        if kind == "all_gather":
            # XLA materializes the SAME logical gather once per consumer
            # physical layout (CSE stops at layout boundaries — e.g. the
            # saved attention context re-gathered for each backward dot's
            # preferred operand order). One logical collective, several
            # wire copies the static pass cannot see: count it once and
            # record the duplication on the row.
            ops = tuple(re.findall(r"%[\w.\-]+", op_text))
            dm = re.search(r"dimensions=\{([0-9,]*)\}", line)
            key = (axes, ops, dm.group(1) if dm else None)
            if ops and key in seen_gathers:
                dup = seen_gathers[key]
                dup["layout_dups"] = dup.get("layout_dups", 0) + 1
                continue
            seen_gathers[key] = row
        row["count"] += 1
        row["bytes"] += payload
    return sorted(rows.values(), key=lambda r: (-r["bytes"], r["kind"]))


def compare_census(predicted: List[dict], measured: List[dict], *,
                   byte_tolerance: float = 1.5,
                   minor_fraction: float = 0.10) -> dict:
    """Hold the predicted census to the measured one.

    Rules: every kind carrying at least ``minor_fraction`` of the measured
    (or predicted) bytes must appear on the other side with the same mesh
    axes, and both the per-major-kind and total byte sums must agree within
    ``byte_tolerance`` in either direction. Small resharding noise (the
    few-KiB all-to-alls GSPMD sprinkles) stays below the fraction floor.
    """
    def by_kind(rows):
        out: Dict[str, dict] = {}
        for r in rows:
            row = out.setdefault(r["kind"], {"bytes": 0, "count": 0,
                                             "axes": set(), "rows": []})
            row["bytes"] += r["bytes"]
            row["count"] += r["count"]
            row["rows"].append(r)
        for row in out.values():
            # axes come only from rows that are major WITHIN the kind —
            # a 2 KiB resharding gather must not pollute the axes of the
            # 80 KiB param gathers
            for r in row["rows"]:
                if r["bytes"] >= minor_fraction * max(row["bytes"], 1):
                    row["axes"] |= set(r["axes"])
            del row["rows"]
        return out

    p, m = by_kind(predicted), by_kind(measured)
    p_total = sum(r["bytes"] for r in p.values())
    m_total = sum(r["bytes"] for r in m.values())
    problems: List[str] = []
    detail: Dict[str, dict] = {}
    majors = set()
    for kind, row in m.items():
        if row["bytes"] >= minor_fraction * max(m_total, 1):
            majors.add(kind)
    for kind, row in p.items():
        if row["bytes"] >= minor_fraction * max(p_total, 1):
            majors.add(kind)
    for kind in sorted(majors):
        pr, mr = p.get(kind), m.get(kind)
        if pr is None or mr is None:
            problems.append(f"kind {kind} only "
                            f"{'measured' if pr is None else 'predicted'}")
            detail[kind] = {"predicted": pr and pr["bytes"],
                            "measured": mr and mr["bytes"]}
            continue
        ratio = (pr["bytes"] / mr["bytes"]) if mr["bytes"] else float("inf")
        detail[kind] = {"predicted_bytes": pr["bytes"],
                        "measured_bytes": mr["bytes"],
                        "ratio": round(ratio, 4),
                        "predicted_axes": sorted(pr["axes"]),
                        "measured_axes": sorted(mr["axes"])}
        if not (1.0 / byte_tolerance <= ratio <= byte_tolerance):
            problems.append(f"{kind} bytes off {ratio:.2f}x")
        if "?" in mr["axes"]:
            problems.append(f"{kind} measured groups match no mesh axes")
        elif pr["axes"] != mr["axes"]:
            problems.append(
                f"{kind} axes differ: predicted {sorted(pr['axes'])} vs "
                f"measured {sorted(mr['axes'])}")
    total_ratio = (p_total / m_total) if m_total else (
        1.0 if not p_total else float("inf"))
    if m_total or p_total:
        if not (1.0 / byte_tolerance <= total_ratio <= byte_tolerance):
            problems.append(f"total bytes off {total_ratio:.2f}x")
    return {
        "ok": not problems,
        "problems": problems,
        "total_ratio": (round(total_ratio, 4)
                        if m_total or p_total else 1.0),
        "predicted_total_bytes": int(p_total),
        "measured_total_bytes": int(m_total),
        "kinds": detail,
    }
