"""DT5xx numerics pass: dtype-flow + value-range abstract interpretation.

Pass 6 of the analysis stack. The DT2xx tier reads the traced train step
for *structural* problems (f64 promotion, dropped donation); this tier
reads the same jaxpr for *numerical* ones, before a single step runs —
predicting at trace/admission time what the runtime Watchdog can only
observe at step N:

- **Dtype-flow** tracks the effective accumulation precision of every
  value: DT500 (dot/conv/reduce accumulating in bf16/f16 without an f32
  ``preferred_element_type``), DT501 (low-precision scan/while carry
  rewritten across >= ``carry_steps`` iterations — the LSTM/streaming
  drift shape) and DT502 (grads or optimizer moments combined below the
  declared PrecisionPolicy compute dtype at an update site).
- **Value-range** interval abstract interpretation seeds invars from
  declared input ranges / initializer bounds and propagates ``[lo, hi]``
  per eqn: DT503 (exp/log/div/sqrt/rsqrt whose input interval admits
  overflow, log(<=0) or divide-through-zero without a clamp), DT504
  (softmax-shaped exp not dominated by a subtract-max — structural) and
  DT505 (advisory: sub-f32 grad flow with no loss scaling configured).

Soundness polarity: an *unknown* bound is ``+/-inf`` and never fires —
hazard rules need evidence, which either a declared seed range or a
traced clamp/literal provides. ``jnp.clip(x, 0, 1)`` therefore makes a
downstream ``log`` fire (zero is admitted) while ``jnp.clip(x, EPS, 1)``
silences it: the clamp IS the guard the hint asks for. The structural
DT504 check needs no intervals at all, so a naive softmax over unknown
logits is still caught.

The walker rides the same traced ``ClosedJaxpr`` the DT2xx pass already
built (``check_network_ir(numerics=True)`` — one ``make_jaxpr``, two
walks), recurses through scan/while/cond/pjit/custom-wrapper eqns like
``shard_flow``, and runs loop bodies to a small widening fixpoint before
the recording pass so carried intervals are sound across iterations.
Findings carry no source line (they describe traced programs), so
suppression is ``ignore=(...)`` / ``--ignore``, as with DT2xx/DT3xx.
"""

from __future__ import annotations

import math
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .findings import Finding, merge_findings
from .rules import get_rule

NUM_SOURCE = "<numerics>"

# Accumulating >= this many elements in bf16/f16 before DT500 fires on a
# reduce (a handful of terms round once; hundreds stop accumulating).
DT500_MIN_REDUCE = 32
# Carries rewritten across >= this many iterations before DT501 fires.
DT501_MIN_STEPS = 8
# Default declared magnitude bound for network inputs/labels/params when
# the caller does not pass one — wide enough to catch unguarded exp/log,
# finite so the interval domain stays informative.
DEFAULT_INPUT_BOUND = 1e3

_LOW = ("bfloat16", "float16")
_INF = math.inf

# log(finfo(dtype).max): an exp argument above this overflows to inf.
_EXP_MAX = {"float64": 709.78, "float32": 88.72, "bfloat16": 88.5,
            "float16": 11.09}

__all__ = [
    "NUM_SOURCE", "DT500_MIN_REDUCE", "DT501_MIN_STEPS",
    "DEFAULT_INPUT_BOUND", "check_jaxpr_numerics", "network_numerics",
    "check_network_numerics", "analyze_config_numerics",
]


# ------------------------------------------------------------- intervals
def _san(lo: float, hi: float) -> Tuple[float, float]:
    if math.isnan(lo):
        lo = -_INF
    if math.isnan(hi):
        hi = _INF
    return (lo, hi) if lo <= hi else (-_INF, _INF)


def _mulc(a: float, b: float) -> float:
    # corner product with the interval convention 0 * inf = 0
    if a == 0.0 or b == 0.0:
        return 0.0
    return a * b


def _iv_add(x, y):
    return _san(x[0] + y[0], x[1] + y[1])


def _iv_neg(x):
    return (-x[1], -x[0])


def _iv_mul(x, y):
    c = (_mulc(x[0], y[0]), _mulc(x[0], y[1]),
         _mulc(x[1], y[0]), _mulc(x[1], y[1]))
    return _san(min(c), max(c))


def _iv_div(x, y):
    if y[0] > 0.0 or y[1] < 0.0:  # divisor bounded away from zero
        c = []
        for a in x:
            for b in y:
                c.append(a / b if not (math.isinf(a) and math.isinf(b))
                         else 0.0)
        lo, hi = min(c), max(c)
        if math.isinf(x[0]) or math.isinf(x[1]):
            lo, hi = -_INF, _INF
        return _san(lo, hi)
    return (-_INF, _INF)


def _iv_union(x, y):
    return (min(x[0], y[0]), max(x[1], y[1]))


def _iv_max(x, y):
    return (max(x[0], y[0]), max(x[1], y[1]))


def _iv_min(x, y):
    return (min(x[0], y[0]), min(x[1], y[1]))


def _exp_b(v: float) -> float:
    if v >= 700.0:
        return _INF
    if v == -_INF:
        return 0.0
    return math.exp(v)


def _log_b(v: float) -> float:
    if v <= 0.0:
        return -_INF
    if v == _INF:
        return _INF
    return math.log(v)


# -------------------------------------------------------- abstract value
class _Av:
    """Abstract value for one jaxpr var: interval + structural flags.

    ``vid`` is a canonical value identity propagated through
    value-preserving ops (convert/broadcast/reshape/stop_gradient/...),
    so ``sub(x, broadcast(reduce_max(x)))`` is recognizable as a
    subtract-max regardless of the plumbing between.
    """

    __slots__ = ("lo", "hi", "vid", "maxof", "shifted", "is_exp",
                 "sumexp_of", "lineage")

    def __init__(self, lo=-_INF, hi=_INF, vid=None, maxof=frozenset(),
                 shifted=None, is_exp=None, sumexp_of=None,
                 lineage=frozenset()):
        self.lo, self.hi = lo, hi
        self.vid = vid
        self.maxof = maxof          # vids this value is a reduce_max of
        self.shifted = shifted      # vid x when value == x - max(x)
        self.is_exp = is_exp        # None | True (stable) | False
        self.sumexp_of = sumexp_of  # vid of the exp var this sums
        self.lineage = lineage      # subset of {"param", "opt"}

    def iv(self):
        return (self.lo, self.hi)


def _dtype_str(v) -> str:
    try:
        return str(v.aval.dtype)
    except Exception:
        return ""


def _is_float(dt: str) -> bool:
    return dt.startswith("float") or dt in _LOW


def _aval_size(v) -> int:
    try:
        n = 1
        for d in v.aval.shape:
            n *= int(d)
        return n
    except Exception:
        return 1


# value-preserving primitives: interval, identity and flags pass through
_IDENT = {
    "broadcast_in_dim", "reshape", "transpose", "squeeze", "rev", "copy",
    "convert_element_type", "stop_gradient", "reduce_precision",
    "device_put", "expand_dims", "with_sharding_constraint",
    "sharding_constraint", "optimization_barrier",
}
# interval-preserving but identity-erasing (element subset / reorder)
_SUBSET = {"slice", "dynamic_slice", "gather", "sort", "top_k"}
# DT502 update-site arithmetic
_ARITH = {"add", "add_any", "sub", "mul", "div"}

_BOUNDED = {"tanh": (-1.0, 1.0), "logistic": (0.0, 1.0),
            "erf": (-1.0, 1.0), "sin": (-1.0, 1.0), "cos": (-1.0, 1.0),
            "sign": (-1.0, 1.0), "is_finite": (0.0, 1.0)}
_CMP = {"eq", "ne", "lt", "le", "gt", "ge", "and", "or", "not", "xor"}


# eqns whose params hold a 1:1 inner jaxpr (same in/out signature).
# NOT a generic "has a jaxpr param" sniff: the generic `reduce` prim
# carries its scalar combinator as params["jaxpr"] with coincidentally
# matching arity and must be evaluated as a reduction, not inlined.
_WRAPPERS = {
    "pjit", "closed_call", "core_closed_call", "xla_call", "remat",
    "remat2", "checkpoint", "custom_jvp_call", "custom_vjp_call",
    "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr",
}


def _wrapped_closed(eqn):
    """The 1:1-wrapped inner jaxpr of a pjit/remat/custom_*-style eqn."""
    import jax  # noqa: PLC0415

    if eqn.primitive.name not in _WRAPPERS:
        return None
    core = jax.core
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        inner = eqn.params.get(key)
        if inner is None:
            continue
        if isinstance(inner, core.Jaxpr):
            if inner.constvars:
                return None
            inner = core.ClosedJaxpr(inner, ())
        if isinstance(inner, core.ClosedJaxpr) \
                and len(inner.jaxpr.invars) == len(eqn.invars) \
                and len(inner.jaxpr.outvars) == len(eqn.outvars):
            return inner
    return None


class _NumFlow:
    """One combined dtype-flow + value-range walk over a closed jaxpr."""

    def __init__(self, *, compute_dtype=None, params_dtype=None,
                 carry_steps=DT501_MIN_STEPS,
                 reduce_elems=DT500_MIN_REDUCE):
        self.compute_dtype = compute_dtype
        self.params_dtype = params_dtype
        self.carry_steps = int(carry_steps)
        self.reduce_elems = int(reduce_elems)
        self._next_vid = 0
        self.record = True
        self.eqns = 0
        # (rule_id, agg_key) -> [count, first_message]
        self.agg: Dict[Tuple[str, str], list] = {}
        # vid -> (agg_key, message) for unstable exps that may later be
        # reclassified from DT503-overflow to DT504 by a softmax shape
        self.pending_exp: Dict[int, Tuple[str, str]] = {}

    # ------------------------------------------------------------ helpers
    def fresh(self, **kw) -> _Av:
        self._next_vid += 1
        return _Av(vid=self._next_vid, **kw)

    def _hit(self, rule_id: str, key: str, message: str) -> None:
        if not self.record:
            return
        slot = self.agg.setdefault((rule_id, key), [0, message])
        slot[0] += 1

    def _read(self, env, v) -> _Av:
        import jax  # noqa: PLC0415

        if isinstance(v, jax.core.Literal):
            return self._const_av(v.val)
        av = env.get(id(v))
        if av is None:
            av = self.fresh()
            env[id(v)] = av
        return av

    def _const_av(self, val) -> _Av:
        import numpy as np  # noqa: PLC0415

        try:
            arr = np.asarray(val)
            if arr.size and arr.dtype.kind in "fiub" \
                    and arr.size <= 4_000_000:
                return self.fresh(lo=float(arr.min()), hi=float(arr.max()))
        except Exception:
            pass
        return self.fresh()

    # --------------------------------------------------------------- walk
    def walk(self, closed, in_avs: Sequence[_Av]) -> List[_Av]:
        consts = [self._const_av(c) for c in closed.consts]
        return self._jaxpr(closed.jaxpr, consts, list(in_avs))

    def _jaxpr(self, jaxpr, const_avs, in_avs) -> List[_Av]:
        env: Dict[int, _Av] = {}
        for v, av in zip(jaxpr.constvars, const_avs):
            env[id(v)] = av
        for v, av in zip(jaxpr.invars, in_avs):
            env[id(v)] = av
        for eqn in jaxpr.eqns:
            self._eqn(eqn, env)
        return [self._read(env, v) for v in jaxpr.outvars]

    def _eqn(self, eqn, env) -> None:
        name = eqn.primitive.name
        if name == "scan":
            self._scan(eqn, env)
            return
        if name == "while":
            self._while(eqn, env)
            return
        if name == "cond":
            self._cond(eqn, env)
            return
        if name.startswith("pallas_call"):
            # kernel bodies operate on Refs — opaque to this walker; the
            # shipped kernels carry their own >=f32 subtract-max contract
            for v in eqn.outvars:
                env[id(v)] = self.fresh()
            return
        inner = _wrapped_closed(eqn)
        if inner is not None:
            in_avs = [self._read(env, v) for v in eqn.invars]
            outs = self.walk(inner, in_avs)
            for v, av in zip(eqn.outvars, outs):
                env[id(v)] = av
            return
        if self.record:
            self.eqns += 1
        self._prim(eqn, env, name)

    # ----------------------------------------------------- primitive eval
    def _prim(self, eqn, env, name) -> None:
        ins = [self._read(env, v) for v in eqn.invars]
        out_dt = _dtype_str(eqn.outvars[0]) if eqn.outvars else ""
        lineage = frozenset().union(*(a.lineage for a in ins)) \
            if ins else frozenset()
        av = None

        if name in _IDENT and ins:
            a = ins[0]
            av = _Av(lo=a.lo, hi=a.hi, vid=a.vid, maxof=a.maxof,
                     shifted=a.shifted, is_exp=a.is_exp,
                     sumexp_of=a.sumexp_of, lineage=a.lineage)
        elif name in _SUBSET and ins:
            a = ins[0]
            av = self.fresh(lo=a.lo, hi=a.hi, lineage=a.lineage)
        elif name in _CMP:
            av = self.fresh(lo=0.0, hi=1.0, lineage=lineage)
        elif name in _BOUNDED:
            lo, hi = _BOUNDED[name]
            av = self.fresh(lo=lo, hi=hi, lineage=lineage)
        elif name in ("add", "add_any"):
            iv = _iv_add(ins[0].iv(), ins[1].iv())
            av = self.fresh(lo=iv[0], hi=iv[1], lineage=lineage)
        elif name == "sub":
            a, b = ins
            iv = _iv_add(a.iv(), _iv_neg(b.iv()))
            shifted = a.vid if (a.vid is not None and a.vid in b.maxof) \
                else None
            hi = min(iv[1], 0.0) if shifted is not None else iv[1]
            av = self.fresh(lo=iv[0], hi=hi, shifted=shifted,
                            lineage=lineage)
        elif name == "mul":
            a, b = ins
            if a.vid is not None and a.vid == b.vid:  # x*x >= 0
                m = max(abs(a.lo), abs(a.hi))
                av = self.fresh(lo=0.0, hi=_mulc(m, m), lineage=lineage)
            else:
                iv = _iv_mul(a.iv(), b.iv())
                av = self.fresh(lo=iv[0], hi=iv[1], lineage=lineage)
        elif name == "div":
            a, b = ins
            self._div_hazard(a, b, out_dt, eqn)
            iv = _iv_div(a.iv(), b.iv())
            av = self.fresh(lo=iv[0], hi=iv[1], lineage=lineage)
        elif name == "neg":
            iv = _iv_neg(ins[0].iv())
            av = self.fresh(lo=iv[0], hi=iv[1], lineage=lineage)
        elif name == "abs":
            a = ins[0]
            lo = 0.0 if a.lo <= 0.0 <= a.hi else min(abs(a.lo), abs(a.hi))
            av = self.fresh(lo=lo, hi=max(abs(a.lo), abs(a.hi)),
                            lineage=lineage)
        elif name == "max":
            a, b = ins
            # max(x, -inf) == x: jnp.max inserts this wrapper around
            # reduce_max — pass identity/flags through or the stable-
            # softmax maxof chain breaks at it
            ident = a if b.lo == b.hi == -_INF else \
                (b if a.lo == a.hi == -_INF else None)
            if ident is not None:
                av = _Av(lo=ident.lo, hi=ident.hi, vid=ident.vid,
                         maxof=ident.maxof, shifted=ident.shifted,
                         is_exp=ident.is_exp, sumexp_of=ident.sumexp_of,
                         lineage=ident.lineage)
            else:
                iv = _iv_max(a.iv(), b.iv())
                av = self.fresh(lo=iv[0], hi=iv[1], lineage=lineage)
        elif name == "min":
            a, b = ins
            ident = a if b.lo == b.hi == _INF else \
                (b if a.lo == a.hi == _INF else None)
            if ident is not None:
                av = _Av(lo=ident.lo, hi=ident.hi, vid=ident.vid,
                         maxof=ident.maxof, shifted=ident.shifted,
                         is_exp=ident.is_exp, sumexp_of=ident.sumexp_of,
                         lineage=ident.lineage)
            else:
                iv = _iv_min(a.iv(), b.iv())
                av = self.fresh(lo=iv[0], hi=iv[1], lineage=lineage)
        elif name == "clamp":  # clamp(lo_b, x, hi_b) = min(max(x, lo), hi)
            lo_b, x, hi_b = ins
            iv = _iv_min(_iv_max(x.iv(), lo_b.iv()), hi_b.iv())
            av = self.fresh(lo=iv[0], hi=iv[1], lineage=lineage)
        elif name == "exp":
            av = self._exp(ins[0], out_dt, lineage)
        elif name == "expm1":
            base = self._exp(ins[0], out_dt, lineage)
            av = self.fresh(lo=base.lo - 1.0, hi=base.hi - 1.0,
                            is_exp=base.is_exp, lineage=lineage)
            if base.is_exp is False and base.vid in self.pending_exp:
                self.pending_exp[av.vid] = self.pending_exp.pop(base.vid)
        elif name in ("log", "log1p"):
            a = ins[0]
            off = 0.0 if name == "log" else 1.0
            floor = 0.0 if name == "log" else -1.0
            if self.record and a.lo <= floor and a.lo > -_INF:
                self._hit("DT503", f"{name}-domain",
                          f"{name} input interval [{a.lo:.3g}, {a.hi:.3g}] "
                          f"admits {name}(<= {floor:g}) -> -inf/NaN with no "
                          "clamp in between")
            av = self.fresh(lo=_log_b(a.lo + off), hi=_log_b(a.hi + off),
                            lineage=lineage)
        elif name == "sqrt":
            a = ins[0]
            if self.record and a.lo < 0.0 and a.lo > -_INF:
                self._hit("DT503", "sqrt-domain",
                          f"sqrt input interval [{a.lo:.3g}, {a.hi:.3g}] "
                          "admits a negative -> NaN with no clamp in "
                          "between")
            av = self.fresh(lo=math.sqrt(max(a.lo, 0.0)),
                            hi=math.sqrt(a.hi) if a.hi not in (_INF,)
                            else _INF, lineage=lineage)
        elif name == "rsqrt":
            a = ins[0]
            if self.record and a.lo <= 0.0 and a.lo > -_INF:
                self._hit("DT503", "rsqrt-domain",
                          f"rsqrt input interval [{a.lo:.3g}, {a.hi:.3g}] "
                          "admits <= 0 -> inf/NaN with no clamp in between")
            if a.lo > 0.0:
                av = self.fresh(lo=1.0 / math.sqrt(a.hi)
                                if a.hi != _INF else 0.0,
                                hi=1.0 / math.sqrt(a.lo), lineage=lineage)
            else:
                av = self.fresh(lineage=lineage)
        elif name == "integer_pow":
            y = int(eqn.params.get("y", 1))
            a = ins[0]
            if y >= 0 and y % 2 == 0:
                m = max(abs(a.lo), abs(a.hi))
                av = self.fresh(lo=0.0, hi=_mulc(m, m) if y == 2
                                else (m ** y if m != _INF else _INF),
                                lineage=lineage)
            elif y >= 0:
                av = self.fresh(lo=a.lo ** y if a.lo != -_INF else -_INF,
                                hi=a.hi ** y if a.hi != _INF else _INF,
                                lineage=lineage)
            else:
                if self.record and a.lo <= 0.0 <= a.hi \
                        and a.lo > -_INF and _is_float(out_dt):
                    self._hit("DT503", "pow-domain",
                              f"x**{y} base interval [{a.lo:.3g}, "
                              f"{a.hi:.3g}] admits 0 -> divide-through-"
                              "zero with no clamp in between")
                av = self.fresh(lineage=lineage)
        elif name == "pow":
            a, b = ins
            av = self._pow(a, b, lineage)
        elif name == "iota":
            n = _aval_size(eqn.outvars[0])
            av = self.fresh(lo=0.0, hi=float(max(n - 1, 0)))
        elif name == "select_n":
            iv = ins[1].iv() if len(ins) > 1 else (-_INF, _INF)
            for c in ins[2:]:
                iv = _iv_union(iv, c.iv())
            av = self.fresh(lo=iv[0], hi=iv[1], lineage=lineage)
        elif name in ("concatenate", "dynamic_update_slice", "pad",
                      "scatter", "scatter-add", "scatter_add"):
            iv = ins[0].iv()
            for c in ins[1:]:
                if _is_float(_dtype_str(eqn.outvars[0])) or True:
                    iv = _iv_union(iv, c.iv())
            av = self.fresh(lo=iv[0], hi=iv[1], lineage=lineage)
        elif name == "reduce_max":
            a = ins[0]
            av = self.fresh(lo=a.lo, hi=a.hi,
                            maxof=frozenset({a.vid}) | a.maxof,
                            lineage=lineage)
        elif name == "reduce_min":
            a = ins[0]
            av = self.fresh(lo=a.lo, hi=a.hi, lineage=lineage)
        elif name in ("reduce_sum", "cumsum", "reduce_window_sum"):
            av = self._reduce_sum(eqn, ins[0], name, out_dt, lineage)
        elif name == "reduce_prod":
            av = self.fresh(lineage=lineage)
        elif name == "reduce":
            # generic lax.reduce: fire DT500 only for an add combinator
            # (a sum accumulating at operand precision); other monoids
            # (max/min/or) don't compound rounding per element
            k = 1
            try:
                shape = eqn.invars[0].aval.shape
                for d in eqn.params.get("dimensions", ()):
                    k *= int(shape[d])
            except Exception:
                k = 1
            body = eqn.params.get("jaxpr")
            body = getattr(body, "jaxpr", body)
            is_add = (body is not None and len(body.eqns) == 1
                      and body.eqns[0].primitive.name in ("add", "add_any"))
            if self.record and is_add and out_dt in _LOW \
                    and k >= self.reduce_elems:
                self._hit("DT500", f"reduce:{out_dt}",
                          f"lax.reduce(add) accumulates {k} element(s) "
                          f"in {out_dt} — the running sum rounds at "
                          "every add")
            av = self.fresh(lineage=lineage)
        elif name in ("argmax", "argmin"):
            av = self.fresh(lo=0.0, hi=float(max(_aval_size(eqn.invars[0])
                                                 - 1, 0)))
        elif name == "dot_general":
            av = self._dot(eqn, ins, out_dt, lineage)
        elif name == "conv_general_dilated":
            av = self._conv(eqn, ins, out_dt, lineage)
        elif name in ("threefry2x32", "random_bits"):
            av = self.fresh(lo=0.0, hi=4.3e9)
        else:
            av = self.fresh(lineage=lineage)

        # DT502: update-site arithmetic below the declared compute dtype
        if name in _ARITH and self.record \
                and self.compute_dtype == "float32" and out_dt in _LOW \
                and (lineage & {"param", "opt"}):
            kind = "optimizer moments" if "opt" in lineage else "parameters"
            self._hit("DT502", f"{name}:{out_dt}",
                      f"{kind} combined by `{name}` in {out_dt} while the "
                      "declared PrecisionPolicy compute dtype is float32 "
                      "— the optimizer update runs below the compute "
                      "contract")

        for v in eqn.outvars:
            env[id(v)] = av if av is not None else self.fresh()
        if len(eqn.outvars) > 1 and av is not None:
            # independent identities for secondary outputs
            for v in eqn.outvars[1:]:
                env[id(v)] = self.fresh(lo=av.lo, hi=av.hi,
                                        lineage=av.lineage)

    # --------------------------------------------------- hazard sub-evals
    def _exp(self, a: _Av, out_dt: str, lineage) -> _Av:
        cap = _EXP_MAX.get(out_dt, 88.72)
        stable = a.shifted is not None or a.hi <= cap
        if a.shifted is not None:
            av = self.fresh(lo=0.0, hi=min(_exp_b(a.hi), 1.0),
                            is_exp=True, lineage=lineage)
        else:
            av = self.fresh(lo=_exp_b(a.lo), hi=_exp_b(a.hi),
                            is_exp=stable, lineage=lineage)
        if not stable and self.record:
            # deferred: a later softmax shape upgrades this to DT504
            overflow = a.hi > cap and a.hi < _INF
            msg = (f"exp input interval [{a.lo:.3g}, {a.hi:.3g}] exceeds "
                   f"log({out_dt or 'float32'}_max)~{cap:.4g} -> overflow "
                   "to inf with no clamp or subtract-max in between")
            self.pending_exp[av.vid] = ("exp-overflow", msg if overflow
                                        else "")
        return av

    def _pow(self, a: _Av, b: _Av, lineage) -> _Av:
        if self.record and b.hi < 0.0 and a.lo <= 0.0 <= a.hi \
                and a.lo > -_INF:
            self._hit("DT503", "pow-domain",
                      f"pow base interval [{a.lo:.3g}, {a.hi:.3g}] admits "
                      "0 with a negative exponent -> divide-through-zero "
                      "with no clamp in between")
        if 0.0 < a.lo and a.hi < _INF:
            try:
                corners = [a.lo ** b.lo if b.lo > -_INF else
                           (_INF if a.lo < 1.0 else 0.0),
                           a.lo ** b.hi if b.hi < _INF else
                           (0.0 if a.lo < 1.0 else _INF),
                           a.hi ** b.lo if b.lo > -_INF else
                           (_INF if a.hi < 1.0 else 0.0),
                           a.hi ** b.hi if b.hi < _INF else
                           (0.0 if a.hi < 1.0 else _INF)]
                return self.fresh(lo=min(corners), hi=max(corners),
                                  lineage=lineage)
            except OverflowError:
                pass
        return self.fresh(lineage=lineage)

    def _div_hazard(self, a: _Av, b: _Av, out_dt: str, eqn) -> None:
        if not self.record or not _is_float(out_dt):
            return
        # softmax shape: exp(x) normalized by its own sum
        if a.is_exp is not None and b.sumexp_of is not None \
                and b.sumexp_of == a.vid:
            if a.is_exp is False:
                self.pending_exp.pop(a.vid, None)
                self._hit("DT504", "softmax",
                          "softmax-shaped exp(x)/sum(exp(x)) whose "
                          "exponent is not dominated by a subtract-max "
                          "(and not provably bounded) — one hot logit "
                          "overflows the row to inf/inf = NaN")
            return
        if b.lo <= 0.0 <= b.hi and (b.lo > -_INF or b.hi < _INF):
            self._hit("DT503", "div-zero",
                      f"divisor interval [{b.lo:.3g}, {b.hi:.3g}] admits "
                      "zero -> divide-through-zero with no clamp in "
                      "between")

    def _reduce_sum(self, eqn, a: _Av, name: str, out_dt: str,
                    lineage) -> _Av:
        n_in = _aval_size(eqn.invars[0])
        n_out = _aval_size(eqn.outvars[0])
        k = max(n_in // max(n_out, 1), 1)
        if name == "cumsum":
            k = max(n_in // max(n_out, 1), 1) if n_out else 1
            # cumsum preserves shape; accumulation depth is the axis len
            axis = eqn.params.get("axis", 0)
            try:
                k = int(eqn.invars[0].aval.shape[axis])
            except Exception:
                k = 1
        if name == "reduce_window_sum":
            k = 1
            for d in eqn.params.get("window_dimensions", ()):
                k *= int(d)
        if self.record and out_dt in _LOW and k >= self.reduce_elems:
            self._hit("DT500", f"{name}:{out_dt}",
                      f"`{name}` accumulates {k} element(s) in {out_dt} "
                      "— the running sum rounds at every add")
        kf = float(k)
        lo = _mulc(kf, a.lo) if a.lo < 0.0 else min(a.lo, _mulc(kf, a.lo))
        hi = _mulc(kf, a.hi) if a.hi > 0.0 else max(a.hi, _mulc(kf, a.hi))
        sumexp = a.vid if a.is_exp is not None else None
        if a.is_exp is True:
            # the max element contributes exp(0) = 1 to a stable-softmax
            # row sum: log/div of this sum is safe by construction
            lo = max(lo, 1.0)
        return self.fresh(lo=lo, hi=hi, sumexp_of=sumexp, lineage=lineage)

    def _dot(self, eqn, ins, out_dt: str, lineage) -> _Av:
        a, b = ins[0], ins[1]
        dims = eqn.params.get("dimension_numbers")
        k = 1
        try:
            (lc, _rc), _ = dims
            shape = eqn.invars[0].aval.shape
            for d in lc:
                k *= int(shape[d])
        except Exception:
            k = 1
        pref = eqn.params.get("preferred_element_type")
        pref_s = str(pref) if pref is not None else None
        in_dts = [_dtype_str(v) for v in eqn.invars[:2]]
        if self.record and all(dt in _LOW for dt in in_dts) \
                and (pref_s is None or pref_s in _LOW) and out_dt in _LOW:
            self._hit("DT500", f"dot_general:{out_dt}",
                      f"dot_general contracts {k} element(s) with "
                      f"{in_dts[0]} operands and no f32 "
                      "preferred_element_type — the MXU accumulates at "
                      "operand precision")
        m = _mulc(max(abs(a.lo), abs(a.hi)), max(abs(b.lo), abs(b.hi)))
        bound = _mulc(float(k), m)
        return self.fresh(lo=-bound, hi=bound, lineage=lineage)

    def _conv(self, eqn, ins, out_dt: str, lineage) -> _Av:
        a, b = ins[0], ins[1]
        k = 1
        try:
            dn = eqn.params["dimension_numbers"]
            rhs = eqn.invars[1].aval.shape
            k = 1
            for i, d in enumerate(rhs):
                if i != dn.rhs_spec[0]:
                    k *= int(d)
        except Exception:
            k = 1
        pref = eqn.params.get("preferred_element_type")
        pref_s = str(pref) if pref is not None else None
        in_dts = [_dtype_str(v) for v in eqn.invars[:2]]
        if self.record and all(dt in _LOW for dt in in_dts) \
                and (pref_s is None or pref_s in _LOW) and out_dt in _LOW:
            self._hit("DT500", f"conv:{out_dt}",
                      f"conv_general_dilated accumulates {k} element(s) "
                      f"per output in {in_dts[0]} with no f32 "
                      "preferred_element_type")
        m = _mulc(max(abs(a.lo), abs(a.hi)), max(abs(b.lo), abs(b.hi)))
        bound = _mulc(float(k), m)
        return self.fresh(lo=-bound, hi=bound, lineage=lineage)

    # ------------------------------------------------------ control flow
    def _fixpoint(self, run_body, carry: List[_Av]) -> List[_Av]:
        """Two widening passes (silent), returning stabilized carry avs."""
        was = self.record
        self.record = False
        try:
            for _ in range(2):
                outs = run_body(carry)
                changed = False
                nxt = []
                for c, o in zip(carry, outs):
                    lo, hi = c.lo, c.hi
                    if o.lo < lo:
                        lo, changed = -_INF, True
                    if o.hi > hi:
                        hi, changed = _INF, True
                    nxt.append(_Av(lo=lo, hi=hi, vid=c.vid,
                                   lineage=c.lineage | o.lineage))
                carry = nxt
                if not changed:
                    break
        finally:
            self.record = was
        return carry

    def _dt501(self, body_jaxpr, carry_in: List[_Av], carry_vars,
               body_outvars, trip: Optional[int], kind: str) -> None:
        if not self.record:
            return
        import jax  # noqa: PLC0415

        if trip is not None and trip < self.carry_steps:
            return
        for i, v in enumerate(carry_vars):
            dt = _dtype_str(v)
            if dt not in _LOW:
                continue
            out_v = body_outvars[i]
            if out_v is v or isinstance(out_v, jax.core.Literal):
                continue  # passthrough carry: no per-step rounding
            if self.params_dtype == dt \
                    and (carry_in[i].lineage & {"param", "opt"}):
                continue  # declared-storage params/moments: sanctioned
            steps = str(trip) if trip is not None else ">=? (while)"
            self._hit("DT501", f"{kind}:{dt}:{i}",
                      f"{kind} carry slot {i} ({dt} "
                      f"{tuple(getattr(v.aval, 'shape', ()))}) is "
                      f"rewritten across {steps} iterations — rounding "
                      "error compounds once per step")

    def _scan(self, eqn, env) -> None:
        closed = eqn.params["jaxpr"]
        nc = eqn.params["num_consts"]
        ncar = eqn.params["num_carry"]
        trip = eqn.params.get("length")
        ins = [self._read(env, v) for v in eqn.invars]
        consts, carry, xs = ins[:nc], ins[nc:nc + ncar], ins[nc + ncar:]
        xs_avs = [self.fresh(lo=a.lo, hi=a.hi, lineage=a.lineage)
                  for a in xs]

        def run(c):
            return self.walk(closed, consts + list(c) + xs_avs)[:ncar]

        stable = self._fixpoint(run, list(carry))
        body = closed.jaxpr
        self._dt501(body, stable, body.invars[nc:nc + ncar],
                    body.outvars[:ncar],
                    int(trip) if trip is not None else None, "scan")
        outs = self.walk(closed, consts + stable + xs_avs)
        for i, (v, av) in enumerate(zip(eqn.outvars, outs)):
            if i < ncar:
                joined = _iv_union(stable[i].iv(), av.iv())
                env[id(v)] = self.fresh(lo=joined[0], hi=joined[1],
                                        lineage=av.lineage)
            else:
                env[id(v)] = self.fresh(lo=av.lo, hi=av.hi,
                                        lineage=av.lineage)

    def _while(self, eqn, env) -> None:
        cn = eqn.params["cond_nconsts"]
        bn = eqn.params["body_nconsts"]
        cond_j = eqn.params["cond_jaxpr"]
        body_j = eqn.params["body_jaxpr"]
        ins = [self._read(env, v) for v in eqn.invars]
        cond_c, body_c = ins[:cn], ins[cn:cn + bn]
        carry = ins[cn + bn:]

        def run(c):
            return self.walk(body_j, body_c + list(c))

        stable = self._fixpoint(run, list(carry))
        was = self.record
        self.record = False
        try:
            self.walk(cond_j, cond_c + stable)
        finally:
            self.record = was
        nbody = len(body_j.jaxpr.invars) - bn
        self._dt501(body_j.jaxpr, stable,
                    body_j.jaxpr.invars[bn:bn + nbody],
                    body_j.jaxpr.outvars, None, "while")
        outs = self.walk(body_j, body_c + stable)
        for v, av, st in zip(eqn.outvars, outs, stable):
            joined = _iv_union(st.iv(), av.iv())
            env[id(v)] = self.fresh(lo=joined[0], hi=joined[1],
                                    lineage=av.lineage)

    def _cond(self, eqn, env) -> None:
        branches = eqn.params["branches"]
        ins = [self._read(env, v) for v in eqn.invars]
        ops = ins[1:]
        outs = None
        for br in branches:
            o = self.walk(br, ops)
            if outs is None:
                outs = o
            else:
                outs = [self.fresh(lo=min(x.lo, y.lo), hi=max(x.hi, y.hi),
                                   lineage=x.lineage | y.lineage)
                        for x, y in zip(outs, o)]
        for v, av in zip(eqn.outvars, outs or []):
            env[id(v)] = av

    # ------------------------------------------------------------ results
    def findings(self, source: str) -> List[Finding]:
        # flush exp candidates no softmax shape reclassified
        for key, msg in self.pending_exp.values():
            if msg:
                slot = self.agg.setdefault(("DT503", key), [0, msg])
                slot[0] += 1
        self.pending_exp.clear()
        out: List[Finding] = []
        for (rid, key), (count, msg) in self.agg.items():
            if count > 1:
                msg = f"{msg} [{count} site(s)]"
            out.append(get_rule(rid).finding(
                msg, file=source, context=f"numerics:{key}"))
        return out

    def summary(self) -> dict:
        rules: Dict[str, int] = {}
        for (rid, _k), (count, _m) in self.agg.items():
            rules[rid] = rules.get(rid, 0) + count
        return {"eqns": self.eqns, "rules": rules}


# ------------------------------------------------------------ public API
def check_jaxpr_numerics(closed, *, source: str = NUM_SOURCE,
                         in_ranges: Optional[Sequence] = None,
                         in_lineage: Optional[Sequence] = None,
                         compute_dtype: Optional[str] = None,
                         params_dtype: Optional[str] = None,
                         carry_steps: int = DT501_MIN_STEPS,
                         reduce_elems: int = DT500_MIN_REDUCE,
                         ignore: Iterable[str] = ()
                         ) -> Tuple[List[Finding], dict]:
    """DT5xx numerics lint over a traced ``ClosedJaxpr``.

    ``in_ranges``: optional per-invar ``(lo, hi)`` seeds (None entries
    stay unknown). ``in_lineage``: optional per-invar ``"param"`` /
    ``"opt"`` markers feeding the DT502 update-site check. Returns
    ``(findings, summary)``; findings are aggregated per (rule, site
    kind), deterministic across runs of the same program.
    """
    flow = _NumFlow(compute_dtype=compute_dtype, params_dtype=params_dtype,
                    carry_steps=carry_steps, reduce_elems=reduce_elems)
    invars = closed.jaxpr.invars
    in_avs: List[_Av] = []
    seeded = 0
    for i, v in enumerate(invars):
        rng = None
        if in_ranges is not None and i < len(in_ranges):
            rng = in_ranges[i]
        lin = None
        if in_lineage is not None and i < len(in_lineage):
            lin = in_lineage[i]
        kw = {}
        if rng is not None:
            kw["lo"], kw["hi"] = float(rng[0]), float(rng[1])
            seeded += 1
        if lin:
            kw["lineage"] = frozenset({lin})
        in_avs.append(flow.fresh(**kw))
    flow.walk(closed, in_avs)
    ignore = frozenset(ignore)
    findings = [f for f in flow.findings(source)
                if f.rule_id not in ignore]
    summary = flow.summary()
    summary["invars_seeded"] = seeded
    summary["rules"] = {r: c for r, c in summary["rules"].items()
                        if r not in ignore}
    return merge_findings(findings), summary


def _opt_state_ranges(opt_state, bound: float) -> Optional[List]:
    """Per-leaf seed ranges for an optax state tree, matched against the
    jax flatten order. Second-moment leaves (EMAs of squared grads, field
    name ``nu``/``v``) are non-negative by construction — the invariant
    that keeps ``sqrt(nu)+eps`` out of DT503; step counters count up from
    zero. Returns None when the structure can't be walked safely."""
    import jax  # noqa: PLC0415

    out: List = []

    def rec(obj, hint: str) -> None:
        if obj is None:
            return
        if hasattr(obj, "_fields"):  # NamedTuple (optax states)
            for name, child in zip(obj._fields, obj):
                rec(child, name)
            return
        if isinstance(obj, dict):
            for k in sorted(obj):  # jax flattens dicts by sorted key
                rec(obj[k], hint)
            return
        if isinstance(obj, (tuple, list)):
            for child in obj:
                rec(child, hint)
            return
        if not (hasattr(obj, "shape") or isinstance(obj, (int, float))):
            return
        h = hint.lower()
        if "count" in h or "step" in h:
            out.append((0.0, 1e9))
        elif h in ("nu", "v") or h.endswith("_sq") or "second" in h:
            out.append((0.0, bound * bound))
        else:
            out.append((-bound, bound))

    try:
        rec(opt_state, "")
        if len(out) != len(jax.tree_util.tree_leaves(opt_state)):
            return None
        return out
    except Exception:
        return None


def network_numerics(net, closed, args, *, source: str = NUM_SOURCE,
                     ignore: Iterable[str] = (),
                     input_bound: float = DEFAULT_INPUT_BOUND) -> dict:
    """Numerics pass over a net's already-traced train step.

    ``closed``/``args`` are the ``make_jaxpr`` result and the shell args
    it was traced with (``check_network_ir`` shares its trace — one
    ``make_jaxpr``, two walks). Seeds: inputs/labels/params at the
    declared ``input_bound``, optimizer second moments at ``[0, B^2]``
    (non-negative by construction), step counters at ``[0, 1e9]``.
    Returns ``{"findings": [...], "summary": {...}}``.
    """
    import jax  # noqa: PLC0415

    conf = net.conf
    compute_dtype = getattr(conf, "dtype", "float32")
    params_dtype = getattr(conf, "params_dtype", None)
    loss_scale = getattr(conf, "loss_scale", None)

    params, opt_state = args[0], args[1]
    n_params = len(jax.tree_util.tree_leaves(params))
    n_opt = len(jax.tree_util.tree_leaves(opt_state))
    b = float(input_bound)

    ranges: List = [(-b, b)] * n_params
    opt_ranges = _opt_state_ranges(net.opt_state, b)
    ranges += opt_ranges if opt_ranges is not None \
        else [(-b, b)] * n_opt
    lineage: List = ["param"] * n_params + ["opt"] * n_opt
    for leaf_ in jax.tree_util.tree_leaves(args[2:]):
        dt = str(getattr(leaf_, "dtype", ""))
        ranges.append((-b, b) if _is_float(dt) else None)
        lineage.append(None)

    n_invars = len(closed.jaxpr.invars)
    if len(ranges) != n_invars:  # unexpected flattening: stay sound
        ranges = [None] * n_invars
        lineage = [None] * n_invars

    findings, summary = check_jaxpr_numerics(
        closed, source=source, in_ranges=ranges, in_lineage=lineage,
        compute_dtype=compute_dtype, params_dtype=params_dtype,
        ignore=ignore)

    # DT505 (net-level): sub-f32 grad flow (storage dtype below f32 means
    # the cast transpose emits grads at that dtype) with no loss scale
    low_storage = sorted({
        str(p.dtype) for p in jax.tree_util.tree_leaves(params)
        if str(getattr(p, "dtype", "")) in _LOW})
    if low_storage and not loss_scale and "DT505" not in frozenset(ignore):
        dt = low_storage[0]
        findings = merge_findings(findings + [get_rule("DT505").finding(
            f"parameters are stored in {dt} (gradients flow at {dt} "
            "through the cast transpose) but no loss scale is "
            "configured — set conf.loss_scale / "
            "MeshLayout(params_dtype=..., loss_scale=...) / "
            "PrecisionPolicy(loss_scale=...)",
            file=source, context="numerics:loss-scale")])
        summary["rules"]["DT505"] = summary["rules"].get("DT505", 0) + 1
    summary["policy"] = {"compute_dtype": compute_dtype,
                         "params_dtype": params_dtype,
                         "loss_scale": loss_scale}
    return {"findings": findings, "summary": summary}


def check_network_numerics(net, batch_or_struct=None, *,
                           ignore: Iterable[str] = (),
                           timesteps_probe: Optional[int] = None,
                           input_bound: float = DEFAULT_INPUT_BOUND,
                           source: str = NUM_SOURCE) -> dict:
    """Standalone DT5xx entry over a net's real train step. Traces once
    via :func:`~deeplearning4j_tpu.analysis.ir_checks.check_network_ir`
    (which shares the jaxpr between the DT2xx and DT5xx walks) and
    returns only the numerics block: ``{"findings", "summary"}``."""
    from .ir_checks import check_network_ir  # noqa: PLC0415

    rep = check_network_ir(net, batch_or_struct, ignore=ignore,
                           timesteps_probe=timesteps_probe, source=source,
                           numerics=True, numerics_input_bound=input_bound)
    return {"findings": [f for f in rep["findings"]
                         if f.rule_id.startswith("DT5")],
            "summary": rep["numerics"]}


def analyze_config_numerics(conf, *, batch: int = 4,
                            timesteps_probe: Optional[int] = None,
                            source: str = NUM_SOURCE,
                            ignore: Iterable[str] = (),
                            input_bound: float = DEFAULT_INPUT_BOUND
                            ) -> Tuple[List[Finding], dict]:
    """Headless DT5xx entry for a config (the CLI ``--numerics`` path):
    builds the matching network class and scans its train step. Returns
    ``(findings, summary)``."""
    if hasattr(conf, "vertices"):
        from ..nn.graph import ComputationGraph  # noqa: PLC0415

        net = ComputationGraph(conf)
    else:
        from ..nn.multilayer import MultiLayerNetwork  # noqa: PLC0415

        net = MultiLayerNetwork(conf)
    block = check_network_numerics(
        net, batch, ignore=ignore, timesteps_probe=timesteps_probe,
        input_bound=input_bound, source=source)
    return block["findings"], block["summary"]
