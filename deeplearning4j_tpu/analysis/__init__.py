"""dl4jtpu-check: static analysis for configs and JAX/TPU pitfalls.

Two passes, run before anything compiles:

- **Graph pass** (`graph_checks`): abstract-interpret a
  ``MultiLayerConfiguration`` / ``ComputationGraphConfiguration`` with
  ``jax.eval_shape`` and diff the traced output of every layer/vertex
  against its declared ``get_output_type()`` — the same static contract
  the reference DL4J enforces via ``InputType`` propagation
  (SURVEY.md §2.1), now cross-checked against what JAX will actually
  trace. Also flags TPU-hostile configs (lane padding, float64,
  variable timesteps, NCHW-looking inputs).
- **AST pass** (`ast_checks`): lint Python sources for the classic JAX
  footguns — ``np.*`` under ``jit``, host syncs in hot paths, PRNG key
  reuse, Python control flow on traced values, captured-state mutation.
- **IR pass** (`ir_checks` + `cost_model`): trace the *real* train step
  with ``jax.make_jaxpr`` (zero dispatches) and lint what the compiler
  will actually build — f64 promotion, host callbacks, dropped buffer
  donation, materialization blow-ups, traced gather/scatter indices,
  padding waste, collectives — plus a static roofline cost model
  (FLOPs/bytes/arithmetic intensity, predicted step time). Entry points:
  ``net.analyze_ir(batch)``, ``conf.analyze(ir=True)``, the CLI ``--ir``
  flag, and the compile manager's automatic admission scan.
- **Sharding-flow pass** (`shard_flow`, DT3xx): static sharding
  propagation of a ``MeshLayout``'s PartitionSpecs through the traced
  step — predicts GSPMD's collective census (kind, mesh axes, per-device
  payload) before anything compiles, flags implicit all-gathers /
  reshards / oversized tp all-reduces / per-scan-step collectives, and
  feeds the ``DL4JTPU_ICI_GBPS`` communication roofline term. Validated
  against the measured post-SPMD census (``BENCH_MODEL=shard``). Entry
  points: ``net.analyze_ir(batch, layout=...)``, ``preflight(layout=…)``,
  CLI ``--ir --mesh data=8,fsdp=4,tp=2``, and admission for any program
  compiled with mesh-sharded args.

- **Runtime-guard pass** (`concurrency` + `runtime_checks`, DT4xx):
  concurrency/env/telemetry lint for the threaded serving/fleet/online
  stack. Thread-entry discovery (``Thread(target=...)``, HTTP ``do_*``
  handlers, watchdog/batcher sinks, public methods of lock-owning
  classes) feeds a per-class attribute census with ``with self._lock``
  context tracking: shared attributes raced across entries (DT400),
  blocking calls under a lock (DT401), lock-order inversions (DT402),
  raw ``os.environ`` writes outside ``tune.EnvScope`` (DT403), bare
  ``time.sleep`` outside ``runtime.resilience`` (DT404), trace-unsafe
  global mutation from handler threads (DT405), and ``dl4jtpu_*``
  metric / flight-event schema drift (DT406). Entry points:
  ``check_runtime_paths``, ``conf.analyze(concurrency=True)``, CLI
  ``--concurrency``, and the check.sh self-scan of serving/fleet/
  runtime/telemetry/streaming.

- **Numerics pass** (`numerics`, DT5xx): dtype-flow + value-range
  abstract interpretation over the same traced train step the IR pass
  reads — one ``make_jaxpr``, two walks. Dtype-flow tracks effective
  accumulation precision (DT500 low-precision dot/conv/reduce without an
  f32 ``preferred_element_type``, DT501 low-precision scan/while carry
  compounding across steps, DT502 optimizer updates below the declared
  PrecisionPolicy compute dtype); interval abstract interpretation seeds
  invars from declared input/initializer bounds and propagates
  ``[lo, hi]`` per eqn (DT503 unguarded exp/log/div/sqrt/rsqrt domain
  hazards, DT504 softmax not dominated by a subtract-max — structural,
  DT505 advisory sub-f32 grad flow without a loss scale). Entry points:
  ``net.analyze_ir(batch)["numerics"]`` (on by default),
  ``conf.analyze(numerics=True)``, CLI ``--numerics``, and admission
  (unseeded — clamp/structure evidence only).

Each finding carries a rule id (``DT0xx``-``DT5xx``), severity,
location and fix hint; rules live in a registry (`rules`) so later PRs add
checks cheaply. Inline ``# dl4jtpu: ignore[DT0xx]`` pragmas suppress AST
findings (`pragmas`); IR findings (no source line) suppress via
``ignore=(...)`` / CLI ``--ignore``. CLI:
``python -m deeplearning4j_tpu.analysis``.
"""

from .findings import Finding, Severity, SEVERITY_ORDER, merge_findings
from .rules import Rule, RULES, get_rule, register_rule
from .pragmas import filter_findings
from .graph_checks import (
    check_multi_layer,
    check_graph,
    check_config,
    check_partition_specs,
    check_shardings,
)
from .ast_checks import check_source, check_file
from .cost_model import apply_roofline, jaxpr_cost, roofline_params, static_cost
from .ir_checks import (
    audit_donation,
    analyze_config_ir,
    check_jaxpr_ir,
    check_network_ir,
    check_padding_waste,
)
from .concurrency import check_concurrency_file, check_concurrency_source
from .runtime_checks import (
    TelemetrySchema,
    check_runtime_file,
    check_runtime_package,
    check_runtime_paths,
    check_runtime_source,
)
from .shard_flow import (
    analyze_shard_flow,
    check_network_shard_flow,
    compare_census,
    hlo_collective_census,
)
from .numerics import (
    analyze_config_numerics,
    check_jaxpr_numerics,
    check_network_numerics,
    network_numerics,
)

__all__ = [
    "Finding",
    "Severity",
    "SEVERITY_ORDER",
    "Rule",
    "RULES",
    "get_rule",
    "register_rule",
    "filter_findings",
    "merge_findings",
    "check_multi_layer",
    "check_graph",
    "check_config",
    "check_partition_specs",
    "check_shardings",
    "check_source",
    "check_file",
    "jaxpr_cost",
    "roofline_params",
    "apply_roofline",
    "static_cost",
    "audit_donation",
    "analyze_config_ir",
    "check_jaxpr_ir",
    "check_network_ir",
    "check_padding_waste",
    "analyze_shard_flow",
    "check_network_shard_flow",
    "compare_census",
    "hlo_collective_census",
    "TelemetrySchema",
    "check_concurrency_file",
    "check_concurrency_source",
    "check_runtime_file",
    "check_runtime_package",
    "check_runtime_paths",
    "check_runtime_source",
    "analyze_config_numerics",
    "check_jaxpr_numerics",
    "check_network_numerics",
    "network_numerics",
]
