"""Inline suppression pragmas.

Syntax (anywhere in a comment on the flagged line):
    # dl4jtpu: ignore[DT101]          suppress one rule on this line
    # dl4jtpu: ignore[DT101,DT102]    suppress several
    # dl4jtpu: ignore                 suppress every rule on this line
    # dl4jtpu: skip-file              (first 5 lines) skip the whole file

Graph findings have no line numbers, so pragmas only apply to AST
findings; suppress graph findings by fixing the config or narrowing the
checks passed to check_config().
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, List, Optional, Set

from .findings import Finding

# the pragma may share a comment with prose: "# static arg — dl4jtpu: ignore[DT104]"
_PRAGMA_RE = re.compile(r"#.*?dl4jtpu:\s*ignore(?:\[([A-Z0-9,\s]+)\])?")
_SKIP_FILE_RE = re.compile(r"#.*?dl4jtpu:\s*skip-file")


def file_skipped(source: str) -> bool:
    head = source.splitlines()[:5]
    return any(_SKIP_FILE_RE.search(line) for line in head)


def line_pragmas(source: str) -> Dict[int, Optional[Set[str]]]:
    """1-based line -> set of suppressed rule ids (None = all rules)."""
    out: Dict[int, Optional[Set[str]]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _PRAGMA_RE.search(line)
        if not m:
            continue
        if m.group(1) is None:
            out[i] = None
        else:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def filter_findings(findings: Iterable[Finding], source: str) -> List[Finding]:
    """Drop findings suppressed by pragmas in ``source``."""
    if file_skipped(source):
        return []
    pragmas = line_pragmas(source)
    kept: List[Finding] = []
    for f in findings:
        rules = pragmas.get(f.line, "absent")
        if rules == "absent":
            kept.append(f)
        elif rules is not None and f.rule_id not in rules:
            kept.append(f)
    return kept
