"""Finding: one diagnostic from either analyzer pass.

Locations come in two flavors and share one rendering:
- AST findings: ``file:line:col``
- graph findings: ``source:vertex 'name'`` (configs have no line numbers;
  the vertex/layer name is the address inside the config)
"""

from __future__ import annotations

from dataclasses import dataclass

# total order used by --fail-on and sorting; "never" is a CLI threshold
# only (no finding carries it)
Severity = str
SEVERITY_ORDER = {"info": 0, "warning": 1, "error": 2}


@dataclass(frozen=True)
class Finding:
    rule_id: str  # "DT001" ... registered in rules.py
    severity: Severity
    message: str
    file: str = "<config>"
    line: int = 0  # 0 = no line info (graph findings)
    col: int = 0
    context: str = ""  # vertex/layer/function name the finding anchors to
    hint: str = ""  # how to fix (rule default unless overridden)

    @property
    def location(self) -> str:
        if self.line:
            return f"{self.file}:{self.line}:{self.col}"
        if self.context:
            return f"{self.file}:{self.context}"
        return self.file

    def format_human(self) -> str:
        ctx = f" [{self.context}]" if self.line and self.context else ""
        s = f"{self.location}: {self.rule_id} {self.severity}: {self.message}{ctx}"
        if self.hint:
            s += f"\n    hint: {self.hint}"
        return s

    def to_dict(self) -> dict:
        return {
            "rule_id": self.rule_id,
            "severity": self.severity,
            "message": self.message,
            "file": self.file,
            "line": self.line,
            "col": self.col,
            "context": self.context,
            "hint": self.hint,
        }


def sort_findings(findings) -> list:
    # message/severity participate so equal-location findings order
    # deterministically across passes and repeated runs
    return sorted(
        findings,
        key=lambda f: (f.file, f.line, f.col, f.rule_id, f.context,
                       f.severity, f.message),
    )


def merge_findings(*finding_groups) -> list:
    """Stable-sorted union of finding lists with exact duplicates dropped.

    The three passes (graph/AST/IR) can legitimately rediscover the same
    fact (e.g. ``conf.analyze(ir=True)`` run twice, or a config passed to
    the CLI twice); identity is the full finding tuple, so two findings
    that differ in any user-visible field both survive.
    """
    seen = set()
    out = []
    for f in sort_findings([f for g in finding_groups for f in g]):
        key = (f.rule_id, f.severity, f.message, f.file, f.line, f.col,
               f.context)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out


def count_by_severity(findings) -> dict:
    counts = {"error": 0, "warning": 0, "info": 0}
    for f in findings:
        counts[f.severity] = counts.get(f.severity, 0) + 1
    return counts
