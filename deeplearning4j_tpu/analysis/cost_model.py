"""Static roofline cost model: FLOPs/bytes/intensity of a jaxpr, no devices.

Walks the eqns of a ``jax.make_jaxpr`` trace with a per-primitive cost table
and emits a ``static_cost`` report — total FLOPs, HBM bytes touched,
arithmetic intensity, and a predicted step time from a configurable roofline
(Williams et al., "Roofline: an insightful visual performance model"). The
whole pass is host-side shape algebra: no compile, no dispatch, no profiler
run — cheap enough to gate CI on.

Counting conventions (deliberately simple, deliberately stated):

- ``dot_general``: exact ``2 * batch * M * N * K``; ``conv_general_dilated``:
  exact ``2 * out_elements * kernel_spatial * C_in / feature_groups``. These
  two dominate real models and are bit-exact against the closed forms
  (tests/test_ir_cost.py holds them to equality).
- reductions count one FLOP per input element; every other arithmetic eqn
  counts one FLOP per output element (a transcendental is 1 FLOP — the MXU
  doesn't run it anyway, the VPU cost model is not the bottleneck we chase).
- pure data movement (reshape/transpose/slice/broadcast/convert/...) is
  0 FLOPs but still moves bytes.
- bytes per eqn = operand bytes + result bytes. No fusion modeling: XLA will
  beat this number, so arithmetic intensity is a *lower bound* and the
  predicted step time an *upper bound* — the right polarity for a gate.
- ``scan`` multiplies its body by the static trip count; ``while`` (dynamic
  trip count) counts ONE iteration and sets ``dynamic_loop`` — per-step cost
  is what the report means, and the staged ``fori_loop`` runs one optimizer
  step per iteration.
- ``cond`` takes the most expensive branch (upper bound again).

Collectives (``psum``/``all_gather``/``ppermute``/...) are tallied
separately — count and payload bytes per step — feeding the DT207 check.

Roofline knobs: ``DL4JTPU_PEAK_FLOPS`` (peak FLOP/s), ``DL4JTPU_HBM_GBPS``
(HBM GB/s) and ``DL4JTPU_ICI_GBPS`` (interconnect GB/s per chip); defaults
model one TPU v4 core (275 Tf/s bf16, 1228 GB/s HBM, 300 GB/s aggregate
ICI). The interconnect term makes ``predicted_step_seconds`` cover
compute-, memory- AND communication-bound steps: the per-step collective
bytes (the jaxpr census here, plus the sharding-flow predicted census when
a layout is analyzed — see ``analysis/shard_flow.py``) divide by the ICI
bandwidth, and ``bound`` reports which of the three ceilings wins.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "PEAK_FLOPS_ENV",
    "HBM_GBPS_ENV",
    "ICI_GBPS_ENV",
    "roofline_params",
    "apply_roofline",
    "jaxpr_cost",
    "static_cost",
    "subjaxprs",
]

PEAK_FLOPS_ENV = "DL4JTPU_PEAK_FLOPS"
HBM_GBPS_ENV = "DL4JTPU_HBM_GBPS"
ICI_GBPS_ENV = "DL4JTPU_ICI_GBPS"
DEFAULT_PEAK_FLOPS = 2.75e14  # one TPU v4 core, bf16 MXU
DEFAULT_HBM_GBPS = 1228.0  # TPU v4 HBM2 bandwidth
DEFAULT_ICI_GBPS = 300.0  # TPU v4 aggregate ICI per chip (6 links)

# pure data movement: 0 FLOPs, bytes only
_ZERO_FLOP = frozenset({
    "reshape", "broadcast_in_dim", "transpose", "squeeze", "slice",
    "dynamic_slice", "dynamic_update_slice", "concatenate", "pad",
    "convert_element_type", "bitcast_convert_type", "copy", "rev", "iota",
    "stop_gradient", "gather", "scatter", "select_n", "split",
    "device_put",
})

# one FLOP per INPUT element (tree reductions)
_REDUCE = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "argmax", "argmin", "cumsum", "cumprod", "cummax", "cummin",
})

# cross-device data movement, tallied separately for DT207
_COLLECTIVES = frozenset({
    "psum", "pmax", "pmin", "pmean", "all_gather", "all_to_all", "ppermute",
    "reduce_scatter", "psum_scatter", "pbroadcast",
})

# jaxpr primitive -> census kind: the DT207 census keys (kind, axes) the
# same way the measured post-SPMD census and the sharding-flow predicted
# census do (analysis/shard_flow.py)
_COLLECTIVE_KINDS = {
    "psum": "all_reduce", "pmax": "all_reduce", "pmin": "all_reduce",
    "pmean": "all_reduce", "pbroadcast": "all_reduce",
    "all_gather": "all_gather", "all_to_all": "all_to_all",
    "ppermute": "collective_permute",
    "reduce_scatter": "reduce_scatter", "psum_scatter": "reduce_scatter",
}


def roofline_params() -> dict:
    """The configured roofline: peak FLOP/s, HBM GB/s, and the ridge point
    (FLOPs/byte above which a kernel is compute-bound)."""
    def _env_float(name: str, default: float) -> float:
        raw = os.environ.get(name)
        if raw:
            try:
                return float(raw)
            except ValueError:
                pass
        return default

    peak = _env_float(PEAK_FLOPS_ENV, DEFAULT_PEAK_FLOPS)
    gbps = _env_float(HBM_GBPS_ENV, DEFAULT_HBM_GBPS)
    ici = _env_float(ICI_GBPS_ENV, DEFAULT_ICI_GBPS)
    return {
        "peak_flops": peak,
        "hbm_gbps": gbps,
        "ici_gbps": ici,
        "ridge_flops_per_byte": peak / (gbps * 1e9),
    }


def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0  # abstract tokens / effects
    import numpy as np

    n = 1
    for d in shape:
        n *= int(d)
    try:
        itemsize = int(np.dtype(dtype).itemsize)
    except TypeError:
        # extended dtypes (PRNG key<fry> etc.): negligible, count the
        # elements at 4 bytes rather than crashing the whole report
        itemsize = int(getattr(dtype, "itemsize", 4) or 4)
    return n * itemsize


def _aval_elems(aval) -> int:
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _dot_general_flops(eqn) -> int:
    """Exact 2*batch*M*N*K from the dimension numbers."""
    (lhs_c, rhs_c), (lhs_b, _rhs_b) = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    batch = 1
    for d in lhs_b:
        batch *= int(lhs[d])
    k = 1
    for d in lhs_c:
        k *= int(lhs[d])
    m = 1
    for i, d in enumerate(lhs):
        if i not in lhs_c and i not in lhs_b:
            m *= int(d)
    n = 1
    rhs_b = eqn.params["dimension_numbers"][1][1]
    for i, d in enumerate(rhs):
        if i not in rhs_c and i not in rhs_b:
            n *= int(d)
    return 2 * batch * m * n * k


def _conv_flops(eqn) -> int:
    """Exact 2 * out_elements * kernel_spatial * C_in / feature_groups."""
    dn = eqn.params["dimension_numbers"]
    rhs_spec = dn.rhs_spec  # (out_chan, in_chan, *spatial)
    kernel = eqn.invars[1].aval.shape
    c_in = int(kernel[rhs_spec[1]])  # the kernel dim is already C_in/groups
    spatial = 1
    for d in rhs_spec[2:]:
        spatial *= int(kernel[d])
    out_elems = _aval_elems(eqn.outvars[0].aval)
    return 2 * out_elems * spatial * c_in  # c_in is already per-group


def subjaxprs(eqn) -> List[Tuple[Any, int]]:
    """(closed_jaxpr, multiplier) pairs nested inside one eqn.

    ``scan`` multiplies by its static trip count; ``while`` counts one
    iteration (dynamic trip count — the caller flags it); ``cond`` returns
    every branch (the cost walker takes the max). The generic fallback scans
    params for jaxpr-shaped values so new wrapper primitives (remat, custom
    derivatives, pjit) keep being walked without a registry update.
    """
    from jax import core  # noqa: PLC0415

    def closed(j):
        if isinstance(j, core.ClosedJaxpr):
            return j
        if isinstance(j, core.Jaxpr):
            return core.ClosedJaxpr(j, ())
        return None

    name = eqn.primitive.name
    if name == "scan":
        body = closed(eqn.params["jaxpr"])
        return [(body, int(eqn.params.get("length", 1)))] if body else []
    if name == "while":
        out = []
        for key in ("cond_jaxpr", "body_jaxpr"):
            j = closed(eqn.params.get(key))
            if j is not None:
                out.append((j, 1))
        return out
    if name == "cond":
        return [(b, 1) for b in map(closed, eqn.params.get("branches", ()))
                if b is not None]
    out = []
    for v in eqn.params.values():
        j = closed(v)
        if j is not None:
            out.append((j, 1))
        elif isinstance(v, (tuple, list)):
            out.extend((closed(x), 1) for x in v if closed(x) is not None)
    return out


def _eqn_cost(eqn) -> Tuple[int, int]:
    """(flops, bytes) of one leaf eqn (no nested jaxpr)."""
    name = eqn.primitive.name
    in_bytes = sum(_aval_bytes(v.aval) for v in eqn.invars)
    out_bytes = sum(_aval_bytes(v.aval) for v in eqn.outvars)
    if name == "dot_general":
        flops = _dot_general_flops(eqn)
    elif name == "conv_general_dilated":
        flops = _conv_flops(eqn)
    elif name in _ZERO_FLOP:
        flops = 0
    elif name in _REDUCE or name.startswith("reduce_"):
        flops = sum(_aval_elems(v.aval) for v in eqn.invars)
    else:
        flops = sum(_aval_elems(v.aval) for v in eqn.outvars)
    return flops, in_bytes + out_bytes


def jaxpr_cost(closed_jaxpr) -> dict:
    """Cost report of a (closed) jaxpr: FLOPs, HBM bytes, per-primitive
    breakdown, collective tally, roofline projection. Pure host arithmetic.
    """
    acc = {
        "flops": 0, "hbm_bytes": 0, "eqns": 0, "dynamic_loop": False,
        "by_primitive": {},
        "collectives": {"count": 0, "bytes": 0, "by_primitive": {},
                        "census": {}},
    }

    def walk(closed, mult: int) -> Tuple[int, int]:
        flops_here = 0
        bytes_here = 0
        for eqn in closed.jaxpr.eqns:
            name = eqn.primitive.name
            nested = subjaxprs(eqn)
            if name == "while":
                acc["dynamic_loop"] = True
            if nested:
                if name == "cond":
                    best = (0, 0)
                    for sub, m in nested:
                        best = max(best, walk(sub, mult * m))
                    f, b = best
                else:
                    f = b = 0
                    for sub, m in nested:
                        sf, sb = walk(sub, mult * m)
                        f += sf
                        b += sb
                flops_here += f
                bytes_here += b
                continue
            f, b = _eqn_cost(eqn)
            f *= mult
            b *= mult
            flops_here += f
            bytes_here += b
            acc["eqns"] += mult
            row = acc["by_primitive"].setdefault(
                name, {"count": 0, "flops": 0, "bytes": 0})
            row["count"] += mult
            row["flops"] += f
            row["bytes"] += b
            if name in _COLLECTIVES:
                payload = mult * sum(_aval_bytes(v.aval) for v in eqn.invars)
                acc["collectives"]["count"] += mult
                acc["collectives"]["bytes"] += payload
                # mesh-axis labels: psum/all_gather/... carry the named axes
                # they span, so the jaxpr census keys exactly like the
                # measured post-SPMD census ((kind, axes) — see
                # analysis/shard_flow.hlo_collective_census)
                axes = eqn.params.get("axes") or eqn.params.get(
                    "axis_name") or ()
                if not isinstance(axes, (tuple, list)):
                    axes = (axes,)
                axes = tuple(sorted(str(a) for a in axes))
                crow = acc["collectives"]["by_primitive"].setdefault(
                    name, {"count": 0, "bytes": 0, "axes": []})
                crow["count"] += mult
                crow["bytes"] += payload
                for a in axes:
                    if a not in crow["axes"]:
                        crow["axes"].append(a)
                cens = acc["collectives"]["census"].setdefault(
                    (_COLLECTIVE_KINDS.get(name, name), axes),
                    {"count": 0, "bytes": 0})
                cens["count"] += mult
                cens["bytes"] += payload
        return flops_here, bytes_here

    flops, nbytes = walk(closed_jaxpr, 1)
    acc["flops"] = int(flops)
    acc["hbm_bytes"] = int(nbytes)
    acc["arithmetic_intensity"] = (
        flops / nbytes if nbytes else 0.0)
    # census rows in list form (tuple keys don't survive JSON)
    acc["collectives"]["census"] = [
        {"kind": k, "axes": list(axes), "count": row["count"],
         "bytes": row["bytes"]}
        for (k, axes), row in sorted(acc["collectives"]["census"].items())]
    apply_roofline(acc, comm_bytes=acc["collectives"]["bytes"])
    return acc


def apply_roofline(cost: dict, *, comm_bytes: Optional[int] = None,
                   pipeline: Optional[dict] = None) -> dict:
    """(Re)compute ``cost["roofline"]`` from its flops/bytes and a per-step
    communication volume. ``comm_bytes`` defaults to the jaxpr-level
    collective tally; the sharding-flow pass calls this again with its
    predicted census total, so ``predicted_step_seconds`` covers the
    communication-bound regime and ``bound`` can come back
    ``"communication"``.

    ``pipeline={"stages": P, "microbatches": M}`` models a pipelined step:
    compute/memory work divides across the P stages, and the interleaved
    schedule idles a bubble fraction ``(P-1)/(M+P-1)`` of every tick window
    — the predicted seconds inflate by ``1/(1-bubble)``. Communication
    (the per-microbatch stage handoffs are already in ``comm_bytes``) rides
    the same schedule, so it inflates too."""
    flops = cost.get("flops", 0)
    nbytes = cost.get("hbm_bytes", 0)
    if comm_bytes is None:
        comm_bytes = int(cost.get("collectives", {}).get("bytes", 0))
    rl = roofline_params()
    compute_s = flops / rl["peak_flops"] if rl["peak_flops"] else 0.0
    memory_s = (nbytes / (rl["hbm_gbps"] * 1e9)) if rl["hbm_gbps"] else 0.0
    comm_s = (comm_bytes / (rl["ici_gbps"] * 1e9)) if rl["ici_gbps"] else 0.0
    if pipeline:
        p = max(int(pipeline.get("stages", 1)), 1)
        m = max(int(pipeline.get("microbatches", 1)), 1)
        bubble = (p - 1) / (m + p - 1)
        rl["pipeline_stages"] = p
        rl["pipeline_microbatches"] = m
        rl["bubble_fraction"] = bubble
        compute_s /= p
        memory_s /= p
        rl["predicted_step_seconds"] = (
            max(compute_s, memory_s, comm_s) / (1.0 - bubble))
    else:
        rl["predicted_step_seconds"] = max(compute_s, memory_s, comm_s)
    rl["compute_seconds"] = compute_s
    rl["memory_seconds"] = memory_s
    rl["communication_seconds"] = comm_s
    rl["communication_bytes"] = int(comm_bytes)
    if comm_s > max(compute_s, memory_s):
        rl["bound"] = "communication"
    else:
        rl["bound"] = ("compute" if cost.get("arithmetic_intensity", 0.0)
                       >= rl["ridge_flops_per_byte"] else "memory")
    cost["roofline"] = rl
    return cost


def static_cost(fn, *example_args, **make_jaxpr_kw) -> dict:
    """Trace ``fn`` at ``example_args`` (arrays or ``ShapeDtypeStruct``
    shells — nothing executes) and cost the resulting jaxpr. ``fn`` may be
    ``jax.jit``-wrapped; the walker recurses through the pjit eqn."""
    import jax  # noqa: PLC0415

    closed = jax.make_jaxpr(fn, **make_jaxpr_kw)(*example_args)
    return jaxpr_cost(closed)
