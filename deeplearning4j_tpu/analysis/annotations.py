"""Trace-context annotations for the AST pass.

The lint pass finds jit bodies from decorators (@jax.jit, @partial(jax.jit,
...)) and direct wraps (jax.jit(f), pl.pallas_call(kernel)). Kernels and
steps reached through indirection — functools.partial chains, tables of
functions, factory closures — are invisible to that scan, so they opt in
explicitly with :func:`jit_entry` (a runtime no-op the analyzer treats
exactly like @jax.jit).
"""

from __future__ import annotations

from typing import Callable, TypeVar

F = TypeVar("F", bound=Callable)

# names the AST pass accepts as jit-tracing decorators / wrappers
JIT_DECORATORS = {
    "jit", "pmap", "jit_entry",  # bare names
}
JIT_WRAPPERS = {
    "jit", "pmap", "pallas_call", "jit_entry",
}


def jit_entry(fn: F) -> F:
    """Mark ``fn`` as traced (executed under jit/pallas) for the analyzer.

    Returns ``fn`` unchanged — zero runtime cost, works on kernel bodies
    that must stay plain functions for pallas_call/functools.partial.
    """
    return fn
