"""Rule registry: every check has a DTxxx id, default severity and fix hint.

DT0xx = graph/config rules (pass 1), DT1xx = AST lint rules (pass 2),
DT2xx = jaxpr/HLO IR rules (pass 3 — what the compiler actually built),
DT3xx = sharding-flow rules (pass 4), DT4xx = runtime-guard rules
(pass 5 — concurrency, env hygiene and telemetry schema across the
serving/fleet/online stack). Register new rules with
:func:`register_rule`; the catalog drives ``--list-rules``,
docs/static_analysis.md, and pragma validation.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict

from .findings import Finding, Severity


@dataclass(frozen=True)
class Rule:
    rule_id: str
    title: str
    severity: Severity  # default; individual findings may downgrade
    scope: str  # "graph" | "ast"
    description: str
    hint: str

    def finding(self, message: str, *, severity: Severity = None, **kw) -> Finding:
        return Finding(
            rule_id=self.rule_id,
            severity=severity or self.severity,
            message=message,
            hint=kw.pop("hint", self.hint),
            **kw,
        )


RULES: Dict[str, Rule] = {}


def register_rule(rule: Rule) -> Rule:
    if rule.rule_id in RULES:
        raise ValueError(f"Duplicate rule id {rule.rule_id}")
    RULES[rule.rule_id] = rule
    return rule


def get_rule(rule_id: str) -> Rule:
    try:
        return RULES[rule_id]
    except KeyError:
        raise KeyError(
            f"Unknown rule id {rule_id!r}. Known: {', '.join(sorted(RULES))}"
        ) from None


# --------------------------------------------------------------- graph rules
register_rule(Rule(
    "DT001", "shape contract drift", "error", "graph",
    "A layer/vertex's declared get_output_type() disagrees with the shape "
    "jax.eval_shape traces through its apply() — the static shape algebra "
    "is lying about what XLA will actually build.",
    "Fix get_output_type() (or the layer's apply()) so the declared and "
    "traced shapes match; shape inference feeds preprocessor insertion and "
    "distributed sharding, so drift compounds downstream.",
))
register_rule(Rule(
    "DT002", "dtype contract drift", "error", "graph",
    "A layer/vertex output dtype differs from the configured compute dtype "
    "(e.g. accidental float64 promotion from a NumPy scalar, or a hardcoded "
    "float32 cast under a bfloat16 config).",
    "Keep constants weakly-typed (Python floats / jnp scalars), avoid "
    "np.float64 intermediates, and derive casts from x.dtype.",
))
register_rule(Rule(
    "DT003", "dim not padded to TPU lanes", "warning", "graph",
    "A feature/channel dim is not a multiple of the 128-wide TPU lane "
    "(VPU/MXU tile (8, 128)); XLA pads each such tensor, silently wasting "
    "compute and HBM bandwidth.",
    "Round hidden/channel sizes up to a multiple of 128 (or at least 8) "
    "when the model permits; padding waste scales with every op touching "
    "the tensor.",
))
register_rule(Rule(
    "DT004", "variable timesteps force recompiles", "warning", "graph",
    "A recurrent input declares timesteps=None (variable length): every "
    "distinct sequence length traces and compiles a fresh XLA program at "
    "runtime.",
    "Pad/bucket sequences to a fixed set of lengths (datasets/bucketing) "
    "and declare InputType.recurrent(size, timesteps=T) per bucket.",
))
register_rule(Rule(
    "DT005", "NCHW-shaped input suspected", "warning", "graph",
    "A convolutional input looks channels-first (tiny height, large "
    "channel count). This stack is NHWC-native on TPU; NCHW data fed as "
    "NHWC trains on scrambled pixels without any error.",
    "Declare InputType.convolutional(height, width, channels) in NHWC "
    "order and transpose the data once at ingest (x.transpose(0, 2, 3, 1)).",
))
register_rule(Rule(
    "DT006", "TPU-hostile compute dtype", "warning", "graph",
    "The configured compute dtype is float64: TPUs have no f64 ALU path — "
    "XLA emulates it in software at a massive slowdown.",
    "Use float32 (or bfloat16 for MXU-bound nets) as the compute dtype; "
    "keep float64 for offline gradient checks only.",
))
register_rule(Rule(
    "DT008", "sharding spec disagrees with the mesh", "error", "graph",
    "A declared PartitionSpec references a mesh axis that does not exist on "
    "the mesh it will be applied to (or uses one axis for two dimensions, "
    "exceeds the array rank, or shards a dimension the axis size does not "
    "divide): device_put/jit rejects it at dispatch time — or GSPMD "
    "silently falls back to full replication, training slower with no "
    "error.",
    "Create meshes and specs from one source of truth (parallel.make_mesh "
    "+ parallel.sharding.tree_shardings); validate hand-written specs with "
    "analysis.check_partition_specs(specs, mesh, params) before the first "
    "device_put.",
))
register_rule(Rule(
    "DT009", "cross-device transfer between consecutive vertices", "warning",
    "graph",
    "Consecutive layers/vertices are pinned to different device sets or "
    "shardings (or a jitted body calls jax.device_put/device_get): every "
    "training step pays a cross-device resharding transfer of the "
    "activations on that edge.",
    "Place consecutive vertices' params on ONE mesh (parallel/sharding."
    "shard_params) and let GSPMD insert collectives; inside jit use "
    "lax.with_sharding_constraint, never device_put — explicit transfers "
    "belong outside the step (e.g. DevicePrefetchIterator).",
))
register_rule(Rule(
    "DT007", "network output has no loss head", "info", "graph",
    "A network output layer/vertex is not an output (loss-bearing) layer; "
    "fit() will have no loss to differentiate.",
    "End trainable networks with OutputLayer/RnnOutputLayer/LossLayer "
    "(inference-only models can ignore this).",
))

# ----------------------------------------------------------------- AST rules
register_rule(Rule(
    "DT100", "unparseable source", "error", "ast",
    "The file could not be parsed as Python, so none of the AST checks ran "
    "on it.",
    "Fix the syntax error (the analyzer uses the running interpreter's "
    "grammar).",
))
register_rule(Rule(
    "DT101", "numpy call inside jit", "error", "ast",
    "np.* called inside a jit/pallas-traced body: NumPy executes at trace "
    "time on host — on traced values it either crashes (TracerArrayConversion) "
    "or silently bakes a constant into the compiled program.",
    "Use jnp.* inside traced code; np.* is fine only on static values "
    "(shapes, python ints) — suppress with # dl4jtpu: ignore[DT101] there.",
))
register_rule(Rule(
    "DT102", "host sync in traced/hot path", "error", "ast",
    ".item()/.tolist()/float()/int()/np.asarray() on a traced value blocks "
    "the host on the device queue — under jit it fails or constant-folds; "
    "in a train-step hot path it serializes every dispatch.",
    "Keep values on device; aggregate with jnp and sync once per logging "
    "interval outside the step function.",
))
register_rule(Rule(
    "DT103", "PRNG key reused", "error", "ast",
    "The same jax.random key is consumed by two or more random ops without "
    "an intervening split: both draw identical randomness (correlated "
    "dropout masks, identical init columns).",
    "jax.random.split the key once per consumer: k1, k2 = jax.random.split(key).",
))
register_rule(Rule(
    "DT104", "Python control flow on traced value", "warning", "ast",
    "if/while on a parameter of a jit-traced function: tracing a Python "
    "branch on a traced value raises TracerBoolConversionError, or silently "
    "specializes on the traced-time value if it is static-adjacent.",
    "Use lax.cond / lax.while_loop / jnp.where, or mark the argument "
    "static_argnums if it is genuinely static.",
))
register_rule(Rule(
    "DT105", "captured state mutated under jit", "error", "ast",
    "Assignment to self.*/global/nonlocal state inside a jit-traced body: "
    "the mutation happens once at trace time, then never again — cached "
    "executions silently skip it.",
    "Thread state functionally: take it as an argument, return the new "
    "value (see how layer state/rnn_state are threaded in nn/).",
))
register_rule(Rule(
    "DT106", "host side effect inside jit", "warning", "ast",
    "print()/logging inside a jit-traced body runs at trace time only (and "
    "prints tracers, not values); it vanishes from cached executions.",
    "Use jax.debug.print / jax.debug.callback for runtime values, or move "
    "logging outside the jitted function.",
))
register_rule(Rule(
    "DT107", "zero-copy view crosses a donation boundary", "error", "ast",
    "np.asarray()/np.array(..., copy=False) takes a zero-copy VIEW of a "
    "device buffer that is later passed through a donate_argnums boundary: "
    "donation lets the allocator recycle the buffer, silently rewriting the "
    "numpy view's contents (the nlp _sync_tables bug class fixed in PR 1).",
    "Materialize a real copy (np.array(x), no copy=False) before the "
    "donating call, or take the view only after the LAST donating call on "
    "that buffer.",
))
register_rule(Rule(
    "DT108", "lax.scan carry seeded with weak Python scalar", "warning", "ast",
    "A lax.scan carry component is initialized from a bare Python number: "
    "weakly-typed scalars take their dtype from the first loop operation, "
    "so the carry-out dtype can differ from the carry-in and scan fails "
    "with a carry-shape/dtype mismatch (or silently upcasts every step). "
    "The carry must be loop-invariant in shape AND dtype.",
    "Seed carry components as typed arrays: jnp.zeros((), dtype=x.dtype) / "
    "jnp.asarray(0.0, jnp.float32) instead of 0 / 0.0.",
))

# ------------------------------------------------------------------ IR rules
# Pass 3 operates on the traced jaxpr / lowered artifacts, so these findings
# carry no source line; suppress them with the ``ignore=`` argument of
# ``analyze_ir``/``conf.analyze(ir=True)`` or the CLI ``--ignore`` flag
# instead of line pragmas.
register_rule(Rule(
    "DT200", "silent float64 promotion in a traced step", "warning", "ir",
    "An eqn in the traced step produces a strongly-typed float64 result "
    "from non-float64 inputs (a NumPy f64 scalar constant, an explicit "
    "astype, or x64-mode promotion): from that point on the whole dataflow "
    "cone runs in software-emulated f64 on TPU.",
    "Keep constants weakly typed (Python floats / jnp scalars), never "
    "np.float64; derive casts from x.dtype. jax.config.jax_enable_x64 "
    "belongs in offline gradient checks only.",
))
register_rule(Rule(
    "DT201", "host callback inside a jitted step", "warning", "ir",
    "io_callback/pure_callback/debug_callback (incl. jax.debug.print) "
    "traced into the step function: every execution round-trips to the "
    "Python host, serializing the device queue — the per-step sync the "
    "whole staged path exists to avoid.",
    "Move host I/O outside the step (telemetry's K-step fetch pattern); "
    "keep jax.debug.* for debugging sessions, not training code.",
))
register_rule(Rule(
    "DT202", "requested donation dropped by the compiler", "warning", "ir",
    "An argument was donated (donate_argnums) but no output matches its "
    "shape/dtype, so the donation is silently dropped: params/optimizer "
    "state stay double-buffered and the step pays peak HBM for two copies.",
    "Make the donated argument's update an OUTPUT with identical "
    "shape/dtype (thread it through the step), or stop donating it; "
    "audit with analysis.audit_donation(fn, args, donate_argnums=...).",
))
register_rule(Rule(
    "DT203", "materialization blow-up", "warning", "ir",
    "An eqn materializes an output orders of magnitude larger than its "
    "operands (broadcast/outer-product/one-hot style): if XLA fails to "
    "fuse it, the temporary alone can blow the HBM budget.",
    "Reformulate to keep the big intermediate virtual (e.g. einsum the "
    "factors directly, use jnp.take instead of one-hot @ table), or remat "
    "the region; check memory_report()/the executable's temp bytes.",
))
register_rule(Rule(
    "DT204", "gather/scatter with traced indices", "warning", "ir",
    "A gather/scatter eqn consumes indices that are traced values: dynamic "
    "addressing defeats TPU vectorization — XLA serializes it through "
    "scalar cores or worse, one DMA per row.",
    "Prefer dense formulations (one-hot matmul for small vocabularies, "
    "masked select_n), sort indices host-side, or accept it knowingly "
    "(embedding lookups) via ignore=(\"DT204\",).",
))
register_rule(Rule(
    "DT205", "padding waste above threshold", "warning", "ir",
    "The BucketedStager's power-of-two buckets padded this epoch far past "
    "the real data: more than the threshold fraction of staged elements "
    "(hence FLOPs) were padding.",
    "Pick bucket boundaries closer to the real length distribution "
    "(BucketedStager(time_boundaries=...)), sort/batch by length upstream, "
    "or reduce the stage window so partial tails pad less.",
))
register_rule(Rule(
    "DT206", "step projected memory-bound", "info", "ir",
    "The step's arithmetic intensity (FLOPs/HBM byte, un-fused upper-bound "
    "traffic) sits below the configured roofline ridge point: the MXU will "
    "stall on HBM no matter how the schedule shakes out.",
    "Raise intensity: bigger batch, bf16 compute/params, fuse more steps "
    "per dispatch (fit_on_device), remat instead of materializing. Tune "
    "the roofline via DL4JTPU_PEAK_FLOPS / DL4JTPU_HBM_GBPS.",
))
# ------------------------------------------------------ sharding-flow rules
# Pass 4 (analysis/shard_flow.py): static sharding propagation over the
# traced jaxpr, seeded with a MeshLayout's PartitionSpecs. Predicts the
# collectives GSPMD will insert BEFORE anything compiles; findings carry no
# source line (suppress via ignore=/--ignore like the DT2xx family). The
# predicted census is validated against the measured post-SPMD HLO census
# (BENCH_MODEL=shard, tests/test_shard_flow.py).
register_rule(Rule(
    "DT300", "implicit full all-gather of a sharded tensor", "warning", "ir",
    "Sharding propagation predicts GSPMD will materialize the FULL tensor "
    "from a sharded one (an activation gathered at a dot whose contraction "
    "dim it shards, a reshape/slice that breaks the sharded dim, ...): the "
    "per-device HBM saving the spec promised is silently gone for that "
    "tensor, and the gather bytes move over ICI every step.",
    "Re-spec the producing layer so consumer and producer agree (shard a "
    "kept dim, not the contraction dim), or add an explicit "
    "lax.with_sharding_constraint at the site; the ZeRO param all-gather "
    "under fsdp is expected and exempt.",
))
register_rule(Rule(
    "DT301", "producer/consumer sharding reshard", "warning", "ir",
    "Two operands of one eqn arrive with incompatible shardings (the same "
    "mesh axis on different dims): GSPMD inserts a resharding transfer of "
    "the smaller operand between producer and consumer, every step.",
    "Emit both tensors under ONE layout rule (parallel.MeshLayout) instead "
    "of hand-placing them; inside jit, align specs with "
    "lax.with_sharding_constraint at the producer.",
))
register_rule(Rule(
    "DT302", "oversized contraction all-reduce", "warning", "ir",
    "A contraction over a non-batch-axis-sharded dim (tensor-parallel "
    "matmul) all-reduces an ACTIVATION-sized payload every step — larger "
    "than any gradient sync, and it scales with batch x features, not with "
    "the model. This is the Megatron lesson: tp layouts live or die on "
    "which activations get all-reduced.",
    "Pair column-parallel with row-parallel projections so only one "
    "all-reduce survives per block, shard the other operand's kept dim, or "
    "drop tp for this layer (fsdp alone avoids activation collectives).",
))
register_rule(Rule(
    "DT303", "batch axis dropped — compute replicated", "warning", "ir",
    "Propagation predicts the batch axis is gathered off an activation "
    "(a reshape merging batch into features, a spec conflict resolved "
    "against the batch dim): everything downstream runs identically on "
    "every device — the parallel speedup silently becomes 1x.",
    "Keep the batch dim major through reshapes (reshape (B,T,F)->(B*T,F) "
    "keeps it; (T,B,F)->(T*B,F) does not), and check the layout's "
    "batch_sharding() reaches the loss.",
))
register_rule(Rule(
    "DT304", "per-step collective inside scan", "warning", "ir",
    "A collective sits inside a scan body, so it runs once per TIME STEP, "
    "not once per optimizer step: the payload multiplies by the trip count "
    "(T x per step), and each one is a latency-bound small transfer — the "
    "worst shape for ICI.",
    "Hoist the resharding out of the loop (gather/reshard once before the "
    "scan), make the offending operand a loop-invariant const, or re-spec "
    "so the carry stays sharded the same way the body produces it.",
))
register_rule(Rule(
    "DT305", "head-aware tp spec would eliminate this collective", "info",
    "ir",
    "The layout shards attention/LSTM-gate kernels over their flat last dim "
    "(the generic tp rule), splitting heads/gates across devices: the "
    "predicted census shows per-step tp collectives on those activations "
    "that a head-aware spec (whole heads/gates per device) would not need.",
    "Shard the head dim (reshape kernels to [in, heads, d_head] and spec "
    "P(None, 'tp', None)) or gate dim for LSTM kernels, so each device "
    "computes whole heads locally — the ROADMAP 'head-aware tp specs' item.",
))
register_rule(Rule(
    "DT306", "per-microbatch collective inside a pipeline stage", "warning",
    "ir",
    "A non-pipe-axis collective inside the pipelined region repeats once "
    "per micro-batch tick (the piped twin of DT304): with M micro-batches "
    "the payload multiplies by M per optimizer step, each a latency-bound "
    "small transfer riding the same ICI the stage handoffs need.",
    "Hoist it above the schedule's tick loop — e.g. all-gather fsdp-sharded "
    "stage params ONCE per step before the micro-batch loop (the transpose "
    "becomes one reduce-scatter of the stage gradient), not inside the "
    "stage body.",
))

# ------------------------------------------------------ runtime-guard rules
# Pass 5 (analysis/concurrency.py + analysis/runtime_checks.py): AST lint
# over the multi-threaded serving/fleet/online stack. Thread-entry discovery
# (Thread targets, HTTP do_* handlers, watchdog/batcher sinks, public methods
# of lock-owning classes) feeds a per-class attribute read/write census with
# ``with self._lock`` context tracking. Findings carry source lines, so the
# usual ``# dl4jtpu: ignore[DT4xx]`` pragmas apply.
register_rule(Rule(
    "DT400", "shared attribute raced across thread entries", "warning",
    "runtime",
    "A mutable attribute is written from one thread entry point and "
    "read/written from another with no common lock held (or read-modified-"
    "written without any lock inside a handler/callback entry that can run "
    "concurrently with itself): lost updates, torn reads, and "
    "mutated-during-iteration crashes on the stats/snapshot paths.",
    "Guard every access to the attribute with ONE lock (the owning class's "
    "existing lock where present); for counters, increment under the lock; "
    "for rings/lists, snapshot under the lock before iterating.",
))
register_rule(Rule(
    "DT401", "blocking call while holding a lock", "warning", "runtime",
    "A blocking operation (time.sleep, HTTP, subprocess, unbounded "
    "queue.get, Future.result, device fetch/compile, rnn_time_step, "
    "socket recv/accept) runs while a lock is held: every other thread "
    "contending for that lock stalls behind the slow operation — on a "
    "serving hot path this serializes the whole request fleet.",
    "Move the blocking call outside the ``with lock:`` block (snapshot the "
    "state you need under the lock, then release before blocking); if the "
    "lock deliberately serializes a single-threaded resource (e.g. one "
    "stateful net), say so with # dl4jtpu: ignore[DT401].",
))
register_rule(Rule(
    "DT402", "inconsistent lock acquisition order", "warning", "runtime",
    "Two locks are acquired in nested ``with`` blocks in one order on one "
    "code path and the opposite order on another: two threads taking one "
    "lock each then waiting for the other deadlock the process.",
    "Pick one global order for the pair (document it where the locks are "
    "created) and re-nest the second path; or collapse the critical "
    "sections so only one lock spans both.",
))
register_rule(Rule(
    "DT403", "raw os.environ mutation outside EnvScope", "warning",
    "runtime",
    "os.environ is written/deleted directly (subscript assignment, pop, "
    "update, clear, putenv): the prior value — including its absence — is "
    "lost, so the process leaks config state across trials, tests and "
    "serving rollouts.",
    "Mutate env vars only through tune.EnvScope / tune.scoped_env, which "
    "record the prior state and restore it bit-identically on exit; the "
    "EnvScope implementation itself carries the justified ignore pragma.",
))
register_rule(Rule(
    "DT404", "bare time.sleep outside resilience policies", "warning",
    "runtime",
    "time.sleep() pauses a thread with no deadline, no stop-event and no "
    "pacing accounting: shutdown hangs for the residual sleep, tests slow "
    "down by the worst case, and the wait is invisible to the resilience "
    "stats. (AST successor to the old check.sh grep gate.)",
    "Use runtime.resilience primitives: Deadline(t).pace(interval, "
    "stop=event) for poll loops, DeadlinePolicy(...).start().wait_event(ev) "
    "for waits, event.wait(timeout) for plain delays; genuinely intentional "
    "sleeps take # dl4jtpu: ignore[DT404] with a reason.",
))
register_rule(Rule(
    "DT405", "trace-unsafe global mutation from a thread entry", "warning",
    "runtime",
    "jax.config updates, kernel set_site_override calls, or module-global "
    "rebinding reachable from a thread/handler entry point: compiled "
    "executables already cached ignore the new value, executables compiled "
    "after it embed it — the fleet serves from two configs at once.",
    "Apply process-global config once at startup (before warmup) from the "
    "main thread; per-request variation must be threaded as arguments, "
    "not globals (see tune.EnvScope for env-read knobs).",
))
register_rule(Rule(
    "DT406", "telemetry schema drift", "warning", "runtime",
    "A dl4jtpu_* metric name is declared twice with a different type or "
    "label set, or a flight-recorder event is recorded with a kind that no "
    "module registered: dashboards silently split series and replay "
    "tooling drops the unregistered events.",
    "Declare each metric once (one owner module) and reuse the handle; "
    "register new flight-event kinds with "
    "telemetry.flight_recorder.register_event_kind at import time.",
))

register_rule(Rule(
    "DT207", "per-step collective volume", "info", "ir",
    "The step contains cross-device collectives (psum/all_gather/"
    "ppermute/...); the estimated payload moves over ICI/DCN on EVERY "
    "optimizer step and scales with the mesh, not the batch.",
    "Expected for data-parallel gradients — verify the volume matches "
    "2*param_bytes; anything larger suggests resharding inside the step "
    "(check DT009 and with_sharding_constraint placement).",
))

# ---------------------------------------------------------- numerics rules
# DT5xx = numerics pass (pass 6 — analysis/numerics.py): dtype-flow +
# value-range abstract interpretation over the traced train step.
register_rule(Rule(
    "DT500", "low-precision accumulation", "warning", "numerics",
    "A dot_general/conv/reduce accumulates in bf16/f16: the MXU (and the "
    "XLA reduce emitter) carry the running sum at the operand precision "
    "when no wider preferred_element_type is requested, so every partial "
    "product below the accumulator's ulp is silently dropped — at bf16's "
    "8 mantissa bits a sum of ~256 same-sign terms stops growing.",
    "Pass preferred_element_type=jnp.float32 to dot_general/conv (free on "
    "the MXU: it accumulates in f32 natively); for reduces, cast the "
    "input to f32 before the sum and round the result back.",
))
register_rule(Rule(
    "DT501", "low-precision loop carry", "warning", "numerics",
    "A scan/while carry is held in bf16/f16 and rewritten every "
    "iteration: rounding error compounds once per step across the whole "
    "trip (the LSTM-state / streaming-statistics drift shape) — after N "
    "steps the carry has ~log2(N) fewer good bits than one rounding.",
    "Keep an f32 island for the carry: upcast at loop entry, accumulate "
    "in f32, round to the storage dtype once at loop exit (storage-dtype "
    "params/moments under a declared PrecisionPolicy are exempt — their "
    "per-step update already computes in f32).",
))
register_rule(Rule(
    "DT502", "optimizer update below compute dtype", "warning", "numerics",
    "Gradients or optimizer moments are combined in arithmetic below the "
    "declared PrecisionPolicy compute dtype at an update site: a bf16 "
    "`p + lr*u` drops any update smaller than ~0.4%% of the weight, so "
    "small late-training gradients stop moving the model entirely.",
    "Run the optimizer update in an f32 island (upcast grads/moments/"
    "params, tx.update + apply_updates in f32, round back to the storage "
    "dtype) — nn.updaters.optimizer_update does exactly this.",
))
register_rule(Rule(
    "DT503", "unguarded domain hazard", "warning", "numerics",
    "An exp/log/div/sqrt/rsqrt input's propagated value interval admits "
    "overflow, log(<=0), sqrt of a negative, or a divide-through-zero "
    "with no clamp between the producer and the hazard: one such element "
    "turns the loss into inf/NaN and the Watchdog can only roll back "
    "after the damage.",
    "Clamp the input just before the hazard: jnp.clip(x, EPS, hi) for "
    "log, jnp.maximum(d, EPS) for divisors, jnp.maximum(v, 0.0) before "
    "sqrt/rsqrt; bound exp arguments (subtract-max, or clip the "
    "logits/log-variance like the VAE's +/-10 window).",
))
register_rule(Rule(
    "DT504", "softmax without subtract-max", "warning", "numerics",
    "A softmax-shaped expression (exp(x) normalized by its own sum) is "
    "not dominated by a subtract-max: exp overflows at x>~88 in f32 "
    "(x>~11 in f16), so one hot logit makes the whole row inf/inf = NaN.",
    "Use the stable form exp(x - max(x)) / sum(exp(x - max(x))) — "
    "jax.nn.softmax/log_softmax and ops.softmax_xent_rows already do "
    "this; a clamp that provably bounds the exponent also satisfies the "
    "check.",
))
register_rule(Rule(
    "DT505", "sub-f32 grad flow without loss scaling", "info", "numerics",
    "Parameters (hence gradients, via the cast transpose) are stored "
    "below f32 but no loss scale is configured: backward-pass values "
    "smaller than the storage dtype's tiniest subnormal (~9e-41 for "
    "bf16, ~6e-8 for f16) flush to zero before the optimizer sees them.",
    "Set the policy knob: MeshLayout(params_dtype=..., loss_scale=...) / "
    "PrecisionPolicy(loss_scale=...) / conf.loss_scale — a power-of-two "
    "scale multiplies the loss before backward and is divided back out "
    "in f32 before the update, bit-exact when nothing clips.",
))
