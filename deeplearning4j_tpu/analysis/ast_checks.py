"""Pass 2: AST lint for JAX/TPU pitfalls.

Trace-context discovery first: a function body is considered *traced*
("jit body") when it is

- decorated with ``@jax.jit`` / ``@jit`` / ``@jax.pmap`` /
  ``@functools.partial(jax.jit, ...)`` / ``@jit_entry`` (analysis.annotations),
- wrapped at a call site — ``jax.jit(f)``, ``pl.pallas_call(kernel, ...)``,
  ``pallas_call(functools.partial(kernel, ...), ...)``, or
- lexically nested inside a traced function.

Inside traced bodies the pass hunts np.* calls (DT101), host syncs
(DT102), Python control flow on traced parameters (DT104), mutation of
captured state (DT105) and print/logging side effects (DT106). PRNG key
reuse (DT103) is checked in *every* function — reusing a key is wrong
whether or not the call is traced. Two whole-scope dataflow rules run
everywhere too: DT107 (a zero-copy ``np.asarray`` view taken before the
viewed buffer crosses a ``donate_argnums`` boundary — donation recycles
the buffer and rewrites the view) and DT108 (``lax.scan`` carry seeded
with bare Python scalars, whose weak dtype can drift between carry-in
and carry-out).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .annotations import JIT_DECORATORS, JIT_WRAPPERS
from .findings import Finding
from .pragmas import filter_findings
from .rules import get_rule

# jax.random.* that do NOT consume a key's randomness
_NONCONSUMING = {
    "split", "fold_in", "PRNGKey", "key", "key_data", "wrap_key_data",
    "clone", "key_impl",
}
# attribute reads that make a traced value static (shape algebra)
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding"}
_LOGGING_NAMES = {"logging", "logger", "log"}


def _full_name(node: ast.AST) -> str:
    """Dotted name of a Name/Attribute chain ('' when not a plain chain)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _last(name: str) -> str:
    return name.rsplit(".", 1)[-1]


def _decorator_is_jit(dec: ast.AST) -> bool:
    if _last(_full_name(dec)) in JIT_DECORATORS:
        return True
    if isinstance(dec, ast.Call):
        head = _full_name(dec.func)
        if _last(head) in JIT_DECORATORS:  # @jax.jit(static_argnums=...)
            return True
        if _last(head) == "partial" and dec.args:  # @partial(jax.jit, ...)
            return _last(_full_name(dec.args[0])) in JIT_DECORATORS
    return False


def _wrapped_function_names(call: ast.Call) -> List[str]:
    """Function names passed into jax.jit(f) / pallas_call(kernel) /
    pallas_call(functools.partial(kernel, ...))."""
    if _last(_full_name(call.func)) not in JIT_WRAPPERS:
        return []
    names = []
    for arg in call.args[:1]:  # the traced callable is the first argument
        if isinstance(arg, ast.Name):
            names.append(arg.id)
        elif isinstance(arg, ast.Call):
            if _last(_full_name(arg.func)) == "partial" and arg.args:
                inner = arg.args[0]
                if isinstance(inner, ast.Name):
                    names.append(inner.id)
    return names


class _Index(ast.NodeVisitor):
    """Collect functions, their nesting, jax.random aliases and jit marks."""

    def __init__(self):
        self.functions: List[ast.FunctionDef] = []
        self.parents: Dict[ast.AST, Optional[ast.AST]] = {}
        self.by_name: Dict[str, List[ast.FunctionDef]] = {}
        self.jit_marked: Set[ast.AST] = set()
        self.random_aliases: Set[str] = set()
        self._stack: List[ast.AST] = []

    def generic_visit(self, node):
        for child in ast.iter_child_nodes(node):
            self.parents[child] = self._stack[-1] if self._stack else None
        is_fn = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        if is_fn:
            self.functions.append(node)
            self.by_name.setdefault(node.name, []).append(node)
            if any(_decorator_is_jit(d) for d in node.decorator_list):
                self.jit_marked.add(node)
            self._stack.append(node)
        if isinstance(node, ast.Call):
            for fname in _wrapped_function_names(node):
                for fn in self.by_name.get(fname, []):
                    self.jit_marked.add(fn)
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "jax.random":
                    self.random_aliases.add(alias.asname or "jax")
        if isinstance(node, ast.ImportFrom):
            if node.module == "jax":
                for alias in node.names:
                    if alias.name == "random":
                        self.random_aliases.add(alias.asname or "random")
        super().generic_visit(node)
        if is_fn:
            self._stack.pop()

    def resolve_nesting(self):
        """A function nested in a jit body is itself traced. Wrap calls can
        appear after the def, so iterate to a fixed point."""
        changed = True
        while changed:
            changed = False
            for fn in self.functions:
                if fn in self.jit_marked:
                    continue
                p = self.parents.get(fn)
                while p is not None:
                    if p in self.jit_marked:
                        self.jit_marked.add(fn)
                        changed = True
                        break
                    p = self.parents.get(p)


def _is_jax_random_call(call: ast.Call, aliases: Set[str]) -> Optional[str]:
    """Return the jax.random function name when ``call`` is one, else None."""
    name = _full_name(call.func)
    if not name:
        return None
    head, _, fn = name.rpartition(".")
    if head == "jax.random":
        return fn
    if head and head in aliases:
        return fn
    if head.endswith(".random") and head.split(".")[0] in aliases:
        return fn
    return None


def _key_arg_name(call: ast.Call) -> Optional[str]:
    if call.args and isinstance(call.args[0], ast.Name):
        return call.args[0].id
    for kw in call.keywords:
        if kw.arg == "key" and isinstance(kw.value, ast.Name):
            return kw.value.id
    return None


def _assigned_names(node: ast.AST) -> Iterable[str]:
    if isinstance(node, ast.Name):
        yield node.id
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            yield from _assigned_names(elt)
    elif isinstance(node, ast.Starred):
        yield from _assigned_names(node.value)


class _KeyReuseSim:
    """DT103: abstract interpretation of one function/module scope.

    Tracks which key variables have been consumed along each control-flow
    path. Branches of an if/try are simulated independently; the consumed
    sets of the paths that *fall through* are INTERSECTED afterwards, so a
    scheme-dispatch chain of mutually exclusive `if ...: return draw(key)`
    arms (one consumption per call) stays clean while straight-line double
    draws are flagged. Paths ending in return/raise/break/continue do not
    merge back.
    """

    def __init__(self, aliases: Set[str], filename: str):
        self.aliases = aliases
        self.filename = filename
        self.findings: List[Finding] = []

    # -- expression-level events, in source order
    def _expr_events(self, node: ast.AST):
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                continue  # nested scopes get their own pass
            if isinstance(sub, ast.Call):
                fn = _is_jax_random_call(sub, self.aliases)
                if fn and fn not in _NONCONSUMING:
                    key = _key_arg_name(sub)
                    if key:
                        yield sub, key

    def _consume(self, consumed: Dict[str, int], node: ast.AST):
        for call, key in sorted(
            self._expr_events(node),
            key=lambda e: (e[0].lineno, e[0].col_offset),
        ):
            if key in consumed:
                self.findings.append(get_rule("DT103").finding(
                    f"PRNG key '{key}' already consumed at line "
                    f"{consumed[key]} — both draws return identical "
                    "randomness",
                    file=self.filename, line=call.lineno,
                    col=call.col_offset, context=key,
                ))
            else:
                consumed[key] = call.lineno

    def _assign(self, consumed: Dict[str, int], target: ast.AST):
        for name in _assigned_names(target):
            consumed.pop(name, None)

    @staticmethod
    def _merge(branches: List[Optional[Dict[str, int]]]) -> Dict[str, int]:
        """Intersect consumed-sets of fall-through branches (None = path
        terminated); all-terminated yields an empty (unreachable) state."""
        live = [b for b in branches if b is not None]
        if not live:
            return {}
        keys = set(live[0])
        for b in live[1:]:
            keys &= set(b)
        return {k: live[0][k] for k in keys}

    def run(self, body: List[ast.stmt],
            consumed: Dict[str, int]) -> Optional[Dict[str, int]]:
        """Simulate a statement list; returns the fall-through consumed set,
        or None when every path terminates."""
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.Return, ast.Raise)):
                self._consume(consumed, stmt)
                return None
            if isinstance(stmt, (ast.Break, ast.Continue)):
                return None
            if isinstance(stmt, ast.If):
                self._consume(consumed, stmt.test)
                then = self.run(stmt.body, dict(consumed))
                other = self.run(stmt.orelse, dict(consumed))
                consumed = self._merge([then, other])
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._consume(consumed, stmt.iter)
                self._assign(consumed, stmt.target)
                loop = self.run(stmt.body, dict(consumed))
                tail = self.run(stmt.orelse, dict(consumed))
                consumed = self._merge([loop, tail, consumed])
                continue
            if isinstance(stmt, ast.While):
                self._consume(consumed, stmt.test)
                loop = self.run(stmt.body, dict(consumed))
                tail = self.run(stmt.orelse, dict(consumed))
                consumed = self._merge([loop, tail, consumed])
                continue
            if isinstance(stmt, ast.Try):
                tried = self.run(stmt.body, dict(consumed))
                paths = [tried]
                for handler in stmt.handlers:
                    paths.append(self.run(handler.body, dict(consumed)))
                paths.append(self.run(stmt.orelse, dict(consumed)))
                merged = self._merge(paths + [consumed])
                fin = self.run(stmt.finalbody, merged)
                consumed = merged if fin is None else fin
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._consume(consumed, item.context_expr)
                inner = self.run(stmt.body, consumed)
                if inner is None:
                    return None
                consumed = inner
                continue
            # simple statement: uses first, then assignment resets
            self._consume(consumed, stmt)
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    self._assign(consumed, t)
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                self._assign(consumed, stmt.target)
        return consumed


def _check_key_reuse(scope_body: List[ast.stmt], aliases: Set[str],
                     filename: str) -> List[Finding]:
    sim = _KeyReuseSim(aliases, filename)
    sim.run(scope_body, {})
    return sim.findings


def _is_zero_copy_view(call: ast.Call) -> bool:
    """np.asarray(x) / np.array(x, copy=False): a (potential) zero-copy view."""
    name = _full_name(call.func)
    head, _, fn = name.rpartition(".")
    if head not in ("np", "numpy") or not call.args:
        return False
    if fn == "asarray":
        # an explicit dtype can force a copy only when it differs; stay
        # conservative and treat dtype-less asarray as the view case
        return not any(kw.arg == "copy" for kw in call.keywords)
    if fn == "array":
        return any(
            kw.arg == "copy" and isinstance(kw.value, ast.Constant)
            and kw.value.value is False
            for kw in call.keywords
        )
    return False


def _donating_callables(tree: ast.AST) -> Set[str]:
    """Names bound (anywhere in the file) to a jit with donate_argnums:
    ``f = jax.jit(g, donate_argnums=...)`` assignments and functions
    decorated ``@partial(jax.jit, donate_argnums=...)``."""

    def _call_donates(call: ast.Call) -> bool:
        head = _last(_full_name(call.func))
        if head in ("jit", "pmap"):
            return any(kw.arg == "donate_argnums" for kw in call.keywords)
        if head == "partial" and call.args:
            if _last(_full_name(call.args[0])) in ("jit", "pmap"):
                return any(kw.arg == "donate_argnums" for kw in call.keywords)
        return False

    donating: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _call_donates(node.value):
                for t in node.targets:
                    name = _full_name(t)
                    if name:
                        donating.add(name)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) and _call_donates(dec):
                    donating.add(node.name)
    return donating


class _DonationAliasScan:
    """DT107: one scope's statement-ordered dataflow. Tracks zero-copy view
    sources; a later call of a donating callable on a viewed source means
    the donated buffer may be recycled under the live numpy view."""

    def __init__(self, donating: Set[str], filename: str):
        self.donating = donating
        self.filename = filename
        self.findings: List[Finding] = []

    def run(self, body: List[ast.stmt]) -> None:
        views: Dict[str, int] = {}  # viewed source name -> view line
        for stmt in body:
            self._stmt(stmt, views)

    def _stmt(self, stmt: ast.stmt, views: Dict[str, int]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested scopes run their own pass
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            name = _full_name(node.func)
            if name in self.donating:
                arg_names = [_full_name(a) for a in node.args] + [
                    _full_name(kw.value) for kw in node.keywords
                ]
                for an in arg_names:
                    if an and an in views:
                        self.findings.append(get_rule("DT107").finding(
                            f"'{an}' is donated here but a zero-copy view "
                            f"of it was taken at line {views[an]}; donation "
                            "recycles the buffer and silently rewrites the "
                            "view",
                            file=self.filename, line=node.lineno,
                            col=node.col_offset, context=an,
                        ))
                        views.pop(an, None)  # one report per view
        if isinstance(stmt, ast.Assign):
            viewed = (
                _full_name(stmt.value.args[0])
                if isinstance(stmt.value, ast.Call)
                and _is_zero_copy_view(stmt.value) and stmt.value.args
                else ""
            )
            for t in stmt.targets:
                tname = _full_name(t)
                # rebinding a name breaks any alias recorded against it
                views.pop(tname, None)
            if viewed:
                views[viewed] = stmt.lineno
        # recurse into compound statements in order (approximate: branches
        # merge by union — a view on any path stays suspect)
        for attr in ("body", "orelse", "finalbody"):
            for sub in getattr(stmt, attr, []) or []:
                self._stmt(sub, views)
        for handler in getattr(stmt, "handlers", []) or []:
            for sub in handler.body:
                self._stmt(sub, views)


def _check_donation_aliasing(tree: ast.AST, index: "_Index",
                             filename: str) -> List[Finding]:
    donating = _donating_callables(tree)
    if not donating:
        return []
    findings: List[Finding] = []
    scan = _DonationAliasScan(donating, filename)
    scan.run(tree.body)
    for fn in index.functions:
        scan.run(fn.body)
    findings += scan.findings
    return findings


_SCAN_HEADS = ("lax", "jax.lax")


def _bare_scalars(node: ast.AST):
    """Bare numeric literals inside a carry-init expression — descends only
    through tuple/list structure and unary minus, never into calls (a shape
    literal in jnp.zeros((4, 8)) is not a carry component)."""
    if isinstance(node, ast.Constant) and type(node.value) in (int, float):
        yield node
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            yield from _bare_scalars(elt)
    elif isinstance(node, ast.UnaryOp) and isinstance(node.op,
                                                      (ast.USub, ast.UAdd)):
        yield from _bare_scalars(node.operand)


def _check_scan_carry(tree: ast.AST, filename: str) -> List[Finding]:
    """DT108: lax.scan carry initialized from weakly-typed Python scalars."""
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _full_name(node.func)
        head, _, fn = name.rpartition(".")
        if fn != "scan" or (head not in _SCAN_HEADS
                            and not head.endswith(".lax")):
            continue
        init = None
        if len(node.args) >= 2:
            init = node.args[1]
        else:
            init = next((kw.value for kw in node.keywords
                         if kw.arg == "init"), None)
        if init is None:
            continue
        for const in _bare_scalars(init):
            findings.append(get_rule("DT108").finding(
                f"lax.scan carry component seeded with bare Python scalar "
                f"{const.value!r}: its weak dtype is set by the first loop "
                "op and can differ from the carry-out dtype",
                file=filename, line=const.lineno, col=const.col_offset,
                context="scan carry",
            ))
    return findings


def _test_uses_traced_param(test: ast.AST, params: Set[str]) -> Optional[str]:
    """A param referenced in a branch test, ignoring static uses
    (x.shape/x.ndim/..., isinstance(x, ...), x is None)."""
    skip: Set[ast.AST] = set()
    for node in ast.walk(test):
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            for sub in ast.walk(node.value):
                skip.add(sub)
        elif isinstance(node, ast.Call):
            head = _last(_full_name(node.func))
            if head in ("isinstance", "len", "getattr", "hasattr", "callable"):
                for sub in ast.walk(node):
                    skip.add(sub)
        elif isinstance(node, ast.Compare):
            cmps = [node.left] + list(node.comparators)
            if any(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                for c in cmps:
                    for sub in ast.walk(c):
                        skip.add(sub)
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and node.id in params and node not in skip:
            return node.id
    return None


# annotations that mark a parameter as a static Python scalar, not a traced
# array (kernel convention: `block_k: int, causal: bool` are partial-bound)
_STATIC_ANNOTATIONS = {"bool", "int", "float", "str"}


def _param_names(fn: ast.FunctionDef) -> Set[str]:
    """Parameters that may carry traced values (annotated static scalars and
    self excluded)."""
    a = fn.args
    params = list(a.posonlyargs + a.args + a.kwonlyargs)
    if a.vararg:
        params.append(a.vararg)
    if a.kwarg:
        params.append(a.kwarg)
    names = set()
    for p in params:
        if p.arg == "self":
            continue
        ann = getattr(p, "annotation", None)
        if ann is not None and _last(_full_name(ann)) in _STATIC_ANNOTATIONS:
            continue
        names.add(p.arg)
    return names


def _check_jit_body(fn: ast.FunctionDef, filename: str) -> List[Finding]:
    """DT101/102/104/105/106 inside one traced function body."""
    findings: List[Finding] = []
    params = _param_names(fn)
    globals_nonlocals: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            globals_nonlocals.update(node.names)
    ctx = fn.name
    for node in ast.walk(fn):
        loc = dict(file=filename, line=getattr(node, "lineno", 0),
                   col=getattr(node, "col_offset", 0), context=ctx)
        if isinstance(node, ast.Call):
            name = _full_name(node.func)
            head = name.split(".", 1)[0]
            if head in ("np", "numpy") and "." in name:
                findings.append(get_rule("DT101").finding(
                    f"{name}() inside jit body '{ctx}' executes on host at "
                    "trace time", **loc))
            elif _last(name) in ("device_put", "device_get"):
                # DT009 (AST half): an explicit transfer inside a traced
                # body executes on EVERY step — resharding belongs to
                # lax.with_sharding_constraint, staging outside the step
                findings.append(get_rule("DT009").finding(
                    f"{name}() inside jit body '{ctx}' forces a cross-device "
                    "transfer every step", **loc))
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("item", "tolist"):
                findings.append(get_rule("DT102").finding(
                    f".{node.func.attr}() inside jit body '{ctx}' forces a "
                    "host sync", **loc))
            elif isinstance(node.func, ast.Name) and \
                    node.func.id in ("float", "int", "bool") and \
                    len(node.args) == 1 and \
                    not isinstance(node.args[0], ast.Constant):
                findings.append(get_rule("DT102").finding(
                    f"{node.func.id}() on a traced value inside jit body "
                    f"'{ctx}' forces a host sync", **loc))
            elif isinstance(node.func, ast.Name) and node.func.id == "print":
                findings.append(get_rule("DT106").finding(
                    f"print() inside jit body '{ctx}' runs at trace time "
                    "only", **loc))
            elif head in _LOGGING_NAMES and "." in name:
                findings.append(get_rule("DT106").finding(
                    f"{name}() inside jit body '{ctx}' runs at trace time "
                    "only", **loc))
        elif isinstance(node, (ast.If, ast.While)):
            used = _test_uses_traced_param(node.test, params)
            if used is not None:
                kind = "if" if isinstance(node, ast.If) else "while"
                findings.append(get_rule("DT104").finding(
                    f"Python `{kind}` on traced parameter '{used}' in jit "
                    f"body '{ctx}'", **loc))
        elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Attribute):
                    base = _full_name(t.value)
                    if base.split(".", 1)[0] == "self":
                        findings.append(get_rule("DT105").finding(
                            f"assignment to {_full_name(t)} inside jit body "
                            f"'{ctx}' mutates captured state at trace time "
                            "only", **loc))
                for nm in _assigned_names(t):
                    if nm in globals_nonlocals:
                        findings.append(get_rule("DT105").finding(
                            f"assignment to global/nonlocal '{nm}' inside "
                            f"jit body '{ctx}' happens at trace time only",
                            **loc))
    return findings


def check_source(source: str, filename: str = "<source>") -> List[Finding]:
    """Lint one Python source string; pragma-filtered findings."""
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        return [get_rule("DT100").finding(
            f"could not parse: {e.msg}", file=filename,
            line=e.lineno or 1, col=(e.offset or 1) - 1,
        )]
    index = _Index()
    index.visit(tree)
    index.resolve_nesting()

    findings: List[Finding] = []
    # DT103 in every scope (module + each function)
    findings += _check_key_reuse(tree.body, index.random_aliases, filename)
    for fn in index.functions:
        findings += _check_key_reuse(fn.body, index.random_aliases, filename)
    # whole-scope dataflow rules, traced or not
    findings += _check_donation_aliasing(tree, index, filename)
    findings += _check_scan_carry(tree, filename)
    # traced-body rules; nested jit functions are reached via their own
    # entry in jit_marked, so dedup on (rule, line, col)
    seen: Set[Tuple[str, int, int]] = set()
    for fn in index.jit_marked:
        for f in _check_jit_body(fn, filename):
            k = (f.rule_id, f.line, f.col)
            if k not in seen:
                seen.add(k)
                findings.append(f)
    return filter_findings(findings, source)


def check_file(path: str) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        return check_source(fh.read(), filename=path)
