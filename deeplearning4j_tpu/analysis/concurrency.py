"""Pass 5a: concurrency lint over the threaded runtime stack (DT400-DT402,
DT405).

Thread-entry discovery first: a function is an *entry point* when it is

- a ``threading.Thread(target=...)`` / ``Timer`` / executor ``submit``
  target (non-reentrant: one thread per start),
- a callback/sink handed to another component (``on_*=``, ``sink=``,
  ``callback=`` kwargs, ``add_sink(...)`` args, or a ``*_sink`` method —
  reentrant: the owner may invoke it from several threads),
- a ``do_*`` method of an ``http.server`` request-handler class
  (reentrant: ThreadingHTTPServer runs one thread per request), or
- a public method of a class that owns a lock or spawns threads
  (reentrant: any thread may call into it).

Entries close transitively over same-module calls (``self._helper()``,
bare functions, uniquely-named methods), so a helper's accesses belong to
every entry that reaches it. A per-class attribute census from
``__init__`` classifies attributes (lock / sync primitive / queue /
container / scalar; ``Condition(self._lock)`` aliases to the wrapped
lock), and a lock-context walk over each function records which locks are
held at every attribute access — that census powers:

- **DT400** — attribute written from one entry and touched from another
  with no common lock, or read-modified-written lock-free inside a
  reentrant entry. Plain scalar assignment/read is treated as an atomic
  publish and stays clean; container iteration/mutation does not.
- **DT401** — blocking call (sleep, HTTP, subprocess, unbounded
  ``queue.get``, ``Future.result``, device fetch/compile, ``join``)
  while holding a lock. ``cond.wait()`` on the lock being held is exempt
  (it releases the lock).
- **DT402** — two locks nested in opposite orders on different paths.
- **DT405** — trace-unsafe global mutation (``jax.config`` updates,
  ``set_site_override``, ``global`` rebinds) reachable from an entry.

All findings are line-anchored, so ``# dl4jtpu: ignore[DT4xx]`` pragmas
apply as in pass 2.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, NamedTuple, Optional, Set, Tuple

from .ast_checks import _full_name, _last
from .findings import Finding, sort_findings
from .pragmas import filter_findings
from .rules import get_rule

__all__ = ["check_concurrency_source", "check_concurrency_file"]

_LOCK_CTORS = {"Lock", "RLock"}
_SYNC_CTORS = {
    "Lock", "RLock", "Condition", "Event", "Semaphore", "BoundedSemaphore",
    "Barrier",
}
_QUEUE_CTORS = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue"}
_CONTAINER_CTORS = {
    "deque", "list", "dict", "set", "OrderedDict", "defaultdict", "Counter",
}
_CONTAINER_LITERALS = (
    ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp,
)
_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "add", "pop", "popleft",
    "clear", "update", "setdefault", "remove", "discard", "insert",
    "popitem",
}
_ITERATING = {
    "list", "tuple", "set", "dict", "frozenset", "sorted", "sum", "max",
    "min", "len", "any", "all", "extend", "percentile", "mean", "median",
    "asarray", "array",
}
_THREAD_CTORS = {"Thread", "Timer"}
_CALLBACK_KWARGS = {"sink", "sinks", "callback", "callbacks", "target"}
_SINK_REGISTRARS = {"add_sink", "register_sink", "add_callback",
                    "add_done_callback"}
_HANDLER_BASES = {
    "BaseHTTPRequestHandler", "SimpleHTTPRequestHandler",
    "StreamRequestHandler", "BaseRequestHandler",
}
# method names too generic to resolve on a non-self receiver (they are
# almost always dict/list/thread-primitive methods, not module methods)
_GENERIC_METHODS = {
    "get", "pop", "update", "clear", "items", "keys", "values", "append",
    "extend", "add", "remove", "discard", "insert", "put", "read", "write",
    "copy", "count", "index", "sort", "reverse", "setdefault", "popitem",
    "join", "split", "strip", "format", "encode", "decode", "wait",
    "notify", "notify_all", "acquire", "release", "set", "is_set",
    "qsize", "empty", "full", "get_nowait", "put_nowait", "close", "flush",
}
_BLOCKING_LASTS = {
    "urlopen", "communicate", "block_until_ready", "device_get",
    "rnn_time_step", "fit_on_device", "readline", "accept", "recv",
    "connect", "create_connection", "wait_event", "pace", "aot", "result",
}
_REQUESTS_VERBS = {"get", "post", "put", "delete", "head", "request"}
_SUBPROCESS_BLOCKING = {"run", "call", "check_output", "check_call"}

LockId = Tuple[str, str]  # (owner class or "<module>", canonical attr/name)


class _ClassCensus:
    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.name = node.name
        self.lock_attrs: Dict[str, str] = {}  # attr -> canonical lock attr
        self.sync_attrs: Set[str] = set()
        self.queue_attrs: Set[str] = set()
        self.container_attrs: Set[str] = set()
        self.scalar_attrs: Set[str] = set()
        self.methods: Dict[str, ast.FunctionDef] = {}
        self.is_handler = any(
            _last(_full_name(base)) in _HANDLER_BASES for base in node.bases)
        self.spawns_threads = False

    def data_kind(self, attr: str) -> Optional[str]:
        if attr in self.container_attrs:
            return "container"
        if attr in self.scalar_attrs:
            return "scalar"
        return None

    def owns(self, attr: str) -> bool:
        return (attr in self.lock_attrs or attr in self.sync_attrs
                or attr in self.queue_attrs or attr in self.container_attrs
                or attr in self.scalar_attrs)


class _Access(NamedTuple):
    cls: str
    attr: str
    kind: str  # "write" | "read"
    rmw: bool
    locks: FrozenSet[LockId]
    line: int
    col: int


class _Blocking(NamedTuple):
    desc: str
    lock: str
    line: int
    col: int


class _Mutation(NamedTuple):  # DT405 candidate
    desc: str
    line: int
    col: int


def _classify_init_value(value: ast.AST) -> Tuple[str, Optional[str]]:
    """('lock'|'sync'|'queue'|'container'|'scalar', condition-alias)."""
    if isinstance(value, ast.Call):
        ctor = _last(_full_name(value.func))
        if ctor in _LOCK_CTORS:
            return "lock", None
        if ctor == "Condition":
            alias = None
            if value.args:
                wrapped = _full_name(value.args[0])
                if wrapped.startswith("self."):
                    alias = wrapped.split(".", 1)[1]
            return "lock", alias
        if ctor in _QUEUE_CTORS:
            return "queue", None
        if ctor in _SYNC_CTORS:
            return "sync", None
        if ctor in _CONTAINER_CTORS:
            return "container", None
        return "scalar", None
    if isinstance(value, _CONTAINER_LITERALS):
        return "container", None
    return "scalar", None


class _Module:
    """Census + entry discovery + call graph for one parsed module."""

    def __init__(self, tree: ast.Module, filename: str):
        self.tree = tree
        self.filename = filename
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.functions: List[ast.FunctionDef] = [
            n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        self.by_name: Dict[str, List[ast.FunctionDef]] = {}
        for fn in self.functions:
            self.by_name.setdefault(fn.name, []).append(fn)
        self.classes: Dict[str, _ClassCensus] = {}
        self.module_locks: Set[str] = set()
        self.imports: Set[str] = set()
        self._build_census()
        # attr -> owning class (unique across module; ambiguous names drop)
        self.data_owner: Dict[str, _ClassCensus] = {}
        self.lock_owner: Dict[str, _ClassCensus] = {}
        self.queue_owner: Dict[str, _ClassCensus] = {}
        self._build_owner_maps()
        self.entries: Dict[ast.FunctionDef, Set[str]] = {}
        self._discover_entries()
        self.edges: Dict[ast.FunctionDef, Set[ast.FunctionDef]] = {}
        self._build_call_graph()

    # -- census ------------------------------------------------------------
    def _build_census(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                for alias in node.names:
                    self.imports.add((alias.asname or alias.name).split(".")[0])
            if isinstance(node, ast.ClassDef):
                census = _ClassCensus(node)
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        census.methods[item.name] = item
                init = census.methods.get("__init__")
                if init is not None:
                    self._census_init(census, init)
                self.classes[node.name] = census
        for stmt in self.tree.body:
            if isinstance(stmt, ast.Assign) and isinstance(stmt.value,
                                                           ast.Call):
                ctor = _last(_full_name(stmt.value.func))
                if ctor in _LOCK_CTORS | {"Condition"}:
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            self.module_locks.add(t.id)

    def _census_init(self, census: _ClassCensus,
                     init: ast.FunctionDef) -> None:
        for node in ast.walk(init):
            target = value = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                target, value = node.target, node.value
            if (not isinstance(target, ast.Attribute)
                    or _full_name(target.value) != "self"):
                continue
            attr = target.attr
            if census.owns(attr):
                continue
            kind, alias = _classify_init_value(value)
            if kind == "lock":
                canonical = attr
                if alias is not None:
                    canonical = census.lock_attrs.get(alias, alias)
                census.lock_attrs[attr] = canonical
            elif kind == "sync":
                census.sync_attrs.add(attr)
            elif kind == "queue":
                census.queue_attrs.add(attr)
            elif kind == "container":
                census.container_attrs.add(attr)
            else:
                census.scalar_attrs.add(attr)

    def _build_owner_maps(self) -> None:
        seen: Dict[str, int] = {}
        for census in self.classes.values():
            for attr in (census.container_attrs | census.scalar_attrs
                         | set(census.lock_attrs) | census.sync_attrs
                         | census.queue_attrs):
                seen[attr] = seen.get(attr, 0) + 1
        for census in self.classes.values():
            for attr in census.container_attrs | census.scalar_attrs:
                if seen[attr] == 1:
                    self.data_owner[attr] = census
            for attr in census.lock_attrs:
                if seen[attr] == 1:
                    self.lock_owner[attr] = census
            for attr in census.queue_attrs:
                if seen[attr] == 1:
                    self.queue_owner[attr] = census

    # -- structural lookups ------------------------------------------------
    def enclosing_class(self, node: ast.AST) -> Optional[_ClassCensus]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return self.classes.get(cur.name)
            cur = self.parents.get(cur)
        return None

    def enclosing_function(self, node: ast.AST) -> Optional[ast.FunctionDef]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            if isinstance(cur, ast.ClassDef):
                return None
            cur = self.parents.get(cur)
        return None

    def display(self, fn: ast.FunctionDef) -> str:
        cls = self.enclosing_class(fn)
        return f"{cls.name}.{fn.name}" if cls else fn.name

    # -- entry discovery ---------------------------------------------------
    def _resolve_callable(self, expr: ast.AST,
                          site: ast.AST) -> List[ast.FunctionDef]:
        if isinstance(expr, ast.Lambda):
            out: List[ast.FunctionDef] = []
            for call in ast.walk(expr.body):
                if isinstance(call, ast.Call):
                    out.extend(self._resolve_callable(call.func, site))
            return out
        name = _full_name(expr)
        if not name:
            return []
        if name.startswith("self."):
            parts = name.split(".")
            cls = self.enclosing_class(site)
            if len(parts) == 2 and cls and parts[1] in cls.methods:
                return [cls.methods[parts[1]]]
            return []
        if "." in name:
            return []
        return list(self.by_name.get(name, []))

    def _mark(self, fns: List[ast.FunctionDef], kind: str) -> None:
        for fn in fns:
            self.entries.setdefault(fn, set()).add(kind)

    def _discover_entries(self) -> None:
        for call in ast.walk(self.tree):
            if not isinstance(call, ast.Call):
                continue
            fname = _full_name(call.func)
            ctor = _last(fname)
            if ctor in _THREAD_CTORS:
                cls = self.enclosing_class(call)
                if cls is not None:
                    cls.spawns_threads = True
                for kw in call.keywords:
                    if kw.arg == "target":
                        self._mark(self._resolve_callable(kw.value, call),
                                   "thread")
                if ctor == "Timer" and len(call.args) >= 2:
                    self._mark(self._resolve_callable(call.args[1], call),
                               "thread")
                continue
            if ctor == "submit" and call.args:
                self._mark(self._resolve_callable(call.args[0], call),
                           "thread")
            if ctor in _SINK_REGISTRARS:
                for arg in call.args:
                    self._mark(self._resolve_callable(arg, call), "callback")
            for kw in call.keywords:
                if kw.arg and (kw.arg.startswith("on_")
                               or kw.arg in _CALLBACK_KWARGS):
                    self._mark(self._resolve_callable(kw.value, call),
                               "callback")
        for census in self.classes.values():
            for mname, fn in census.methods.items():
                if mname.endswith("_sink") or mname == "sink":
                    self._mark([fn], "callback")
                if census.is_handler and mname.startswith("do_"):
                    self._mark([fn], "handler")
        for census in self.classes.values():
            qualifies = (bool(census.lock_attrs) or census.spawns_threads
                         or any(fn in self.entries
                                for fn in census.methods.values()))
            if not qualifies:
                continue
            for mname, fn in census.methods.items():
                if mname.startswith("_") or fn in self.entries:
                    continue
                self.entries.setdefault(fn, set()).add("public")

    def reentrant(self, fn: ast.FunctionDef) -> bool:
        return any(k != "thread" for k in self.entries.get(fn, ()))

    # -- call graph --------------------------------------------------------
    def _build_call_graph(self) -> None:
        method_owner: Dict[str, List[ast.FunctionDef]] = {}
        for census in self.classes.values():
            for mname, fn in census.methods.items():
                method_owner.setdefault(mname, []).append(fn)
        for fn in self.functions:
            targets: Set[ast.FunctionDef] = set()
            for node in ast.walk(fn):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and node is not fn \
                        and self.enclosing_function(node) is fn \
                        and node not in self.entries:
                    targets.add(node)  # nested helper runs on this thread
                if not isinstance(node, ast.Call):
                    continue
                fname = _full_name(node.func)
                if not fname:
                    continue
                if fname.startswith("self."):
                    parts = fname.split(".")
                    cls = self.enclosing_class(fn)
                    if len(parts) == 2 and cls and parts[1] in cls.methods:
                        targets.add(cls.methods[parts[1]])
                        continue
                if "." in fname:
                    mname = _last(fname)
                    head = fname.split(".")[0]
                    if (mname not in _GENERIC_METHODS
                            and head not in self.imports
                            and len(method_owner.get(mname, ())) >= 1):
                        targets.update(method_owner.get(mname, ()))
                elif fname in self.by_name and fname not in self.imports:
                    targets.update(self.by_name[fname])
            self.edges[fn] = targets

    def reaching_entries(self) -> Dict[ast.FunctionDef,
                                       List[ast.FunctionDef]]:
        reach: Dict[ast.FunctionDef, List[ast.FunctionDef]] = {}
        for entry in self.entries:
            stack, seen = [entry], {entry}
            while stack:
                fn = stack.pop()
                reach.setdefault(fn, []).append(entry)
                for nxt in self.edges.get(fn, ()):
                    if nxt not in seen:
                        seen.add(nxt)
                        stack.append(nxt)
        return reach


class _FunctionScan:
    """Lock-context walk of one function body: attribute accesses, blocking
    calls under locks, nested lock-acquisition edges, DT405 candidates."""

    def __init__(self, module: _Module, fn: ast.FunctionDef):
        self.module = module
        self.fn = fn
        self.cls = module.enclosing_class(fn)
        self.accesses: List[_Access] = []
        self.blocking: List[_Blocking] = []
        self.acq_edges: List[Tuple[LockId, LockId, int, int]] = []
        self.mutations: List[_Mutation] = []
        self.globals: Set[str] = set()
        self._walk_stmts(fn.body, frozenset())

    # -- resolution --------------------------------------------------------
    def _resolve_data(self, node: ast.AST) -> Optional[Tuple[str, str, str]]:
        """(class, attr, 'container'|'scalar') for a census'd attribute."""
        if not isinstance(node, ast.Attribute):
            return None
        base = _full_name(node.value)
        attr = node.attr
        if base == "self" and self.cls is not None:
            kind = self.cls.data_kind(attr)
            if kind:
                return (self.cls.name, attr, kind)
            return None
        if not base or base.split(".")[0] in self.module.imports:
            return None
        owner = self.module.data_owner.get(attr)
        if owner is not None:
            return (owner.name, attr, owner.data_kind(attr))
        return None

    def _lock_id(self, expr: ast.AST) -> Optional[LockId]:
        name = _full_name(expr)
        if not name:
            return None
        if "." not in name:
            if name in self.module.module_locks:
                return ("<module>", name)
            return None
        base, attr = name.rsplit(".", 1)
        if base == "self" and self.cls and attr in self.cls.lock_attrs:
            return (self.cls.name, self.cls.lock_attrs[attr])
        owner = self.module.lock_owner.get(attr)
        if owner is not None and base.split(".")[0] not in self.module.imports:
            return (owner.name, owner.lock_attrs[attr])
        return None

    def _is_queue_attr(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Attribute):
            return False
        base = _full_name(node.value)
        attr = node.attr
        if base == "self" and self.cls is not None:
            return attr in self.cls.queue_attrs
        return attr in self.module.queue_owner

    # -- recording ---------------------------------------------------------
    def _record(self, resolved, kind: str, rmw: bool, node: ast.AST,
                held: FrozenSet[LockId]) -> None:
        cls, attr, _ = resolved
        self.accesses.append(_Access(cls, attr, kind, rmw, held,
                                     node.lineno, node.col_offset))

    # -- statement walk ----------------------------------------------------
    def _walk_stmts(self, stmts, held: FrozenSet[LockId]) -> None:
        for stmt in stmts:
            self._walk_stmt(stmt, held)

    def _walk_stmt(self, stmt: ast.stmt, held: FrozenSet[LockId]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # scanned as their own functions / class bodies
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            acquired: List[LockId] = []
            for item in stmt.items:
                lid = self._lock_id(item.context_expr)
                if lid is not None:
                    for outer in held | frozenset(acquired):
                        if outer != lid:
                            self.acq_edges.append(
                                (outer, lid, stmt.lineno, stmt.col_offset))
                    acquired.append(lid)
                else:
                    self._scan_expr(item.context_expr, held)
            self._walk_stmts(stmt.body, held | frozenset(acquired))
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            resolved = self._resolve_data(stmt.iter)
            if resolved and resolved[2] == "container":
                self._record(resolved, "read", False, stmt.iter, held)
            self._scan_expr(stmt.iter, held)
            self._walk_stmts(stmt.body, held)
            self._walk_stmts(stmt.orelse, held)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._scan_expr(stmt.test, held)
            self._walk_stmts(stmt.body, held)
            self._walk_stmts(stmt.orelse, held)
            return
        if isinstance(stmt, ast.Try):
            self._walk_stmts(stmt.body, held)
            for handler in stmt.handlers:
                self._walk_stmts(handler.body, held)
            self._walk_stmts(stmt.orelse, held)
            self._walk_stmts(stmt.finalbody, held)
            return
        if isinstance(stmt, ast.Global):
            self.globals.update(stmt.names)
            return
        if isinstance(stmt, ast.Assign):
            for target in stmt.targets:
                self._scan_target(target, held)
            self._scan_expr(stmt.value, held)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._scan_target(stmt.target, held)
                self._scan_expr(stmt.value, held)
            return
        if isinstance(stmt, ast.AugAssign):
            resolved = self._resolve_data(stmt.target)
            if resolved:
                self._record(resolved, "write", True, stmt, held)
            elif isinstance(stmt.target, ast.Subscript):
                inner = self._resolve_data(stmt.target.value)
                if inner:
                    self._record(inner, "write", True, stmt, held)
            elif (isinstance(stmt.target, ast.Name)
                  and stmt.target.id in self.globals):
                self.mutations.append(_Mutation(
                    f"augmented assignment to global '{stmt.target.id}'",
                    stmt.lineno, stmt.col_offset))
            self._scan_expr(stmt.value, held)
            return
        if isinstance(stmt, ast.Delete):
            for target in stmt.targets:
                if isinstance(target, ast.Subscript):
                    inner = self._resolve_data(target.value)
                    if inner:
                        self._record(inner, "write", True, target, held)
            return
        # Return/Expr/Assert/Raise/...: scan all contained expressions
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._scan_expr(child, held)

    def _scan_target(self, target: ast.AST,
                     held: FrozenSet[LockId]) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._scan_target(elt, held)
            return
        if isinstance(target, ast.Attribute):
            resolved = self._resolve_data(target)
            # reassigning a shared container swaps it under readers; a plain
            # scalar rebind is an atomic publish and stays clean
            if resolved and resolved[2] == "container":
                self._record(resolved, "write", False, target, held)
            fname = _full_name(target)
            if fname.startswith("jax.config."):
                self.mutations.append(_Mutation(
                    f"assignment to {fname}", target.lineno,
                    target.col_offset))
            return
        if isinstance(target, ast.Subscript):
            inner = self._resolve_data(target.value)
            if inner:
                self._record(inner, "write", True, target, held)
            self._scan_expr(target.value, held)
            if isinstance(target.slice, ast.expr):
                self._scan_expr(target.slice, held)
            return
        if isinstance(target, ast.Name) and target.id in self.globals:
            self.mutations.append(_Mutation(
                f"rebind of global '{target.id}'", target.lineno,
                target.col_offset))

    def _scan_expr(self, expr: ast.AST, held: FrozenSet[LockId]) -> None:
        if expr is None or isinstance(expr, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
            return
        if isinstance(expr, ast.Call):
            self._scan_call(expr, held)
            return
        if isinstance(expr, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            for gen in expr.generators:
                resolved = self._resolve_data(gen.iter)
                if resolved and resolved[2] == "container":
                    self._record(resolved, "read", False, gen.iter, held)
            for child in ast.iter_child_nodes(expr):
                self._scan_expr(child, held)
            return
        if isinstance(expr, ast.Subscript) and isinstance(expr.ctx, ast.Load):
            resolved = self._resolve_data(expr.value)
            if resolved and resolved[2] == "container":
                self._record(resolved, "read", False, expr, held)
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, (ast.expr, ast.comprehension, ast.keyword)):
                self._scan_expr(
                    child.value if isinstance(child, ast.keyword) else child,
                    held)

    def _scan_call(self, call: ast.Call, held: FrozenSet[LockId]) -> None:
        fname = _full_name(call.func)
        last = _last(fname)
        # container mutation through a method: self.ring.append(x)
        if isinstance(call.func, ast.Attribute) and last in _MUTATORS:
            resolved = self._resolve_data(call.func.value)
            if resolved:
                self._record(resolved, "write", True, call, held)
        # iteration-shaped reads: list(self.ring), sorted(entry.latencies)
        if last in _ITERATING:
            for arg in call.args:
                resolved = self._resolve_data(arg)
                if resolved and resolved[2] == "container":
                    self._record(resolved, "read", False, arg, held)
        # DT405 candidates (attributed to entries later)
        if fname.startswith("jax.config.") or last == "set_site_override":
            self.mutations.append(_Mutation(
                f"call to {fname or last}", call.lineno, call.col_offset))
        if held:
            self._check_blocking(call, fname, last, held)
        for child in ast.iter_child_nodes(call):
            self._scan_expr(child, held)

    def _check_blocking(self, call: ast.Call, fname: str, last: str,
                        held: FrozenSet[LockId]) -> None:
        desc = None
        head = fname.split(".")[0] if fname else ""
        if fname == "time.sleep" or (last == "sleep" and head == "time"):
            desc = "time.sleep"
        elif last in _BLOCKING_LASTS:
            desc = fname or last
        elif head == "requests" and last in _REQUESTS_VERBS:
            desc = fname
        elif head == "subprocess" and last in _SUBPROCESS_BLOCKING:
            desc = fname
        elif (last == "join" and isinstance(call.func, ast.Attribute)
              and not call.args and not call.keywords):
            desc = f"{fname or last}"
        elif (last == "wait" and isinstance(call.func, ast.Attribute)
              and not call.args and not call.keywords):
            receiver = self._lock_id(call.func.value)
            if receiver is None or receiver not in held:
                desc = f"{fname or last}"
        elif last == "get" and isinstance(call.func, ast.Attribute):
            if self._is_queue_attr(call.func.value):
                bounded = (len(call.args) >= 2 or any(
                    kw.arg in ("timeout", "block") for kw in call.keywords))
                if not bounded:
                    desc = f"{fname or 'queue.get'}"
        if desc is not None:
            lock = sorted(f"{c}.{a}" for c, a in held)[0]
            self.blocking.append(
                _Blocking(desc, lock, call.lineno, call.col_offset))


def _check_tree(tree: ast.Module, filename: str) -> List[Finding]:
    module = _Module(tree, filename)
    findings: List[Finding] = []
    scans: Dict[ast.FunctionDef, _FunctionScan] = {}
    for fn in module.functions:
        if fn.name in ("__init__", "__post_init__", "__del__"):
            continue  # construction/teardown is single-threaded
        scans[fn] = _FunctionScan(module, fn)

    reach = module.reaching_entries()

    # ---- DT400: per-attribute cross-entry census
    per_attr: Dict[Tuple[str, str],
                   List[Tuple[ast.FunctionDef, ast.FunctionDef,
                              _Access]]] = {}
    for fn, scan in scans.items():
        entries = reach.get(fn)
        if not entries:
            continue
        for acc in scan.accesses:
            per_attr.setdefault((acc.cls, acc.attr), []).extend(
                (entry, fn, acc) for entry in entries)
    rule400 = get_rule("DT400")
    for (cls, attr), recs in sorted(per_attr.items()):
        writes = [r for r in recs if r[2].kind == "write"]
        if not writes:
            continue
        fired = False
        for w_entry, w_fn, w_acc in writes:
            for a_entry, a_fn, a_acc in recs:
                if a_entry is w_entry:
                    continue
                if w_acc.locks & a_acc.locks:
                    continue
                findings.append(rule400.finding(
                    f"'{cls}.{attr}' is written in "
                    f"'{module.display(w_fn)}' (entry "
                    f"'{module.display(w_entry)}') and accessed in "
                    f"'{module.display(a_fn)}' (entry "
                    f"'{module.display(a_entry)}', line {a_acc.line}) with "
                    f"no common lock",
                    file=filename, line=w_acc.line, col=w_acc.col,
                    context=f"{cls}.{attr}"))
                fired = True
                break
            if fired:
                break
        if fired:
            continue
        for w_entry, w_fn, w_acc in writes:
            if w_acc.rmw and not w_acc.locks and module.reentrant(w_entry):
                findings.append(rule400.finding(
                    f"'{cls}.{attr}' is read-modified-written without a "
                    f"lock in '{module.display(w_fn)}', reachable from "
                    f"entry '{module.display(w_entry)}' which can run "
                    f"concurrently with itself",
                    file=filename, line=w_acc.line, col=w_acc.col,
                    context=f"{cls}.{attr}"))
                break

    # ---- DT401: blocking while locked (any function, entry or not)
    rule401 = get_rule("DT401")
    for fn, scan in scans.items():
        for block in scan.blocking:
            findings.append(rule401.finding(
                f"blocking call {block.desc}() in '{module.display(fn)}' "
                f"while holding lock '{block.lock}'",
                file=filename, line=block.line, col=block.col,
                context=module.display(fn)))

    # ---- DT402: lock-order inversions (module-global)
    rule402 = get_rule("DT402")
    edges: Dict[Tuple[LockId, LockId], Tuple[int, int, str]] = {}
    for fn, scan in scans.items():
        for outer, inner, line, col in scan.acq_edges:
            edges.setdefault((outer, inner),
                             (line, col, module.display(fn)))
    for (outer, inner), (line, col, where) in sorted(edges.items()):
        if (inner, outer) in edges:
            rline, _, rwhere = edges[(inner, outer)]
            findings.append(rule402.finding(
                f"lock '{outer[0]}.{outer[1]}' is taken before "
                f"'{inner[0]}.{inner[1]}' in '{where}' but after it in "
                f"'{rwhere}' (line {rline}): opposite orders can deadlock",
                file=filename, line=line, col=col,
                context=f"{outer[0]}.{outer[1]}<->{inner[0]}.{inner[1]}"))

    # ---- DT405: trace-unsafe global mutation from entries
    rule405 = get_rule("DT405")
    for fn, scan in scans.items():
        entries = reach.get(fn)
        if not entries:
            continue
        names = sorted({module.display(e) for e in entries})
        for mut in scan.mutations:
            findings.append(rule405.finding(
                f"{mut.desc} in '{module.display(fn)}' is reachable from "
                f"thread entry "
                f"{', '.join(repr(n) for n in names[:3])}: executables "
                f"compiled before and after it disagree",
                file=filename, line=mut.line, col=mut.col,
                context=module.display(fn)))

    return findings


def check_concurrency_source(source: str,
                             filename: str = "<source>") -> List[Finding]:
    """DT400-DT402 + DT405 over one module's source."""
    try:
        tree = ast.parse(source)
    except SyntaxError as exc:
        return [get_rule("DT100").finding(
            f"could not parse: {exc.msg}", file=filename,
            line=exc.lineno or 0, col=exc.offset or 0)]
    findings = sort_findings(_check_tree(tree, filename))
    return filter_findings(findings, source)


def check_concurrency_file(path: str) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    return check_concurrency_source(source, filename=path)
