"""Pass 3: IR lint (DT2xx) over the traced jaxpr + compiled artifacts.

PR 1's passes stop at Python AST and layer-graph level; this pass asks the
question neither can answer — *what did the compiler actually do to the step
function?* It traces the real train step with ``jax.make_jaxpr`` over
``ShapeDtypeStruct`` shells (zero device dispatches — proven by a
counting-tracer test) and walks the eqns:

- **DT200** strong float64 appearing from non-f64 inputs (silent promotion)
- **DT201** host callbacks traced into the step
- **DT202** requested buffer donation the compiler will drop (audited by
  replaying jax's own shape/dtype output-matching over the donated avals)
- **DT203** materialization blow-ups (output ≫ operands)
- **DT204** gather/scatter with traced (non-constant) indices — constness
  is propagated forward AND across nested-jaxpr boundaries (a baked numpy
  index array threaded into a scanned/pjit sub-jaxpr stays constant)
- **DT205** padding waste from the BucketedStager's pow2 buckets vs the
  real batch statistics of an epoch
- **DT206** arithmetic intensity below the roofline ridge (memory-bound)
- **DT207** per-step collective count + payload volume

The static roofline numbers come from :mod:`.cost_model`; the compile
manager calls :func:`admission_check` on every AOT executable it admits
(findings → ``dl4jtpu_ir_findings_total{rule}`` + flight-recorder events,
cost reports next to the PR 4 memory records), and ``preflight()`` folds the
same report in so "donation dropped, step predicted HBM-bound" arrives
before the first real dispatch.

IR findings carry no source line, so line pragmas cannot suppress them; use
the ``ignore=("DT204", ...)`` argument (or the CLI ``--ignore`` flag).
"""

from __future__ import annotations

from typing import Any, Iterable, List, Optional, Sequence, Tuple

from .cost_model import jaxpr_cost, subjaxprs
from .findings import Finding, merge_findings
from .rules import get_rule

__all__ = [
    "check_jaxpr_ir",
    "audit_donation",
    "check_network_ir",
    "analyze_config_ir",
    "check_padding_waste",
    "record_findings",
    "ir_findings_family",
    "admission_check",
]

IR_SOURCE = "<ir>"

# DT203 thresholds: an eqn only counts as a blow-up when its output is BOTH
# this many times bigger than its operands AND big in absolute terms (tiny
# bias broadcasts are free — XLA fuses them)
DT203_RATIO = 8.0
DT203_FLOOR_BYTES = 32 << 20  # 32 MiB

# DT205 default: warn when >30% of staged elements were padding
DT205_THRESHOLD = 0.30

_CALLBACK_PRIMS = {"pure_callback", "io_callback", "debug_callback"}


def _fmt_bytes(n: float) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0:
            return f"{n:.1f} {unit}"
        n /= 1024.0
    return f"{n:.1f} TiB"


def _is_strong_f64(aval) -> bool:
    import numpy as np

    dt = getattr(aval, "dtype", None)
    return (dt is not None and dt == np.dtype("float64")
            and not getattr(aval, "weak_type", False))


def _is_f64(aval) -> bool:
    import numpy as np

    dt = getattr(aval, "dtype", None)
    return dt is not None and dt == np.dtype("float64")


def _nested_const_invars(eqn, nested, const_flags):
    """Map constness across a call boundary: for each ``(sub, mult)`` in
    ``nested`` (the :func:`subjaxprs` output for ``eqn``), the set of the
    sub-jaxpr's invars that receive a trace-time constant.

    ``const_flags[i]`` says whether ``eqn.invars[i]`` is constant in the
    enclosing jaxpr. Primitive-specific layouts:

    - ``scan``: invars are ``[*consts, *carry, *xs]``; consts map 1:1 and a
      constant stacked ``xs`` array stays constant per-slice, but the carry
      mutates across iterations and is never propagated.
    - ``while``: ``[*cond_consts, *body_consts, *carry]``; each sub-jaxpr
      sees its own consts followed by the (non-const) carry.
    - ``cond``: ``[pred, *operands]``; every branch sees the operands.
    - generic wrappers (pjit/remat/custom_*): 1:1 when the arities match,
      conservatively nothing otherwise.
    """
    name = eqn.primitive.name
    out = []
    if name == "scan":
        n_consts = int(eqn.params.get("num_consts", 0))
        n_carry = int(eqn.params.get("num_carry", 0))
        for sub, _ in nested:
            iv = sub.jaxpr.invars
            cs = set()
            for j in range(min(n_consts, len(iv), len(const_flags))):
                if const_flags[j]:
                    cs.add(iv[j])
            base = n_consts + n_carry
            for k in range(base, min(len(iv), len(const_flags))):
                if const_flags[k]:
                    cs.add(iv[k])
            out.append(cs)
        return out
    if name == "while":
        cn = int(eqn.params.get("cond_nconsts", 0))
        bn = int(eqn.params.get("body_nconsts", 0))
        offsets = []
        if eqn.params.get("cond_jaxpr") is not None:
            offsets.append((0, cn))
        if eqn.params.get("body_jaxpr") is not None:
            offsets.append((cn, bn))
        for (off, n), (sub, _) in zip(offsets, nested):
            iv = sub.jaxpr.invars
            cs = set()
            for j in range(min(n, len(iv))):
                if off + j < len(const_flags) and const_flags[off + j]:
                    cs.add(iv[j])
            out.append(cs)
        return out
    if name == "cond":
        for sub, _ in nested:
            iv = sub.jaxpr.invars
            cs = {v for j, v in enumerate(iv)
                  if 1 + j < len(const_flags) and const_flags[1 + j]}
            out.append(cs)
        return out
    for sub, _ in nested:
        iv = sub.jaxpr.invars
        cs = ({v for v, flag in zip(iv, const_flags) if flag}
              if len(iv) == len(const_flags) else set())
        out.append(cs)
    return out


def _iter_leaf_eqns(closed):
    """Yield ``(eqn, const_derived)`` for every leaf eqn (no nested jaxpr),
    recursing through pjit/scan/while/cond/remat wrappers.

    ``const_derived`` is the set of vars in the eqn's enclosing jaxpr that
    are trace-time constants — the constvars plus anything computed from
    constants alone (forward const propagation, so indices that pass
    through a ``convert_element_type`` of a baked numpy array still read as
    static). Constness crosses nested-jaxpr boundaries: a baked index array
    threaded into a scanned/cond/pjit sub-jaxpr as an argument arrives there
    as a constant (:func:`_nested_const_invars` maps the positions), closing
    the DT204 per-jaxpr limitation PR 5 shipped with.
    """
    from jax import core  # noqa: PLC0415

    stack = [(closed, frozenset())]
    seen = set()
    while stack:
        c, const_in = stack.pop()
        key = (id(c.jaxpr), tuple(sorted(id(v) for v in const_in)))
        if key in seen:
            continue
        seen.add(key)
        constish = set(c.jaxpr.constvars) | set(const_in)
        for eqn in c.jaxpr.eqns:
            nested = subjaxprs(eqn)
            if nested:
                flags = [isinstance(v, core.Literal) or v in constish
                         for v in eqn.invars]
                stack.extend(
                    (sub, frozenset(cs)) for (sub, _), cs in zip(
                        nested, _nested_const_invars(eqn, nested, flags)))
            else:
                yield eqn, constish
            if eqn.invars and all(
                    isinstance(v, core.Literal) or v in constish
                    for v in eqn.invars):
                constish.update(eqn.outvars)


# ------------------------------------------------------------- jaxpr checks
def check_jaxpr_ir(closed_jaxpr, *, source: str = IR_SOURCE,
                   cost: Optional[dict] = None,
                   blowup_ratio: float = DT203_RATIO,
                   blowup_floor_bytes: int = DT203_FLOOR_BYTES) -> List[Finding]:
    """DT200/201/203/204 over the eqns of a traced jaxpr, plus DT206/207
    from a :func:`~.cost_model.jaxpr_cost` report (computed here when not
    passed in). Findings are aggregated per (rule, primitive, signature) so
    a promotion repeated through the backward pass reads as ONE finding."""
    from .cost_model import _aval_bytes  # noqa: PLC0415 - shared helper

    findings: List[Finding] = []
    promo: dict = {}
    callbacks: dict = {}
    blowups: dict = {}
    dynamic_idx: dict = {}

    for eqn, const_derived in _iter_leaf_eqns(closed_jaxpr):
        name = eqn.primitive.name
        ins = [getattr(v, "aval", None) for v in eqn.invars]
        outs = [getattr(v, "aval", None) for v in eqn.outvars]

        # DT200: a strong f64 result from at least one non-f64 operand is
        # the promotion POINT; all-f64 eqns are downstream of one already.
        # Scalar results are exempt — x64-mode scalar bookkeeping (optax
        # bias correction etc.) runs on the scalar core for free; the
        # hazard is a promoted TENSOR dragging its dataflow cone to f64.
        from .cost_model import _aval_elems  # noqa: PLC0415

        if ins and any(not _is_f64(a) for a in ins) and any(
                _is_strong_f64(o) and _aval_elems(o) > 1 for o in outs):
            sig = (name, tuple(str(getattr(a, "dtype", "?")) for a in ins))
            promo[sig] = promo.get(sig, 0) + 1

        # DT201: host callbacks traced into the step
        if name in _CALLBACK_PRIMS:
            cb = eqn.params.get("callback")
            label = getattr(cb, "__name__", None) or str(cb or name)
            callbacks[(name, label)] = callbacks.get((name, label), 0) + 1

        # DT203: output bytes dwarf operand bytes
        in_bytes = sum(_aval_bytes(a) for a in ins if a is not None)
        out_bytes = sum(_aval_bytes(a) for a in outs if a is not None)
        if (out_bytes >= blowup_floor_bytes
                and out_bytes >= blowup_ratio * max(in_bytes, 1)):
            shape = tuple(getattr(outs[0], "shape", ()))
            key = (name, shape)
            row = blowups.setdefault(key, {"count": 0, "in": in_bytes,
                                           "out": out_bytes})
            row["count"] += 1

        # DT204: gather/scatter whose indices operand is a traced value
        if name == "gather" or name.startswith("scatter"):
            from jax import core  # noqa: PLC0415

            idx = eqn.invars[1] if len(eqn.invars) > 1 else None
            traced = (idx is not None and not isinstance(idx, core.Literal)
                      and idx not in const_derived)
            if traced:
                shape = tuple(getattr(getattr(idx, "aval", None), "shape", ()))
                dynamic_idx[(name, shape)] = dynamic_idx.get(
                    (name, shape), 0) + 1

    for (name, in_dtypes), count in sorted(promo.items()):
        findings.append(get_rule("DT200").finding(
            f"{name} produces strong float64 from operands "
            f"({', '.join(in_dtypes)}) — {count} occurrence(s) in the "
            "traced step", file=source, context=name))
    for (name, label), count in sorted(callbacks.items()):
        findings.append(get_rule("DT201").finding(
            f"{name} ({label}) traced into the step function, "
            f"{count} occurrence(s): every execution round-trips to the "
            "Python host", file=source, context=name))
    for (name, shape), row in sorted(blowups.items()):
        findings.append(get_rule("DT203").finding(
            f"{name} materializes {_fmt_bytes(row['out'])} "
            f"(shape {list(shape)}) from {_fmt_bytes(row['in'])} of "
            f"operands ({row['count']} occurrence(s)) — "
            f">{blowup_ratio:.0f}x blow-up", file=source, context=name))
    for (name, shape), count in sorted(dynamic_idx.items()):
        findings.append(get_rule("DT204").finding(
            f"{name} with traced indices (shape {list(shape)}), "
            f"{count} occurrence(s): dynamic addressing defeats TPU "
            "vectorization", file=source, context=name))

    if cost is None:
        cost = jaxpr_cost(closed_jaxpr)
    rl = cost["roofline"]
    ai = cost["arithmetic_intensity"]
    if cost["flops"] and ai < rl["ridge_flops_per_byte"]:
        findings.append(get_rule("DT206").finding(
            f"arithmetic intensity {ai:.2f} FLOPs/byte is below the "
            f"roofline ridge {rl['ridge_flops_per_byte']:.1f} "
            f"({rl['peak_flops']:.3g} FLOP/s / {rl['hbm_gbps']:.0f} GB/s): "
            "the step is projected memory-bound "
            f"(predicted {rl['predicted_step_seconds']:.3g}s/step)",
            file=source, context="roofline"))
    col = cost["collectives"]
    if col["count"]:
        # census rows carry mesh-axis labels, so the message (and the
        # machine-readable census) key exactly like the measured post-SPMD
        # census: (kind, axes) -> count/bytes
        rows = col.get("census") or [
            {"kind": n, "axes": r.get("axes", []), "count": r["count"]}
            for n, r in sorted(col["by_primitive"].items())]
        parts = ", ".join(
            f"{r['kind']}[{','.join(r['axes']) or '?'}]×{r['count']}"
            for r in rows)
        findings.append(get_rule("DT207").finding(
            f"{col['count']} collective eqn(s) per optimizer step ({parts}), "
            f"~{_fmt_bytes(col['bytes'])} moved per step",
            file=source, context="collectives"))
    return findings


# ---------------------------------------------------------- donation audit
def _flat_avals(tree) -> List[Tuple[Tuple[int, ...], str]]:
    import jax  # noqa: PLC0415

    out = []
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            out.append((tuple(leaf.shape), str(leaf.dtype)))
    return out


def _match_donations(donated: Sequence[Tuple], outputs: Sequence[Tuple]):
    """Replay jax's donation matching: each donated input aliases at most
    one remaining output of identical (shape, dtype). Returns the donated
    avals that find no match — the ones the compiler silently drops."""
    pool: dict = {}
    for o in outputs:
        pool[o] = pool.get(o, 0) + 1
    dropped = []
    for d in donated:
        if pool.get(d, 0) > 0:
            pool[d] -= 1
        else:
            dropped.append(d)
    return dropped


def audit_donation(fn, args, donate_argnums: Sequence[int] = (), *,
                   source: str = IR_SOURCE,
                   context: str = "donation") -> List[Finding]:
    """DT202: would the donations requested for ``fn`` survive compilation?

    Pure tracing (``jax.make_jaxpr`` over arrays or ShapeDtypeStruct
    shells — nothing compiles or dispatches): a donated argument whose
    (shape, dtype) matches no remaining output cannot be aliased, and XLA
    drops the donation with only a UserWarning — params stay
    double-buffered. ``fn`` may be jitted (the unjitted ``__wrapped__`` is
    traced so passthrough outputs aren't elided)."""
    import jax  # noqa: PLC0415

    if not donate_argnums:
        return []
    inner = getattr(fn, "__wrapped__", fn)
    closed = jax.make_jaxpr(inner)(*args)
    donated = []
    for i in donate_argnums:
        donated += _flat_avals(args[int(i)])
    outputs = [(tuple(v.aval.shape), str(v.aval.dtype))
               for v in closed.jaxpr.outvars if hasattr(v, "aval")]
    dropped = _match_donations(donated, outputs)
    if not dropped:
        return []
    import numpy as np

    drop_bytes = sum(
        int(np.prod(s, dtype=np.int64)) * np.dtype(d).itemsize
        for s, d in dropped)
    examples = ", ".join(f"{d}{list(s)}" for s, d in dropped[:3])
    more = f" (+{len(dropped) - 3} more)" if len(dropped) > 3 else ""
    return [get_rule("DT202").finding(
        f"{len(dropped)} of {len(donated)} donated buffers match no output "
        f"and will NOT be aliased ({examples}{more}): "
        f"{_fmt_bytes(drop_bytes)} stays double-buffered",
        file=source, context=context)]


# ------------------------------------------------------------ network entry
def _shell_tree(tree, conf_dtype: Optional[str] = None):
    """ShapeDtypeStruct shells of a pytree. With ``conf_dtype`` (and unless
    it is float64 itself), float64 leaves are re-dtyped to the configured
    compute dtype: under an x64-enabled host (the test env) ``init()``
    inflates params to f64, and analyzing THAT trace would drown DT200 in
    findings about the host config rather than the step — the production
    trace (x64 off) is what the analysis models. Mirrors
    ``graph_checks._retype_floats``."""
    import jax  # noqa: PLC0415
    import numpy as np  # noqa: PLC0415

    target = None
    if conf_dtype and conf_dtype != "float64":
        target = np.dtype("float32")

    def one(a):
        if hasattr(a, "shape") and hasattr(a, "dtype"):
            dt = a.dtype
            try:
                if target is not None and np.dtype(dt) == np.dtype("float64"):
                    dt = target
            except TypeError:
                pass  # extended dtypes (PRNG keys)
            return jax.ShapeDtypeStruct(tuple(a.shape), dt)
        return a

    return jax.tree_util.tree_map(one, tree)


def _label_structs(net, batch: int, timesteps_probe: int):
    """ShapeDtypeStruct shells for the labels the train step expects."""
    import jax  # noqa: PLC0415
    import numpy as np  # noqa: PLC0415

    conf = net.conf

    def shape_of(it):
        if getattr(it, "kind", None) == "rnn" and it.timesteps is None:
            return (timesteps_probe, it.size)
        return it.example_shape()

    if hasattr(conf, "vertices"):
        return [jax.ShapeDtypeStruct((batch,) + tuple(shape_of(t)),
                                     np.float32)
                for t in conf.output_types()]
    return jax.ShapeDtypeStruct(
        (batch,) + tuple(shape_of(conf.output_type())), np.float32)


def check_network_ir(net, batch_or_struct=None, *,
                     ignore: Iterable[str] = (),
                     timesteps_probe: Optional[int] = None,
                     layout=None,
                     numerics: bool = True,
                     numerics_input_bound: Optional[float] = None,
                     source: str = IR_SOURCE) -> dict:
    """The DT2xx pass + static cost model over a net's real train step.

    Traces ``net._build_train_step()`` with ``jax.make_jaxpr`` over
    ShapeDtypeStruct shells of params/optimizer state/batch — pure abstract
    interpretation, zero device dispatches (``net.init()`` must already
    have run or will run once here; the analysis itself never executes).

    Returns ``{"findings": [...], "static_cost": {...}}``. The donation
    audit always checks the TPU contract (``donate_argnums=(0, 1, 2)``)
    even on backends where the fit path skips donation.

    ``layout``: a :class:`~deeplearning4j_tpu.parallel.MeshLayout` — adds
    the DT3xx sharding-flow pass (``analysis/shard_flow.py``): the report
    gains a ``"shard_flow"`` block (predicted collective census, per-step
    communication bytes), the DT300-DT305 findings join the list, and the
    roofline's interconnect term (``DL4JTPU_ICI_GBPS``) is fed the
    predicted census so ``predicted_step_seconds`` covers the
    communication-bound regime.

    ``numerics`` (default on): the DT5xx dtype-flow + value-range pass
    (``analysis/numerics.py``) walks the SAME traced jaxpr — one
    ``make_jaxpr``, two walks — seeding input/param/label invars at
    ``numerics_input_bound`` (default ±1e3) and optimizer moments from
    their structural invariants. The report gains a ``"numerics"``
    summary block and the DT500-DT505 findings join the list.
    """
    import jax  # noqa: PLC0415

    from ..telemetry.memory import (  # noqa: PLC0415 - shared struct builder
        DEFAULT_TIMESTEPS_PROBE, _input_structs)

    t_probe = (DEFAULT_TIMESTEPS_PROBE if timesteps_probe is None
               else int(timesteps_probe))
    net.init()
    inputs = _input_structs(net, batch_or_struct, timesteps_probe=t_probe)
    batch = int(inputs[0].shape[0])
    labels = _label_structs(net, batch, t_probe)
    conf_dtype = getattr(net.conf, "dtype", "float32")
    params = _shell_tree(net.params, conf_dtype)
    opt_state = _shell_tree(net.opt_state, conf_dtype)
    state = _shell_tree(net.state, conf_dtype)
    rng = jax.ShapeDtypeStruct(tuple(net._rng.shape), net._rng.dtype)

    step = net._build_train_step()
    inner = getattr(step, "__wrapped__", step)
    is_graph = hasattr(net.conf, "vertices")
    x_arg = inputs if is_graph else inputs[0]
    args = (params, opt_state, state, x_arg, labels, rng, None, None)

    closed = jax.make_jaxpr(inner)(*args)
    cost = jaxpr_cost(closed)
    findings = check_jaxpr_ir(closed, source=source, cost=cost)
    findings += audit_donation(inner, args, donate_argnums=(0, 1, 2),
                               source=source, context="train_step donation")
    report = {"static_cost": cost}
    if layout is not None:
        from .cost_model import apply_roofline  # noqa: PLC0415
        from .shard_flow import check_network_shard_flow  # noqa: PLC0415

        flow = check_network_shard_flow(
            net, batch_or_struct, layout, timesteps_probe=timesteps_probe,
            source=source)
        findings += flow.pop("findings")
        report["shard_flow"] = flow
        apply_roofline(cost, comm_bytes=cost["collectives"]["bytes"]
                       + flow["comm_bytes_per_step"])
    if numerics:
        from .numerics import (  # noqa: PLC0415
            DEFAULT_INPUT_BOUND, network_numerics)

        bound = (DEFAULT_INPUT_BOUND if numerics_input_bound is None
                 else float(numerics_input_bound))
        block = network_numerics(net, closed, args, source=source,
                                 input_bound=bound)
        findings += block["findings"]
        report["numerics"] = block["summary"]
    ignore = frozenset(ignore)
    findings = [f for f in findings if f.rule_id not in ignore]
    report["findings"] = merge_findings(findings)
    return report


def analyze_config_ir(conf, *, batch: int = 4,
                      timesteps_probe: Optional[int] = None,
                      source: str = IR_SOURCE, layout=None,
                      numerics: bool = False,
                      ignore: Iterable[str] = ()) -> Tuple[List[Finding], dict]:
    """Headless DT2xx entry for a config (the CLI ``--ir`` path): builds the
    matching network class, initializes it, and runs
    :func:`check_network_ir`. Returns ``(findings, static_cost)`` — with
    ``layout`` (e.g. the CLI ``--mesh`` flag's abstract MeshLayout) the
    static_cost carries the DT3xx ``shard_flow`` census block too.
    ``numerics=True`` (the CLI ``--ir --numerics`` composition) adds the
    DT5xx pass over the same trace and a ``"numerics"`` cost block —
    default off so the ``ir``/``numerics`` flags stay independent."""
    if hasattr(conf, "vertices"):
        from ..nn.graph import ComputationGraph  # noqa: PLC0415

        net = ComputationGraph(conf)
    else:
        from ..nn.multilayer import MultiLayerNetwork  # noqa: PLC0415

        net = MultiLayerNetwork(conf)
    report = check_network_ir(net, batch, timesteps_probe=timesteps_probe,
                              source=source, ignore=ignore, layout=layout,
                              numerics=numerics)
    cost = report["static_cost"]
    if "shard_flow" in report or "numerics" in report:
        cost = dict(cost)
    if "shard_flow" in report:
        cost["shard_flow"] = {
            k: v for k, v in report["shard_flow"].items()
            if k in ("census", "comm_bytes_per_step", "layout")}
    if "numerics" in report:
        cost["numerics"] = report["numerics"]
    return report["findings"], cost


# ------------------------------------------------------------ padding waste
def check_padding_waste(stats: Optional[dict], *,
                        threshold: float = DT205_THRESHOLD,
                        source: str = "<BucketedStager>") -> List[Finding]:
    """DT205: compare the stager's pow2 bucket shapes against the real batch
    statistics it accumulated over an epoch; flag when more than
    ``threshold`` of the staged elements (hence FLOPs) were padding."""
    if not stats or not stats.get("windows"):
        return []
    frac = float(stats.get("padding_fraction", 0.0))
    if frac <= threshold:
        return []
    return [get_rule("DT205").finding(
        f"{frac:.0%} of staged elements were padding this epoch "
        f"({stats['windows']} window(s), {stats['batches']} batch(es), "
        f"{_fmt_bytes(stats.get('staged_bytes', 0))} staged for "
        f"{_fmt_bytes(stats.get('real_bytes', 0))} of real data) — "
        f"above the {threshold:.0%} threshold",
        file=source, context="padding")]


# ----------------------------------------------------------- observability
def ir_findings_family(registry):
    """The single owning declaration of ``dl4jtpu_ir_findings_total`` —
    :func:`record_findings` and the compile manager both draw the family
    from here so the schema (labels, help text) cannot drift (DT406)."""
    return registry.counter(
        "dl4jtpu_ir_findings_total",
        "IR-lint (DT2xx) findings from admission/preflight/epoch scans",
        labelnames=("rule",))


def record_findings(findings: Sequence[Finding], *, registry=None,
                    flight=None) -> None:
    """Route IR findings into telemetry: one
    ``dl4jtpu_ir_findings_total{rule}`` increment and one flight-recorder
    ``ir_finding`` event per finding. ``registry=False`` skips the counter
    (for callers that already own the metric family). Never raises —
    observability must not break the path that produced the findings."""
    if not findings:
        return
    if registry is not False:
        try:
            if registry is None:
                from ..telemetry import get_registry  # noqa: PLC0415

                registry = get_registry()
            fam = ir_findings_family(registry)
            for f in findings:
                fam.labels(rule=f.rule_id).inc()
        except Exception:
            pass
    try:
        if flight is None:
            from ..telemetry.flight_recorder import get_flight_recorder  # noqa: PLC0415

            flight = get_flight_recorder()
        for f in findings:
            flight.record("ir_finding", rule=f.rule_id, severity=f.severity,
                          context=f.context, message=f.message[:300])
    except Exception:
        pass


# ------------------------------------------------------ compile admission
def admission_check(jitted, compiled, args, *, kind: str = "aot") -> Tuple[
        List[Finding], dict]:
    """IR lint + cost model for an executable the compile manager is about
    to admit. ``jitted`` is the jit-wrapped callable (re-traced host-side —
    the XLA compile it just paid dwarfs this), ``compiled`` the AOT
    executable (its ``memory_analysis`` corroborates the donation audit).
    Returns ``(findings, static_cost)``."""
    import jax  # noqa: PLC0415

    closed = jax.make_jaxpr(jitted)(*args)
    cost = jaxpr_cost(closed)
    source = f"<ir:{kind}>"
    findings = check_jaxpr_ir(closed, source=source, cost=cost)

    # DT3xx sharding-flow at admission: when the program is compiled with
    # mesh-sharded arguments, propagate those ACTUAL shardings through the
    # jaxpr and predict the collective census before lower() runs. Invars
    # are spec-indistinguishable here (a ZeRO param shard and a batch shard
    # both read P('fsdp')), so invar gathers are treated as the documented
    # param cost and never fire DT300/DT303 — net.analyze_ir(layout=...)
    # is the precise entry. Failures degrade silently: analysis must never
    # break compilation.
    try:
        flat, _ = jax.tree_util.tree_flatten(args)
        mesh = None
        specs = []
        flags = []
        for leaf in flat:
            sh = getattr(leaf, "sharding", None)
            if type(sh).__name__ == "NamedSharding" \
                    and sh.mesh.devices.size > 1:
                mesh = mesh or sh.mesh
                specs.append(sh.spec)
                flags.append(True)
            else:
                specs.append(None)
                flags.append(False)
        if mesh is not None:
            from ..parallel.layout import MeshLayout  # noqa: PLC0415
            from .cost_model import apply_roofline  # noqa: PLC0415
            from .shard_flow import (  # noqa: PLC0415
                flow_report, propagate_jaxpr, shard_findings)

            tp = ("tp" if "tp" in mesh.shape and mesh.shape["tp"] > 1
                  else None)
            layout = MeshLayout.from_mesh(mesh, model_axis=tp)
            flow = propagate_jaxpr(closed, specs, layout, param_flags=flags)
            findings += shard_findings(flow, source=source)
            cost["shard_flow"] = flow_report(flow)
            apply_roofline(
                cost, comm_bytes=cost["collectives"]["bytes"]
                + cost["shard_flow"]["comm_bytes_per_step"])
    except Exception:
        pass

    # DT5xx numerics at admission: same jaxpr, one extra host-side walk.
    # No declared ranges/policy are available for an arbitrary executable,
    # so invars stay unknown — hazard rules only fire on evidence the
    # trace itself provides (literal clamps, structural softmax shape,
    # low-precision accumulation dtypes); net.analyze_ir is the seeded,
    # policy-aware entry. Failures degrade silently like the DT3xx block.
    try:
        from .numerics import check_jaxpr_numerics  # noqa: PLC0415

        num_findings, num_summary = check_jaxpr_numerics(
            closed, source=source)
        findings += num_findings
        cost["numerics"] = num_summary
    except Exception:
        pass

    # DT202 at admission: the pjit eqn records the donation actually
    # requested; a requested donation with ZERO aliased bytes in the
    # compiler's own memory analysis was dropped wholesale
    try:
        eqn = closed.jaxpr.eqns[0] if closed.jaxpr.eqns else None
        donated_invars = (eqn.params.get("donated_invars", ())
                          if eqn is not None and eqn.primitive.name == "pjit"
                          else ())
        n_donated = sum(1 for d in donated_invars if d)
        if n_donated:
            ma = None
            try:
                ma = compiled.memory_analysis()
            except Exception:
                ma = None
            alias = int(getattr(ma, "alias_size_in_bytes", 0) or 0) \
                if ma is not None else None
            if alias == 0:
                findings.append(get_rule("DT202").finding(
                    f"{n_donated} donated buffer(s) requested but the "
                    "compiled executable aliases 0 bytes: donation was "
                    "dropped — params/optimizer state are double-buffered",
                    file=source, context=kind))
    except Exception:
        pass
    return merge_findings(findings), cost
