"""Pass 1: abstract interpretation of network configs.

For every layer/vertex the pass runs ``jax.eval_shape`` over
``init_params``/``init_state``/``apply`` (no FLOPs, no allocation — pure
shape/dtype algebra) and diffs the traced output against the layer's
declared ``get_output_type()``. The declared algebra drives preprocessor
insertion, distributed sharding and serialization, so drift between the
two is a latent correctness bug even when both paths "work".

Vertices are checked independently: each one is fed its *declared*
input types, so one drifting vertex yields one finding instead of a
cascade through everything downstream.

On top of the contract diff, config-level TPU heuristics: lane padding
(DT003), variable timesteps (DT004), NCHW-looking inputs (DT005),
float64 compute (DT006), missing loss head (DT007).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .findings import Finding
from .rules import get_rule

# timesteps probe substituted for variable-length (None) recurrent inputs
DEFAULT_TIMESTEPS_PROBE = 16
DEFAULT_BATCH = 4

_LANE = 128  # TPU vector lane width; VPU/MXU tile is (8, 128)
_SUBLANE = 8


# ------------------------------------------------------------------ plumbing
def _compute_dtype(conf_dtype: str):
    if conf_dtype == "bfloat16":
        return jnp.dtype(jnp.bfloat16)
    return jnp.dtype(conf_dtype)


def _probe_shape(it, t_probe: int) -> Tuple[int, ...]:
    """Per-example probe shape; variable timesteps pinned to ``t_probe``."""
    if it.kind == "rnn" and it.timesteps is None:
        return (t_probe, it.size)
    return it.example_shape()


def _retype_floats(tree, dt):
    """Re-dtype floating leaves of a struct pytree to the compute dtype —
    mirrors _cast_params/_cast_input in nn/multilayer.py so the trace sees
    the dtypes the real forward would."""
    def one(s):
        if hasattr(s, "dtype") and jnp.issubdtype(s.dtype, jnp.floating):
            return jax.ShapeDtypeStruct(s.shape, dt)
        if hasattr(s, "dtype"):
            return jax.ShapeDtypeStruct(s.shape, s.dtype)
        return s
    return jax.tree_util.tree_map(one, tree)


def _trace_apply(obj, input_types: Sequence, batch: int, t_probe: int, dt,
                 *, as_vertex: bool):
    """eval_shape through init_params/init_state/apply; returns the output
    ShapeDtypeStruct (first element when apply returns (out, state))."""
    key_struct = jax.ShapeDtypeStruct((2,), jnp.uint32)
    p = jax.eval_shape(lambda k: obj.init_params(k, *input_types), key_struct)
    s = jax.eval_shape(lambda: obj.init_state(*input_types))
    p, s = _retype_floats(p, dt), _retype_floats(s, dt)
    xs = [
        jax.ShapeDtypeStruct((batch,) + _probe_shape(it, t_probe), dt)
        for it in input_types
    ]
    if as_vertex:
        fn = lambda pp, ss, *aa: obj.apply(pp, list(aa), ss, train=False)  # noqa: E731
    else:
        fn = lambda pp, ss, aa: obj.apply(pp, aa, ss, train=False)  # noqa: E731
    out = jax.eval_shape(fn, p, s, *xs)
    if isinstance(out, (tuple, list)):
        out = out[0]
    return out


def _diff_contract(rule_ctx: dict, declared, traced, t_probe: int,
                   compute_dt) -> List[Finding]:
    """DT001/DT002: declared InputType vs traced ShapeDtypeStruct.

    The batch axis is skipped — InputType describes one example, and
    batch-reshaping vertices (Stack/Unstack) change it legitimately.
    """
    out: List[Finding] = []
    want = _probe_shape(declared, t_probe)
    got = tuple(traced.shape[1:])
    if got != tuple(want):
        out.append(get_rule("DT001").finding(
            f"declared output {declared} (example shape {tuple(want)}) but "
            f"jax.eval_shape traced {got}",
            **rule_ctx,
        ))
    if jnp.issubdtype(traced.dtype, jnp.floating) and traced.dtype != compute_dt:
        out.append(get_rule("DT002").finding(
            f"traced output dtype {traced.dtype} != configured compute "
            f"dtype {compute_dt}",
            **rule_ctx,
        ))
    return out


def _lane_findings(it, rule_ctx: dict) -> List[Finding]:
    """DT003 on the trailing (lane) dim of a declared type."""
    if it.kind == "ff":
        dim, label = it.size, "feature dim"
    elif it.kind == "rnn":
        dim, label = it.size, "feature dim"
    elif it.kind == "cnn":
        dim, label = it.channels, "channel dim"
    else:
        return []
    rule = get_rule("DT003")
    if dim >= 64 and dim % _LANE != 0:
        padded = -(-dim // _LANE) * _LANE
        return [rule.finding(
            f"{label} {dim} pads to {padded} on the {_LANE}-wide TPU lane "
            f"({100 * (padded - dim) // padded}% of the tile wasted)",
            **rule_ctx,
        )]
    if _SUBLANE < dim < 64 and dim % _SUBLANE != 0:
        return [rule.finding(
            f"{label} {dim} is not a multiple of the {_SUBLANE}-row sublane",
            severity="info", **rule_ctx,
        )]
    return []


def _input_findings(input_types: Iterable, source: str,
                    names: Optional[Sequence[str]] = None) -> List[Finding]:
    """DT004/DT005 on declared network inputs."""
    out: List[Finding] = []
    for i, it in enumerate(input_types):
        label = names[i] if names else f"input[{i}]"
        ctx = {"file": source, "context": label}
        if it.kind == "rnn" and it.timesteps is None:
            out.append(get_rule("DT004").finding(
                f"{label} declares variable timesteps (None): each distinct "
                "sequence length recompiles the whole step", **ctx,
            ))
        if it.kind in ("cnn", "cnn_flat") and it.height <= 4 and it.channels >= 32:
            out.append(get_rule("DT005").finding(
                f"{label} is {it.height}x{it.width}x{it.channels} (HxWxC) — "
                "tiny height with a large channel count looks like NCHW data "
                "declared as NHWC", **ctx,
            ))
    return out


def _dtype_findings(conf, source: str) -> List[Finding]:
    if conf.dtype == "float64":
        return [get_rule("DT006").finding(
            "compute dtype float64: TPUs emulate f64 in software",
            file=source, context="dtype",
        )]
    return []


# ----------------------------------------------------------------- MLN check
def check_multi_layer(conf, *, batch: int = DEFAULT_BATCH,
                      timesteps_probe: int = DEFAULT_TIMESTEPS_PROBE,
                      source: str = "<MultiLayerConfiguration>") -> List[Finding]:
    """Analyze a MultiLayerConfiguration; returns findings (possibly empty)."""
    findings: List[Finding] = []
    findings += _dtype_findings(conf, source)
    if conf.input_type is not None:
        findings += _input_findings([conf.input_type], source, ["input"])
    if conf.layers and not conf.layers[-1].is_output_layer:
        findings.append(get_rule("DT007").finding(
            f"last layer {type(conf.layers[-1]).__name__} is not an output "
            "layer — fit() has no loss to differentiate",
            file=source, context=f"layer[{len(conf.layers) - 1}]",
        ))
    if conf.input_type is None:
        return findings  # shape pass needs a declared input type

    dt = _compute_dtype(conf.dtype)
    try:
        its = conf.layer_input_types()
    except Exception as e:  # propagation itself failed: one finding, stop
        findings.append(get_rule("DT001").finding(
            f"declared shape propagation failed: {e}",
            file=source, context="layer_input_types",
        ))
        return findings
    for i, (layer, it) in enumerate(zip(conf.layers, its)):
        ctx = {"file": source,
               "context": f"layer[{i}] {type(layer).__name__}"}
        try:
            declared = layer.get_output_type(it)
        except Exception as e:
            findings.append(get_rule("DT001").finding(
                f"get_output_type({it}) raised: {e}", **ctx))
            continue
        findings += _lane_findings(declared, ctx)
        try:
            traced = _trace_apply(layer, [it], batch, timesteps_probe, dt,
                                  as_vertex=False)
        except Exception as e:
            findings.append(get_rule("DT001").finding(
                f"apply() failed to trace at declared input {it}: {e}", **ctx))
            continue
        findings += _diff_contract(ctx, declared, traced, timesteps_probe, dt)
    return findings


# --------------------------------------------------------------- graph check
def check_graph(conf, *, batch: int = DEFAULT_BATCH,
                timesteps_probe: int = DEFAULT_TIMESTEPS_PROBE,
                source: str = "<ComputationGraphConfiguration>") -> List[Finding]:
    """Analyze a ComputationGraphConfiguration; returns findings."""
    findings: List[Finding] = []
    findings += _dtype_findings(conf, source)
    findings += _input_findings(conf.input_types, source, conf.network_inputs)
    for o in conf.network_outputs:
        v = conf.vertices.get(o)
        if v is not None and not v.is_output_layer:
            findings.append(get_rule("DT007").finding(
                f"network output '{o}' ({type(v).__name__}) is not an "
                "output layer — fit() has no loss to differentiate",
                file=source, context=f"vertex '{o}'",
            ))
    if not conf.input_types:
        return findings

    dt = _compute_dtype(conf.dtype)
    try:
        vit = conf.vertex_input_types()
    except Exception as e:
        findings.append(get_rule("DT001").finding(
            f"declared shape propagation failed: {e}",
            file=source, context="vertex_input_types",
        ))
        return findings
    for name in conf.topological_order():
        vertex = conf.vertices[name]
        ins = vit[name]
        ctx = {"file": source, "context": f"vertex '{name}'"}
        try:
            declared = vertex.get_output_type(*ins)
        except Exception as e:
            findings.append(get_rule("DT001").finding(
                f"get_output_type raised: {e}", **ctx))
            continue
        findings += _lane_findings(declared, ctx)
        try:
            traced = _trace_apply(vertex, ins, batch, timesteps_probe, dt,
                                  as_vertex=True)
        except Exception as e:
            findings.append(get_rule("DT001").finding(
                "apply() failed to trace at declared inputs "
                f"{[str(t) for t in ins]}: {e}", **ctx))
            continue
        findings += _diff_contract(ctx, declared, traced, timesteps_probe, dt)
    return findings


# ------------------------------------------------------------ DT008 check
def _spec_axis_names(spec) -> List[str]:
    """Axis names referenced by a PartitionSpec, flattened (an entry may be
    None, one name, or a tuple of names)."""
    names: List[str] = []
    for entry in tuple(spec):
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            names.extend(str(n) for n in entry)
        else:
            names.append(str(entry))
    return names


def check_partition_specs(shardings, mesh, params=None, *,
                          source: str = "<shardings>") -> List[Finding]:
    """DT008: validate declared PartitionSpecs against the mesh axes
    actually present — BEFORE the first ``device_put`` fails (or, worse,
    GSPMD silently replicates).

    ``shardings``: a pytree whose leaves are ``PartitionSpec``s or
    ``NamedSharding``s (e.g. the output of
    ``parallel.sharding.tree_shardings``, or hand-written specs).
    ``mesh``: the mesh the specs will be applied on. ``params`` (optional,
    same tree structure): enables the shape checks — a spec longer than the
    array rank, or a sharded dimension the axis size does not divide.
    """
    from jax.sharding import NamedSharding, PartitionSpec  # noqa: PLC0415

    rule = get_rule("DT008")
    findings: List[Finding] = []
    mesh_axes = {str(a): int(s) for a, s in mesh.shape.items()}
    is_leaf = lambda x: isinstance(x, (NamedSharding, PartitionSpec))  # noqa: E731
    flat, treedef = jax.tree_util.tree_flatten_with_path(
        shardings, is_leaf=is_leaf)
    param_leaves = None
    if params is not None:
        leaves = jax.tree_util.tree_leaves(params)
        if len(leaves) == len(flat):
            param_leaves = leaves

    for i, (path, leaf) in enumerate(flat):
        label = jax.tree_util.keystr(path) or f"leaf[{i}]"
        ctx = {"file": source, "context": label}
        if isinstance(leaf, NamedSharding):
            spec = leaf.spec
            own_axes = {str(a) for a in leaf.mesh.axis_names}
            if own_axes != set(mesh_axes):
                findings.append(rule.finding(
                    f"NamedSharding was built on a mesh with axes "
                    f"{sorted(own_axes)} but will be applied on a mesh with "
                    f"axes {sorted(mesh_axes)}", **ctx))
                continue
            if leaf.mesh != mesh:
                # same axis names, different mesh: a stale layout's params
                # mixed with a fresh mesh (different axis sizes or device
                # sets) — lower() would fail with a raw incompatible-devices
                # error, or worse, silently resolve to a different factor
                own_shape = {str(a): int(s) for a, s in leaf.mesh.shape.items()}
                detail = (f"axis sizes {own_shape} vs {mesh_axes}"
                          if own_shape != mesh_axes
                          else "a different device set")
                findings.append(rule.finding(
                    "NamedSharding was built on a DIFFERENT mesh than it "
                    f"will be applied on ({detail}) — stale layout?", **ctx))
                continue
        elif isinstance(leaf, PartitionSpec):
            spec = leaf
        else:
            continue
        names = _spec_axis_names(spec)
        unknown = [n for n in names if n not in mesh_axes]
        if unknown:
            findings.append(rule.finding(
                f"PartitionSpec{tuple(spec)} references "
                f"{'axes' if len(unknown) > 1 else 'axis'} "
                f"{sorted(set(unknown))} absent from the mesh (axes "
                f"present: {sorted(mesh_axes)})", **ctx))
            continue
        dupes = sorted({n for n in names if names.count(n) > 1})
        if dupes:
            findings.append(rule.finding(
                f"PartitionSpec{tuple(spec)} uses mesh "
                f"{'axes' if len(dupes) > 1 else 'axis'} {dupes} for more "
                "than one dimension", **ctx))
            continue
        if param_leaves is None:
            continue
        shape = getattr(param_leaves[i], "shape", None)
        if shape is None:
            continue
        entries = tuple(spec)
        if len(entries) > len(shape):
            findings.append(rule.finding(
                f"PartitionSpec{entries} has {len(entries)} entries but the "
                f"array is rank {len(shape)} ({tuple(shape)})", **ctx))
            continue
        for dim, (size, entry) in enumerate(zip(shape, entries)):
            if entry is None:
                continue
            axes = entry if isinstance(entry, (tuple, list)) else (entry,)
            factor = 1
            for a in axes:
                factor *= mesh_axes[str(a)]
            if factor > 1 and int(size) % factor != 0:
                findings.append(rule.finding(
                    f"dim {dim} of shape {tuple(shape)} is {size}, not "
                    f"divisible by the {factor}-way sharding of "
                    f"PartitionSpec{entries}", severity="warning", **ctx))
    return findings


# ------------------------------------------------------------ DT009 check
def _leaf_shardings(params_subtree):
    """Distinct (device-set, spec) placements of a param subtree's leaves.
    Device sets are frozensets of device ids; spec is the NamedSharding
    PartitionSpec when present (SingleDeviceSharding and friends report
    None — only the device set matters for transfer detection)."""
    placements = {}
    for leaf in jax.tree_util.tree_leaves(params_subtree):
        sharding = getattr(leaf, "sharding", None)
        if sharding is None:
            continue
        try:
            devices = frozenset(d.id for d in sharding.device_set)
        except Exception:
            continue
        spec = getattr(sharding, "spec", None)
        placements[(devices, str(spec))] = (devices, spec)
    return placements


def check_shardings(net, *, source: str = "<network>") -> List[Finding]:
    """DT009: detect per-step cross-device transfers between consecutive
    vertices/layers of an *initialized* network.

    Unlike the config passes this inspects live param placements (configs
    carry no sharding), so it runs after ``init()``/``shard_params``: for
    every graph edge (or layer i -> i+1 in a MultiLayerNetwork), if the two
    ends' parameters live on different device sets, the activation crossing
    that edge is resharded on EVERY optimizer step — usually an accidental
    ``device_put`` of one subtree onto the wrong mesh. A vertex whose own
    leaves span several device sets is flagged too.
    """
    findings: List[Finding] = []
    net.init()
    rule = get_rule("DT009")

    if hasattr(net, "conf") and hasattr(net.conf, "vertices"):
        names = net.conf.topological_order()
        params_of = lambda n: net.params[n]  # noqa: E731
        edges = [
            (src, dst)
            for dst in names
            for src in net.conf.vertex_inputs[dst]
            if src in net.conf.vertices
        ]
        label = lambda n: f"vertex '{n}'"  # noqa: E731
    else:
        names = list(range(len(net.conf.layers)))
        params_of = lambda i: net.params[i]  # noqa: E731
        edges = [(i, i + 1) for i in names[:-1]]
        label = lambda i: f"layer[{i}]"  # noqa: E731

    placements = {n: _leaf_shardings(params_of(n)) for n in names}
    for n in names:
        device_sets = {devs for devs, _ in placements[n].values()}
        if len(device_sets) > 1:
            findings.append(rule.finding(
                f"{label(n)} parameters span {len(device_sets)} distinct "
                "device sets — the vertex reshards its own params every step",
                file=source, context=label(n),
            ))
    for src, dst in edges:
        a, b = placements.get(src), placements.get(dst)
        if not a or not b:
            continue  # param-less vertex (merge/activation): no placement
        sets_a = {devs for devs, _ in a.values()}
        sets_b = {devs for devs, _ in b.values()}
        if len(sets_a) == 1 and len(sets_b) == 1 and sets_a != sets_b:
            da, db = next(iter(sets_a)), next(iter(sets_b))
            findings.append(rule.finding(
                f"edge {label(src)} -> {label(dst)}: parameters live on "
                f"different device sets ({sorted(da)} vs {sorted(db)}) — the "
                "activation crossing this edge is resharded every step",
                file=source, context=f"{label(src)} -> {label(dst)}",
            ))
    return findings


def check_config(conf, **kw) -> List[Finding]:
    """Dispatch on config type (or a parsed to_dict()-style mapping)."""
    from ..nn.conf.multi_layer import MultiLayerConfiguration
    from ..nn.conf.computation_graph import ComputationGraphConfiguration

    if isinstance(conf, dict):
        if "vertices" in conf:
            conf = ComputationGraphConfiguration.from_dict(conf)
        else:
            conf = MultiLayerConfiguration.from_dict(conf)
    if isinstance(conf, ComputationGraphConfiguration):
        return check_graph(conf, **kw)
    if isinstance(conf, MultiLayerConfiguration):
        return check_multi_layer(conf, **kw)
    raise TypeError(f"Cannot analyze {type(conf).__name__}")
