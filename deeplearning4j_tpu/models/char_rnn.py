"""Char-RNN: GravesLSTM language model (BASELINE config #3).

The era-canonical DL4J example architecture (stacked GravesLSTM +
RnnOutputLayer with MCXENT, TBPTT) — reference layer semantics from
nn/layers/recurrent/LSTMHelpers.java; trained with truncated BPTT
(MultiLayerNetwork.doTruncatedBPTT, MultiLayerNetwork.java:1080).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..nn.conf.inputs import InputType
from ..nn.conf.multi_layer import MultiLayerConfiguration
from ..nn.layers.recurrent import GravesLSTM, RnnOutputLayer
from ..nn.updaters import UpdaterConfig


def char_rnn(
    vocab_size: int,
    hidden_size: int = 256,
    num_layers: int = 2,
    tbptt_length: int = 50,
    learning_rate: float = 1e-3,
    dtype: str = "float32",
    seed: int = 12345,
) -> MultiLayerConfiguration:
    """Stacked-LSTM character model over one-hot inputs [B, T, vocab]."""
    layers = []
    for i in range(num_layers):
        layers.append(
            GravesLSTM(
                n_in=vocab_size if i == 0 else hidden_size,
                n_out=hidden_size,
                activation="tanh",
            )
        )
    layers.append(
        RnnOutputLayer(
            n_in=hidden_size, n_out=vocab_size, activation="softmax", loss="mcxent"
        )
    )
    return MultiLayerConfiguration(
        layers=layers,
        input_type=InputType.recurrent(vocab_size),
        updater=UpdaterConfig(updater="adam", learning_rate=learning_rate),
        backprop_type="tbptt",
        tbptt_fwd_length=tbptt_length,
        tbptt_back_length=tbptt_length,
        dtype=dtype,
        seed=seed,
    )


class CharIterator:
    """Text -> one-hot next-char-prediction minibatches (the DL4J
    CharacterIterator example's role: features [B,T,V], labels shifted by 1)."""

    prefetch_supported = True

    def __init__(self, text: str, seq_length: int = 50, batch_size: int = 32,
                 seed: int = 0):
        self.chars = sorted(set(text))
        self.char_to_idx = {c: i for i, c in enumerate(self.chars)}
        self.vocab_size = len(self.chars)
        self.encoded = np.array([self.char_to_idx[c] for c in text], dtype=np.int32)
        self.seq_length = seq_length
        self.batch_size = batch_size
        self._rng = np.random.default_rng(seed)
        self.reset()

    def reset(self):
        n_seq = (len(self.encoded) - 1) // self.seq_length
        self._starts = self._rng.permutation(n_seq) * self.seq_length
        self._pos = 0

    def __iter__(self):
        return self

    def __next__(self):
        from ..datasets.iterators import DataSet

        if self._pos + self.batch_size > len(self._starts):
            raise StopIteration
        starts = self._starts[self._pos : self._pos + self.batch_size]
        self._pos += self.batch_size
        T, V = self.seq_length, self.vocab_size
        x = np.zeros((len(starts), T, V), dtype=np.float32)
        y = np.zeros((len(starts), T, V), dtype=np.float32)
        for b, s in enumerate(starts):
            seq = self.encoded[s : s + T + 1]
            x[b, np.arange(T), seq[:-1]] = 1.0
            y[b, np.arange(T), seq[1:]] = 1.0
        return DataSet(x, y)
