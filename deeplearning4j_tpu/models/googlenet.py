"""GoogLeNet / Inception-v1 — the reference era's deep multi-branch CNN.

Role parity: the model-zoo GoogLeNet the reference ecosystem shipped (its
graph demands exactly the pieces ComputationGraph provides: MergeVertex
concatenation of parallel branches — nn/conf/graph/MergeVertex.java — plus
LRN and overlapping pools). Optional auxiliary classifier heads exercise the
graph's multi-output training (losses sum, as the reference's score
aggregation across output layers).

TPU-native: every branch is an independent XLA conv lowered onto the MXU;
the concat is a free layout op; the whole 9-module graph traces into one
jitted program.
"""

from __future__ import annotations

from ..nn.conf.computation_graph import ComputationGraphConfiguration, GraphBuilder
from ..nn.conf.inputs import InputType
from ..nn.graph.vertices import MergeVertex
from ..nn.layers.convolution import ConvolutionLayer
from ..nn.layers.dense import DenseLayer, DropoutLayer, OutputLayer
from ..nn.layers.normalization import LocalResponseNormalization
from ..nn.layers.pooling import GlobalPoolingLayer, SubsamplingLayer
from ..nn.updaters import UpdaterConfig


def _conv(b: GraphBuilder, name: str, inp: str, n_out: int, kernel, stride=(1, 1)) -> str:
    b.add_layer(
        name,
        ConvolutionLayer(n_out=n_out, kernel=kernel, stride=stride,
                         convolution_mode="same", activation="relu"),
        inp,
    )
    return name


def _inception(b: GraphBuilder, name: str, inp: str,
               ch1: int, ch3r: int, ch3: int, ch5r: int, ch5: int, pool: int) -> str:
    """One inception module: 1x1 | 1x1→3x3 | 1x1→5x5 | pool→1x1, concat."""
    b1 = _conv(b, f"{name}_1x1", inp, ch1, (1, 1))
    r3 = _conv(b, f"{name}_3x3r", inp, ch3r, (1, 1))
    b3 = _conv(b, f"{name}_3x3", r3, ch3, (3, 3))
    r5 = _conv(b, f"{name}_5x5r", inp, ch5r, (1, 1))
    b5 = _conv(b, f"{name}_5x5", r5, ch5, (5, 5))
    b.add_layer(
        f"{name}_pool",
        SubsamplingLayer(pooling_type="max", kernel=(3, 3), stride=(1, 1),
                         convolution_mode="same"),
        inp,
    )
    bp = _conv(b, f"{name}_poolproj", f"{name}_pool", pool, (1, 1))
    b.add_vertex(name, MergeVertex(), b1, b3, b5, bp)
    return name


def _aux_head(b: GraphBuilder, name: str, inp: str, n_classes: int,
              dropout: float) -> str:
    """Auxiliary classifier (Szegedy 2014): avgpool 5x5/3 → 1x1 conv →
    dense 1024 → softmax. Trains with the main head via multi-output loss."""
    b.add_layer(
        f"{name}_pool",
        SubsamplingLayer(pooling_type="avg", kernel=(5, 5), stride=(3, 3)),
        inp,
    )
    _conv(b, f"{name}_proj", f"{name}_pool", 128, (1, 1))
    b.add_layer(f"{name}_fc", DenseLayer(n_out=1024, activation="relu",
                                         dropout=dropout), f"{name}_proj")
    b.add_layer(
        name,
        OutputLayer(n_out=n_classes, activation="softmax", loss="mcxent"),
        f"{name}_fc",
    )
    return name


def googlenet_conf(
    height: int = 224,
    width: int = 224,
    channels: int = 3,
    n_classes: int = 1000,
    learning_rate: float = 1e-2,
    updater: str = "nesterovs",
    dropout: float = 0.4,
    aux_heads: bool = False,
    dtype: str = "float32",
    seed: int = 12345,
) -> ComputationGraphConfiguration:
    b = (
        ComputationGraphConfiguration.builder()
        .add_inputs("in")
        .set_input_types(InputType.convolutional(height, width, channels))
        .seed(seed)
        .dtype(dtype)
        .updater(UpdaterConfig(updater=updater, learning_rate=learning_rate))
    )
    _conv(b, "stem_conv1", "in", 64, (7, 7), (2, 2))
    b.add_layer("stem_pool1", SubsamplingLayer(pooling_type="max", kernel=(3, 3),
                                               stride=(2, 2), convolution_mode="same"),
                "stem_conv1")
    b.add_layer("stem_lrn1", LocalResponseNormalization(), "stem_pool1")
    _conv(b, "stem_conv2r", "stem_lrn1", 64, (1, 1))
    _conv(b, "stem_conv2", "stem_conv2r", 192, (3, 3))
    b.add_layer("stem_lrn2", LocalResponseNormalization(), "stem_conv2")
    b.add_layer("stem_pool2", SubsamplingLayer(pooling_type="max", kernel=(3, 3),
                                               stride=(2, 2), convolution_mode="same"),
                "stem_lrn2")

    t = _inception(b, "i3a", "stem_pool2", 64, 96, 128, 16, 32, 32)
    t = _inception(b, "i3b", t, 128, 128, 192, 32, 96, 64)
    b.add_layer("pool3", SubsamplingLayer(pooling_type="max", kernel=(3, 3),
                                          stride=(2, 2), convolution_mode="same"), t)
    t = _inception(b, "i4a", "pool3", 192, 96, 208, 16, 48, 64)
    aux1_src = t
    t = _inception(b, "i4b", t, 160, 112, 224, 24, 64, 64)
    t = _inception(b, "i4c", t, 128, 128, 256, 24, 64, 64)
    t = _inception(b, "i4d", t, 112, 144, 288, 32, 64, 64)
    aux2_src = t
    t = _inception(b, "i4e", t, 256, 160, 320, 32, 128, 128)
    b.add_layer("pool4", SubsamplingLayer(pooling_type="max", kernel=(3, 3),
                                          stride=(2, 2), convolution_mode="same"), t)
    t = _inception(b, "i5a", "pool4", 256, 160, 320, 32, 128, 128)
    t = _inception(b, "i5b", t, 384, 192, 384, 48, 128, 128)

    # paper head: avgpool → dropout → linear softmax (no hidden dense)
    b.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), t)
    b.add_layer("drop", DropoutLayer(dropout=dropout), "avgpool")
    b.add_layer("out", OutputLayer(n_out=n_classes, activation="softmax",
                                   loss="mcxent"), "drop")
    outputs = ["out"]
    if aux_heads:
        outputs.append(_aux_head(b, "aux1", aux1_src, n_classes, dropout))
        outputs.append(_aux_head(b, "aux2", aux2_src, n_classes, dropout))
    b.set_outputs(*outputs)
    return b.build()
