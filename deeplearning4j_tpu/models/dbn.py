"""Deep Belief Network — stacked RBMs with layerwise pretraining.

Role parity: the architecture the reference project was FOUNDED on (its
2014-16 flagship examples: DeepBeliefNetworkExample / MnistDBNExample —
stacked conf/layers/RBM.java layers pretrained by CD-k, then fine-tuned with
a softmax head). TPU-native: each RBM's CD-k pretrain loss is one jitted
program (nn/layers/pretrain.py); ``MultiLayerNetwork.pretrain(data)`` runs
the layerwise schedule, then ``fit`` backprops end to end.
"""

from __future__ import annotations

from typing import Sequence

from ..nn.conf.inputs import InputType
from ..nn.conf.multi_layer import MultiLayerConfiguration
from ..nn.layers.dense import OutputLayer
from ..nn.layers.pretrain import RBM
from ..nn.updaters import UpdaterConfig


def dbn_conf(
    n_in: int = 784,
    layer_sizes: Sequence[int] = (500, 250, 100),
    n_classes: int = 10,
    k: int = 1,
    visible_unit: str = "binary",
    learning_rate: float = 1e-2,
    updater: str = "sgd",
    dtype: str = "float32",
    seed: int = 12345,
) -> MultiLayerConfiguration:
    """Classic DBN: RBM stack (first layer's visible units match the data —
    'gaussian' for real-valued inputs) + softmax classifier head.

    Train as the reference did: ``net.pretrain(it)`` (greedy layerwise CD-k),
    then ``net.fit(it)`` (supervised fine-tune through the whole stack).
    """
    layers = []
    for i, size in enumerate(layer_sizes):
        layers.append(RBM(
            n_out=int(size), k=k,
            visible_unit=visible_unit if i == 0 else "binary",
            hidden_unit="binary",
        ))
    layers.append(OutputLayer(n_out=n_classes, activation="softmax", loss="mcxent"))
    return MultiLayerConfiguration(
        layers=layers,
        input_type=InputType.feed_forward(n_in),
        updater=UpdaterConfig(updater=updater, learning_rate=learning_rate),
        dtype=dtype,
        seed=seed,
    )
