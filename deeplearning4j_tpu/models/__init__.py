"""Model zoo: standard configs built from the public config DSL.

Reference analog: trainedmodels/TrainedModels.java (VGG16) + the example
configs users built with MultiLayerConfiguration/ComputationGraphConfiguration.
"""

from .lenet import lenet_mnist_conf
from .resnet import resnet_conf, resnet18_conf, resnet34_conf, resnet50_conf

__all__ = [
    "lenet_mnist_conf",
    "resnet_conf",
    "resnet18_conf",
    "resnet34_conf",
    "resnet50_conf",
]
