"""Model zoo: standard configs built from the public config DSL.

Reference analog: trainedmodels/TrainedModels.java (VGG16) + the example
configs users built with MultiLayerConfiguration/ComputationGraphConfiguration.
"""

from .alexnet import alexnet_conf
from .googlenet import googlenet_conf
from .lenet import lenet_mnist_conf
from .resnet import (resnet_conf, resnet18_conf, resnet34_conf,
                     resnet50_conf, resnet101_conf, resnet152_conf)
from .char_rnn import char_rnn
from .dbn import dbn_conf
from ..modelimport.trained_models import vgg16_configuration

__all__ = [
    "alexnet_conf",
    "char_rnn",
    "dbn_conf",
    "googlenet_conf",
    "lenet_mnist_conf",
    "resnet_conf",
    "resnet18_conf",
    "resnet34_conf",
    "resnet50_conf",
    "resnet101_conf",
    "resnet152_conf",
    "vgg16_configuration",
]
