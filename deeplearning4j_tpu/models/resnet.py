"""ResNet family as ComputationGraph configs — the flagship bench model.

The reference era's "model zoo" is downloadable VGG16 weights
(modelimport/.../trainedmodels/TrainedModels.java); its ComputationGraph was
the tool users built ResNets with. Here the zoo is code: graph configs built
from the same vertex set a user has (LayerVertex conv/BN, ElementWiseVertex
add — the residual sum), so ResNet-50 doubles as the ComputationGraph
stress test and the BASELINE throughput model (SURVEY.md §6, §7 stage 4).

TPU notes: NHWC layout; bottleneck 1x1/3x3 convs are MXU-shaped matmuls after
XLA's spatial tiling; set ``dtype="bfloat16"`` on the returned conf for the
mixed-precision path used in benchmarks.
"""

from __future__ import annotations

from typing import Sequence, Tuple

from ..nn.conf.computation_graph import ComputationGraphConfiguration, GraphBuilder
from ..nn.conf.inputs import InputType
from ..nn.graph.vertices import ElementWiseVertex
from ..nn.layers.base import BaseLayer
from ..nn.layers.convolution import ConvolutionLayer
from ..nn.layers.dense import ActivationLayer, OutputLayer
from ..nn.layers.normalization import BatchNormalization
from ..nn.layers.pooling import GlobalPoolingLayer, SubsamplingLayer
from ..nn.updaters import UpdaterConfig


def _conv_bn(
    b: GraphBuilder,
    name: str,
    inp: str,
    n_out: int,
    kernel: Tuple[int, int],
    stride: Tuple[int, int] = (1, 1),
    relu: bool = True,
) -> str:
    """conv → BN [→ relu]; returns the last vertex name."""
    b.add_layer(
        f"{name}_conv",
        ConvolutionLayer(
            n_out=n_out, kernel=kernel, stride=stride,
            convolution_mode="same", has_bias=False, weight_init="relu",
        ),
        inp,
    )
    b.add_layer(f"{name}_bn", BatchNormalization(), f"{name}_conv")
    if relu:
        b.add_layer(f"{name}_relu", ActivationLayer(activation="relu"), f"{name}_bn")
        return f"{name}_relu"
    return f"{name}_bn"


def _bottleneck(
    b: GraphBuilder, name: str, inp: str, mid: int, stride: Tuple[int, int], project: bool
) -> str:
    """ResNet-v1 bottleneck: 1x1(mid) → 3x3(mid, stride) → 1x1(4*mid), + shortcut."""
    out_ch = 4 * mid
    t = _conv_bn(b, f"{name}_a", inp, mid, (1, 1), stride)
    t = _conv_bn(b, f"{name}_b", t, mid, (3, 3))
    t = _conv_bn(b, f"{name}_c", t, out_ch, (1, 1), relu=False)
    if project:
        shortcut = _conv_bn(b, f"{name}_proj", inp, out_ch, (1, 1), stride, relu=False)
    else:
        shortcut = inp
    b.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), t, shortcut)
    b.add_layer(f"{name}_out", ActivationLayer(activation="relu"), f"{name}_add")
    return f"{name}_out"


def _basic_block(
    b: GraphBuilder, name: str, inp: str, ch: int, stride: Tuple[int, int], project: bool
) -> str:
    """ResNet-v1 basic block (ResNet-18/34): 3x3 → 3x3, + shortcut."""
    t = _conv_bn(b, f"{name}_a", inp, ch, (3, 3), stride)
    t = _conv_bn(b, f"{name}_b", t, ch, (3, 3), relu=False)
    if project:
        shortcut = _conv_bn(b, f"{name}_proj", inp, ch, (1, 1), stride, relu=False)
    else:
        shortcut = inp
    b.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), t, shortcut)
    b.add_layer(f"{name}_out", ActivationLayer(activation="relu"), f"{name}_add")
    return f"{name}_out"


def resnet_conf(
    blocks: Sequence[int],
    *,
    bottleneck: bool = True,
    num_classes: int = 1000,
    image_size: Tuple[int, int] = (224, 224),
    channels: int = 3,
    dtype: str = "float32",
    updater: UpdaterConfig | None = None,
    seed: int = 12345,
) -> ComputationGraphConfiguration:
    """Generic ResNet-v1 graph. ``blocks``: residual blocks per stage."""
    b = (
        ComputationGraphConfiguration.builder()
        .add_inputs("in")
        .set_input_types(InputType.convolutional(image_size[0], image_size[1], channels))
        .seed(seed)
        .dtype(dtype)
        .updater(updater or UpdaterConfig(updater="sgd", learning_rate=0.1))
    )
    stem = _conv_bn(b, "stem", "in", 64, (7, 7), (2, 2))
    b.add_layer(
        "stem_pool",
        SubsamplingLayer(pooling_type="max", kernel=(3, 3), stride=(2, 2),
                         convolution_mode="same"),
        stem,
    )
    t = "stem_pool"
    block_fn = _bottleneck if bottleneck else _basic_block
    width = 64
    cur_ch = 64  # channels flowing out of the stem
    for stage, n_blocks in enumerate(blocks):
        out_ch = 4 * width if bottleneck else width
        for i in range(n_blocks):
            stride = (2, 2) if (stage > 0 and i == 0) else (1, 1)
            # projection shortcut only where identity can't carry the residual:
            # stride ≠ 1 or channel count changes (standard ResNet-v1; an
            # unconditional stage-0 projection would not be ResNet-18/34)
            project = i == 0 and (stride != (1, 1) or cur_ch != out_ch)
            t = block_fn(b, f"s{stage}_b{i}", t, width, stride, project)
            cur_ch = out_ch
        width *= 2
    b.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), t)
    b.add_layer(
        "out",
        OutputLayer(n_out=num_classes, activation="softmax", loss="mcxent"),
        "avgpool",
    )
    b.set_outputs("out")
    return b.build()


def resnet50_conf(**kw) -> ComputationGraphConfiguration:
    """ResNet-50: [3, 4, 6, 3] bottleneck stages — BASELINE config #2."""
    return resnet_conf([3, 4, 6, 3], bottleneck=True, **kw)


def resnet18_conf(**kw) -> ComputationGraphConfiguration:
    return resnet_conf([2, 2, 2, 2], bottleneck=False, **kw)


def resnet34_conf(**kw) -> ComputationGraphConfiguration:
    return resnet_conf([3, 4, 6, 3], bottleneck=False, **kw)


def resnet101_conf(**kw) -> ComputationGraphConfiguration:
    return resnet_conf([3, 4, 23, 3], bottleneck=True, **kw)


def resnet152_conf(**kw) -> ComputationGraphConfiguration:
    return resnet_conf([3, 8, 36, 3], bottleneck=True, **kw)
