"""AlexNet (one-tower variant) — the reference era's flagship ImageNet CNN.

Role parity: dl4j-examples AnimalsClassification / the model-zoo AlexNet the
reference ecosystem shipped (reference's own layer set: conv + LRN + overlap
max-pool + dropout-regularized dense — nn/conf/layers/LocalResponseNormalization.java
is exactly this model's normalization). TPU-native: LRN dispatches to the
Pallas fused kernel (ops/pallas_kernels.py) when measured faster; convs lower
to XLA MXU convolutions in NHWC.
"""

from __future__ import annotations

from ..nn.conf.inputs import InputType
from ..nn.conf.multi_layer import MultiLayerConfiguration
from ..nn.layers.convolution import ConvolutionLayer
from ..nn.layers.dense import DenseLayer, OutputLayer
from ..nn.layers.normalization import LocalResponseNormalization
from ..nn.layers.pooling import SubsamplingLayer
from ..nn.updaters import UpdaterConfig


def alexnet_conf(
    height: int = 224,
    width: int = 224,
    channels: int = 3,
    n_classes: int = 1000,
    learning_rate: float = 1e-2,
    updater: str = "nesterovs",
    dropout: float = 0.5,
    dtype: str = "float32",
    seed: int = 12345,
) -> MultiLayerConfiguration:
    """Krizhevsky-2012 single-tower AlexNet: 5 conv (2 LRN'd) + 3 dense."""
    return MultiLayerConfiguration(
        layers=[
            ConvolutionLayer(n_out=96, kernel=(11, 11), stride=(4, 4),
                             convolution_mode="same", activation="relu"),
            LocalResponseNormalization(),
            SubsamplingLayer(pooling_type="max", kernel=(3, 3), stride=(2, 2)),
            ConvolutionLayer(n_out=256, kernel=(5, 5), stride=(1, 1),
                             convolution_mode="same", activation="relu"),
            LocalResponseNormalization(),
            SubsamplingLayer(pooling_type="max", kernel=(3, 3), stride=(2, 2)),
            ConvolutionLayer(n_out=384, kernel=(3, 3), stride=(1, 1),
                             convolution_mode="same", activation="relu"),
            ConvolutionLayer(n_out=384, kernel=(3, 3), stride=(1, 1),
                             convolution_mode="same", activation="relu"),
            ConvolutionLayer(n_out=256, kernel=(3, 3), stride=(1, 1),
                             convolution_mode="same", activation="relu"),
            SubsamplingLayer(pooling_type="max", kernel=(3, 3), stride=(2, 2)),
            DenseLayer(n_out=4096, activation="relu", dropout=dropout),
            DenseLayer(n_out=4096, activation="relu", dropout=dropout),
            OutputLayer(n_out=n_classes, activation="softmax", loss="mcxent"),
        ],
        input_type=InputType.convolutional(height, width, channels),
        updater=UpdaterConfig(updater=updater, learning_rate=learning_rate),
        dtype=dtype,
        seed=seed,
    )
