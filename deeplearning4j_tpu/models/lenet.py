"""LeNet-5-style MNIST model (BASELINE config #1).

Role parity: the reference era's canonical first CNN (dl4j-examples
LenetMnistExample shape; model-zoo role of modelimport's TrainedModels —
SURVEY.md §2.7). Built from the framework's own config DSL.
"""

from __future__ import annotations

from ..nn.conf.inputs import InputType
from ..nn.conf.multi_layer import MultiLayerConfiguration
from ..nn.layers.convolution import ConvolutionLayer
from ..nn.layers.dense import DenseLayer, OutputLayer
from ..nn.layers.pooling import SubsamplingLayer
from ..nn.updaters import UpdaterConfig


def lenet_mnist_conf(
    height: int = 28,
    width: int = 28,
    channels: int = 1,
    n_classes: int = 10,
    learning_rate: float = 1e-3,
    updater: str = "adam",
    dtype: str = "float32",
    seed: int = 12345,
) -> MultiLayerConfiguration:
    return MultiLayerConfiguration(
        layers=[
            ConvolutionLayer(n_out=20, kernel=(5, 5), stride=(1, 1), activation="identity"),
            SubsamplingLayer(pooling_type="max", kernel=(2, 2), stride=(2, 2)),
            ConvolutionLayer(n_out=50, kernel=(5, 5), stride=(1, 1), activation="identity"),
            SubsamplingLayer(pooling_type="max", kernel=(2, 2), stride=(2, 2)),
            DenseLayer(n_out=500, activation="relu"),
            OutputLayer(n_out=n_classes, activation="softmax", loss="mcxent"),
        ],
        input_type=InputType.convolutional(height, width, channels),
        updater=UpdaterConfig(updater=updater, learning_rate=learning_rate),
        dtype=dtype,
        seed=seed,
    )
