"""Streaming pipelines: records → DataSet → online fit / serving routes.

Reference: dl4j-streaming (SURVEY.md §2.4) wires Kafka + Camel + Spark
Streaming: ``BaseKafkaPipeline`` turns a record stream into ``DataSet``s,
``DL4jServeRouteBuilder`` routes them into online ``fit`` or inference with
results published back. The TPU-native shape: a ``RecordSource`` SPI feeding
a background pipeline thread that micro-batches records and hands them to
pluggable routes — ``TrainRoute`` (online fit; one jitted step per
micro-batch) and ``ServeRoute`` (predictions to a sink callback/queue). A
Kafka source is provided behind a gated import (kafka-python is not in the
image; any broker client can implement ``RecordSource.poll``).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np


class RecordSource:
    """SPI: poll() returns a record (features[, label]) or None when idle."""

    def poll(self, timeout: float = 0.1):
        raise NotImplementedError

    def close(self) -> None:
        pass


class ReplayableSource(RecordSource):
    """Optional replay contract on top of :class:`RecordSource`.

    A replayable source exposes a monotonically increasing **cursor** —
    the count of records it has delivered — and can re-yield any recent
    span ``(start, end]`` of them. The OnlineTrainer uses this after a
    drift rollback: the poisoned span ``[last_good_cursor,
    rollback_cursor]`` is re-ingested through a validation-only pass
    (loss-band gate, no optimizer updates) before normal ingestion
    resumes; sources without the contract keep today's behavior and the
    rollback records an explicit ``replay: unsupported`` event. See
    docs/robustness.md for the full contract.
    """

    def replay_cursor(self) -> int:
        """Records delivered so far (0 before the first poll)."""
        raise NotImplementedError

    def replay(self, start: int, end: int):
        """Iterable of the records delivered in cursor span (start, end].
        Records that have aged out of the source's retention are simply
        absent — replay is best-effort over what is still held."""
        raise NotImplementedError


class ReplayBufferSource(ReplayableSource):
    """Make ANY source replayable by remembering its last ``capacity``
    delivered records (the in-process analogue of broker retention —
    a Kafka-backed source would instead seek on stored offsets)."""

    def __init__(self, inner: RecordSource, capacity: int = 65536):
        import collections  # noqa: PLC0415
        self.inner = inner
        self._buf = collections.deque(maxlen=int(capacity))
        self._n = 0
        self._lock = threading.Lock()

    def poll(self, timeout: float = 0.1):
        rec = self.inner.poll(timeout=timeout)
        if rec is not None:
            with self._lock:
                self._n += 1
                self._buf.append((self._n, rec))
        return rec

    def replay_cursor(self) -> int:
        with self._lock:
            return self._n

    def replay(self, start: int, end: int):
        with self._lock:
            return [rec for i, rec in self._buf if start < i <= end]

    def close(self) -> None:
        self.inner.close()


class QueueSource(RecordSource):
    """In-process source (tests / direct feeding; the 'direct:' Camel route)."""

    def __init__(self, maxsize: int = 1024):
        self._q: "queue.Queue" = queue.Queue(maxsize=maxsize)

    def put(self, features, label=None, timeout: float = 30.0) -> None:
        """Bounded put: raises rather than blocking forever when the consumer
        (pipeline pump) has died — see StreamingPipeline.alive."""
        try:
            self._q.put(
                (np.asarray(features, np.float32),
                 None if label is None else np.asarray(label, np.float32)),
                timeout=timeout,
            )
        except queue.Full:
            raise RuntimeError(
                "QueueSource full after "
                f"{timeout}s — is the StreamingPipeline stopped or dead? "
                "(check pipeline.alive / pipeline.raise_if_failed())"
            ) from None

    def poll(self, timeout: float = 0.1):
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None


class KafkaSource(RecordSource):
    """Kafka consumer source (reference: kafka/NDArrayKafkaClient.java).

    Gated: requires the ``kafka`` package (absent in this image — the SPI
    keeps the seam; deserializer maps message bytes → (features, label)).
    """

    def __init__(self, topic: str, deserializer: Callable,
                 consumer_factory: Optional[Callable] = None, **consumer_kwargs):
        # consumer_factory injects any kafka-python-shaped consumer (tests,
        # alternative broker clients); only the default transport is gated.
        if consumer_factory is not None:
            self._consumer = consumer_factory(topic, **consumer_kwargs)
        else:
            try:
                from kafka import KafkaConsumer  # noqa: PLC0415
            except ImportError as e:
                raise ImportError(
                    "kafka-python is required for KafkaSource; implement "
                    "RecordSource.poll over your broker client instead, or "
                    "pass consumer_factory"
                ) from e
            self._consumer = KafkaConsumer(topic, **consumer_kwargs)
        self._deserializer = deserializer

    def poll(self, timeout: float = 0.1):
        polled = self._consumer.poll(timeout_ms=int(timeout * 1000), max_records=1)
        for records in polled.values():
            for rec in records:
                return self._deserializer(rec.value)
        return None

    def close(self) -> None:
        self._consumer.close()


class Route:
    """SPI: receives assembled micro-batches."""

    def on_batch(self, features: np.ndarray, labels: Optional[np.ndarray]) -> None:
        raise NotImplementedError


class TrainRoute(Route):
    """Online learning: one fit step per micro-batch (reference:
    DL4jServeRouteBuilder's fit path)."""

    def __init__(self, net):
        self.net = net
        self.batches_seen = 0

    def on_batch(self, features, labels):
        if labels is None:
            raise ValueError("TrainRoute needs labelled records")
        from ..datasets.iterators import DataSet  # noqa: PLC0415

        self.net.fit(DataSet(features, labels))
        self.batches_seen += 1


class ServeRoute(Route):
    """Inference: predictions go to the sink callback (reference: serving
    route publishing results back to the transport)."""

    def __init__(self, net, sink: Callable[[np.ndarray, np.ndarray], None]):
        self.net = net
        self.sink = sink

    def on_batch(self, features, labels):
        out = np.asarray(self.net.output(features))
        self.sink(features, out)


class StreamingPipeline:
    """Micro-batching pump: source → (batch assembly) → routes.

    ``batch`` records are grouped (padding is NOT applied — records must be
    homogeneous) and every route sees each micro-batch. ``linger`` bounds the
    wait before a short batch is flushed, keeping latency bounded like the
    reference's Camel aggregator timeouts.

    ``device_prefetch``: stage each assembled micro-batch into device memory
    (``jax.device_put`` — asynchronous) the moment it is built, BEFORE the
    routes run. The H2D transfer of batch i then overlaps the routes'
    device compute on batch i-1 (whose dispatches are still draining — the
    fit/output steps never block the host), the same double-buffering the
    staged fit path uses. Host-only routes still work: device arrays
    np.asarray back transparently.
    """

    def __init__(self, source: RecordSource, routes: Sequence[Route],
                 batch: int = 32, linger: float = 0.5, registry=None,
                 device_prefetch: bool = False):
        from ..telemetry import get_registry  # noqa: PLC0415

        self.source = source
        self.routes = list(routes)
        self.batch = int(batch)
        self.linger = float(linger)
        self.device_prefetch = bool(device_prefetch)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        reg = registry if registry is not None else get_registry()
        self._m_staged = reg.counter(
            "dl4jtpu_streaming_device_staged_total",
            "micro-batches device_put ahead of route dispatch")
        self._m_records = reg.counter(
            "dl4jtpu_streaming_records_total",
            "records consumed from the source")
        self._m_batches = reg.counter(
            "dl4jtpu_streaming_batches_total",
            "micro-batches delivered to routes")
        self._m_batch_size = reg.histogram(
            "dl4jtpu_streaming_batch_size",
            "assembled micro-batch sizes",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024))
        self._m_errors = reg.counter(
            "dl4jtpu_streaming_pump_failures_total",
            "pump-thread deaths from a route/source error")

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "StreamingPipeline":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dl4j-streaming-pipeline")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        self.source.close()
        self.raise_if_failed()

    @property
    def alive(self) -> bool:
        """False once the pump thread exited (route error or stop())."""
        return self._thread is not None and self._thread.is_alive()

    def raise_if_failed(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- pump -----------------------------------------------------------
    def _run(self) -> None:
        buf: List[Tuple[np.ndarray, Optional[np.ndarray]]] = []
        deadline = None
        try:
            while not self._stop.is_set():
                rec = self.source.poll(timeout=0.05)
                now = time.monotonic()
                if rec is not None:
                    # labelled/unlabelled records never share a micro-batch:
                    # flush the current one at a label-presence boundary
                    if buf and (rec[1] is None) != (buf[0][1] is None):
                        self._flush(buf)
                        buf, deadline = [], None
                    buf.append(rec)
                    if deadline is None:
                        deadline = now + self.linger
                if buf and (len(buf) >= self.batch or now >= (deadline or now)):
                    self._flush(buf)
                    buf, deadline = [], None
            # stop() drains: records the source already buffered but the
            # pump never polled were silently dropped before — a producer
            # that put N records and called stop() lost the tail whenever
            # the pump was behind. Poll the source dry (bounded, in case a
            # producer is still live), flushing through the same batch and
            # label-boundary rules, THEN flush the residual partial buffer.
            drain_deadline = time.monotonic() + 5.0
            while time.monotonic() < drain_deadline:
                rec = self.source.poll(timeout=0)
                if rec is None:
                    break
                if buf and (rec[1] is None) != (buf[0][1] is None):
                    self._flush(buf)
                    buf = []
                buf.append(rec)
                if len(buf) >= self.batch:
                    self._flush(buf)
                    buf = []
            if buf:
                self._flush(buf)
        except BaseException as e:  # surfaced on stop()/raise_if_failed()
            self._m_errors.inc()
            self._error = e

    def _flush(self, buf) -> None:
        feats = np.stack([f for f, _ in buf])
        labels = None
        if buf[0][1] is not None:
            labels = np.stack([l for _, l in buf])
        if self.device_prefetch:
            import jax  # noqa: PLC0415

            # async H2D: overlaps the previous batch's still-draining route
            # dispatches; routes receive committed device arrays
            feats = jax.device_put(feats)
            if labels is not None:
                labels = jax.device_put(labels)
            self._m_staged.inc()
        for route in self.routes:
            route.on_batch(feats, labels)
        self._m_records.inc(len(buf))
        self._m_batches.inc()
        self._m_batch_size.observe(len(buf))
