"""In-process Kafka analog: a protocol-faithful broker + consumer/producer
implementing the kafka-python client surface, with no external dependency.

Reference: dl4j-streaming's tests stand up a real embedded broker
(``dl4j-streaming/src/test/java/org/deeplearning4j/streaming/embedded/
EmbeddedKafkaCluster.java``) so ``NDArrayKafkaClient``/``BaseKafkaPipeline``
exercise true topic/partition/offset semantics rather than a stub. This
module is the TPU-native equivalent: ``EmbeddedKafkaBroker`` keeps
partitioned, offset-addressed logs per topic; ``EmbeddedKafkaConsumer``
implements the ``kafka.KafkaConsumer`` surface that
``pipeline.KafkaSource`` consumes (``poll(timeout_ms, max_records) ->
{TopicPartition: [ConsumerRecord]}``, ``subscribe``, ``seek``,
``position``, ``commit``/``committed``, ``close``), and
``EmbeddedKafkaProducer`` mirrors ``KafkaProducer.send(topic, value,
key=...)`` with keyed or round-robin partitioning (the reference publishes
NDArray messages through ``NDArrayPublisher``).

Because the surface is faithful, code written against this module runs
unchanged against kafka-python by swapping the factory — which is exactly
the ``KafkaSource(consumer_factory=...)`` seam.
"""

from __future__ import annotations

import threading
import time
import zlib
from collections import namedtuple
from typing import Dict, Iterable, List, Optional, Tuple

# kafka-python's public record types, shape-for-shape.
TopicPartition = namedtuple("TopicPartition", ["topic", "partition"])
ConsumerRecord = namedtuple(
    "ConsumerRecord",
    ["topic", "partition", "offset", "timestamp", "key", "value"],
)
OffsetAndMetadata = namedtuple("OffsetAndMetadata", ["offset", "metadata"])


class EmbeddedKafkaBroker:
    """Partitioned, offset-addressed in-memory log store.

    One broker can back many consumers/producers across threads; every log
    append and fetch is under one lock (the embedded cluster the reference
    tests use is likewise a single local broker, EmbeddedKafkaCluster.java).
    """

    def __init__(self, num_partitions: int = 2):
        if num_partitions < 1:
            raise ValueError("num_partitions must be >= 1")
        self.num_partitions = int(num_partitions)
        self._logs: Dict[TopicPartition, List[ConsumerRecord]] = {}
        self._lock = threading.Lock()
        self._clock = 0  # deterministic timestamps (no wall clock in tests)
        self._rr: Dict[str, int] = {}  # per-topic round-robin for unkeyed sends

    def _ensure_topic(self, topic: str) -> None:
        # owns the lock itself; topics are never deleted, so callers may
        # ensure first and re-acquire for their own critical section
        with self._lock:
            for p in range(self.num_partitions):
                self._logs.setdefault(TopicPartition(topic, p), [])

    def create_topic(self, topic: str) -> None:
        self._ensure_topic(topic)

    def partitions_for(self, topic: str) -> List[TopicPartition]:
        self._ensure_topic(topic)
        with self._lock:
            return [tp for tp in self._logs if tp.topic == topic]

    def append(self, topic: str, value: bytes,
               key: Optional[bytes] = None) -> ConsumerRecord:
        """Produce one message; returns the committed record (with offset).

        Keyed messages hash to a stable partition (ordering per key);
        unkeyed messages round-robin — kafka's default partitioner contract.
        """
        self._ensure_topic(topic)
        with self._lock:
            if key is not None:
                # deterministic across processes (hash() is seed-randomized)
                part = zlib.crc32(bytes(key)) % self.num_partitions
            else:
                part = self._rr.get(topic, 0) % self.num_partitions
                self._rr[topic] = part + 1
            tp = TopicPartition(topic, part)
            log = self._logs[tp]
            self._clock += 1
            rec = ConsumerRecord(topic, part, len(log), self._clock, key, value)
            log.append(rec)
            return rec

    def fetch(self, tp: TopicPartition, offset: int,
              max_records: int) -> List[ConsumerRecord]:
        with self._lock:
            log = self._logs.get(tp, [])
            return list(log[offset:offset + max_records])

    def end_offset(self, tp: TopicPartition) -> int:
        with self._lock:
            return len(self._logs.get(tp, []))


class EmbeddedKafkaProducer:
    """``KafkaProducer.send`` against the embedded broker (NDArrayPublisher
    role — dl4j-streaming/kafka/NDArrayPublisher.java)."""

    def __init__(self, broker: EmbeddedKafkaBroker):
        self._broker = broker
        self.closed = False

    def send(self, topic: str, value: bytes,
             key: Optional[bytes] = None) -> ConsumerRecord:
        if self.closed:
            raise RuntimeError("producer is closed")
        return self._broker.append(topic, value, key=key)

    def flush(self) -> None:  # in-memory appends are already durable
        pass

    def close(self) -> None:
        self.closed = True


class EmbeddedKafkaConsumer:
    """kafka-python ``KafkaConsumer`` surface over an ``EmbeddedKafkaBroker``.

    Implements the exact subset ``pipeline.KafkaSource`` (and typical user
    code) touches: construction with topics, ``subscribe``, ``poll`` with
    ``timeout_ms``/``max_records`` returning ``{TopicPartition:
    [ConsumerRecord]}``, ``position``/``seek``/``seek_to_beginning``,
    ``commit``/``committed``, ``close``. Offsets advance per partition as
    records are handed out, like a real consumer's fetch position.
    """

    def __init__(self, *topics: str, broker: EmbeddedKafkaBroker,
                 group_id: Optional[str] = None,
                 auto_offset_reset: str = "earliest", **_ignored):
        self._broker = broker
        self.group_id = group_id
        if auto_offset_reset not in ("earliest", "latest"):
            raise ValueError(f"bad auto_offset_reset: {auto_offset_reset!r}")
        self._reset = auto_offset_reset
        self._positions: Dict[TopicPartition, int] = {}
        self._committed: Dict[TopicPartition, OffsetAndMetadata] = {}
        self._rr = 0  # fairness cursor across partitions
        self.closed = False
        self._assignment: List[TopicPartition] = []
        if topics:
            self.subscribe(list(topics))

    # -- assignment ----------------------------------------------------
    def subscribe(self, topics: Iterable[str]) -> None:
        self._check_open()
        self._assignment = []
        for t in topics:
            self._assignment.extend(sorted(self._broker.partitions_for(t)))
        for tp in self._assignment:
            if tp not in self._positions:
                self._positions[tp] = (0 if self._reset == "earliest"
                                       else self._broker.end_offset(tp))

    def assignment(self) -> List[TopicPartition]:
        return list(self._assignment)

    # -- positions -----------------------------------------------------
    def position(self, tp: TopicPartition) -> int:
        self._check_open()
        return self._positions[tp]

    def seek(self, tp: TopicPartition, offset: int) -> None:
        self._check_open()
        if tp not in self._positions:
            raise ValueError(f"{tp} is not assigned")
        if int(offset) < 0:
            raise ValueError(f"offset must be >= 0, got {offset}")
        self._positions[tp] = int(offset)

    def seek_to_beginning(self, *tps: TopicPartition) -> None:
        for tp in tps or self._assignment:
            self.seek(tp, 0)

    def commit(self) -> None:
        self._check_open()
        for tp, pos in self._positions.items():
            self._committed[tp] = OffsetAndMetadata(pos, "")

    def committed(self, tp: TopicPartition) -> Optional[OffsetAndMetadata]:
        return self._committed.get(tp)

    # -- fetch ---------------------------------------------------------
    def poll(self, timeout_ms: int = 100, max_records: int = 500
             ) -> Dict[TopicPartition, List[ConsumerRecord]]:
        """Fetch up to ``max_records`` across assigned partitions.

        Partitions are drained fairly (rotating start), each batch keyed by
        TopicPartition exactly as kafka-python returns it. Like the real
        client, an empty topic BLOCKS up to ``timeout_ms`` before returning
        {} — without that, a pipeline polling in a loop busy-spins at 100%
        CPU whenever the topic is drained.
        """
        from ..runtime.resilience import Deadline
        self._check_open()
        deadline = Deadline(max(0, timeout_ms) / 1000.0)
        while True:
            out: Dict[TopicPartition, List[ConsumerRecord]] = {}
            remaining = int(max_records)
            n = len(self._assignment)
            for i in range(n):
                if remaining <= 0:
                    break
                tp = self._assignment[(self._rr + i) % n]
                recs = self._broker.fetch(tp, self._positions[tp], remaining)
                if recs:
                    out[tp] = recs
                    self._positions[tp] += len(recs)
                    remaining -= len(recs)
            self._rr += 1
            if out or deadline.expired:
                return out
            deadline.pace(min(0.005, max(0.0005, timeout_ms / 1000.0 / 4)))

    def close(self) -> None:
        self.closed = True

    def _check_open(self) -> None:
        if self.closed:
            raise RuntimeError("consumer is closed")
