"""Cross-process record transport for the streaming tier: TCP sink/source.

Reference: dl4j-streaming moves records between producer and training/
serving JVMs through Kafka — ``NDArrayKafkaClient`` publishes ndarrays to a
topic, ``BaseKafkaPipeline`` consumes them into DataSets
(dl4j-streaming/.../kafka/NDArrayKafkaClient.java, BaseKafkaPipeline.java).
This module is the same seam with zero external deps: a length-prefixed TCP
stream (the framing shared with the parameter server, utils/netio.py)
carries (features[, label]) records from any number of producer processes
into one ``SocketRecordSource``, which plugs into ``StreamingPipeline``
exactly like the in-process ``QueueSource``. A broker-backed transport
(``KafkaSource``) remains available for deployments that have one; the
design difference vs the reference is that the transport is an SPI seam
(``RecordSource``) rather than a hard Camel/Kafka dependency.

Wire format per record: one JSON frame ``{"f": feature_shape, "l":
label_shape | null}`` followed by the feature array frame and, when
labelled, the label array frame (float32, C-order — netio framing).
"""

from __future__ import annotations

import queue
import socket
import threading
from typing import List, Optional, Tuple

import numpy as np

from ..utils.netio import (
    recv_array,
    recv_json_frame,
    send_array,
    send_json_frame,
)
from .pipeline import RecordSource


class SocketRecordSource(RecordSource):
    """Listening end: accepts producer connections, reads record frames into
    a bounded queue served by ``poll`` (the ``BaseKafkaPipeline`` consumer
    role). Start before producers connect; ``port=0`` picks a free port
    (read it back from ``.port``)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 maxsize: int = 4096, backlog: int = 16):
        self._q: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self._stop = threading.Event()
        self._server = socket.create_server((host, port), backlog=backlog)
        self._server.settimeout(0.2)
        self.host, self.port = self._server.getsockname()[:2]
        self._readers: List[threading.Thread] = []
        self._conns: List[socket.socket] = []
        self._registry_lock = threading.Lock()  # guards the two lists above
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="record-source-accept"
        )
        self._accept_thread.start()

    # -- server side ---------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._server.accept()
            except socket.timeout:
                continue
            except OSError:  # closed under us during shutdown
                return
            t = threading.Thread(target=self._read_loop, args=(conn,),
                                 daemon=True, name="record-source-reader")
            with self._registry_lock:
                self._conns.append(conn)  # close() closes these to unblock recv
                self._readers.append(t)
            t.start()

    @staticmethod
    def _shaped(arr, shape) -> "np.ndarray":
        """Protocol check: a size/shape mismatch is a framing error from a
        buggy or version-skewed producer — drop the CONNECTION loudly, not
        the reader thread silently."""
        expected = 1
        for d in shape:
            expected *= int(d)
        if arr.size != expected:
            raise ConnectionError(
                f"record frame mismatch: payload {arr.size} elements, "
                f"header shape {shape}"
            )
        return arr.reshape(shape)

    def _read_loop(self, conn: socket.socket) -> None:
        try:
            with conn:
                while not self._stop.is_set():
                    header = recv_json_frame(conn)
                    if header is None:  # orderly close from the producer
                        return
                    feats = self._shaped(recv_array(conn), header["f"])
                    label = None
                    if header.get("l") is not None:
                        label = self._shaped(recv_array(conn), header["l"])
                    while not self._stop.is_set():
                        try:
                            self._q.put((feats, label), timeout=0.2)
                            break
                        except queue.Full:
                            continue
        except (ConnectionError, OSError):
            # dropped/misbehaving producer (or close() closed the socket
            # under us): records delivered before the break survive
            return
        finally:
            # a long-lived source with churning producers must not
            # accumulate dead sockets/threads without bound
            with self._registry_lock:
                if conn in self._conns:
                    self._conns.remove(conn)
                me = threading.current_thread()
                if me in self._readers:
                    self._readers.remove(me)

    # -- RecordSource --------------------------------------------------
    def poll(self, timeout: float = 0.1):
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def close(self) -> None:
        self._stop.set()
        try:
            self._server.close()
        except OSError:
            pass
        with self._registry_lock:
            conns, readers = list(self._conns), list(self._readers)
        for c in conns:         # unblocks readers parked in recv: close()
            try:                # alone does not wake a blocked recv — the
                c.shutdown(socket.SHUT_RDWR)  # FIN/reset from shutdown does
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        self._accept_thread.join(timeout=5)
        for t in readers:
            t.join(timeout=5)


class SocketRecordSink:
    """Producer end: connects to a ``SocketRecordSource`` and publishes
    records (the ``NDArrayKafkaClient`` role). Safe for one thread per sink;
    open one sink per producer thread/process."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._sock.settimeout(timeout)
        self._lock = threading.Lock()

    def put(self, features, label=None) -> None:
        feats = np.asarray(features, np.float32)
        lab = None if label is None else np.asarray(label, np.float32)
        with self._lock:
            send_json_frame(self._sock, {
                "f": list(feats.shape),
                "l": None if lab is None else list(lab.shape),
            })
            send_array(self._sock, feats)
            if lab is not None:
                send_array(self._sock, lab)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "SocketRecordSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def serve_records(host: str, port: int,
                  records: List[Tuple[np.ndarray, Optional[np.ndarray]]]) -> None:
    """Convenience producer: publish ``records`` to a source and close
    (what a producer process's main() typically does)."""
    with SocketRecordSink(host, port) as sink:
        for feats, label in records:
            sink.put(feats, label)
