"""Streaming tier (reference: dl4j-streaming Kafka+Camel pipelines)."""

from .embedded_kafka import (
    EmbeddedKafkaBroker,
    EmbeddedKafkaConsumer,
    EmbeddedKafkaProducer,
)
from .pipeline import (
    KafkaSource,
    Route,
    QueueSource,
    RecordSource,
    ReplayableSource,
    ReplayBufferSource,
    ServeRoute,
    StreamingPipeline,
    TrainRoute,
)
from .socket_transport import SocketRecordSink, SocketRecordSource, serve_records

__all__ = [
    "EmbeddedKafkaBroker",
    "EmbeddedKafkaConsumer",
    "EmbeddedKafkaProducer",
    "KafkaSource",
    "Route",
    "QueueSource",
    "RecordSource",
    "ReplayBufferSource",
    "ReplayableSource",
    "ServeRoute",
    "SocketRecordSink",
    "SocketRecordSource",
    "StreamingPipeline",
    "TrainRoute",
    "serve_records",
]
