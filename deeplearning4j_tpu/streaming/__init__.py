"""Streaming tier (reference: dl4j-streaming Kafka+Camel pipelines)."""

from .pipeline import (
    KafkaSource,
    Route,
    QueueSource,
    RecordSource,
    ServeRoute,
    StreamingPipeline,
    TrainRoute,
)

__all__ = [
    "KafkaSource",
    "Route",
    "QueueSource",
    "RecordSource",
    "ServeRoute",
    "StreamingPipeline",
    "TrainRoute",
]
