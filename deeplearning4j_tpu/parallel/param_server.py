"""Push/pull parameter server — the reference's experimental DP-3 transport.

Reference: ParameterServerParallelWrapper.java:159-216 embeds an Aeron
MediaDriver + ParameterServerNode; trainer threads push gradients and pull
parameters through ParameterServerClient (SURVEY.md §2.4). Here the transport
is a length-prefixed TCP protocol on localhost/DCN; the server owns the flat
parameter vector and applies pushed gradients with a plain SGD step, clients
pull the latest snapshot. On TPU pods the first-class path is mesh
collectives (wrapper.py) — this tier exists for reference parity and for
CPU-host asynchronous topologies.

Wire format: 1 op byte ('G' push grad, 'P' pull, 'Q' shutdown probe) +
uint64 length + float32 payload. No pickle — fixed binary frames only.
"""

from __future__ import annotations

import socket
import threading
from typing import List, Optional

import numpy as np

from ..utils.netio import (
    recv_array as _recv_array,
    recv_exact as _recv_exact,
    send_array as _send_array,
)


class ParameterServer:
    """Owns the flat parameter vector; applies pushed gradients (SGD)."""

    def __init__(self, initial_params: np.ndarray, learning_rate: float = 0.01,
                 host: str = "127.0.0.1", port: int = 0,
                 max_frame_bytes: Optional[int] = None, registry=None):
        from ..telemetry import get_registry  # noqa: PLC0415

        reg = registry if registry is not None else get_registry()
        self._m_pushes = reg.counter(
            "dl4jtpu_param_server_pushes_total",
            "gradient pushes applied by the parameter server")
        self._m_pulls = reg.counter(
            "dl4jtpu_param_server_pulls_total",
            "parameter snapshot pulls served")
        self._m_rejects = reg.counter(
            "dl4jtpu_param_server_rejected_pushes_total",
            "gradient pushes rejected (shape mismatch)")
        self._m_updates = reg.gauge(
            "dl4jtpu_param_server_updates",
            "total SGD updates applied to the server's parameter vector")
        self._params = np.ascontiguousarray(initial_params, np.float32).copy()
        # Frame cap (DoS guard) sized to the model: a legit gradient is exactly
        # params-sized, so default to that (+slack) rather than the global cap,
        # which a VGG16-scale (~553MB) model would exceed.
        self.max_frame_bytes = int(
            max_frame_bytes
            if max_frame_bytes is not None
            else max(self._params.nbytes * 2, 1 << 20)
        )
        self.learning_rate = float(learning_rate)
        self._lock = threading.Lock()
        self._updates = 0
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self.host, self.port = self._srv.getsockname()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="dl4j-param-server")
        self._thread.start()

    # -- lifecycle ------------------------------------------------------
    def shutdown(self) -> None:
        self._stop.set()
        try:
            # unblock accept()
            poke = socket.create_connection((self.host, self.port), timeout=1)
            poke.sendall(b"Q")
            poke.close()
        except OSError:
            pass
        self._srv.close()
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()

    # -- state ----------------------------------------------------------
    @property
    def params(self) -> np.ndarray:
        with self._lock:
            return self._params.copy()

    @property
    def num_updates(self) -> int:
        with self._lock:
            return self._updates

    # -- server loop ----------------------------------------------------
    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,), daemon=True).start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            while True:
                op = conn.recv(1)
                if not op or op == b"Q":
                    return
                if op == b"G":
                    grad = _recv_array(conn, max_bytes=self.max_frame_bytes)
                    with self._lock:
                        if grad.shape != self._params.shape:
                            conn.sendall(b"E")
                            self._m_rejects.inc()
                            continue
                        self._params -= self.learning_rate * grad
                        self._updates += 1
                        self._m_updates.set(self._updates)
                    self._m_pushes.inc()
                    conn.sendall(b"A")  # ack
                elif op == b"P":
                    with self._lock:
                        snapshot = self._params.copy()
                    self._m_pulls.inc()
                    _send_array(conn, snapshot)
                else:
                    return
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()


class ParameterServerClient:
    """Reference: nd4j ParameterServerClient (push/pull over the transport)."""

    def __init__(self, host: str, port: int, max_frame_bytes: Optional[int] = None):
        self._sock = socket.create_connection((host, port))
        self.max_frame_bytes = max_frame_bytes

    def push_gradient(self, grad: np.ndarray) -> None:
        self._sock.sendall(b"G")
        _send_array(self._sock, grad)
        ack = _recv_exact(self._sock, 1)
        if ack != b"A":
            raise RuntimeError("parameter server rejected gradient (shape mismatch)")

    def pull_params(self) -> np.ndarray:
        self._sock.sendall(b"P")
        if self.max_frame_bytes is not None:
            return _recv_array(self._sock, max_bytes=self.max_frame_bytes)
        return _recv_array(self._sock)

    def close(self) -> None:
        try:
            self._sock.sendall(b"Q")
        except OSError:
            pass
        self._sock.close()


class ParameterServerParallelWrapper:
    """Asynchronous data parallelism through the parameter server.

    Reference: ParameterServerParallelWrapper.java — N trainer threads, each
    with a model replica, pushing gradients and pulling fresh parameters
    per minibatch (no barrier; the 'hogwild-over-transport' topology).

    Mesh handling folds onto :class:`~.layout.MeshLayout` (the one
    layout/spec source): pass ``layout=`` and the wrapper DT008-validates
    the net's param specs against it up front (``layout.validate``) and
    places every pulled snapshot with ``layout.put_params`` so replicas
    live on the layout's shardings instead of a bespoke placement rule.
    The flat wire vector comes from ``jax.flatten_util.ravel_pytree`` —
    no hand-rolled shape/offset bookkeeping to drift from the net.
    """

    def __init__(self, net, workers: int = 2, learning_rate: float = 0.01,
                 port: int = 0, layout=None):
        from jax.flatten_util import ravel_pytree  # noqa: PLC0415

        self.net = net
        net.init()
        self.layout = layout
        if layout is not None:
            findings = layout.validate(
                net.params, net=net,
                source="<ParameterServerParallelWrapper>")
            errors = [f for f in findings if f.severity == "error"]
            if errors:
                raise ValueError(
                    "layout failed DT008 validation: "
                    + "; ".join(f.message for f in errors))
        self.workers = int(workers)
        flat, self._unravel = ravel_pytree(net.params)
        self.server = ParameterServer(
            np.ascontiguousarray(np.asarray(flat), np.float32),
            learning_rate=learning_rate, port=port)

    def _unflatten(self, flat: np.ndarray):
        params = self._unravel(np.asarray(flat))
        if self.layout is not None and self.layout.mesh is not None:
            params = self.layout.put_params(params)
        return params

    def _flatten_tree(self, tree) -> np.ndarray:
        from jax.flatten_util import ravel_pytree  # noqa: PLC0415

        return np.ascontiguousarray(
            np.asarray(ravel_pytree(tree)[0]), np.float32)

    def fit(self, data, epochs: int = 1) -> "ParameterServerParallelWrapper":
        import jax  # noqa: PLC0415

        from ..datasets.iterators import as_iterator

        net = self.net
        grad_fn = jax.jit(
            lambda p, state, x, y, rng: jax.grad(
                lambda pp: net._loss(pp, state, x, y, rng, True)[0]
            )(p)
        )

        def worker(batches: List, seed: int):
            client = ParameterServerClient(self.server.host, self.server.port)
            rng = jax.random.PRNGKey(seed)
            try:
                for ds in batches:
                    params = self._unflatten(client.pull_params())
                    rng, k = jax.random.split(rng)
                    grads = grad_fn(params, net.state, ds.features, ds.labels, k)
                    client.push_gradient(self._flatten_tree(grads))
            finally:
                client.close()

        for _ in range(epochs):
            it = as_iterator(data)
            if hasattr(it, "reset"):
                it.reset()
            shards: List[List] = [[] for _ in range(self.workers)]
            for i, ds in enumerate(it):
                shards[i % self.workers].append(ds)
            threads = [
                threading.Thread(target=worker, args=(shard, i), daemon=True)
                for i, shard in enumerate(shards) if shard
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        net.params = self._unflatten(self.server.params)
        return self

    def shutdown(self) -> None:
        self.server.shutdown()
