"""Distributed training front-ends.

Reference (SURVEY.md §2.4 "Spark front-ends"): SparkDl4jMultiLayer.java (656
LoC: fit/evaluate/scoring on RDDs through a TrainingMaster) and
SparkComputationGraph.java. The TPU-native analog wraps a model + a
TrainingMaster strategy over the device mesh: fit routes through the master
(sync all-reduce or periodic averaging), evaluation/scoring run on the
trained replica — the same one-stop surface without a cluster framework in
the middle.
"""

from __future__ import annotations

from typing import Optional

from .training_master import SyncAllReduceTrainingMaster, TrainingMaster


class MeshDl4jMultiLayer:
    """reference: spark/impl/multilayer/SparkDl4jMultiLayer.java."""

    def __init__(self, net, training_master: Optional[TrainingMaster] = None):
        self.net = net
        self.training_master = training_master or SyncAllReduceTrainingMaster()

    def fit(self, data, epochs: int = 1):
        """reference: SparkDl4jMultiLayer.fit(JavaRDD<DataSet>)."""
        self.training_master.execute_training(self.net, data, epochs=epochs)
        return self.net

    def evaluate(self, data, top_n: int = 1):
        """reference: SparkDl4jMultiLayer.evaluate → Evaluation."""
        return self.net.evaluate(data, top_n=top_n)

    def score(self, data) -> float:
        """reference: SparkDl4jMultiLayer.calculateScore."""
        from ..datasets.iterators import as_iterator  # noqa: PLC0415

        total, n = 0.0, 0
        for ds in as_iterator(data):
            b = ds.num_examples()
            total += float(self.net.score(ds)) * b
            n += b
        return total / max(n, 1)

    def get_network(self):
        return self.net

    def get_training_master_stats(self):
        return self.training_master.get_stats()


class MeshComputationGraph(MeshDl4jMultiLayer):
    """reference: spark/impl/graph/SparkComputationGraph.java — identical
    surface over a ComputationGraph (the master SPI is model-agnostic)."""
