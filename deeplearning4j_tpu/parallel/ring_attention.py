"""Ring attention — sequence/context parallelism over the device mesh.

The reference has NO long-context machinery (SURVEY.md §5.7: TBPTT + masking
only, no attention of any kind in 2016). This module is the framework's
first-class long-context tier, built the TPU way (prompt requirement): Q/K/V
live sharded over a ``seq`` mesh axis; each device computes attention of its
query shard against every key/value shard while K/V blocks rotate around the
ICI ring via ``lax.ppermute``. Accumulation uses the online-softmax
(flash-attention) recurrence so nothing materializes beyond one [Tq_local,
Tk_local] score block per step — sequence length scales with the number of
devices at constant per-device memory.

Layout: [batch, heads, time, head_dim], time sharded. Collectives ride ICI
(mesh axis order puts ``seq`` innermost) — the design recipe of the scaling
book: pick a mesh, annotate shardings, let XLA overlap the ppermute with the
block matmuls.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _block_accumulate(q, k, v, m, l, o, scale, causal, q_off, k_off,
                      kmask=None):
    """One online-softmax accumulation of a K/V block into (m, l, o).

    q [B,H,Tq,D]; k,v [B,H,Tk,D]; m,l [B,H,Tq]; o [B,H,Tq,D].
    ``q_off``/``k_off`` are the blocks' global time offsets for causal masks;
    ``kmask`` [B,Tk] marks valid (1) vs padded (0) keys — padded keys get
    score -inf (NOT zero: zero would keep softmax mass exp(0)).
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    valid = None
    if causal:
        Tq, Tk = q.shape[2], k.shape[2]
        qi = q_off + lax.broadcasted_iota(jnp.int32, (Tq, Tk), 0)
        ki = k_off + lax.broadcasted_iota(jnp.int32, (Tq, Tk), 1)
        valid = (qi >= ki)[None, None]
    if kmask is not None:
        km = kmask[:, None, None, :].astype(bool)
        valid = km if valid is None else jnp.logical_and(valid, km)
    if valid is not None:
        s = jnp.where(valid, s, _NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # guard fully-masked rows (m_new == -inf): exp(-inf - -inf) would be NaN
    m_safe = jnp.where(m_new <= _NEG_INF, 0.0, m_new)
    p = jnp.exp(s - m_safe[..., None])
    if valid is not None:
        p = jnp.where(valid, p, 0.0)
    corr = jnp.exp(jnp.where(m <= _NEG_INF, _NEG_INF, m - m_safe))
    l_new = l * corr + p.sum(axis=-1)
    o_new = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return m_new, l_new, o_new


def attention(q, k, v, causal: bool = False, scale: Optional[float] = None,
              key_mask=None):
    """Single-device softmax attention (the ring's local/reference case).
    ``key_mask`` [B,T]: 1 = real key, 0 = padding (excluded via -inf score)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    B, H, Tq, D = q.shape
    m = jnp.full((B, H, Tq), _NEG_INF, q.dtype)
    l = jnp.zeros((B, H, Tq), q.dtype)
    o = jnp.zeros((B, H, Tq, D), q.dtype)
    m, l, o = _block_accumulate(q, k, v, m, l, o, scale, causal, 0, 0, key_mask)
    return o / jnp.maximum(l, 1e-30)[..., None]


def _batch_entry(mesh, batch_axes):
    """The PartitionSpec batch-dim entry for the live batch axes (size-1
    axes trimmed): shard_map treats every mesh axis as manual, so a batch
    axis left out of the in_specs would force GSPMD to all-gather the
    activations over it at the region boundary."""
    live = tuple(a for a in (batch_axes or ()) if mesh.shape.get(a, 1) > 1)
    return live if live else None


def ring_attention(q, k, v, mesh, seq_axis: str = "seq",
                   causal: bool = False, scale: Optional[float] = None,
                   key_mask=None, batch_axes=()):
    """Sequence-parallel attention: time axis sharded over ``seq_axis``.

    Full q/k/v are passed in [B,H,T,D]; shard_map splits T over the mesh
    axis and the K/V shards circulate the ring (P-1 ppermute hops); the
    ``key_mask`` [B,T] shard (padding exclusion) travels with its K block.
    ``batch_axes`` names the mesh axes the batch dim is sharded over
    (kept sharded inside the region). The result equals :func:`attention`
    on the gathered arrays.
    """
    from jax.sharding import PartitionSpec as P  # noqa: PLC0415

    try:
        from jax import shard_map  # noqa: PLC0415
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map  # noqa: PLC0415

    scale = scale if scale is not None else q.shape[-1] ** -0.5
    n_shards = mesh.shape[seq_axis]
    batch = _batch_entry(mesh, batch_axes)
    spec = P(batch, None, seq_axis, None)
    mspec = P(batch, seq_axis)

    local = functools.partial(
        _ring_local, n_shards=n_shards, seq_axis=seq_axis,
        causal=causal, scale=scale,
    )
    if key_mask is None:
        return shard_map(
            local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        )(q, k, v)
    return shard_map(
        functools.partial(local, masked=True), mesh=mesh,
        in_specs=(spec, spec, spec, mspec), out_specs=spec,
    )(q, k, v, key_mask)


def _ring_local(q, k, v, kmask=None, *, n_shards, seq_axis, causal, scale,
                masked: bool = False):
    idx = lax.axis_index(seq_axis)
    B, H, Tq, D = q.shape
    m = jnp.full((B, H, Tq), _NEG_INF, q.dtype)
    l = jnp.zeros((B, H, Tq), q.dtype)
    o = jnp.zeros((B, H, Tq, D), q.dtype)
    q_off = idx * Tq
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]

    k_cur, v_cur, km_cur = k, v, kmask
    for step in range(n_shards):
        src = (idx - step) % n_shards  # origin device of the current K/V block
        m, l, o = _block_accumulate(
            q, k_cur, v_cur, m, l, o, scale, causal, q_off, src * Tq, km_cur
        )
        if step + 1 < n_shards:
            # rotate K/V (and their mask) one hop around the ICI ring
            k_cur = lax.ppermute(k_cur, seq_axis, perm)
            v_cur = lax.ppermute(v_cur, seq_axis, perm)
            if km_cur is not None:
                km_cur = lax.ppermute(km_cur, seq_axis, perm)
    return o / jnp.maximum(l, 1e-30)[..., None]


def all_to_all_attention(q, k, v, mesh, seq_axis: str = "seq",
                         causal: bool = False, scale: Optional[float] = None,
                         key_mask=None, batch_axes=()):
    """DeepSpeed-Ulysses-style sequence parallelism: all-to-all swaps the
    sharded axis from time to heads, computes full-sequence attention locally
    per head group, and swaps back. Complements ring attention: better when
    heads ≥ devices and the full sequence fits per device."""
    from jax.sharding import PartitionSpec as P  # noqa: PLC0415

    try:
        from jax import shard_map  # noqa: PLC0415
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map  # noqa: PLC0415

    scale = scale if scale is not None else q.shape[-1] ** -0.5
    n = mesh.shape[seq_axis]
    if q.shape[1] % n != 0:
        raise ValueError(f"heads ({q.shape[1]}) must divide mesh axis ({n})")
    batch = _batch_entry(mesh, batch_axes)
    spec = P(batch, None, seq_axis, None)
    mspec = P(batch, seq_axis)

    def local(q, k, v, kmask=None):
        # [B, H, T/n, D] -> all_to_all -> [B, H/n, T, D]
        def swap_in(x):
            return lax.all_to_all(x, seq_axis, split_axis=1, concat_axis=2,
                                  tiled=True)

        def swap_out(x):
            return lax.all_to_all(x, seq_axis, split_axis=2, concat_axis=1,
                                  tiled=True)

        if kmask is not None:
            # heads axis is fully replicated in the mask; gather time shards
            kmask = lax.all_gather(kmask, seq_axis, axis=1, tiled=True)
        out = attention(swap_in(q), swap_in(k), swap_in(v),
                        causal=causal, scale=scale, key_mask=kmask)
        return swap_out(out)

    if key_mask is None:
        return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec)(q, k, v)
    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec, mspec),
                     out_specs=spec)(q, k, v, key_mask)
