"""Pipeline parallelism: a first-class "pipe" mesh axis on MeshLayout.

No counterpart exists in the reference (SURVEY.md §2.4: DL4J 0.7's only
strategy is data parallelism) — this is the last axis of the framework's
distributed-first extension set (dp / fsdp / tp / sp / **pp**).

Two tiers live here:

1. The legacy GPipe primitives (``stack_stage_params`` /
   ``pipeline_shardings`` / ``pipeline_apply`` / ``sequential_apply``):
   homogeneous stacked blocks, a ``lax.scan`` over the schedule ticks.
   ``pipeline_shardings`` used to hand-build its own NamedSharding rule —
   it now routes through :meth:`MeshLayout.from_mesh` + ``stage_spec`` so
   the one layout/spec source covers it (and DT008 validates the result).
   ``sequential_apply`` stays bit-exact as the regression oracle.

2. :class:`PipelinedTrainer`: ``MeshLayout(pipe=P)`` stages a
   MultiLayerNetwork's layer list across the pipe axis with an interleaved
   micro-batch schedule (stage *s* runs micro-batch *m* at tick ``m + s``;
   the backward pipeline — one backward per forward, in reverse tick order
   — falls out of ``jax.grad`` through the unrolled schedule). Stage
   handoffs are ``shard_map`` ``ppermute`` sends over ICI with
   double-buffered activation stashes (the in-flight ``recv`` buffer plus
   the tick's outgoing ``y``); stage partitioning is cost-balanced by the
   per-layer FLOPs/bytes walker (:func:`plan_stages`) instead of naive
   equal-count splits. The whole step is ONE jitted SPMD program admitted
   through the CompileManager (zero warm compiles), the sharding-flow pass
   walks it natively (per-microbatch ppermute attribution, DT306), HBM
   preflight projects stage params + stashed activations × in-flight
   micro-batches, and the roofline gains the bubble term
   ``(P-1)/(M+P-1)``.

Composition contract (see docs/distributed.md "Pipeline axis"):

- **pipe × data**: micro-batches shard over the batch axes inside the
  manual region; the gradient all-reduce over ``data`` is inserted by
  shard_map's transpose (stage params carry no data axis in their specs).
- **pipe × fsdp**: the packed per-stage parameter vector STORES its flat
  dim sharded over ``fsdp`` (ZeRO-3), but the region's in_spec drops the
  fsdp name, so GSPMD un-shards it ONCE at the region boundary per step —
  never per micro-batch (DT306 polices the per-tick variant).
- **pipe × tp**: the stage bodies run full-manual (this jaxlib cannot
  partially-auto a shard_map region — XLA hard-crashes on
  ``IsManualSubgroup`` mismatches), so tp applies to the replicated output
  head via the ordinary spec rules, not inside stages.
- **pipe × seq**: rejected loudly — the schedule owns the region and the
  ring kernels cannot run inside it.

The schedule ticks are Python-unrolled (M + P - 1 ticks), deliberately:
the measured census parses post-SPMD HLO *text*, where a collective inside
``lax.scan`` appears once regardless of trip count — unrolling keeps
predicted == measured per-microbatch attribution exact.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "PipelinePlan",
    "PipelinedTrainer",
    "pipeline_apply",
    "pipeline_shardings",
    "plan_stages",
    "sequential_apply",
    "stack_stage_params",
]


# --------------------------------------------------------------- legacy GPipe
def stack_stage_params(per_stage_params):
    """[stage0_tree, stage1_tree, ...] -> one tree with leading stage dim."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params
    )


def pipeline_shardings(stacked_params, mesh, axis: str = "pipe"):
    """NamedShardings placing each stage's slice on its pipe-axis device.

    Routed through :meth:`MeshLayout.from_mesh` — the layout is the ONE
    sharding rule source (``stage_spec``: dim 0 over the pipe axis), and the
    resulting specs are DT008-validated against the mesh before any data
    moves. The old hand-built NamedSharding rule silently diverged from the
    layout layer; a bad axis/mesh combination now fails loudly here."""
    from jax.sharding import PartitionSpec as P

    from ..analysis import check_partition_specs
    from .layout import MeshLayout

    if axis not in mesh.shape:
        raise ValueError(
            f"pipeline axis '{axis}' not in mesh axes {tuple(mesh.shape)}")
    layout = MeshLayout.from_mesh(mesh)
    if axis == "pipe":
        specs = layout.stage_specs(stacked_params)
    else:  # a legacy mesh that names its stage axis differently
        specs = jax.tree_util.tree_map(lambda a: P(axis), stacked_params)
    findings = check_partition_specs(specs, mesh, stacked_params,
                                     source="<pipeline_shardings>")
    if findings:
        raise ValueError(
            "pipeline_shardings failed DT008 validation: "
            + "; ".join(f.message for f in findings))
    return jax.tree_util.tree_map(
        layout.sharding, specs,
        is_leaf=lambda x: isinstance(x, P))


def pipeline_apply(block_fn: Callable, stacked_params, microbatches, mesh,
                   axis: str = "pipe"):
    """Apply P homogeneous stages as a pipeline over M microbatches.

    ``block_fn(stage_params, x) -> y`` with y.shape == x.shape (homogeneous
    contract); ``stacked_params``: leaves [P, ...] (use
    :func:`stack_stage_params` / :func:`pipeline_shardings`);
    ``microbatches``: [M, mb, ...]. Returns [M, mb, ...] — the composition
    block_{P-1}(...block_0(x)) per microbatch, computed with the GPipe
    schedule. Differentiable end-to-end.
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    n_stages = mesh.shape[axis]
    m = microbatches.shape[0]
    n_stacked = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_stacked != n_stages:
        # a divisible mismatch would otherwise silently run a SUBSET of
        # stages (each device keeps only slice [0] of its local shard)
        raise ValueError(
            f"{n_stacked} stacked stages but the '{axis}' mesh axis has "
            f"{n_stages} devices; one stage per device is the contract"
        )

    def per_stage(params, xs):
        # params: local stage slice with leading dim 1; xs: full [M, mb, ...]
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        idx = jax.lax.axis_index(axis)
        mb_shape = xs.shape[1:]
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(recv, t):
            # stage 0 injects microbatch t; later stages consume what the
            # previous stage sent last tick (drained-feed ticks are bubble
            # ticks, replaced below)
            feed = xs[jnp.clip(t, 0, m - 1)]
            x_in = jnp.where(idx == 0, feed, recv)
            # Bubble ticks (stage idx is busy only for idx <= t < m + idx)
            # must compute on SAFE inputs, not the zero filler: reverse-mode
            # AD multiplies the dropped outputs' zero cotangents by the
            # block's partials, and 0 * NaN = NaN (the jnp.where trap) — a
            # block like x/||x|| would poison gradients from the zeros.
            valid = (t >= idx) & (t < m + idx)
            x_in = jnp.where(valid, x_in, jnp.ones(mb_shape, xs.dtype))
            y = block_fn(params, x_in)
            return jax.lax.ppermute(y, axis, perm), y

        recv0 = jnp.zeros(mb_shape, xs.dtype)
        _, ys = jax.lax.scan(tick, recv0, jnp.arange(m + n_stages - 1))
        # microbatch j completes on the LAST stage at tick j + P - 1; a
        # masked psum hands every stage the gathered outputs (out_specs
        # replicate, so each device must return the same array). where (not
        # multiply) so bubble-tick NaNs on earlier stages cannot poison the
        # sum (NaN * 0 == NaN).
        outs = ys[n_stages - 1 :]  # [M, mb, ...]
        outs = jnp.where(idx == n_stages - 1, outs, jnp.zeros_like(outs))
        return jax.lax.psum(outs, axis)

    param_specs = jax.tree_util.tree_map(
        lambda a: P(axis, *([None] * (a.ndim - 1))), stacked_params
    )
    fn = shard_map(
        per_stage,
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_rep=False,
    )
    return fn(stacked_params, microbatches)


def sequential_apply(block_fn: Callable, stacked_params, microbatches):
    """Reference semantics: the same composition without the pipeline —
    the bit-exact regression oracle for tests and single-device fallback."""
    n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]

    def one(x):
        for i in range(n_stages):
            params_i = jax.tree_util.tree_map(lambda a: a[i], stacked_params)
            x = block_fn(params_i, x)
        return x

    return jax.vmap(one)(microbatches)


# ------------------------------------------------------------ stage planning
@dataclass(frozen=True)
class PipelinePlan:
    """Contiguous assignment of a net's hidden layers to pipeline stages.

    ``stages[s]`` lists the layer indices stage ``s`` runs (in order);
    ``costs[s]`` is the stage's static roofline weight (compute seconds +
    memory seconds at the planning batch). The output layer (index
    ``out_index``) never joins a stage — it runs replicated outside the
    pipelined region so the loss head composes with tp/fsdp via the
    ordinary spec rules."""

    stages: Tuple[Tuple[int, ...], ...]
    costs: Tuple[float, ...]
    layer_costs: Tuple[float, ...]
    out_index: int
    balanced: bool

    @property
    def n_stages(self) -> int:
        return len(self.stages)

    @property
    def max_cost(self) -> float:
        return max(self.costs) if self.costs else 0.0

    def describe(self) -> dict:
        return {
            "stages": [list(s) for s in self.stages],
            "stage_costs": [round(c, 9) for c in self.costs],
            "max_stage_cost": round(self.max_cost, 9),
            "out_index": self.out_index,
            "balanced": self.balanced,
        }


def _hidden_layer_costs(net, batch_or_struct) -> List[float]:
    """Static per-hidden-layer weight from the FLOPs/bytes walker: cost of
    the forward prefix through layer ``i`` minus the prefix through
    ``i - 1`` (preprocessors and dtype casts land on the layer that owns
    them). Falls back to the memory report's per-layer bytes when the
    walker cannot trace a layer."""
    from ..analysis.cost_model import roofline_params, static_cost
    from ..telemetry.memory import _input_structs

    net.init()
    out_idx = len(net.conf.layers) - 1
    x_struct = _input_structs(net, batch_or_struct)[0]
    rl = roofline_params()
    peak = float(rl.get("peak_flops") or 1.0)
    bw = float(rl.get("hbm_gbps") or 1.0) * 1e9
    try:
        prefix = [0.0]
        for i in range(1, out_idx + 1):
            cost = static_cost(
                lambda p, x, _i=i: net._forward(
                    p, x, net.state, False, None, upto=_i)[0],
                net.params, x_struct)
            prefix.append(cost["flops"] / peak + cost["hbm_bytes"] / bw)
        return [max(prefix[i + 1] - prefix[i], 1e-12)
                for i in range(out_idx)]
    except Exception:
        from ..telemetry.memory import memory_report

        rows = memory_report(net, batch_or_struct)["layers"]
        return [max(float(rows[i]["total_bytes"]), 1.0) / bw
                for i in range(out_idx)]


def _balanced_partition(costs: Sequence[float], k: int) -> List[Tuple[int, ...]]:
    """Contiguous partition of ``costs`` into ``k`` non-empty groups
    minimizing the max group sum (classic linear-partition DP)."""
    n = len(costs)
    prefix = [0.0]
    for c in costs:
        prefix.append(prefix[-1] + float(c))

    def seg(i, j):  # cost of layers [i, j)
        return prefix[j] - prefix[i]

    # best[g][j] = minimal max-cost splitting the first j layers into g
    best = [[math.inf] * (n + 1) for _ in range(k + 1)]
    cut = [[0] * (n + 1) for _ in range(k + 1)]
    best[0][0] = 0.0
    for g in range(1, k + 1):
        for j in range(g, n + 1):
            for i in range(g - 1, j):
                cand = max(best[g - 1][i], seg(i, j))
                if cand < best[g][j]:
                    best[g][j] = cand
                    cut[g][j] = i
    bounds = [n]
    for g in range(k, 0, -1):
        bounds.append(cut[g][bounds[-1]])
    bounds.reverse()
    return [tuple(range(bounds[g], bounds[g + 1])) for g in range(k)]


def plan_stages(net, n_stages: int, batch_or_struct=None, *,
                balance: bool = True) -> PipelinePlan:
    """Partition a net's hidden layers into ``n_stages`` contiguous pipeline
    stages. ``balance=True`` (default) minimizes the max per-stage static
    cost via the per-layer FLOPs/bytes walker; ``balance=False`` is the
    naive equal-count split (kept for A/B benchmarking — the balanced plan
    must beat it on skewed models, tests/test_pipeline_axis.py asserts
    it)."""
    net.init()
    conf = net.conf
    if hasattr(conf, "vertices"):
        # ComputationGraph: topo order is the staging order; per-vertex
        # bytes from the memory report weigh the split
        from ..telemetry.memory import memory_report

        rows = memory_report(net, batch_or_struct)["layers"]
        n_hidden = len(rows) - 1
        costs = [max(float(rows[i]["total_bytes"]), 1.0)
                 for i in range(n_hidden)]
        out_idx = n_hidden
    else:
        out_idx = len(conf.layers) - 1
        costs = _hidden_layer_costs(net, batch_or_struct)
    if out_idx < n_stages:
        raise ValueError(
            f"cannot stage {out_idx} hidden layers across {n_stages} "
            "pipeline stages; need at least one layer per stage")
    if balance:
        stages = _balanced_partition(costs, n_stages)
    else:
        per = out_idx // n_stages
        extra = out_idx % n_stages
        stages, start = [], 0
        for s in range(n_stages):
            size = per + (1 if s < extra else 0)
            stages.append(tuple(range(start, start + size)))
            start += size
    stage_costs = tuple(sum(costs[i] for i in grp) for grp in stages)
    return PipelinePlan(stages=tuple(stages), costs=stage_costs,
                        layer_costs=tuple(costs), out_index=out_idx,
                        balanced=bool(balance))


# --------------------------------------------------------- pipelined trainer
def _flat_meta(tree):
    """(treedef, [(shape, dtype, size)...], total) for one layer's params."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    meta = [(tuple(np.shape(l)), np.dtype(l.dtype),
             int(np.prod(np.shape(l), dtype=np.int64)) if np.shape(l)
             else 1) for l in leaves]
    return treedef, meta, sum(m[2] for m in meta)


class PipelinedTrainer:
    """Train a ``MultiLayerNetwork`` on a ``MeshLayout(pipe=P)`` mesh.

    Hidden layers are staged across the pipe axis (:func:`plan_stages`,
    cost-balanced); each stage's parameters are packed into one flat
    per-stage vector (``[P, Lmax]``, dim 0 sharded over ``pipe``, dim 1
    over ``fsdp`` at zero_stage=3) so heterogeneous stages ride one
    ``lax.switch`` inside a single full-manual ``shard_map`` region. The
    output layer stays outside the region (replicated / tp-sharded by the
    ordinary spec rules) and sees the gathered hidden states in original
    batch order — the loss, regularization, RNG split chain and optimizer
    update all mirror ``MultiLayerNetwork._build_train_step``, which is
    what makes trajectory parity vs the unpiped net hold to float
    tolerance.

    Restrictions (all rejected loudly in ``__init__``): MultiLayerNetwork
    only, stateless deterministic hidden layers (no BN running stats, no
    dropout RNG inside stages), no seq axis, uniform parameter dtype."""

    def __init__(self, net, layout, *, microbatches: Optional[int] = None,
                 plan: Optional[PipelinePlan] = None, balance: bool = True,
                 batch_struct=None):
        from .layout import MeshLayout  # noqa: F401 (typing/doc aid)

        if layout.mesh is None:
            raise ValueError("PipelinedTrainer needs a concrete (non-"
                             "abstract) MeshLayout")
        if layout.pipe_size < 2:
            raise ValueError(
                f"layout has pipe={layout.pipe_size}; a pipeline needs "
                "pipe >= 2 (use MeshLayout(pipe=P))")
        if getattr(layout, "_seq_axis", None) is not None:
            raise ValueError(
                "pipe x seq is not supported: the pipelined region is "
                "full-manual over the whole mesh and the seq-axis ring "
                "kernels cannot run inside it; compose pipe with "
                "data/fsdp/tp instead")
        if hasattr(net.conf, "vertices"):
            raise NotImplementedError(
                "PipelinedTrainer stages MultiLayerNetwork layer lists; "
                "ComputationGraph vertex DAGs are plan-only for now "
                "(plan_stages works on both)")
        if microbatches is None:
            from ..tune.knobs import get_knob

            microbatches = int(get_knob("pipe_microbatches").default)
        if microbatches < 1:
            raise ValueError(f"microbatches must be >= 1, got {microbatches}")

        net.init()
        layout.precision.apply_to_net(net)
        self.net = net
        self.layout = layout
        self.mesh = layout.mesh
        self.n_stages = int(layout.pipe_size)
        self.microbatches = int(microbatches)
        self.plan = plan if plan is not None else plan_stages(
            net, self.n_stages, batch_struct, balance=balance)
        if self.plan.n_stages != self.n_stages:
            raise ValueError(
                f"plan has {self.plan.n_stages} stages but the layout's "
                f"pipe axis has {self.n_stages}")
        self._out_idx = self.plan.out_index
        layers = net.conf.layers
        for i in range(self._out_idx):
            if jax.tree_util.tree_leaves(net.state[i]):
                raise ValueError(
                    f"layer[{i}] ({type(layers[i]).__name__}) carries "
                    "mutable state; pipelined stages must be stateless")
        self._has_reg = any(
            getattr(l, a, 0) for l in layers
            for a in ("l1", "l2", "l1_bias", "l2_bias"))
        self._pack_params()
        self._place_train_state()
        self._boundaries = None  # resolved on first fit/analyze (needs mb)
        self._compiled = None
        self._exe_key = None
        from ..runtime.compile_manager import get_compile_manager

        self._cm = get_compile_manager()
        self._token = self._cm.new_token()
        self._rng = net._rng

    # ------------------------------------------------------------- packing
    def _pack_params(self) -> None:
        net, plan = self.net, self.plan
        fsdp = (self.layout._size(self.layout._fsdp_axis)
                if self.layout.zero_stage >= 3 else 1)
        dtypes = {np.dtype(l.dtype)
                  for i in range(self._out_idx)
                  for l in jax.tree_util.tree_leaves(net.params[i])}
        if len(dtypes) > 1:
            raise ValueError(
                f"pipelined stages need one uniform param dtype, found "
                f"{sorted(str(d) for d in dtypes)}")
        self._pack_dtype = dtypes.pop() if dtypes else np.dtype("float32")
        self._layer_meta = {}
        stage_lens = []
        for s, grp in enumerate(plan.stages):
            off = 0
            for li in grp:
                treedef, meta, size = _flat_meta(net.params[li])
                self._layer_meta[li] = (s, off, treedef, meta)
                off += size
            stage_lens.append(off)
        lmax = max(stage_lens) if stage_lens else 1
        if fsdp > 1:
            lmax = ((lmax + fsdp - 1) // fsdp) * fsdp
        self._stage_lens = stage_lens
        self._lmax = int(max(lmax, 1))
        packed = np.zeros((self.n_stages, self._lmax), self._pack_dtype)
        for li, (s, off, _td, meta) in self._layer_meta.items():
            pos = off
            for leaf, (_shape, _dt, size) in zip(
                    jax.tree_util.tree_leaves(net.params[li]), meta):
                packed[s, pos:pos + size] = np.asarray(leaf).reshape(-1)
                pos += size
        self._packed_host = packed
        self._fsdp_packed = fsdp > 1

    def _unpack_layer(self, flat, li):
        """Layer ``li``'s param pytree from one stage's flat vector."""
        s, off, treedef, meta = self._layer_meta[li]
        leaves, pos = [], off
        for shape, dt, size in meta:
            leaves.append(flat[pos:pos + size].reshape(shape).astype(dt))
            pos += size
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def unpack_to_net(self):
        """Write the live packed stage params (and the head) back onto
        ``net.params`` — checkpointing and the parity tests read the net."""
        packed = np.asarray(self._pt["stages"])
        params = list(self.net.params)
        for li in range(self._out_idx):
            s, _off, _td, _meta = self._layer_meta[li]
            params[li] = self._unpack_layer(jnp.asarray(packed[s]), li)
        params[self._out_idx] = self._pt["head"]
        self.net.params = params if isinstance(self.net.params, list) \
            else type(self.net.params)(params)
        return self.net

    # ----------------------------------------------------------- placement
    def _specs(self):
        from jax.sharding import PartitionSpec as P

        packed_spec = P("pipe", "fsdp") if self._fsdp_packed else P("pipe")
        head_specs = self.layout.param_specs(self.net.params[self._out_idx])
        return {"stages": packed_spec, "head": head_specs}

    def _opt_specs_tree(self, opt_state):
        """Moment leaves mirror their param's shape — match [P, Lmax]
        leaves to the packed spec, head-shaped leaves to head specs,
        scalars replicate (the same 'moments follow their param' rule
        MeshLayout.opt_specs applies)."""
        from jax.sharding import PartitionSpec as P

        packed_spec = (P("pipe", "fsdp") if self._fsdp_packed
                       else P("pipe"))
        head_shapes = {
            tuple(np.shape(l))
            for l in jax.tree_util.tree_leaves(
                self.net.params[self._out_idx])}
        packed_shape = (self.n_stages, self._lmax)

        def spec_of(leaf):
            shape = tuple(np.shape(leaf))
            if shape == packed_shape:
                return packed_spec
            if shape in head_shapes and shape:
                return self.layout.param_spec(shape)
            return P()

        return jax.tree_util.tree_map(spec_of, opt_state)

    def _place_train_state(self) -> None:
        lo = self.layout
        specs = self._specs()
        pt = {"stages": jnp.asarray(self._packed_host),
              "head": self.net.params[self._out_idx]}
        self._pt = jax.tree_util.tree_map(
            lambda a, s: lo.put(a, lo.sharding(s)), pt,
            {"stages": specs["stages"], "head": specs["head"]},
            is_leaf=lambda x: not isinstance(x, dict))
        opt = self.net._tx.init(self._pt)
        opt_specs = self._opt_specs_tree(opt)
        self._opt = jax.tree_util.tree_map(
            lambda a, s: lo.put(a, lo.sharding(s)), opt, opt_specs)
        self._pt_specs = specs
        self._opt_spec_tree = opt_specs

    # ---------------------------------------------------------- boundaries
    def _resolve_boundaries(self, mb: int, feat_shape, dtype) -> dict:
        """Per-microbatch boundary shapes entering each stage (plus the
        head), and the flat-padded handoff width Dmax. ``feat_shape`` is
        the REAL per-example feature shape — recurrent nets must trace at
        the batch's actual sequence length, not a probe default."""
        net = self.net
        x_struct = jax.ShapeDtypeStruct((mb,) + tuple(feat_shape),
                                        np.dtype(dtype))
        firsts = [grp[0] for grp in self.plan.stages] + [self._out_idx]
        shapes = []
        for k in firsts:
            if k == 0:
                shapes.append(tuple(x_struct.shape))
                continue
            h = jax.eval_shape(
                lambda x, _k=k: net._forward(
                    net.params, x, net.state, False, None, upto=_k)[0],
                x_struct)
            shapes.append(tuple(h.shape))
        elems = [int(np.prod(s[1:], dtype=np.int64)) for s in shapes]
        return {
            "mb": int(mb),
            "feat": tuple(feat_shape),
            "in_shapes": shapes[:-1],      # entering stage s
            "head_shape": shapes[-1],      # entering the output layer
            "in_elems": elems[:-1],
            "head_elems": elems[-1],
            "dmax": int(max(elems)),
            "x_dtype": x_struct.dtype,
        }

    # ------------------------------------------------------------ the step
    def _stage_branches(self, bnd, compute_dtype):
        """One branch per stage: unpad -> reshape -> preprocessor+layer
        chain -> flatten -> pad. All branches share the signature
        ``(x_pad [mb_local, Dmax], flat [Lmax]) -> y_pad`` lax.switch
        needs."""
        net, plan = self.net, self.plan
        layers = net.conf.layers
        dmax = bnd["dmax"]

        def make_branch(s):
            in_shape = bnd["in_shapes"][s]
            in_elems = bnd["in_elems"][s]

            def branch(x_pad, flat):
                mb_local = x_pad.shape[0]
                x = x_pad[:, :in_elems].reshape(
                    (mb_local,) + in_shape[1:])
                for li in plan.stages[s]:
                    pre = net.conf.preprocessors.get(li)
                    if pre is not None:
                        x = pre.apply(x)
                    p_li = self._unpack_layer(flat, li)
                    p_li = jax.tree_util.tree_map(
                        lambda a: a.astype(compute_dtype)
                        if jnp.issubdtype(a.dtype, jnp.floating) else a,
                        p_li)
                    x, _st = layers[li].apply(
                        p_li, x, net.state[li], train=True, rng=None,
                        mask=None)
                y = x.reshape(mb_local, -1)
                pad = dmax - y.shape[1]
                if pad:
                    y = jnp.pad(y, ((0, 0), (0, pad)))
                return y

            return branch

        return [make_branch(s) for s in range(self.n_stages)]

    def _build_step_fn(self, bnd):
        """The pure step: ``(pt, opt_state, xs_pad, y, rng) ->
        (pt, opt_state, loss)`` — value_and_grad through the pipelined
        forward, optax update, output shardings pinned to the declared
        specs (zero warm compiles: GSPMD must hand params back exactly
        where the next dispatch expects them)."""
        import optax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from ..nn.multilayer import _compute_cast

        net, lo = self.net, self.layout
        n_stages, m = self.n_stages, self.microbatches
        batch_axes = lo.batch_axes
        out_idx = self._out_idx
        conf_dtype = getattr(net.conf, "dtype", "float32")
        compute_dtype = jnp.dtype(
            "float32" if conf_dtype == "bfloat16" else conf_dtype)
        # x64 test runs trace f64 activations through f32-conf nets; the
        # handoff buffers follow whatever dtype the cast input carries
        branches = self._stage_branches(bnd, compute_dtype)
        dmax, mb = bnd["dmax"], bnd["mb"]
        head_shape, head_elems = bnd["head_shape"], bnd["head_elems"]
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        ticks = m + n_stages - 1

        def region(flat_local, xs_local, sid_local):
            # flat_local [1, Lmax]: the stage's FULL flat vector — under
            # ZeRO-3 the storage spec is P("pipe", "fsdp") but the region's
            # in_spec is P("pipe"), so GSPMD un-shards the packed params
            # ONCE at the region boundary (never per micro-batch tick), and
            # the shard_map transpose's automatic psum over the absent
            # batch axes is the gradient sync
            flat = flat_local[0]
            s = sid_local[0]
            recv = jnp.zeros(xs_local.shape[1:], xs_local.dtype)
            ys = []
            # Python-unrolled schedule: stage s computes micro-batch
            # (t - s) at tick t; unrolling (not lax.scan) keeps the
            # measured HLO census' per-microbatch ppermute counts equal to
            # the predicted ones (a collective inside scan shows up ONCE
            # in HLO text regardless of trip count)
            for t in range(ticks):
                feed = xs_local[min(t, m - 1)]
                x_in = jnp.where(s == 0, feed, recv)
                # bubble ticks compute on SAFE inputs (ones, not the zero
                # filler): 0 cotangent x NaN partial = NaN otherwise
                valid = (t >= s) & (t < m + s)
                x_in = jnp.where(valid, x_in, jnp.ones_like(x_in))
                y = jax.lax.switch(s, branches, x_in, flat)
                recv = jax.lax.ppermute(y, "pipe", perm)
                if t >= n_stages - 1:
                    ys.append(y)
            outs = jnp.stack(ys)  # [M, mb_local, Dmax]
            outs = jnp.where(s == n_stages - 1, outs, jnp.zeros_like(outs))
            outs = jax.lax.psum(outs, "pipe")
            # merge micro-batches INSIDE the manual region: the global
            # result is batch-sharded on dim 0 directly (each device's M
            # local micro-batch slices stay its rows), so the head sees a
            # canonically-sharded [B, Dmax] with NO resharding all-to-all —
            # _prepare_batch permutes the labels to the same row order
            return outs.reshape(-1, outs.shape[-1])

        packed_spec = self._pt_specs["stages"]
        region_sm = shard_map(
            region, mesh=self.mesh,
            in_specs=(P("pipe"), P(None, batch_axes or None), P("pipe")),
            out_specs=P(batch_axes or None),
            check_rep=False)

        layers = net.conf.layers

        def regularization(packed, head):
            reg = jnp.asarray(0.0)
            if not self._has_reg:
                return reg
            for li in range(out_idx):
                s, _o, _t, _m2 = self._layer_meta[li]
                reg = reg + layers[li].regularization_loss(
                    self._unpack_layer(packed[s], li))
            return reg + layers[out_idx].regularization_loss(head)

        def loss_of(pt, xs_pad, y, rng):
            fwd_rng, out_rng = (jax.random.split(rng)
                                if rng is not None else (None, None))
            del fwd_rng  # hidden stages are deterministic (no dropout)
            cast_packed, xs_pad = _compute_cast(
                conf_dtype, pt["stages"], xs_pad)
            sid = jnp.arange(n_stages, dtype=jnp.int32)
            h_pad = region_sm(cast_packed, xs_pad, sid)  # [M*mb, Dmax]
            h = h_pad[:, :head_elems].reshape(
                (m * mb,) + head_shape[1:])
            pre = net.conf.preprocessors.get(out_idx)
            if pre is not None:
                h = pre.apply(h)
            h32 = h.astype(jnp.float32) if h.dtype == jnp.bfloat16 else h
            # scalar shell, not h32[:1]: a batch-sharded row slice would
            # read as a (predicted) batch-axis gather in the flow pass
            cast_head, _ = _compute_cast(conf_dtype, pt["head"],
                                         jnp.zeros((), h32.dtype))
            loss = layers[out_idx].compute_loss(
                cast_head, h32, y, None, train=True, rng=out_rng)
            return loss + regularization(pt["stages"], pt["head"])

        tx = net._tx
        pt_shardings = {
            "stages": NamedSharding(self.mesh, packed_spec),
            "head": jax.tree_util.tree_map(
                lo.sharding, self._pt_specs["head"],
                is_leaf=lambda x: isinstance(x, P)),
        }
        opt_shardings = jax.tree_util.tree_map(
            lo.sharding, self._opt_spec_tree)

        ls = getattr(net.conf, "loss_scale", None)

        def step(pt, opt_state, xs_pad, y, rng):
            from ..nn.updaters import (  # noqa: PLC0415
                optimizer_update, scaled_loss, unscale_grads, unscale_loss)

            def scaled_loss_of(*a):
                return scaled_loss(loss_of(*a), ls)

            loss, grads = jax.value_and_grad(scaled_loss_of)(pt, xs_pad, y, rng)
            loss = unscale_loss(loss, ls)
            grads = unscale_grads(grads, ls)
            _, new_opt, new_pt = optimizer_update(tx, grads, opt_state, pt)
            new_pt = jax.tree_util.tree_map(
                jax.lax.with_sharding_constraint, new_pt, pt_shardings,
                is_leaf=lambda x: not isinstance(x, dict))
            new_opt = jax.tree_util.tree_map(
                jax.lax.with_sharding_constraint, new_opt, opt_shardings)
            return new_pt, new_opt, loss

        return step

    # -------------------------------------------------------------- fitting
    def _prepare_batch(self, x, y):
        """[B, ...] -> padded micro-batch stack [M, mb, Dmax] on the mesh
        (+ labels at the batch sharding)."""
        lo, m = self.layout, self.microbatches
        x = np.asarray(x)
        b = x.shape[0]
        if b % m:
            raise ValueError(
                f"batch of {b} rows does not divide into {m} micro-batches")
        mb = b // m
        bf = lo.batch_factor
        if mb % max(bf, 1):
            raise ValueError(
                f"micro-batch of {mb} rows does not divide the batch "
                f"shard factor {bf} (data x fsdp)")
        if self._boundaries is None or self._boundaries["mb"] != mb \
                or self._boundaries["feat"] != tuple(x.shape[1:]):
            self._boundaries = self._resolve_boundaries(
                mb, x.shape[1:], x.dtype)
        bnd = self._boundaries
        flat = x.reshape(m, mb, -1)
        if flat.shape[-1] < bnd["dmax"]:
            flat = np.pad(flat, ((0, 0), (0, 0),
                                 (0, bnd["dmax"] - flat.shape[-1])))
        from jax.sharding import PartitionSpec as P

        batch_axes = lo.batch_axes or None
        xs_pad = lo.put(jnp.asarray(flat),
                        lo.sharding(P(None, batch_axes)))
        # the region emits [M*mb] rows grouped device-major (each batch
        # shard keeps its M micro-batch slices contiguous); permute the
        # labels to that order on the host — the row-wise loss + mean is
        # permutation-invariant, so the scalar and every gradient match
        # the unpiped step exactly
        y = np.asarray(y)
        g = np.arange(b)
        mbl = mb // max(bf, 1)
        d, rem = g // (m * mbl), g % (m * mbl)
        y_d = lo.put(jnp.asarray(y[rem // mbl * mb + d * mbl + rem % mbl]),
                     lo.batch_sharding())
        return xs_pad, y_d, bnd

    def _ensure_compiled(self, xs_pad, y_d):
        key = (self._token, "pipeline_step", self.microbatches,
               self.n_stages, tuple(xs_pad.shape), str(xs_pad.dtype),
               tuple(np.shape(y_d)))
        if self._exe_key == key and self._compiled is not None:
            return self._compiled
        bnd = self._boundaries
        step = self._build_step_fn(bnd)
        args = (self._pt, self._opt, xs_pad, y_d, self._rng)
        self._compiled = self._cm.aot(key, lambda: jax.jit(step), args)
        self._exe_key = key
        self._step_fn = step
        return self._compiled

    def fit_batch(self, x, y) -> float:
        """One pipelined optimizer step over ``x``/``y`` (B rows split into
        M micro-batches). Returns the loss."""
        xs_pad, y_d, _bnd = self._prepare_batch(x, y)
        exe = self._ensure_compiled(xs_pad, y_d)
        self._rng, step_key = jax.random.split(self._rng)
        self._pt, self._opt, loss = exe(self._pt, self._opt, xs_pad, y_d,
                                        step_key)
        return float(loss)

    def fit(self, x, y, steps: int = 1) -> List[float]:
        """``steps`` pipelined optimizer steps over the same batch (the
        bench/warmup loop). The first call pays the one AOT compile; every
        later call reuses the admitted executable (zero warm compiles).
        The batch is prepared and placed ONCE and the per-step losses are
        fetched at the end, so steady-state steps dispatch back-to-back
        without a host round-trip between them."""
        xs_pad, y_d, _bnd = self._prepare_batch(x, y)
        exe = self._ensure_compiled(xs_pad, y_d)
        losses = []
        for _ in range(int(steps)):
            self._rng, step_key = jax.random.split(self._rng)
            self._pt, self._opt, loss = exe(self._pt, self._opt, xs_pad,
                                            y_d, step_key)
            losses.append(loss)
        return [float(v) for v in losses]

    def warm_up(self, x, y) -> None:
        """Pay the AOT compile without taking an optimizer step."""
        xs_pad, y_d, _ = self._prepare_batch(x, y)
        self._ensure_compiled(xs_pad, y_d)

    # ------------------------------------------------------------- analysis
    def analyze(self, x, y) -> dict:
        """The sharding-flow pass over the REAL pipelined step (zero device
        dispatches): predicted collective census with per-microbatch
        ppermute attribution, DT300-DT306 findings (DT306 = per-microbatch
        collective inside a stage body), per-step comm bytes."""
        from jax.sharding import PartitionSpec as P

        from ..analysis.shard_flow import analyze_shard_flow

        xs_pad, y_d, bnd = self._prepare_batch(x, y)
        step = self._build_step_fn(bnd)
        batch_axes = self.layout.batch_axes or None
        in_specs = (
            {"stages": self._pt_specs["stages"],
             "head": self._pt_specs["head"]},
            self._opt_spec_tree,
            P(None, batch_axes),
            self.layout.batch_spec(),
            P(),
        )
        return analyze_shard_flow(
            step, (self._pt, self._opt, xs_pad, y_d, self._rng),
            in_specs, self.layout, param_argnums=(0, 1),
            pipeline_microbatches=self.microbatches,
            source="<pipelined_step>")

    def measured_census(self, x, y) -> List[dict]:
        """Collective census parsed from the compiled step's post-SPMD HLO
        (compiles on first use via the same AOT admission as fit)."""
        from ..analysis.shard_flow import hlo_collective_census

        xs_pad, y_d, _ = self._prepare_batch(x, y)
        exe = self._ensure_compiled(xs_pad, y_d)
        return hlo_collective_census(exe.as_text(), self.layout)

    def roofline(self, x, y) -> dict:
        """Static roofline of the pipelined step with the bubble-fraction
        term: per-device work divides across P stages and the schedule
        idles ``(P-1)/(M+P-1)`` of the mesh."""
        from ..analysis.cost_model import apply_roofline, static_cost

        xs_pad, y_d, bnd = self._prepare_batch(x, y)
        step = self._build_step_fn(bnd)
        cost = static_cost(step, self._pt, self._opt, xs_pad, y_d,
                           self._rng)
        flow = self.analyze(x, y)
        apply_roofline(cost, comm_bytes=flow["comm_bytes_per_step"],
                       pipeline={"stages": self.n_stages,
                                 "microbatches": self.microbatches})
        return cost

    def preflight(self, x, y=None, *, limit_bytes: Optional[int] = None,
                  headroom: float = 0.9) -> dict:
        """Per-device HBM projection of the pipelined step: the stage's
        packed param share (param + grad + moments over pipe/fsdp), the
        replicated head, the stashed activations — per-microbatch stage
        activations × the in-flight micro-batch count (every forward
        micro-batch's residuals wait for its backward) — and the
        double-buffered handoffs. Raises
        :class:`~deeplearning4j_tpu.telemetry.memory.MemoryPreflightError`
        when the worst stage exceeds the budget (an over-stash
        ``microbatches`` choice fails HERE, before a doomed compile)."""
        from ..telemetry.memory import (MemoryPreflightError, _hbm_limit,
                                        memory_report)

        m, p = self.microbatches, self.n_stages
        x = np.asarray(x)
        mb = x.shape[0] // m if x.shape[0] >= m else 1
        if self._boundaries is None or self._boundaries["mb"] != mb \
                or self._boundaries["feat"] != tuple(x.shape[1:]):
            self._boundaries = self._resolve_boundaries(
                mb, x.shape[1:], x.dtype)
        bnd = self._boundaries
        report = memory_report(self.net, x.shape[0])
        rows = report["layers"]
        itemsize = np.dtype(self._pack_dtype).itemsize
        fsdp = (self.layout._size(self.layout._fsdp_axis)
                if self._fsdp_packed else 1)
        packed_pd = self._lmax * itemsize / fsdp
        # moments: optax adam = 2 leaves mirroring the packed vector; read
        # the real opt tree instead of assuming
        opt_pd = sum(
            int(np.prod(np.shape(l), dtype=np.int64)) *
            np.dtype(l.dtype).itemsize
            for l in jax.tree_util.tree_leaves(self._opt)
            if tuple(np.shape(l)) == (p, self._lmax)) / (p * fsdp)
        head_pd = sum(r["param_bytes"] * 2 + r["opt_state_bytes"]
                      for r in rows[self._out_idx:self._out_idx + 1])
        bf = max(self.layout.batch_factor, 1)
        in_flight = m + p - 1  # unrolled ticks each stash residuals
        stage_rows = []
        for s, grp in enumerate(self.plan.stages):
            act_mb = sum(rows[i]["activation_bytes"] for i in grp) \
                / max(x.shape[0] // mb, 1) / bf
            handoff = 2 * mb * bnd["dmax"] * itemsize / bf
            stage_rows.append({
                "stage": s,
                "layers": list(grp),
                "param_bytes": int(2 * packed_pd),
                "opt_state_bytes": int(opt_pd),
                "stash_bytes": int(act_mb * in_flight),
                "handoff_bytes": int(handoff),
                "total_bytes": int(2 * packed_pd + opt_pd + head_pd
                                   + act_mb * in_flight + handoff),
            })
        projected = max(r["total_bytes"] for r in stage_rows)
        source = "explicit limit_bytes"
        if limit_bytes is None:
            limit_bytes, source = _hbm_limit()
        report["pipeline"] = {
            "stages": stage_rows,
            "microbatches": m,
            "in_flight": in_flight,
            "projected_peak_bytes_per_device": int(projected),
            "plan": self.plan.describe(),
        }
        if limit_bytes is None:
            report["preflight"] = {"checked": False, "reason": source}
            return report
        budget = int(limit_bytes * headroom)
        report["preflight"] = {
            "checked": True,
            "fits": projected <= budget,
            "projected_peak_bytes": int(projected),
            "per_device": True,
            "limit_bytes": int(limit_bytes),
            "headroom": headroom,
            "limit_source": source,
        }
        if projected > budget:
            worst = max(stage_rows, key=lambda r: r["total_bytes"])
            raise MemoryPreflightError(
                f"projected per-device pipeline peak "
                f"{projected / 2**20:.1f} MiB (stage {worst['stage']}: "
                f"{worst['stash_bytes'] / 2**20:.1f} MiB stashed over "
                f"{in_flight} in-flight micro-batch ticks) exceeds "
                f"{budget / 2**20:.1f} MiB ({headroom:.0%} of "
                f"{limit_bytes / 2**20:.1f} MiB from {source}); lower "
                "microbatches= or raise the budget",
                report, int(projected), int(limit_bytes))
        return report

    def describe(self) -> dict:
        return {
            "layout": self.layout.describe(),
            "plan": self.plan.describe(),
            "microbatches": self.microbatches,
            "bubble_fraction": round(
                (self.n_stages - 1)
                / (self.microbatches + self.n_stages - 1), 6),
            "packed_bytes": int(self.n_stages * self._lmax
                                * np.dtype(self._pack_dtype).itemsize),
        }
