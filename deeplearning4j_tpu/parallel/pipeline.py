"""Pipeline parallelism: GPipe-style microbatch schedule over a "pipe" mesh axis.

No counterpart exists in the reference (SURVEY.md §2.4: DL4J 0.7's only
strategy is data parallelism) — this is part of the framework's
distributed-first extension set (dp / tp / sp / ep / pp).

TPU-native design (the scaling-book recipe, functional form): the pipeline is
ONE jitted SPMD program under ``shard_map`` — each device along the pipe axis
holds one stage's parameters (stacked homogeneous blocks, leading dim sharded
over the axis) and a ``lax.scan`` runs the M + P - 1 schedule ticks. Stage 0
feeds a fresh microbatch each tick; activations hop stage-to-stage with
``ppermute`` over ICI; the last stage's outputs are gathered with a masked
psum. Because the whole schedule is pure JAX, ``jax.grad`` differentiates
straight through it — the backward pipeline (reverse ppermute chain) falls
out of autodiff instead of being hand-scheduled.

Homogeneous stages are the contract (identical block structure per stage —
the production-transformer case). Bubble fraction is (P-1)/(M+P-1): use
several microbatches per step.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np


def stack_stage_params(per_stage_params):
    """[stage0_tree, stage1_tree, ...] -> one tree with leading stage dim."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params
    )


def pipeline_shardings(stacked_params, mesh, axis: str = "pipe"):
    """NamedShardings placing each stage's slice on its pipe-axis device."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def rule(a):
        return NamedSharding(mesh, P(axis, *([None] * (np.ndim(a) - 1))))

    return jax.tree_util.tree_map(rule, stacked_params)


def pipeline_apply(block_fn: Callable, stacked_params, microbatches, mesh,
                   axis: str = "pipe"):
    """Apply P homogeneous stages as a pipeline over M microbatches.

    ``block_fn(stage_params, x) -> y`` with y.shape == x.shape (homogeneous
    contract); ``stacked_params``: leaves [P, ...] (use
    :func:`stack_stage_params` / :func:`pipeline_shardings`);
    ``microbatches``: [M, mb, ...]. Returns [M, mb, ...] — the composition
    block_{P-1}(...block_0(x)) per microbatch, computed with the GPipe
    schedule. Differentiable end-to-end.
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    n_stages = mesh.shape[axis]
    m = microbatches.shape[0]
    n_stacked = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_stacked != n_stages:
        # a divisible mismatch would otherwise silently run a SUBSET of
        # stages (each device keeps only slice [0] of its local shard)
        raise ValueError(
            f"{n_stacked} stacked stages but the '{axis}' mesh axis has "
            f"{n_stages} devices; one stage per device is the contract"
        )

    def per_stage(params, xs):
        # params: local stage slice with leading dim 1; xs: full [M, mb, ...]
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        idx = jax.lax.axis_index(axis)
        mb_shape = xs.shape[1:]
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(recv, t):
            # stage 0 injects microbatch t; later stages consume what the
            # previous stage sent last tick (drained-feed ticks are bubble
            # ticks, replaced below)
            feed = xs[jnp.clip(t, 0, m - 1)]
            x_in = jnp.where(idx == 0, feed, recv)
            # Bubble ticks (stage idx is busy only for idx <= t < m + idx)
            # must compute on SAFE inputs, not the zero filler: reverse-mode
            # AD multiplies the dropped outputs' zero cotangents by the
            # block's partials, and 0 * NaN = NaN (the jnp.where trap) — a
            # block like x/||x|| would poison gradients from the zeros.
            valid = (t >= idx) & (t < m + idx)
            x_in = jnp.where(valid, x_in, jnp.ones(mb_shape, xs.dtype))
            y = block_fn(params, x_in)
            return jax.lax.ppermute(y, axis, perm), y

        recv0 = jnp.zeros(mb_shape, xs.dtype)
        _, ys = jax.lax.scan(tick, recv0, jnp.arange(m + n_stages - 1))
        # microbatch j completes on the LAST stage at tick j + P - 1; a
        # masked psum hands every stage the gathered outputs (out_specs
        # replicate, so each device must return the same array). where (not
        # multiply) so bubble-tick NaNs on earlier stages cannot poison the
        # sum (NaN * 0 == NaN).
        outs = ys[n_stages - 1 :]  # [M, mb, ...]
        outs = jnp.where(idx == n_stages - 1, outs, jnp.zeros_like(outs))
        return jax.lax.psum(outs, axis)

    param_specs = jax.tree_util.tree_map(
        lambda a: P(axis, *([None] * (a.ndim - 1))), stacked_params
    )
    fn = shard_map(
        per_stage,
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_rep=False,
    )
    return fn(stacked_params, microbatches)


def sequential_apply(block_fn: Callable, stacked_params, microbatches):
    """Reference semantics: the same composition without the pipeline —
    for tests and single-device fallback."""
    n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]

    def one(x):
        for i in range(n_stages):
            params_i = jax.tree_util.tree_map(lambda a: a[i], stacked_params)
            x = block_fn(params_i, x)
        return x

    return jax.vmap(one)(microbatches)
