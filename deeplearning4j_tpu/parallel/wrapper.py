"""ParallelWrapper: multi-device data-parallel training on one mesh.

Reference: deeplearning4j-scaleout-parallelwrapper/.../ParallelWrapper.java:44
(fit loop :141-247, parameter averaging :170-235, updater-state averaging
:198-224). The reference spawns N replica threads pinned to devices, dispatches
minibatches round-robin, and every ``averaging_frequency`` iterations barriers
and calls ``Nd4j.averageAndPropagate``.

TPU-native design — the thread/queue machinery does not exist:

- ``averaging_frequency == 1`` (sync mode, the modern strictly-better default,
  SURVEY.md §5.8): params live replicated on the mesh, the global batch is
  sharded over the "data" axis, and the net's OWN jitted train step runs
  SPMD — XLA inserts the gradient all-reduce (psum) over ICI. Per-step
  all-reduce ≡ averaging every iteration, with none of the reference's barrier
  or propagate steps.

- ``averaging_frequency > 1`` (parameter-averaging parity mode): each device
  holds an INDEPENDENT replica (params stacked on a leading replica axis,
  sharded over "data"); ``jax.vmap`` of the train step over that axis runs all
  replicas in parallel with zero communication — the exact semantics of the
  reference's free-running threads — and a jitted averaging program (mean over
  the replica axis = all-reduce, broadcast back = all-gather) replaces
  ``Nd4j.averageAndPropagate``. Updater state averaging matches
  ``averageUpdaters`` (ParallelWrapper.java:198-224).

Every sharding this wrapper places comes from ONE authority — the
:class:`~deeplearning4j_tpu.parallel.layout.MeshLayout` (dp×fsdp×tp layout
rules + precision policy, docs/distributed.md); the wrapper is a thin
training strategy over it.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .layout import MeshLayout
from .mesh import make_mesh, global_put, global_put_local


def _stack_tree(tree, n: int):
    return jax.tree_util.tree_map(lambda a: jnp.stack([a] * n), tree)


def _mean_tree(tree):
    return jax.tree_util.tree_map(
        lambda a: jnp.mean(a, axis=0) if jnp.issubdtype(a.dtype, jnp.floating)
        else a[0],
        tree,
    )


class ParallelWrapper:
    """Data-parallel trainer over a device mesh (reference API:
    ParallelWrapper.Builder → workers/averagingFrequency/averageUpdaters/
    reportScoreAfterAveraging, ParallelWrapper.java:44)."""

    def __init__(
        self,
        net,
        workers: Optional[int] = None,
        averaging_frequency: int = 1,
        average_updaters: bool = True,
        report_score_after_averaging: bool = True,
        prefetch_buffer: int = 2,
        mesh=None,
        model_axis: Optional[str] = None,
        expert_axis: Optional[str] = None,
        data_is_local: bool = False,
        layout: Optional[MeshLayout] = None,
    ):
        self.net = net
        # ONE sharding authority: every batch/param/opt-state sharding this
        # wrapper uses comes from a MeshLayout (parallel/layout.py). Pass
        # ``layout=`` for the canonical dp×fsdp×tp mesh; the legacy
        # mesh/model_axis/expert_axis arguments wrap into a layout too
        # (model_axis plays the tp role), so both paths share the rule set.
        if layout is not None:
            if mesh is not None or model_axis or expert_axis:
                raise ValueError(
                    "pass either layout= or mesh=/model_axis=/expert_axis=, "
                    "not both — the layout already owns the mesh and axes")
            self.layout = layout
        else:
            m = mesh if mesh is not None else make_mesh(workers)
            # dp×tp: batch shards over "data", params over model_axis (GSPMD
            # inserts the tensor-parallel collectives); dp×ep: MoE
            # expert-stacked weights shard over expert_axis — from_mesh
            # raises on an axis name absent from the mesh (typo = loud)
            self.layout = MeshLayout.from_mesh(m, model_axis, expert_axis)
        self.mesh = self.layout.mesh
        self.model_axis = self.layout._tp_axis
        self.expert_axis = self.layout._expert_axis
        if averaging_frequency > 1 and (
                self.layout._tp_axis or self.layout._expert_axis
                or self.layout._fsdp_axis):
            raise ValueError(
                "fsdp/tensor/expert parallelism requires sync mode "
                "(averaging_frequency=1); periodic replica averaging stacks "
                "independent UNSHARDED replicas and would silently drop the "
                "declared param sharding"
            )
        self._data_axes = self.layout.batch_axes
        self.workers = int(self.layout.batch_factor)
        # data_is_local: each PROCESS feeds only its shard of the global
        # batch (per-host input pipelines, SURVEY.md §7(d)); default is the
        # broadcast pattern (every process holds the full batch). Sync mode
        # only — periodic mode stacks per-replica batches globally.
        self.data_is_local = data_is_local
        if data_is_local and averaging_frequency > 1:
            raise ValueError("data_is_local requires sync mode "
                             "(averaging_frequency=1)")
        if data_is_local:
            # every process must address an equal, non-zero share of the
            # mesh: a mesh over a device subset leaves some process with
            # zero addressable shards (and another with extra), which
            # mis-assembles the global batch instead of failing loudly
            pidx = jax.process_index()
            local_devs = sum(1 for d in self.mesh.devices.flat
                             if d.process_index == pidx)
            total = int(np.prod(self.mesh.devices.shape))
            if local_devs == 0 or local_devs * jax.process_count() != total:
                raise ValueError(
                    f"data_is_local needs every process to address an equal "
                    f"share of the mesh; process {pidx} addresses "
                    f"{local_devs}/{total} devices"
                )
            if self.workers % jax.process_count() != 0:
                # group_size = workers // process_count must tile the data
                # sharding exactly (e.g. data=4 over 3 processes cannot)
                raise ValueError(
                    f"data_is_local needs the {self.workers}-way data "
                    f"sharding to divide evenly over "
                    f"{jax.process_count()} processes"
                )
            # NOTE: per-host pipelines must feed IDENTICAL step counts on
            # every host — a host with more full groups enters a collective
            # the others never join and the cluster hangs (inherent to SPMD;
            # pad or truncate per-host data to equal length).
        self.averaging_frequency = int(averaging_frequency)
        self.average_updaters = average_updaters
        self.report_score_after_averaging = report_score_after_averaging
        self.prefetch_buffer = prefetch_buffer
        self.iteration = 0
        self._replica = None  # (params, opt_state, state) stacked, periodic mode
        self._vstep = None
        self._avg_fn = None
        self._sync_ready = False
        # Shared instrumentation path (profiler.StepTimer): the same
        # data/step/average phases feed the TrainingMaster's phase stats, the
        # StatsListener records (UI system page), the bench breakdown AND the
        # telemetry registry (dl4jtpu_phase_seconds at /metrics) —
        # reference: ParameterAveragingTrainingWorkerStats per-phase events.
        from ..profiler import StepTimer  # noqa: PLC0415
        from ..telemetry import get_registry  # noqa: PLC0415

        self.timer = StepTimer(registry=get_registry(),
                               component="parallel_wrapper")
        net._phase_timer = self.timer

    # ------------------------------------------------------------- sync mode
    def _setup_sync(self):
        net = self.net
        # layout.apply: precision policy + params/opt-state sharded by the
        # rule set (moments follow their param's spec; training state is
        # preserved, not reset), state replicated, net stamped so the
        # serving fast path discovers the placement
        self.layout.apply(net)
        # the rng key rides every staged dispatch and comes back
        # mesh-replicated; placing it up front keeps the FIRST dispatch's
        # cache signature identical to every later one (zero warm compiles)
        net._rng = self.layout.put(net._rng, self.layout.replicated())
        if net._train_step is None:
            net._train_step = net._build_train_step()
        self._sync_ready = True

    def _batch_sharding(self):
        """Batch-dim sharding over every batch (data×fsdp) mesh axis."""
        return self.layout.batch_sharding()

    def _fit_sync(self, global_ds) -> None:
        """One SPMD step on a globally-sharded batch; grads psum over ICI."""
        net = self.net
        shard = self._batch_sharding()
        put = global_put_local if self.data_is_local else global_put
        with self.timer.phase("data"):
            x = put(np.asarray(global_ds.features), shard)
            y = put(np.asarray(global_ds.labels), shard)
            net._rng, step_key = jax.random.split(net._rng)
            lm_ = getattr(global_ds, "labels_mask", None)
            fm_ = getattr(global_ds, "features_mask", None)
            lm = None if lm_ is None else put(np.asarray(lm_), shard)
            fm = None if fm_ is None else put(np.asarray(fm_), shard)
        tel = getattr(net, "telemetry", None)
        with self.timer.phase("step"):
            if tel is not None:
                # telemetry-instrumented SPMD step: the metrics vector is
                # reduced on-mesh (grad-norm psums ride ICI with the grads)
                if net._telemetry_step is None:
                    net._telemetry_step = net._build_train_step(
                        with_telemetry=True)
                (net.params, net.opt_state, net.state, loss, mvec) = \
                    net._telemetry_step(
                        net.params, net.opt_state, net.state, x, y, step_key,
                        lm, fm,
                    )
            else:
                net.params, net.opt_state, net.state, loss = net._train_step(
                    net.params, net.opt_state, net.state, x, y, step_key, lm, fm
                )
        net._last_loss = loss
        net.iteration += 1
        self.iteration += 1
        if tel is not None:
            tel.on_step(net.iteration, mvec)
        for lst in net.listeners:
            lst.iteration_done(net, net.iteration, loss)

    def fit_on_device(self, xs, ys, steps: Optional[int] = None,
                      features_masks=None, labels_masks=None):
        """Whole training loop in ONE dispatch, in either wrapper mode.

        Sync mode (``averaging_frequency=1``): ``xs``/``ys`` are K global
        batches ``[K, B_global, ...]`` staged sharded over the data axes
        (batch dim is axis 1); lax.scan of the SPMD train step — gradient
        psums ride ICI *inside* the scan, with zero host round-trips between
        steps.

        Periodic mode (``averaging_frequency=F > 1``): ``xs``/``ys`` are K
        replica-stacked groups ``[K, workers, batch, ...]`` (the same shape
        each sequential ``_fit_periodic`` step consumes); the scan runs every
        replica's independent step per tick and folds the
        averageAndPropagate mean/broadcast in via ``lax.cond`` on the same
        ``iteration % F`` schedule — Spark-parity parameter averaging with
        the host out of the loop entirely.

        Both paths match sequential :meth:`fit` numerics exactly (same RNG
        chains). Multi-process: every process calls this with the same K and
        steps; under ``data_is_local`` each passes only its per-process share
        of each global batch.
        """
        if self.averaging_frequency > 1:
            return self._fit_on_device_periodic(xs, ys, steps,
                                                features_masks, labels_masks)
        if not self._sync_ready:
            self._setup_sync()
        net = self.net
        shard = self.layout.staged_batch_sharding()
        put = global_put_local if self.data_is_local else global_put
        try:
            with self.timer.phase("data"):
                xs = put(np.asarray(xs), shard)
                ys = put(np.asarray(ys), shard)
                fm = None if features_masks is None else put(np.asarray(features_masks), shard)
                lm = None if labels_masks is None else put(np.asarray(labels_masks), shard)
            with self.timer.phase("step"):
                losses = net.fit_on_device(xs, ys, steps=steps,
                                           features_masks=fm, labels_masks=lm)
        finally:
            # same stale-breakdown guard as fit(): a later plain net.fit must
            # not report this wrapper's frozen phase timings
            if getattr(net, "_phase_timer", None) is self.timer:
                net._phase_timer = None
        self.iteration += len(losses)
        return losses

    def _build_periodic_multi_step(self, num_steps: int, num_groups: int,
                                   start_iter: int):
        """lax.scan over the vmapped per-replica step with the averaging
        fold-in: tick i runs every replica's independent step, then
        ``lax.cond((start_iter + i + 1) % F == 0)`` applies the
        averageAndPropagate mean/broadcast — the exact schedule sequential
        ``_fit_periodic`` follows, so numerics match per-step dispatch."""
        one_step, average = self._one_step, self._avg_pure
        n, F = self.workers, self.averaging_frequency

        def run(replica, rng, xs, ys, xmasks, ymasks):
            def body(carry, i):
                (params, opt, state), rng = carry
                rng, k = jax.random.split(rng)
                keys = jax.random.split(k, n)
                idx = i % num_groups
                x = jax.lax.dynamic_index_in_dim(xs, idx, 0, keepdims=False)
                y = jax.lax.dynamic_index_in_dim(ys, idx, 0, keepdims=False)
                fm = (jax.lax.dynamic_index_in_dim(xmasks, idx, 0, keepdims=False)
                      if xmasks is not None else None)
                lm = (jax.lax.dynamic_index_in_dim(ymasks, idx, 0, keepdims=False)
                      if ymasks is not None else None)
                params, opt, state, losses = jax.vmap(one_step)(
                    params, opt, state, x, y, keys, lm, fm
                )
                params, opt, state = jax.lax.cond(
                    (start_iter + i + 1) % F == 0,
                    lambda t: average(*t),
                    lambda t: t,
                    (params, opt, state),
                )
                return ((params, opt, state), rng), jnp.mean(losses)

            (replica, rng), losses = jax.lax.scan(
                body, (replica, rng), jnp.arange(num_steps)
            )
            return replica, rng, losses

        donate = (0, 1) if jax.default_backend() != "cpu" else ()
        return jax.jit(run, donate_argnums=donate)

    def _fit_on_device_periodic(self, xs, ys, steps, features_masks, labels_masks):
        if self._replica is None:
            self._setup_periodic()
        net = self.net
        xs = np.asarray(xs)
        ys = np.asarray(ys)
        num_groups = int(xs.shape[0])
        if num_groups == 0:
            raise ValueError("fit_on_device needs at least one staged group")
        if int(xs.shape[1]) != self.workers:
            raise ValueError(
                f"periodic fit_on_device groups must stack one batch per "
                f"replica: got axis-1 size {int(xs.shape[1])}, "
                f"workers={self.workers}"
            )
        from ..nn.multilayer import _check_staged_counts  # noqa: PLC0415

        _check_staged_counts(num_groups, (("ys", ys),
                                          ("features_masks", features_masks),
                                          ("labels_masks", labels_masks)))
        n_steps = int(steps) if steps is not None else num_groups
        if n_steps <= 0:  # match the sync path: no-op, no dispatch
            return np.zeros((0,), np.float32)
        # the averaging schedule is phase-dependent: bake the entry
        # iteration's offset into the compiled program (and its cache key)
        phase = self.iteration % self.averaging_frequency
        if getattr(self, "_periodic_multi_cache", None) is None:
            self._periodic_multi_cache = {}
        cache_key = (n_steps, num_groups, phase,
                     features_masks is not None, labels_masks is not None)
        fn = self._periodic_multi_cache.get(cache_key)
        if fn is None:
            fn = self._build_periodic_multi_step(n_steps, num_groups, phase)
            self._periodic_multi_cache[cache_key] = fn
        # groups [K, workers, batch, ...]: replica axis is 1
        group_shard = self.layout.staged_batch_sharding()
        try:
            with self.timer.phase("data"):
                xs = global_put(xs, group_shard)
                ys = global_put(ys, group_shard)
                fm = (None if features_masks is None
                      else global_put(np.asarray(features_masks), group_shard))
                lm = (None if labels_masks is None
                      else global_put(np.asarray(labels_masks), group_shard))
            with self.timer.phase("step"):
                # the scan body splits the carried rng exactly as sequential
                # _fit_periodic splits net._rng each step — seed the carry
                # with net._rng itself and write back the final carry so a
                # later sequential step continues the same chain
                self._replica, net._rng, losses = fn(
                    self._replica, net._rng, xs, ys, fm, lm
                )
                losses = np.asarray(losses)  # host fetch = sync
        finally:
            if getattr(net, "_phase_timer", None) is self.timer:
                net._phase_timer = None
        # replay the sequential per-step bookkeeping so listeners observe
        # iteration/score in lockstep (reference IterationListener contract):
        # score updates at averaging boundaries when
        # report_score_after_averaging, else every step — then the callback
        F = self.averaging_frequency
        for j, loss in enumerate(losses):
            self.iteration += 1
            net.iteration += 1
            at_boundary = (phase + j + 1) % F == 0
            if (at_boundary and self.report_score_after_averaging) or (
                    not self.report_score_after_averaging):
                net._last_loss = loss
            for lst in net.listeners:
                lst.iteration_done(net, net.iteration, loss)
        # propagate trained weights into the wrapped net, exactly as fit()
        # does at the end of its epochs (net.output/save must see them)
        self._finalize_periodic()
        return losses

    # --------------------------------------------------------- periodic mode
    def _setup_periodic(self):
        net = self.net
        net.init()
        n = self.workers
        self._replica = (
            _stack_tree(net.params, n),
            _stack_tree(net.opt_state, n),
            _stack_tree(net.state, n),
        )
        # leading replica axis over the batch devices; the layout REFUSES
        # this placement for tp/expert layouts (stacked replicas would
        # silently drop the declared param sharding — the constructor
        # guards the same combination)
        shard0 = self.layout.replica_sharding()
        self._replica = jax.tree_util.tree_map(
            lambda a: global_put(a, shard0), self._replica)

        tx = net._tx
        ls = getattr(net.conf, "loss_scale", None)

        def one_step(params, opt_state, state, x, y, rng, labels_mask, features_mask):
            from ..nn.updaters import (  # noqa: PLC0415
                optimizer_update, scaled_loss, unscale_grads, unscale_loss)

            def loss_of(p):
                loss, new_state, _ = net._loss(
                    p, state, x, y, rng, True, labels_mask, features_mask
                )
                return scaled_loss(loss, ls), new_state

            (loss, new_state), grads = jax.value_and_grad(loss_of, has_aux=True)(params)
            loss = unscale_loss(loss, ls)
            grads = unscale_grads(grads, ls)
            _, new_opt, new_params = optimizer_update(
                tx, grads, opt_state, params)
            return new_params, new_opt, new_state, loss

        # vmap over the replica axis: every replica steps independently in one
        # XLA program; sharding over "data" keeps each on its own device.
        self._one_step = one_step  # pure, un-jitted: reused by the scanned loop
        self._vstep = jax.jit(jax.vmap(one_step))

        avg_upd = self.average_updaters

        def average(params, opt_state, state):
            """averageAndPropagate: mean over replicas, broadcast back."""
            p = _stack_tree(_mean_tree(params), n)
            o = _stack_tree(_mean_tree(opt_state), n) if avg_upd else opt_state
            s = _stack_tree(_mean_tree(state), n)
            return p, o, s

        self._avg_pure = average  # pure, un-jitted: reused by the scanned loop
        self._avg_fn = jax.jit(average)
        self._periodic_multi_cache = None  # closures above changed

    def _fit_periodic(self, stacked_ds) -> None:
        """stacked_ds features/labels: [workers, batch, ...] — one independent
        step per replica (round-robin dispatch parity, ParallelWrapper.java:141-151)."""
        net = self.net
        params, opt_state, state = self._replica
        net._rng, k = jax.random.split(net._rng)
        keys = jax.random.split(k, self.workers)
        shard0 = self.layout.replica_sharding()
        with self.timer.phase("data"):
            x = global_put(np.asarray(stacked_ds.features), shard0)
            y = global_put(np.asarray(stacked_ds.labels), shard0)
            # Masks ride the replica axis too — each replica's loss must see
            # its own masks exactly as its net.fit would (round-1 weak #4:
            # periodic mode silently computed unmasked loss). None passes
            # through vmap as an empty pytree.
            lm = global_put(getattr(stacked_ds, "labels_mask", None), shard0)
            fm = global_put(getattr(stacked_ds, "features_mask", None), shard0)
        with self.timer.phase("step"):
            params, opt_state, state, losses = self._vstep(
                params, opt_state, state, x, y, keys, lm, fm
            )
        self.iteration += 1
        net.iteration += 1
        if self.iteration % self.averaging_frequency == 0:
            with self.timer.phase("average"):
                params, opt_state, state = self._avg_fn(params, opt_state, state)
            if self.report_score_after_averaging:
                net._last_loss = jnp.mean(losses)
        if not self.report_score_after_averaging:
            net._last_loss = jnp.mean(losses)
        self._replica = (params, opt_state, state)
        for lst in net.listeners:
            lst.iteration_done(net, net.iteration, jnp.mean(losses))

    def _finalize_periodic(self):
        """Propagate averaged replica params back into the wrapped net."""
        if self._replica is None:
            return
        params, opt_state, state = self._avg_fn(*self._replica)
        net = self.net
        net.params = _mean_tree(params)
        net.opt_state = _mean_tree(opt_state)
        net.state = _mean_tree(state)

    # ------------------------------------------------------------------- fit
    def fit(self, data, epochs: int = 1) -> "ParallelWrapper":
        """Reference: ParallelWrapper.fit(DataSetIterator):317. Minibatches are
        pulled through async prefetch and grouped ``workers`` at a time."""
        sync = self.averaging_frequency <= 1
        if sync and not self._sync_ready:
            self._setup_sync()
        if not sync and self._replica is None:
            self._setup_periodic()
        try:
            self._fit_epochs(data, epochs, sync)
        finally:
            # Detach even on mid-fit failure: a later plain net.fit must not
            # report this wrapper's frozen breakdown as the new run's timings.
            if getattr(self.net, "_phase_timer", None) is self.timer:
                self.net._phase_timer = None
            if getattr(self.net, "telemetry", None) is not None:
                self.net.telemetry.flush()  # drain a partial K-window
        return self

    def _fit_epochs(self, data, epochs: int, sync: bool) -> None:
        from ..datasets.iterators import as_iterator, AsyncDataSetIterator

        for _ in range(epochs):
            it = as_iterator(data)
            if hasattr(it, "reset"):
                it.reset()
            if getattr(it, "prefetch_supported", False):
                it = AsyncDataSetIterator(it, queue_size=self.prefetch_buffer)
            group_size = self.workers
            if self.data_is_local:
                group_size = self.workers // jax.process_count()
            group: List[Any] = []
            for ds in it:
                group.append(ds)
                if len(group) < group_size:
                    continue
                if sync:
                    self._fit_sync(_concat_group(group))
                else:
                    self._fit_periodic(_stack_group(group))
                group = []
            if group:
                # Trailing partial group. Sync mode can still shard it as one
                # global batch when the example count divides the data axes;
                # otherwise (and always in periodic mode, which needs exactly
                # one batch per replica) it is dropped — warn instead of the
                # silent drop that made small iterators train zero steps.
                import warnings  # noqa: PLC0415

                partial = _concat_group(group)
                if self.data_is_local:
                    # A trailing partial cannot train here: each process
                    # decides locally, and a process entering the collective
                    # step alone (or with a different local size) hangs or
                    # mis-assembles the global batch. Dropping it locally is
                    # only safe when every host drops the same way — hosts
                    # MUST feed identical full-group counts (see the
                    # constructor note); this warning may print on a
                    # different host than the one that then hangs.
                    warnings.warn(
                        "ParallelWrapper(data_is_local=True) dropped a "
                        f"trailing partial group of {len(group)} local "
                        "minibatch(es); ALL hosts must feed identical step "
                        "counts or the cluster deadlocks",
                        stacklevel=2,
                    )
                elif sync and partial.num_examples() % self.workers == 0:
                    if partial.num_examples() != self.workers * (
                        group[0].num_examples()
                    ) and self.iteration > len(group):
                        warnings.warn(
                            "ParallelWrapper: trailing partial group trains at "
                            f"a new global batch shape ({partial.num_examples()} "
                            "examples) — XLA compiles the train step a second "
                            "time for this shape",
                            stacklevel=2,
                        )
                    self._fit_sync(partial)
                elif sync:
                    warnings.warn(
                        "ParallelWrapper dropped a trailing partial group: its "
                        f"{partial.num_examples()} examples do not divide the "
                        f"{self.workers}-way data sharding; pad the final "
                        "minibatches or size the epoch accordingly",
                        stacklevel=2,
                    )
                else:
                    warnings.warn(
                        f"ParallelWrapper dropped a trailing partial group of "
                        f"{len(group)} minibatch(es) (periodic mode needs "
                        f"exactly {self.workers}, one per replica)",
                        stacklevel=2,
                    )
        if not sync:
            self._finalize_periodic()

    def average_model(self):
        """Current averaged model params (periodic mode) or the net's params."""
        if self._replica is not None:
            return _mean_tree(self._replica[0])
        return self.net.params


def _concat_group(group):
    from ..datasets.iterators import DataSet

    return DataSet(
        np.concatenate([np.asarray(d.features) for d in group]),
        np.concatenate([np.asarray(d.labels) for d in group]),
        _cat_masks([getattr(d, "features_mask", None) for d in group]),
        _cat_masks([getattr(d, "labels_mask", None) for d in group]),
    )


def _stack_group(group):
    from ..datasets.iterators import DataSet

    return DataSet(
        np.stack([np.asarray(d.features) for d in group]),
        np.stack([np.asarray(d.labels) for d in group]),
        _merge_masks([getattr(d, "features_mask", None) for d in group], np.stack),
        _merge_masks([getattr(d, "labels_mask", None) for d in group], np.stack),
    )


def _merge_masks(masks, combine):
    if all(m is None for m in masks):
        return None
    if any(m is None for m in masks):
        raise ValueError("mixed masked/unmasked minibatches in one group")
    return combine([np.asarray(m) for m in masks])


def _cat_masks(masks):
    return _merge_masks(masks, np.concatenate)
