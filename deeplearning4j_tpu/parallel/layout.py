"""MeshLayout: ONE sharding layer under training AND serving (dp×fsdp×tp).

`parallel/` grew four overlapping scale paths (wrapper, param_server,
training_master, pipeline), each doing its own mesh handling, and none of
them could shard *parameters* over a data-parallel axis — the largest
trainable model was bounded by one chip's HBM. This module is the single
GSPMD-style layout authority (per Xu et al., *GSPMD*; ZeRO-style parameter
sharding per Rajbhandari et al., *ZeRO*) the ROADMAP tentpole names:

- **One named mesh** with ``("data", "fsdp", "tp", "seq", "pipe")`` axes.
  Any axis of size 1 collapses out of the emitted PartitionSpecs (the mesh
  keeps all names so specs stay portable across layouts).
- **Parameter-name→spec assignment** in the style of SNIPPETS.md [2]
  (``SpecLayout``): 2-D+ kernels shard their last dim over ``tp`` when
  divisible and a divisible non-tp dim over ``fsdp``; 1-D vectors follow
  the legacy tp rule; exactly-3-D expert-stacked MoE weights shard dim 0
  over an expert axis. Optimizer moments mirror their param's shape, so the
  same shape rule lands them on the same spec ("moments follow their
  param").
- **Batch sharding** over ``data×fsdp`` (the ZeRO convention: fsdp ranks
  see different data; GSPMD inserts the per-step all-gather of params and
  reduce-scatter of gradients).
- **Precision policy**: ``params_dtype="bfloat16"`` carries parameters,
  gradients and optimizer moments in bf16 *storage* while the forward/
  backward compute (and the loss/psum accumulation) runs in f32 — the
  promoted form of the ``__graft_entry__`` §8 dryrun. bf16 leaves shard
  exactly like f32 ones, so fsdp + bf16 compound: per-device param bytes
  drop by ``2 × fsdp`` and gradient all-reduce bytes halve.

ParallelWrapper, the TrainingMasters and the serving stack
(`runtime/inference.py`, `serving/service.py`) are thin strategy wrappers
over this class — none of them constructs a NamedSharding/PartitionSpec of
its own. Every layout is validated by the DT008 ``check_partition_specs``
rule (here via :meth:`MeshLayout.validate`, and automatically at
``CompileManager.aot`` admission for any executable compiled with sharded
arguments). See docs/distributed.md.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["MeshLayout", "PrecisionPolicy", "layout_of"]


@dataclass(frozen=True)
class PrecisionPolicy:
    """Storage-vs-compute dtype contract of a layout.

    ``params_dtype`` is what parameter/gradient/moment *leaves* are stored
    (and communicated) in; ``compute_dtype`` is what the forward/backward
    math runs in (``nn.multilayer._compute_cast`` upcasts bf16 storage to
    f32 per step when they differ — loss and reductions accumulate in f32).
    """

    params_dtype: Optional[str] = None  # None = keep the model's own dtype
    compute_dtype: str = "float32"
    # loss scaling for sub-f32 grad flow: gradients transit the storage
    # dtype (the cast transpose), so small cotangents flush to zero in
    # bf16/f16. None = auto: DEFAULT_LOSS_SCALE under a sub-f32
    # params_dtype, no scaling otherwise. Keep explicit values a power of
    # two — the exponent shift is then bit-exact through scale/unscale.
    loss_scale: Optional[float] = None

    #: power-of-two default applied when ``params_dtype`` is sub-f32
    DEFAULT_LOSS_SCALE = 4096.0

    def effective_loss_scale(self) -> Optional[float]:
        """The loss scale this policy implies (explicit, or the sub-f32
        default, or None when storage is full precision)."""
        if self.loss_scale:
            return float(self.loss_scale)
        if self.params_dtype in ("bfloat16", "float16"):
            return self.DEFAULT_LOSS_SCALE
        return None

    def apply_to_net(self, net) -> None:
        """Stamp the policy onto a net: conf carries it forward (JSON
        round-trips), and already-initialized params/opt-state leaves are
        cast to the storage dtype in place."""
        if self.params_dtype is None:
            return
        import jax
        import jax.numpy as jnp

        net.conf.params_dtype = self.params_dtype
        net.conf.loss_scale = self.effective_loss_scale()
        # the compiled step closed over the old loss_scale/update island
        net._train_step = None
        if net.params is None:
            return

        target = jnp.dtype(self.params_dtype)

        def cast(a):
            if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating) \
                    and a.dtype != target:
                return a.astype(target)
            return a

        net.params = jax.tree_util.tree_map(cast, net.params)
        if net.opt_state is not None:
            # moments mirror their param's storage (scalar counts stay int)
            net.opt_state = jax.tree_util.tree_map(cast, net.opt_state)

    def describe(self) -> dict:
        return {"params_dtype": self.params_dtype,
                "compute_dtype": self.compute_dtype,
                "loss_scale": self.effective_loss_scale()}


def _is_spec(x) -> bool:
    from jax.sharding import PartitionSpec

    return isinstance(x, PartitionSpec)


def layout_of(net) -> Optional["MeshLayout"]:
    """The MeshLayout a net was sharded with (``MeshLayout.apply``), or
    None — how the serving fast path discovers mesh placement."""
    return getattr(net, "_mesh_layout", None)


class MeshLayout:
    """One named mesh + the spec rules every scale path shares."""

    def __init__(self, data: Optional[int] = None, fsdp: int = 1, tp: int = 1,
                 seq: int = 1, pipe: int = 1, *,
                 devices: Optional[Sequence] = None,
                 params_dtype: Optional[str] = None,
                 loss_scale: Optional[float] = None, zero_stage: int = 3,
                 roles: bool = False):
        import jax
        from jax.sharding import Mesh

        fsdp, tp, seq, pipe = int(fsdp), int(tp), int(seq), int(pipe)
        if fsdp < 1 or tp < 1 or seq < 1 or pipe < 1:
            raise ValueError(
                f"axis sizes must be >= 1, got fsdp={fsdp} tp={tp} "
                f"seq={seq} pipe={pipe}")
        devs = list(devices) if devices is not None else jax.devices()
        if data is None:
            data = max(1, len(devs) // (fsdp * tp * seq * pipe))
        data = int(data)
        need = data * fsdp * tp * seq * pipe
        if need > len(devs):
            raise ValueError(
                f"layout data={data} x fsdp={fsdp} x tp={tp} x seq={seq} "
                f"x pipe={pipe} needs {need} devices, have {len(devs)}")
        arr = np.array(devs[:need]).reshape(data, fsdp, tp, seq, pipe)
        self.mesh = Mesh(arr, axis_names=("data", "fsdp", "tp", "seq",
                                          "pipe"))
        self._init_axes({"data": data, "fsdp": fsdp, "tp": tp, "seq": seq,
                         "pipe": pipe},
                        params_dtype=params_dtype, loss_scale=loss_scale,
                        zero_stage=zero_stage, roles=roles)

    def _init_axes(self, sizes: dict, *, params_dtype: Optional[str],
                   loss_scale: Optional[float] = None,
                   zero_stage: int, canonical: bool = True,
                   model_axis: Optional[str] = None,
                   expert_axis: Optional[str] = None,
                   roles: bool = False) -> None:
        if int(zero_stage) not in (1, 3):
            raise ValueError(
                f"zero_stage must be 1 (moments-only fsdp sharding) or 3 "
                f"(params+grads+moments), got {zero_stage}")
        self._axis_sizes = {str(a): int(s) for a, s in sizes.items()}
        if canonical:
            # the canonical dp x fsdp x tp mesh: size-1 axes collapse out
            self._batch_axes = tuple(
                a for a in ("data", "fsdp") if self._axis_sizes.get(a, 1) > 1)
            self._fsdp_axis = "fsdp" if self._axis_sizes.get("fsdp", 1) > 1 \
                else None
            self._tp_axis = "tp" if self._axis_sizes.get("tp", 1) > 1 else None
            self._expert_axis = None
            self._seq_axis = ("seq" if self._axis_sizes.get("seq", 1) > 1
                              else None)
            self._pipe_axis = ("pipe" if self._axis_sizes.get("pipe", 1) > 1
                               else None)
        else:
            # legacy from_mesh semantics: every non-model/expert axis is a
            # batch axis, size-1 included (spec spellings feed cache keys).
            # An axis literally named "pipe" carries pipeline stages, never
            # batch rows — the legacy GPipe path's silent divergence was
            # exactly a hand-rolled rule set that had to know this.
            self._batch_axes = tuple(
                a for a in self._axis_sizes
                if a not in (model_axis, expert_axis, "pipe"))
            self._fsdp_axis = "fsdp" if (
                self._axis_sizes.get("fsdp", 1) > 1
                and "fsdp" not in (model_axis, expert_axis)) else None
            self._tp_axis = model_axis
            self._expert_axis = expert_axis
            self._seq_axis = ("seq" if (
                self._axis_sizes.get("seq", 1) > 1
                and "seq" not in (model_axis, expert_axis)) else None)
            if self._seq_axis is not None:
                self._batch_axes = tuple(
                    a for a in self._batch_axes if a != "seq")
            self._pipe_axis = "pipe" if "pipe" in self._axis_sizes else None
        self.zero_stage = int(zero_stage)
        self.precision = PrecisionPolicy(params_dtype=params_dtype,
                                         loss_scale=loss_scale)
        self.roles = bool(roles)
        # layer-semantics binding (MeshLayout.bind): path-suffix
        # (layer key, param name) -> (role, layer). None until bound.
        self._role_map = None
        self._role_ctx: dict = {}
        self._role_sites: List[dict] = []

    @classmethod
    def from_mesh(cls, mesh, model_axis: Optional[str] = None,
                  expert_axis: Optional[str] = None,
                  params_dtype: Optional[str] = None,
                  loss_scale: Optional[float] = None,
                  zero_stage: int = 3) -> "MeshLayout":
        """Wrap an existing mesh (the legacy ParallelWrapper construction
        path): ``model_axis`` plays the tp role, ``expert_axis`` enables the
        MoE expert-stacked rule, every other axis is a batch axis. A named
        axis absent from the mesh raises — a typo must fail loudly, not
        silently train replicated."""
        self = cls.__new__(cls)
        for ax, label in ((model_axis, "model_axis"),
                          (expert_axis, "expert_axis")):
            if ax is not None and ax not in mesh.shape:
                raise ValueError(
                    f"{label} '{ax}' not in mesh axes {tuple(mesh.shape)}")
        self.mesh = mesh
        self._init_axes(dict(mesh.shape), params_dtype=params_dtype,
                        loss_scale=loss_scale,
                        zero_stage=zero_stage, canonical=False,
                        model_axis=model_axis, expert_axis=expert_axis)
        return self

    @classmethod
    def abstract(cls, data: int = 1, fsdp: int = 1, tp: int = 1,
                 seq: int = 1, pipe: int = 1, *,
                 params_dtype: Optional[str] = None,
                 loss_scale: Optional[float] = None,
                 zero_stage: int = 3, roles: bool = False) -> "MeshLayout":
        """A device-less layout: pure spec algebra (``param_spec``,
        ``batch_spec``, the sharding-flow pass) with NO jax mesh behind it —
        the CLI ``--mesh`` flag analyzes a 64-chip layout from a laptop.
        Methods that place real data (``sharding``/``put``/``apply``)
        raise."""
        self = cls.__new__(cls)
        self.mesh = None
        self._init_axes({"data": int(data), "fsdp": int(fsdp),
                         "tp": int(tp), "seq": int(seq), "pipe": int(pipe)},
                        params_dtype=params_dtype, loss_scale=loss_scale,
                        zero_stage=zero_stage, roles=roles)
        return self

    # ------------------------------------------------------------ geometry
    @property
    def axis_sizes(self) -> dict:
        return dict(self._axis_sizes)

    def _size(self, axis: Optional[str]) -> int:
        return int(self._axis_sizes.get(axis, 1)) if axis is not None else 1

    @property
    def batch_axes(self) -> Tuple[str, ...]:
        return self._batch_axes

    @property
    def batch_factor(self) -> int:
        """How many ways the batch dim shards (global batch must divide it)."""
        return int(np.prod([self.mesh.shape[a] for a in self._batch_axes],
                           dtype=np.int64)) if self._batch_axes else 1

    @property
    def pipe_axis(self) -> Optional[str]:
        return self._pipe_axis

    @property
    def pipe_size(self) -> int:
        """Pipeline stage count (1 = no pipe axis)."""
        return self._size(self._pipe_axis) if self._pipe_axis else int(
            self._axis_sizes.get("pipe", 1))

    @property
    def num_devices(self) -> int:
        if self.mesh is None:  # abstract layout: the sizes ARE the geometry
            return int(np.prod(list(self._axis_sizes.values()),
                               dtype=np.int64))
        return int(self.mesh.devices.size)

    # ---------------------------------------------------------------- specs
    def batch_spec(self):
        """Dim-0 (batch/replica) spec over every batch axis (data×fsdp)."""
        from jax.sharding import PartitionSpec as P

        return P(self._batch_axes) if self._batch_axes else P()

    def staged_batch_spec(self):
        """Spec for staged windows/groups ``[K, B, ...]`` — batch dim is 1."""
        from jax.sharding import PartitionSpec as P

        return P(None, self._batch_axes) if self._batch_axes else P()

    def input_spec(self, ndim: Optional[int] = None):
        """Spec for one input/label tensor: dim 0 over the batch axes, and —
        under an active seq axis — dim 1 (time, ``[B, T, ...]``) over
        ``seq``. Rank-2-or-less tensors (and layouts without a seq axis)
        fall back to :meth:`batch_spec`."""
        from jax.sharding import PartitionSpec as P

        if self._seq_axis is not None and ndim is not None and ndim >= 3:
            return P(self._batch_axes or None, self._seq_axis)
        return self.batch_spec()

    def stage_spec(self, shape=None):
        """Spec for a stage-stacked leaf ``[P, ...]``: dim 0 over the pipe
        axis, every other dim replicated — the one rule the pipeline path
        shares with everything else (``pipeline_shardings`` routes here
        instead of hand-building NamedShardings)."""
        from jax.sharding import PartitionSpec as P

        if self._pipe_axis is None and "pipe" not in self._axis_sizes:
            raise ValueError(
                "stage_spec needs a pipe axis; this layout has axes "
                f"{tuple(self._axis_sizes)}")
        return P("pipe")

    def stage_specs(self, tree):
        """PartitionSpec pytree for a stage-stacked param tree."""
        import jax

        return jax.tree_util.tree_map(
            lambda a: self.stage_spec(np.shape(a)), tree)

    def input_sharding(self, arr=None):
        """NamedSharding for one input tensor (:meth:`input_spec` of its
        rank — pass the array/struct, or nothing for the plain batch
        sharding)."""
        ndim = len(np.shape(arr)) if arr is not None else None
        return self.sharding(self.input_spec(ndim))

    def param_spec(self, shape) -> "Any":
        """The fsdp/tp/expert rule set for one parameter shape:

        - exactly-3-D leaves whose dim 0 divides an expert axis (MoE
          expert-stacked ``[E, F, H]``) shard dim 0 over it;
        - 2-D+ kernels shard the last dim over ``tp`` when divisible, then
          the first remaining divisible dim over ``fsdp``;
        - 1-D vectors shard over ``fsdp`` when divisible (ZeRO shards
          biases too — and GSPMD's own propagation picks exactly this
          placement, so declaring it keeps executable outputs at the
          declared specs: zero warm recompiles), else over ``tp`` when
          divisible (legacy parity);
        - everything else replicates.

        Under ``zero_stage=1`` params (and so grads) skip the fsdp rule and
        stay replicated over the fsdp axis — only optimizer moments shard
        (:meth:`opt_spec`): the cheaper default for small meshes where the
        per-step ZeRO param all-gather costs more than it saves.
        """
        return self._shape_spec(
            shape, with_fsdp=(self.zero_stage >= 3))

    def opt_spec(self, shape) -> "Any":
        """Spec for one optimizer-moment leaf: the FULL fsdp/tp rule at
        every zero stage — ZeRO-1 shards the moments even while params
        replicate (that is its entire point: Adam moments are 2x param
        bytes and nothing in the step needs them gathered)."""
        return self._shape_spec(shape, with_fsdp=True)

    def _shape_spec(self, shape, *, with_fsdp: bool,
                    with_tp: bool = True) -> "Any":
        from jax.sharding import PartitionSpec as P

        shape = tuple(int(s) for s in shape)
        esize = self._size(self._expert_axis)
        tsize = self._size(self._tp_axis) if with_tp else 1
        fsize = self._size(self._fsdp_axis) if with_fsdp else 1
        if (self._expert_axis and len(shape) == 3 and esize > 1
                and shape[0] % esize == 0 and shape[0] >= esize):
            return P(self._expert_axis, *([None] * (len(shape) - 1)))
        entries: List[Any] = [None] * len(shape)
        if len(shape) >= 2:
            if tsize > 1 and shape[-1] > 0 and shape[-1] % tsize == 0:
                entries[-1] = self._tp_axis
            if fsize > 1:
                for d, size in enumerate(shape):
                    if entries[d] is None and size % fsize == 0 \
                            and size >= fsize:
                        entries[d] = self._fsdp_axis
                        break
        elif len(shape) == 1:
            if fsize > 1 and shape[0] % fsize == 0 and shape[0] >= fsize:
                entries[0] = self._fsdp_axis
            elif tsize > 1 and shape[0] % tsize == 0 and shape[0] >= tsize:
                entries[0] = self._tp_axis
        while entries and entries[-1] is None:
            entries.pop()  # canonical form: P() not P(None,) — GSPMD emits
            #               the trimmed spelling, and cache keys compare it
        return P(*entries)

    # ------------------------------------------------------------ shardings
    def sharding(self, spec):
        from jax.sharding import NamedSharding

        if self.mesh is None:
            raise RuntimeError(
                "this MeshLayout is abstract (MeshLayout.abstract): it can "
                "compute specs and run the sharding-flow analysis but has "
                "no devices to build a NamedSharding on")
        return NamedSharding(self.mesh, spec)

    def replicated(self):
        from jax.sharding import PartitionSpec as P

        return self.sharding(P())

    def batch_sharding(self):
        return self.sharding(self.batch_spec())

    def staged_batch_sharding(self):
        return self.sharding(self.staged_batch_spec())

    def replica_sharding(self):
        """Leading-replica-axis sharding for the periodic-averaging mode
        (one independent replica per batch-axis slot). tp/expert layouts
        have no replica semantics — :class:`ParallelWrapper` refuses the
        combination before this is ever called."""
        if self._tp_axis is not None or self._expert_axis is not None:
            raise ValueError(
                "replica (periodic-averaging) placement is undefined for "
                "tp/expert layouts; use sync mode (averaging_frequency=1)")
        return self.batch_sharding()

    # ------------------------------------------------------ role resolution
    def bind(self, net) -> "MeshLayout":
        """Resolve the layer-semantics registry against ``net``'s layers
        (``roles=True`` layouts only — a no-op otherwise): every param whose
        layer declares a role gets a role-resolved spec keyed by its tree
        path suffix ``(layer key, param name)``, so optimizer moments (and
        any shape-mirroring tree) follow their param's role. Divisibility
        is checked here — ``apply``/``validate``/``describe`` all reject a
        tp size that does not divide a head count or row dim instead of
        silently falling back (:class:`roles.RoleDivisibilityError`)."""
        if not self.roles:
            return self
        from . import roles as R

        conf = net.conf
        if hasattr(conf, "vertices"):
            items = [(str(k), getattr(v, "layer", v))
                     for k, v in conf.vertices.items()]
        else:
            items = [(str(i), l) for i, l in enumerate(conf.layers)]
        tsize = self._size(self._tp_axis)
        role_map: dict = {}
        role_ctx: dict = {}
        sites: List[dict] = []
        prev = None
        for key, layer in items:
            # ffn_down is row-parallel ONLY when the producing stage is
            # feature-local math (attention/dense): after an LSTM scan the
            # row-parallel backward would send a tp-sharded cotangent into
            # every scan step — replicate the head over tp instead
            ctx = {"after_scan": prev is not None
                   and "LSTM" in type(prev).__name__}
            prev = layer
            rmap = R.roles_for(layer)
            if not any(r != R.GENERIC for r in rmap.values()):
                continue
            role_map[key] = layer
            role_ctx[key] = ctx
            for pname, role in sorted(rmap.items()):
                if role == R.GENERIC:
                    continue
                sites.append({"layer": key,
                              "layer_type": type(layer).__name__,
                              "param": pname, "role": role, **ctx})
                # early divisibility rejection for checks that need only
                # layer attrs (n_heads); shape-dependent ones re-check at
                # spec resolution
                if role in R.HEAD_AWARE_ROLES:
                    heads = getattr(layer, "n_heads", None)
                    if heads is not None and tsize > 1 \
                            and int(heads) % tsize != 0:
                        R.check_role_site(layer, key, pname, role, (),
                                          tsize)
        self._role_map = role_map
        self._role_ctx = role_ctx
        self._role_sites = sites
        return self

    @property
    def role_sites(self) -> List[dict]:
        """Every (layer, param, role) the binding resolved — empty until
        :meth:`bind` (``apply`` binds automatically)."""
        return list(self._role_sites)

    def role_resolved_types(self) -> set:
        """Layer type names whose params resolved through a HEAD-AWARE role
        rule (attention_qkv/attention_out/lstm_gates) — the DT305 advisory
        skips these sites."""
        from . import roles as R

        return {s["layer_type"] for s in self._role_sites
                if s["role"] in R.HEAD_AWARE_ROLES}

    def _path_site(self, path):
        """(layer key, param name) from a tree-path SUFFIX, or None. Param
        trees end ``(..., layer key, param name)`` on both net classes —
        and optax moment trees mirror params, so the same suffix matches
        ``mu``/``nu`` leaves without knowing the optimizer's structure."""
        if self._role_map is None or len(path) < 2:
            return None
        name_k, layer_k = path[-1], path[-2]
        name = getattr(name_k, "key", None)
        if not isinstance(name, str):
            return None
        layer = getattr(layer_k, "key", None)
        if layer is None:
            layer = getattr(layer_k, "idx", None)
        if layer is None:
            return None
        return (str(layer), name)

    def _resolve_leaf_spec(self, path, shape, *, with_fsdp: bool):
        """Role spec for one leaf when bound and matched, else the generic
        shape rule."""
        site = self._path_site(path)
        if site is not None:
            layer = self._role_map.get(site[0])
            if layer is not None:
                from . import roles as R

                role = R.role_of(layer, site[1])
                if role is not None and role != R.GENERIC:
                    ctx = getattr(self, "_role_ctx", {}).get(site[0]) or {}
                    R.check_role_site(layer, site[0], site[1], role, shape,
                                      self._size(self._tp_axis), ctx=ctx)
                    spec = R.resolve_role_spec(self, role, site[1], shape,
                                               with_fsdp=with_fsdp, ctx=ctx)
                    if spec is not None:
                        return spec
        return self._shape_spec(shape, with_fsdp=with_fsdp)

    def _spec_tree(self, tree, *, with_fsdp: bool):
        import jax

        if self._role_map is None:
            return jax.tree_util.tree_map(
                lambda a: self._shape_spec(np.shape(a),
                                           with_fsdp=with_fsdp), tree)
        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        return jax.tree_util.tree_unflatten(
            treedef, [self._resolve_leaf_spec(p, np.shape(l),
                                              with_fsdp=with_fsdp)
                      for p, l in flat])

    def param_specs(self, tree):
        """PartitionSpec pytree for params — or any shape-mirroring tree
        (scalar bookkeeping replicates). Role-resolved per site after
        :meth:`bind`; the generic shape rules otherwise."""
        return self._spec_tree(tree, with_fsdp=(self.zero_stage >= 3))

    def param_shardings(self, tree):
        import jax

        return jax.tree_util.tree_map(
            self.sharding, self.param_specs(tree),
            is_leaf=_is_spec)

    def opt_specs(self, tree):
        """PartitionSpec pytree for optimizer state (moments follow their
        param's shape rule — and, once bound, their param's ROLE — at
        zero_stage=3; ZeRO-1 shards them over fsdp while params
        replicate)."""
        return self._spec_tree(tree, with_fsdp=True)

    def opt_shardings(self, tree):
        import jax

        return jax.tree_util.tree_map(
            self.sharding, self.opt_specs(tree),
            is_leaf=_is_spec)

    # -------------------------------------------------------------- devices
    def put(self, arr, sharding=None):
        """Place host data on the mesh (multi-process safe — delegates to
        :func:`parallel.mesh.global_put`). Default: batch sharding."""
        from .mesh import global_put

        return global_put(arr, sharding if sharding is not None
                          else self.batch_sharding())

    def put_params(self, tree):
        """device_put a param-shaped pytree leaf-wise on its layout specs
        (role-resolved per site once :meth:`bind` ran)."""
        import jax

        from .mesh import global_put

        return jax.tree_util.tree_map(
            lambda a, s: global_put(a, self.sharding(s)),
            tree, self.param_specs(tree))

    def put_opt_state(self, tree):
        """device_put optimizer state on its moment specs (= param specs at
        zero_stage=3; fsdp-sharded even under ZeRO-1)."""
        import jax

        from .mesh import global_put

        return jax.tree_util.tree_map(
            lambda a, s: global_put(a, self.sharding(s)),
            tree, self.opt_specs(tree))

    def put_replicated(self, tree):
        import jax

        from .mesh import global_put

        rep = self.replicated()
        return jax.tree_util.tree_map(lambda a: global_put(a, rep), tree)

    # ------------------------------------------------------------- networks
    def apply(self, net) -> "MeshLayout":
        """Make ``net`` live on this layout: apply the precision policy,
        shard params + optimizer state by the rule set (state replicates),
        and stamp the layout so the serving fast path (and a later
        ParallelWrapper) discovers the placement. Idempotent."""
        import jax

        if self._pipe_axis is not None:
            raise ValueError(
                f"pipe={self._size(self._pipe_axis)} stages layers across "
                "devices — generic leaf-wise placement cannot express it. "
                "Use parallel.pipeline.PipelinedTrainer(net, layout) for "
                "pipelined training")
        net.init()
        self.bind(net)
        if self._seq_axis is not None:
            self._install_seq(net)
        self.precision.apply_to_net(net)
        net.params = self.put_params(net.params)
        if net.opt_state is not None:
            net.opt_state = self.put_opt_state(net.opt_state)
        if jax.tree_util.tree_leaves(net.state):
            net.state = self.put_replicated(net.state)
        net._mesh_layout = self
        return self

    def _install_seq(self, net) -> None:
        """Wire the sequence axis: attention layers route q/k/v through the
        shard_map ring/all-to-all kernels (``parallel/ring_attention.py``)
        on this mesh — the escape hatch where GSPMD's own propagation would
        reshard K/V every block. Recurrent scan layers consume time
        sequentially, so a seq axis cannot shard their scan — reject loudly
        instead of silently training with per-step resharding."""
        conf = net.conf
        if hasattr(conf, "vertices"):
            layers = [getattr(v, "layer", v) for v in conf.vertices.values()]
        else:
            layers = list(conf.layers)
        recurrent = [type(l).__name__ for l in layers
                     if "LSTM" in type(l).__name__]
        if recurrent:
            raise ValueError(
                f"seq={self._size(self._seq_axis)} shards the time dim, but "
                f"{', '.join(sorted(set(recurrent)))} consumes time "
                "sequentially inside lax.scan — the seq axis supports "
                "attention nets (ring/all-to-all sequence parallelism); "
                "use data/fsdp/tp for recurrent nets")
        if any(hasattr(l, "n_heads") for l in layers):
            from ..nn.layers.attention import set_attention_mesh

            set_attention_mesh(self.mesh, "seq", nets=(net,),
                               batch_axes=self._batch_axes)

    def shard_params(self, net):
        """:meth:`apply` returning the param sharding pytree (checkpoint
        restore wants it) — the layout twin of the legacy
        ``parallel.sharding.shard_params``."""
        self.apply(net)
        return self.param_shardings(net.params)

    # ------------------------------------------------------------ validation
    def validate(self, params=None, *, net=None,
                 source: str = "<MeshLayout>"):
        """DT008 ``check_partition_specs`` over this layout's param specs
        (axis membership, duplicate axes, divisibility when ``params`` is
        given). Role-resolved specs are validated too: pass ``net`` (or
        :meth:`bind` first) and a tp size that does not divide a head count
        or row dim comes back as an ERROR finding naming the layer and dim
        instead of silently falling back. Returns analysis findings — empty
        means clean."""
        from ..analysis import check_partition_specs

        findings = []
        if net is not None and self.roles and self._role_map is None:
            try:
                self.bind(net)
            except ValueError as e:
                from ..analysis.rules import get_rule

                return [get_rule("DT008").finding(str(e), file=source,
                                                  context="roles")]
        tree = params if params is not None else {}
        try:
            specs = self.param_specs(tree) if params is not None else {}
        except ValueError as e:
            from ..analysis.rules import get_rule

            return [get_rule("DT008").finding(str(e), file=source,
                                              context="roles")]
        findings += check_partition_specs(specs, self.mesh, params,
                                          source=source)
        return findings

    # ------------------------------------------------------- fsdp HBM math
    def _leaf_bytes(self, leaf, *, storage: bool, sharded: bool,
                    spec_fn=None) -> float:
        import jax.numpy as jnp

        shape = getattr(leaf, "shape", None)
        if shape is None:
            return 0.0
        dt = np.dtype(leaf.dtype)
        if storage and self.precision.params_dtype is not None \
                and jnp.issubdtype(dt, np.floating):
            dt = np.dtype(self.precision.params_dtype)
        n = float(np.prod(shape, dtype=np.float64)) * dt.itemsize
        if not sharded:
            return n
        factor = 1
        for entry in tuple((spec_fn or self.param_spec)(shape)):
            if entry is None:
                continue
            for ax in (entry if isinstance(entry, (tuple, list)) else (entry,)):
                factor *= self._size(ax)
        return n / factor

    def _activation_factor(self, shape, activation_factors=None) -> int:
        """Shard factor of one activation shape: the propagated spec from
        the sharding-flow pass when available (tp-sharded hidden dims count
        — the PR 9 preflight bugfix), else the batch factor."""
        shape = tuple(int(s) for s in shape or ())
        if activation_factors:
            f = activation_factors.get(shape)
            if f:
                return int(f)
        return self.batch_factor

    def sharded_totals(self, net, report: dict,
                       activation_factors: Optional[dict] = None) -> dict:
        """Per-device byte projection of a :func:`telemetry.memory_report`
        under this layout — the fsdp HBM math ``preflight(layout=...)``
        checks against the budget:

        - params/grads divide by each leaf's ``param_spec`` factor (under
          ZeRO-1 that factor has no fsdp term — params replicate), moments
          by their ``opt_spec`` factor, and both drop to the storage dtype
          under the precision policy;
        - activations divide by their PROPAGATED shard factor when the
          sharding-flow pass supplied one (``activation_factors``: shape ->
          factor — a tp-sharded hidden activation counts its tp split, the
          bug the old batch-factor-only projection had), else by the batch
          factor; inputs divide by the batch factor.
        """
        import jax

        def _tree_bytes(tree, spec_tree):
            leaves = jax.tree_util.tree_leaves(tree)
            specs = jax.tree_util.tree_leaves(spec_tree, is_leaf=_is_spec)
            return sum(self._leaf_bytes(l, storage=True, sharded=True,
                                        spec_fn=lambda _s, s=s: s)
                       for l, s in zip(leaves, specs))

        # per-leaf spec TREES, not shape rules: once a net is bound, two
        # same-shaped params can resolve to different role specs
        p_pd = _tree_bytes(net.params, self.param_specs(net.params))
        o_pd = _tree_bytes(net.opt_state, self.opt_specs(net.opt_state))
        bf = self.batch_factor
        act_pd = 0.0
        rows = report.get("layers") or []
        for row in rows:
            act_pd += row["activation_bytes"] / self._activation_factor(
                row.get("activation_shape"), activation_factors)
        if not rows:
            act_pd = report["totals"]["activation_bytes"] / bf
        in_pd = report["totals"]["input_bytes"] / bf
        projected = 2 * p_pd + o_pd + act_pd + in_pd
        return {
            "param_bytes": int(p_pd),
            "grad_bytes": int(p_pd),
            "opt_state_bytes": int(o_pd),
            "activation_bytes": int(act_pd),
            "input_bytes": int(in_pd),
            "projected_peak_bytes": int(projected),
            "batch_factor": bf,
            "zero_stage": self.zero_stage,
        }

    # ---------------------------------------------------------------- misc
    def describe(self) -> dict:
        """JSON-ready layout summary (serving stats / flight events). A
        bound roles layout lists its resolved sites; binding already
        rejected non-divisible tp sizes, so a describable layout is a
        valid one."""
        out = {
            "axes": self.axis_sizes,
            "batch_axes": list(self._batch_axes),
            "fsdp_axis": self._fsdp_axis,
            "tp_axis": self._tp_axis,
            "seq_axis": self._seq_axis,
            "pipe_axis": self._pipe_axis,
            "expert_axis": self._expert_axis,
            "devices": self.num_devices,
            "zero_stage": self.zero_stage,
            "roles": self.roles,
            "precision": self.precision.describe(),
        }
        if self._role_map is not None:
            out["role_sites"] = self.role_sites
        return out

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        sizes = "x".join(f"{a}={s}" for a, s in self.axis_sizes.items())
        return f"MeshLayout({sizes}, params_dtype={self.precision.params_dtype})"
