"""TrainingMaster SPI: pluggable distributed-training strategies.

Reference: spark/api/TrainingMaster.java:29 + TrainingWorker.java — the SPI
that made the Spark parameter-averaging strategy pluggable
(ParameterAveragingTrainingMaster.java: executeTraining:344, split/repartition
:655-664, processResults:770-811). Kept as an SPI here (SURVEY.md §5.8) so
per-step all-reduce AND periodic averaging coexist behind one interface; both
run on the same mesh machinery (wrapper.py) instead of Spark RDD shuffles.

Per-phase timing stats mirror the reference's SparkTrainingStats
(spark/stats/StatsUtils.java, ParameterAveragingTrainingMasterStats.java):
every split/broadcast/fit/aggregate phase is timed and queryable.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class TrainingStats:
    """Phase-timing events (reference: SparkTrainingStats / StatsUtils.java)."""

    events: List[Dict[str, Any]] = field(default_factory=list)

    def record(self, phase: str, start: float, end: float, **meta) -> None:
        self.events.append(
            {"phase": phase, "start": start, "duration_ms": (end - start) * 1e3, **meta}
        )

    def record_total(self, phase: str, duration_ms: float, **meta) -> None:
        """Record an aggregate phase total (e.g. from a profiler.StepTimer)."""
        self.events.append({"phase": phase, "start": None,
                            "duration_ms": duration_ms, **meta})

    def merge_timer(self, timer, prefix: str = "") -> None:
        """Fold a profiler.StepTimer breakdown into the phase events — the
        single instrumentation path shared with bench.py and the UI system
        page (reference: worker-phase stats folded into SparkTrainingStats)."""
        for phase, info in timer.breakdown().items():
            self.record_total(prefix + phase, info["total_s"] * 1e3,
                              count=info["count"], mean_ms=info["mean_ms"])

    def total_ms(self, phase: str) -> float:
        return sum(e["duration_ms"] for e in self.events if e["phase"] == phase)

    def phases(self) -> List[str]:
        seen = []
        for e in self.events:
            if e["phase"] not in seen:
                seen.append(e["phase"])
        return seen

    def summary(self) -> Dict[str, float]:
        return {p: self.total_ms(p) for p in self.phases()}

    def export_html(self, path: str) -> None:
        """Reference: StatsUtils.exportStatsAsHtml — simple bar-chart export."""
        rows = "".join(
            f"<tr><td>{p}</td><td>{ms:.1f}</td>"
            f"<td><div style='background:#4a7;height:12px;width:{min(ms, 600):.0f}px'></div></td></tr>"
            for p, ms in self.summary().items()
        )
        html = (
            "<html><body><h2>Training phase timings</h2>"
            f"<table border=1><tr><th>phase</th><th>total ms</th><th></th></tr>{rows}</table>"
            "</body></html>"
        )
        with open(path, "w") as f:
            f.write(html)


class TrainingMaster:
    """Strategy SPI (reference: spark/api/TrainingMaster.java:29)."""

    def execute_training(self, net, data, epochs: int = 1):
        raise NotImplementedError

    def get_stats(self) -> TrainingStats:
        raise NotImplementedError


class SyncAllReduceTrainingMaster(TrainingMaster):
    """Per-step gradient all-reduce over the mesh — the modern, strictly better
    form of averagingFrequency=1 (SURVEY.md §5.8). Subsumes both the reference's
    ParallelWrapper (single host) and its Spark master when the mesh spans hosts."""

    def __init__(self, workers: Optional[int] = None, mesh=None, layout=None):
        from .wrapper import ParallelWrapper

        self._wrapper_cls = ParallelWrapper
        self.workers = workers
        self.mesh = mesh
        # MeshLayout (parallel/layout.py): the single sharding authority —
        # dp×fsdp×tp placement plus the precision policy; mesh= stays as the
        # legacy data-parallel spelling (it wraps into a layout downstream)
        self.layout = layout
        self.stats = TrainingStats()

    def execute_training(self, net, data, epochs: int = 1):
        t0 = time.perf_counter()
        wrapper = self._wrapper_cls(
            net, workers=self.workers, averaging_frequency=1, mesh=self.mesh,
            layout=self.layout,
        )
        self.stats.record("setup", t0, time.perf_counter())
        t1 = time.perf_counter()
        wrapper.fit(data, epochs=epochs)
        self.stats.record("fit", t1, time.perf_counter(), iterations=wrapper.iteration)
        self.stats.merge_timer(wrapper.timer)
        return net

    def get_stats(self) -> TrainingStats:
        return self.stats


class ParameterAveragingTrainingMaster(TrainingMaster):
    """Periodic parameter averaging (reference:
    impl/paramavg/ParameterAveragingTrainingMaster.java). The reference's
    driver-side split → broadcast → worker-fit → treeAggregate loop maps to:
    replica-stacked params on the mesh (broadcast ≡ initial stack), independent
    vmapped worker steps (ExecuteWorkerFlatMap ≡ vmap), and a mean over the
    replica axis (treeAggregate ≡ all-reduce) every ``averaging_frequency``
    iterations. ``batches_per_worker`` sizes each worker's share of a split
    (reference: batchSizePerWorker/averagingFrequency split sizing :655-664)."""

    def __init__(
        self,
        workers: Optional[int] = None,
        averaging_frequency: int = 5,
        batches_per_worker: int = 1,
        average_updaters: bool = True,
        report_score_after_averaging: bool = True,
        collect_training_stats: bool = True,
        mesh=None,
        layout=None,
    ):
        self.workers = workers
        self.averaging_frequency = averaging_frequency
        self.batches_per_worker = batches_per_worker
        self.average_updaters = average_updaters
        self.report_score_after_averaging = report_score_after_averaging
        self.collect_training_stats = collect_training_stats
        self.mesh = mesh
        # pure-dp MeshLayouts only: the wrapper refuses fsdp/tp/expert
        # layouts in periodic mode (replica stacking drops param sharding)
        self.layout = layout
        self.stats = TrainingStats()

    def execute_training(self, net, data, epochs: int = 1):
        from .wrapper import ParallelWrapper

        t0 = time.perf_counter()
        wrapper = ParallelWrapper(
            net,
            workers=self.workers,
            averaging_frequency=self.averaging_frequency,
            average_updaters=self.average_updaters,
            report_score_after_averaging=self.report_score_after_averaging,
            mesh=self.mesh,
            layout=self.layout,
        )
        if self.collect_training_stats:
            self.stats.record("broadcast", t0, time.perf_counter())
        t1 = time.perf_counter()
        wrapper.fit(data, epochs=epochs)
        if self.collect_training_stats:
            self.stats.record("fit", t1, time.perf_counter(), iterations=wrapper.iteration)
            self.stats.merge_timer(wrapper.timer)
        return net

    def get_stats(self) -> TrainingStats:
        return self.stats
