"""CLI front-end for data-parallel training (reference:
parallelism/main/ParallelWrapperMain.java — the only training CLI the
reference ships: load a serialized model, build an iterator, train through
ParallelWrapper, write the trained model back).

TPU-native shape: the model is the checkpoint zip triple
(utils/serialization), the data is a directory of exported ``.npz`` DataSet
shards (datasets/export — the Spark-export analog), and the wrapper trains
over a device mesh with sync all-reduce or periodic averaging. Flag names
mirror the reference's (--model-path, --workers, --averaging-frequency,
--report-score, --average-updaters, --model-output-path).

Run:  python -m deeplearning4j_tpu.parallel.main \
        --model-path model.zip --data-dir shards/ --epochs 2 \
        --workers 8 --averaging-frequency 1 --model-output-path out.zip
"""

from __future__ import annotations

import argparse
from typing import Optional, Sequence


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="deeplearning4j_tpu.parallel.main",
        description="Train a serialized model data-parallel over the device "
                    "mesh (ParallelWrapperMain parity).",
    )
    ap.add_argument("--model-path", required=True,
                    help="checkpoint zip triple to load (ModelSerializer format)")
    ap.add_argument("--data-dir", required=True,
                    help="directory of exported .npz DataSet shards")
    ap.add_argument("--model-output-path", required=True,
                    help="where the trained checkpoint triple is written")
    ap.add_argument("--workers", type=int, default=None,
                    help="devices to use (default: all)")
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--averaging-frequency", type=int, default=1,
                    help="1 = sync all-reduce every step (modern default); "
                         "N>1 = periodic parameter averaging (reference default)")
    ap.add_argument("--prefetch-size", type=int, default=2)
    ap.add_argument("--report-score", action="store_true",
                    help="log the score each iteration (ScoreIterationListener)")
    ap.add_argument("--no-average-updaters", action="store_true",
                    help="do not average updater state at averaging rounds")
    ap.add_argument("--shuffle", action="store_true",
                    help="shuffle shard order each epoch")
    ap.add_argument("--seed", type=int, default=0, help="shuffle seed")
    return ap


def run(argv: Optional[Sequence[str]] = None) -> str:
    args = build_parser().parse_args(argv)

    from ..datasets.export import FileDataSetIterator
    from ..optimize.listeners import ScoreIterationListener
    from ..utils.serialization import restore_model, write_model
    from .wrapper import ParallelWrapper

    net = restore_model(args.model_path)
    if args.report_score:
        net.set_listeners(ScoreIterationListener(print_every=1))
    it = FileDataSetIterator(args.data_dir, shuffle=args.shuffle,
                             seed=args.seed)
    wrapper = ParallelWrapper(
        net,
        workers=args.workers,
        averaging_frequency=args.averaging_frequency,
        average_updaters=not args.no_average_updaters,
        prefetch_buffer=args.prefetch_size,
    )
    wrapper.fit(it, epochs=args.epochs)
    write_model(net, args.model_output_path)
    return args.model_output_path


if __name__ == "__main__":
    run()
