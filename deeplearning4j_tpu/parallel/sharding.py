"""Tensor-parallel parameter sharding rules.

The reference's only parallelism is replica data-parallelism (SURVEY.md §2.4);
tensor parallelism is part of this framework's first-class distributed design.
The TPU-native mechanism is GSPMD: annotate each parameter leaf with a
NamedSharding over the mesh's ``model`` axis and let XLA partition every
matmul and insert the reduce-scatter/all-gather collectives — no hand-written
megatron forward/backward pair is needed.

Default layout: 2-D kernels shard their output (last) dimension, biases and
other 1-D vectors shard when divisible, everything else replicates. XLA's
sharding propagation then picks column-parallel → row-parallel transitions
automatically.
"""

from __future__ import annotations

import jax
import numpy as np


def tree_shardings(tree, mesh, model_axis: str = "model"):
    """NamedShardings for an arbitrary pytree by the shape rules above.
    Works for params AND optimizer state (Adam moments share their param's
    shape, so they land on the same sharding; scalar counts replicate)."""
    from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: PLC0415

    size = mesh.shape[model_axis]

    def rule(a):
        shape = np.shape(a)
        if len(shape) >= 2 and shape[-1] % size == 0:
            spec = P(*([None] * (len(shape) - 1)), model_axis)
        elif len(shape) == 1 and shape[0] % size == 0 and shape[0] >= size:
            spec = P(model_axis)
        else:
            spec = P()
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map(rule, tree)


def param_shardings(params, mesh, model_axis: str = "model"):
    """A pytree of NamedShardings matching ``params``' structure."""
    return tree_shardings(params, mesh, model_axis)


def shard_params(net, mesh, model_axis: str = "model"):
    """device_put the net's params (and existing optimizer state) with
    tensor-parallel shardings; returns the param sharding pytree so callers
    can reuse it for checkpoint restore."""
    net.init()
    shardings = param_shardings(net.params, mesh, model_axis)
    net.params = jax.device_put(net.params, shardings)
    if net.opt_state is not None:
        net.opt_state = jax.device_put(
            net.opt_state, tree_shardings(net.opt_state, mesh, model_axis)
        )
    return shardings
