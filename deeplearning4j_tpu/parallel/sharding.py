"""Tensor-parallel parameter sharding rules.

The reference's only parallelism is replica data-parallelism (SURVEY.md §2.4);
tensor parallelism is part of this framework's first-class distributed design.
The TPU-native mechanism is GSPMD: annotate each parameter leaf with a
NamedSharding over the mesh's ``model`` axis and let XLA partition every
matmul and insert the reduce-scatter/all-gather collectives — no hand-written
megatron forward/backward pair is needed.

Default layout: 2-D kernels shard their output (last) dimension, biases and
other 1-D vectors shard when divisible, everything else replicates. XLA's
sharding propagation then picks column-parallel → row-parallel transitions
automatically.
"""

from __future__ import annotations

import jax
import numpy as np


def tree_shardings(tree, mesh, model_axis: str | None = "model",
                   expert_axis: str | None = None):
    """NamedShardings for an arbitrary pytree by the shape rules above.
    Works for params AND optimizer state (Adam moments share their param's
    shape, so they land on the same sharding; scalar counts replicate).

    ``model_axis=None`` disables the tensor-parallel rules (expert-only
    layouts); a NAMED axis must exist on the mesh — a typo'd axis raising
    beats silently training fully replicated.

    ``expert_axis`` adds the expert-parallel rule: exactly-3-D leaves whose
    leading dim divides the axis (MoE expert-stacked weights [E, F, H])
    shard dim 0 over it — XLA then derives the dispatch/combine all-to-alls
    from the routing einsums, the GSPMD form of expert parallelism. (3-D
    exactly: 4-D conv kernels whose height happens to divide must not
    match.)

    Since the dp×fsdp×tp unification this is a thin wrapper over
    :class:`~deeplearning4j_tpu.parallel.layout.MeshLayout` — ONE rule set
    serves the legacy model/expert spelling and the canonical layout (a
    mesh carrying an ``fsdp`` axis additionally gets the fsdp rule)."""
    from .layout import MeshLayout  # noqa: PLC0415

    return MeshLayout.from_mesh(
        mesh, model_axis, expert_axis).param_shardings(tree)


def param_shardings(params, mesh, model_axis: str | None = "model",
                    expert_axis: str | None = None):
    """A pytree of NamedShardings matching ``params``' structure."""
    return tree_shardings(params, mesh, model_axis, expert_axis)


def validate_shardings(shardings, mesh, params=None, *,
                       source: str = "<shardings>"):
    """DT008 pre-dispatch validation of declared PartitionSpecs /
    NamedShardings against the mesh axes actually present (plus shape
    divisibility when ``params`` is given). Returns analysis findings —
    empty means every spec is applicable on this mesh. Delegates to
    :func:`deeplearning4j_tpu.analysis.check_partition_specs`."""
    from ..analysis import check_partition_specs  # noqa: PLC0415

    return check_partition_specs(shardings, mesh, params, source=source)


def shard_params(net, mesh, model_axis: str | None = "model",
                 expert_axis: str | None = None):
    """device_put the net's params (and existing optimizer state) with
    tensor/expert-parallel shardings; returns the param sharding pytree so
    callers can reuse it for checkpoint restore. Delegates to
    :meth:`MeshLayout.shard_params` (which also replicates layer state and
    stamps the net so the serving fast path sees the placement)."""
    from .layout import MeshLayout  # noqa: PLC0415

    return MeshLayout.from_mesh(mesh, model_axis,
                                expert_axis).shard_params(net)
