"""Tensor-parallel parameter sharding rules.

The reference's only parallelism is replica data-parallelism (SURVEY.md §2.4);
tensor parallelism is part of this framework's first-class distributed design.
The TPU-native mechanism is GSPMD: annotate each parameter leaf with a
NamedSharding over the mesh's ``model`` axis and let XLA partition every
matmul and insert the reduce-scatter/all-gather collectives — no hand-written
megatron forward/backward pair is needed.

Default layout: 2-D kernels shard their output (last) dimension, biases and
other 1-D vectors shard when divisible, everything else replicates. XLA's
sharding propagation then picks column-parallel → row-parallel transitions
automatically.
"""

from __future__ import annotations

import jax
import numpy as np


def tree_shardings(tree, mesh, model_axis: str | None = "model",
                   expert_axis: str | None = None):
    """NamedShardings for an arbitrary pytree by the shape rules above.
    Works for params AND optimizer state (Adam moments share their param's
    shape, so they land on the same sharding; scalar counts replicate).

    ``model_axis=None`` disables the tensor-parallel rules (expert-only
    layouts); a NAMED axis must exist on the mesh — a typo'd axis raising
    beats silently training fully replicated.

    ``expert_axis`` adds the expert-parallel rule: exactly-3-D leaves whose
    leading dim divides the axis (MoE expert-stacked weights [E, F, H])
    shard dim 0 over it — XLA then derives the dispatch/combine all-to-alls
    from the routing einsums, the GSPMD form of expert parallelism. (3-D
    exactly: 4-D conv kernels whose height happens to divide must not
    match.)"""
    from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: PLC0415

    for ax in (model_axis, expert_axis):
        if ax is not None and ax not in mesh.shape:
            raise ValueError(f"axis '{ax}' not in mesh axes {tuple(mesh.shape)}")
    size = mesh.shape[model_axis] if model_axis is not None else 1
    esize = mesh.shape[expert_axis] if expert_axis else 1

    def rule(a):
        shape = np.shape(a)
        if (expert_axis and len(shape) == 3 and shape[0] % esize == 0
                and shape[0] >= esize):
            spec = P(expert_axis, *([None] * (len(shape) - 1)))
        elif len(shape) >= 2 and size > 1 and shape[-1] % size == 0:
            spec = P(*([None] * (len(shape) - 1)), model_axis)
        elif len(shape) == 1 and size > 1 and shape[0] % size == 0 and shape[0] >= size:
            spec = P(model_axis)
        else:
            spec = P()
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map(rule, tree)


def param_shardings(params, mesh, model_axis: str | None = "model",
                    expert_axis: str | None = None):
    """A pytree of NamedShardings matching ``params``' structure."""
    return tree_shardings(params, mesh, model_axis, expert_axis)


def validate_shardings(shardings, mesh, params=None, *,
                       source: str = "<shardings>"):
    """DT008 pre-dispatch validation of declared PartitionSpecs /
    NamedShardings against the mesh axes actually present (plus shape
    divisibility when ``params`` is given). Returns analysis findings —
    empty means every spec is applicable on this mesh. Delegates to
    :func:`deeplearning4j_tpu.analysis.check_partition_specs`."""
    from ..analysis import check_partition_specs  # noqa: PLC0415

    return check_partition_specs(shardings, mesh, params, source=source)


def shard_params(net, mesh, model_axis: str | None = "model",
                 expert_axis: str | None = None):
    """device_put the net's params (and existing optimizer state) with
    tensor/expert-parallel shardings; returns the param sharding pytree so
    callers can reuse it for checkpoint restore."""
    net.init()
    shardings = param_shardings(net.params, mesh, model_axis, expert_axis)
    net.params = jax.device_put(net.params, shardings)
    if net.opt_state is not None:
        net.opt_state = jax.device_put(
            net.opt_state, tree_shardings(net.opt_state, mesh, model_axis,
                                          expert_axis)
        )
    return shardings
