"""Device mesh construction + multi-host initialization.

TPU-native replacement for the reference's THREE distribution transports
(SURVEY.md §5.8): ParallelWrapper device threads (ParallelWrapper.java:120-126),
Spark TorrentBroadcast/treeAggregate (ParameterAveragingTrainingMaster.java),
and the Aeron parameter server (ParameterServerParallelWrapper.java:159-216).
All collapse into ONE abstraction: a `jax.sharding.Mesh` whose collectives ride
ICI within a slice and DCN across slices — XLA inserts them from sharding
annotations; there is no hand-written transport tier to maintain.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def make_mesh(workers: Optional[int] = None, axis_names: Tuple[str, ...] = ("data",),
              shape: Optional[Sequence[int]] = None):
    """Build a Mesh over the first `workers` devices (default: all).

    ``shape`` reshapes devices into a multi-axis mesh (e.g. (2, 4) with
    axis_names ("data", "model") for DP×TP). 1-D data mesh is the
    ParallelWrapper-parity default.
    """
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if workers is not None:
        if workers > len(devices):
            raise ValueError(f"requested {workers} workers, have {len(devices)} devices")
        devices = devices[:workers]
    arr = np.array(devices)
    if shape is not None:
        arr = arr.reshape(tuple(shape))
        if len(axis_names) != arr.ndim:
            raise ValueError("axis_names must match mesh shape rank")
    return Mesh(arr, axis_names=axis_names)


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> None:
    """Join a multi-host JAX runtime (reference-equivalent of standing up the
    Spark cluster / Aeron media driver). On TPU pods with standard env vars all
    arguments are auto-detected; afterwards ``jax.devices()`` spans every host
    and meshes built from it produce DCN-crossing collectives automatically."""
    import jax

    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)


def global_put(arr, sharding):
    """Place host data on a (possibly multi-process) sharding.

    Single-process: plain ``device_put``. Multi-process: every process holds
    the same host array and contributes its addressable shards via
    ``make_array_from_callback`` — the multi-controller analog of the Spark
    driver's broadcast (SURVEY.md §3.5): identical host-side data, one global
    device array spanning all hosts.
    """
    import jax

    if arr is None:
        return None
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    import numpy as _np

    a = _np.asarray(arr)
    return jax.make_array_from_callback(a.shape, sharding, lambda idx: a[idx])


def global_put_tree(tree, sharding):
    """``global_put`` over a pytree (one sharding for every leaf)."""
    import jax

    return jax.tree_util.tree_map(lambda a: global_put(a, sharding), tree)


def global_put_local(local_arr, sharding):
    """Assemble a global array from PER-PROCESS shards (SURVEY.md §7 hard
    part (d): per-host input pipelines feeding one mesh batch).

    Unlike :func:`global_put` (every process holds the full array — the
    broadcast pattern), each process passes only ITS slice of the global
    batch; jax stitches them into one global array over the sharding. This is
    how real multi-host input pipelines feed training: every host reads only
    its shard of the data. Single-process: plain device_put (the local shard
    IS the global array).
    """
    import jax

    if local_arr is None:
        return None
    if jax.process_count() == 1:
        return jax.device_put(local_arr, sharding)
    return jax.make_array_from_process_local_data(sharding, local_arr)


def replicated_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec())


def data_sharding(mesh, axis: str = "data"):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(axis))
