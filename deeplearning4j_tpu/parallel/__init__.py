"""Parallelism tier: mesh trainers replacing the reference's ParallelWrapper /
Spark parameter averaging / Aeron parameter server (SURVEY.md §2.4, §5.8)."""

from .mesh import (
    make_mesh,
    initialize_multihost,
    replicated_sharding,
    data_sharding,
)
from .layout import MeshLayout, PrecisionPolicy, layout_of
from .roles import (
    HEAD_AWARE_ROLES,
    RoleDivisibilityError,
    register_layer_role,
    registered_roles,
    roles_for,
)
from .wrapper import ParallelWrapper
from .training_master import (
    TrainingMaster,
    TrainingStats,
    SyncAllReduceTrainingMaster,
    ParameterAveragingTrainingMaster,
)
from .front_end import MeshComputationGraph, MeshDl4jMultiLayer
from .param_server import (
    ParameterServer,
    ParameterServerClient,
    ParameterServerParallelWrapper,
)
from .ring_attention import all_to_all_attention, attention, ring_attention
from .pipeline import (
    PipelinePlan,
    PipelinedTrainer,
    pipeline_apply,
    pipeline_shardings,
    plan_stages,
    sequential_apply,
    stack_stage_params,
)
from .sharding import param_shardings, shard_params

__all__ = [
    "make_mesh",
    "initialize_multihost",
    "replicated_sharding",
    "data_sharding",
    "MeshLayout",
    "PrecisionPolicy",
    "layout_of",
    "HEAD_AWARE_ROLES",
    "RoleDivisibilityError",
    "register_layer_role",
    "registered_roles",
    "roles_for",
    "ParallelWrapper",
    "TrainingMaster",
    "TrainingStats",
    "SyncAllReduceTrainingMaster",
    "ParameterAveragingTrainingMaster",
    "MeshDl4jMultiLayer",
    "MeshComputationGraph",
    "ParameterServer",
    "ParameterServerClient",
    "ParameterServerParallelWrapper",
    "attention",
    "ring_attention",
    "PipelinePlan",
    "PipelinedTrainer",
    "pipeline_apply",
    "pipeline_shardings",
    "plan_stages",
    "sequential_apply",
    "stack_stage_params",
    "all_to_all_attention",
    "param_shardings",
    "shard_params",
]
