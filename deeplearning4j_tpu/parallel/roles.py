"""Layer-semantics spec registry: per-site PartitionSpecs by ROLE, not ndim.

`MeshLayout._shape_spec` picks specs from parameter *shapes* ("2-D+ kernels
shard the last dim over tp"), which is the right default for dense stacks
but wrong for attention and LSTM: the flat last dim of an attention
projection is ``heads x head_dim`` and the flat last dim of an LSTM kernel
is the ``4H`` gate block ``[a|f|o|i]`` — splitting either across tp devices
pays per-step activation collectives that DT305 names site-by-site
(``analysis/shard_flow.py``). This module is the spec-rule half of ROADMAP
direction 2 (the analyzer half landed in PR 9), in the style of
SNIPPETS.md [2]'s ``SpecLayout``: layers declare what their parameters
*mean*, and the layout resolves head-aware specs from those roles.

Roles and their tp rules (fsdp composes per the usual ZeRO placement):

==================  =======================================================
``attention_qkv``   column-parallel ``[n_in, H*D] -> P(fsdp?, tp)``: each
                    device computes whole heads (tp must divide the head
                    count — the reshape ``[B,T,d] -> [B,T,H,D]`` then keeps
                    tp on the head dim and per-head attention math is local)
``attention_out``   row-parallel ``[d, d] -> P(tp, fsdp?)``: the contraction
                    dim is sharded on BOTH sides, so GSPMD keeps partial
                    sums and the whole block pays ONE all-reduce (Megatron
                    pattern; Shoeybi et al.)
``lstm_gates``      the input kernel ``W [n_in, 4H]`` goes row-parallel
                    ``P(tp, fsdp?)`` (tp shards the big hoisted ``x @ W``
                    projection; ONE all-reduce outside the scan) while the
                    recurrent kernel/bias/peepholes replicate over tp — the
                    ``i/f/g/o`` gate blocks stay device-local, so the scan
                    body runs with ZERO per-step collectives (the DT304/305
                    fix)
``ffn_up``          column-parallel ``P(fsdp?, tp)``, bias ``P(tp)`` — the
                    first half of a Megatron MLP pair
``ffn_down``        row-parallel ``P(tp, fsdp?)``, bias replicated over tp —
                    the gather-back half (also the right role for output/
                    softmax layers: logits come back whole, so the loss
                    softmax runs without cross-device reduces)
``embedding``       table replicated over tp (vocab rows shard over fsdp
                    when divisible) — lookups never pay a per-token gather
``generic``         the existing shape rules, unchanged
==================  =======================================================

The registry is keyed by layer class + param name. Layers ship their own
declarations via a ``PARAM_ROLES`` class attribute (resolved through the
MRO, ``bwd_``-prefixed bidirectional params follow their forward twin);
external/custom layers join with :func:`register_layer_role`. Role
resolution is OPT-IN per layout (``MeshLayout(..., roles=True)``) so every
existing layout stays bit-compatible with the shape rules.

Divisibility is checked, not silently skipped: a tp size that does not
divide the head count (or the LSTM/FFN row dim) raises
:class:`RoleDivisibilityError` naming the layer and dim — the old behavior
(fall back to the next shape rule) masked a misconfigured mesh.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

__all__ = [
    "ATTENTION_QKV", "ATTENTION_OUT", "LSTM_GATES", "FFN_UP", "FFN_DOWN",
    "EMBEDDING", "GENERIC", "HEAD_AWARE_ROLES", "RoleDivisibilityError",
    "register_layer_role", "registered_roles", "roles_for",
    "resolve_role_spec", "check_role_site",
]

ATTENTION_QKV = "attention_qkv"
ATTENTION_OUT = "attention_out"
LSTM_GATES = "lstm_gates"
FFN_UP = "ffn_up"
FFN_DOWN = "ffn_down"
EMBEDDING = "embedding"
GENERIC = "generic"

#: roles whose resolution makes a layer "head-aware" — DT305 must not fire
#: on a site that resolved through one of these
HEAD_AWARE_ROLES = frozenset({ATTENTION_QKV, ATTENTION_OUT, LSTM_GATES})

_ALL_ROLES = frozenset({ATTENTION_QKV, ATTENTION_OUT, LSTM_GATES, FFN_UP,
                        FFN_DOWN, EMBEDDING, GENERIC})

# (layer class name, param name) -> role. Class NAMES key the table so
# registration never imports layer modules (and JSON round-trips stay
# trivial); lookups walk the layer's MRO.
_REGISTRY: Dict[Tuple[str, str], str] = {}


class RoleDivisibilityError(ValueError):
    """tp size does not divide a role-sharded dim (head count / row dim)."""


def register_layer_role(layer_cls, param_name: str, role: str) -> None:
    """Map ``(layer class, param name)`` to a role. ``layer_cls`` may be the
    class or its name. This is THE extension point custom layers use to opt
    into head-aware tp — see docs/distributed.md "Layer roles"."""
    if role not in _ALL_ROLES:
        raise ValueError(f"unknown role {role!r}; valid: {sorted(_ALL_ROLES)}")
    name = layer_cls if isinstance(layer_cls, str) else layer_cls.__name__
    _REGISTRY[(str(name), str(param_name))] = role


def registered_roles() -> Dict[Tuple[str, str], str]:
    """Snapshot of the explicit registry (PARAM_ROLES declarations on layer
    classes are resolved per-layer by :func:`roles_for`, not listed here)."""
    return dict(_REGISTRY)


def roles_for(layer) -> Dict[str, str]:
    """Every ``param name -> role`` mapping for ``layer``: explicit
    registrations (by any class in the MRO) override the class's own
    ``PARAM_ROLES`` declaration. Empty dict = purely generic layer."""
    out: Dict[str, str] = {}
    for cls in reversed(type(layer).__mro__):
        out.update(getattr(cls, "PARAM_ROLES", None) or {})
    for cls in reversed(type(layer).__mro__):
        cname = cls.__name__
        for (lname, pname), role in _REGISTRY.items():
            if lname == cname:
                out[pname] = role
    return out


def role_of(layer, param_name: str) -> Optional[str]:
    """The role of one param, or None. ``bwd_``-prefixed params (the
    bidirectional-LSTM direction twin) follow their forward name."""
    rmap = roles_for(layer)
    if param_name in rmap:
        return rmap[param_name]
    if param_name.startswith("bwd_") and param_name[4:] in rmap:
        return rmap[param_name[4:]]
    return None


# --------------------------------------------------------------- spec rules
def _require(cond: bool, layer, param: str, msg: str) -> None:
    if not cond:
        raise RoleDivisibilityError(
            f"{type(layer).__name__}.{param}: {msg} — a non-divisible tp "
            "size would silently split heads/gates across devices; shrink "
            "tp or change the layer width")


def check_role_site(layer, layer_key, param: str, role: str, shape,
                    tp_size: int, ctx: Optional[dict] = None) -> None:
    """The divisibility contract, checked at bind time (so ``describe()``/
    ``validate()``/``apply()`` all reject early instead of silently falling
    back to the next shape rule). ``ctx`` carries bind-time site context
    (``after_scan``: the producing stage is an LSTM scan, so ffn_down
    resolves replicated and its row-dim constraint does not apply)."""
    if tp_size <= 1:
        return
    ctx = ctx or {}
    shape = tuple(int(s) for s in shape)
    base = param[4:] if param.startswith("bwd_") else param
    if role in (ATTENTION_QKV, ATTENTION_OUT):
        heads = getattr(layer, "n_heads", None)
        if heads is not None:
            _require(int(heads) % tp_size == 0, layer, param,
                     f"tp={tp_size} does not divide n_heads={int(heads)} "
                     "(the head dim)")
        if role == ATTENTION_QKV and len(shape) >= 2:
            _require(shape[-1] % tp_size == 0, layer, param,
                     f"tp={tp_size} does not divide the projection width "
                     f"dim [-1]={shape[-1]}")
        if role == ATTENTION_OUT and len(shape) >= 2:
            _require(shape[0] % tp_size == 0, layer, param,
                     f"tp={tp_size} does not divide the row (contraction) "
                     f"dim [0]={shape[0]}")
    elif role == LSTM_GATES and base == "W" and len(shape) >= 2:
        gate_block = shape[-1] // 4 if shape[-1] % 4 == 0 else shape[-1]
        _require(shape[0] % tp_size == 0, layer, param,
                 f"tp={tp_size} does not divide the input dim "
                 f"[0]={shape[0]} (the 4H gate block [4x{gate_block}] "
                 "stays device-local; tp shards the input rows)")
    elif role in (FFN_DOWN,) and len(shape) >= 2 \
            and not ctx.get("after_scan"):
        _require(shape[0] % tp_size == 0, layer, param,
                 f"tp={tp_size} does not divide the row (contraction) "
                 f"dim [0]={shape[0]}")
    elif role in (FFN_UP,) and len(shape) >= 2:
        _require(shape[-1] % tp_size == 0, layer, param,
                 f"tp={tp_size} does not divide the column dim "
                 f"[-1]={shape[-1]}")


def _column_parallel(layout, shape, with_fsdp: bool):
    # [.., out_features] -> out features over tp, a non-tp dim over fsdp
    return layout._shape_spec(shape, with_fsdp=with_fsdp)


def _row_parallel(layout, shape, with_fsdp: bool):
    from jax.sharding import PartitionSpec as P

    shape = tuple(int(s) for s in shape)
    tsize = layout._size(layout._tp_axis)
    fsize = layout._size(layout._fsdp_axis) if with_fsdp else 1
    entries: list = [None] * len(shape)
    if tsize > 1 and shape[0] % tsize == 0:
        entries[0] = layout._tp_axis
    if fsize > 1:
        for d in range(len(shape) - 1, -1, -1):
            if entries[d] is None and shape[d] % fsize == 0 \
                    and shape[d] >= fsize:
                entries[d] = layout._fsdp_axis
                break
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def _replicated_over_tp(layout, shape, with_fsdp: bool):
    # the generic shape rule with the tp axis masked out
    return layout._shape_spec(shape, with_fsdp=with_fsdp, with_tp=False)


def _tp_vector(layout, shape, with_fsdp: bool):
    from jax.sharding import PartitionSpec as P

    tsize = layout._size(layout._tp_axis)
    if len(shape) == 1 and tsize > 1 and int(shape[0]) % tsize == 0:
        return P(layout._tp_axis)
    return _replicated_over_tp(layout, shape, with_fsdp)


def resolve_role_spec(layout, role: str, param: str, shape,
                      with_fsdp: bool, ctx: Optional[dict] = None):
    """PartitionSpec for one role site, or None to fall back to the generic
    shape rule. ``layout`` is the MeshLayout doing the resolution; ``ctx``
    is the bind-time site context (see :func:`check_role_site`)."""
    shape = tuple(int(s) for s in shape)
    ctx = ctx or {}
    base = param[4:] if param.startswith("bwd_") else param
    if role == GENERIC or not shape:
        return None
    if role == FFN_DOWN and ctx.get("after_scan"):
        # row-parallel assumes the producing stage left features tp-local
        # (attention/column-parallel math). After an LSTM scan the input is
        # replicated, and a row-parallel head would push a tp-sharded
        # cotangent into EVERY backward scan step — replicate instead.
        return _replicated_over_tp(layout, shape, with_fsdp)
    if role == ATTENTION_QKV:
        return _column_parallel(layout, shape, with_fsdp) \
            if len(shape) >= 2 else _tp_vector(layout, shape, with_fsdp)
    if role in (ATTENTION_OUT, FFN_DOWN):
        return _row_parallel(layout, shape, with_fsdp) \
            if len(shape) >= 2 else _replicated_over_tp(layout, shape,
                                                        with_fsdp)
    if role == FFN_UP:
        return _column_parallel(layout, shape, with_fsdp) \
            if len(shape) >= 2 else _tp_vector(layout, shape, with_fsdp)
    if role == LSTM_GATES:
        if base == "W" and len(shape) >= 2:
            return _row_parallel(layout, shape, with_fsdp)
        # RW / b / peepholes: gate math stays device-local
        return _replicated_over_tp(layout, shape, with_fsdp)
    if role == EMBEDDING:
        return _replicated_over_tp(layout, shape, with_fsdp)
    return None
