"""Test-support subsystem: deterministic fault injection (:mod:`.chaos`)."""

from .chaos import CHAOS_PLAN_ENV, ChaosSource, FaultPlan, corrupt_file, truncate_file

__all__ = ["CHAOS_PLAN_ENV", "ChaosSource", "FaultPlan", "corrupt_file",
           "truncate_file"]
