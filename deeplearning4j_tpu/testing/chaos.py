"""Deterministic fault injection: seeded schedules of named faults.

A :class:`FaultPlan` is a seed plus a list of fault specs. Production
code exposes explicit hooks — ``store.chaos.fire("checkpoint.write",
path=...)``, ``worker.chaos.fire("worker.healthz")``, a
:class:`ChaosSource` wrapped around any ``RecordSource`` — and the plan
decides, purely from per-site occurrence counters and the seed, which
calls actually fault. No monkeypatching: a site that isn't instrumented
can't fault, and the same seed always yields the same fault sequence
(``plan.fired``), which is what lets ``scripts/chaos_soak.py`` and the
fleet chaos self-scan in ``scripts/check.sh`` replay an exact failure
scenario and compare recovery event trails run to run.

Fault kinds (``spec["fault"]``):

- ``corrupt-checkpoint`` — flip bytes of the just-written version file
  (offsets drawn from ``Random((seed, site, n))``).
- ``torn-tmp``           — drop a stale ``.tmp-v*`` file in the store
  directory, as a killed writer would.
- ``hang-worker``        — the worker's ``/healthz`` handler sleeps past
  the router's health deadline (params: ``seconds``).
- ``partial-http``       — ``/healthz`` declares a Content-Length but
  sends only half the body.
- ``source-error``       — the wrapped source raises ``ConnectionError``
  for ``params["polls"]`` consecutive polls.
- ``source-slow``        — delay one poll by ``params["seconds"]``.
- ``nan-burst``          — the next ``params["records"]`` records get
  their features replaced with NaN.
- ``kill-worker``        — descriptive only: the plan records it and the
  harness (soak script / self-scan) delivers the actual SIGKILL.

A spec triggers by occurrence index at its site: ``{"at": [3, 9]}``
fires on the 3rd and 9th call, ``{"every": 300}`` fires on every 300th.
An optional ``{"marker": path}`` makes a fault at-most-once *across
processes* (first process to atomically create the marker file wins) —
used so a respawned worker doesn't re-hang forever. Plans round-trip
through the ``DL4JTPU_CHAOS_PLAN`` env var (JSON) so subprocess fleet
workers join the same plan. See docs/robustness.md for the schema.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Any, Dict, Iterable, List, Optional

__all__ = ["CHAOS_PLAN_ENV", "ChaosSource", "FaultPlan", "corrupt_file",
           "truncate_file"]

CHAOS_PLAN_ENV = "DL4JTPU_CHAOS_PLAN"

FAULT_KINDS = ("corrupt-checkpoint", "torn-tmp", "kill-worker", "hang-worker",
               "partial-http", "source-error", "source-slow", "nan-burst")


def corrupt_file(path: str, seed: int, n_bytes: int = 64) -> List[int]:
    """Flip ``n_bytes`` bytes of ``path`` at seed-deterministic offsets."""
    rng = random.Random(seed)
    size = os.path.getsize(path)
    if size == 0:
        return []
    offsets = sorted({rng.randrange(size) for _ in range(max(1, n_bytes))})
    with open(path, "r+b") as fh:
        for off in offsets:
            fh.seek(off)
            b = fh.read(1)
            fh.seek(off)
            fh.write(bytes([b[0] ^ 0xFF]))
    return offsets


def truncate_file(path: str, keep_frac: float = 0.5) -> int:
    """Truncate ``path`` to ``keep_frac`` of its size; return the new size."""
    size = os.path.getsize(path)
    keep = max(0, int(size * float(keep_frac)))
    with open(path, "r+b") as fh:
        fh.truncate(keep)
    return keep


class FaultPlan:
    """A seeded, data-driven schedule of faults over named sites."""

    def __init__(self, seed: int, faults: Optional[Iterable[Dict[str, Any]]] = None):
        self.seed = int(seed)
        self.faults: List[Dict[str, Any]] = [dict(f) for f in (faults or [])]
        for spec in self.faults:
            if spec.get("fault") not in FAULT_KINDS:
                raise ValueError(f"unknown fault kind: {spec.get('fault')!r}")
            if "at" not in spec and "every" not in spec:
                raise ValueError(f"fault spec needs 'at' or 'every': {spec!r}")
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self.fired: List[Dict[str, Any]] = []

    # ------------------------------------------------------------- scheduling
    def _matches(self, spec: Dict[str, Any], n: int) -> bool:
        if "at" in spec:
            return n in spec["at"]
        every = int(spec["every"])
        return every > 0 and n % every == 0

    def _claim_marker(self, spec: Dict[str, Any]) -> bool:
        marker = spec.get("marker")
        if not marker:
            return True
        try:
            fd = os.open(marker, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.close(fd)
            return True
        except FileExistsError:
            return False
        except OSError:
            return False

    def fire(self, site: str, **ctx) -> Optional[Dict[str, Any]]:
        """One instrumented call at ``site``; returns the fault fired (if any).

        File-level faults (corrupt/torn-tmp) execute here against the
        paths in ``ctx``; behavioral faults (hang, partial-http, slow,
        error, nan-burst) are returned for the caller to interpret.
        """
        with self._lock:
            n = self._counts.get(site, 0) + 1
            self._counts[site] = n
            hit = None
            for spec in self.faults:
                if spec.get("site") == site and self._matches(spec, n):
                    hit = spec
                    break
            if hit is None or not self._claim_marker(hit):
                return None
            fault = {"site": site, "n": n, "fault": hit["fault"],
                     **dict(hit.get("params") or {})}
            self.fired.append({k: v for k, v in fault.items()})
        self._execute(fault, ctx)
        return fault

    def _execute(self, fault: Dict[str, Any], ctx: Dict[str, Any]) -> None:
        kind = fault["fault"]
        if kind == "corrupt-checkpoint" and ctx.get("path"):
            sub = hash((self.seed, fault["site"], fault["n"])) & 0x7FFFFFFF
            fault["offsets"] = len(corrupt_file(
                ctx["path"], sub, n_bytes=int(fault.get("bytes", 64))))
        elif kind == "torn-tmp" and ctx.get("directory"):
            version = int(ctx.get("version", 0)) + 1
            # A pid that cannot be alive: linux pid_max caps at 2**22.
            name = f".tmp-v{version:08d}-{2**22 + 1}"
            path = os.path.join(ctx["directory"], name)
            with open(path, "wb") as fh:
                fh.write(b"torn write, never completed")
            fault["tmp"] = name

    # ------------------------------------------------------------ inspection
    def schedule(self) -> List[Dict[str, Any]]:
        """The static trigger table (for docs / debugging)."""
        return [dict(spec) for spec in self.faults]

    def summary(self) -> dict:
        with self._lock:
            return {"seed": self.seed, "fired": [dict(f) for f in self.fired],
                    "counts": dict(self._counts)}

    # ---------------------------------------------------------- env transport
    def to_env(self) -> str:
        return json.dumps({"seed": self.seed, "faults": self.faults},
                          sort_keys=True)

    @classmethod
    def from_json(cls, raw: str) -> "FaultPlan":
        data = json.loads(raw)
        return cls(int(data.get("seed", 0)), data.get("faults") or [])

    @classmethod
    def from_env(cls, env=None) -> Optional["FaultPlan"]:
        raw = (env if env is not None else os.environ).get(CHAOS_PLAN_ENV)
        if not raw:
            return None
        try:
            return cls.from_json(raw)
        except Exception:
            return None


class ChaosSource:
    """RecordSource wrapper that injects plan-scheduled source faults.

    Sites: ``source.poll`` fires per poll call (``source-error`` /
    ``source-slow``), ``source.record`` fires per delivered record
    (``nan-burst``). Replay passes straight through to the inner source
    so a wrapped :class:`~..streaming.pipeline.ReplayBufferSource` (or
    any replayable inner) keeps working — wrap the buffer *around* this
    source when the replayed records must include the injected NaNs.
    """

    def __init__(self, inner, plan: FaultPlan):
        self.inner = inner
        self.plan = plan
        self._down_left = 0
        self._nan_left = 0
        self.outages = 0
        self.nan_records = 0

    def poll(self, timeout: float = 0.1):
        fault = self.plan.fire("source.poll")
        if fault is not None:
            if fault["fault"] == "source-error":
                self._down_left = max(self._down_left, int(fault.get("polls", 1)))
                self.outages += 1
            elif fault["fault"] == "source-slow":
                from ..runtime.resilience import Deadline  # noqa: PLC0415
                seconds = float(fault.get("seconds", 0.05))
                Deadline(seconds).pace(seconds)
        if self._down_left > 0:
            self._down_left -= 1
            raise ConnectionError("chaos: source outage")
        rec = self.inner.poll(timeout=timeout)
        if rec is None:
            return None
        fault = self.plan.fire("source.record")
        if fault is not None and fault["fault"] == "nan-burst":
            self._nan_left = max(self._nan_left, int(fault.get("records", 1)))
        if self._nan_left > 0:
            self._nan_left -= 1
            rec = self._poison(rec)
        return rec

    def _poison(self, rec):
        """Replace a record's features with NaN (tuple and dict shapes)."""
        import numpy as np  # noqa: PLC0415
        try:
            if isinstance(rec, (tuple, list)) and len(rec) >= 2:
                f = np.full_like(np.asarray(rec[0], np.float32), np.nan)
                self.nan_records += 1
                return (f,) + tuple(rec[1:])
            if isinstance(rec, dict) and "features" in rec:
                rec = dict(rec)
                rec["features"] = np.full_like(
                    np.asarray(rec["features"], np.float32), np.nan)
                self.nan_records += 1
                return rec
        except Exception:
            pass
        return rec

    def close(self) -> None:
        self.inner.close()

    def __getattr__(self, name):
        # Forward replay_cursor/replay/etc. to the wrapped source.
        return getattr(self.inner, name)
