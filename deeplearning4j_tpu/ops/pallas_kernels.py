"""Pallas TPU kernels — the architectural slot of the reference's cuDNN helper
tier (SURVEY.md §2.3: CudnnConvolutionHelper etc.).

On TPU, XLA already *is* the fast path for conv/BN/pooling, so unlike the
reference there is no helper needed for those. What earns hand-written kernels
here is what XLA fuses poorly (SURVEY.md §7):

- the LSTM recurrent cell: the h_{t-1}@RW matmul + 4 gate nonlinearities +
  peephole/cell update chain, executed T times under ``lax.scan``. One fused
  VMEM kernel per step keeps every intermediate on-chip (the reference's hot
  loop, LSTMHelpers.java:159-179).
- cross-channel LRN: windowed sum-of-squares + pow, a bandwidth-bound chain
  (CudnnLocalResponseNormalizationHelper's slot).

Both ops carry a custom VJP whose backward is also a fused kernel, mirroring
the reference pattern of helpers implementing both activate and
backpropGradient. Everything falls back to pure-XLA math off-TPU or for
unsupported activations — the same "helper absent → builtin math" fallback as
ConvolutionLayer.java:69-79's reflective loading.

Kernels run compiled on TPU; ``interpret=True`` (CPU tests) exercises
identical code paths.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from ..analysis.annotations import jit_entry

# gate/activation catalog usable inside kernels, with value-derivatives
# (derivative expressed in terms of the *activated* value, so the backward
# kernel needs no pre-activation residuals)
def _sigmoid_kernel(x):
    """sigmoid(x) = (tanh(x/2)+1)/2 — used ONLY inside Pallas kernel bodies.

    jax.nn.sigmoid (lax.logistic) trips a Mosaic bf16 lowering bug inside
    Pallas TPU kernels ('vector.broadcast' f32 scalar into a bf16 vector,
    verification error); the tanh form lowers cleanly at every dtype and is
    mathematically identical. The XLA scan path keeps lax.logistic: the
    tanh form underflows to exactly 0/1 for saturated gates where
    lax.logistic preserves tiny values — a relative-precision loss the
    float64 finite-difference gradchecks can resolve."""
    return 0.5 * (jnp.tanh(0.5 * x) + 1.0)


_ACT = {
    "tanh": (jnp.tanh, lambda y: 1.0 - y * y),
    "sigmoid": (jax.nn.sigmoid, lambda y: y * (1.0 - y)),
    "hardsigmoid": (
        lambda x: jnp.clip(0.2 * x + 0.5, 0.0, 1.0),
        lambda y: jnp.where((y > 0.0) & (y < 1.0), 0.2, 0.0),
    ),
    "relu": (jax.nn.relu, lambda y: (y > 0.0).astype(y.dtype)),
    "identity": (lambda x: x, lambda y: jnp.ones_like(y)),
}

# kernel-side table: identical except for the Mosaic-safe sigmoid
_ACT_KERNEL = dict(_ACT)
_ACT_KERNEL["sigmoid"] = (_sigmoid_kernel, _ACT["sigmoid"][1])


def _acc_dtype(dt):
    """Matmul accumulator dtype: ≥f32 always (Mosaic rejects a bf16 acc —
    'Expected matmul acc to be 32-bit'), but never BELOW the input dtype
    (f32 accumulation under the float64 gradcheck suites would truncate)."""
    return jnp.float32 if jnp.dtype(dt).itemsize < 4 else dt


def supported_lstm_activations(act: str, gate: str) -> bool:
    return act in _ACT and gate in _ACT


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# fused LSTM cell
# ---------------------------------------------------------------------------


def _cell_math(zx, h_prev, c_prev, RW, pF, pI, pO, act, gate):
    """Shared gate math (column order [a, f, o, i] — LSTMHelpers parity)."""
    H = c_prev.shape[-1]
    # Mosaic requires a 32-bit matmul accumulator (bf16 acc is rejected at
    # verification); accumulate f32 and cast back to the compute dtype
    z = zx + jnp.dot(h_prev, RW,
                     preferred_element_type=_acc_dtype(zx.dtype)).astype(zx.dtype)
    a = act(z[..., :H])
    f = gate(z[..., H : 2 * H] + c_prev * pF)
    i = gate(z[..., 3 * H :] + c_prev * pI)
    c = f * c_prev + i * a
    o = gate(z[..., 2 * H : 3 * H] + c * pO)
    cact = act(c)
    h = o * cact
    return h, c, a, f, o, i, cact


@jit_entry
def _fwd_kernel(act, gate, zx_ref, h_ref, c_ref, rw_ref, pf_ref, pi_ref,
                po_ref, h_out, c_out, a_out, f_out, o_out, i_out, cact_out):
    h, c, a, f, o, i, cact = _cell_math(
        zx_ref[:], h_ref[:], c_ref[:], rw_ref[:],
        pf_ref[:], pi_ref[:], po_ref[:], act, gate,
    )
    h_out[:], c_out[:] = h, c
    a_out[:], f_out[:], o_out[:], i_out[:], cact_out[:] = a, f, o, i, cact


@jit_entry
def _bwd_kernel(dact, dgate, a_ref, f_ref, o_ref, i_ref, cact_ref, cprev_ref,
                c_ref, hprev_ref, rw_ref, pf_ref, pi_ref, po_ref,
                dh_ref, dc_ref,
                dzx_out, dhprev_out, dcprev_out, drw_out, dpf_out, dpi_out,
                dpo_out):
    a, f, o, i = a_ref[:], f_ref[:], o_ref[:], i_ref[:]
    cact, c_prev, c = cact_ref[:], cprev_ref[:], c_ref[:]
    dh, dc = dh_ref[:], dc_ref[:]
    pF, pI, pO = pf_ref[:], pi_ref[:], po_ref[:]

    do = dh * cact * dgate(o)
    dc_tot = dc + dh * o * dact(cact) + do * pO
    df = dc_tot * c_prev * dgate(f)
    di = dc_tot * a * dgate(i)
    da = dc_tot * i * dact(a)
    dzx = jnp.concatenate([da, df, do, di], axis=-1)
    dcprev_out[:] = dc_tot * f + df * pF + di * pI
    dzx_out[:] = dzx
    dhprev_out[:] = jnp.dot(
        dzx, rw_ref[:].T, preferred_element_type=_acc_dtype(dzx.dtype)
    ).astype(dzx.dtype)
    drw_out[:] = jnp.dot(
        hprev_ref[:].T, dzx, preferred_element_type=_acc_dtype(dzx.dtype)
    ).astype(dzx.dtype)
    dpf_out[:] = jnp.sum(df * c_prev, axis=0)
    dpi_out[:] = jnp.sum(di * c_prev, axis=0)
    dpo_out[:] = jnp.sum(do * c, axis=0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8))
def fused_lstm_cell(zx, h_prev, c_prev, RW, pF, pI, pO,
                    act_name: str = "tanh", gate_name: str = "sigmoid"):
    """One LSTM step, fused in VMEM. Returns (h, c).

    ``zx`` is the precomputed input projection x_t@W + b for this step
    ([B, 4H]); the kernel performs the recurrent matmul and every gate op
    without round-tripping intermediates through HBM.
    """
    h, c, *_ = _cell_fwd_impl(zx, h_prev, c_prev, RW, pF, pI, pO,
                              act_name, gate_name)
    return h, c


def _cell_fwd_impl(zx, h_prev, c_prev, RW, pF, pI, pO, act_name, gate_name):
    from jax.experimental import pallas as pl  # noqa: PLC0415

    act, _ = _ACT_KERNEL[act_name]
    gate, _ = _ACT_KERNEL[gate_name]
    B, H = c_prev.shape
    dt = zx.dtype
    shapes = [jax.ShapeDtypeStruct((B, H), dt)] * 7
    kernel = functools.partial(_fwd_kernel, act, gate)
    return pl.pallas_call(
        kernel,
        out_shape=tuple(shapes),
        interpret=_interpret(),
    )(zx, h_prev, c_prev, RW, pF, pI, pO)


def _cell_fwd(zx, h_prev, c_prev, RW, pF, pI, pO, act_name, gate_name):
    h, c, a, f, o, i, cact = _cell_fwd_impl(
        zx, h_prev, c_prev, RW, pF, pI, pO, act_name, gate_name
    )
    residuals = (a, f, o, i, cact, c_prev, c, h_prev, RW, pF, pI, pO)
    return (h, c), residuals


def _cell_bwd(act_name, gate_name, residuals, grads):
    from jax.experimental import pallas as pl  # noqa: PLC0415

    a, f, o, i, cact, c_prev, c, h_prev, RW, pF, pI, pO = residuals
    dh, dc = grads
    _, dact = _ACT_KERNEL[act_name]
    _, dgate = _ACT_KERNEL[gate_name]
    B, H = c_prev.shape
    dt = dh.dtype
    out_shape = (
        jax.ShapeDtypeStruct((B, 4 * H), dt),   # dzx
        jax.ShapeDtypeStruct((B, H), dt),       # dh_prev
        jax.ShapeDtypeStruct((B, H), dt),       # dc_prev
        jax.ShapeDtypeStruct((H, 4 * H), dt),   # dRW
        jax.ShapeDtypeStruct((H,), dt),         # dpF
        jax.ShapeDtypeStruct((H,), dt),         # dpI
        jax.ShapeDtypeStruct((H,), dt),         # dpO
    )
    kernel = functools.partial(_bwd_kernel, dact, dgate)
    return pl.pallas_call(
        kernel,
        out_shape=out_shape,
        interpret=_interpret(),
    )(a, f, o, i, cact, c_prev, c, h_prev, RW, pF, pI, pO, dh, dc)


fused_lstm_cell.defvjp(_cell_fwd, _cell_bwd)


# ---------------------------------------------------------------------------
# LRN
# ---------------------------------------------------------------------------


def _window_sum(x: jnp.ndarray, n: int) -> jnp.ndarray:
    """sum over channel window W(c) = [c - n//2, c + n - 1 - n//2]."""
    half = n // 2
    C = x.shape[-1]
    padded = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(half, half)])
    acc = jnp.zeros_like(x)
    for j in range(n):
        acc = acc + jax.lax.slice_in_dim(padded, j, j + C, axis=-1)
    return acc


def _window_sum_adjoint(x: jnp.ndarray, n: int) -> jnp.ndarray:
    """Adjoint of _window_sum: channel c receives from every j with
    c ∈ W(j), i.e. the window offsets flip sign. Identical to _window_sum
    for odd n (symmetric window); shifted by one for even n."""
    lo = n - 1 - n // 2  # pad so offset range becomes [-(n-1-half), half]
    hi = n // 2
    C = x.shape[-1]
    padded = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(lo, hi)])
    acc = jnp.zeros_like(x)
    for j in range(n):
        acc = acc + jax.lax.slice_in_dim(padded, j, j + C, axis=-1)
    return acc


@jit_entry
def _lrn_fwd_kernel(k, n, alpha, beta, x_ref, y_ref, d_ref):
    x = x_ref[:]
    d = k + alpha * _window_sum(x * x, n)
    d_ref[:] = d
    y_ref[:] = x * d**-beta


@jit_entry
def _lrn_bwd_kernel(k, n, alpha, beta, x_ref, d_ref, g_ref, dx_ref):
    x, d, g = x_ref[:], d_ref[:], g_ref[:]
    # dx_c = g_c d_c^-b - 2ab x_c * Σ_{j: c∈W(j)} g_j x_j d_j^{-b-1}
    dx_ref[:] = g * d**-beta - 2.0 * alpha * beta * x * _window_sum_adjoint(
        g * x * d ** (-beta - 1.0), n
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def fused_lrn(x, k: float = 2.0, n: int = 5, alpha: float = 1e-4,
              beta: float = 0.75):
    """Cross-channel LRN on the trailing axis, one fused VMEM pass."""
    y, _ = _lrn_fwd_impl(x, k, n, alpha, beta)
    return y


def _as2d(x):
    return x.reshape(-1, x.shape[-1])


# rows per grid step: keeps each VMEM block ≲1MB for typical channel counts
_LRN_TILE_ROWS = 1024


def _lrn_specs(rows: int, C: int, n_arrays: int):
    """Row-tiled grid so arbitrarily large activations never exceed VMEM.
    The channel (window) axis stays whole inside each block."""
    from jax.experimental import pallas as pl  # noqa: PLC0415

    tile = min(_LRN_TILE_ROWS, rows)
    grid = (pl.cdiv(rows, tile),)
    spec = pl.BlockSpec((tile, C), lambda i: (i, 0))
    return grid, [spec] * n_arrays, spec


def _lrn_fwd_impl(x, k, n, alpha, beta):
    from jax.experimental import pallas as pl  # noqa: PLC0415

    x2 = _as2d(x)
    grid, in_specs, out_spec = _lrn_specs(x2.shape[0], x2.shape[1], 1)
    kernel = functools.partial(_lrn_fwd_kernel, k, n, alpha, beta)
    y, d = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=(out_spec, out_spec),
        out_shape=(jax.ShapeDtypeStruct(x2.shape, x2.dtype),) * 2,
        interpret=_interpret(),
    )(x2)
    return y.reshape(x.shape), d


def _lrn_fwd(x, k, n, alpha, beta):
    y, d = _lrn_fwd_impl(x, k, n, alpha, beta)
    return y, (x, d)


def _lrn_bwd(k, n, alpha, beta, residuals, g):
    from jax.experimental import pallas as pl  # noqa: PLC0415

    x, d = residuals
    x2, g2 = _as2d(x), _as2d(g)
    grid, in_specs, out_spec = _lrn_specs(x2.shape[0], x2.shape[1], 3)
    kernel = functools.partial(_lrn_bwd_kernel, k, n, alpha, beta)
    dx = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_spec,
        out_shape=jax.ShapeDtypeStruct(x2.shape, x2.dtype),
        interpret=_interpret(),
    )(x2, d, g2)
    return (dx.reshape(x.shape),)


fused_lrn.defvjp(_lrn_fwd, _lrn_bwd)


# ---------------------------------------------------------------------------
# time-fused LSTM sequence — the cuDNN "fused LSTM" analog
# ---------------------------------------------------------------------------
#
# The per-step fused cell above loses to XLA's scan on TPU because its custom
# VJP spills 7 residual arrays to HBM every step. This kernel fuses the WHOLE
# time loop instead: grid=(T,) executes sequentially on TPU, h/c live in VMEM
# scratch across grid steps, RW stays VMEM-resident, and only the 5 residual
# tensors cuDNN also reserves (gate activations + cell state) stream out —
# c_{t-1}/h_{t-1} are re-read in the backward via shifted block indices
# rather than stored twice. Select with DL4J_TPU_PALLAS=seq (measured winner
# becomes the default).

_SEQ_VMEM_BUDGET_BYTES = 12 * 1024 * 1024


def _seq_fits(B: int, H: int, itemsize: int) -> bool:
    # Model the BACKWARD kernel — its footprint dominates: RW plus the f32
    # (H, 4H) dRW accumulator are resident, dh/dc carries in scratch, and
    # per-step it streams dy + 5 residuals + c_prev/h_prev + dzx blocks
    # (double-buffered). The forward (RW + 2 carries + 7 streamed blocks)
    # is strictly smaller.
    resident = (H * 4 * H * itemsize      # RW
                + H * 4 * H * 4           # f32 dRW accumulator
                + 2 * B * H * itemsize    # dh/dc carries
                + 3 * H * 4)              # peephole accumulators
    streamed = 2 * (8 * B * H + B * 4 * H) * itemsize
    return resident + streamed < _SEQ_VMEM_BUDGET_BYTES


@jit_entry
def _seq_fwd_kernel(act, gate,
                    zx_ref, h0_ref, c0_ref, rw_ref, pf_ref, pi_ref, po_ref,
                    y_out, a_out, f_out, o_out, i_out, c_out, hT_out, cT_out,
                    h_scr, c_scr):
    from jax.experimental import pallas as pl  # noqa: PLC0415

    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = h0_ref[:]
        c_scr[:] = c0_ref[:]

    h, c, a, f, o, i, _cact = _cell_math(
        zx_ref[0], h_scr[:], c_scr[:], rw_ref[:],
        pf_ref[:], pi_ref[:], po_ref[:], act, gate,
    )
    y_out[0], a_out[0], f_out[0], o_out[0], i_out[0], c_out[0] = h, a, f, o, i, c
    h_scr[:], c_scr[:] = h, c
    # constant-index outputs: written every step, the last write is h_T/c_T
    hT_out[:], cT_out[:] = h, c


@jit_entry
def _seq_bwd_kernel(act, dact, dgate, T,
                    dy_ref, dhT_ref, dcT_ref,
                    a_ref, f_ref, o_ref, i_ref, cprev_ref, hprev_ref,
                    rw_ref, pf_ref, pi_ref, po_ref, h0_ref, c0_ref,
                    dzx_out, dh0_out, dc0_out, drw_out, dpf_out, dpi_out,
                    dpo_out,
                    dh_scr, dc_scr, drw_scr, dpf_scr, dpi_scr, dpo_scr):
    from jax.experimental import pallas as pl  # noqa: PLC0415

    k = pl.program_id(0)          # reverse-time grid: time t = T-1-k

    @pl.when(k == 0)
    def _init():
        dh_scr[:] = dhT_ref[:]
        dc_scr[:] = dcT_ref[:]
        drw_scr[:] = jnp.zeros(drw_scr.shape, drw_scr.dtype)
        dpf_scr[:] = jnp.zeros(dpf_scr.shape, dpf_scr.dtype)
        dpi_scr[:] = jnp.zeros(dpi_scr.shape, dpi_scr.dtype)
        dpo_scr[:] = jnp.zeros(dpo_scr.shape, dpo_scr.dtype)

    a, f, o, i = a_ref[0], f_ref[0], o_ref[0], i_ref[0]
    first = k == T - 1            # t == 0: previous state is the initial one
    c_prev = jnp.where(first, c0_ref[:], cprev_ref[0])
    h_prev = jnp.where(first, h0_ref[:], hprev_ref[0])
    # c_t recomputed from the gates (VPU-cheap) — only the prev-indexed c
    # stream is read, saving a T×B×H HBM stream (same as the masked kernel)
    c = f * c_prev + i * a
    cact = act(c)                 # recomputed, not stored
    pF, pI, pO = pf_ref[:], pi_ref[:], po_ref[:]

    dh = dy_ref[0] + dh_scr[:]
    dc = dc_scr[:]
    do = dh * cact * dgate(o)
    dc_tot = dc + dh * o * dact(cact) + do * pO
    df = dc_tot * c_prev * dgate(f)
    di = dc_tot * a * dgate(i)
    da = dc_tot * i * dact(a)
    dzx = jnp.concatenate([da, df, do, di], axis=-1)
    dzx_out[0] = dzx
    dh_scr[:] = jnp.dot(
        dzx, rw_ref[:].T, preferred_element_type=_acc_dtype(dzx.dtype)
    ).astype(dzx.dtype)
    dc_scr[:] = dc_tot * f + df * pF + di * pI
    f32 = drw_scr.dtype
    drw_scr[:] += jnp.dot(h_prev.T, dzx, preferred_element_type=f32)
    dpf_scr[:] += jnp.sum(df * c_prev, axis=0, dtype=f32)[None]
    dpi_scr[:] += jnp.sum(di * c_prev, axis=0, dtype=f32)[None]
    dpo_scr[:] += jnp.sum(do * c, axis=0, dtype=f32)[None]
    # constant-index outputs: last (t==0) write carries the full sums
    dt = dzx.dtype
    dh0_out[:] = dh_scr[:]
    dc0_out[:] = dc_scr[:]
    drw_out[:] = drw_scr[:].astype(dt)
    dpf_out[:] = dpf_scr[0].astype(dt)
    dpi_out[:] = dpi_scr[0].astype(dt)
    dpo_out[:] = dpo_scr[0].astype(dt)


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8))
def fused_lstm_sequence(zx, h0, c0, RW, pF, pI, pO,
                        act_name: str = "tanh", gate_name: str = "sigmoid"):
    """Whole-sequence fused LSTM: ``zx`` [T, B, 4H] (precomputed x@W + b),
    returns (ys [T, B, H], h_T, c_T). Unmasked, forward-direction.

    The primal (inference) path runs a LEAN kernel that emits only
    ys/hT/cT; the five gate residuals stream to HBM only under jax.grad
    (the VJP's forward rule) where the backward actually consumes them."""
    return _seq_lean_impl(zx, None, h0, c0, RW, pF, pI, pO,
                          act_name, gate_name)


@jit_entry
def _seq_lean_kernel(act, gate, masked, *refs):
    from jax.experimental import pallas as pl  # noqa: PLC0415

    if masked:  # static via partial — dl4jtpu: ignore[DT104]
        (zx_ref, m_ref, h0_ref, c0_ref, rw_ref, pf_ref, pi_ref, po_ref,
         y_out, hT_out, cT_out, h_scr, c_scr) = refs
    else:
        (zx_ref, h0_ref, c0_ref, rw_ref, pf_ref, pi_ref, po_ref,
         y_out, hT_out, cT_out, h_scr, c_scr) = refs
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = h0_ref[:]
        c_scr[:] = c0_ref[:]

    h_prev, c_prev = h_scr[:], c_scr[:]
    h, c, *_ = _cell_math(zx_ref[0], h_prev, c_prev, rw_ref[:],
                          pf_ref[:], pi_ref[:], po_ref[:], act, gate)
    if masked:  # static via partial — dl4jtpu: ignore[DT104]
        m = m_ref[0]
        h = m * h + (1.0 - m) * h_prev
        c = m * c + (1.0 - m) * c_prev
    y_out[0] = h
    h_scr[:], c_scr[:] = h, c
    hT_out[:], cT_out[:] = h, c


def _seq_lean_impl(zx, mask, h0, c0, RW, pF, pI, pO, act_name, gate_name):
    from jax.experimental import pallas as pl  # noqa: PLC0415
    from jax.experimental.pallas import tpu as pltpu  # noqa: PLC0415

    act, _ = _ACT_KERNEL[act_name]
    gate, _ = _ACT_KERNEL[gate_name]
    T, B, H4 = zx.shape
    H = H4 // 4
    dt = zx.dtype
    step = lambda t: (t, 0, 0)  # noqa: E731
    const = lambda t: (0, 0)    # noqa: E731
    in_specs = [pl.BlockSpec((1, B, H4), step)]
    args = [zx]
    if mask is not None:
        in_specs.append(pl.BlockSpec((1, B, 1), step))
        args.append(mask.astype(dt))
    in_specs += [
        pl.BlockSpec((B, H), const),
        pl.BlockSpec((B, H), const),
        pl.BlockSpec((H, H4), const),
        pl.BlockSpec((H,), lambda t: (0,)),
        pl.BlockSpec((H,), lambda t: (0,)),
        pl.BlockSpec((H,), lambda t: (0,)),
    ]
    args += [h0, c0, RW, pF, pI, pO]
    return pl.pallas_call(
        functools.partial(_seq_lean_kernel, act, gate, mask is not None),
        grid=(T,),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((1, B, H), step),
            pl.BlockSpec((B, H), const),
            pl.BlockSpec((B, H), const),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((T, B, H), dt),
            jax.ShapeDtypeStruct((B, H), dt),
            jax.ShapeDtypeStruct((B, H), dt),
        ),
        scratch_shapes=[pltpu.VMEM((B, H), dt), pltpu.VMEM((B, H), dt)],
        interpret=_interpret(),
    )(*args)


def _seq_fwd_impl(zx, h0, c0, RW, pF, pI, pO, act_name, gate_name):
    from jax.experimental import pallas as pl  # noqa: PLC0415
    from jax.experimental.pallas import tpu as pltpu  # noqa: PLC0415

    act, _ = _ACT_KERNEL[act_name]
    gate, _ = _ACT_KERNEL[gate_name]
    T, B, H4 = zx.shape
    H = H4 // 4
    dt = zx.dtype
    step = lambda t: (t, 0, 0)  # noqa: E731
    const3 = lambda t: (0, 0)   # noqa: E731
    seq_spec = lambda w: pl.BlockSpec((1, B, w), step)  # noqa: E731
    out_shape = (
        jax.ShapeDtypeStruct((T, B, H), dt),  # ys
        *[jax.ShapeDtypeStruct((T, B, H), dt) for _ in range(5)],  # a f o i c
        jax.ShapeDtypeStruct((B, H), dt),     # hT
        jax.ShapeDtypeStruct((B, H), dt),     # cT
    )
    return pl.pallas_call(
        functools.partial(_seq_fwd_kernel, act, gate),
        grid=(T,),
        in_specs=[
            seq_spec(H4),
            pl.BlockSpec((B, H), const3),
            pl.BlockSpec((B, H), const3),
            pl.BlockSpec((H, H4), const3),
            pl.BlockSpec((H,), lambda t: (0,)),
            pl.BlockSpec((H,), lambda t: (0,)),
            pl.BlockSpec((H,), lambda t: (0,)),
        ],
        out_specs=(
            seq_spec(H), seq_spec(H), seq_spec(H), seq_spec(H), seq_spec(H),
            seq_spec(H),
            pl.BlockSpec((B, H), const3),
            pl.BlockSpec((B, H), const3),
        ),
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((B, H), dt), pltpu.VMEM((B, H), dt)],
        interpret=_interpret(),
    )(zx, h0, c0, RW, pF, pI, pO)


def _seq_fwd(zx, h0, c0, RW, pF, pI, pO, act_name, gate_name):
    ys, a, f, o, i, c, hT, cT = _seq_fwd_impl(
        zx, h0, c0, RW, pF, pI, pO, act_name, gate_name
    )
    residuals = (ys, a, f, o, i, c, h0, c0, RW, pF, pI, pO)
    return (ys, hT, cT), residuals


def _seq_bwd(act_name, gate_name, residuals, grads):
    from jax.experimental import pallas as pl  # noqa: PLC0415
    from jax.experimental.pallas import tpu as pltpu  # noqa: PLC0415

    ys, a, f, o, i, c, h0, c0, RW, pF, pI, pO = residuals
    dys, dhT, dcT = grads
    act, dact = _ACT_KERNEL[act_name]
    _, dgate = _ACT_KERNEL[gate_name]
    T, B, H = ys.shape
    dt = ys.dtype
    rev = lambda k: (T - 1 - k, 0, 0)   # noqa: E731
    # previous-step state: block t-1, clamped at 0 (t==0 substitutes the
    # initial state inside the kernel)
    prev = lambda k: (jnp.maximum(T - 2 - k, 0), 0, 0)  # noqa: E731
    const = lambda k: (0, 0)            # noqa: E731
    seq = lambda ix: pl.BlockSpec((1, B, H), ix)  # noqa: E731
    out_shape = (
        jax.ShapeDtypeStruct((T, B, 4 * H), dt),  # dzx
        jax.ShapeDtypeStruct((B, H), dt),         # dh0
        jax.ShapeDtypeStruct((B, H), dt),         # dc0
        jax.ShapeDtypeStruct((H, 4 * H), dt),     # dRW
        jax.ShapeDtypeStruct((H,), dt),           # dpF
        jax.ShapeDtypeStruct((H,), dt),           # dpI
        jax.ShapeDtypeStruct((H,), dt),           # dpO
    )
    dzx, dh0, dc0, dRW, dpF, dpI, dpO = pl.pallas_call(
        functools.partial(_seq_bwd_kernel, act, dact, dgate, T),
        grid=(T,),
        in_specs=[
            seq(rev),                       # dys
            pl.BlockSpec((B, H), const),    # dhT
            pl.BlockSpec((B, H), const),    # dcT
            seq(rev), seq(rev), seq(rev), seq(rev),  # a f o i
            seq(prev),                      # c_{t-1} (from c)
            seq(prev),                      # h_{t-1} (from ys)
            pl.BlockSpec((H, 4 * H), const),
            pl.BlockSpec((H,), lambda k: (0,)),
            pl.BlockSpec((H,), lambda k: (0,)),
            pl.BlockSpec((H,), lambda k: (0,)),
            pl.BlockSpec((B, H), const),    # h0
            pl.BlockSpec((B, H), const),    # c0
        ],
        out_specs=(
            pl.BlockSpec((1, B, 4 * H), rev),
            pl.BlockSpec((B, H), const),
            pl.BlockSpec((B, H), const),
            pl.BlockSpec((H, 4 * H), const),
            pl.BlockSpec((H,), lambda k: (0,)),
            pl.BlockSpec((H,), lambda k: (0,)),
            pl.BlockSpec((H,), lambda k: (0,)),
        ),
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((B, H), dt), pltpu.VMEM((B, H), dt),
            pltpu.VMEM((H, 4 * H), jnp.float32),
            pltpu.VMEM((1, H), jnp.float32), pltpu.VMEM((1, H), jnp.float32),
            pltpu.VMEM((1, H), jnp.float32),
        ],
        interpret=_interpret(),
    )(dys, dhT, dcT, a, f, o, i, c, ys, RW, pF, pI, pO, h0, c0)
    return dzx, dh0, dc0, dRW, dpF, dpI, dpO


fused_lstm_sequence.defvjp(_seq_fwd, _seq_bwd)


# -- masked variant: padded/bucketed sequences ride the fused loop too ------
#
# Masked steps carry h/c through unchanged (h_t = m·h̃ + (1−m)·h_{t-1} — the
# scan path's semantics exactly). The backward recomputes the pre-mask cell
# state c̃ = f·c_prev + i·a from the stored gates, so the residual set stays
# the same five tensors plus the [T, B, 1] mask.


@jit_entry
def _seq_fwd_kernel_masked(act, gate,
                           zx_ref, m_ref, h0_ref, c0_ref, rw_ref, pf_ref,
                           pi_ref, po_ref,
                           y_out, a_out, f_out, o_out, i_out, c_out,
                           hT_out, cT_out, h_scr, c_scr):
    from jax.experimental import pallas as pl  # noqa: PLC0415

    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = h0_ref[:]
        c_scr[:] = c0_ref[:]

    h_prev, c_prev = h_scr[:], c_scr[:]
    h_tilde, c_tilde, a, f, o, i, _cact = _cell_math(
        zx_ref[0], h_prev, c_prev, rw_ref[:],
        pf_ref[:], pi_ref[:], po_ref[:], act, gate,
    )
    m = m_ref[0]
    h = m * h_tilde + (1.0 - m) * h_prev
    c = m * c_tilde + (1.0 - m) * c_prev
    y_out[0], a_out[0], f_out[0], o_out[0], i_out[0], c_out[0] = h, a, f, o, i, c
    h_scr[:], c_scr[:] = h, c
    hT_out[:], cT_out[:] = h, c


@jit_entry
def _seq_bwd_kernel_masked(act, dact, dgate, T,
                           dy_ref, dhT_ref, dcT_ref, m_ref,
                           a_ref, f_ref, o_ref, i_ref, cprev_ref,
                           hprev_ref, rw_ref, pf_ref, pi_ref, po_ref,
                           h0_ref, c0_ref,
                           dzx_out, dh0_out, dc0_out, drw_out, dpf_out,
                           dpi_out, dpo_out,
                           dh_scr, dc_scr, drw_scr, dpf_scr, dpi_scr, dpo_scr):
    from jax.experimental import pallas as pl  # noqa: PLC0415

    k = pl.program_id(0)

    @pl.when(k == 0)
    def _init():
        dh_scr[:] = dhT_ref[:]
        dc_scr[:] = dcT_ref[:]
        drw_scr[:] = jnp.zeros(drw_scr.shape, drw_scr.dtype)
        dpf_scr[:] = jnp.zeros(dpf_scr.shape, dpf_scr.dtype)
        dpi_scr[:] = jnp.zeros(dpi_scr.shape, dpi_scr.dtype)
        dpo_scr[:] = jnp.zeros(dpo_scr.shape, dpo_scr.dtype)

    a, f, o, i = a_ref[0], f_ref[0], o_ref[0], i_ref[0]
    first = k == T - 1
    c_prev = jnp.where(first, c0_ref[:], cprev_ref[0])
    h_prev = jnp.where(first, h0_ref[:], hprev_ref[0])
    m = m_ref[0]
    c_tilde = f * c_prev + i * a        # pre-mask cell state, recomputed
    cact = act(c_tilde)
    pF, pI, pO = pf_ref[:], pi_ref[:], po_ref[:]

    dh_t = dy_ref[0] + dh_scr[:]
    dc_t = dc_scr[:]
    dh = m * dh_t                        # gradient into the cell outputs
    dc = m * dc_t
    do = dh * cact * dgate(o)
    dc_tot = dc + dh * o * dact(cact) + do * pO
    df = dc_tot * c_prev * dgate(f)
    di = dc_tot * a * dgate(i)
    da = dc_tot * i * dact(a)
    dzx = jnp.concatenate([da, df, do, di], axis=-1)
    dzx_out[0] = dzx
    # carry-through paths: masked steps pass dh/dc straight to t-1
    dh_scr[:] = (jnp.dot(dzx, rw_ref[:].T,
                         preferred_element_type=_acc_dtype(dzx.dtype)
                         ).astype(dzx.dtype)
                 + (1.0 - m) * dh_t)
    dc_scr[:] = dc_tot * f + df * pF + di * pI + (1.0 - m) * dc_t
    f32 = drw_scr.dtype
    drw_scr[:] += jnp.dot(h_prev.T, dzx, preferred_element_type=f32)
    dpf_scr[:] += jnp.sum(df * c_prev, axis=0, dtype=f32)[None]
    dpi_scr[:] += jnp.sum(di * c_prev, axis=0, dtype=f32)[None]
    dpo_scr[:] += jnp.sum(do * c_tilde, axis=0, dtype=f32)[None]
    dt = dzx.dtype
    dh0_out[:] = dh_scr[:]
    dc0_out[:] = dc_scr[:]
    drw_out[:] = drw_scr[:].astype(dt)
    dpf_out[:] = dpf_scr[0].astype(dt)
    dpi_out[:] = dpi_scr[0].astype(dt)
    dpo_out[:] = dpo_scr[0].astype(dt)


@functools.partial(jax.custom_vjp, nondiff_argnums=(8, 9))
def fused_lstm_sequence_masked(zx, mask, h0, c0, RW, pF, pI, pO,
                               act_name: str = "tanh",
                               gate_name: str = "sigmoid"):
    """Masked whole-sequence fused LSTM: ``mask`` [T, B, 1]; masked steps
    hold h/c (scan-path semantics). Returns (ys, h_T, c_T). The primal runs
    the lean (no-residual) kernel; see fused_lstm_sequence."""
    return _seq_lean_impl(zx, mask, h0, c0, RW, pF, pI, pO,
                          act_name, gate_name)


def _seq_masked_fwd_impl(zx, mask, h0, c0, RW, pF, pI, pO, act_name,
                         gate_name):
    from jax.experimental import pallas as pl  # noqa: PLC0415
    from jax.experimental.pallas import tpu as pltpu  # noqa: PLC0415

    act, _ = _ACT_KERNEL[act_name]
    gate, _ = _ACT_KERNEL[gate_name]
    T, B, H4 = zx.shape
    H = H4 // 4
    dt = zx.dtype
    step = lambda t: (t, 0, 0)  # noqa: E731
    const = lambda t: (0, 0)    # noqa: E731
    seq_spec = lambda w: pl.BlockSpec((1, B, w), step)  # noqa: E731
    out_shape = (
        jax.ShapeDtypeStruct((T, B, H), dt),
        *[jax.ShapeDtypeStruct((T, B, H), dt) for _ in range(5)],
        jax.ShapeDtypeStruct((B, H), dt),
        jax.ShapeDtypeStruct((B, H), dt),
    )
    return pl.pallas_call(
        functools.partial(_seq_fwd_kernel_masked, act, gate),
        grid=(T,),
        in_specs=[
            seq_spec(H4),
            pl.BlockSpec((1, B, 1), step),
            pl.BlockSpec((B, H), const),
            pl.BlockSpec((B, H), const),
            pl.BlockSpec((H, H4), const),
            pl.BlockSpec((H,), lambda t: (0,)),
            pl.BlockSpec((H,), lambda t: (0,)),
            pl.BlockSpec((H,), lambda t: (0,)),
        ],
        out_specs=(
            seq_spec(H), seq_spec(H), seq_spec(H), seq_spec(H), seq_spec(H),
            seq_spec(H),
            pl.BlockSpec((B, H), const),
            pl.BlockSpec((B, H), const),
        ),
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((B, H), dt), pltpu.VMEM((B, H), dt)],
        interpret=_interpret(),
    )(zx, mask.astype(dt), h0, c0, RW, pF, pI, pO)


def _seq_masked_fwd(zx, mask, h0, c0, RW, pF, pI, pO, act_name, gate_name):
    ys, a, f, o, i, c, hT, cT = _seq_masked_fwd_impl(
        zx, mask, h0, c0, RW, pF, pI, pO, act_name, gate_name
    )
    residuals = (ys, a, f, o, i, c, mask, h0, c0, RW, pF, pI, pO)
    return (ys, hT, cT), residuals


def _seq_masked_bwd(act_name, gate_name, residuals, grads):
    from jax.experimental import pallas as pl  # noqa: PLC0415
    from jax.experimental.pallas import tpu as pltpu  # noqa: PLC0415

    ys, a, f, o, i, c, mask, h0, c0, RW, pF, pI, pO = residuals
    dys, dhT, dcT = grads
    act, dact = _ACT_KERNEL[act_name]
    _, dgate = _ACT_KERNEL[gate_name]
    T, B, H = ys.shape
    dt = ys.dtype
    rev = lambda k: (T - 1 - k, 0, 0)   # noqa: E731
    prev = lambda k: (jnp.maximum(T - 2 - k, 0), 0, 0)  # noqa: E731
    const = lambda k: (0, 0)            # noqa: E731
    seq = lambda ix: pl.BlockSpec((1, B, H), ix)  # noqa: E731
    out_shape = (
        jax.ShapeDtypeStruct((T, B, 4 * H), dt),
        jax.ShapeDtypeStruct((B, H), dt),
        jax.ShapeDtypeStruct((B, H), dt),
        jax.ShapeDtypeStruct((H, 4 * H), dt),
        jax.ShapeDtypeStruct((H,), dt),
        jax.ShapeDtypeStruct((H,), dt),
        jax.ShapeDtypeStruct((H,), dt),
    )
    dzx, dh0, dc0, dRW, dpF, dpI, dpO = pl.pallas_call(
        functools.partial(_seq_bwd_kernel_masked, act, dact, dgate, T),
        grid=(T,),
        in_specs=[
            seq(rev),
            pl.BlockSpec((B, H), const),
            pl.BlockSpec((B, H), const),
            pl.BlockSpec((1, B, 1), rev),
            seq(rev), seq(rev), seq(rev), seq(rev),
            # the kernel recomputes c_tilde from the gates, so only the
            # prev-indexed c stream is read (one T×B×H HBM stream saved)
            seq(prev),
            seq(prev),
            pl.BlockSpec((H, 4 * H), const),
            pl.BlockSpec((H,), lambda k: (0,)),
            pl.BlockSpec((H,), lambda k: (0,)),
            pl.BlockSpec((H,), lambda k: (0,)),
            pl.BlockSpec((B, H), const),
            pl.BlockSpec((B, H), const),
        ],
        out_specs=(
            pl.BlockSpec((1, B, 4 * H), rev),
            pl.BlockSpec((B, H), const),
            pl.BlockSpec((B, H), const),
            pl.BlockSpec((H, 4 * H), const),
            pl.BlockSpec((H,), lambda k: (0,)),
            pl.BlockSpec((H,), lambda k: (0,)),
            pl.BlockSpec((H,), lambda k: (0,)),
        ),
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((B, H), dt), pltpu.VMEM((B, H), dt),
            pltpu.VMEM((H, 4 * H), jnp.float32),
            pltpu.VMEM((1, H), jnp.float32), pltpu.VMEM((1, H), jnp.float32),
            pltpu.VMEM((1, H), jnp.float32),
        ],
        interpret=_interpret(),
    )(dys, dhT, dcT, mask.astype(dt), a, f, o, i, c, ys,
      RW, pF, pI, pO, h0, c0)
    return dzx, None, dh0, dc0, dRW, dpF, dpI, dpO


fused_lstm_sequence_masked.defvjp(_seq_masked_fwd, _seq_masked_bwd)


# ---------------------------------------------------------------------------
# fused softmax + cross-entropy — the loss-head hot path
# ---------------------------------------------------------------------------
#
# The reference fuses LossMCXENT with softmax numerically (losses.py keeps
# that); this kernel fuses it PHYSICALLY: one VMEM pass computes the per-row
# loss from logits+labels without materializing max/exp/sum/logp between HBM
# round trips, and the backward rebuilds the softmax in-tile to emit
# d(logits) and d(labels) in a single fused pass. Selected by the
# "softmax_xent" kernel_select site where the roofline says the loss head is
# bandwidth-bound (it always is — pure elementwise/reduce chains).

_SXENT_TILE_ROWS = 1024


def _sxent_specs(rows: int, C: int):
    from jax.experimental import pallas as pl  # noqa: PLC0415

    tile = min(_SXENT_TILE_ROWS, rows)
    grid = (pl.cdiv(rows, tile),)
    mat = pl.BlockSpec((tile, C), lambda i: (i, 0))
    col = pl.BlockSpec((tile, 1), lambda i: (i, 0))
    return grid, mat, col


def _sxent_compute_dt(dt):
    # bf16/f16 logits get f32 softmax math (exp/log at data precision loses
    # the loss's small differences); f32/f64 stay at their own precision
    return jnp.promote_types(dt, jnp.float32)


@jit_entry
def _sxent_fwd_kernel(x_ref, l_ref, loss_ref):
    cdt = _sxent_compute_dt(x_ref.dtype)
    x = x_ref[:].astype(cdt)
    lab = l_ref[:].astype(cdt)
    m = jnp.max(x, axis=-1, keepdims=True)
    lse = jnp.log(jnp.sum(jnp.exp(x - m), axis=-1, keepdims=True)) + m
    loss_ref[:] = (-jnp.sum(lab * (x - lse), axis=-1, keepdims=True)
                   ).astype(loss_ref.dtype)


@jit_entry
def _sxent_bwd_kernel(x_ref, l_ref, g_ref, dx_ref, dl_ref):
    cdt = _sxent_compute_dt(x_ref.dtype)
    x = x_ref[:].astype(cdt)
    lab = l_ref[:].astype(cdt)
    g = g_ref[:].astype(cdt)  # [R, 1]
    m = jnp.max(x, axis=-1, keepdims=True)
    ex = jnp.exp(x - m)
    s = jnp.sum(ex, axis=-1, keepdims=True)
    p = ex / s
    logp = x - (jnp.log(s) + m)
    # d/dx_j of -Σ_c lab_c·logp_c = p_j·Σ_c lab_c − lab_j  (general labels,
    # reduces to p − lab for one-hot)
    lab_sum = jnp.sum(lab, axis=-1, keepdims=True)
    dx_ref[:] = ((p * lab_sum - lab) * g).astype(dx_ref.dtype)
    dl_ref[:] = (-logp * g).astype(dl_ref.dtype)


@jax.custom_vjp
def fused_softmax_xent(preout, labels):
    """Per-row -Σ labels·log_softmax(preout) for 2D [N, C] inputs, one fused
    VMEM pass. Returns [N] row losses (mask/mean stay at the caller, exactly
    like losses._apply_mask over the unfused form)."""
    return _sxent_fwd_impl(preout, labels)


def _sxent_fwd_impl(preout, labels):
    from jax.experimental import pallas as pl  # noqa: PLC0415

    N, C = preout.shape
    grid, mat, col = _sxent_specs(N, C)
    out = pl.pallas_call(
        _sxent_fwd_kernel,
        grid=grid,
        in_specs=[mat, mat],
        out_specs=col,
        out_shape=jax.ShapeDtypeStruct((N, 1), _sxent_compute_dt(preout.dtype)),
        interpret=_interpret(),
    )(preout, labels)
    return out[:, 0]


def _sxent_fwd(preout, labels):
    return _sxent_fwd_impl(preout, labels), (preout, labels)


def _sxent_bwd(residuals, g):
    from jax.experimental import pallas as pl  # noqa: PLC0415

    preout, labels = residuals
    N, C = preout.shape
    grid, mat, col = _sxent_specs(N, C)
    g2 = g.reshape(N, 1).astype(_sxent_compute_dt(preout.dtype))
    dx, dl = pl.pallas_call(
        _sxent_bwd_kernel,
        grid=grid,
        in_specs=[mat, mat, col],
        out_specs=(mat, mat),
        out_shape=(jax.ShapeDtypeStruct((N, C), preout.dtype),
                   jax.ShapeDtypeStruct((N, C), labels.dtype)),
        interpret=_interpret(),
    )(preout, labels, g2)
    return dx, dl


fused_softmax_xent.defvjp(_sxent_fwd, _sxent_bwd)


# ---------------------------------------------------------------------------
# fused Adam update — the optimizer-step hot path
# ---------------------------------------------------------------------------
#
# The optax chain materializes every intermediate of the moment/bias-correct/
# scale pipeline as a tree-wide HBM round trip; per parameter leaf this
# kernel reads (g, m, v) and writes (update, m, v) once — the bandwidth
# floor of the math. Selected by the "optimizer" kernel_select site (the
# update is elementwise, i.e. always below the roofline ridge). Not
# differentiated: optimizer updates sit outside jax.grad by construction.

_ADAM_LANES = 128
_ADAM_TILE_ROWS = 4096


@jit_entry
def _adam_kernel(b1, b2, eps, g_ref, m_ref, v_ref, sc_ref,
                 u_out, m_out, v_out):
    g = g_ref[:]
    dt = g.dtype
    lr = sc_ref[0, 0].astype(dt)
    bc1 = sc_ref[0, 1].astype(dt)  # 1 - b1**t
    bc2 = sc_ref[0, 2].astype(dt)  # 1 - b2**t
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * g * g
    u_out[:] = -lr * (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    m_out[:] = m
    v_out[:] = v


def fused_adam_update(g, m, v, lr, bc1, bc2,
                      b1: float, b2: float, eps: float):
    """One fused Adam step for one parameter leaf: returns
    ``(update, new_m, new_v)`` with ``update = -lr·m̂/(√v̂+eps)`` using
    exactly optax's ``scale_by_adam`` bias corrections (``bc1``/``bc2`` are
    the traced ``1 - βᵢ**t`` scalars, ``lr`` the schedule's value). Any leaf
    shape: the view is flattened, lane-padded, and row-tiled; padded slots
    compute a zero update and are sliced off."""
    from jax.experimental import pallas as pl  # noqa: PLC0415

    shape, dt = g.shape, g.dtype
    n = g.size
    cols = _ADAM_LANES if n >= _ADAM_LANES else max(n, 1)
    pad = (-n) % cols
    rows = (n + pad) // cols

    def flat(a):
        a = a.reshape(-1).astype(dt)
        if pad:
            a = jnp.concatenate([a, jnp.zeros((pad,), dt)])
        return a.reshape(rows, cols)

    # traced scalars ride one (1, 3) array: lr, 1-b1^t, 1-b2^t (kept at
    # >=f32 — f64 under the x64 test env so parity against optax holds)
    sdt = jnp.promote_types(dt, jnp.float32)
    scalars = jnp.stack([jnp.asarray(lr), jnp.asarray(bc1),
                         jnp.asarray(bc2)]).astype(sdt).reshape(1, 3)
    tile = min(_ADAM_TILE_ROWS, rows)
    grid = (pl.cdiv(rows, tile),)
    mat = pl.BlockSpec((tile, cols), lambda i: (i, 0))
    sc = pl.BlockSpec((1, 3), lambda i: (0, 0))
    u2, m2, v2 = pl.pallas_call(
        functools.partial(_adam_kernel, float(b1), float(b2), float(eps)),
        grid=grid,
        in_specs=[mat, mat, mat, sc],
        out_specs=(mat, mat, mat),
        out_shape=(jax.ShapeDtypeStruct((rows, cols), dt),) * 3,
        interpret=_interpret(),
    )(flat(g), flat(m), flat(v), scalars)

    def unflat(a):
        return a.reshape(-1)[:n].reshape(shape)

    return unflat(u2), unflat(m2), unflat(v2)
