"""Pallas TPU kernels — the architectural slot of the reference's cuDNN helper
tier (SURVEY.md §2.3: CudnnConvolutionHelper etc.).

On TPU, XLA already *is* the fast path for conv/BN/pooling, so unlike the
reference there is no helper needed for those. What earns hand-written kernels
here is what XLA fuses poorly (SURVEY.md §7):

- the LSTM recurrent cell: the h_{t-1}@RW matmul + 4 gate nonlinearities +
  peephole/cell update chain, executed T times under ``lax.scan``. One fused
  VMEM kernel per step keeps every intermediate on-chip (the reference's hot
  loop, LSTMHelpers.java:159-179).
- cross-channel LRN: windowed sum-of-squares + pow, a bandwidth-bound chain
  (CudnnLocalResponseNormalizationHelper's slot).

Both ops carry a custom VJP whose backward is also a fused kernel, mirroring
the reference pattern of helpers implementing both activate and
backpropGradient. Everything falls back to pure-XLA math off-TPU or for
unsupported activations — the same "helper absent → builtin math" fallback as
ConvolutionLayer.java:69-79's reflective loading.

Kernels run compiled on TPU; ``interpret=True`` (CPU tests) exercises
identical code paths.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

# gate/activation catalog usable inside kernels, with value-derivatives
# (derivative expressed in terms of the *activated* value, so the backward
# kernel needs no pre-activation residuals)
_ACT = {
    "tanh": (jnp.tanh, lambda y: 1.0 - y * y),
    "sigmoid": (jax.nn.sigmoid, lambda y: y * (1.0 - y)),
    "hardsigmoid": (
        lambda x: jnp.clip(0.2 * x + 0.5, 0.0, 1.0),
        lambda y: jnp.where((y > 0.0) & (y < 1.0), 0.2, 0.0),
    ),
    "relu": (jax.nn.relu, lambda y: (y > 0.0).astype(y.dtype)),
    "identity": (lambda x: x, lambda y: jnp.ones_like(y)),
}


def supported_lstm_activations(act: str, gate: str) -> bool:
    return act in _ACT and gate in _ACT


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# fused LSTM cell
# ---------------------------------------------------------------------------


def _cell_math(zx, h_prev, c_prev, RW, pF, pI, pO, act, gate):
    """Shared gate math (column order [a, f, o, i] — LSTMHelpers parity)."""
    H = c_prev.shape[-1]
    z = zx + jnp.dot(h_prev, RW, preferred_element_type=zx.dtype)
    a = act(z[..., :H])
    f = gate(z[..., H : 2 * H] + c_prev * pF)
    i = gate(z[..., 3 * H :] + c_prev * pI)
    c = f * c_prev + i * a
    o = gate(z[..., 2 * H : 3 * H] + c * pO)
    cact = act(c)
    h = o * cact
    return h, c, a, f, o, i, cact


def _fwd_kernel(act, gate, zx_ref, h_ref, c_ref, rw_ref, pf_ref, pi_ref,
                po_ref, h_out, c_out, a_out, f_out, o_out, i_out, cact_out):
    h, c, a, f, o, i, cact = _cell_math(
        zx_ref[:], h_ref[:], c_ref[:], rw_ref[:],
        pf_ref[:], pi_ref[:], po_ref[:], act, gate,
    )
    h_out[:], c_out[:] = h, c
    a_out[:], f_out[:], o_out[:], i_out[:], cact_out[:] = a, f, o, i, cact


def _bwd_kernel(dact, dgate, a_ref, f_ref, o_ref, i_ref, cact_ref, cprev_ref,
                c_ref, hprev_ref, rw_ref, pf_ref, pi_ref, po_ref,
                dh_ref, dc_ref,
                dzx_out, dhprev_out, dcprev_out, drw_out, dpf_out, dpi_out,
                dpo_out):
    a, f, o, i = a_ref[:], f_ref[:], o_ref[:], i_ref[:]
    cact, c_prev, c = cact_ref[:], cprev_ref[:], c_ref[:]
    dh, dc = dh_ref[:], dc_ref[:]
    pF, pI, pO = pf_ref[:], pi_ref[:], po_ref[:]

    do = dh * cact * dgate(o)
    dc_tot = dc + dh * o * dact(cact) + do * pO
    df = dc_tot * c_prev * dgate(f)
    di = dc_tot * a * dgate(i)
    da = dc_tot * i * dact(a)
    dzx = jnp.concatenate([da, df, do, di], axis=-1)
    dcprev_out[:] = dc_tot * f + df * pF + di * pI
    dzx_out[:] = dzx
    dhprev_out[:] = jnp.dot(dzx, rw_ref[:].T, preferred_element_type=dzx.dtype)
    drw_out[:] = jnp.dot(hprev_ref[:].T, dzx, preferred_element_type=dzx.dtype)
    dpf_out[:] = jnp.sum(df * c_prev, axis=0)
    dpi_out[:] = jnp.sum(di * c_prev, axis=0)
    dpo_out[:] = jnp.sum(do * c, axis=0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8))
def fused_lstm_cell(zx, h_prev, c_prev, RW, pF, pI, pO,
                    act_name: str = "tanh", gate_name: str = "sigmoid"):
    """One LSTM step, fused in VMEM. Returns (h, c).

    ``zx`` is the precomputed input projection x_t@W + b for this step
    ([B, 4H]); the kernel performs the recurrent matmul and every gate op
    without round-tripping intermediates through HBM.
    """
    h, c, *_ = _cell_fwd_impl(zx, h_prev, c_prev, RW, pF, pI, pO,
                              act_name, gate_name)
    return h, c


def _cell_fwd_impl(zx, h_prev, c_prev, RW, pF, pI, pO, act_name, gate_name):
    from jax.experimental import pallas as pl  # noqa: PLC0415

    act, _ = _ACT[act_name]
    gate, _ = _ACT[gate_name]
    B, H = c_prev.shape
    dt = zx.dtype
    shapes = [jax.ShapeDtypeStruct((B, H), dt)] * 7
    kernel = functools.partial(_fwd_kernel, act, gate)
    return pl.pallas_call(
        kernel,
        out_shape=tuple(shapes),
        interpret=_interpret(),
    )(zx, h_prev, c_prev, RW, pF, pI, pO)


def _cell_fwd(zx, h_prev, c_prev, RW, pF, pI, pO, act_name, gate_name):
    h, c, a, f, o, i, cact = _cell_fwd_impl(
        zx, h_prev, c_prev, RW, pF, pI, pO, act_name, gate_name
    )
    residuals = (a, f, o, i, cact, c_prev, c, h_prev, RW, pF, pI, pO)
    return (h, c), residuals


def _cell_bwd(act_name, gate_name, residuals, grads):
    from jax.experimental import pallas as pl  # noqa: PLC0415

    a, f, o, i, cact, c_prev, c, h_prev, RW, pF, pI, pO = residuals
    dh, dc = grads
    _, dact = _ACT[act_name]
    _, dgate = _ACT[gate_name]
    B, H = c_prev.shape
    dt = dh.dtype
    out_shape = (
        jax.ShapeDtypeStruct((B, 4 * H), dt),   # dzx
        jax.ShapeDtypeStruct((B, H), dt),       # dh_prev
        jax.ShapeDtypeStruct((B, H), dt),       # dc_prev
        jax.ShapeDtypeStruct((H, 4 * H), dt),   # dRW
        jax.ShapeDtypeStruct((H,), dt),         # dpF
        jax.ShapeDtypeStruct((H,), dt),         # dpI
        jax.ShapeDtypeStruct((H,), dt),         # dpO
    )
    kernel = functools.partial(_bwd_kernel, dact, dgate)
    return pl.pallas_call(
        kernel,
        out_shape=out_shape,
        interpret=_interpret(),
    )(a, f, o, i, cact, c_prev, c, h_prev, RW, pF, pI, pO, dh, dc)


fused_lstm_cell.defvjp(_cell_fwd, _cell_bwd)


# ---------------------------------------------------------------------------
# LRN
# ---------------------------------------------------------------------------


def _window_sum(x: jnp.ndarray, n: int) -> jnp.ndarray:
    """sum over channel window W(c) = [c - n//2, c + n - 1 - n//2]."""
    half = n // 2
    C = x.shape[-1]
    padded = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(half, half)])
    acc = jnp.zeros_like(x)
    for j in range(n):
        acc = acc + jax.lax.slice_in_dim(padded, j, j + C, axis=-1)
    return acc


def _window_sum_adjoint(x: jnp.ndarray, n: int) -> jnp.ndarray:
    """Adjoint of _window_sum: channel c receives from every j with
    c ∈ W(j), i.e. the window offsets flip sign. Identical to _window_sum
    for odd n (symmetric window); shifted by one for even n."""
    lo = n - 1 - n // 2  # pad so offset range becomes [-(n-1-half), half]
    hi = n // 2
    C = x.shape[-1]
    padded = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(lo, hi)])
    acc = jnp.zeros_like(x)
    for j in range(n):
        acc = acc + jax.lax.slice_in_dim(padded, j, j + C, axis=-1)
    return acc


def _lrn_fwd_kernel(k, n, alpha, beta, x_ref, y_ref, d_ref):
    x = x_ref[:]
    d = k + alpha * _window_sum(x * x, n)
    d_ref[:] = d
    y_ref[:] = x * d**-beta


def _lrn_bwd_kernel(k, n, alpha, beta, x_ref, d_ref, g_ref, dx_ref):
    x, d, g = x_ref[:], d_ref[:], g_ref[:]
    # dx_c = g_c d_c^-b - 2ab x_c * Σ_{j: c∈W(j)} g_j x_j d_j^{-b-1}
    dx_ref[:] = g * d**-beta - 2.0 * alpha * beta * x * _window_sum_adjoint(
        g * x * d ** (-beta - 1.0), n
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def fused_lrn(x, k: float = 2.0, n: int = 5, alpha: float = 1e-4,
              beta: float = 0.75):
    """Cross-channel LRN on the trailing axis, one fused VMEM pass."""
    y, _ = _lrn_fwd_impl(x, k, n, alpha, beta)
    return y


def _as2d(x):
    return x.reshape(-1, x.shape[-1])


# rows per grid step: keeps each VMEM block ≲1MB for typical channel counts
_LRN_TILE_ROWS = 1024


def _lrn_specs(rows: int, C: int, n_arrays: int):
    """Row-tiled grid so arbitrarily large activations never exceed VMEM.
    The channel (window) axis stays whole inside each block."""
    from jax.experimental import pallas as pl  # noqa: PLC0415

    tile = min(_LRN_TILE_ROWS, rows)
    grid = (pl.cdiv(rows, tile),)
    spec = pl.BlockSpec((tile, C), lambda i: (i, 0))
    return grid, [spec] * n_arrays, spec


def _lrn_fwd_impl(x, k, n, alpha, beta):
    from jax.experimental import pallas as pl  # noqa: PLC0415

    x2 = _as2d(x)
    grid, in_specs, out_spec = _lrn_specs(x2.shape[0], x2.shape[1], 1)
    kernel = functools.partial(_lrn_fwd_kernel, k, n, alpha, beta)
    y, d = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=(out_spec, out_spec),
        out_shape=(jax.ShapeDtypeStruct(x2.shape, x2.dtype),) * 2,
        interpret=_interpret(),
    )(x2)
    return y.reshape(x.shape), d


def _lrn_fwd(x, k, n, alpha, beta):
    y, d = _lrn_fwd_impl(x, k, n, alpha, beta)
    return y, (x, d)


def _lrn_bwd(k, n, alpha, beta, residuals, g):
    from jax.experimental import pallas as pl  # noqa: PLC0415

    x, d = residuals
    x2, g2 = _as2d(x), _as2d(g)
    grid, in_specs, out_spec = _lrn_specs(x2.shape[0], x2.shape[1], 3)
    kernel = functools.partial(_lrn_bwd_kernel, k, n, alpha, beta)
    dx = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_spec,
        out_shape=jax.ShapeDtypeStruct(x2.shape, x2.dtype),
        interpret=_interpret(),
    )(x2, d, g2)
    return (dx.reshape(x.shape),)


fused_lrn.defvjp(_lrn_fwd, _lrn_bwd)
