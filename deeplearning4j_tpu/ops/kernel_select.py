"""Cost-model-guided kernel auto-selection — the routing layer over the
Pallas helper tier.

The reference hand-routed every hot path to the fastest native kernel it had
(LSTMHelpers/CudnnConvolutionHelper discovery, SURVEY.md §2.3). This module
is the TPU-native generalization: every *fusable site* (LSTM sequence,
attention, LRN, softmax+cross-entropy, the optimizer update) registers its
kernel variants here with a per-variant static cost estimate, and at trace
time the PR 5 roofline (:mod:`..analysis.cost_model`) scores the variants
for the concrete shapes and picks the winner. Layers stop hardcoding
``DL4J_TPU_PALLAS`` dispatch logic; a future kernel becomes a drop-in win by
registering one more variant.

How a selection resolves, in precedence order:

1. **forced** — the call site's legacy knobs (``DL4J_TPU_PALLAS``,
   ``set_helpers_enabled``, an explicit ``attention_impl=``) still win, so
   every pre-existing escape hatch keeps its exact meaning.
2. **per-site override** — ``set_site_override("lstm_seq", "reference")`` or
   the env form ``DL4JTPU_KERNELS=lstm_seq=reference,attention=flash``: the
   pragma-style escape hatch for one site without touching the others.
3. **mode** — ``DL4JTPU_KERNELS=auto|reference|fused`` (default ``auto``).
   ``reference`` pins every site to the XLA path, ``fused`` to the preferred
   fused variant (still subject to hard feasibility: VMEM fit, supported
   activations), ``auto`` scores.
4. **auto scoring** — each feasible variant's (FLOPs, HBM bytes, fixed
   launch overhead) estimate becomes a predicted time
   ``max(flops/peak, bytes/bw) + overhead`` on the configured roofline
   (``DL4JTPU_PEAK_FLOPS``/``DL4JTPU_HBM_GBPS``); minimum wins, fused
   breaking ties. Fused Pallas variants only *compete* when the process runs
   on a TPU backend (or :func:`set_force_available` is on — tests/CI score
   them in interpret mode), mirroring the helper tier's TPU-auto default.

Byte estimates for the XLA reference variants use the cost model's deliberate
un-fused counting (a known upper bound — PR 5 limits note). The bench feeds
its measured ``predicted_vs_measured`` ratio back through
:func:`update_calibration`; the persisted factor (``KERNEL_CALIBRATION.json``)
discounts exactly those un-fused byte counts, so the model tightens round
over round instead of staying a static guess.

Every selection is observable end to end: a
``dl4jtpu_kernel_selected_total{site,variant}`` counter in the PR 2 registry,
a ``kernel_select`` event in the PR 4 flight recorder, and a ``kernels``
block in ``CompileManager.stats()`` / ``/api/ircost`` / the BENCH_* artifact.
Selections are cached per (site, shape key, config), so the same shapes
always resolve to the same variant and are logged exactly once — pinned by
tests/test_kernel_select.py.

Host-side only: nothing here touches device buffers; selection runs during
tracing (zero dispatches) and is pure shape algebra plus the roofline.
"""

from __future__ import annotations

import json
import os
import threading
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

__all__ = [
    "KERNELS_ENV",
    "CALIBRATION_PATH_ENV",
    "FLASH_MIN_SEQ_ENV",
    "Variant",
    "Site",
    "register_site",
    "select",
    "mode",
    "set_mode",
    "forced_mode",
    "set_site_override",
    "set_force_available",
    "force_available",
    "calibration_factor",
    "update_calibration",
    "selection_log",
    "stats",
    "reset",
]

# env knob: auto | reference | fused, optionally mixed with per-site
# overrides ("auto,lstm_seq=reference") — see docs/performance.md
KERNELS_ENV = "DL4JTPU_KERNELS"
# env knob: where the fusion-discount calibration JSON lives (default:
# KERNEL_CALIBRATION.json next to this package's repo root)
CALIBRATION_PATH_ENV = "DL4JTPU_KERNEL_CALIBRATION"
# env knob: sequence-length threshold below which auto mode keeps the XLA
# attention path even when flash is feasible (launch overhead + small [T,T]
# scores make the fused kernel a wash at short context)
FLASH_MIN_SEQ_ENV = "DL4JTPU_FLASH_MIN_SEQ"
DEFAULT_FLASH_MIN_SEQ = 256

_MODES = ("auto", "reference", "fused")

# calibration discount floor: never trust a measured ratio enough to claim
# XLA fuses >95% of the modeled traffic away
_CAL_MIN, _CAL_MAX = 0.05, 1.0


@dataclass(frozen=True)
class Variant:
    """One selectable kernel implementation at a site.

    ``available`` is HARD feasibility (VMEM fit, supported activations) —
    consulted for every resolution path including forced. ``auto_gate`` is
    soft policy (e.g. the flash min-seq threshold) consulted only by auto
    scoring. ``cost`` returns (flops, hbm_bytes, overhead_seconds) for the
    ctx; ``unfused_bytes`` marks estimates produced by the cost model's
    un-fused counting, which the measured calibration factor discounts.
    """

    name: str
    fused: bool
    cost: Callable[[dict], Tuple[float, float, float]]
    available: Callable[[dict], bool] = lambda ctx: True
    auto_gate: Callable[[dict], bool] = lambda ctx: True
    unfused_bytes: bool = False


@dataclass
class Site:
    name: str
    reference: str
    preferred_fused: str
    variants: Dict[str, Variant] = field(default_factory=dict)


_SITES: Dict[str, Site] = {}
_LOCK = threading.RLock()
_CACHE: Dict[Tuple, dict] = {}
_LOG: List[dict] = []
_FORCE_AVAILABLE = False
_MODE_OVERRIDE: Optional[str] = None
_SITE_OVERRIDES: Dict[str, str] = {}
_CAL_CACHE: Optional[Tuple[float, dict, float]] = None  # (mtime, data, factor)


def register_site(site: Site) -> None:
    with _LOCK:
        _SITES[site.name] = site


def _parse_env() -> Tuple[str, Dict[str, str]]:
    """``DL4JTPU_KERNELS`` grammar: comma-separated tokens; a bare token is
    the global mode, ``site=variant`` a per-site override."""
    raw = os.environ.get(KERNELS_ENV, "")
    env_mode = "auto"
    overrides: Dict[str, str] = {}
    for tok in raw.split(","):
        tok = tok.strip()
        if not tok:
            continue
        if "=" in tok:
            site, _, variant = tok.partition("=")
            overrides[site.strip()] = variant.strip()
        elif tok in _MODES:
            env_mode = tok
    return env_mode, overrides


def mode() -> str:
    """The effective global mode (programmatic override > env > auto)."""
    if _MODE_OVERRIDE is not None:
        return _MODE_OVERRIDE
    return _parse_env()[0]


def set_mode(m: Optional[str]) -> None:
    """Programmatic mode override (None restores env/auto resolution)."""
    global _MODE_OVERRIDE
    if m is not None and m not in _MODES:
        raise ValueError(f"mode must be one of {_MODES}, got {m!r}")
    _MODE_OVERRIDE = m


@contextmanager
def forced_mode(m: str):
    """Scoped :func:`set_mode` — the bench's auto-vs-reference A/B uses it."""
    prev = _MODE_OVERRIDE
    set_mode(m)
    try:
        yield
    finally:
        set_mode(prev)


def set_site_override(site: str, variant: Optional[str]) -> None:
    """Pin one site to one variant (None clears) — the per-site pragma
    escape hatch; env-form overrides ride ``DL4JTPU_KERNELS=site=variant``."""
    with _LOCK:
        if variant is None:
            _SITE_OVERRIDES.pop(site, None)
        else:
            _SITE_OVERRIDES[site] = variant


def _site_override(site: str) -> Optional[str]:
    ov = _SITE_OVERRIDES.get(site)
    if ov is not None:
        return ov
    return _parse_env()[1].get(site)


def set_force_available(flag: bool) -> None:
    """Let fused variants compete in auto scoring off-TPU (interpret mode).
    CI's kernel-selection self-scan and the parity tests run under this —
    production auto mode only scores fused kernels on a real TPU backend."""
    global _FORCE_AVAILABLE
    _FORCE_AVAILABLE = bool(flag)


def force_available() -> bool:
    return _FORCE_AVAILABLE


def _fused_competes() -> bool:
    if _FORCE_AVAILABLE:
        return True
    try:
        import jax  # noqa: PLC0415 - keep module import light

        # "axon" is the tunnel-attached TPU backend this harness trains on —
        # Pallas lowers there exactly as on a directly-attached chip
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def flash_min_seq() -> int:
    try:
        return int(os.environ.get(FLASH_MIN_SEQ_ENV, DEFAULT_FLASH_MIN_SEQ))
    except ValueError:
        return DEFAULT_FLASH_MIN_SEQ


# ------------------------------------------------------------- calibration
def _calibration_path() -> str:
    explicit = os.environ.get(CALIBRATION_PATH_ENV)
    if explicit:
        return explicit
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(pkg_root, "KERNEL_CALIBRATION.json")


def _load_calibration() -> Tuple[dict, float]:
    """(raw data, discount factor). Cached by file mtime; a missing or
    malformed file means factor 1.0 (trust the un-fused counts as-is)."""
    global _CAL_CACHE
    path = _calibration_path()
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return {}, 1.0
    with _LOCK:
        if _CAL_CACHE is not None and _CAL_CACHE[0] == mtime:
            return _CAL_CACHE[1], _CAL_CACHE[2]
    try:
        with open(path) as f:
            data = json.load(f)
        if not isinstance(data, dict):
            data = {}
    except (OSError, json.JSONDecodeError):
        data = {}
    ratios = [v for k, v in data.items()
              if isinstance(v, (int, float)) and v > 0]
    if ratios:
        # geometric mean of predicted/measured across modes; >1 means the
        # un-fused byte counts over-predicted, so discount by its inverse
        import math

        g = math.exp(sum(math.log(r) for r in ratios) / len(ratios))
        factor = min(_CAL_MAX, max(_CAL_MIN, 1.0 / g)) if g > 1.0 else 1.0
    else:
        factor = 1.0
    with _LOCK:
        _CAL_CACHE = (mtime, data, factor)
    return data, factor


def calibration_factor() -> float:
    """Multiplier applied to un-fused byte estimates during auto scoring."""
    return _load_calibration()[1]


def calibration_snapshot() -> Tuple[str, dict]:
    """(path, raw ratios) of the active calibration file — what warm-boot
    bundles embed so a fresh fleet worker scores kernels with the same
    measured discounts as the process that built the bundle."""
    return _calibration_path(), dict(_load_calibration()[0])


def site_overrides() -> dict:
    """The pinned site→variant map (both set_site_override and
    ``DL4JTPU_KERNELS`` env form), for warm-boot bundle capture."""
    with _LOCK:
        pinned = dict(_SITE_OVERRIDES)
    env_form = _parse_env()[1]
    return {**env_form, **pinned}


def update_calibration(key: str, predicted_vs_measured: float) -> bool:
    """Persist one bench mode's predicted/measured step-time ratio — the
    feedback half of the calibration loop (bench.py calls this from its
    ``static_cost`` block). Returns True when written."""
    try:
        ratio = float(predicted_vs_measured)
    except (TypeError, ValueError):
        return False
    if not (ratio > 0):
        return False
    path = _calibration_path()
    data, _ = _load_calibration()
    data = dict(data)
    data[str(key)] = round(ratio, 6)
    try:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        return False
    global _CAL_CACHE
    with _LOCK:
        _CAL_CACHE = None  # next read re-derives the factor
    return True


# --------------------------------------------------------------- selection
def _predicted_seconds(v: Variant, ctx: dict, cal: float) -> float:
    from ..analysis.cost_model import roofline_params  # noqa: PLC0415

    flops, nbytes, overhead = v.cost(ctx)
    if v.unfused_bytes:
        nbytes *= cal
    rl = roofline_params()
    compute_s = flops / rl["peak_flops"] if rl["peak_flops"] else 0.0
    memory_s = nbytes / (rl["hbm_gbps"] * 1e9) if rl["hbm_gbps"] else 0.0
    return max(compute_s, memory_s) + overhead


def _observe(record: dict) -> None:
    """Counter + flight-recorder event for one NEW (site, key) selection.
    Observability must never break the traced path that asked."""
    try:
        from ..telemetry import get_registry  # noqa: PLC0415

        get_registry().counter(
            "dl4jtpu_kernel_selected_total",
            "kernel-variant selections by site (one per distinct shape key)",
            labelnames=("site", "variant"),
        ).labels(site=record["site"], variant=record["variant"]).inc()
    except Exception:
        pass
    try:
        from ..telemetry.flight_recorder import get_flight_recorder  # noqa: PLC0415

        get_flight_recorder().record(
            "kernel_select", site=record["site"], variant=record["variant"],
            reason=record["reason"], ctx=dict(record["ctx"]),
            predicted_s=record.get("predicted_s"))
    except Exception:
        pass


def select(site_name: str, ctx: dict, forced: Optional[str] = None) -> str:
    """Resolve the variant for ``site_name`` at the concrete ``ctx`` shapes.

    ``forced`` carries a call site's legacy knob (highest precedence); it is
    still subject to the variant's hard feasibility check and falls back to
    the reference variant when infeasible. Resolutions are cached per
    (site, ctx, config) — deterministic, and logged/counted exactly once.
    """
    site = _SITES[site_name]
    m = mode()
    ov = _site_override(site_name)
    cal = calibration_factor()
    key = (site_name, tuple(sorted(ctx.items())), forced, m, ov,
           _FORCE_AVAILABLE, round(cal, 4))
    with _LOCK:
        hit = _CACHE.get(key)
        if hit is not None:
            return hit["variant"]

    def feasible(name: Optional[str]) -> Optional[str]:
        v = site.variants.get(name or "")
        return v.name if v is not None and v.available(ctx) else None

    choice: Optional[str] = None
    reason = "auto"
    predicted: Optional[dict] = None
    if forced is not None:
        choice = feasible(forced)
        reason = "forced"
    if choice is None and ov is not None:
        choice = feasible(ov)
        if choice is not None:
            reason = "override"
    if choice is None and m == "reference":
        choice, reason = site.reference, "mode"
    if choice is None and m == "fused":
        choice = feasible(site.preferred_fused) or next(
            (feasible(n) for n, v in site.variants.items()
             if v.fused and feasible(n)), None)
        reason = "mode"
    if choice is None:
        fused_ok = _fused_competes()
        candidates = [
            v for v in site.variants.values()
            if v.available(ctx) and v.auto_gate(ctx)
            and (fused_ok or not v.fused)
        ]
        if not candidates:
            choice, reason = site.reference, "fallback"
        else:
            predicted = {v.name: _predicted_seconds(v, ctx, cal)
                         for v in candidates}
            # minimum predicted time; fused breaks ties (it is the variant
            # whose byte estimate we actually trust)
            choice = min(
                candidates,
                key=lambda v: (predicted[v.name], 0 if v.fused else 1),
            ).name
            reason = "auto"
    if choice not in site.variants:
        choice = site.reference

    record = {"site": site_name, "variant": choice, "reason": reason,
              "ctx": dict(ctx), "mode": m}
    if predicted is not None:
        record["predicted_s"] = {k: float(f"{v:.3e}")
                                 for k, v in predicted.items()}
    with _LOCK:
        # racing first-selection: keep the winner, log once
        hit = _CACHE.get(key)
        if hit is not None:
            return hit["variant"]
        _CACHE[key] = record
        _LOG.append(record)
    _observe(record)
    return choice


# ------------------------------------------------------------- introspection
def selection_log() -> List[dict]:
    with _LOCK:
        return list(_LOG)


def stats(last: int = 32) -> dict:
    """Snapshot for ``cm.stats()['kernels']`` / ``/api/ircost`` / bench."""
    with _LOCK:
        log = list(_LOG)
    by_site: Dict[str, Dict[str, int]] = {}
    for rec in log:
        row = by_site.setdefault(rec["site"], {})
        row[rec["variant"]] = row.get(rec["variant"], 0) + 1
    data, factor = _load_calibration()
    return {
        "mode": mode(),
        "force_available": _FORCE_AVAILABLE,
        "sites": sorted(_SITES),
        "selections_total": len(log),
        "by_site": by_site,
        "recent": log[-last:],
        "calibration": {"factor": round(factor, 4), "entries": len(data),
                        "path": _calibration_path()},
    }


def reset() -> None:
    """Test hook: clear cached selections, the log, and every override."""
    global _FORCE_AVAILABLE, _MODE_OVERRIDE, _CAL_CACHE
    with _LOCK:
        _CACHE.clear()
        _LOG.clear()
        _SITE_OVERRIDES.clear()
        _FORCE_AVAILABLE = False
        _MODE_OVERRIDE = None
        _CAL_CACHE = None


# ---------------------------------------------------------------------------
# Site registrations. Cost closed forms are deliberately simple RANKERS, not
# simulators (same philosophy as the PR 5 cost model): FLOPs are identical
# across variants of a site, byte counts model the HBM streams each variant
# actually moves (un-fused counting for the XLA reference paths — flagged so
# calibration discounts them), and overhead models fixed kernel-launch cost.
# tests/test_kernel_select.py pins the rankings the ISSUE demands.
# ---------------------------------------------------------------------------

_LAUNCH_S = 5e-6  # one pallas_call dispatch


def _lstm_flops(ctx) -> float:
    T, B, H = ctx["T"], ctx["B"], ctx["H"]
    # fwd recurrent matmul + bwd dzx@RW.T + dRW accumulation, plus gate math
    return 24.0 * T * B * H * H + 60.0 * T * B * H


def _lstm_seqfused_cost(ctx):
    T, B, H, itemsize = ctx["T"], ctx["B"], ctx["H"], ctx["itemsize"]
    # fwd: zx in + y out + 5 residual streams; bwd: dy + 5 residuals +
    # shifted c/h re-reads + dzx out; RW resident once per pass
    nbytes = itemsize * (2.0 * T * B * 4 * H + 14.0 * T * B * H
                         + 3.0 * H * 4 * H)
    return _lstm_flops(ctx), nbytes, 2 * _LAUNCH_S


def _lstm_fusedcell_cost(ctx):
    T, B, H, itemsize = ctx["T"], ctx["B"], ctx["H"], ctx["itemsize"]
    # per-step pallas_call: 7 residual arrays spill to HBM fwd AND re-load
    # bwd (the measured reason XLA's scan beats it — ops/__init__ docstring)
    nbytes = itemsize * T * (4.0 * B * 4 * H + 28.0 * B * H
                             + 4.0 * H * 4 * H)
    return _lstm_flops(ctx), nbytes, 2 * ctx["T"] * _LAUNCH_S


def _lstm_reference_cost(ctx):
    T, B, H, itemsize = ctx["T"], ctx["B"], ctx["H"], ctx["itemsize"]
    # un-fused counting of the scan body: every gate/cell intermediate is a
    # materialized [B,H] (or [B,4H]) round trip, fwd + ~2x bwd
    nbytes = itemsize * T * 66.0 * B * H
    return _lstm_flops(ctx), nbytes, 0.0


def _seq_fits_ctx(ctx) -> bool:
    from .pallas_kernels import _seq_fits  # noqa: PLC0415

    return bool(ctx["acts_ok"]) and _seq_fits(ctx["B"], ctx["H"],
                                              ctx["itemsize"])


def _cell_fits_ctx(ctx) -> bool:
    from . import _cell_fits  # noqa: PLC0415

    return bool(ctx["acts_ok"]) and _cell_fits(ctx["B"], ctx["H"],
                                               ctx["itemsize"])


register_site(Site(
    name="lstm_seq",
    reference="reference",
    preferred_fused="seqfused",
    variants={
        "seqfused": Variant("seqfused", fused=True,
                            cost=_lstm_seqfused_cost,
                            available=_seq_fits_ctx),
        "fusedcell": Variant("fusedcell", fused=True,
                             cost=_lstm_fusedcell_cost,
                             available=_cell_fits_ctx),
        "reference": Variant("reference", fused=False,
                             cost=_lstm_reference_cost, unfused_bytes=True),
    },
))


def _attn_dims(ctx):
    return ctx["B"] * ctx["heads"], ctx["T"], ctx["D"], ctx["itemsize"]


def _attn_flash_cost(ctx):
    bh, t, d, itemsize = _attn_dims(ctx)
    # online-softmax recompute in the two backward passes costs extra FLOPs
    # but HBM traffic stays O(T*D) streams
    flops = 14.0 * bh * t * t * d
    nbytes = itemsize * 12.0 * bh * t * d + 8.0 * bh * t
    return flops, nbytes, 3 * _LAUNCH_S


def _attn_xla_cost(ctx):
    bh, t, d, itemsize = _attn_dims(ctx)
    flops = 10.0 * bh * t * t * d
    # the [T,T] score/prob/dprob/dscore tensors materialize in HBM
    nbytes = itemsize * (8.0 * bh * t * t + 8.0 * bh * t * d)
    return flops, nbytes, 0.0


def _flash_auto_gate(ctx) -> bool:
    from .flash_attention import _KV_VMEM_BUDGET_BYTES  # noqa: PLC0415

    t, d, itemsize = ctx["T"], ctx["D"], ctx["itemsize"]
    return (ctx["T"] >= flash_min_seq()
            and 2 * t * d * itemsize <= _KV_VMEM_BUDGET_BYTES)


register_site(Site(
    name="attention",
    reference="xla",
    preferred_fused="flash",
    variants={
        # flash is always *feasible* (it falls back internally past the KV
        # VMEM budget); the threshold is auto-mode policy only, so an
        # explicit attention_impl="flash" keeps meaning flash
        "flash": Variant("flash", fused=True, cost=_attn_flash_cost,
                         auto_gate=_flash_auto_gate),
        "xla": Variant("xla", fused=False, cost=_attn_xla_cost,
                       unfused_bytes=True),
    },
))


def _lrn_fused_cost(ctx):
    rows, C, n, itemsize = ctx["rows"], ctx["C"], ctx["n"], ctx["itemsize"]
    flops = (2.0 * n + 8.0) * rows * C
    # fwd: x in, y+d out; bwd: x, d, g in, dx out
    return flops, itemsize * 7.0 * rows * C, 2 * _LAUNCH_S


def _lrn_reference_cost(ctx):
    rows, C, n, itemsize = ctx["rows"], ctx["C"], ctx["n"], ctx["itemsize"]
    flops = (2.0 * n + 8.0) * rows * C
    # un-fused window sum: n shifted slices materialize fwd and again in the
    # adjoint, plus the pow/mul chain
    return flops, itemsize * (4.0 * n + 6.0) * rows * C, 0.0


register_site(Site(
    name="lrn",
    reference="reference",
    preferred_fused="fused",
    variants={
        "fused": Variant("fused", fused=True, cost=_lrn_fused_cost),
        "reference": Variant("reference", fused=False,
                             cost=_lrn_reference_cost, unfused_bytes=True),
    },
))


def _sxent_fused_cost(ctx):
    N, C, itemsize = ctx["N"], ctx["C"], ctx["itemsize"]
    flops = 10.0 * N * C
    # fwd: preout+labels in, per-row loss out; bwd: preout+labels+g in,
    # dpre+dlabels out
    return flops, itemsize * 7.0 * N * C, 2 * _LAUNCH_S


def _sxent_reference_cost(ctx):
    N, C, itemsize = ctx["N"], ctx["C"], ctx["itemsize"]
    flops = 10.0 * N * C
    # un-fused: max/exp/sum/log/mul materialize between HBM round trips,
    # fwd + bwd softmax recompute
    return flops, itemsize * 12.0 * N * C, 0.0


register_site(Site(
    name="softmax_xent",
    reference="reference",
    preferred_fused="fused",
    variants={
        "fused": Variant("fused", fused=True, cost=_sxent_fused_cost),
        "reference": Variant("reference", fused=False,
                             cost=_sxent_reference_cost, unfused_bytes=True),
    },
))


def _opt_fused_cost(ctx):
    n, itemsize = ctx["n_elems"], ctx["itemsize"]
    # read g/m/v, write u/m/v in one pass per leaf
    return 12.0 * n, itemsize * 7.0 * n, ctx.get("n_leaves", 1) * _LAUNCH_S


def _opt_reference_cost(ctx):
    n, itemsize = ctx["n_elems"], ctx["itemsize"]
    # un-fused optax chain: moment updates, bias corrections, sqrt, scale —
    # each a materialized tree-wide intermediate
    return 12.0 * n, itemsize * 14.0 * n, 0.0


register_site(Site(
    name="optimizer",
    reference="reference",
    preferred_fused="fused",
    variants={
        "fused": Variant("fused", fused=True, cost=_opt_fused_cost,
                         available=lambda ctx: ctx.get("updater") == "adam"),
        "reference": Variant("reference", fused=False,
                             cost=_opt_reference_cost, unfused_bytes=True),
    },
))
