"""Flash attention as a Pallas TPU kernel — blockwise online-softmax with
O(T) memory and a fused custom-VJP backward.

The architectural slot: the reference's cuDNN tier existed to win the hot-op
fight (SURVEY.md §2.3); on TPU the one attention shape XLA does NOT handle
optimally is long-sequence softmax attention, whose naive form materializes
the [T, T] score matrix in HBM. This kernel computes attention in [block_q x
block_k] VMEM tiles with the online-softmax recurrence (running row max m and
denominator l), so HBM traffic is O(T·D) instead of O(T^2):

    m'  = max(m, rowmax(s))
    acc = acc * e^(m - m') + e^(s - m') @ v
    l   = l  * e^(m - m') + rowsum(e^(s - m'))

The backward follows the standard flash recipe: save only (out, lse); rebuild
p = e^(s - lse) per tile and accumulate dq over k-tiles (one kernel) and
dk/dv over q-tiles (a second kernel).

VMEM note: scores/probabilities are tiled, but each grid program stages the
full per-head K/V [T, D] strip in VMEM (the k-loop runs inside the kernel,
not the grid), so per-program VMEM is O(T·D). A budget guard in
:func:`flash_attention` falls back to the XLA path beyond ~8 MB of K+V per
head — beyond that length, ring attention (sequence parallelism) is the
intended tool anyway. Grid-tiled K/V streaming is the upgrade path.

Used by SelfAttentionLayer via ``attention_impl="flash"``; interpret mode
(CPU) runs identical code for tests. Causal masking and key padding masks are
applied inside the tiles. Inputs [B, H, T, D], same contract as
``parallel.ring_attention.attention`` (which remains the XLA reference path).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .pallas_kernels import _interpret

_NEG_INF = -1e30
_KV_VMEM_BUDGET_BYTES = 8 * 1024 * 1024


def _fwd_kernel(block_k: int, causal: bool, scale: float,
                q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref):
    """One q-tile vs all k-tiles. Refs: q [1,Bq,D]; k/v [1,T,D]; mask
    [1,1,T]; out o [1,Bq,D], lse [1,1,Bq]. (Mask/lse ride a unit middle axis:
    TPU lowering requires each block's last two dims to divide (8, 128) or
    equal the array dims — a [1, T] block on a [BH, T] array violates the
    sublane rule, a [1, 1, T] block on [BH, 1, T] does not.)"""
    q = q_ref[0].astype(jnp.float32)  # [Bq, D]
    bq, d = q.shape
    t = k_ref.shape[1]
    qi0 = pl.program_id(1) * bq

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        s = (q @ k.T) * scale  # [Bq, Bk]
        kmask = mask_ref[0, 0, pl.dslice(j * block_k, block_k)]  # [Bk]
        s = jnp.where(kmask[None, :] > 0, s, _NEG_INF)
        if causal:
            rows = qi0 + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        # Rows with NO valid key yet have m_new == _NEG_INF; exp(s - m_new)
        # would then be exp(0) = 1 at every masked position (the reference
        # guards this with m_safe + explicit zeroing — ring_attention.py).
        # Subtracting 0 instead keeps exp(-1e30) == 0 for those rows.
        m_safe = jnp.where(m_new <= _NEG_INF / 2, 0.0, m_new)
        alpha = jnp.exp(jnp.where(m <= _NEG_INF / 2, m_safe, m) - m_safe)
        p = jnp.exp(s - m_safe[:, None])
        acc = acc * alpha[:, None] + p @ v
        l = l * alpha + p.sum(axis=-1)
        return acc, m_new, l

    nk = t // block_k
    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, nk, body, (acc0, m0, l0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # Fully-masked rows (l == 0): out = 0, and lse = 0 (finite) so the
    # backward's exp(s - lse) = exp(-1e30) = 0 instead of exp(0) = 1.
    m_fin = jnp.where(m <= _NEG_INF / 2, 0.0, m)
    lse = jnp.where(l > 0, m_fin + jnp.log(l_safe), 0.0)
    lse_ref[0, 0] = lse.astype(lse_ref.dtype)


def _dq_kernel(block_k: int, causal: bool, scale: float,
               q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
               dq_ref):
    """dq for one q-tile: loop over k-tiles (flash backward, dq pass)."""
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0].astype(jnp.float32)
    delta = delta_ref[0, 0].astype(jnp.float32)  # rowsum(do * o)
    bq, d = q.shape
    t = k_ref.shape[1]
    qi0 = pl.program_id(1) * bq

    def body(j, dq):
        k = k_ref[0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        s = (q @ k.T) * scale
        kmask = mask_ref[0, 0, pl.dslice(j * block_k, block_k)]
        s = jnp.where(kmask[None, :] > 0, s, _NEG_INF)
        if causal:
            rows = qi0 + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])  # [Bq, Bk]
        dp = do @ v.T  # [Bq, Bk]
        ds = p * (dp - delta[:, None])
        return dq + (ds @ k) * scale

    dq = jax.lax.fori_loop(0, t // block_k, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(block_q: int, causal: bool, scale: float,
                q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref):
    """dk/dv for one k-tile: loop over q-tiles (flash backward, dk/dv pass).
    Refs: k/v tile [1,Bk,D]; q/do [1,T,D]; lse/delta [1,1,T]; mask tile
    [1,1,Bk] (unit middle axis — see _fwd_kernel)."""
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    bk, d = k.shape
    tq = q_ref.shape[1]
    kj0 = pl.program_id(1) * bk
    kmask = mask_ref[0, 0]  # [Bk]

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.dslice(i * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.dslice(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.dslice(i * block_q, block_q)].astype(jnp.float32)
        delta = delta_ref[0, 0, pl.dslice(i * block_q, block_q)].astype(jnp.float32)
        s = (q @ k.T) * scale  # [Bq, Bk]
        s = jnp.where(kmask[None, :] > 0, s, _NEG_INF)
        if causal:
            rows = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            cols = kj0 + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dv = dv + p.T @ do
        dp = do @ v.T
        ds = p * (dp - delta[:, None])
        dk = dk + (ds.T @ q) * scale
        return dk, dv

    zero = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, tq // block_q, body, (zero, zero))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _pad_to(x, axis: int, mult: int):
    t = x.shape[axis]
    pad = (-t) % mult
    if pad == 0:
        return x, t
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths), t


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_core(q, k, v, mask, causal, scale, block_q, block_k):
    out, _ = _flash_fwd(q, k, v, mask, causal, scale, block_q, block_k)
    return out


def _flash_call(q, k, v, mask, causal, scale, block_q, block_k):
    bh, t, d = q.shape
    grid = (bh, t // block_q)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, block_k, causal, scale),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, t), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, mask)


def _flash_fwd(q, k, v, mask, causal, scale, block_q, block_k):
    out, lse = _flash_call(q, k, v, mask, causal, scale, block_q, block_k)
    return out, (q, k, v, mask, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, residuals, g):
    q, k, v, mask, out, lse = residuals
    bh, t, d = q.shape
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)[:, None, :]
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, block_k, causal, scale),
        grid=(bh, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        interpret=_interpret(),
    )(q, k, v, mask, g, lse, delta)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, block_q, causal, scale),
        grid=(bh, t // block_k),
        in_specs=[
            pl.BlockSpec((1, t, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, 1, block_k), lambda b, j: (b, 0, j)),
            pl.BlockSpec((1, t, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda b, j: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), k.dtype),
            jax.ShapeDtypeStruct((bh, t, d), v.dtype),
        ],
        interpret=_interpret(),
    )(q, k, v, mask, g, lse, delta)
    return dq, dk, dv, None


_flash_core.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None, key_mask=None,
                    block_q: int = 128, block_k: int = 128):
    """Blockwise flash attention. q/k/v: [B, H, T, D]; key_mask: [B, T]
    (1 = real key). Same contract as ``ring_attention.attention``.

    T is padded internally to a block multiple (padded keys masked out,
    padded query rows sliced off), so any sequence length works; block sizes
    shrink automatically for short sequences.
    """
    b, h, t, d = q.shape
    scale = float(scale if scale is not None else d ** -0.5)
    # K+V strip per grid program must fit VMEM (see module docstring);
    # past the budget the XLA reference path is used instead — same
    # measured-default fallback philosophy as ops/__init__'s LSTM helper.
    if 2 * t * d * q.dtype.itemsize > _KV_VMEM_BUDGET_BYTES:
        from ..parallel.ring_attention import attention as _xla_attention

        return _xla_attention(q, k, v, causal=causal, scale=scale,
                              key_mask=key_mask)
    block_q = min(block_q, max(t, 1))
    block_k = min(block_k, max(t, 1))

    qf = q.reshape(b * h, t, d)
    kf = k.reshape(b * h, t, d)
    vf = v.reshape(b * h, t, d)
    if key_mask is None:
        mask = jnp.ones((b, t), jnp.float32)
    else:
        mask = key_mask.astype(jnp.float32)
    maskf = jnp.repeat(mask[:, None, :], h, axis=1).reshape(b * h, 1, t)

    # one pad straight to the lcm: q must reach a block_k multiple for the
    # dkv q-loop and k a block_q multiple for the dq k-loop; zero mask
    # padding == masked out
    import math

    lcm = math.lcm(block_q, block_k)
    qf, t_real = _pad_to(qf, 1, lcm)
    kf, _ = _pad_to(kf, 1, lcm)
    vf, _ = _pad_to(vf, 1, lcm)
    maskf, _ = _pad_to(maskf, 2, lcm)

    out = _flash_core(qf, kf, vf, maskf, causal, scale, block_q, block_k)
    return out[:, :t_real, :].reshape(b, h, t_real, d)
