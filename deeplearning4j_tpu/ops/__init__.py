"""Op dispatch: Pallas helpers on TPU, pure-XLA math elsewhere.

Mirrors the reference's helper discovery (ConvolutionLayer.java:69-79 loads
CudnnConvolutionHelper reflectively and falls back to builtin math): here the
"helper" is a Pallas kernel, enabled when running on TPU (or forced via the
``DL4J_TPU_PALLAS`` env var: "1" forces on — interpret mode off-TPU, for
testing — and "0" forces off).
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from .pallas_kernels import (
    _ACT,
    _cell_math,
    _window_sum,
    fused_lrn,
    fused_lstm_cell,
    supported_lstm_activations,
)
from .flash_attention import flash_attention

_FORCED: Optional[bool] = None  # set_helpers_enabled override

# keep every fused-cell buffer comfortably inside ~16MB VMEM
_CELL_VMEM_BUDGET_BYTES = 4 * 1024 * 1024


def set_helpers_enabled(enabled: Optional[bool]) -> None:
    """Force pallas helpers on/off (None = auto). Auto = TPU backend only."""
    global _FORCED
    _FORCED = enabled


def helpers_enabled() -> bool:
    if _FORCED is not None:
        return _FORCED
    env = os.environ.get("DL4J_TPU_PALLAS")
    if env == "0":
        return False
    if env == "1":
        return True
    return jax.default_backend() == "tpu"


def _cell_fits(B: int, H: int, itemsize: int) -> bool:
    # zx[B,4H] + 7×[B,H] + RW[H,4H] residuals/outputs
    return (B * 4 * H + 7 * B * H + H * 4 * H) * itemsize < _CELL_VMEM_BUDGET_BYTES


def lstm_helper_enabled() -> bool:
    """The fused LSTM cell is opt-in only: measured on v5e, XLA's fused
    scan-body beats the per-step pallas_call at every VMEM-fitting shape
    (e.g. B=128,H=256: 3.3ms vs 4.5ms/grad-step), because the custom VJP
    must spill 7 residual arrays per step that XLA instead rematerializes.
    Kept for parity with the reference's helper tier and as the base for
    future multi-step fusion; force with set_helpers_enabled(True) or
    DL4J_TPU_PALLAS=1."""
    if _FORCED is not None:
        return _FORCED
    return os.environ.get("DL4J_TPU_PALLAS") == "1"


def lstm_sequence_enabled() -> bool:
    """The time-fused whole-sequence kernel (fused_lstm_sequence): grid over
    T with h/c carried in VMEM scratch — the multi-step fusion the cell
    docstring anticipates.

    DEFAULT ON for TPU (measured, v5e char-RNN bench B=64 H=512 T=256:
    3.10M chars/sec median seq-fused vs 1,489,072 scan — 2.1x; probe steps
    charrnn/charrnn_seqfused, round 5). ``DL4J_TPU_PALLAS=seq`` still
    forces it on off-TPU (interpret mode, tests); "0"/"1" select the scan
    or per-step-cell paths instead; unset means TPU-auto like
    helpers_enabled. ``set_helpers_enabled(False)`` disables it like every
    other Pallas helper — the programmatic kill-switch covers the
    default-on kernel too. Shapes the VMEM guard rejects fall back to the
    scan path at call sites (sequence_fits)."""
    if _FORCED is not None:
        return _FORCED
    env = os.environ.get("DL4J_TPU_PALLAS")
    if env == "seq":
        return True
    if env in ("0", "1"):  # explicit other-path selection
        return False
    return jax.default_backend() == "tpu"


def sequence_fits(B: int, H: int, itemsize: int) -> bool:
    from .pallas_kernels import _seq_fits  # noqa: PLC0415

    return _seq_fits(B, H, itemsize)


def lstm_cell(zx, h_prev, c_prev, RW, pF, pI, pO,
              act_name: str = "tanh", gate_name: str = "sigmoid"):
    """One LSTM step (h, c). Pallas-fused when available, XLA otherwise."""
    B, H = c_prev.shape
    if (
        lstm_helper_enabled()
        and supported_lstm_activations(act_name, gate_name)
        and _cell_fits(B, H, zx.dtype.itemsize)
    ):
        return fused_lstm_cell(zx, h_prev, c_prev, RW, pF, pI, pO,
                               act_name, gate_name)
    act = _ACT.get(act_name)
    gate = _ACT.get(gate_name)
    if act is not None and gate is not None:
        h, c, *_ = _cell_math(zx, h_prev, c_prev, RW, pF, pI, pO,
                              act[0], gate[0])
        return h, c
    raise ValueError(f"Unknown LSTM activations ({act_name}, {gate_name})")


def lrn(x, k: float = 2.0, n: int = 5, alpha: float = 1e-4, beta: float = 0.75):
    """Cross-channel LRN over the trailing axis."""
    if helpers_enabled():
        return fused_lrn(x, k, n, alpha, beta)
    d = k + alpha * _window_sum(x * x, n)
    return x * d**-beta


__all__ = [
    "flash_attention",
    "fused_lrn",
    "fused_lstm_cell",
    "helpers_enabled",
    "lrn",
    "lstm_cell",
    "lstm_helper_enabled",
    "set_helpers_enabled",
    "supported_lstm_activations",
]
