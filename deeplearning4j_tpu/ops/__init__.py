"""Op dispatch: Pallas helpers on TPU, pure-XLA math elsewhere.

Mirrors the reference's helper discovery (ConvolutionLayer.java:69-79 loads
CudnnConvolutionHelper reflectively and falls back to builtin math): here the
"helper" is a Pallas kernel, enabled when running on TPU (or forced via the
``DL4J_TPU_PALLAS`` env var: "1" forces on — interpret mode off-TPU, for
testing — and "0" forces off).

Since the kernel-selection rework, *which* implementation runs at each
fusable site is decided by :mod:`.kernel_select`: the ``select_*_variant``
wrappers below translate this module's legacy knobs (``DL4J_TPU_PALLAS``,
``set_helpers_enabled``) into a ``forced`` choice — preserving their exact
historical meaning — and otherwise let the PR 5 roofline score the variants
for the concrete shapes (``DL4JTPU_KERNELS=auto|reference|fused``).
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from . import kernel_select
from .pallas_kernels import (
    _ACT,
    _cell_math,
    _window_sum,
    fused_adam_update,
    fused_lrn,
    fused_lstm_cell,
    fused_softmax_xent,
    supported_lstm_activations,
)
from .flash_attention import flash_attention

_FORCED: Optional[bool] = None  # set_helpers_enabled override

# keep every fused-cell buffer comfortably inside ~16MB VMEM
_CELL_VMEM_BUDGET_BYTES = 4 * 1024 * 1024


def set_helpers_enabled(enabled: Optional[bool]) -> None:
    """Force pallas helpers on/off (None = auto). Auto = TPU backend only."""
    global _FORCED
    _FORCED = enabled


def helpers_enabled() -> bool:
    if _FORCED is not None:
        return _FORCED
    env = os.environ.get("DL4J_TPU_PALLAS")
    if env == "0":
        return False
    if env == "1":
        return True
    return jax.default_backend() == "tpu"


def _cell_fits(B: int, H: int, itemsize: int) -> bool:
    # zx[B,4H] + 7×[B,H] + RW[H,4H] residuals/outputs
    return (B * 4 * H + 7 * B * H + H * 4 * H) * itemsize < _CELL_VMEM_BUDGET_BYTES


def lstm_helper_enabled() -> bool:
    """The fused LSTM cell is opt-in only: measured on v5e, XLA's fused
    scan-body beats the per-step pallas_call at every VMEM-fitting shape
    (e.g. B=128,H=256: 3.3ms vs 4.5ms/grad-step), because the custom VJP
    must spill 7 residual arrays per step that XLA instead rematerializes.
    Kept for parity with the reference's helper tier and as the base for
    future multi-step fusion; force with set_helpers_enabled(True) or
    DL4J_TPU_PALLAS=1."""
    if _FORCED is not None:
        return _FORCED
    return os.environ.get("DL4J_TPU_PALLAS") == "1"


def lstm_sequence_enabled() -> bool:
    """The time-fused whole-sequence kernel (fused_lstm_sequence): grid over
    T with h/c carried in VMEM scratch — the multi-step fusion the cell
    docstring anticipates.

    DEFAULT ON for TPU (measured, v5e char-RNN bench B=64 H=512 T=256:
    3.10M chars/sec median seq-fused vs 1,489,072 scan — 2.1x; probe steps
    charrnn/charrnn_seqfused, round 5). ``DL4J_TPU_PALLAS=seq`` still
    forces it on off-TPU (interpret mode, tests); "0"/"1" select the scan
    or per-step-cell paths instead; unset means TPU-auto like
    helpers_enabled. ``set_helpers_enabled(False)`` disables it like every
    other Pallas helper — the programmatic kill-switch covers the
    default-on kernel too. Shapes the VMEM guard rejects fall back to the
    scan path at call sites (sequence_fits)."""
    if _FORCED is not None:
        return _FORCED
    env = os.environ.get("DL4J_TPU_PALLAS")
    if env == "seq":
        return True
    if env in ("0", "1"):  # explicit other-path selection
        return False
    return jax.default_backend() == "tpu"


def sequence_fits(B: int, H: int, itemsize: int) -> bool:
    from .pallas_kernels import _seq_fits  # noqa: PLC0415

    return _seq_fits(B, H, itemsize)


def lstm_cell(zx, h_prev, c_prev, RW, pF, pI, pO,
              act_name: str = "tanh", gate_name: str = "sigmoid"):
    """One LSTM step (h, c). Pallas-fused when available, XLA otherwise."""
    B, H = c_prev.shape
    if (
        lstm_helper_enabled()
        and supported_lstm_activations(act_name, gate_name)
        and _cell_fits(B, H, zx.dtype.itemsize)
    ):
        return fused_lstm_cell(zx, h_prev, c_prev, RW, pF, pI, pO,
                               act_name, gate_name)
    act = _ACT.get(act_name)
    gate = _ACT.get(gate_name)
    if act is not None and gate is not None:
        h, c, *_ = _cell_math(zx, h_prev, c_prev, RW, pF, pI, pO,
                              act[0], gate[0])
        return h, c
    raise ValueError(f"Unknown LSTM activations ({act_name}, {gate_name})")


def lrn(x, k: float = 2.0, n: int = 5, alpha: float = 1e-4, beta: float = 0.75):
    """Cross-channel LRN over the trailing axis. The variant (fused Pallas
    pass vs unrolled XLA window sum) is picked by the ``lrn`` selection
    site; legacy ``set_helpers_enabled``/``DL4J_TPU_PALLAS`` forcing wins."""
    C = x.shape[-1]
    rows = max(x.size // max(C, 1), 1)
    if select_lrn_variant(rows, C, n, x.dtype.itemsize) == "fused":
        return fused_lrn(x, k, n, alpha, beta)
    d = k + alpha * _window_sum(x * x, n)
    return x * d**-beta


def softmax_xent_rows(labels2d, preout2d):
    """Per-row softmax cross-entropy for 2D [N, C] logits/labels — fused
    Pallas pass or the numerically-identical unfused XLA form, per the
    ``softmax_xent`` selection site (losses.mcxent routes here)."""
    N, C = preout2d.shape
    if select_softmax_xent_variant(N, C, preout2d.dtype.itemsize) == "fused":
        return fused_softmax_xent(preout2d, labels2d)
    import jax.numpy as jnp  # noqa: PLC0415

    # match the fused kernel's >=f32 compute contract (_sxent_compute_dt):
    # log_softmax subtracts the row max, but in bf16/f16 the log-sum-exp and
    # the label-weighted reduction still lose mantissa. The fused kernel
    # returns per-row losses in the promoted dtype; mirror that here.
    cdt = jnp.promote_types(preout2d.dtype, jnp.float32)
    logp = jax.nn.log_softmax(preout2d.astype(cdt), axis=-1)
    return -jnp.sum(labels2d.astype(cdt) * logp, axis=-1)


# ------------------------------------------------------ selection wrappers
# Each wrapper maps this module's legacy forcing knobs onto kernel_select's
# ``forced`` argument (exact historical semantics), then lets the roofline
# decide. All are host-side, run at trace time, and are cached/logged by
# kernel_select — same shapes always resolve identically.


def select_lstm_variant(T: int, B: int, H: int, itemsize: int,
                        acts_ok: bool, masked: bool = False) -> str:
    """'seqfused' | 'fusedcell' | 'reference' for one LSTM direction."""
    forced = None
    env = os.environ.get("DL4J_TPU_PALLAS")
    if _FORCED is False:
        forced = "reference"
    elif _FORCED is True:
        forced = "seqfused"
    elif env == "0":
        forced = "reference"
    elif env == "seq":
        forced = "seqfused"
    elif env == "1":
        forced = "fusedcell"
    ctx = {"T": int(T), "B": int(B), "H": int(H), "itemsize": int(itemsize),
           "acts_ok": bool(acts_ok), "masked": bool(masked)}
    return kernel_select.select("lstm_seq", ctx, forced=forced)


def select_attention_variant(B: int, heads: int, T: int, D: int,
                             itemsize: int, impl: str = "auto",
                             causal: bool = False) -> str:
    """'flash' | 'xla' for a local attention call; an explicit
    ``attention_impl`` ("flash"/"xla") is the per-site escape hatch."""
    forced = impl if impl in ("flash", "xla") else None
    if _FORCED is False:
        forced = "xla"
    ctx = {"B": int(B), "heads": int(heads), "T": int(T), "D": int(D),
           "itemsize": int(itemsize), "causal": bool(causal)}
    return kernel_select.select("attention", ctx, forced=forced)


def select_lrn_variant(rows: int, C: int, n: int, itemsize: int) -> str:
    forced = None
    env = os.environ.get("DL4J_TPU_PALLAS")
    if _FORCED is False:
        forced = "reference"
    elif _FORCED is True:
        forced = "fused"
    elif env == "0":
        forced = "reference"
    elif env == "1":
        forced = "fused"
    ctx = {"rows": int(rows), "C": int(C), "n": int(n),
           "itemsize": int(itemsize)}
    return kernel_select.select("lrn", ctx, forced=forced)


def select_softmax_xent_variant(N: int, C: int, itemsize: int) -> str:
    forced = "reference" if _FORCED is False else None
    ctx = {"N": int(N), "C": int(C), "itemsize": int(itemsize)}
    return kernel_select.select("softmax_xent", ctx, forced=forced)


def select_optimizer_variant(n_elems: int, itemsize: int, updater: str,
                             n_leaves: int = 1) -> str:
    forced = "reference" if _FORCED is False else None
    ctx = {"n_elems": int(n_elems), "itemsize": int(itemsize),
           "updater": str(updater), "n_leaves": int(n_leaves)}
    return kernel_select.select("optimizer", ctx, forced=forced)


__all__ = [
    "flash_attention",
    "fused_adam_update",
    "fused_lrn",
    "fused_lstm_cell",
    "fused_softmax_xent",
    "helpers_enabled",
    "kernel_select",
    "lrn",
    "lstm_cell",
    "lstm_helper_enabled",
    "select_attention_variant",
    "select_lrn_variant",
    "select_lstm_variant",
    "select_optimizer_variant",
    "select_softmax_xent_variant",
    "set_helpers_enabled",
    "softmax_xent_rows",
    "supported_lstm_activations",
]
