"""deeplearning4j_tpu — a TPU-native deep-learning framework.

Brand-new JAX/XLA/Pallas/pjit implementation of the capabilities of
Deeplearning4J 0.7.x (reference: /root/reference, surveyed in SURVEY.md).
Not a port: layers are pure functions, backprop is autodiff, the cuDNN helper
tier is XLA, and ParallelWrapper/Spark/Aeron collapse into mesh collectives.
"""

__version__ = "0.1.0"

from .nn.conf.inputs import InputType
from .nn.conf.multi_layer import MultiLayerConfiguration
from .nn.updaters import UpdaterConfig
from .nn.multilayer import MultiLayerNetwork
from .nn.layers.base import BaseLayer, register_layer
from .nn.conf.computation_graph import ComputationGraphConfiguration, GraphBuilder
from .nn.graph import (
    ComputationGraph,
    BaseVertex,
    LayerVertex,
    ElementWiseVertex,
    MergeVertex,
    SubsetVertex,
    StackVertex,
    UnstackVertex,
    ScaleVertex,
    ShiftVertex,
    L2Vertex,
    L2NormalizeVertex,
    PreprocessorVertex,
    LastTimeStepVertex,
    DuplicateToTimeSeriesVertex,
    ReshapeVertex,
)
from .nn.layers.dense import (
    DenseLayer,
    OutputLayer,
    LossLayer,
    ActivationLayer,
    DropoutLayer,
    EmbeddingLayer,
)
from .nn.layers.convolution import (
    ConvolutionLayer,
    Convolution1DLayer,
    ZeroPaddingLayer,
)
from .nn.layers.pooling import SubsamplingLayer, GlobalPoolingLayer
from .nn.layers.recurrent import (
    GravesLSTM,
    GravesBidirectionalLSTM,
    RnnOutputLayer,
    RnnEmbeddingLayer,
    LastTimeStepLayer,
)
from .nn.layers.normalization import BatchNormalization, LocalResponseNormalization
from .nn.layers.attention import LayerNormLayer, SelfAttentionLayer
from .nn.layers.moe import MixtureOfExpertsLayer
from .nn.layers.center_loss import CenterLossOutputLayer
from .datasets.iterators import (
    DataSet,
    MultiDataSet,
    DataSetIterator,
    NumpyDataSetIterator,
    ListDataSetIterator,
    AsyncDataSetIterator,
    MultipleEpochsIterator,
)
from .eval.evaluation import Evaluation
from .eval.roc import ROC, ROCMultiClass
from .eval.regression import RegressionEvaluation
from .nn.layers.frozen import FrozenLayer
from .nn.layers.pretrain import AutoEncoder, RBM
from .nn.layers.variational import (
    VariationalAutoencoder,
    BernoulliReconstruction,
    GaussianReconstruction,
    ExponentialReconstruction,
    CompositeReconstruction,
    LossFunctionWrapper,
)
from .nn.transferlearning import (
    TransferLearning,
    TransferLearningBuilder,
    TransferLearningGraphBuilder,
    FineTuneConfiguration,
)
from .optimize.listeners import (
    ComposableIterationListener,
    IterationListener,
    TrainingListener,
    ParamAndGradientIterationListener,
    ScoreIterationListener,
    CollectScoresIterationListener,
    PerformanceListener,
)
from .utils.serialization import write_model, restore_model
from .telemetry import (
    MetricsRegistry,
    Telemetry,
    Watchdog,
    get_registry,
)

__all__ = [
    "InputType",
    "MultiLayerConfiguration",
    "UpdaterConfig",
    "MultiLayerNetwork",
    "BaseLayer",
    "register_layer",
    "ComputationGraphConfiguration",
    "GraphBuilder",
    "ComputationGraph",
    "BaseVertex",
    "LayerVertex",
    "ElementWiseVertex",
    "MergeVertex",
    "SubsetVertex",
    "StackVertex",
    "UnstackVertex",
    "ScaleVertex",
    "ShiftVertex",
    "L2Vertex",
    "L2NormalizeVertex",
    "PreprocessorVertex",
    "LastTimeStepVertex",
    "DuplicateToTimeSeriesVertex",
    "ReshapeVertex",
    "DenseLayer",
    "OutputLayer",
    "LossLayer",
    "ActivationLayer",
    "DropoutLayer",
    "EmbeddingLayer",
    "ConvolutionLayer",
    "Convolution1DLayer",
    "ZeroPaddingLayer",
    "SubsamplingLayer",
    "GlobalPoolingLayer",
    "GravesLSTM",
    "GravesBidirectionalLSTM",
    "RnnOutputLayer",
    "RnnEmbeddingLayer",
    "LastTimeStepLayer",
    "BatchNormalization",
    "LocalResponseNormalization",
    "DataSet",
    "MultiDataSet",
    "DataSetIterator",
    "NumpyDataSetIterator",
    "ListDataSetIterator",
    "AsyncDataSetIterator",
    "MultipleEpochsIterator",
    "Evaluation",
    "ROC",
    "ROCMultiClass",
    "RegressionEvaluation",
    "FrozenLayer",
    "AutoEncoder",
    "RBM",
    "VariationalAutoencoder",
    "BernoulliReconstruction",
    "GaussianReconstruction",
    "ExponentialReconstruction",
    "CompositeReconstruction",
    "LossFunctionWrapper",
    "TransferLearning",
    "TransferLearningBuilder",
    "TransferLearningGraphBuilder",
    "FineTuneConfiguration",
    "IterationListener",
    "TrainingListener",
    "ComposableIterationListener",
    "ParamAndGradientIterationListener",
    "ScoreIterationListener",
    "CollectScoresIterationListener",
    "PerformanceListener",
    "write_model",
    "restore_model",
    "MetricsRegistry",
    "Telemetry",
    "Watchdog",
    "get_registry",
]
